"""nns-kscope static analysis: VMEM residency, tile alignment, index-map
hazards and roofline cost rows for every registered Pallas kernel
(ops/pallas/registry.py) — derived abstractly. No device, nothing
allocated, nothing traced.

What kernel authors otherwise take on faith — "the blocks fit and the
DMA engine is fed" — becomes checkable facts:

- **VMEM residency** (NNS-W127): per grid step the Pallas pipeline
  keeps every operand/result block resident, DOUBLE-buffered when its
  index-map output changes between consecutive steps (that overlap is
  what hides the next DMA behind compute), plus all scratch. The sum
  must fit per-core VMEM (``[tpu] vmem_bytes``, default 16 MiB —
  costmodel.configured_vmem_bound).
- **Tile alignment** (NNS-W128): a block dim that is neither the whole
  axis nor 1 pads up to the hardware tile — last dim to the 128-wide
  lane, second-minor to the dtype sublane (f32 8, bf16 16, int8 32); a
  misaligned pick silently wastes the padded fraction of every DMA and
  every register.
- **Index-map hazards** (NNS-W128): the REAL index-map callables run
  over the REAL grid (with representative scalar-prefetch values),
  catching out-of-bounds block picks and prefetch shape drift
  statically.
- **Roofline row**: HBM traffic by index-map transition counting (a
  block refetches only when its index CHANGES between steps), FLOPs
  from the plan, arithmetic intensity = flops / hbm_bytes — the
  analysis/costmodel.py vocabulary extended to kernel granularity
  (costmodel.KernelCost).

:func:`pallas_request_pass` is the pipeline-level consumer (NNS-W129):
a pipeline that REQUESTS impl=pallas on an element whose kernel would
degrade to the jnp path (unsupported dtype, kill switch, a mode with no
kernel) is told at lint time, not by reading dispatch tallies after the
frames already ran. :func:`differential_sweep` and :func:`engage` are
the dynamic complements: interpret-mode parity vs each kernel's jnp
reference, and dispatch-tally proof that a requested pallas path
actually engaged (docs/kernel-analysis.md).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from nnstreamer_tpu.analysis.costmodel import (
    KernelCost,
    configured_vmem_bound,
)
from nnstreamer_tpu.analysis.diagnostics import LintReport
from nnstreamer_tpu.ops.pallas import registry as kernel_registry
from nnstreamer_tpu.ops.pallas.registry import (
    BlockDesc,
    KernelSpec,
    LaunchPlan,
    ShapeCase,
)

#: TPU vector-register lane width: the last block dim tiles to this.
LANE = 128

#: dtype itemsize → minimum second-minor (sublane) tile.
SUBLANE = {4: 8, 2: 16, 1: 32}

#: grid-enumeration budget: beyond this many steps the walk stops and
#: varying-block fetch counts scale linearly (noted on the report).
GRID_ENUM_CAP = 100_000


def _np_dtype(name: str) -> np.dtype:
    """numpy dtype from a registry dtype name; ml_dtypes supplies the
    TPU dtypes plain numpy does not know (bfloat16, fp8)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, str(name)))


# -- per-case report ---------------------------------------------------------


@dataclass
class BlockReport:
    """One operand/result block's static verdicts for one shape case."""

    name: str
    kind: str                      # "in" | "out"
    array_shape: Tuple[int, ...]
    block_shape: Tuple[int, ...]
    dtype: str
    block_bytes: int               # one buffer
    buffers: int                   # 2 when the index map varies over grid
    fetches: int                   # estimated DMA transitions over the grid
    problems: List[str] = field(default_factory=list)

    @property
    def vmem_bytes(self) -> int:
        return self.block_bytes * self.buffers

    @property
    def hbm_bytes(self) -> int:
        return self.block_bytes * self.fetches


@dataclass
class CaseReport:
    """Everything nns-kscope derives for one kernel × shape case."""

    kernel: str
    case: str
    grid: Tuple[int, ...]
    steps: int                     # total grid steps
    enumerated: int                # steps actually walked (cap)
    vmem_bytes: int                # blocks (buffered) + scratch
    vmem_bound: int
    smem_bytes: int                # scalar-prefetch operands
    scratch_bytes: int
    cost: KernelCost
    blocks: List[BlockReport]
    hazards: List[str] = field(default_factory=list)
    notes: str = ""

    @property
    def over_budget(self) -> bool:
        return self.vmem_bytes > self.vmem_bound

    @property
    def misaligned(self) -> List[BlockReport]:
        return [b for b in self.blocks if b.problems]

    def to_row(self) -> Dict[str, Any]:
        return {
            "kernel": self.kernel,
            "case": self.case,
            "grid": list(self.grid),
            "steps": self.steps,
            "vmem_bytes": self.vmem_bytes,
            "vmem_bound": self.vmem_bound,
            "over_budget": self.over_budget,
            "smem_bytes": self.smem_bytes,
            "scratch_bytes": self.scratch_bytes,
            "hbm_read_bytes": self.cost.hbm_read_bytes,
            "hbm_write_bytes": self.cost.hbm_write_bytes,
            "flops": self.cost.flops,
            "arithmetic_intensity": self.cost.arithmetic_intensity,
            "misaligned": sorted(b.name for b in self.misaligned),
            "hazards": list(self.hazards),
            "notes": self.notes,
        }


# -- alignment ---------------------------------------------------------------


def _alignment_problems(b: BlockDesc) -> List[str]:
    """Lane/sublane tile verdicts for one block. A dim equal to the
    whole axis is exempt (Pallas pads a sole partial block once, not
    per step); so is 1 (broadcast/scalar rows live in their own
    layout)."""
    probs: List[str] = []
    if not b.block_shape:
        return probs
    dt = _np_dtype(b.dtype)
    last_b, last_a = b.block_shape[-1], b.array_shape[-1]
    if last_b not in (1, last_a) and last_b % LANE:
        probs.append(
            f"last dim {last_b} is neither the whole axis ({last_a}) nor "
            f"a multiple of the {LANE}-wide lane tile"
        )
    sub = SUBLANE.get(dt.itemsize)
    if sub is not None and len(b.block_shape) >= 2:
        sec_b, sec_a = b.block_shape[-2], b.array_shape[-2]
        if sec_b not in (1, sec_a) and sec_b % sub:
            probs.append(
                f"second-minor dim {sec_b} is neither the whole axis "
                f"({sec_a}) nor a multiple of the {dt.name} sublane "
                f"tile ({sub})"
            )
    return probs


# -- grid enumeration --------------------------------------------------------


def _prefetch_values(plan: LaunchPlan, hazards: List[str]) -> List[np.ndarray]:
    """Representative scalar-prefetch arrays for index-map enumeration;
    shape drift between ``make()`` and the declared SMEM shape is a
    hazard (the kernel would read garbage past the real rows)."""
    vals: List[np.ndarray] = []
    for p in plan.prefetch:
        arr: Optional[np.ndarray] = None
        if p.make is not None:
            try:
                arr = np.asarray(p.make())
            except Exception as exc:  # noqa: BLE001 - reported, not fatal
                hazards.append(
                    f"prefetch {p.name!r}: make() raised "
                    f"{type(exc).__name__}: {exc}"
                )
        if arr is not None and tuple(arr.shape) != tuple(p.shape):
            hazards.append(
                f"prefetch {p.name!r}: make() shape {tuple(arr.shape)} "
                f"drifts from the declared SMEM shape {tuple(p.shape)}"
            )
        if arr is None:
            arr = np.zeros(tuple(p.shape), dtype=np.int32)
        vals.append(arr)
    return vals


def _n_blocks(b: BlockDesc) -> Tuple[int, ...]:
    return tuple(
        -(-int(a) // int(k)) for a, k in zip(b.array_shape, b.block_shape)
    )


def _enumerate(plan: LaunchPlan):
    """Walk the grid row-major, calling every block's REAL index map
    with representative prefetch values. Returns
    ``(usage, hazards, total_steps, enumerated_steps)`` where usage maps
    block name → dict(fetches, varies, problem)."""
    hazards: List[str] = []
    prefetch = _prefetch_values(plan, hazards)
    total = 1
    for g in plan.grid:
        total *= int(g)
    usage: Dict[str, Dict[str, Any]] = {
        b.name: {"fetches": 0, "varies": False, "last": None, "problem": None}
        for b in plan.blocks
    }
    enumerated = min(total, GRID_ENUM_CAP)
    walker = itertools.product(*(range(int(g)) for g in plan.grid))
    for step, coords in enumerate(walker):
        if step >= enumerated:
            break
        for b in plan.blocks:
            u = usage[b.name]
            if u["problem"]:
                continue
            try:
                raw = b.index_map(*coords, *prefetch)
            except Exception as exc:  # noqa: BLE001 - reported, not fatal
                u["problem"] = (
                    f"index map raised {type(exc).__name__} at grid step "
                    f"{coords}: {exc}"
                )
                continue
            idx = tuple(int(v) for v in raw)
            if len(idx) != len(b.block_shape):
                u["problem"] = (
                    f"index map returns {len(idx)} coordinates for a "
                    f"rank-{len(b.block_shape)} block"
                )
                continue
            bounds = _n_blocks(b)
            if any(not 0 <= c < n for c, n in zip(idx, bounds)):
                u["problem"] = (
                    f"index map picks block {idx} outside the {bounds} "
                    f"block grid at step {coords}"
                )
                continue
            if idx != u["last"]:
                u["fetches"] += 1
                if u["last"] is not None:
                    u["varies"] = True
                u["last"] = idx
    return usage, hazards, total, enumerated


# -- the analyzer ------------------------------------------------------------


def analyze_case(
    spec: KernelSpec,
    case: Union[ShapeCase, str],
    bound: Optional[int] = None,
) -> CaseReport:
    """Static verdicts for one kernel × shape case."""
    if isinstance(case, str):
        case = next(c for c in spec.cases if c.name == case)
    plan = spec.plan(dict(case.params))
    vmem_bound = configured_vmem_bound() if bound is None else int(bound)
    usage, hazards, total, enumerated = _enumerate(plan)
    # linear scale-up for fetch counts past the enumeration cap; a
    # constant-index block fetched once stays once regardless of cap
    scale = (total / enumerated) if enumerated else 0.0
    notes = ""
    if total > enumerated:
        notes = (
            f"grid has {total} steps; walked {enumerated} and scaled "
            f"varying-block fetch counts linearly"
        )
    blocks: List[BlockReport] = []
    vmem = 0
    hbm_read = 0
    hbm_write = 0
    for b in plan.blocks:
        dt = _np_dtype(b.dtype)
        nbytes = int(np.prod(b.block_shape, dtype=np.int64)) * dt.itemsize
        u = usage[b.name]
        buffers = 2 if u["varies"] else 1
        fetches = (
            int(round(u["fetches"] * scale)) if u["varies"]
            else u["fetches"]
        )
        problems = _alignment_problems(b)
        if u["problem"]:
            problems.append(u["problem"])
        row = BlockReport(
            name=b.name, kind=b.kind,
            array_shape=tuple(b.array_shape),
            block_shape=tuple(b.block_shape),
            dtype=b.dtype, block_bytes=nbytes, buffers=buffers,
            fetches=fetches, problems=problems,
        )
        blocks.append(row)
        vmem += row.vmem_bytes
        if b.kind == "out":
            hbm_write += row.hbm_bytes
        else:
            hbm_read += row.hbm_bytes
    scratch_bytes = sum(
        int(np.prod(s.shape, dtype=np.int64)) * _np_dtype(s.dtype).itemsize
        for s in plan.scratch
    )
    smem_bytes = sum(
        int(np.prod(p.shape, dtype=np.int64)) * _np_dtype(p.dtype).itemsize
        for p in plan.prefetch
    )
    return CaseReport(
        kernel=spec.name, case=case.name,
        grid=tuple(int(g) for g in plan.grid),
        steps=total, enumerated=enumerated,
        vmem_bytes=vmem + scratch_bytes, vmem_bound=vmem_bound,
        smem_bytes=smem_bytes, scratch_bytes=scratch_bytes,
        cost=KernelCost(
            hbm_read_bytes=hbm_read, hbm_write_bytes=hbm_write,
            flops=int(plan.flops),
        ),
        blocks=blocks, hazards=hazards, notes=notes,
    )


def analyze(
    specs: Optional[Sequence[KernelSpec]] = None,
    bound: Optional[int] = None,
) -> Tuple[List[CaseReport], LintReport]:
    """Every registered kernel × shape case → case reports + a
    LintReport carrying NNS-W127 (VMEM over budget) and NNS-W128
    (misaligned tile / index-map hazard) findings."""
    if specs is None:
        specs = kernel_registry.all_specs()
    report = LintReport()
    reports: List[CaseReport] = []
    for spec in specs:
        for case in spec.cases:
            r = analyze_case(spec, case, bound)
            reports.append(r)
            where = f"{r.kernel}:{r.case}"
            if r.over_budget:
                report.add(
                    "NNS-W127", where,
                    f"per-grid-step VMEM residency {r.vmem_bytes} B "
                    f"(blocks double-buffered where their index varies, "
                    f"+ {r.scratch_bytes} B scratch) exceeds the "
                    f"{r.vmem_bound} B bound",
                    "shrink the block shapes (the pipeline refetches "
                    "more, but fits) or raise [tpu] vmem_bytes if the "
                    "target core really has more",
                )
            for blk in r.blocks:
                for p in blk.problems:
                    report.add(
                        "NNS-W128", where,
                        f"block {blk.name!r}: {p}",
                        "pick block dims that are whole axes or "
                        "multiples of the dtype tile (lane 128; sublane "
                        "8/16/32 for 4/2/1-byte dtypes), and index maps "
                        "that stay inside the block grid",
                    )
            for h in r.hazards:
                report.add(
                    "NNS-W128", where, h,
                    "keep the PrefetchDesc declared shape and its "
                    "make() in lockstep — the kernel indexes SMEM by "
                    "the declared shape",
                )
    return reports, report


# -- dynamic complements: parity sweep + engagement proof --------------------


def _leaf_pairs(got: Any, want: Any) -> Iterable[Tuple[Any, Any]]:
    if isinstance(got, (tuple, list)):
        for g, w in zip(got, want):
            yield from _leaf_pairs(g, w)
    else:
        yield got, want


def _max_err(got: Any, want: Any, atol: float) -> float:
    """Compare in float64 (uint8 differences would wrap) and raise on
    mismatch; returns the max abs error across all leaves."""
    worst = 0.0
    for g, w in _leaf_pairs(got, want):
        ga = np.asarray(g, dtype=np.float64)
        wa = np.asarray(w, dtype=np.float64)
        np.testing.assert_allclose(ga, wa, atol=atol, rtol=1e-5)
        if ga.size:
            worst = max(worst, float(np.max(np.abs(ga - wa))))
    return worst


def differential_sweep(
    specs: Optional[Sequence[KernelSpec]] = None,
    full: bool = False,
) -> List[Dict[str, Any]]:
    """Interpret-mode parity: run every kernel against its jnp
    reference over the tier-1 shape subset (``full=True`` takes the
    whole grid — the `slow` sweep). One row per kernel × case."""
    if specs is None:
        specs = kernel_registry.all_specs()
    rows: List[Dict[str, Any]] = []
    for spec in specs:
        cases = spec.cases if full else spec.tier1_cases()
        for case in cases:
            row: Dict[str, Any] = {
                "kernel": spec.name, "case": case.name,
                "ok": True, "max_err": 0.0, "error": None,
            }
            try:
                got, want, atol = spec.run_case(dict(case.params))
                row["max_err"] = _max_err(got, want, atol)
            except Exception as exc:  # noqa: BLE001 - one row per failure
                row["ok"] = False
                row["error"] = f"{type(exc).__name__}: {exc}"
            rows.append(row)
    return rows


def engage(
    specs: Optional[Sequence[KernelSpec]] = None,
) -> List[Dict[str, Any]]:
    """Dispatch-tally proof that each kernel's requested pallas path
    engages: snapshot the tally, run the spec's tiny probe (explicit
    impl=pallas through the public op), and diff. A row is ``ok`` only
    when the probe ran clean AND the op dispatched to pallas and
    nothing else — a silent jnp fallback fails the row (the
    ``nns-kscope --engage`` / ``bench.py --capture-tpu`` contract)."""
    from nnstreamer_tpu.ops import dispatch

    if specs is None:
        specs = kernel_registry.all_specs()
    rows: List[Dict[str, Any]] = []
    for spec in specs:
        snap = dispatch.tally.snapshot()
        error: Optional[str] = None
        try:
            spec.probe()
        except Exception as exc:  # noqa: BLE001 - one row per failure
            error = f"{type(exc).__name__}: {exc}"
        impls = dispatch.engaged_impls(spec.dispatch_op, snap)
        rows.append({
            "kernel": spec.name,
            "op": spec.dispatch_op,
            "impls": impls,
            "ok": error is None and impls == ["pallas"],
            "error": error,
        })
    return rows


# -- pipeline-level pass (NNS-W129) ------------------------------------------

#: tensor_transform image modes with a Pallas kernel behind them.
_TRANSFORM_KERNELS = {
    "resize": "resize_bilinear",
    "crop-resize": "crop_and_resize",
}


def _transform_input_dtype(pipeline, specs, e) -> Optional[str]:
    """The dtype the transform's kernel would see: the image tensor of
    the upstream out spec (first rank≥3 tensor, else the first)."""
    for link in pipeline.in_links(e):
        up = specs.get(link.src.name)
        if not up or link.src_pad >= len(up):
            continue
        spec = up[link.src_pad]
        tensors = getattr(spec, "tensors", None)
        if not tensors:
            continue
        img = next((t for t in tensors if t.rank >= 3), tensors[0])
        try:
            return np.dtype(img.dtype.np_dtype).name
        except Exception:  # noqa: BLE001 - dtype stays unknown
            return None
    return None


def pallas_request_pass(pipeline, report: LintReport, specs) -> None:
    """NNS-W129: the pipeline REQUESTS a pallas implementation that
    would dispatch the jnp/xla path — an unsupported dtype, the
    NNS_TPU_PALLAS_DISABLE kill switch, or a mode with no kernel at
    all. Runs as a lint() pass after spec negotiation (the specs dict
    supplies the upstream dtypes)."""
    from nnstreamer_tpu.ops.pallas._compat import pallas_ok

    for e in pipeline.elements:
        factory = getattr(type(e), "FACTORY_NAME", "")
        if factory == "tensor_transform":
            if str(e.get_property("impl", "auto") or "auto").lower() != (
                "pallas"
            ):
                continue
            mode = str(e.get_property("mode", "") or "").lower()
            kernel = _TRANSFORM_KERNELS.get(mode)
            if kernel is None:
                report.add(
                    "NNS-W129", e.name,
                    f"impl=pallas requested but mode={mode} has no "
                    "Pallas kernel; every frame runs the jnp path",
                    "only resize / crop-resize dispatch to kernels — "
                    "drop impl=pallas or switch modes",
                )
                continue
            dtype = _transform_input_dtype(pipeline, specs, e)
            ok, reason = pallas_ok(kernel, dtype)
            if not ok:
                report.add(
                    "NNS-W129", e.name,
                    f"impl=pallas requested but {kernel} would dispatch "
                    f"jnp: {reason}",
                    "fix the input dtype (or clear "
                    "NNS_TPU_PALLAS_DISABLE) so the requested kernel "
                    "can engage, or drop impl=pallas",
                )
        elif factory == "tensor_llm_serversink":
            impl = str(e.get_property("attn-impl", "") or "").strip()
            if impl.lower() != "pallas":
                continue
            from nnstreamer_tpu.config import conf

            layout = str(e.get_property("kv-layout", "") or "").strip() or (
                conf().get("llm", "kv_layout", "slot")
            )
            if str(e.get_property("plane", "") or "").strip() and (
                layout == "slot"
                and not str(e.get_property("kv-layout", "") or "").strip()
            ):
                layout = "paged"  # plane= implies the shared paged batcher
            kernel = (
                "paged_decode_attention" if layout == "paged"
                else "decode_attention"
            )
            cache_dtype = str(
                e.get_property("cache-dtype", "auto") or "auto"
            ).strip()
            dtype = "int8" if cache_dtype == "int8" else "float32"
            ok, reason = pallas_ok(kernel, dtype)
            if not ok:
                report.add(
                    "NNS-W129", e.name,
                    f"attn-impl=pallas requested but {kernel} would "
                    f"dispatch xla: {reason}",
                    "fix cache-dtype (or clear NNS_TPU_PALLAS_DISABLE) "
                    "so the serving attention kernel can engage, or "
                    "drop attn-impl=pallas",
                )
