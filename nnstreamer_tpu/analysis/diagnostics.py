"""Structured lint diagnostics (the reference's gst-validate report model:
one issue-type registry, many reports per run, never fail-fast).

Every problem `nns-lint` can find has a stable code in the ``NNS-Exxx``
(error) / ``NNS-Wxxx`` (warning) namespace so scripts and CI can match on
codes instead of message text. The catalog below is the single source of
truth; docs/linting.md renders from the same table.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value


# code → (severity, slug, one-line description)
CATALOG: Dict[str, Tuple[Severity, str, str]] = {
    "NNS-E001": (
        Severity.ERROR, "unlinked-sink-pad",
        "an element's required sink pad has nothing linked to it",
    ),
    "NNS-E002": (
        Severity.ERROR, "cycle",
        "the pipeline graph contains a cycle (use tensor_repo for loops)",
    ),
    "NNS-E003": (
        Severity.ERROR, "caps-mismatch",
        "spec negotiation would fail on this element at build time",
    ),
    "NNS-E004": (
        Severity.ERROR, "unknown-element",
        "no element factory registered under this name",
    ),
    "NNS-E005": (
        Severity.ERROR, "bad-property-value",
        "a property value cannot be coerced to its declared type",
    ),
    "NNS-E006": (
        Severity.ERROR, "unknown-framework",
        "tensor_filter framework= names no registered backend",
    ),
    "NNS-E007": (
        Severity.ERROR, "unknown-decoder",
        "tensor_decoder mode= names no registered decoder subplugin",
    ),
    "NNS-E008": (
        Severity.ERROR, "unknown-converter",
        "tensor_converter mode= names no registered converter subplugin",
    ),
    "NNS-E009": (
        Severity.ERROR, "parse-error",
        "the launch string does not parse (bad token, dangling '!', ...)",
    ),
    "NNS-E010": (
        Severity.ERROR, "restricted-element",
        "the element exists but is blocked by [common] restricted_elements",
    ),
    "NNS-E011": (
        Severity.ERROR, "construction-failed",
        "the element constructor raised (missing required property, "
        "unopenable resource, ...)",
    ),
    "NNS-W101": (
        Severity.WARNING, "unknown-property",
        "property is not in the element's schema (typo?)",
    ),
    "NNS-W102": (
        Severity.WARNING, "missing-model-file",
        "tensor_filter model path does not exist on disk",
    ),
    "NNS-W103": (
        Severity.WARNING, "unqueued-tee-branch",
        "mux fan-in branches share a tee ancestor without an intervening "
        "queue (classic deadlock topology)",
    ),
    "NNS-W104": (
        Severity.WARNING, "unreachable-element",
        "element is not reachable from any source; it will never see data",
    ),
    "NNS-W105": (
        Severity.WARNING, "unlinked-src-pad",
        "an element's src pad has nothing linked; its output is dropped",
    ),
    "NNS-W106": (
        Severity.WARNING, "suspicious-property-value",
        "the value parses at runtime but probably not as intended "
        "(e.g. an unrecognized boolean string silently becomes false)",
    ),
    "NNS-W107": (
        Severity.WARNING, "unrouted-error-pad",
        "on-error=route but the dead-letter error pad is unlinked; "
        "failed frames are silently dropped",
    ),
    # -- nns-san graph-level deadlock/capacity pass (analysis/lint.py) ------
    "NNS-W108": (
        Severity.WARNING, "channel-capacity",
        "a bounded channel is sized so it cannot do its job (non-positive "
        "queue-size is clamped to 1; max-batch larger than the input "
        "channel depth can never fill a batch)",
    ),
    "NNS-W109": (
        Severity.WARNING, "unqueued-fanout-join",
        "fan-in branches share a non-tee fan-out ancestor (demux/split) "
        "with no intervening queue on some branch — the same blocking "
        "topology as the tee case (NNS-W103)",
    ),
    "NNS-W110": (
        Severity.WARNING, "rate-skewed-join",
        "a synchronizing fan-in has a data-dependent frame dropper "
        "(tensor_if SKIP, on-error=drop/retry) on a strict subset of its "
        "branches; the join can starve waiting for skipped counterparts",
    ),
    "NNS-W111": (
        Severity.WARNING, "unbounded-query-server",
        "a tensor_query_serversrc has no admission bound (max-clients / "
        "max-inflight / per-client-inflight / rate); overload degrades "
        "as unbounded queueing and silent latency collapse",
    ),
    "NNS-W112": (
        Severity.WARNING, "replica-no-failover-policy",
        "a multi-replica filter (replicas=N) keeps the default "
        "on-error=stop: losing every replica then kills the whole "
        "pipeline, and in a serving pipeline admitted clients hang "
        "instead of receiving terminal NACKs",
    ),
    "NNS-W113": (
        Severity.WARNING, "host-split-device-segments",
        "a host-bound element sits between two device-capable "
        "(traceable) filters: every frame materializes to host and "
        "back mid-stream, defeating the resident device-to-device "
        "segment handoff",
    ),
    "NNS-W114": (
        Severity.WARNING, "duplicate-model-no-sharing",
        "two or more tensor_filter instances open the same "
        "model/framework without shared-tensor-filter-key or a serving "
        "plane: each loads its own copy of the weights on device",
    ),
    "NNS-W115": (
        Severity.WARNING, "oversized-static-kv-cache",
        "an LLM serving element's slot-layout KV cache (n-slots × "
        "max-len, sized for the worst case of every slot) exceeds the "
        "declared device memory bound while kv-layout=paged is "
        "available: a block-table arena serves the same requests in "
        "the actually-used tokens, with prefix sharing on top",
    ),
    "NNS-W116": (
        Severity.WARNING, "host-postproc-splits-device-chain",
        "a tensor_decoder whose decode math HAS a device (traceable) "
        "path runs as a host node between two device-capable filters: "
        "every frame materializes its (usually much larger) decoder "
        "inputs to host mid-stream; postproc=device folds the decode "
        "into the adjacent fused segment and only the small decoded "
        "tensor ever leaves the device",
    ),
    "NNS-W118": (
        Severity.WARNING, "blocking-plane-submit-under-ring",
        "a serving-plane stream that cannot overlap its submits: either "
        "a plane filter sets ring-depth>1 but disables the local window "
        "collector (batching=false forces per-frame blocking submits, "
        "so the in-flight ring never engages), or several streams share "
        "one plane with every in-flight depth left at 1 — each stream "
        "then blocks a full plane round trip per window while the "
        "async ticket ring would overlap submit/compute/delivery",
    ),
    "NNS-W119": (
        Severity.WARNING, "single-endpoint-no-failover",
        "a tensor_query_client stamps a per-request SLO (deadline-ms) "
        "but binds exactly one endpoint with retry-max=0: any endpoint "
        "hiccup is a terminal error with no reconnect, no failover, and "
        "no hedge — bind a fleet (hosts=h1:p1,h2:p2) or grant a "
        "retry-max budget",
    ),
    "NNS-W117": (
        Severity.WARNING, "paged-gather-materializes-cache",
        "a paged LLM serving element is pinned to kv-attn=gather, whose "
        "step programs materialize the full contiguous per-slot view "
        "beside the block arena (a transient HBM doubling) and the "
        "combined footprint exceeds the declared memory bound; the "
        "block-native default (kv-attn=auto/block) attends the arena "
        "directly through the block tables with no gathered view",
    ),
    # -- nns-xray chain analysis (analysis/xray.py, docs/chain-analysis.md) -
    "NNS-W120": (
        Severity.WARNING, "chain-split-by-host-node",
        "a host-path tensor op severs an otherwise compileable chain "
        "of fused segments: frames materialize to host and re-stage to "
        "device at the split, and the span can never become one "
        "resident program; a device-capable framework (or "
        "postproc=device for decoders, which W116 pinpoints) rejoins "
        "the chain",
    ),
    "NNS-W121": (
        Severity.WARNING, "recompile-hazard-cache-keys",
        "a fused segment's jit-cache key space is unbounded or "
        "explodes: a flexible (per-frame shape) input spec under "
        "micro-batching, or arity x buckets x donation variants over "
        "the retrace bound — each new key is a fresh XLA compile on "
        "the hot path",
    ),
    "NNS-W122": (
        Severity.WARNING, "dtype-promotion-in-device-segment",
        "a device segment's traced program silently promotes to f64/"
        "complex128 (or drifts from its negotiated output dtype) with "
        "no 64-bit input: on TPU that is an emulated-precision slowdown "
        "and a doubled activation footprint the specs never declared",
    ),
    "NNS-W123": (
        Severity.WARNING, "donation-defeating-output",
        "a segment streams with donated input buffers (donate under "
        "ring-depth>1) but no output matches any input's shape/dtype, "
        "so XLA can reuse nothing: every frame pays a fresh output "
        "allocation while the donated arena is discarded",
    ),
    "NNS-W124": (
        Severity.WARNING, "chain-transient-hbm-over-bound",
        "a chain's static cost (resident params + peak per-program "
        "transient working set at the max micro-batch bucket) exceeds "
        "the declared [plane] memory_per_device bound: the chain OOMs "
        "on a real chip even though each stage fits alone",
    ),
    "NNS-W125": (
        Severity.WARNING, "chain-eligible-not-compiled",
        "a hazard-free multi-segment chain is running with chain_mode="
        "off: every frame still crosses one service thread per node "
        "where ONE resident whole-chain program (dispatched once per "
        "unrolled window) would serve it — host-dispatch overhead the "
        "compiled-chain path exists to remove",
    ),
    # -- fleet serving robustness (docs/llm-serving.md) ---------------------
    "NNS-W126": (
        Severity.WARNING, "llm-drain-loses-generations",
        "a fleet-tuned query serversrc (explicit retry-after-ms — its "
        "clients re-route on drain NACKs) feeds an LLM serversink with "
        "no migrate-to peer and no checkpoint-dir: draining this "
        "server abandons every in-flight generation's KV and decoded "
        "tokens, so re-routed requests pay a full re-prefill from "
        "token zero on the next endpoint",
    ),
    # -- nns-kscope kernel analysis (analysis/kernels.py, ------------------
    # docs/kernel-analysis.md)
    "NNS-W127": (
        Severity.WARNING, "kernel-vmem-over-budget",
        "a Pallas kernel's per-grid-step VMEM residency (operand/result "
        "blocks, double-buffered where their index map varies over the "
        "grid, plus scratch) exceeds the configured per-core VMEM bound "
        "([tpu] vmem_bytes, default 16 MiB): the launch OOMs or spills "
        "on a real chip even though the HBM arrays fit",
    ),
    "NNS-W128": (
        Severity.WARNING, "misaligned-tile",
        "a Pallas block is misaligned or its index map is hazardous: a "
        "block dim that is neither the whole axis nor a multiple of the "
        "hardware tile (lane 128; sublane 8/16/32 for 4/2/1-byte "
        "dtypes) pads every DMA and register, and an index map that "
        "picks blocks outside the block grid (or a scalar-prefetch "
        "operand whose values drift from its declared SMEM shape) reads "
        "garbage",
    ),
    "NNS-W129": (
        Severity.WARNING, "pipeline-requests-pallas-but-dispatches-jnp",
        "an element explicitly requests a Pallas implementation "
        "(impl=pallas / attn-impl=pallas) that would silently dispatch "
        "the jnp/xla fallback: the input dtype is outside the kernel's "
        "registered support, the NNS_TPU_PALLAS_DISABLE kill switch is "
        "set, or the configured mode has no kernel at all",
    ),
    "NNS-W130": (
        Severity.WARNING, "prefill-role-no-decode-peer",
        "an LLM serversink declares role=prefill but names no "
        "decode-peers: every request it prefills decodes locally — the "
        "disaggregation it was configured for never happens, and with "
        "no checkpoint-dir either, a drain abandons the in-flight "
        "generations it was supposed to hand off",
    ),
    # -- nns-san race lint (analysis/racecheck.py): findings over SOURCE ----
    # code, not pipelines; `element` carries file:line
    "NNS-R001": (
        Severity.WARNING, "unlocked-shared-write",
        "a shared counter (self.attr += ...) is read-modify-written from "
        "more than one method of a thread-spawning class without the "
        "owning lock held at every site",
    ),
    "NNS-R002": (
        Severity.WARNING, "blocking-call-under-lock",
        "an unbounded blocking call (sleep, join without timeout, bare "
        "wait, recv/accept) runs while a threading lock is held",
    ),
    "NNS-R003": (
        Severity.ERROR, "swallowed-interrupt",
        "a bare except (or except BaseException) that does not re-raise "
        "swallows KeyboardInterrupt/SystemExit",
    ),
    "NNS-R004": (
        Severity.WARNING, "silent-except-in-loop",
        "except Exception with a pass/continue-only body inside a loop: a "
        "service loop that silently eats every failure forever",
    ),
    "NNS-R005": (
        Severity.WARNING, "thread-without-join",
        "a thread is created with no join-or-daemon story (neither "
        "daemon=True nor a reachable .join())",
    ),
    "NNS-R006": (
        Severity.ERROR, "dekker-ordering",
        "a channel class violates the documented _Chan parking discipline "
        "(advertise the waiting flag BEFORE re-checking the deque; check "
        "the peer's flag AFTER the deque op) — a missed-wakeup bug",
    ),
    # -- nns-san runtime sanitizer (pipeline/sanitize.py) -------------------
    "NNS-S001": (
        Severity.ERROR, "spec-violation",
        "a frame on a negotiated static link does not conform to the "
        "pad's TensorsSpec (shape/dtype drift the jit would mask or a "
        "downstream consumer would crash on)",
    ),
    "NNS-S002": (
        Severity.ERROR, "accounting-leak",
        "a node's frame accounting broke at EOS: offered != delivered + "
        "dropped + routed (frames vanished or were duplicated)",
    ),
    "NNS-S003": (
        Severity.WARNING, "lock-order-cycle",
        "watched locks were acquired in cyclic order by different "
        "threads — a latent deadlock",
    ),
    "NNS-S004": (
        Severity.WARNING, "thread-leak",
        "threads were still alive after Executor shutdown joined "
        "everything it started (stragglers listed)",
    ),
}


@dataclass(frozen=True)
class Diagnostic:
    """One finding: stable code + severity + offending element + advice."""

    code: str
    severity: Severity
    element: Optional[str]  # element (instance) name, None = whole pipeline
    message: str
    hint: str = ""

    @property
    def slug(self) -> str:
        return CATALOG[self.code][1] if self.code in CATALOG else ""

    def __str__(self) -> str:
        where = f" [{self.element}]" if self.element else ""
        hint = f" (hint: {self.hint})" if self.hint else ""
        return (
            f"{self.code} {self.severity.value}{where}: {self.message}{hint}"
        )


def make(code: str, element: Optional[str], message: str, hint: str = "") -> Diagnostic:
    """Build a Diagnostic with the catalog's severity for `code`."""
    sev, _, _ = CATALOG[code]
    return Diagnostic(code, sev, element, message, hint)


@dataclass
class LintReport:
    """All diagnostics from one lint run, never fail-fast."""

    diagnostics: List[Diagnostic] = field(default_factory=list)

    def add(self, code: str, element: Optional[str], message: str,
            hint: str = "") -> None:
        self.diagnostics.append(make(code, element, message, hint))

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity is Severity.WARNING]

    @property
    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    @property
    def exit_code(self) -> int:
        """nns-lint / nns-launch --check contract: 0 clean, 1 warnings
        only, 2 any error."""
        if self.errors:
            return 2
        if self.warnings:
            return 1
        return 0

    def by_element(self) -> Dict[Optional[str], List[Diagnostic]]:
        out: Dict[Optional[str], List[Diagnostic]] = {}
        for d in self.diagnostics:
            out.setdefault(d.element, []).append(d)
        return out

    def render(self) -> str:
        if not self.diagnostics:
            return "pipeline is clean"
        lines = [str(d) for d in self.diagnostics]
        lines.append(
            f"{len(self.errors)} error(s), {len(self.warnings)} warning(s)"
        )
        return "\n".join(lines)
