"""nns-san --race: AST concurrency lint over Python source.

The executor is a real concurrent system (per-node service threads,
GIL-atomic ``_Chan`` Dekker pairing, fault gates, batched drain loops, a
stall watchdog) and nothing structural keeps those idioms correct as the
code grows. This pass encodes the repo's concurrency discipline as
checkable rules and reports violations as the same structured
:class:`~nnstreamer_tpu.analysis.diagnostics.Diagnostic` findings nns-lint
uses — ``element`` carries ``file:line`` instead of an element name.

Checks (codes in the shared catalog, ``NNS-R0xx``):

- **NNS-R001 unlocked-shared-write** — in a class that spawns threads, a
  ``self.<attr> += ...`` read-modify-write reached from more than one
  method with at least one site not under a ``with <lock>``. Single-writer
  counters (the FaultStats/BatchStats contract) stay legal because they
  mutate from exactly one method.
- **NNS-R002 blocking-call-under-lock** — ``time.sleep``, ``.join()`` /
  ``.wait()`` without a timeout, ``.recv(`` / ``.accept(`` while a
  ``threading.Lock`` is held (condition variables are exempt: waiting is
  what they are for).
- **NNS-R003 swallowed-interrupt** — bare ``except:`` / ``except
  BaseException:`` that never re-raises (eats KeyboardInterrupt).
- **NNS-R004 silent-except-in-loop** — ``except Exception:`` whose body is
  only ``pass``/``continue`` inside a loop: a service loop that silently
  eats every failure forever.
- **NNS-R005 thread-without-join** — ``threading.Thread(...)`` with
  neither ``daemon=True`` nor a reachable ``.join()``/``.daemon = True``.
- **NNS-R006 dekker-ordering** — a channel-like class (two ``*_waiting``
  flags over a deque) that breaks the documented parking discipline
  (pipeline/executor.py ``_Chan``): the waiter must advertise its flag
  BEFORE re-checking the deque and before parking; the mover must check
  the peer flag AFTER its deque op.

A finding is waived by ``# nns-san: ok`` or any ``# noqa`` on the
offending line — intentional broad catches in this repo already carry
``noqa: BLE001`` annotations with a reason.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from nnstreamer_tpu.analysis.diagnostics import LintReport

_LOCK_NAME = re.compile(r"(lock|mutex)s?$", re.IGNORECASE)
_SYNC_NAME = re.compile(r"(lock|mutex|cv|cond)", re.IGNORECASE)
_WAIVE = re.compile(r"#\s*(nns-san:\s*ok|noqa)")
_GENERATED = ("_pb2.py", "_pb2_grpc.py")


def _dotted(expr: ast.AST) -> Optional[str]:
    """'self._err_lock' for Attribute chains, 'x' for Names, else None."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_ctx(expr: ast.AST, strict: bool) -> bool:
    """True when a `with` context expression names a lock. strict=True
    matches mutexes only (R002: condition waits are idiomatic); False
    also counts condition variables (R001: any synchronized context)."""
    name = _dotted(expr)
    if name is None:
        return False
    last = name.rsplit(".", 1)[-1]
    return bool((_LOCK_NAME if strict else _SYNC_NAME).search(last))


def _catches(handler: ast.ExceptHandler, names: Tuple[str, ...]) -> bool:
    t = handler.type
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    for x in types:
        if isinstance(x, ast.Name) and x.id in names:
            return True
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread":
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    return isinstance(f, ast.Name) and f.id == "Thread"


class _FileChecker:
    def __init__(self, path: str, src: str, report: LintReport) -> None:
        self.path = path
        self.src = src
        self.lines = src.splitlines()
        self.report = report
        self.tree = ast.parse(src, filename=path)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[child] = node

    # -- plumbing ----------------------------------------------------------
    def _where(self, node: ast.AST) -> str:
        return f"{self.path}:{node.lineno}"

    def _waived(self, node: ast.AST) -> bool:
        i = node.lineno - 1
        return 0 <= i < len(self.lines) and bool(_WAIVE.search(self.lines[i]))

    def _add(self, code: str, node: ast.AST, message: str,
             hint: str = "") -> None:
        if not self._waived(node):
            self.report.add(code, self._where(node), message, hint)

    def _ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            yield cur
            cur = self._parents.get(cur)

    def run(self) -> None:
        self._check_excepts()
        self._check_locked_blocking()
        self._check_threads()
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                self._check_shared_writes(node)
                self._check_dekker(node)

    # -- R003 / R004 -------------------------------------------------------
    def _check_excepts(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            bare = node.type is None
            if (bare or _catches(node, ("BaseException",))) \
                    and not _reraises(node):
                kind = "bare except" if bare else "except BaseException"
                self._add(
                    "NNS-R003", node,
                    f"{kind} without re-raise swallows KeyboardInterrupt",
                    "catch Exception, or re-raise after cleanup",
                )
                continue  # don't double-report as R004
            if not (bare or _catches(node, ("Exception", "BaseException"))):
                continue
            if not all(isinstance(s, (ast.Pass, ast.Continue))
                       for s in node.body):
                continue
            in_loop = any(
                isinstance(a, (ast.While, ast.For))
                for a in self._ancestors(node)
            )
            if in_loop:
                self._add(
                    "NNS-R004", node,
                    "except Exception with a pass/continue-only body inside "
                    "a loop silently eats every failure",
                    "log the exception, count it, or narrow the except",
                )

    # -- R002 --------------------------------------------------------------
    def _check_locked_blocking(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.With):
                continue
            if not any(_is_lock_ctx(i.context_expr, strict=True)
                       for i in node.items):
                continue
            for call in self._calls_under(node.body):
                self._flag_blocking(call)

    def _calls_under(self, body: List[ast.stmt]) -> Iterable[ast.Call]:
        """Calls lexically executed under the with — nested function
        bodies run later, outside the lock, so they don't descend."""
        stack: List[ast.AST] = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))

    def _flag_blocking(self, call: ast.Call) -> None:
        f = call.func
        name = _dotted(f) or ""
        kwargs = {k.arg for k in call.keywords}
        if name == "time.sleep":
            self._add("NNS-R002", call,
                      "time.sleep while holding a lock",
                      "sleep outside the critical section")
            return
        if not isinstance(f, ast.Attribute):
            return
        unbounded = not call.args and "timeout" not in kwargs
        if f.attr in ("join", "wait") and unbounded:
            self._add(
                "NNS-R002", call,
                f".{f.attr}() without a timeout while holding a lock",
                "bound the wait or release the lock first",
            )
        elif f.attr in ("recv", "accept"):
            self._add(
                "NNS-R002", call,
                f"blocking socket .{f.attr}() while holding a lock",
                "do network I/O outside the critical section",
            )

    # -- R005 --------------------------------------------------------------
    def _check_threads(self) -> None:
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            if any(k.arg == "daemon" for k in node.keywords):
                continue  # daemon story declared at the ctor
            target = self._assign_target_of(node)
            if target is not None and self._has_join_story(target):
                continue
            self._add(
                "NNS-R005", node,
                "thread created with neither daemon=True nor a reachable "
                ".join()",
                "pass daemon=True or join it on shutdown",
            )

    def _assign_target_of(self, call: ast.Call) -> Optional[str]:
        parent = self._parents.get(call)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
            return _dotted(parent.targets[0])
        if isinstance(parent, ast.AnnAssign):
            return _dotted(parent.target)
        return None

    def _has_join_story(self, target: str) -> bool:
        # textual whole-file search: the join/daemon site usually lives in
        # another method (close/stop), precise scoping buys little here
        pat = rf"(?<![\w.]){re.escape(target)}"
        return bool(
            re.search(rf"{pat}\.join\(", self.src)
            or re.search(rf"{pat}\.daemon\s*=", self.src)
        )

    # -- R001 --------------------------------------------------------------
    def _check_shared_writes(self, cls: ast.ClassDef) -> None:
        if not any(
            isinstance(n, ast.Call) and _is_thread_ctor(n)
            for n in ast.walk(cls)
        ):
            return
        methods = [n for n in cls.body if isinstance(n, ast.FunctionDef)]
        # chain -> [(method name, AugAssign node, under a sync context)]
        sites: Dict[str, List[Tuple[str, ast.AugAssign, bool]]] = {}
        for m in methods:
            for node in ast.walk(m):
                if not isinstance(node, ast.AugAssign):
                    continue
                chain = _dotted(node.target)
                if chain is None or not chain.startswith("self."):
                    continue
                locked = any(
                    isinstance(a, ast.With)
                    and any(_is_lock_ctx(i.context_expr, strict=False)
                            for i in a.items)
                    for a in self._ancestors(node)
                )
                sites.setdefault(chain, []).append((m.name, node, locked))
        for chain, occ in sites.items():
            if len({m for m, _, _ in occ}) < 2:
                continue  # single-writer method: the documented contract
            for m, node, locked in occ:
                if not locked:
                    self._add(
                        "NNS-R001", node,
                        f"{chain} += from {cls.name}.{m} without the owning "
                        "lock, and other methods also read-modify-write it",
                        "hold the lock at every site, or funnel the "
                        "mutation through one method",
                    )

    # -- R006 --------------------------------------------------------------
    def _check_dekker(self, cls: ast.ClassDef) -> None:
        waiting_attrs: Set[str] = set()
        deque_attrs: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    name = _dotted(t)
                    if name is None or not name.startswith("self."):
                        continue
                    attr = name[5:]
                    if "waiting" in attr:
                        waiting_attrs.add(attr)
                    v = node.value
                    if isinstance(v, ast.Call) and (
                        (isinstance(v.func, ast.Name)
                         and v.func.id == "deque")
                        or (isinstance(v.func, ast.Attribute)
                            and v.func.attr == "deque")
                    ):
                        deque_attrs.add(attr)
        if len(waiting_attrs) < 2 or not deque_attrs:
            return  # not channel-like
        peer_checkers = self._methods_reading(cls, waiting_attrs)
        for m in (n for n in cls.body if isinstance(n, ast.FunctionDef)):
            self._dekker_method(m, waiting_attrs, deque_attrs, peer_checkers)

    def _methods_reading(self, cls: ast.ClassDef, attrs: Set[str]) -> Set[str]:
        out: Set[str] = set()
        for m in (n for n in cls.body if isinstance(n, ast.FunctionDef)):
            for node in ast.walk(m):
                if isinstance(node, ast.Attribute) and node.attr in attrs \
                        and isinstance(node.ctx, ast.Load):
                    out.add(m.name)
                    break
        return out

    def _dekker_method(
        self, m: ast.FunctionDef, waiting: Set[str], deques: Set[str],
        peer_checkers: Set[str],
    ) -> None:
        aliases: Set[str] = set()
        for node in ast.walk(m):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                src = _dotted(node.value)
                if src is not None and src.startswith("self.") \
                        and src[5:] in deques:
                    aliases.add(node.targets[0].id)

        def refs_deque(node: ast.AST) -> bool:
            for n in ast.walk(node):
                if isinstance(n, ast.Name) and n.id in aliases:
                    return True
                if isinstance(n, ast.Attribute) and n.attr in deques:
                    return True
            return False

        flag_sets: List[int] = []      # lineno of self._x_waiting = True
        rechecks: List[int] = []       # lineno of an If test over the deque
        for node in ast.walk(m):
            if isinstance(node, ast.Assign):
                name = _dotted(node.targets[0]) if node.targets else None
                if name and name.startswith("self.") \
                        and name[5:] in waiting \
                        and isinstance(node.value, ast.Constant) \
                        and node.value.value is True:
                    flag_sets.append(node.lineno)
            if isinstance(node, (ast.If, ast.While)) \
                    and refs_deque(node.test):
                rechecks.append(node.lineno)

        for node in ast.walk(m):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            # (a) waiter side: event .wait(...) needs an earlier flag set
            # with a deque recheck in between
            if f.attr == "wait":
                prior = [ln for ln in flag_sets if ln < node.lineno]
                if not prior:
                    self._add(
                        "NNS-R006", node,
                        "event wait without advertising a *_waiting flag "
                        "first — the peer cannot see the parked waiter",
                        "set the waiting flag, re-check the deque, then "
                        "wait (executor._Chan discipline)",
                    )
                    continue
                last_set = max(prior)
                if not any(last_set < ln <= node.lineno for ln in rechecks):
                    self._add(
                        "NNS-R006", node,
                        "no deque re-check between setting the waiting "
                        "flag and parking — a push between the first "
                        "check and the flag set is missed",
                        "re-check the deque after advertising the flag",
                    )
            # (b) mover side: append/popleft must be followed by a peer
            # flag check (directly or via a sibling helper)
            if f.attr in ("append", "popleft"):
                tgt = _dotted(f.value) or ""
                is_chan_deque = tgt in aliases or (
                    tgt.startswith("self.") and tgt[5:] in deques
                )
                if not is_chan_deque:
                    continue
                if not self._flag_check_after(m, node, waiting,
                                              peer_checkers):
                    self._add(
                        "NNS-R006", node,
                        f"deque .{f.attr}() with no peer waiting-flag "
                        "check afterwards — a parked peer sleeps out its "
                        "full timeout beat",
                        "check the *_waiting flag (or call the wake "
                        "helper) after the deque op",
                    )

    def _flag_check_after(
        self, m: ast.FunctionDef, op: ast.Call, waiting: Set[str],
        peer_checkers: Set[str],
    ) -> bool:
        for node in ast.walk(m):
            ln = getattr(node, "lineno", None)
            if ln is None or ln < op.lineno:
                continue
            if isinstance(node, ast.Attribute) and node.attr in waiting \
                    and isinstance(node.ctx, ast.Load):
                return True
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in peer_checkers:
                return True
        return False


# -- entry points ------------------------------------------------------------

def iter_py_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for root in paths:
        if os.path.isfile(root):
            out.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py") and not fn.endswith(_GENERATED):
                    out.append(os.path.join(dirpath, fn))
    return out


def check_source(src: str, path: str, report: LintReport) -> None:
    try:
        _FileChecker(path, src, report).run()
    except SyntaxError as exc:
        report.add("NNS-E009", path, f"not parseable as Python: {exc}")


def run_race_lint(paths: Iterable[str],
                  report: Optional[LintReport] = None) -> LintReport:
    """Race-lint every .py under `paths`; returns the shared LintReport."""
    report = report if report is not None else LintReport()
    for path in iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError as exc:
            report.add("NNS-E009", path, f"unreadable: {exc}")
            continue
        check_source(src, os.path.relpath(path), report)
    return report
