"""Shared static byte/cost estimation for placement and nns-xray.

One home for every "how many bytes" question the static tooling asks
(docs/chain-analysis.md), so the Hermes-style placement planner
(serving_plane/placement.py) and the chain analyzer (analysis/xray.py)
cannot drift apart:

- :func:`parse_bytes` / :func:`params_bytes` / :func:`spec_bytes` /
  :func:`estimate_backend_bytes` / :func:`estimate_stage_bytes` — the
  per-stage resident-memory estimators (moved here from placement.py,
  which re-exports them for compatibility).
- :func:`plan_transfer_boundaries` / :func:`predict_frame_transfers` —
  the static mirror of the executor's host<->device negotiation
  (``Node._out_wants_host``, SinkNode ``READS_HOST`` fetches, staged
  H2D): every link where frame bytes will cross the host boundary,
  with the per-frame byte count, so ``TransferTally`` measurements
  have a prediction to be checked against
  (``Executor.transfer_crosscheck``).
- :func:`chain_cost` — per-chain params / activation / transient-HBM
  bytes over :meth:`ExecPlan.chains` compile units.

Everything here is abstract arithmetic over negotiated specs and
params pytrees — ``eval_shape``-style, nothing is allocated on device.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from nnstreamer_tpu.log import get_logger

_log = get_logger("analysis.costmodel")


def parse_bytes(raw: str) -> int:
    """``"256M"`` → 268435456 (K/M/G binary suffixes; plain ints pass
    through)."""
    s = str(raw).strip()
    if not s:
        raise ValueError("empty byte size")
    mult = 1
    suffix = s[-1].upper()
    if suffix in ("K", "M", "G"):
        mult = {"K": 1 << 10, "M": 1 << 20, "G": 1 << 30}[suffix]
        s = s[:-1]
    return int(float(s) * mult)


def params_bytes(tree: Any) -> int:
    """Total bytes of a params pytree (weights resident on device)."""
    if tree is None:
        return 0
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return total


_VIDEO_CHANNELS = {"RGB": 3, "BGR": 3, "RGBA": 4, "BGRA": 4, "GRAY8": 1}


def spec_bytes(spec: Any) -> int:
    """Activation bytes of a TensorsSpec (0 for flexible/None specs).
    Video MediaSpecs (a source feeding tensor_converter — the bytes a
    staged H2D upload would move) estimate width x height x channels."""
    if spec is None:
        return 0
    if getattr(spec, "media_type", None) == "video":
        w = getattr(spec, "width", None)
        h = getattr(spec, "height", None)
        if not w or not h:
            return 0
        ch = _VIDEO_CHANNELS.get(getattr(spec, "format", "RGB"), 3)
        return int(w) * int(h) * ch
    if not getattr(spec, "is_static", False):
        return 0
    total = 0
    for t in spec:
        total += int(
            np.prod(t.shape, dtype=np.int64)
        ) * np.dtype(t.dtype.np_dtype).itemsize
    return total


def estimate_backend_bytes(backend: Any) -> int:
    """Resident bytes an opened backend will hold on its device:
    params (the dominant term for real models) + one in-flight set of
    input/output activations. Abstract arithmetic over specs — nothing
    is allocated."""
    total = params_bytes(getattr(backend, "_params", None))
    try:
        in_spec, out_spec = backend.get_model_info()
    except Exception:  # noqa: BLE001 — shape-polymorphic: activations unknown
        return total
    return total + spec_bytes(in_spec) + spec_bytes(out_spec)


def estimate_stage_bytes(elem: Any) -> int:
    """Per-stage estimate for a tensor_filter element (opens the
    backend it will serve with anyway — no throwaway copy)."""
    backend = elem._ensure_open()
    return estimate_backend_bytes(backend)


# -- static transfer prediction ---------------------------------------------
#
# The executor decides per link whether frame bytes cross the host
# boundary (pipeline/executor.py Node._out_wants_host, SinkNode
# READS_HOST, FusedNode staging; docs/streaming.md). The functions
# below re-derive those decisions STATICALLY from the compiled plan so
# the per-frame transfer bytes are a prediction, not only a runtime
# tally.

@dataclass(frozen=True)
class TransferBoundary:
    """One link where frame bytes cross the host<->device boundary."""

    producer: str        # element whose output crosses
    consumer: str        # element that triggers the crossing
    direction: str       # "h2d" | "d2h"
    bytes_per_frame: int
    reason: str          # producer-fetch | host-node-fetch | sink-fetch
    #                    # | stage


def _is_transparent(e: Any) -> bool:
    """Elements the executor wires AROUND for handoff purposes: queue
    and capsfilter declare DEVICE_PASSTHROUGH (device arrays ride
    through untouched); tee is eliminated at build, so a producer sees
    the tee's consumers directly."""
    from nnstreamer_tpu.elements.flow import Tee

    return bool(getattr(type(e), "DEVICE_PASSTHROUGH", False)) or isinstance(
        e, Tee
    )


def _effective_consumers(pipeline, e: Any) -> List[Any]:
    """Downstream elements of ``e`` with transparent plumbing resolved
    away (the post-elimination consumer set the executor negotiates
    with)."""
    out: List[Any] = []
    seen = set()
    frontier = [l.dst for l in pipeline.out_links(e)]
    while frontier:
        n = frontier.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        if _is_transparent(n):
            frontier.extend(l.dst for l in pipeline.out_links(n))
        else:
            out.append(n)
    return out


def _consumer_reads_host(plan, e: Any) -> bool:
    """Static mirror of the consumer side of ``Node._out_wants_host``:
    True when delivering a device array to ``e`` costs a D2H fetch
    (at the producer or at the consumer's own node — tallied bytes are
    the same either way)."""
    from nnstreamer_tpu.elements.base import Routing, Sink, TensorOp

    if getattr(type(e), "WANTS_HOST", False):
        return True
    if isinstance(e, Sink):
        return bool(getattr(e, "READS_HOST", True))
    if isinstance(e, Routing):
        return False  # regroups frames without touching bytes
    if isinstance(e, TensorOp):
        if e in plan.seg_of:
            return False  # fused: the segment chains on device
        probe = getattr(e, "wants_host_input", None)
        if callable(probe):
            try:
                return bool(probe())
            except Exception:  # noqa: BLE001 — unopened backend: host path
                return True
        return True  # host-path TensorOp node reads host bytes
    return True  # HostElement and anything unknown: assume host reader


def _out_is_device(plan, e: Any, memo: Dict[int, bool]) -> bool:
    """Static device-residency of an element's output frames."""
    from nnstreamer_tpu.elements.base import Routing, Source, TensorOp

    key = id(e)
    if key in memo:
        return memo[key]
    memo[key] = False  # cycle guard (lint runs on arbitrary graphs)
    pipeline = plan.pipeline

    def inputs_device() -> bool:
        return any(
            _out_is_device(plan, l.src, memo) for l in pipeline.in_links(e)
        )

    if isinstance(e, Source):
        dev = bool(getattr(e, "device", False))
    elif e in plan.seg_of:
        seg = plan.seg_of[e]
        # identity segments (passthrough backends) forward frames
        # untouched, so residency propagates; real programs emit device
        # arrays (jax outputs count for the D2H tally even on the CPU
        # backend — pipeline/transfer.py FrameFetch)
        dev = True
        try:
            if seg.is_identity():
                dev = any(
                    _out_is_device(plan, l.src, memo)
                    for l in pipeline.in_links(seg.first)
                )
        except Exception:  # noqa: BLE001 — unopened backend: not identity
            dev = True
    elif _is_transparent(e) or isinstance(e, Routing):
        dev = inputs_device()
    elif isinstance(e, TensorOp):
        # host-path node: device-pinned filters (wants_host_input False)
        # run a placed program and emit device arrays; plain host ops
        # emit numpy
        probe = getattr(e, "wants_host_input", None)
        if callable(probe):
            try:
                dev = not probe()
            except Exception:  # noqa: BLE001
                dev = False
        else:
            dev = False
    else:
        dev = False  # HostElement / sinks produce nothing device
    memo[key] = dev
    return dev


def plan_transfer_boundaries(
    plan, assume_tpu: Optional[bool] = None
) -> List[TransferBoundary]:
    """Every host-boundary crossing the executor will pay per frame.

    ``assume_tpu`` overrides the platform default: on a process-local
    CPU backend staged H2D is a pass-through (pipeline/transfer.py
    ``stage_frame``), so predicted h2d is 0 there; D2H fetches tally on
    every backend. Pass ``assume_tpu=True`` for the what-would-TPU-pay
    view nns-xray reports."""
    from nnstreamer_tpu.elements.base import Sink, TensorOp
    from nnstreamer_tpu.pipeline.transfer import default_backend_is_cpu

    if assume_tpu is None:
        assume_tpu = not default_backend_is_cpu()
    pipeline = plan.pipeline
    memo: Dict[int, bool] = {}
    out: List[TransferBoundary] = []
    for e in pipeline.elements:
        if isinstance(e, Sink) or _is_transparent(e):
            continue
        if not pipeline.out_links(e):
            continue
        consumers = _effective_consumers(pipeline, e)
        if not consumers:
            continue
        out_bytes = spec_bytes(e.out_specs[0]) if e.out_specs else 0
        if _out_is_device(plan, e, memo):
            readers = [
                c for c in consumers if _consumer_reads_host(plan, c)
            ]
            if not readers:
                continue
            if len(readers) == len(consumers) and not any(
                isinstance(c, Sink) for c in consumers
            ):
                # Node._out_wants_host: every consumer reads host and
                # none is a sink — ONE coalesced producer-side fetch
                out.append(TransferBoundary(
                    e.name, ",".join(c.name for c in readers), "d2h",
                    out_bytes, "producer-fetch",
                ))
                continue
            for c in readers:
                reason = (
                    "sink-fetch" if isinstance(c, Sink)
                    else "host-node-fetch"
                )
                out.append(TransferBoundary(
                    e.name, c.name, "d2h", out_bytes, reason,
                ))
        elif assume_tpu:
            # host-resident output: each fused-segment consumer stages
            # its input to device (FusedNode H2D; free on local CPU)
            for c in consumers:
                if isinstance(c, TensorOp) and c in plan.seg_of:
                    out.append(TransferBoundary(
                        e.name, c.name, "h2d", out_bytes, "stage",
                    ))
    return out


def predict_frame_transfers(
    plan, assume_tpu: Optional[bool] = None
) -> Dict[str, int]:
    """Predicted host<->device bytes PER FRAME for a 1:1 pipeline —
    the static counterpart of ``Executor.transfer_totals()`` divided
    by frames produced. Cardinality-changing elements (rate limiters,
    aggregation windows) make the per-frame view approximate; the
    executor's cross-check weighs each boundary by its producer node's
    own frame count instead."""
    totals = {"h2d": 0, "d2h": 0}
    for b in plan_transfer_boundaries(plan, assume_tpu=assume_tpu):
        totals[b.direction] += b.bytes_per_frame
    return totals


# -- per-chain cost model ---------------------------------------------------

@dataclass
class ChainCost:
    """Static memory/transfer cost of one compile-unit chain
    (docs/chain-analysis.md "Cost model"):

    - ``params_bytes``: member backends' weights, resident for the
      chain's lifetime.
    - ``activation_bytes``: one in-flight frame's negotiated inputs +
      outputs summed over the chain's segments.
    - ``transient_bytes``: peak per-segment working set — the widest
      segment's input + output + jaxpr intermediate values, scaled by
      the max micro-batch bucket (the arena XLA needs while that
      program runs; upper bound, no buffer-reuse modeling).
    - ``boundary_in_bytes`` / ``boundary_out_bytes``: per-frame bytes
      entering/leaving the chain at its edges (what the chain would pay
      at a host boundary if one appears there).
    """

    params_bytes: int = 0
    activation_bytes: int = 0
    transient_bytes: int = 0
    boundary_in_bytes: int = 0
    boundary_out_bytes: int = 0
    segments: List[str] = field(default_factory=list)

    @property
    def resident_bytes(self) -> int:
        return self.params_bytes + self.transient_bytes


def _segment_intermediate_bytes(seg) -> int:
    """Sum of jaxpr intermediate-value bytes for one segment's composed
    program at the negotiated per-frame signature (eval_shape-style —
    abstract tracing only). 0 when the segment cannot be traced here
    (unopened/host backend): the in+out activations still count."""
    import jax

    sig = seg._negotiated_sig()
    if sig is None:
        return 0
    try:
        composed = seg._compose()
        shapes = [
            jax.ShapeDtypeStruct(shape, dtype) for shape, dtype in sig
        ]
        jaxpr = jax.make_jaxpr(composed)(*shapes)
    except Exception:  # noqa: BLE001 — cost model degrades, never raises
        return 0
    total = 0
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = getattr(aval, "shape", None)
            if shape is None:
                continue
            total += int(
                np.prod(shape, dtype=np.int64)
            ) * np.dtype(aval.dtype).itemsize
    return total


def chain_cost(chain, open_backends: bool = True) -> ChainCost:
    """Static cost of one :class:`~nnstreamer_tpu.pipeline.graph.Chain`.
    ``open_backends=False`` skips params estimation (no model load) —
    activation/transient arithmetic still runs."""
    cost = ChainCost(segments=[seg.name for seg in chain.segments])
    for seg in chain.segments:
        in_b = spec_bytes(seg.first.in_specs[0] if seg.first.in_specs else None)
        out_b = spec_bytes(
            seg.last.out_specs[0] if seg.last.out_specs else None
        )
        cost.activation_bytes += in_b + out_b
        bucket = 1
        cfg = seg.batch_config
        if cfg is not None and getattr(cfg, "active", False) and cfg.buckets:
            bucket = int(cfg.buckets[-1])
        transient = (in_b + out_b + _segment_intermediate_bytes(seg)) * bucket
        cost.transient_bytes = max(cost.transient_bytes, transient)
        if open_backends:
            for op in seg.ops:
                ensure = getattr(op, "_ensure_open", None)
                if not callable(ensure):
                    continue
                try:
                    cost.params_bytes += params_bytes(
                        getattr(ensure(), "_params", None)
                    )
                except Exception:  # noqa: BLE001 — unopenable: skip params
                    pass
    first, last = chain.segments[0], chain.segments[-1]
    cost.boundary_in_bytes = spec_bytes(
        first.first.in_specs[0] if first.first.in_specs else None
    )
    cost.boundary_out_bytes = spec_bytes(
        last.last.out_specs[0] if last.last.out_specs else None
    )
    return cost


# -- per-kernel roofline cost model (nns-kscope) ----------------------------

#: VMEM per TensorCore on every shipping TPU generation to date; the
#: default for :func:`configured_vmem_bound` when ``[tpu] vmem_bytes``
#: is unset.
DEFAULT_VMEM_BYTES = 16 << 20


@dataclass
class KernelCost:
    """Static roofline row for one registered Pallas kernel × shape
    (docs/kernel-analysis.md "Roofline columns"): HBM bytes moved (the
    index-map transition count over the grid — what the pallas pipeline
    actually re-fetches — not the naive operand-size sum), FLOPs from
    the kernel's registered estimate, and their ratio. Abstract
    arithmetic over the registered LaunchPlan; nothing is allocated."""

    hbm_read_bytes: int = 0
    hbm_write_bytes: int = 0
    flops: int = 0

    @property
    def hbm_bytes(self) -> int:
        return self.hbm_read_bytes + self.hbm_write_bytes

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per HBM byte — the roofline x-axis. Kernels below a
        TPU's ridge point (~100s of flops/byte) are memory-bound: more
        VMEM blocking won't help, less HBM traffic will."""
        return self.flops / self.hbm_bytes if self.hbm_bytes else 0.0


def configured_vmem_bound() -> int:
    """The per-core VMEM budget the W127 kernel lint checks per-grid-
    step residency against: ``[tpu] vmem_bytes`` (bytes, K/M/G
    suffixes), defaulting to 16 MiB — unlike the HBM bound, a VMEM
    ceiling always exists in hardware, so the lint never stays silent
    for want of configuration."""
    from nnstreamer_tpu.config import conf

    raw = conf().get("tpu", "vmem_bytes", "")
    if not raw:
        return DEFAULT_VMEM_BYTES
    try:
        return parse_bytes(raw)
    except ValueError:
        _log.warning(
            "[tpu] vmem_bytes=%r is not a byte size; using the %d MiB "
            "default", raw, DEFAULT_VMEM_BYTES >> 20,
        )
        return DEFAULT_VMEM_BYTES


def configured_device_bound() -> Optional[int]:
    """The per-device HBM bound the placement planner and the W124
    chain lint share: ``[plane] memory_per_device`` (bytes, K/M/G
    suffixes). None = no bound declared, W124 stays silent."""
    from nnstreamer_tpu.config import conf

    raw = conf().get("plane", "memory_per_device", "")
    if not raw:
        return None
    try:
        return parse_bytes(raw)
    except ValueError:
        _log.warning(
            "[plane] memory_per_device=%r is not a byte size; no bound",
            raw,
        )
        return None
