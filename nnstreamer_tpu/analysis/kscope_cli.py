"""nns-kscope: the static Pallas kernel analyzer CLI.

    nns-kscope                     # VMEM/alignment/roofline per kernel x shape
    nns-kscope --json              # machine-readable rows + findings
    nns-kscope --kernel flash_attention
    nns-kscope --self-check        # wiring check + interpret-mode parity sweep
    nns-kscope --self-check --full # ... over the full shape grid (slow)
    nns-kscope --engage            # prove requested pallas paths engage
    nns-kscope --strict            # warnings fail hard (exit 2)

Reports, for every registered kernel x representative shape
(ops/pallas/registry.py): per-grid-step VMEM residency vs the
``[tpu] vmem_bytes`` bound, lane/sublane tile alignment, index-map
hazards, and a roofline cost row (HBM bytes by index-map transition
counting, FLOPs, arithmetic intensity) — all statically, no device.
Findings are NNS-W127/W128 (docs/kernel-analysis.md). ``--engage``
runs each kernel's tiny interpret-mode probe and diffs the dispatch
tally; a requested pallas path that silently fell back exits nonzero.
Exit codes: 0 clean, 1 warnings only, 2 errors.
"""

from __future__ import annotations

import argparse
import json
import sys

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()


def _print_case(r) -> None:
    flags = []
    if r.over_budget:
        flags.append("OVER-VMEM")
    if r.misaligned:
        flags.append("MISALIGNED:" + ",".join(b.name for b in r.misaligned))
    if r.hazards:
        flags.append(f"{len(r.hazards)} hazard(s)")
    tail = (" [" + " ".join(flags) + "]") if flags else ""
    print(
        f"{r.kernel}:{r.case}: grid={r.grid} "
        f"vmem={r.vmem_bytes}/{r.vmem_bound}B "
        f"hbm={r.cost.hbm_bytes}B flops={r.cost.flops} "
        f"ai={r.cost.arithmetic_intensity:.2f}{tail}"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-kscope", description=__doc__)
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--kernel", default="",
        help="analyze only this registered kernel",
    )
    ap.add_argument(
        "--self-check", action="store_true",
        help="W127-W129 emitters<->catalog<->docs + registry wiring, "
        "then the interpret-mode differential sweep vs each kernel's "
        "jnp reference (tier-1 shape subset)",
    )
    ap.add_argument(
        "--full", action="store_true",
        help="with --self-check: sweep the FULL shape grid (slow)",
    )
    ap.add_argument(
        "--engage", action="store_true",
        help="run each kernel's tiny probe with pallas requested and "
        "diff the dispatch tally; nonzero if any path fell back",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors (warnings-only runs exit 2)",
    )
    ap.add_argument("--quiet", "-q", action="store_true")
    args = ap.parse_args(argv)

    from nnstreamer_tpu.analysis import kernels as K
    from nnstreamer_tpu.ops.pallas import registry as kreg

    specs = None
    if args.kernel:
        spec = kreg.find(args.kernel)
        if spec is None:
            print(
                f"unknown kernel {args.kernel!r}; registered: "
                + ", ".join(kreg.names()),
                file=sys.stderr,
            )
            return 2
        specs = [spec]

    if args.self_check:
        from nnstreamer_tpu.analysis.selfcheck import kscope_self_check

        problems = kscope_self_check()
        for p in problems:
            print(p)
        rows = K.differential_sweep(specs, full=args.full)
        for row in rows:
            if row["ok"]:
                if not args.quiet:
                    print(
                        f"{row['kernel']}:{row['case']}: OK "
                        f"(max_err={row['max_err']:.2e})"
                    )
            else:
                print(
                    f"{row['kernel']}:{row['case']}: FAIL {row['error']}"
                )
        bad = [r for r in rows if not r["ok"]]
        print(
            "kscope self-check: "
            + ("OK" if not problems and not bad
               else f"{len(problems)} problem(s), {len(bad)} parity "
               "failure(s)")
        )
        return 1 if problems or bad else 0

    if args.engage:
        rows = K.engage(specs)
        if args.json:
            print(json.dumps(rows, indent=2))
        else:
            for row in rows:
                impls = ",".join(row["impls"]) or "-"
                line = (
                    f"{row['kernel']} ({row['op']}): "
                    f"{'engaged' if row['ok'] else 'FELL BACK'} "
                    f"[{impls}]"
                )
                if row.get("error"):
                    line += f" ({row['error']})"
                print(line)
        return 0 if all(r["ok"] for r in rows) else 1

    reports, lint_report = K.analyze(specs)
    rc = lint_report.exit_code
    if args.strict and rc == 1:
        rc = 2  # warnings fail hard under --strict
    if args.json:
        print(json.dumps(
            {
                "exit_code": rc,
                "cases": [r.to_row() for r in reports],
                "diagnostics": [
                    {
                        "code": d.code,
                        "severity": d.severity.value,
                        "slug": d.slug,
                        "element": d.element,
                        "message": d.message,
                        "hint": d.hint,
                    }
                    for d in lint_report.diagnostics
                ],
            },
            indent=2,
        ))
        return rc
    if not args.quiet:
        for r in reports:
            _print_case(r)
    if lint_report.diagnostics:
        print(lint_report.render())
    elif not args.quiet:
        print(f"{len(reports)} kernel case(s) clean")
    return rc


if __name__ == "__main__":
    sys.exit(main())
