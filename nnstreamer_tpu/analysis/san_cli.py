"""nns-san: the concurrency race/deadlock analyzer CLI.

    nns-san --race [paths...]     # AST concurrency lint (default: the
                                  # installed nnstreamer_tpu package)
    nns-san --deadlock "a ! b"    # graph deadlock/capacity findings only
    nns-san --self-check          # diagnostic catalog covers the code?
    nns-san --json --race ...     # machine-readable findings

Exit codes: 0 clean, 1 warnings only, 2 errors (and 1 on --self-check
failure); ``--strict`` treats warnings as errors. The RUNTIME half of the
sanitizer is enabled per run with ``NNS_TPU_SANITIZE=1`` (see
docs/sanitizer.md) — this CLI is the static half.
"""

from __future__ import annotations

import argparse
import json
import sys

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()


def _emit(report, as_json: bool, strict: bool) -> int:
    rc = report.exit_code
    if strict and rc == 1:
        rc = 2
    if as_json:
        print(json.dumps(
            {
                "exit_code": rc,
                "diagnostics": [
                    {
                        "code": d.code,
                        "severity": d.severity.value,
                        "slug": d.slug,
                        "where": d.element,
                        "message": d.message,
                        "hint": d.hint,
                    }
                    for d in report.diagnostics
                ],
            },
            indent=2,
        ))
    else:
        print(report.render())
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-san", description=__doc__)
    ap.add_argument(
        "--race", nargs="*", metavar="PATH",
        help="race-lint .py sources (default: the nnstreamer_tpu package)",
    )
    ap.add_argument(
        "--deadlock", metavar="DESC",
        help="graph deadlock/capacity analysis of a pipeline description",
    )
    ap.add_argument(
        "--self-check", action="store_true",
        help="validate the diagnostic catalog against the code",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings")
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors (exit 2)",
    )
    args = ap.parse_args(argv)

    if args.self_check:
        from nnstreamer_tpu.analysis.selfcheck import san_self_check

        problems = san_self_check()
        for p in problems:
            print(p)
        if problems:
            print(f"{len(problems)} catalog problem(s)")
            return 1
        print("diagnostic catalog covers the code")
        return 0

    if args.deadlock is not None:
        from nnstreamer_tpu.analysis.diagnostics import LintReport
        from nnstreamer_tpu.analysis.lint import DEADLOCK_CODES, lint

        full = lint(args.deadlock)
        report = LintReport(
            [d for d in full.diagnostics if d.code in DEADLOCK_CODES]
        )
        return _emit(report, args.json, args.strict)

    if args.race is not None:
        import os

        import nnstreamer_tpu
        from nnstreamer_tpu.analysis.racecheck import run_race_lint

        paths = args.race or [os.path.dirname(nnstreamer_tpu.__file__)]
        report = run_race_lint(paths)
        return _emit(report, args.json, args.strict)

    ap.error("one of --race, --deadlock, --self-check is required")
    return 2  # pragma: no cover - ap.error exits


if __name__ == "__main__":
    sys.exit(main())
