"""nns-xray: the whole-chain compile-unit analyzer CLI.

    nns-xray "videotestsrc device=true ! tensor_converter ! ..."
    nns-xray --json "..."          # machine-readable chains + findings
    nns-xray --dispatch            # which Pallas/jnp kernels engage
    nns-xray --self-check          # W120-W124 emitters<->catalog<->docs
    nns-xray --strict "..."        # warnings fail hard (exit 2)

Reports compile units (chains of fused segments joined by device
handoffs), per-chain params/activation/transient bytes, predicted
per-frame host-transfer bytes at every boundary, and the jaxpr lint
findings (NNS-W120..W124) — see docs/chain-analysis.md. Exit codes:
0 clean/degraded, 1 warnings only, 2 errors. The pipeline is compiled
(negotiation runs, backends open) but NEVER started.
"""

from __future__ import annotations

import argparse
import json
import sys

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-xray", description=__doc__)
    ap.add_argument("description", nargs="?", help="pipeline description")
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--dispatch", action="store_true",
        help="print the kernel dispatch table (impl=auto: pallas vs "
        "fallback, statically and measured by tiny probe invocations)",
    )
    ap.add_argument(
        "--no-probe", action="store_true",
        help="with --dispatch: static columns only, no probe invocations",
    )
    ap.add_argument(
        "--self-check", action="store_true",
        help="verify the W120-W124 emitters<->catalog<->docs wiring",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors (warnings-only runs exit 2)",
    )
    ap.add_argument("--quiet", "-q", action="store_true")
    args = ap.parse_args(argv)

    if args.self_check:
        from nnstreamer_tpu.analysis.selfcheck import xray_self_check

        problems = xray_self_check()
        for p in problems:
            print(p)
        print(
            "xray self-check: "
            + ("OK" if not problems else f"{len(problems)} problem(s)")
        )
        return 1 if problems else 0

    from nnstreamer_tpu.analysis.xray import dispatch_table, xray

    if args.dispatch and not args.description:
        rows = dispatch_table(run=not args.no_probe)
        if args.json:
            print(json.dumps(rows, indent=2))
        else:
            for row in rows:
                measured = ",".join(row["measured"]) or "-"
                line = (
                    f"{row['op']}: on-tpu={row['auto_on_tpu']} "
                    f"here={row['auto_here']} measured={measured}"
                )
                if row.get("error"):
                    line += f" ({row['error']})"
                print(line)
        return 0
    if not args.description:
        ap.error(
            "pipeline description required (or --dispatch / --self-check)"
        )

    result = xray(args.description)
    if args.dispatch:
        result.dispatch = dispatch_table(run=not args.no_probe)
    rc = result.exit_code
    if args.strict and rc == 1:
        rc = 2  # warnings fail hard under --strict
    if args.json:
        print(json.dumps(
            {
                "exit_code": rc,
                "degraded": result.degraded,
                "chains": [
                    {
                        "name": c.name,
                        "segments": c.segments,
                        "n_ops": c.n_ops,
                        "params_bytes": c.cost.params_bytes,
                        "activation_bytes": c.cost.activation_bytes,
                        "transient_bytes": c.cost.transient_bytes,
                        "boundary_in_bytes": c.cost.boundary_in_bytes,
                        "boundary_out_bytes": c.cost.boundary_out_bytes,
                        "notes": c.notes,
                    }
                    for c in result.chains
                ],
                "boundaries": [
                    {
                        "producer": b.producer,
                        "consumer": b.consumer,
                        "direction": b.direction,
                        "bytes_per_frame": b.bytes_per_frame,
                        "reason": b.reason,
                    }
                    for b in result.boundaries
                ],
                "predicted": result.predicted,
                "predicted_tpu": result.predicted_tpu,
                "dispatch": result.dispatch,
                "notes": result.notes,
                "errors": result.errors,
                "diagnostics": [
                    {
                        "code": d.code,
                        "severity": d.severity.value,
                        "slug": d.slug,
                        "element": d.element,
                        "message": d.message,
                        "hint": d.hint,
                    }
                    for d in result.diagnostics
                ],
            },
            indent=2,
        ))
        return rc
    if not args.quiet or result.diagnostics or result.errors:
        print(result.render())
    return rc


if __name__ == "__main__":
    sys.exit(main())
