"""nns-lint: the standalone static-analyzer CLI.

    nns-lint "videotestsrc ! tensor_converter ! tensor_sink"
    nns-lint --dot "..." > graph.dot     # diagnostics painted on nodes
    nns-lint --json "..."                # machine-readable findings
    nns-lint --self-check                # PROPERTIES schemas cover code?
    nns-lint --strict "..."              # warnings fail hard (exit 2)

Exit codes: 0 clean, 1 warnings only, 2 errors (and 1 on --self-check
failure). The pipeline is parsed and analyzed but NEVER started. The
sibling `nns-san` CLI covers the concurrency race lint and the runtime
sanitizer (docs/sanitizer.md).
"""

from __future__ import annotations

import argparse
import json
import sys

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="nns-lint", description=__doc__)
    ap.add_argument("description", nargs="?", help="pipeline description")
    ap.add_argument(
        "--dot", action="store_true",
        help="print graphviz with diagnostics annotated on the nodes",
    )
    ap.add_argument("--json", action="store_true", help="JSON findings")
    ap.add_argument(
        "--self-check", action="store_true",
        help="verify every builtin element's PROPERTIES schema covers the "
        "properties its code reads",
    )
    ap.add_argument(
        "--strict", action="store_true",
        help="treat warnings as errors (warnings-only runs exit 2)",
    )
    ap.add_argument("--quiet", "-q", action="store_true")
    args = ap.parse_args(argv)

    if args.self_check:
        from nnstreamer_tpu.analysis.selfcheck import main as selfcheck_main

        return selfcheck_main()
    if not args.description:
        ap.error("pipeline description required (or --self-check)")

    from nnstreamer_tpu.analysis import annotated_dot, lint

    result = lint(args.description)
    rc = result.exit_code
    if args.strict and rc == 1:
        rc = 2  # warnings fail hard under --strict
    if args.dot:
        print(annotated_dot(result))
        return rc
    if args.json:
        print(json.dumps(
            {
                "exit_code": rc,
                "diagnostics": [
                    {
                        "code": d.code,
                        "severity": d.severity.value,
                        "slug": d.slug,
                        "element": d.element,
                        "message": d.message,
                        "hint": d.hint,
                    }
                    for d in result.diagnostics
                ],
            },
            indent=2,
        ))
        return rc
    if not args.quiet or result.diagnostics:
        print(result.render())
    return rc


if __name__ == "__main__":
    sys.exit(main())
