// nns_shm: single-producer/single-consumer shared-memory ring transport.
//
// Role: the same-host fast path of the among-device layer. The reference
// moves frames between co-located pipelines through loopback TCP via the
// nnstreamer-edge library (gst/edge/, tensor_query elements); for pipeline
// shards living on one host that pays two socket copies plus syscall
// round-trips per frame. This ring hands length-prefixed messages through
// one POSIX shm segment with a process-shared mutex/condvar pair — one
// memcpy in, one memcpy out, no sockets, honest blocking with timeouts.
//
// Not derived from the reference's C sources: different wire model
// (framed ring, not stream), different sync (pthread process-shared
// condvars, not poll loops).
//
// ABI (extern "C", used via ctypes from edge/shm.py):
//   nns_shm_create(name, capacity) -> handle   producer side, creates
//   nns_shm_open(name)             -> handle   consumer side, attaches
//   nns_shm_write(h, buf, len, timeout_ms) -> 1 ok, 0 timeout, -1 error
//   nns_shm_read(h, buf, cap, timeout_ms) -> n bytes, 0 timeout,
//                                            -1 closed+drained, -2 cap too small
//   nns_shm_reader_count(h) -> attached consumers
//   nns_shm_mark_closed(h)   producer EOS: readers drain then see -1
//   nns_shm_close(h, unlink)
//
// Layout: [Header][ring bytes]. Messages are u32-length-prefixed and may
// wrap. A length prefix of 0xFFFFFFFF is a wrap marker (skip to ring
// start) so a prefix never splits across the boundary.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

// Bumped with every Header-layout change: stale segments from older
// builds then fail the magic check and get reclaimed instead of being
// misread ("2" added creator_pid).
constexpr uint64_t kMagic = 0x4e4e53534d454d32ull;  // "NNSSMEM2"
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;

struct Header {
  uint64_t magic;
  uint64_t capacity;       // ring data bytes
  uint64_t write_pos;      // absolute byte offsets (mod capacity for index)
  uint64_t read_pos;
  uint32_t closed;         // producer finished
  uint32_t readers;        // attached consumer count
  int32_t creator_pid;     // liveness probe target for stale reclamation
  pthread_mutex_t mu;
  pthread_cond_t can_read;
  pthread_cond_t can_write;
};

struct Handle {
  Header* h;
  uint8_t* ring;
  size_t map_len;
  char name[256];
  int creator;
};

uint64_t used(const Header* h) { return h->write_pos - h->read_pos; }

void abs_deadline(struct timespec* ts, int timeout_ms) {
  clock_gettime(CLOCK_REALTIME, ts);
  ts->tv_sec += timeout_ms / 1000;
  ts->tv_nsec += (long)(timeout_ms % 1000) * 1000000L;
  if (ts->tv_nsec >= 1000000000L) {
    ts->tv_sec += 1;
    ts->tv_nsec -= 1000000000L;
  }
}

// copy into the ring at logical offset (no wrap handling — callers ensure
// the region is contiguous)
void ring_put(Header* h, uint8_t* ring, uint64_t pos, const void* src,
              size_t n) {
  memcpy(ring + (pos % h->capacity), src, n);
}

void ring_get(Header* h, const uint8_t* ring, uint64_t pos, void* dst,
              size_t n) {
  memcpy(dst, ring + (pos % h->capacity), n);
}

}  // namespace

extern "C" {

void* nns_shm_create(const char* name, uint64_t capacity) {
  if (capacity < 4096) capacity = 4096;
  size_t total = sizeof(Header) + capacity;
  // A LIVE producer's segment must not be clobbered (mirror TCP listen's
  // EADDRINUSE). Reclaim only when the previous producer marked it closed
  // or its pid is gone (crashed run).
  int probe = shm_open(name, O_RDWR, 0600);
  if (probe >= 0) {
    struct stat st;
    bool reclaim = false;
    if (fstat(probe, &st) == 0 && (size_t)st.st_size >= sizeof(Header)) {
      void* mem = mmap(nullptr, sizeof(Header), PROT_READ, MAP_SHARED,
                       probe, 0);
      if (mem != MAP_FAILED) {
        Header* ph = (Header*)mem;
        bool creator_dead =
            ph->creator_pid > 0 &&
            kill(ph->creator_pid, 0) != 0 && errno == ESRCH;
        reclaim = (ph->magic != kMagic) || ph->closed || creator_dead;
        munmap(mem, sizeof(Header));
      }
    } else {
      reclaim = true;  // truncated debris
    }
    close(probe);
    if (!reclaim) return nullptr;  // live producer owns the name
    shm_unlink(name);
  }
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* h = (Header*)mem;
  memset(h, 0, sizeof(Header));
  h->capacity = capacity;
  h->creator_pid = (int32_t)getpid();

  pthread_mutexattr_t ma;
  pthread_mutexattr_init(&ma);
  pthread_mutexattr_setpshared(&ma, PTHREAD_PROCESS_SHARED);
  pthread_mutex_init(&h->mu, &ma);
  pthread_mutexattr_destroy(&ma);
  pthread_condattr_t ca;
  pthread_condattr_init(&ca);
  pthread_condattr_setpshared(&ca, PTHREAD_PROCESS_SHARED);
  pthread_cond_init(&h->can_read, &ca);
  pthread_cond_init(&h->can_write, &ca);
  pthread_condattr_destroy(&ca);
  h->magic = kMagic;  // last: attachers spin on it

  Handle* hd = new Handle();
  hd->h = h;
  hd->ring = (uint8_t*)mem + sizeof(Header);
  hd->map_len = total;
  hd->creator = 1;
  strncpy(hd->name, name, sizeof(hd->name) - 1);
  return hd;
}

void* nns_shm_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (size_t)st.st_size < sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED,
           fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Header* h = (Header*)mem;
  if (h->magic != kMagic ||
      sizeof(Header) + h->capacity > (uint64_t)st.st_size) {
    munmap(mem, (size_t)st.st_size);
    return nullptr;
  }
  Handle* hd = new Handle();
  hd->h = h;
  hd->ring = (uint8_t*)mem + sizeof(Header);
  hd->map_len = (size_t)st.st_size;
  hd->creator = 0;
  strncpy(hd->name, name, sizeof(hd->name) - 1);
  pthread_mutex_lock(&h->mu);
  h->readers += 1;
  pthread_mutex_unlock(&h->mu);
  return hd;
}

int nns_shm_write(void* handle, const void* data, uint64_t len,
                  int timeout_ms) {
  Handle* hd = (Handle*)handle;
  if (!hd || !hd->h) return -1;
  Header* h = hd->h;
  uint64_t cap = h->capacity;
  // cap/2 bound: a wrap can consume up to room_to_end (< 4+len) padding
  // bytes on top of 4+len, so the worst-case need is < 2*(4+len); bounding
  // len at cap/2-8 guarantees any message eventually fits from any ring
  // position (no livelock on an empty-but-misaligned ring)
  if (len + 8 > cap / 2) return -1;
  struct timespec ts;
  abs_deadline(&ts, timeout_ms);
  pthread_mutex_lock(&h->mu);
  for (;;) {
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -1;
    }
    // exact need from the CURRENT write position: wrapping consumes the
    // padding/marker bytes to the ring end plus the prefixed message
    uint64_t idx_now = h->write_pos % cap;
    uint64_t room_now = cap - idx_now;
    uint64_t need =
        (room_now < 4 + len) ? room_now + 4 + len : 4 + len;
    if (cap - used(h) >= need) break;
    if (timeout_ms <= 0 ||
        pthread_cond_timedwait(&h->can_write, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return 0;
    }
  }
  uint64_t idx = h->write_pos % cap;
  uint64_t room_to_end = cap - idx;
  if (room_to_end < 4) {
    // not even a prefix fits contiguously: pad to ring start
    h->write_pos += room_to_end;
  } else if (room_to_end < 4 + len) {
    // prefix fits but payload would split: wrap marker, then restart
    uint32_t marker = kWrapMarker;
    ring_put(h, hd->ring, h->write_pos, &marker, 4);
    h->write_pos += room_to_end;
  }
  uint32_t len32 = (uint32_t)len;
  ring_put(h, hd->ring, h->write_pos, &len32, 4);
  ring_put(h, hd->ring, h->write_pos + 4, data, len);
  h->write_pos += 4 + len;
  pthread_cond_broadcast(&h->can_read);
  pthread_mutex_unlock(&h->mu);
  return 1;
}

int64_t nns_shm_read(void* handle, void* buf, uint64_t buf_cap,
                     int timeout_ms) {
  Handle* hd = (Handle*)handle;
  if (!hd || !hd->h) return -1;
  Header* h = hd->h;
  uint64_t cap = h->capacity;
  struct timespec ts;
  abs_deadline(&ts, timeout_ms);
  pthread_mutex_lock(&h->mu);
  for (;;) {
    while (used(h) >= 4) {
      uint64_t idx = h->read_pos % cap;
      uint64_t room_to_end = cap - idx;
      if (room_to_end < 4) {  // producer padded to ring start
        h->read_pos += room_to_end;
        continue;
      }
      uint32_t len32;
      ring_get(h, hd->ring, h->read_pos, &len32, 4);
      if (len32 == kWrapMarker) {
        h->read_pos += room_to_end;
        continue;
      }
      if (len32 > buf_cap) {
        pthread_mutex_unlock(&h->mu);
        return -2;  // caller's buffer too small; message stays queued
      }
      ring_get(h, hd->ring, h->read_pos + 4, buf, len32);
      h->read_pos += 4 + len32;
      pthread_cond_broadcast(&h->can_write);
      pthread_mutex_unlock(&h->mu);
      return (int64_t)len32;
    }
    if (h->closed) {
      pthread_mutex_unlock(&h->mu);
      return -1;  // drained and producer is done
    }
    if (timeout_ms <= 0 ||
        pthread_cond_timedwait(&h->can_read, &h->mu, &ts) == ETIMEDOUT) {
      pthread_mutex_unlock(&h->mu);
      return 0;
    }
  }
}

uint32_t nns_shm_reader_count(void* handle) {
  Handle* hd = (Handle*)handle;
  if (!hd || !hd->h) return 0;
  pthread_mutex_lock(&hd->h->mu);
  uint32_t n = hd->h->readers;
  pthread_mutex_unlock(&hd->h->mu);
  return n;
}

void nns_shm_mark_closed(void* handle) {
  Handle* hd = (Handle*)handle;
  if (!hd || !hd->h) return;
  pthread_mutex_lock(&hd->h->mu);
  hd->h->closed = 1;
  pthread_cond_broadcast(&hd->h->can_read);
  pthread_cond_broadcast(&hd->h->can_write);
  pthread_mutex_unlock(&hd->h->mu);
}

void nns_shm_close(void* handle, int unlink_seg) {
  Handle* hd = (Handle*)handle;
  if (!hd) return;
  if (hd->h) {
    pthread_mutex_lock(&hd->h->mu);
    if (!hd->creator && hd->h->readers > 0) hd->h->readers -= 1;
    pthread_mutex_unlock(&hd->h->mu);
    munmap((void*)hd->h, hd->map_len);
  }
  if (unlink_seg) shm_unlink(hd->name);
  delete hd;
}

}  // extern "C"
