// TCP tensor transport — the native core of the distributed edge layer.
//
// Role model: the external nnstreamer-edge C library the reference's
// tensor_query/edge elements call (nns_edge_create_handle/start/connect/
// send + event callbacks; see SURVEY.md §2.4/§5.8). Like the reference's,
// this is plain native code with no framework dependency: a handle is
// either a listening server (many clients, demultiplexed by client id) or
// a connected client, moving opaque length-prefixed blobs. Framing:
//
//     uint64_le payload_length | payload bytes
//
// The payload is the framework's flexible-tensor wire encoding plus a
// small frame header, both applied by the Python layer — the native layer
// is deliberately payload-agnostic.
//
// Threading: one acceptor thread per server, one reader thread per
// connection; received messages land in a mutex+condvar queue drained by
// nns_edge_recv (the Python side runs its event callbacks off that).
//
// C ABI (ctypes-friendly):
//   nns_edge_create/listen/connect/get_port/send/recv/free_buf/close

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Msg {
  uint64_t client_id;
  std::vector<uint8_t> data;
};

// Read exactly n bytes; false on EOF/error.
bool read_exact(int fd, void *buf, size_t n) {
  auto *p = static_cast<uint8_t *>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_all(int fd, const void *buf, size_t n) {
  auto *p = static_cast<const uint8_t *>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

struct Handle {
  std::atomic<bool> running{false};
  std::atomic<int> recv_inflight{0};  // close() waits for these to drain
  bool is_server = false;
  int listen_fd = -1;
  int bound_port = 0;

  std::thread acceptor;
  std::mutex conn_mu;  // guards conns + next_id + reader thread bookkeeping
  std::map<uint64_t, int> conns;  // client_id -> fd
  std::vector<std::thread> readers;
  std::vector<std::thread::id> done_readers;  // exited, joinable immediately
  uint64_t next_id = 1;

  std::mutex q_mu;
  std::condition_variable q_cv;
  std::deque<Msg> queue;
  size_t max_queue = 4096;  // backpressure bound, reference edge queues are
                            // bounded the same way (drop-oldest)

  std::mutex send_mu;
  // fds of disconnected peers, kept OPEN (shutdown only) until no send can
  // be writing to them: closing in the reader would let the kernel reuse
  // the fd number while a concurrent send still holds a stale snapshot,
  // delivering a frame to the wrong client. Guarded by send_mu.
  std::vector<int> dead_fds;

  // Caller holds send_mu (so no write_all is in flight on these fds).
  void drain_dead_fds_locked() {
    for (int fd : dead_fds) ::close(fd);
    dead_fds.clear();
  }

  void enqueue(uint64_t id, std::vector<uint8_t> &&data) {
    std::lock_guard<std::mutex> lk(q_mu);
    if (queue.size() >= max_queue) queue.pop_front();
    queue.push_back(Msg{id, std::move(data)});
    q_cv.notify_one();
  }

  void reader_loop(uint64_t id, int fd) {
    for (;;) {
      uint64_t len_le = 0;
      if (!read_exact(fd, &len_le, sizeof(len_le))) break;
      uint64_t len = le64toh(len_le);
      if (len > (1ull << 33)) break;  // 8 GiB sanity cap
      std::vector<uint8_t> data(len);
      if (len > 0 && !read_exact(fd, data.data(), len)) break;
      enqueue(id, std::move(data));
    }
    {
      std::lock_guard<std::mutex> lk(conn_mu);
      auto it = conns.find(id);
      if (it != conns.end()) {
        ::shutdown(it->second, SHUT_RDWR);
        {
          std::lock_guard<std::mutex> slk(send_mu);
          dead_fds.push_back(it->second);
        }
        conns.erase(it);
      }
      done_readers.push_back(std::this_thread::get_id());
    }
    // empty message signals connection-closed to the event layer
    if (running.load()) enqueue(id, std::vector<uint8_t>());
  }

  // Join reader threads that have exited (client churn must not grow the
  // readers vector without bound). Caller holds conn_mu.
  void prune_readers_locked() {
    for (auto tid : done_readers) {
      for (auto it = readers.begin(); it != readers.end(); ++it) {
        if (it->get_id() == tid) {
          it->join();
          readers.erase(it);
          break;
        }
      }
    }
    done_readers.clear();
  }

  void acceptor_loop() {
    while (running.load()) {
      sockaddr_in peer {};
      socklen_t plen = sizeof(peer);
      int fd = ::accept(listen_fd, reinterpret_cast<sockaddr *>(&peer), &plen);
      if (fd < 0) {
        if (!running.load()) break;
        continue;
      }
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      uint64_t id;
      // opportunistic drain so a receive-only server with client churn
      // doesn't accumulate dead fds waiting for a send
      if (send_mu.try_lock()) {
        drain_dead_fds_locked();
        send_mu.unlock();
      }
      {
        std::lock_guard<std::mutex> lk(conn_mu);
        prune_readers_locked();
        id = next_id++;
        conns[id] = fd;
        readers.emplace_back(&Handle::reader_loop, this, id, fd);
      }
    }
  }
};

}  // namespace

extern "C" {

Handle *nns_edge_create() { return new Handle(); }

// Bind + listen; port 0 = ephemeral. Returns 0 on success.
int nns_edge_listen(Handle *h, const char *host, int port) {
  h->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (h->listen_fd < 0) return -1;
  auto fail = [h](int rc) {  // error paths must not leak the fd
    ::close(h->listen_fd);
    h->listen_fd = -1;
    return rc;
  };
  int one = 1;
  setsockopt(h->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr {};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return fail(-2);
  if (::bind(h->listen_fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)))
    return fail(-3);
  socklen_t alen = sizeof(addr);
  getsockname(h->listen_fd, reinterpret_cast<sockaddr *>(&addr), &alen);
  h->bound_port = ntohs(addr.sin_port);
  if (::listen(h->listen_fd, 64)) return fail(-4);
  h->is_server = true;
  h->running.store(true);
  h->acceptor = std::thread(&Handle::acceptor_loop, h);
  return 0;
}

int nns_edge_get_port(Handle *h) { return h->bound_port; }

// Connect to a server. Returns 0 on success.
int nns_edge_connect(Handle *h, const char *host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  auto fail = [fd](int rc) {  // error paths must not leak the fd
    ::close(fd);
    return rc;
  };
  sockaddr_in addr {};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1) return fail(-2);
  if (::connect(fd, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)))
    return fail(-3);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  h->running.store(true);
  {
    std::lock_guard<std::mutex> lk(h->conn_mu);
    h->conns[0] = fd;  // client side: single connection, id 0
    h->readers.emplace_back(&Handle::reader_loop, h, 0, fd);
  }
  return 0;
}

// Send a blob. Server: client_id selects the destination connection
// (client_id 0 broadcasts best-effort to every connected client — the
// pub/sub path; a dead subscriber is skipped, its reader thread prunes
// the connection). Client: client_id is ignored. Returns 0 on success.
int nns_edge_send(Handle *h, uint64_t client_id, const uint8_t *data,
                  uint64_t len) {
  bool broadcast = h->is_server && client_id == 0;
  std::vector<int> fds;
  // send_mu must be held from snapshot time onward: every dead-fd close
  // happens under send_mu, so a snapshotted fd cannot be closed (and its
  // number kernel-reused by a new client) before our writes finish. Lock
  // order conn_mu → send_mu matches reader_loop's disconnect path.
  std::unique_lock<std::mutex> clk(h->conn_mu);
  std::unique_lock<std::mutex> lk(h->send_mu);
  if (broadcast) {
    for (auto &kv : h->conns) fds.push_back(kv.second);
  } else {
    uint64_t key = h->is_server ? client_id : 0;
    auto it = h->conns.find(key);
    if (it == h->conns.end()) return -1;
    fds.push_back(it->second);
  }
  clk.unlock();
  uint64_t len_le = htole64(len);
  int rc = 0;
  for (int fd : fds) {
    if (!write_all(fd, &len_le, sizeof(len_le)) ||
        (len > 0 && !write_all(fd, data, len))) {
      if (!broadcast) rc = -2;
    }
  }
  // close after the writes: a snapshot fd that went dead mid-send stays a
  // valid (shutdown) fd until here, so the write fails instead of hitting
  // a kernel-reused fd number belonging to a new client
  h->drain_dead_fds_locked();
  return rc;
}

// Number of currently connected peers.
int nns_edge_peer_count(Handle *h) {
  std::lock_guard<std::mutex> lk(h->conn_mu);
  return static_cast<int>(h->conns.size());
}

// Dequeue the next message, waiting up to timeout_ms (<0 = forever).
// On success returns byte length (>= 0), fills *client_id and *out with a
// malloc'd buffer the caller releases via nns_edge_free_buf. Returns -1 on
// timeout. A 0-length message with *out == nullptr is a connection-closed
// event for that client.
int64_t nns_edge_recv(Handle *h, uint64_t *client_id, uint8_t **out,
                      int timeout_ms) {
  struct InflightGuard {  // close() waits for in-flight recv to finish
    std::atomic<int> &c;
    explicit InflightGuard(std::atomic<int> &c_) : c(c_) { ++c; }
    ~InflightGuard() { --c; }
  } guard(h->recv_inflight);
  std::unique_lock<std::mutex> lk(h->q_mu);
  auto ready = [h] { return !h->queue.empty() || !h->running.load(); };
  if (timeout_ms < 0) {
    h->q_cv.wait(lk, ready);
  } else if (!h->q_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                               ready)) {
    return -1;
  }
  if (h->queue.empty()) return -1;
  Msg m = std::move(h->queue.front());
  h->queue.pop_front();
  lk.unlock();
  *client_id = m.client_id;
  if (m.data.empty()) {
    *out = nullptr;
    return 0;
  }
  *out = static_cast<uint8_t *>(std::malloc(m.data.size()));
  std::memcpy(*out, m.data.data(), m.data.size());
  return static_cast<int64_t>(m.data.size());
}

void nns_edge_free_buf(uint8_t *buf) { std::free(buf); }

void nns_edge_close(Handle *h) {
  {
    // store under q_mu so a recv that just evaluated its predicate cannot
    // miss the wake-up (lost-wakeup race would hang recv + this close)
    std::lock_guard<std::mutex> lk(h->q_mu);
    h->running.store(false);
  }
  // Teardown order matters on three counts:
  // 1. join the ACCEPTOR before sweeping conns — it may be past accept()
  //    with a fresh fd and insert it right after a sweep, leaving a
  //    reader on a never-shutdown socket (close would hang on its join);
  // 2. shutdown() conn fds but close() them only after their reader
  //    threads have RETURNED from recv and been joined — close while a
  //    thread is inside recv(fd) frees the fd number for kernel reuse
  //    and the woken thread could touch an unrelated fd (TSAN flags it);
  // 3. do NOT route these fds through dead_fds: its invariant is that
  //    pushed fds are no longer used by their reader, and send/acceptor
  //    drains may run before the joins below.
  if (h->listen_fd >= 0) ::shutdown(h->listen_fd, SHUT_RDWR);
  if (h->acceptor.joinable()) h->acceptor.join();
  std::vector<int> conn_fds;
  {
    std::lock_guard<std::mutex> lk(h->conn_mu);
    for (auto &kv : h->conns) {
      ::shutdown(kv.second, SHUT_RDWR);
      conn_fds.push_back(kv.second);
    }
    h->conns.clear();
  }
  h->q_cv.notify_all();
  // join outside conn_mu: a reader may be blocked on conn_mu erasing itself
  std::vector<std::thread> readers;
  {
    std::lock_guard<std::mutex> lk(h->conn_mu);
    readers.swap(h->readers);
  }
  for (auto &t : readers)
    if (t.joinable()) t.join();
  // readers are gone: now the fd numbers are safe to release
  for (int fd : conn_fds) ::close(fd);
  if (h->listen_fd >= 0) ::close(h->listen_fd);
  {
    std::lock_guard<std::mutex> lk(h->send_mu);
    h->drain_dead_fds_locked();
  }
  // a concurrent nns_edge_recv may still be unwinding after the wake-up;
  // deleting under it would be a use-after-free
  while (h->recv_inflight.load() > 0)
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  delete h;
}

}  // extern "C"
