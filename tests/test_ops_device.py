"""On-device pre/post-processing (docs/on-device-ops.md): Pallas kernel
parity, device-path decoders, fused composite plumbing with the
zero-host-transfer pin, and the int8 fused-dequant epilogue.

Pallas kernels run in interpret mode on the CPU mesh (the
ops/pallas/_compat.py discipline) against their jnp references; the
pipeline tests mirror PR-8's adjacent-segments test: lightweight jax
stages in the exact detect→crop→landmark shape, with the real face-model
cascade (heavier compiles) marked slow.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.ops import detection as det
from nnstreamer_tpu.ops.image import crop_and_resize as jnp_crop
from nnstreamer_tpu.ops.image import resize_bilinear as jnp_resize
from nnstreamer_tpu.ops.pallas.image_kernels import (
    crop_and_resize as pallas_crop,
    resize_bilinear as pallas_resize,
)
from nnstreamer_tpu.ops.pallas.nms import nms as pallas_nms
from nnstreamer_tpu.pipeline import transfer
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec


# ------------------------------------------------- Pallas image kernels
class TestPallasImageParity:
    def test_crop_matches_jnp_reference(self):
        rng = np.random.default_rng(0)
        img = jnp.asarray(rng.standard_normal((16, 12, 3)), jnp.float32)
        boxes = jnp.asarray(
            [
                [0.0, 0.0, 12.0, 16.0],     # full image
                [2.5, 3.5, 9.5, 12.5],      # subpixel interior
                [-4.0, -2.0, 30.0, 40.0],   # clamps to edges
                [5.0, 5.0, 5.0, 5.0],       # degenerate box
            ],
            jnp.float32,
        )
        want = np.asarray(jnp_crop(img, boxes, 8, 6, impl="jnp"))
        got = np.asarray(pallas_crop(img, boxes, 8, 6, interpret=True))
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_crop_normalize_epilogue(self):
        """The fused ``·scale + offset`` epilogue equals normalizing the
        jnp reference's output — one kernel, zero extra passes."""
        rng = np.random.default_rng(1)
        img = jnp.asarray(rng.integers(0, 255, (16, 12, 3), np.uint8))
        boxes = jnp.asarray([[1.0, 2.0, 11.0, 14.0]], jnp.float32)
        got = np.asarray(pallas_crop(
            img, boxes, 8, 6, scale=1 / 127.5, offset=-1.0, interpret=True
        ))
        want = (
            np.asarray(jnp_crop(
                img.astype(jnp.float32), boxes, 8, 6, impl="jnp"
            )) / 127.5 - 1.0
        )
        assert got.dtype == np.float32
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_integer_output_rounds_and_clips(self):
        rng = np.random.default_rng(2)
        img = jnp.asarray(rng.integers(0, 255, (10, 10, 1), np.uint8))
        boxes = jnp.asarray([[0.25, 0.25, 9.75, 9.75]], jnp.float32)
        got = np.asarray(pallas_crop(
            img, boxes, 5, 5, out_dtype=jnp.uint8, interpret=True
        ))
        assert got.dtype == np.uint8
        ref = np.asarray(jnp_crop(
            img.astype(jnp.float32), boxes, 5, 5, impl="jnp"
        ))
        want = np.clip(np.round(ref), 0, 255).astype(np.uint8)
        # float-associativity differences between the matmul and gather
        # forms can flip a sample sitting exactly on a .5 boundary
        assert np.abs(got.astype(int) - want.astype(int)).max() <= 1

    def test_resize_matches_jnp(self):
        rng = np.random.default_rng(3)
        batch = jnp.asarray(
            rng.standard_normal((2, 9, 7, 2)), jnp.float32
        )
        want = np.asarray(jnp_resize(batch, 5, 4, impl="jnp"))
        got = np.asarray(pallas_resize(batch, 5, 4, interpret=True))
        np.testing.assert_allclose(got, want, atol=1e-5)


class TestPallasNms:
    @pytest.mark.parametrize("n", [40, 200])  # under/over one lane pad
    def test_bit_parity_with_jnp(self, n):
        rng = np.random.default_rng(n)
        boxes = rng.random((n, 4)).astype(np.float32)
        boxes[:, 2:] = boxes[:, :2] + rng.random((n, 2)).astype(np.float32)
        scores = rng.random(n).astype(np.float32)
        scores[scores < 0.3] = 0.0
        ji, js = det.nms(
            jnp.asarray(boxes), jnp.asarray(scores), 0.5, 20, impl="jnp"
        )
        pi, ps = pallas_nms(
            jnp.asarray(boxes), jnp.asarray(scores), 0.5, 20,
            interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(ji), np.asarray(pi))
        np.testing.assert_array_equal(np.asarray(js), np.asarray(ps))

    def test_detection_dispatch_impl_pallas(self):
        """ops/detection.nms impl=pallas routes through the kernel (the
        interpreter off-TPU) and stays bit-identical. Same static
        params as the parity case above, so the jitted kernel entry is
        reused rather than recompiled."""
        rng = np.random.default_rng(40)
        boxes = rng.random((40, 4)).astype(np.float32)
        boxes[:, 2:] += boxes[:, :2]
        scores = rng.random(40).astype(np.float32)
        a = det.nms(jnp.asarray(boxes), jnp.asarray(scores), 0.5, 20)
        b = det.nms(
            jnp.asarray(boxes), jnp.asarray(scores), 0.5, 20,
            impl="pallas",
        )
        np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))


# --------------------------------------------------- device-path decoders
def _decoder(mode, postproc="auto", **props):
    from nnstreamer_tpu.elements.decoder import TensorDecoder

    return TensorDecoder(mode=mode, postproc=postproc, **props)


class TestDeviceDecoders:
    def test_yolov5_bitwise_parity_with_host_path(self):
        spec = TensorsSpec.of(TensorSpec((25, 10), DType.FLOAT32))
        pred = np.random.default_rng(0).random((25, 10)).astype(np.float32)
        dev = _decoder("bounding_boxes", "device", option1="yolov5")
        (out_spec,) = dev.fix_negotiation([spec])
        assert out_spec[0].shape == (100, 6)
        assert dev.is_traceable()
        got = np.asarray(dev.make_fn()((jnp.asarray(pred),))[0])
        host = _decoder("bounding_boxes", option1="yolov5")
        host.fix_negotiation([spec])
        want = host._sub._detections(Frame((pred,)))
        np.testing.assert_array_equal(got, want)

    def test_mobilenet_ssd_bitwise_parity(self, tmp_path):
        n = 16
        rng = np.random.default_rng(1)
        priors = tmp_path / "priors.txt"
        rows = rng.random((4, n)).astype(np.float32) * 0.5 + 0.25
        priors.write_text(
            "\n".join(" ".join(f"{v:.6f}" for v in r) for r in rows)
        )
        spec = TensorsSpec(
            (TensorSpec((n, 4), DType.FLOAT32),
             TensorSpec((n, 5), DType.FLOAT32))
        )
        loc = rng.standard_normal((n, 4)).astype(np.float32)
        sco = rng.standard_normal((n, 5)).astype(np.float32)
        dev = _decoder("bounding_boxes", "device",
                       option1="mobilenet-ssd", option3=str(priors))
        dev.fix_negotiation([spec])
        got = np.asarray(
            dev.make_fn()((jnp.asarray(loc), jnp.asarray(sco)))[0]
        )
        host = _decoder("bounding_boxes", option1="mobilenet-ssd",
                        option3=str(priors))
        host.fix_negotiation([spec])
        want = host._sub._detections(Frame((loc, sco)))
        np.testing.assert_array_equal(got, want)

    def test_image_segment_matches_host_label_map(self):
        spec = TensorsSpec.of(TensorSpec((1, 6, 5, 21), DType.FLOAT32))
        scores = np.random.default_rng(2).random((1, 6, 5, 21)).astype(
            np.float32
        )
        dev = _decoder("image_segment", "device", option1="tflite-deeplab")
        (out_spec,) = dev.fix_negotiation([spec])
        assert out_spec[0].dtype is DType.UINT8
        got = np.asarray(dev.make_fn()((jnp.asarray(scores),))[0])
        host = _decoder("image_segment", option1="tflite-deeplab")
        host.fix_negotiation([spec])
        decoded = host._sub.decode(Frame((scores,)), host.options)
        np.testing.assert_array_equal(got, decoded.meta["label_map"])

    def test_pose_matches_host_keypoints_meta(self):
        spec = TensorsSpec.of(TensorSpec((1, 9, 9, 14), DType.FLOAT32))
        heat = np.random.default_rng(3).standard_normal(
            (1, 9, 9, 14)
        ).astype(np.float32)
        dev = _decoder("pose_estimation", "device")
        dev.fix_negotiation([spec])
        got = np.asarray(dev.make_fn()((jnp.asarray(heat),))[0])
        host = _decoder("pose_estimation")
        host.fix_negotiation([spec])
        decoded = host._sub.decode(Frame((heat,)), host.options)
        np.testing.assert_allclose(
            got, decoded.meta["keypoints"], atol=1e-5
        )

    def test_postproc_host_forces_host_node(self):
        spec = TensorsSpec.of(TensorSpec((1, 10), DType.FLOAT32))
        host = _decoder("image_labeling", "host")
        host.fix_negotiation([spec])
        assert not host.is_traceable()
        auto = _decoder("image_labeling")
        auto.fix_negotiation([spec])
        assert auto.is_traceable()

    def test_postproc_device_without_device_path_raises(self, tmp_path):
        from nnstreamer_tpu.elements.base import NegotiationError

        labels = tmp_path / "labels.txt"
        labels.write_text("a\nb\n")
        spec = TensorsSpec.of(TensorSpec((1, 2), DType.FLOAT32))
        dec = _decoder("image_labeling", "device", option1=str(labels))
        with pytest.raises(NegotiationError, match="no device decode"):
            dec.fix_negotiation([spec])

    def test_custom_code_postproc_device_raises(self):
        from nnstreamer_tpu.elements.base import NegotiationError
        from nnstreamer_tpu.elements.decoder import (
            register_custom_decoder,
            unregister_custom_decoder,
        )

        register_custom_decoder("t_ops_dev", lambda f, o: f)
        try:
            dec = _decoder("custom-code", "device", option1="t_ops_dev")
            with pytest.raises(NegotiationError, match="host callback"):
                dec.fix_negotiation(
                    [TensorsSpec.of(TensorSpec((2,), DType.FLOAT32))]
                )
        finally:
            unregister_custom_decoder("t_ops_dev")


# ------------------------------------------------ image transform/converter
class TestImageTransforms:
    def test_crop_resize_matches_tensor_crop_semantics(self):
        """int32 (x,y,w,h) regions: zero-size rows zero their crops and
        uint8 output rounds+clips — the tensor_crop out-size=
        conventions, now as a 1→1 fusable op."""
        from nnstreamer_tpu.elements.transform import TensorTransform

        rng = np.random.default_rng(0)
        img = rng.integers(0, 255, (1, 16, 12, 3), np.uint8)
        regions = np.asarray(
            [[0, 0, 12, 16], [2, 3, 6, 8], [0, 0, 0, 0]], np.int32
        )
        t = TensorTransform(mode="crop-resize", option="8:6")
        (out,) = t.fix_negotiation([TensorsSpec((
            TensorSpec((1, 16, 12, 3), DType.UINT8),
            TensorSpec((3, 4), DType.INT32),
        ))])
        assert out[0].shape == (3, 8, 6, 3) and out[0].dtype is DType.UINT8
        crops = np.asarray(
            t.make_fn()((jnp.asarray(img), jnp.asarray(regions)))[0]
        )
        assert (crops[2] == 0).all()
        b = regions.astype(np.float32)
        xyxy = np.concatenate([b[:, :2], b[:, :2] + b[:, 2:4]], axis=-1)
        ref = np.asarray(jnp_crop(
            jnp.asarray(img[0], jnp.float32), jnp.asarray(xyxy), 8, 6,
            impl="jnp",
        )).copy()
        ref[2] = 0.0
        np.testing.assert_array_equal(
            crops, np.clip(np.round(ref), 0, 255).astype(np.uint8)
        )

    def test_crop_resize_rejects_bad_boxes(self):
        from nnstreamer_tpu.elements.base import NegotiationError
        from nnstreamer_tpu.elements.transform import TensorTransform

        t = TensorTransform(mode="crop-resize", option="8:6")
        with pytest.raises(NegotiationError, match="boxes"):
            t.fix_negotiation([TensorsSpec((
                TensorSpec((1, 16, 12, 3), DType.UINT8),
                TensorSpec((3, 5), DType.INT32),
            ))])

    def test_resize_spec_and_rank_guard(self):
        from nnstreamer_tpu.elements.base import NegotiationError
        from nnstreamer_tpu.elements.transform import TensorTransform

        t = TensorTransform(mode="resize", option="4:4")
        (out,) = t.fix_negotiation(
            [TensorsSpec.of(TensorSpec((1, 8, 8, 3), DType.UINT8))]
        )
        assert out[0].shape == (1, 4, 4, 3)
        t2 = TensorTransform(mode="resize", option="4:4")
        with pytest.raises(NegotiationError, match="resize"):
            t2.fix_negotiation(
                [TensorsSpec.of(TensorSpec((8, 8), DType.FLOAT32))]
            )

    def test_converter_input_norm_rejects_non_video(self):
        from nnstreamer_tpu.elements.base import NegotiationError
        from nnstreamer_tpu.elements.converter import TensorConverter

        cv = TensorConverter(**{"input-norm": "127.5:127.5"})
        with pytest.raises(NegotiationError, match="input-norm"):
            cv.fix_negotiation(
                [TensorsSpec.of(TensorSpec((4,), DType.FLOAT32))]
            )

    def test_crop_impl_pallas_dispatch_off_tpu_interprets(self):
        """Explicit impl=pallas off-TPU routes through the interpreter
        (same contract as ops/detection.nms) instead of crashing on
        Mosaic lowering; integer results match the jnp path's
        round+clip within the .5-boundary tolerance."""
        rng = np.random.default_rng(9)
        img = jnp.asarray(rng.integers(0, 255, (10, 8, 2), np.uint8))
        boxes = jnp.asarray([[1.0, 1.0, 7.0, 9.0]], jnp.float32)
        a = np.asarray(jnp_crop(img, boxes, 5, 4, impl="jnp"))
        b = np.asarray(jnp_crop(img, boxes, 5, 4, impl="pallas"))
        assert a.dtype == b.dtype == np.uint8
        assert np.abs(a.astype(int) - b.astype(int)).max() <= 1

    def test_converter_input_norm_fuses_float_spec(self):
        from nnstreamer_tpu.elements.base import MediaSpec
        from nnstreamer_tpu.elements.converter import TensorConverter

        cv = TensorConverter(**{"input-norm": "127.5:127.5"})
        (out,) = cv.fix_negotiation(
            [MediaSpec("video", width=6, height=4, format="RGB")]
        )
        assert out[0].dtype is DType.FLOAT32
        assert cv.is_traceable()
        img = np.random.default_rng(1).integers(
            0, 255, (4, 6, 3), np.uint8
        )
        got = np.asarray(cv.make_fn()((jnp.asarray(img),))[0])
        assert got.shape == (1, 4, 6, 3)
        np.testing.assert_allclose(
            got[0], (img.astype(np.float32) - 127.5) / 127.5, atol=1e-6
        )


# -------------------------------------------- fused pipeline + transfer pins
def _detector_script(tmp_path, h=32, w=32):
    """Tiny detect-shaped jax stage: image → (image, regions) — the
    2-tensor output the crop-resize transform fuses with."""
    path = tmp_path / "det.py"
    path.write_text(
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "_REG = jnp.asarray(np.array([[0, 0, %d, %d], [4, 4, 8, 8],"
        " [0, 0, 0, 0], [2, 2, 6, 6]], np.int32))\n"
        "def get_model(options):\n"
        "    return (lambda img: (img, _REG)), None\n" % (w, h)
    )
    return str(path)


def _landmark_script(tmp_path):
    """Tiny landmark-shaped jax stage: crop batch → [N, 8] features."""
    path = tmp_path / "lmk.py"
    path.write_text(
        "import jax.numpy as jnp\n"
        "def get_model(options):\n"
        "    def fn(crops):\n"
        "        x = crops.astype(jnp.float32)\n"
        "        pooled = jnp.mean(x, axis=(1, 2))  # [N, C]\n"
        "        return jnp.concatenate([pooled, -pooled], axis=-1)\n"
        "    return fn, None\n"
    )
    return str(path)


class TestFusedPostprocPipeline:
    def test_device_decoder_fuses_and_counts(self):
        """A postproc=device decoder joins the upstream filter's fused
        segment; the plan counts it as a postproc op, stats() exposes
        it, and nns_fused_postproc_total counts the frames."""
        from nnstreamer_tpu import obs as obs_metrics
        from nnstreamer_tpu.pipeline.executor import FusedNode

        obs_metrics.enable()
        p = parse_pipeline(
            "tensorsrc dimensions=16 types=float32 pattern=random "
            "num-frames=12 ! tensor_filter framework=scaler ! "
            "tensor_decoder mode=image_labeling postproc=device ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        fused = [n for n in ex.nodes if isinstance(n, FusedNode)]
        assert len(fused) == 1
        assert "tensor_decoder" in fused[0].name  # decoder IS the segment
        assert fused[0].seg.postproc_ops == 1
        row = ex.stats()[fused[0].name]
        assert row["fused_postproc"] == 1
        total = sum(
            m["value"] for m in obs_metrics.get().to_dict()["metrics"]
            if m["name"] == "nns_fused_postproc_total"
        )
        assert total >= 12
        # and the decode math is right: argmax of the scaled row
        out = [np.asarray(f.tensors[0]) for f in p["out"].frames]
        assert all(o.dtype == np.uint32 and o.shape == (1,) for o in out)

    def test_packed_fetch_drops_with_device_decode(self):
        """The satellite pin: with a HOST decoder after a fused filter,
        the coalesced D2H prefetch carries the decoder's (large)
        inputs; with the decode fused on device only the small decoded
        tensor is ever fetched — the per-run D2H byte count collapses."""
        desc = (
            "tensorsrc dimensions=4096 types=float32 pattern=random "
            "num-frames=16 ! tensor_filter framework=scaler ! "
            "tensor_decoder mode=image_labeling postproc={pp} ! "
            "tensor_sink name=out"
        )
        p1 = parse_pipeline(desc.format(pp="host"))
        host_d2h = p1.run(timeout=60).transfer_totals()["d2h"]
        p2 = parse_pipeline(desc.format(pp="device"))
        dev_d2h = p2.run(timeout=60).transfer_totals()["d2h"]
        # host mode fetches 16 KiB of logits per frame; device mode
        # fetches the 4-byte label index (the sink's only read)
        assert dev_d2h == 16 * 4  # uint32 per frame, nothing else
        assert host_d2h >= 16 * 4096 * 4
        # decoded values identical either way
        a = [np.asarray(f.tensors[0]) for f in p1["out"].frames]
        b = [np.asarray(f.tensors[0]) for f in p2["out"].frames]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_detect_crop_landmark_zero_host_transfer(self, tmp_path):
        """The PR-8 adjacent-segments mirror in the composite shape:
        detect → crop-resize → (queue) → landmark as two fused device
        segments, device source, discarding sink — ZERO bytes cross the
        host boundary in either direction."""
        desc = (
            "videotestsrc pattern=gradient num-frames=8 device=true "
            "width=32 height=32 ! tensor_converter ! "
            f"tensor_filter framework=jax model={_detector_script(tmp_path)} ! "
            "tensor_transform mode=crop-resize option=8:8 ! queue ! "
            f"tensor_filter framework=jax model={_landmark_script(tmp_path)} ! "
            "fakesink"
        )
        p = parse_pipeline(desc)
        ex = p.run(timeout=120)
        assert not ex.errors
        totals = ex.transfer_totals()
        assert totals == {"h2d": 0, "d2h": 0}

    def test_detect_crop_landmark_sink_fetches_only_landmarks(
        self, tmp_path
    ):
        """With a reading sink, the coalesced fetch packs ONLY the
        post-decode tensor: D2H is exactly n_frames × the landmark
        tensor's bytes — the image and the crop batch never leave the
        device."""
        desc = (
            "videotestsrc pattern=gradient num-frames=8 device=true "
            "width=32 height=32 ! tensor_converter ! "
            f"tensor_filter framework=jax model={_detector_script(tmp_path)} ! "
            "tensor_transform mode=crop-resize option=8:8 ! queue ! "
            f"tensor_filter framework=jax model={_landmark_script(tmp_path)} ! "
            "tensor_sink name=out"
        )
        p = parse_pipeline(desc)
        ex = p.run(timeout=120)
        lm = [np.asarray(f.tensors[0]) for f in p["out"].frames]
        assert len(lm) == 8 and lm[0].shape == (4, 6)
        assert ex.transfer_totals()["d2h"] == 8 * lm[0].nbytes
        # crop semantics carried through: the zero region's features
        # pool to zero in the first half
        assert np.allclose(lm[0][2][:3], 0.0)


# ----------------------------------------------- int8 dequant epilogue
class TestInt8DequantParity:
    def test_wo_conv1x1_matches_host_dequant(self):
        """The fused dequant epilogue (models/quantize._wo_conv1x1)
        against the host dequant reference (dequantize_w + plain
        matmul): same math, same numbers."""
        from nnstreamer_tpu.models import quantize as qz

        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.standard_normal((1, 1, 12, 8)), jnp.float32)
        x = jnp.asarray(rng.standard_normal((2, 3, 12)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
        w8, scale = qz._quantize_w(w)
        got = np.asarray(qz._wo_conv1x1(
            x, {"w8": w8, "wscale": scale, "b": b}
        ))
        host_w = np.asarray(qz.dequantize_w(w8, scale))[0, 0]
        want = np.asarray(x) @ host_w + np.asarray(b)
        np.testing.assert_allclose(got, want, atol=1e-5)
        # the int8 form really is int8 (¼ the weight bytes resident)
        assert np.asarray(w8).dtype == np.int8

    @pytest.mark.slow
    def test_apply_int8w_full_model_matches_host_dequant(self):
        """End-to-end: apply_int8w over the whole quantized MobileNet
        equals the fp32 forward over host-dequantized weights, exactly
        (same float structure, dequant folded at the operand)."""
        from nnstreamer_tpu.models import mobilenet_v2 as mv2
        from nnstreamer_tpu.models import nn
        from nnstreamer_tpu.models import quantize as qz

        params = mv2.init_params(
            jax.random.PRNGKey(0), num_classes=10, width=0.25
        )
        folded = qz.fold_mobilenet(params)
        q = qz.quantize_mobilenet_weights(folded)
        x = jnp.asarray(np.random.default_rng(0).integers(
            0, 255, (1, 64, 64, 3), np.uint8
        ))
        got = np.asarray(qz.apply_int8w(q, x))
        deq = {
            "stem": folded["stem"],
            "classifier": folded["classifier"],
            "blocks": [],
        }
        for blk, qb in zip(folded["blocks"], q["blocks"]):
            b = {"dw": blk["dw"]}
            for part in ("expand", "project"):
                if part in qb:
                    b[part] = {
                        "w": qz.dequantize_w(
                            qb[part]["w8"], qb[part]["wscale"]
                        ),
                        "b": qb[part]["b"],
                    }
            deq["blocks"].append(b)
        deq["head"] = {
            "w": qz.dequantize_w(q["head"]["w8"], q["head"]["wscale"]),
            "b": folded["head"]["b"],
        }
        y = qz._folded_forward(deq, qz.normalize_uint8(x), [])
        want = np.asarray(nn.dense(
            jnp.mean(y.astype(jnp.float32), axis=(1, 2)),
            folded["classifier"],
        ))
        np.testing.assert_allclose(got, want, atol=1e-5)


# ------------------------------------------------------------ NNS-W116
class TestW116HostPostproc:
    DESC = (
        "tensorsrc dimensions=25:10 types=float32 num-frames=4 ! "
        "tensor_filter framework=scaler ! "
        "tensor_decoder mode=bounding_boxes option1=yolov5{pp} ! "
        "{tail}"
    )

    def _codes(self, pp="", tail="tensor_filter framework=scaler ! fakesink"):
        from nnstreamer_tpu.analysis.lint import lint

        r = lint(self.DESC.format(pp=pp, tail=tail))
        return [d.code for d in r.diagnostics]

    def test_fires_for_host_decoder_between_device_filters(self):
        assert "NNS-W116" in self._codes()

    def test_silent_with_postproc_device(self):
        codes = self._codes(pp=" postproc=device")
        assert "NNS-W116" not in codes
        assert "NNS-W113" not in codes

    def test_silent_at_chain_tail(self):
        assert "NNS-W116" not in self._codes(tail="fakesink")

    def test_postproc_device_with_error_pad_serves_host_path(self):
        """A linked error pad is a fusion barrier, so a postproc=device
        decoder lands on the host loop — it must serve the SAME traced
        decode (structured tensor out), never the video tail."""
        desc = (
            "tensorsrc dimensions=25:10 types=float32 pattern=random "
            "num-frames=4 ! tensor_filter framework=scaler ! "
            "tensor_decoder name=dec mode=bounding_boxes option1=yolov5 "
            "postproc=device on-error=route ! tensor_sink name=out "
            "dec.src_1 ! fakesink"
        )
        p = parse_pipeline(desc)
        ex = p.run(timeout=60)
        assert not ex.errors
        outs = [np.asarray(f.tensors[0]) for f in p["out"].frames]
        assert len(outs) == 4
        assert all(o.shape == (100, 6) and o.dtype == np.float32
                   for o in outs)

    def test_postproc_device_pipeline_lints_and_runs_clean(self):
        from nnstreamer_tpu.analysis.lint import lint

        desc = self.DESC.format(
            pp=" postproc=device",
            tail="tensor_filter framework=scaler ! tensor_sink name=out",
        )
        assert lint(desc).exit_code == 0
        p = parse_pipeline(desc)
        ex = p.run(timeout=60)
        assert not ex.errors
        assert len(p["out"].frames) == 4


# ----------------------------------------------- real face cascade (slow)
@pytest.mark.slow
class TestRealFaceCascade:
    FUSED = (
        "videotestsrc pattern=gradient num-frames={n} device=true "
        "width=128 height=128 ! tensor_converter ! "
        "tensor_filter framework=jax model=zoo:face_detect "
        'custom="output:regions+image,threshold:0.0,frame_size:128:128" ! '
        "tensor_transform mode=crop-resize option=112:112 ! queue ! "
        "tensor_filter framework=jax model=zoo:face_landmark "
        'custom="batch:16" ! {sink}'
    )

    def test_zero_host_transfer_and_parity_with_tensor_crop(self):
        # zero-transfer pin on the real models
        p = parse_pipeline(self.FUSED.format(n=3, sink="fakesink"))
        ex = p.run(timeout=300)
        assert ex.transfer_totals() == {"h2d": 0, "d2h": 0}
        # numeric parity vs the tensor_crop element cascade
        p2 = parse_pipeline(self.FUSED.format(n=2, sink="tensor_sink name=out"))
        p2.run(timeout=300)
        fused_lm = [np.asarray(f.tensors[0]) for f in p2["out"].frames]
        crop_desc = (
            "videotestsrc pattern=gradient num-frames=2 width=128 "
            "height=128 ! tensor_converter ! tee name=t "
            "t. ! queue ! tensor_filter framework=jax "
            "model=zoo:face_detect "
            'custom="output:regions,threshold:0.0,frame_size:128:128" ! '
            "crop.sink_1 t. ! queue ! crop.sink_0 "
            "tensor_crop name=crop out-size=112:112 max-crops=16 ! "
            "tensor_filter framework=jax model=zoo:face_landmark "
            'custom="batch:16" ! tensor_sink name=out'
        )
        p3 = parse_pipeline(crop_desc)
        p3.run(timeout=300)
        crop_lm = [np.asarray(f.tensors[0]) for f in p3["out"].frames]
        assert len(fused_lm) == len(crop_lm) == 2
        for a, b in zip(fused_lm, crop_lm):
            np.testing.assert_allclose(a, b, atol=1e-4)
