"""shared-tensor-filter-key: filters sharing a key share ONE opened
backend (reference shared-model table, tensor_filter_common.c
shared_tensor_filter_key): one weight copy, reload swaps for all."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.base import NegotiationError
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.tensors.spec import TensorsSpec


def _spec():
    return TensorsSpec.from_strings("4", "float32")


def test_same_key_shares_backend_instance():
    a = TensorFilter(framework="scaler", custom="factor:3",
                     **{"shared-tensor-filter-key": "k1"})
    b = TensorFilter(framework="scaler", custom="factor:3",
                     **{"shared-tensor-filter-key": "k1"})
    try:
        a.negotiate([_spec()])
        b.negotiate([_spec()])
        assert a.backend is b.backend
    finally:
        a.stop()
        b.stop()


def test_refcounted_close():
    a = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "k2"})
    b = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "k2"})
    a.negotiate([_spec()])
    b.negotiate([_spec()])
    shared = a.backend
    a.stop()
    # still open for b: a third filter re-acquires the SAME instance
    c = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "k2"})
    c.negotiate([_spec()])
    assert c.backend is shared
    b.stop()
    c.stop()
    # all refs dropped: a new filter gets a FRESH instance
    d = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "k2"})
    d.negotiate([_spec()])
    assert d.backend is not shared
    d.stop()


def test_key_conflict_rejected():
    a = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "k3"})
    a.negotiate([_spec()])
    try:
        b = TensorFilter(framework="passthrough",
                         **{"shared-tensor-filter-key": "k3"})
        with pytest.raises(NegotiationError, match="already bound"):
            b.negotiate([_spec()])
    finally:
        a.stop()


def test_reload_visible_to_all_sharers(tmp_path):
    """is-updatable reload through one sharer swaps the model for all
    (the reference's shared-model reload semantics)."""
    m1 = tmp_path / "m1.py"
    m2 = tmp_path / "m2.py"
    for path, k in ((m1, 10.0), (m2, 100.0)):
        path.write_text(
            "def get_model(options):\n"
            f"    return (lambda x: x * {k}), None\n"
        )
    a = TensorFilter(framework="jax", model=str(m1),
                     input="4", inputtype="float32",
                     **{"shared-tensor-filter-key": "k4"})
    b = TensorFilter(framework="jax", model=str(m1),
                     input="4", inputtype="float32",
                     **{"shared-tensor-filter-key": "k4"})
    try:
        from nnstreamer_tpu.tensors.frame import Frame

        a.negotiate([_spec()])
        b.negotiate([_spec()])
        x = Frame((np.ones(4, np.float32),))
        np.testing.assert_allclose(
            np.asarray(b.host_process(x).tensors[0]), np.full(4, 10.0)
        )
        a.reload_model(str(m2))
        np.testing.assert_allclose(
            np.asarray(b.host_process(x).tensors[0]), np.full(4, 100.0)
        )
    finally:
        a.stop()
        b.stop()


def test_shared_backend_stats_are_per_element():
    """Sharers of one backend must not report each other's invokes
    (reference: latency/throughput live per element, tensor_filter.c:334)."""
    import numpy as np

    from nnstreamer_tpu.tensors.frame import Frame

    a = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "ks"})
    b = TensorFilter(framework="scaler", **{"shared-tensor-filter-key": "ks"})
    try:
        a.negotiate([_spec()])
        b.negotiate([_spec()])
        f = Frame((np.ones(4, np.float32),))
        for _ in range(3):
            a.host_process(f)
        b.host_process(f)
        assert a.invoke_stats.total_invoke_num == 3
        assert b.invoke_stats.total_invoke_num == 1
        # the shared backend keeps the cumulative per-framework view
        assert a.backend.stats.total_invoke_num == 4
        a.stop()
        before = a.invoke_stats.total_invoke_num
        b.host_process(f)  # other sharer keeps running
        assert a.invoke_stats.total_invoke_num == before  # frozen view
    finally:
        a.stop()
        b.stop()
