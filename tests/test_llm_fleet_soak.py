"""Slow fleet soak: zero-loss LLM serving under failure
(docs/llm-serving.md "Migration & recovery", docs/edge-serving.md).

Two LLM servers in one "fleet" — A drains and live-migrates its
in-flight generations over the real CTRL wire handshake to B; a
refused late request re-routes; B is then hard-killed mid-decode and
its successor B2 adopts the span checkpoints; a corrupted span and a
draining destination exercise the refusal paths. The ledger at the
end: every submitted request reached a terminal outcome, every
finished stream is bitwise identical to its uninterrupted run, and
no completed prefill chunk was ever re-run.

Failure matrix pinned here: drain (A), kill (B), refuse (draining
destination), corrupt (CRC-flipped span NACKed, bystanders unharmed).
"""

import threading
import time

import jax
import numpy as np
import pytest

from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.tensors.frame import Frame

pytestmark = pytest.mark.slow

OPTS = {
    "vocab": "211", "d_model": "32", "n_heads": "2", "n_layers": "1",
    "seed": "5",
}
N_HEADS = 2

_PARAMS = None


def _mk(**kw):
    from nnstreamer_tpu.elements.llm_serve import _LlmServer

    base = dict(
        model="zoo:transformer_lm", options=dict(OPTS), n_slots=4,
        max_len=64, prompt_len=16, default_new=10, kv_layout="paged",
        block_size=16, kv_blocks=0,
    )
    base.update(kw)
    return _LlmServer(**base)


def _alone(prompt, n_new=10):
    global _PARAMS
    if _PARAMS is None:
        _PARAMS = tfm.init_params(
            jax.random.PRNGKey(5), vocab=211, d_model=32, n_heads=2,
            n_layers=1,
        )
    toks = dec.generate(
        _PARAMS, np.asarray(prompt, np.int32)[None, :], N_HEADS, n_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _prompt(seed, n=6):
    return np.random.default_rng(seed).integers(1, 211, (n,)).astype(
        np.int32
    )


def _pump_until(srv, cond, timeout=180.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        srv.pump()


def _pop_by_req(srv, n, timeout=180.0):
    """Pump until n outputs landed; return {meta['req']: (tokens, meta)}."""
    _pump_until(
        srv, lambda: len(srv._out) >= n, timeout=timeout,
        what=f"{n} finished generations",
    )
    out = {}
    for _ in range(n):
        toks, meta = srv.pop()
        out[meta["req"]] = ([int(t) for t in toks], meta)
    return out


def test_fleet_migrate_kill_restart_soak(tmp_path):
    from nnstreamer_tpu.edge import query as q
    from nnstreamer_tpu.elements import llm_serve
    from nnstreamer_tpu.kv.migrate import encode_span

    ckpt = str(tmp_path / "spans")
    prompts = {f"m{i}": _prompt(20 + i) for i in range(3)}
    prompts["late"] = _prompt(30)
    prompts["k0"] = _prompt(40)
    prompts["k1"] = _prompt(41)
    expect = {k: _alone(p) for k, p in prompts.items()}
    done = {}

    # B's edge endpoint: a real query serversrc answering the
    # migrate_probe/migrate_span CTRL messages for whichever LLM
    # server is registered under id 2 (B now, B2 after the restart)
    src_b = q.TensorQueryServerSrc("soak-src-b", port=0, id="soak-b")
    src_b.start()
    stop = threading.Event()
    pump_thread = threading.Thread(
        target=lambda: [src_b.generate() for _ in iter(stop.is_set, True)],
        daemon=True,
    )
    pump_thread.start()

    srv_b = _mk(
        srv_id="2", checkpoint_every_tokens=2, checkpoint_dir=ckpt,
    )
    srv_a = _mk(
        srv_id="1",
        migrate_to=f"127.0.0.1:{src_b.bound_port}/2",
    )
    srv_b2 = None
    with llm_serve._table_lock:
        llm_serve._table["soak-1"] = srv_a
    try:
        # -- phase 1: drain A, live-migrate 3 mid-decode requests ------
        for k in ("m0", "m1", "m2"):
            srv_a.submit(Frame((prompts[k],), meta={
                "req": k, "frame_id": f"f-{k}", "client_id": 7,
            }))
        rids_a = list(srv_a._pending)
        assert len(rids_a) == 3
        _pump_until(
            srv_a,
            lambda: all(
                len(srv_a.cb.partials(rids_a).get(r) or ()) >= 3
                for r in rids_a
            ),
            what="3 decoded tokens on every A request",
        )
        summary = llm_serve.drain_server("soak-1")  # operator surface
        assert summary["migrated"] == 3, summary
        assert summary["resumed"] == 0 and summary["kept"] == 0
        assert srv_a.draining
        # A's ledger: migrated is a terminal state, nothing lingers
        states = {r: srv_a.cb.requests()[r]["state"] for r in rids_a}
        assert set(states.values()) == {"migrated"}, states
        assert srv_a.cb.stats().get("kv_migrations_out") == 3
        # B adopted straight into decode — zero prefill re-run
        assert (srv_b.cb.stats().get("kv_prefill_queue") or 0) == 0
        assert srv_b.cb.stats().get("kv_migrations_in") == 3

        # a late request hits the draining server, is refused with the
        # typed terminal error, and re-routes to the healthy peer (the
        # edge path NACKs `draining` + retry-after — tests/test_fleet.py)
        with pytest.raises(ElementError, match="draining"):
            srv_a.submit(Frame((prompts["late"],), meta={"req": "late"}))
        srv_b.submit(Frame((prompts["late"],), meta={
            "req": "late", "frame_id": "f-late",
        }))
        done.update(_pop_by_req(srv_b, 4))
        for k in ("m0", "m1", "m2"):
            toks, meta = done[k]
            assert toks == expect[k], f"{k}: migrated stream diverged"
            assert meta["frame_id"] == f"f-{k}"
            assert "client_id" not in meta  # hop-local, stripped at span
        assert done["late"][0] == expect["late"]

        # -- phase 2: hard-kill B mid-decode, restart over checkpoints -
        for k in ("k0", "k1"):
            srv_b.submit(Frame((prompts[k],), meta={
                "req": k, "frame_id": f"f-{k}",
            }))
        rids_b = list(srv_b._pending)
        assert len(rids_b) == 2
        _pump_until(
            srv_b,
            lambda: all(
                len(srv_b.cb.partials(rids_b).get(r) or ()) >= 5
                for r in rids_b
            ),
            what="5 decoded tokens on every B request",
        )
        # hard kill: NO drain, NO extraction — only the atomic span
        # checkpoints survive the "process"
        srv_b.release_plane()
        files = sorted((tmp_path / "spans").glob("req-*.span"))
        assert len(files) == 2, (
            "expected exactly the 2 in-flight checkpoints (finished "
            f"requests reap theirs): {[f.name for f in files]}"
        )
        srv_b2 = _mk(
            srv_id="2", checkpoint_every_tokens=2, checkpoint_dir=ckpt,
        )
        assert len(srv_b2._pending) == 2, "restart did not adopt both"
        # adopted spans land in the arena directly — no prefill re-run
        assert (srv_b2.cb.stats().get("kv_prefill_queue") or 0) == 0
        assert srv_b2.cb.stats().get("kv_migrations_in") == 2

        # chaos: a CRC-flipped span arrives over the wire mid-decode —
        # NACKed as corrupt, and the live generations are unharmed
        rid = next(iter(srv_b2._pending))
        span = srv_b2.cb.extract_request(rid, remove=False)
        wire = bytearray(encode_span(span))
        wire[-1] ^= 0xFF
        with pytest.raises(q.MigrationRefused, match="SpanCorruptError"):
            q.send_migration(
                "127.0.0.1", src_b.bound_port, bytes(wire), llm_id=2
            )

        done.update(_pop_by_req(srv_b2, 2))
        for k in ("k0", "k1"):
            toks, meta = done[k]
            assert toks == expect[k], f"{k}: resumed stream diverged"
            assert meta["frame_id"] == f"f-{k}"
        # finished: checkpoints reaped, no ghost on a further restart
        assert not sorted((tmp_path / "spans").glob("req-*.span"))

        # chaos: a draining destination refuses spans outright — the
        # endpoint is leaving, nothing must land on it
        src_b.drain()
        with pytest.raises(q.MigrationRefused, match="draining"):
            q.probe_migration(
                "127.0.0.1", src_b.bound_port, [1, 2, 3], llm_id=2
            )
    finally:
        # release_plane is idempotent — safe for every exit path
        srv_a.release_plane()
        srv_b.release_plane()
        if srv_b2 is not None:
            srv_b2.release_plane()
        with llm_serve._table_lock:
            llm_serve._table.pop("soak-1", None)
        stop.set()
        pump_thread.join(timeout=2)
        src_b.stop()

    # the ledger: 6 submitted (3 migrated, 1 refused-then-rerouted,
    # 2 killed-then-resumed), 6 terminal, all bitwise == solo runs
    assert sorted(done) == sorted(prompts)
    for k in prompts:
        assert done[k][0] == expect[k]
