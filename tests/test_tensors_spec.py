"""Tensor spec / dim-string unit tests.

Mirrors the reference's tests/common/unittest_common.cc coverage of
gst_tensor_parse_dimension / gst_tensors_info_* utilities.
"""

import numpy as np
import pytest

from nnstreamer_tpu.tensors.spec import (
    DType,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    format_dimension,
    parse_dimension,
    NNS_TENSOR_SIZE_LIMIT,
)


class TestParseDimension:
    def test_innermost_first_reversal(self):
        # reference syntax "3:224:224:1" = ch-3 224x224 batch-1 → NHWC
        assert parse_dimension("3:224:224:1") == (1, 224, 224, 3)

    def test_single(self):
        assert parse_dimension("5") == (5,)

    def test_wildcard(self):
        assert parse_dimension("3:0:0:1") == (1, None, None, 3)
        assert parse_dimension("3:?:?:1") == (1, None, None, 3)

    def test_roundtrip(self):
        for s in ["3:224:224:1", "1001:1", "7", "2:3:4:5:6"]:
            assert format_dimension(parse_dimension(s)) == s

    def test_rank_limit(self):
        with pytest.raises(ValueError):
            parse_dimension(":".join(["2"] * 9))

    def test_bad_strings(self):
        with pytest.raises(ValueError):
            parse_dimension("")
        with pytest.raises(ValueError):
            parse_dimension("-3:2")
        with pytest.raises(ValueError):
            parse_dimension("a:b")


class TestDType:
    def test_from_any(self):
        assert DType.from_any("uint8") is DType.UINT8
        assert DType.from_any(np.float32) is DType.FLOAT32
        assert DType.from_any(np.dtype("int64")) is DType.INT64
        assert DType.from_any(DType.BFLOAT16) is DType.BFLOAT16

    def test_bfloat16_numpy(self):
        assert DType.BFLOAT16.itemsize == 2
        a = np.zeros(3, DType.BFLOAT16.np_dtype)
        assert a.dtype.name == "bfloat16"

    def test_itemsize(self):
        assert DType.UINT8.itemsize == 1
        assert DType.FLOAT64.itemsize == 8

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            DType.from_any("float128xyz")


class TestTensorSpec:
    def test_sizes(self):
        t = TensorSpec.from_dim_string("3:224:224:1", "uint8")
        assert t.element_count == 3 * 224 * 224
        assert t.byte_size == 3 * 224 * 224
        assert t.dim_string == "3:224:224:1"

    def test_not_static(self):
        t = TensorSpec((None, 3), DType.FLOAT32)
        assert not t.is_static
        with pytest.raises(ValueError):
            _ = t.element_count

    def test_compat_wildcard(self):
        a = TensorSpec((None, 224, 224, 3), DType.UINT8)
        b = TensorSpec((1, 224, 224, 3), DType.UINT8)
        assert a.is_compatible(b)
        assert a.merge(b).shape == (1, 224, 224, 3)

    def test_compat_rank_padding(self):
        # rank mismatch handled by leading-1 padding like uint32[4] dims
        a = TensorSpec((224, 224, 3), DType.UINT8)
        b = TensorSpec((1, 224, 224, 3), DType.UINT8)
        assert a.is_compatible(b)

    def test_incompatible_dtype(self):
        a = TensorSpec((3,), DType.UINT8)
        b = TensorSpec((3,), DType.FLOAT32)
        assert not a.is_compatible(b)


class TestTensorsSpec:
    def test_from_strings(self):
        s = TensorsSpec.from_strings(
            "3:224:224:1,1001:1", "uint8,float32", names="image,logits"
        )
        assert s.num_tensors == 2
        assert s[0].dtype is DType.UINT8
        assert s[1].shape == (1, 1001)
        assert s[0].name == "image"
        assert s.dimensions_string == "3:224:224:1,1001:1"
        assert s.types_string == "uint8,float32"

    def test_type_broadcast(self):
        s = TensorsSpec.from_strings("3:4,5:6", "float32")
        assert all(t.dtype is DType.FLOAT32 for t in s)

    def test_size_limit(self):
        with pytest.raises(ValueError):
            TensorsSpec(
                tuple(TensorSpec((1,)) for _ in range(NNS_TENSOR_SIZE_LIMIT + 1))
            )

    def test_caps_string(self):
        s = TensorsSpec.from_strings("3:4:5:1", "uint8", rate=30)
        caps = s.to_caps_string()
        assert "other/tensors" in caps
        assert "format=static" in caps
        assert "framerate=30/1" in caps

    def test_from_arrays(self):
        s = TensorsSpec.from_arrays([np.zeros((2, 3), np.int16)])
        assert s[0].shape == (2, 3) and s[0].dtype is DType.INT16

    def test_flexible_compat(self):
        a = TensorsSpec(format=TensorFormat.FLEXIBLE)
        b = TensorsSpec(
            (TensorSpec((5,)),), format=TensorFormat.FLEXIBLE
        )
        assert a.is_compatible(b)
        assert not a.is_compatible(TensorsSpec(format=TensorFormat.STATIC))
