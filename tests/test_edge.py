"""Distributed edge layer tests (reference: tests/nnstreamer_edge/query/
runTest.sh loopback pipelines, unittest_query.cc / unittest_edge.cc).

Like the reference's strategy, 'multi-node' runs as loopback on one host:
server and client sides talk over 127.0.0.1 with ephemeral ports.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge._build import native_lib_path
from nnstreamer_tpu.edge.serialize import decode_message, encode_message
from nnstreamer_tpu.edge.transport import NativeTransport, PyTransport
from nnstreamer_tpu.tensors.frame import EOS, EOS_FRAME, Frame

HAVE_NATIVE = native_lib_path() is not None


def _impls():
    impls = [PyTransport]
    if HAVE_NATIVE:
        impls.append(NativeTransport)
    return impls


# ------------------------------------------------------------------ transport
@pytest.mark.parametrize("impl", _impls())
def test_transport_roundtrip(impl):
    server = impl()
    client = impl()
    try:
        port = server.listen("127.0.0.1", 0)
        client.connect("127.0.0.1", port)
        client.send(0, b"hello-tensors")
        got = server.recv(timeout=5)
        assert got is not None
        cid, payload = got
        assert payload == b"hello-tensors" and cid >= 1
        server.send(cid, b"reply")
        got = client.recv(timeout=5)
        assert got is not None and got[1] == b"reply"
    finally:
        client.close()
        server.close()


@pytest.mark.skipif(not HAVE_NATIVE, reason="no C++ toolchain")
def test_transport_cross_impl():
    """Native server interoperates with python client (same framing)."""
    server = NativeTransport()
    client = PyTransport()
    try:
        port = server.listen("127.0.0.1", 0)
        client.connect("127.0.0.1", port)
        blob = bytes(range(256)) * 10
        client.send(0, blob)
        got = server.recv(timeout=5)
        assert got is not None and got[1] == blob
        server.send(got[0], blob[::-1])
        got2 = client.recv(timeout=5)
        assert got2 is not None and got2[1] == blob[::-1]
    finally:
        client.close()
        server.close()


@pytest.mark.parametrize("impl", _impls())
def test_transport_broadcast(impl):
    server = impl()
    subs = [impl(), impl()]
    try:
        port = server.listen("127.0.0.1", 0)
        for s in subs:
            s.connect("127.0.0.1", port)
        deadline = time.monotonic() + 5
        while server.peer_count() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert server.peer_count() == 2
        server.send(0, b"fanout")  # client_id 0 = broadcast
        for s in subs:
            got = s.recv(timeout=5)
            assert got is not None and got[1] == b"fanout"
    finally:
        for s in subs:
            s.close()
        server.close()


# -------------------------------------------------------------- serialization
def test_message_roundtrip():
    f = Frame(
        (np.arange(12, dtype=np.float32).reshape(3, 4),
         np.arange(4, dtype=np.uint8)),
        pts=123456789,
        duration=1000,
    )
    back = decode_message(encode_message(f))
    assert back.pts == 123456789 and back.duration == 1000
    np.testing.assert_array_equal(back.tensors[0], f.tensors[0])
    np.testing.assert_array_equal(back.tensors[1], f.tensors[1])


def test_message_eos():
    assert isinstance(decode_message(encode_message(EOS_FRAME)), EOS)


def test_message_malformed():
    with pytest.raises(ValueError):
        decode_message(b"xx")


# -------------------------------------------------------------- query elements
def _echo_server(src, sink, scale, stop_evt):
    """Minimal server pipeline loop: serversrc → ×scale → serversink."""
    while not stop_evt.is_set():
        frame = src.generate()
        if frame is None:
            continue
        out = frame.with_tensors(
            [np.asarray(t) * scale for t in frame.tensors]
        )
        sink.render(out)


def test_query_client_server_roundtrip():
    from nnstreamer_tpu.edge.query import (
        TensorQueryClient,
        TensorQueryServerSink,
        TensorQueryServerSrc,
    )

    src = TensorQueryServerSrc("qsrc", port=0, id="t1")
    sink = TensorQueryServerSink("qsink", id="t1")
    src.start()
    stop_evt = threading.Event()
    t = threading.Thread(
        target=_echo_server, args=(src, sink, 2.0, stop_evt), daemon=True
    )
    t.start()
    client = TensorQueryClient(
        "qc", **{"dest-host": "127.0.0.1", "dest-port": src.bound_port,
                 "timeout": 5}
    )
    try:
        client.negotiate([Frame((np.zeros(1, np.float32),)).spec()])
        client.start()
        reply = client.process(
            Frame((np.full((2, 3), 3.0, np.float32),), pts=42)
        )
        assert reply is not None
        np.testing.assert_allclose(
            np.asarray(reply.tensors[0]), np.full((2, 3), 6.0)
        )
        assert reply.pts == 42  # reply keeps request timing
        # second round trip on the same connection
        reply2 = client.process(Frame((np.ones(4, np.float32),)))
        np.testing.assert_allclose(np.asarray(reply2.tensors[0]), np.full(4, 2.0))
    finally:
        stop_evt.set()
        client.stop()
        t.join(timeout=2)
        src.stop()


def test_query_multiple_clients_demux():
    """Two clients share one server; replies route by client_id
    (reference GstMetaQuery demultiplexing)."""
    from nnstreamer_tpu.edge.query import (
        TensorQueryClient,
        TensorQueryServerSink,
        TensorQueryServerSrc,
    )

    src = TensorQueryServerSrc("qsrc2", port=0, id="t2")
    sink = TensorQueryServerSink("qsink2", id="t2")
    src.start()
    stop_evt = threading.Event()
    t = threading.Thread(
        target=_echo_server, args=(src, sink, 1.0, stop_evt), daemon=True
    )
    t.start()
    c1 = TensorQueryClient("c1", **{"dest-port": src.bound_port, "timeout": 5})
    c2 = TensorQueryClient("c2", **{"dest-port": src.bound_port, "timeout": 5})
    try:
        c1.start()
        c2.start()
        r1 = c1.process(Frame((np.full(2, 10.0, np.float32),)))
        r2 = c2.process(Frame((np.full(2, 20.0, np.float32),)))
        assert float(np.asarray(r1.tensors[0])[0]) == 10.0
        assert float(np.asarray(r2.tensors[0])[0]) == 20.0
    finally:
        stop_evt.set()
        c1.stop()
        c2.stop()
        t.join(timeout=2)
        src.stop()


def test_query_client_timeout():
    from nnstreamer_tpu.edge.query import TensorQueryClient
    from nnstreamer_tpu.edge.transport import PyTransport
    from nnstreamer_tpu.elements.base import ElementError

    silent = PyTransport()
    port = silent.listen("127.0.0.1", 0)
    client = TensorQueryClient(
        "qt", **{"dest-port": port, "timeout": 0.2}
    )
    try:
        client.start()
        with pytest.raises(ElementError, match="timeout"):
            client.process(Frame((np.zeros(1, np.float32),)))
    finally:
        client.stop()
        silent.close()


# ---------------------------------------------------------------- pub/sub
def test_edge_pubsub_pipeline():
    """edgesink pipeline publishes, edgesrc pipeline receives — both driven
    by the real executor (reference runTest.sh two-process loopback)."""
    from nnstreamer_tpu.edge.pubsub import EdgeSink, EdgeSrc
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline

    frames = [
        Frame((np.full((2, 2), float(i), np.float32),), pts=i * 1000)
        for i in range(5)
    ]
    pub_src = AppSrc(
        "app0", iterable=frames,
        spec=frames[0].spec(),
    )
    pub_sink = EdgeSink(
        "esink", port=0, **{"wait-connection": "true",
                            "connection-timeout": 5}
    )
    pub = Pipeline("pub").chain(pub_src, pub_sink)
    pub.negotiate()
    plan = pub.compile_plan()

    # start publisher paused until subscriber connects (wait-connection)
    pub_thread = threading.Thread(target=lambda: pub.run(timeout=10), daemon=True)
    pub_thread.start()
    deadline = time.monotonic() + 5
    while pub_sink.bound_port is None and time.monotonic() < deadline:
        time.sleep(0.01)
    assert pub_sink.bound_port

    sub_src = EdgeSrc("esrc", **{"dest-port": pub_sink.bound_port})
    sub_sink = TensorSink("tsink")
    sub = Pipeline("sub").chain(sub_src, sub_sink)
    sub.negotiate()
    sub.run(timeout=10)
    pub_thread.join(timeout=5)

    received = sub_sink.frames
    assert len(received) == 5
    for i, f in enumerate(received):
        assert float(np.asarray(f.tensors[0])[0, 0]) == float(i)
        assert f.pts == i * 1000


# ------------------------------------------------------------------ gRPC
@pytest.mark.parametrize("idl", ["protobuf", "flatbuf"])
def test_grpc_push_pull(idl):
    """Client-mode sink pushes into a server-mode src (SendTensors path),
    over both IDLs (reference nnstreamer_grpc_{protobuf,flatbuf}.cc)."""
    pytest.importorskip("grpc")
    from nnstreamer_tpu.edge.grpc_bridge import GrpcTensorSink, GrpcTensorSrc

    src = GrpcTensorSrc("gsrc", server="true", port=0, idl=idl)
    src.start()
    sink = GrpcTensorSink("gsink", server="false", port=src.bound_port, idl=idl)
    sink.start()
    try:
        sink.render(Frame((np.arange(6, dtype=np.float32).reshape(2, 3),)))
        got = None
        deadline = time.monotonic() + 5
        while got is None and time.monotonic() < deadline:
            got = src.generate()
        assert got is not None and got is not EOS_FRAME
        np.testing.assert_array_equal(
            np.asarray(got.tensors[0]),
            np.arange(6, dtype=np.float32).reshape(2, 3),
        )
    finally:
        sink.stop()
        src.stop()


@pytest.mark.parametrize("idl", ["protobuf", "flatbuf"])
def test_grpc_serve_stream(idl):
    """Server-mode sink streams to a client-mode src (RecvTensors path),
    over both IDLs."""
    pytest.importorskip("grpc")
    from nnstreamer_tpu.edge.grpc_bridge import GrpcTensorSink, GrpcTensorSrc

    sink = GrpcTensorSink("gsink2", server="true", port=0, idl=idl)
    sink.start()
    src = GrpcTensorSrc("gsrc2", server="false", port=sink.bound_port, idl=idl)
    src.start()
    try:
        # wait for the subscriber's RecvTensors stream to attach
        deadline = time.monotonic() + 5
        while not sink._subscribers and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sink._subscribers
        for i in range(3):
            sink.render(Frame((np.full(2, float(i), np.float32),)))
        got = []
        deadline = time.monotonic() + 5
        while len(got) < 3 and time.monotonic() < deadline:
            f = src.generate()
            if f is not None and f is not EOS_FRAME:
                got.append(f)
        assert len(got) == 3
        assert float(np.asarray(got[2].tensors[0])[0]) == 2.0
    finally:
        sink.stop()
        src.stop()


@pytest.mark.parametrize("impl", _impls())
def test_broadcast_survives_dead_subscriber(impl):
    """One dead subscriber must not kill the publisher (best-effort
    broadcast; the reference's edge pub/sub behaves the same)."""
    server = impl()
    alive = impl()
    dead = impl()
    try:
        port = server.listen("127.0.0.1", 0)
        alive.connect("127.0.0.1", port)
        dead.connect("127.0.0.1", port)
        deadline = time.monotonic() + 5
        while server.peer_count() < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        dead.close()  # subscriber vanishes
        for _ in range(20):  # keep sending until the close is visible
            server.send(0, b"still-alive")
            time.sleep(0.01)
        got = alive.recv(timeout=5)
        assert got is not None and got[1] == b"still-alive"
    finally:
        alive.close()
        server.close()


def test_grpc_idl_mismatch_fails_loudly():
    """A protobuf client against a flatbuf server must error (distinct
    service names), not silently mis-parse — reference behavior."""
    pytest.importorskip("grpc")
    from nnstreamer_tpu.edge.grpc_bridge import GrpcTensorSink, GrpcTensorSrc
    from nnstreamer_tpu.elements.base import ElementError

    src = GrpcTensorSrc("gsrc3", server="true", port=0, idl="flatbuf")
    src.start()
    sink = GrpcTensorSink(
        "gsink3", server="false", port=src.bound_port, idl="protobuf"
    )
    sink.start()
    try:
        with pytest.raises(ElementError):
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                sink.render(Frame((np.zeros(2, np.float32),)))
                time.sleep(0.05)
    finally:
        sink.stop()
        src.stop()


def test_grpc_unknown_idl_rejected():
    pytest.importorskip("grpc")
    from nnstreamer_tpu.edge.grpc_bridge import GrpcTensorSrc
    from nnstreamer_tpu.elements.base import ElementError

    bad = GrpcTensorSrc("gsrc4", server="true", port=0, idl="capnproto")
    with pytest.raises(ElementError, match="unknown idl"):
        bad.start()


def test_grpc_client_unreachable_raises():
    pytest.importorskip("grpc")
    from nnstreamer_tpu.edge.grpc_bridge import GrpcTensorSrc
    from nnstreamer_tpu.elements.base import ElementError

    src = GrpcTensorSrc(
        "gdead", server="false", port=1, **{"connection-timeout": 0.3}
    )
    with pytest.raises(ElementError, match="cannot reach"):
        src.start()


def test_transport_churn_stress():
    """Concurrency stress on the native transport: clients connect, send,
    and vanish while the server broadcasts — exercises the dead-fd
    bookkeeping (fd-reuse race) under churn. Build with
    NNS_EDGE_SANITIZE=thread g++ instrumentation to run it under TSAN."""
    from nnstreamer_tpu.edge.transport import make_transport

    server = make_transport()
    port = server.listen("127.0.0.1", 0)
    stop = threading.Event()

    def broadcaster():
        while not stop.is_set():
            try:
                server.send(0, b"tick" * 64)
            except Exception:
                pass

    bcast = threading.Thread(target=broadcaster, daemon=True)
    bcast.start()

    received = []

    def client_life(i):
        c = make_transport()
        try:
            c.connect("127.0.0.1", port)
            c.send(0, f"hello {i}".encode())
            got = c.recv(timeout=2)
            if got is not None:
                received.append(i)
        finally:
            c.close()

    threads = [threading.Thread(target=client_life, args=(i,)) for i in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=20)
    stop.set()
    bcast.join(timeout=5)
    # server saw the client messages (some may race with disconnect)
    got_msgs = 0
    while True:
        m = server.recv(timeout=0.2)
        if m is None:
            break
        if m[1]:
            got_msgs += 1
    server.close()
    assert got_msgs >= 12, f"only {got_msgs} of 24 client messages arrived"
    assert len(received) >= 12, f"only {len(received)} clients got a broadcast"
