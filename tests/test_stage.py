"""tensor_stage: dedicated-node device upload (double-buffered H2D).

VERDICT r4 #3's overlap evidence: the stage thread must have ALREADY
handed frame N+1 downstream (device_put issued) while the consumer is
still busy with frame N — asserted on dispatch timestamps, not wall
time, so it holds on any machine."""

import time

import numpy as np
import pytest

from nnstreamer_tpu.elements.base import HostElement
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import AppSrc
from nnstreamer_tpu.elements.stage import TensorStage
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


class _SlowConsumer(HostElement):
    """Stands in for a busy filter node: holds each frame ~20 ms and
    records (staged_at, start, end) per frame."""

    def __init__(self):
        super().__init__()
        self.times = []

    def negotiate(self, in_specs):
        return list(in_specs)

    def process(self, frame):
        t0 = time.perf_counter()
        time.sleep(0.02)
        self.times.append(
            (frame.meta.get("staged_at"), t0, time.perf_counter())
        )
        return frame


def _frames(n):
    rng = np.random.default_rng(0)
    return [
        Frame((rng.integers(0, 255, (1, 8, 8, 3)).astype(np.uint8),))
        for _ in range(n)
    ]


class _TypeProbe(HostElement):
    """Records the tensor types flowing past (the sink renders host
    copies, so device placement must be observed mid-pipeline)."""

    def __init__(self):
        super().__init__()
        self.types = []

    def negotiate(self, in_specs):
        return list(in_specs)

    def process(self, frame):
        self.types.append(type(frame.tensors[0]))
        return frame


def test_stage_uploads_to_device_spec_passthrough():
    import jax

    spec = TensorsSpec.from_strings("3:8:8:1", "uint8")
    src = AppSrc(iterable=_frames(3), spec=spec)
    st = TensorStage()
    probe = _TypeProbe()
    sink = TensorSink()
    p = Pipeline().chain(src, st, probe, sink)
    p.run(timeout=30)
    assert sink.rendered == 3
    assert len(probe.types) == 3
    assert all(issubclass(t, jax.Array) for t in probe.types)
    assert st.out_specs == st.in_specs  # placement changes, spec doesn't


def test_stage_overlaps_upload_with_consumer():
    """While the consumer chews frame N, the stage node must already
    have staged frame N+1 (staged_at[N+1] < consumer end[N]) for most
    frames — the double-buffering claim itself."""
    n = 8
    spec = TensorsSpec.from_strings("3:8:8:1", "uint8")
    src = AppSrc(iterable=_frames(n), spec=spec)
    st = TensorStage(stamp=True)
    consumer = _SlowConsumer()
    sink = TensorSink()
    p = Pipeline().chain(src, st, consumer, sink)
    p.run(timeout=60)
    assert sink.rendered == n
    times = consumer.times
    assert len(times) == n and all(t[0] is not None for t in times)
    overlapped = sum(
        1 for i in range(n - 1)
        if times[i + 1][0] < times[i][2]  # staged N+1 before N finished
    )
    # the first hop may serialize (pipeline fill); steady state must not
    assert overlapped >= (n - 1) * 3 // 4, (overlapped, times)


def test_stage_bad_device_index():
    spec = TensorsSpec.from_strings("3:8:8:1", "uint8")
    src = AppSrc(iterable=_frames(1), spec=spec)
    st = TensorStage(device="99")
    with pytest.raises(Exception, match="out of range"):
        p = Pipeline().chain(src, st, TensorSink())
        p.run(timeout=30)
