"""Pallas kernel tests (interpreter mode — the CPU analogue of the
reference's dummy-device strategy; the same kernel code compiles via
Mosaic on TPU, verified on hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.ops.pallas import registry as kreg
from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention, make_flash_attention
from nnstreamer_tpu.parallel.ring_attention import dense_attention


def _qkv(rng, b=2, t=64, h=4, d=16, dtype=jnp.float32):
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32).astype(dtype)
        for _ in range(3)
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(np.random.default_rng(0))
        out = flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
        )
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_padded_sequence(self, causal):
        # T=100 with block 32 → internal pad to 128; padded keys masked
        q, k, v = _qkv(np.random.default_rng(1), t=100, d=32, h=2)
        out = flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
        )
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bfloat16_inputs_f32_softmax(self):
        q, k, v = _qkv(np.random.default_rng(2), dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
        ref = dense_attention(q, k, v)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_transformer_attn_plug(self):
        from nnstreamer_tpu.models import transformer as tfm

        params = tfm.init_params(
            jax.random.PRNGKey(0), vocab=32, d_model=32, n_heads=2, n_layers=1
        )
        toks = jnp.asarray(np.random.default_rng(3).integers(0, 32, (1, 24)), jnp.int32)
        dense = tfm.apply(params, toks, 2)
        flash = tfm.apply(
            params, toks, 2,
            attn_fn=make_flash_attention(interpret=True, block_q=16, block_k=16),
        )
        np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), atol=1e-4)


class TestDecodeAttention:
    """Single-pass decode kernel (ops/pallas/decode_attention.py) vs the
    serving step's inline masked-softmax reference."""

    def _ref(self, q, ck, cv, pos):
        hd = q.shape[-1]
        s = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), ck.astype(jnp.float32)
        ) / (hd ** 0.5)
        mask = jnp.arange(ck.shape[1])[None, :] <= pos[:, None]
        s = jnp.where(mask[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p, cv.astype(jnp.float32))
        return o

    # the shape grid lives in the kernel registry (the single source of
    # parity shapes — nns-kscope sweeps the same cases); the
    # non-dividing lengths pin ceil-covered masked tail blocks, a prime
    # length must keep full-width blocks (ADVICE r2). The independent
    # masked-softmax reference above stays — registry run_case parity
    # against the in-tree jnp reference is the sweep's job.
    @pytest.mark.parametrize(
        "s_len,block_k",
        [
            pytest.param(
                c.params["s_len"], c.params.get("block_k", 128), id=c.name
            )
            for c in kreg.get("decode_attention").cases
            if c.params.get("dtype", "float32") == "float32"
            and c.params.get("s_len", 0) <= 256
        ],
    )
    def test_matches_masked_softmax(self, s_len, block_k):
        from nnstreamer_tpu.ops.pallas.decode_attention import decode_attention

        rng = np.random.default_rng(1)
        b, h, d = 3, 4, 16
        q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
        ck = jnp.asarray(rng.standard_normal((b, s_len, h, d)), jnp.float32)
        cv = jnp.asarray(rng.standard_normal((b, s_len, h, d)), jnp.float32)
        pos = jnp.asarray([0, s_len // 2, s_len - 1], jnp.int32)
        out = decode_attention(q, ck, cv, pos, block_k=block_k, interpret=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, ck, cv, pos)), atol=2e-5
        )

    @pytest.mark.parametrize("s_len,block_k", [(200, 128), (33, 16)])
    def test_windowed_wrap_absolute_pos(self, s_len, block_k):
        """After a ring wrap the batcher passes ABSOLUTE pos (pos+1 >
        s_len, the all-live saturation); with a non-dividing cache
        length the tail block's pad columns must stay masked — the
        kernel clamps live_len to the static cache length (ADVICE r3:
        unclamped, pad columns in [s_len, n_k*block_k) read garbage
        K/V into the softmax)."""
        from nnstreamer_tpu.ops.pallas.decode_attention import decode_attention

        rng = np.random.default_rng(7)
        b, h, d = 2, 2, 16
        q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.float32)
        ck = jnp.asarray(rng.standard_normal((b, s_len, h, d)), jnp.float32)
        cv = jnp.asarray(rng.standard_normal((b, s_len, h, d)), jnp.float32)
        # wrapped: absolute positions far past the cache length, and one
        # exactly at the wrap boundary
        pos = jnp.asarray([s_len, 3 * s_len + 7], jnp.int32)
        out = decode_attention(q, ck, cv, pos, block_k=block_k, interpret=True)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._ref(q, ck, cv, pos)), atol=2e-5
        )

    def test_bfloat16_cache(self):
        from nnstreamer_tpu.ops.pallas.decode_attention import decode_attention

        rng = np.random.default_rng(2)
        b, s_len, h, d = 2, 32, 2, 16
        q = jnp.asarray(rng.standard_normal((b, 1, h, d)), jnp.bfloat16)
        ck = jnp.asarray(rng.standard_normal((b, s_len, h, d)), jnp.bfloat16)
        cv = jnp.asarray(rng.standard_normal((b, s_len, h, d)), jnp.bfloat16)
        pos = jnp.asarray([5, 20], jnp.int32)
        out = decode_attention(q, ck, cv, pos, block_k=16, interpret=True)
        ref = self._ref(
            q.astype(jnp.float32), ck.astype(jnp.float32),
            cv.astype(jnp.float32), pos,
        )
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)

    def test_serving_step_with_pallas_attn(self):
        """ContinuousBatcher(attn_impl="pallas") emits the same greedy
        tokens as the XLA step."""
        from nnstreamer_tpu.models import transformer as tfm
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = tfm.init_params(
            jax.random.PRNGKey(3), vocab=128, d_model=32, n_heads=2,
            n_layers=2,
        )
        prompt = np.random.default_rng(4).integers(1, 128, (6,))
        outs = {}
        for impl in ("xla", "pallas"):
            cb = ContinuousBatcher(
                params, 2, n_slots=2, max_len=32, prompt_len=8,
                attn_impl=impl,
            )
            rid = cb.submit(prompt, 4)
            while cb.result(rid) is None:
                cb.step()
            outs[impl] = cb.result(rid)
        assert outs["xla"] == outs["pallas"]
