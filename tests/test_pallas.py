"""Pallas kernel tests (interpreter mode — the CPU analogue of the
reference's dummy-device strategy; the same kernel code compiles via
Mosaic on TPU, verified on hardware)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention, make_flash_attention
from nnstreamer_tpu.parallel.ring_attention import dense_attention


def _qkv(rng, b=2, t=64, h=4, d=16, dtype=jnp.float32):
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32).astype(dtype)
        for _ in range(3)
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        q, k, v = _qkv(np.random.default_rng(0))
        out = flash_attention(
            q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
        )
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_padded_sequence(self, causal):
        # T=100 with block 32 → internal pad to 128; padded keys masked
        q, k, v = _qkv(np.random.default_rng(1), t=100, d=32, h=2)
        out = flash_attention(
            q, k, v, causal=causal, block_q=32, block_k=32, interpret=True
        )
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_bfloat16_inputs_f32_softmax(self):
        q, k, v = _qkv(np.random.default_rng(2), dtype=jnp.bfloat16)
        out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
        ref = dense_attention(q, k, v)
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_transformer_attn_plug(self):
        from nnstreamer_tpu.models import transformer as tfm

        params = tfm.init_params(
            jax.random.PRNGKey(0), vocab=32, d_model=32, n_heads=2, n_layers=1
        )
        toks = jnp.asarray(np.random.default_rng(3).integers(0, 32, (1, 24)), jnp.int32)
        dense = tfm.apply(params, toks, 2)
        flash = tfm.apply(
            params, toks, 2,
            attn_fn=make_flash_attention(interpret=True, block_q=16, block_k=16),
        )
        np.testing.assert_allclose(np.asarray(flash), np.asarray(dense), atol=1e-4)
