"""SSAT-style golden pipeline tests (reference test strategy, SURVEY.md §4:
44 runTest.sh suites run gst-launch pipelines with deterministic sources,
dump via filesink, and byte-compare against golden files).

Golden files live in tests/golden/ and were produced by the same pipelines
at introduction time; the tests re-run the pipeline through the CLI (the
real user entry point, like SSAT drives gst-launch) and compare bytes.
Regenerate with: python tests/test_golden.py --regen
"""

import os
import subprocess
import sys

import pytest

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")

# name → pipeline template ({out} replaced by the dump path)
PIPELINES = {
    # raw media→tensor ingress (counter pattern = frame index everywhere)
    "converter_video": (
        "videotestsrc pattern=counter num-frames=3 width=4 height=4 ! "
        "tensor_converter ! filesink location={out}"
    ),
    # elementwise chain: typecast then arithmetic (transform suite analogue)
    "transform_arith": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        'tensor_transform mode=arithmetic option="add:1,mul:2" ! '
        "filesink location={out}"
    ),
    # transpose (HWC→CWH style dim reorder)
    "transform_transpose": (
        "videotestsrc pattern=gradient num-frames=2 width=4 height=6 ! "
        "tensor_converter ! tensor_transform mode=transpose option=1:0:2:3 ! "
        "filesink location={out}"
    ),
    # fake-backend inference (custom scaler = the reference's custom .so fake)
    "filter_scaler": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        'tensor_filter framework=scaler custom="factor:0.5" ! '
        "filesink location={out}"
    ),
    # static→sparse→static roundtrip must be byte-identical to the input
    "sparse_roundtrip": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_sparse_enc ! tensor_sparse_dec ! "
        "filesink location={out}"
    ),
    # aggregator: 2-frame temporal batch along the time axis
    "aggregator_window": (
        "videotestsrc pattern=counter num-frames=4 width=4 height=4 ! "
        "tensor_converter ! tensor_aggregator frames-in=1 frames-out=2 "
        "frames-flush=2 ! filesink location={out}"
    ),
}


def _run(pipeline: str, out_path: str) -> None:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         pipeline.format(out=out_path), "-q"],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert proc.returncode == 0, f"pipeline failed:\n{proc.stderr}"


@pytest.mark.parametrize("name", sorted(PIPELINES))
def test_golden(name, tmp_path):
    golden = os.path.join(GOLDEN_DIR, f"{name}.raw")
    assert os.path.isfile(golden), f"missing golden {golden} (run --regen)"
    out = tmp_path / "dump.raw"
    _run(PIPELINES[name], str(out))
    actual = out.read_bytes()
    expected = open(golden, "rb").read()
    assert len(actual) == len(expected), (
        f"{name}: size {len(actual)} != golden {len(expected)}"
    )
    assert actual == expected, f"{name}: byte mismatch vs golden"


if __name__ == "__main__":
    if "--regen" in sys.argv:
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        for name, pipe in sorted(PIPELINES.items()):
            path = os.path.join(GOLDEN_DIR, f"{name}.raw")
            _run(pipe, path)
            print(f"wrote {path} ({os.path.getsize(path)} bytes)")
    else:
        print(__doc__)
