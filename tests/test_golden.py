"""SSAT-style golden pipeline tests (reference test strategy, SURVEY.md §4:
44 runTest.sh suites run gst-launch pipelines with deterministic sources,
dump via filesink, and byte-compare against golden files).

Golden files live in tests/golden/ and were produced by the same pipelines
at introduction time; the tests re-run the pipeline through the CLI (the
real user entry point, like SSAT drives gst-launch) and compare bytes.
Regenerate with: python tests/test_golden.py --regen
"""

import os
import subprocess
import sys

import pytest

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")

# name → pipeline template ({out} replaced by the dump path)
PIPELINES = {
    # raw media→tensor ingress (counter pattern = frame index everywhere)
    "converter_video": (
        "videotestsrc pattern=counter num-frames=3 width=4 height=4 ! "
        "tensor_converter ! filesink location={out}"
    ),
    # elementwise chain: typecast then arithmetic (transform suite analogue)
    "transform_arith": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        'tensor_transform mode=arithmetic option="add:1,mul:2" ! '
        "filesink location={out}"
    ),
    # per-channel arithmetic constants (transform_arithmetic per-channel
    # cases: add:N@CH applies to one channel index)
    "transform_per_channel": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=arithmetic "
        'option="typecast:float32,per-channel:true@0,add:100@0,mul:2@2" ! '
        "filesink location={out}"
    ),
    # remaining transform suites (reference tests/transform_{clamp,stand,
    # dimchg}/runTest.sh)
    "transform_clamp": (
        "videotestsrc pattern=counter num-frames=3 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        "tensor_transform mode=clamp option=0.5:1.5 ! "
        "filesink location={out}"
    ),
    "transform_stand": (
        "videotestsrc pattern=gradient num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        "tensor_transform mode=stand option=default ! "
        "filesink location={out}"
    ),
    "transform_dimchg": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=6 ! "
        "tensor_converter ! tensor_transform mode=dimchg option=0:2 ! "
        "filesink location={out}"
    ),
    # converter frames-per-tensor batching (gsttensor_converter.c)
    "converter_batch": (
        "videotestsrc pattern=counter num-frames=4 width=4 height=4 ! "
        "tensor_converter frames-per-tensor=2 ! filesink location={out}"
    ),
    # transpose (HWC→CWH style dim reorder)
    "transform_transpose": (
        "videotestsrc pattern=gradient num-frames=2 width=4 height=6 ! "
        "tensor_converter ! tensor_transform mode=transpose option=1:0:2:3 ! "
        "filesink location={out}"
    ),
    # fake-backend inference (custom scaler = the reference's custom .so fake)
    "filter_scaler": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        'tensor_filter framework=scaler custom="factor:0.5" ! '
        "filesink location={out}"
    ),
    # static→sparse→static roundtrip must be byte-identical to the input
    "sparse_roundtrip": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_sparse_enc ! tensor_sparse_dec ! "
        "filesink location={out}"
    ),
    # aggregator: 2-frame temporal batch along the time axis
    "aggregator_window": (
        "videotestsrc pattern=counter num-frames=4 width=4 height=4 ! "
        "tensor_converter ! tensor_aggregator frames-in=1 frames-out=2 "
        "frames-flush=2 ! filesink location={out}"
    ),
    # BASELINE composite config #5: detect (device 0) → crop → landmark
    # (device 1) over the virtual mesh, through the CLI
    "composite_face": (
        "videotestsrc pattern=gradient num-frames=2 width=128 height=128 ! "
        "tensor_converter ! tee name=t "
        "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
        'custom="output:regions,threshold:0.0,frame_size:128:128,device:0" '
        "! crop.sink_1 "
        "t. ! queue ! crop.sink_0 "
        "tensor_crop name=crop ! "
        'tensor_filter framework=jax model=zoo:face_landmark custom="device:1" '
        "invoke-dynamic=true input-combination=0 ! filesink location={out}"
    ),
    # decoder goldens (reference tests/nnstreamer_decoder_*/runTest.sh)
    "decoder_bbox_ov": (
        "videotestsrc pattern=gradient num-frames=1 width=128 height=128 ! "
        "tensor_converter ! tensor_filter framework=jax model=zoo:face_detect ! "
        "tensor_decoder mode=bounding_boxes option1=ov-face-detection "
        "option4=64:64 option5=128:128 ! filesink location={out}"
    ),
    "decoder_label": (
        "videotestsrc pattern=gradient num-frames=1 width=64 height=64 ! "
        "tensor_converter ! tensor_filter framework=jax model=zoo:mobilenet_v2 "
        'custom="size:64,num_classes:16" ! '
        "tensor_decoder mode=image_labeling ! filesink location={out}"
    ),
    "decoder_pose": (
        "videotestsrc pattern=gradient num-frames=1 width=257 height=257 ! "
        "tensor_converter ! tensor_filter framework=jax model=zoo:posenet "
        "output-combination=o0,o1 ! "
        "tensor_decoder mode=pose_estimation option1=32:32 option2=257:257 "
        "option4=heatmap-offset ! filesink location={out}"
    ),
    "decoder_segment": (
        "videotestsrc pattern=gradient num-frames=1 width=257 height=257 ! "
        "tensor_converter ! tensor_filter framework=jax model=zoo:deeplab_v3 ! "
        "tensor_decoder mode=image_segment option1=tflite-deeplab ! "
        "filesink location={out}"
    ),
    "decoder_direct_video": (
        "videotestsrc pattern=counter num-frames=2 width=8 height=8 ! "
        "tensor_converter ! tensor_decoder mode=direct_video ! "
        "filesink location={out}"
    ),
    # mux sync policies (synchronization-policies-at-mux-merge.md)
    "mux_slowest": (
        "videotestsrc pattern=counter num-frames=4 width=4 height=4 "
        "framerate=20/1 ! tensor_converter ! mux.sink_0 "
        "videotestsrc pattern=gradient num-frames=2 width=4 height=4 "
        "framerate=10/1 ! tensor_converter ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=slowest ! filesink location={out}"
    ),
    "mux_basepad": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 "
        "framerate=10/1 ! tensor_converter ! mux.sink_0 "
        "videotestsrc pattern=gradient num-frames=4 width=4 height=4 "
        "framerate=20/1 ! tensor_converter ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=basepad sync-option=0:0 ! "
        "filesink location={out}"
    ),
    # demux tensorpick selection/reorder
    "demux_tensorpick": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! mux.sink_0 "
        "videotestsrc pattern=gradient num-frames=2 width=4 height=4 ! "
        "tensor_converter ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=nosync ! "
        "tensor_demux tensorpick=1 ! filesink location={out}"
    ),
    # grouped tensorpick: pads carry tensor GROUPS ('0:1' = first two)
    "demux_grouped": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! mux.sink_0 "
        "videotestsrc pattern=gradient num-frames=2 width=4 height=4 ! "
        "tensor_converter ! mux.sink_1 "
        "videotestsrc pattern=solid num-frames=2 width=4 height=4 ! "
        "tensor_converter ! mux.sink_2 "
        "tensor_mux name=mux sync-mode=nosync ! "
        "tensor_demux tensorpick=0:1 ! filesink location={out}"
    ),
    # refresh policy: emit on every new frame, reusing the other pad's
    # last. The slow pad contributes a SINGLE frame so every thread
    # interleaving yields identical bytes (live refresh is arrival-
    # driven; with one slow-pad frame at pts 0, priming plus stale reuse
    # gives the same 4 groups in any order — see test_routing.py)
    "mux_refresh": (
        "videotestsrc pattern=counter num-frames=4 width=4 height=4 "
        "framerate=20/1 ! tensor_converter ! mux.sink_0 "
        "videotestsrc pattern=gradient num-frames=1 width=4 height=4 "
        "framerate=10/1 ! tensor_converter ! mux.sink_1 "
        "tensor_mux name=mux sync-mode=refresh ! filesink location={out}"
    ),
    # split a tensor along a dim, then merge back (gsttensor_split/merge.c)
    "split_merge": (
        "videotestsrc pattern=counter num-frames=2 width=8 height=4 ! "
        "tensor_converter ! tensor_split tensorseg=3:8:2:1,3:8:2:1 "
        "name=sp sp.src_0 ! m.sink_0 sp.src_1 ! m.sink_1 "
        "tensor_merge name=m mode=linear option=2 sync-mode=nosync ! "
        "filesink location={out}"
    ),
    # data-dependent branch: average-value predicate, else fills zeros
    "if_branch": (
        "videotestsrc pattern=counter num-frames=4 width=4 height=4 ! "
        "tensor_converter ! tensor_transform mode=typecast option=float32 ! "
        "tensor_if compared-value=TENSOR_AVERAGE_VALUE "
        "compared-value-option=0 operator=GT supplied-value=1.5 "
        "then=PASSTHROUGH else=FILL_ZERO ! filesink location={out}"
    ),
    # rate conversion: 20 fps in → 10 fps out (dup/drop path)
    "rate_drop": (
        "videotestsrc pattern=counter num-frames=6 width=4 height=4 "
        "framerate=20/1 ! tensor_converter ! tensor_rate framerate=10/1 ! "
        "filesink location={out}"
    ),
    # wire codecs (tensor_decoder flexbuf/protobuf/flatbuf serializations)
    "decoder_flexbuf": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_decoder mode=flexbuf ! "
        "filesink location={out}"
    ),
    "decoder_protobuf": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_decoder mode=protobuf ! "
        "filesink location={out}"
    ),
    "decoder_flatbuf": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_decoder mode=flatbuf ! "
        "filesink location={out}"
    ),
    "decoder_octet": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_decoder mode=octet_stream ! "
        "filesink location={out}"
    ),
    # overlapping sliding window (frames-flush < frames-out)
    "aggregator_overlap": (
        "videotestsrc pattern=counter num-frames=5 width=4 height=4 ! "
        "tensor_converter ! tensor_aggregator frames-in=1 frames-out=3 "
        "frames-flush=1 ! filesink location={out}"
    ),
    # flexbuf wire roundtrip back to static tensors must be identity
    "converter_flexbuf_roundtrip": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_decoder mode=flexbuf ! "
        "tensor_converter mode=flexbuf ! filesink location={out}"
    ),
    # audio ingress (audio/x-raw → tensors, S16LE)
    "converter_audio": (
        "audiotestsrc samples-per-buffer=32 num-buffers=2 channels=2 ! "
        "tensor_converter ! filesink location={out}"
    ),
    # application/octet-stream ingress with fixed framing
    "converter_octet": (
        "filesrc location={fix}/octet20.bin blocksize=5 ! "
        "tensor_converter input-dim=5 input-type=uint8 ! "
        "filesink location={out}"
    ),
    # tensor_if FILL_WITH_FILE_RPT: else-branch payload comes from a file
    "if_fill_file": (
        "videotestsrc pattern=counter num-frames=3 width=4 height=4 ! "
        "tensor_converter ! "
        "tensor_if compared-value=TENSOR_AVERAGE_VALUE "
        "compared-value-option=0 operator=GE supplied-value=1 "
        "then=PASSTHROUGH else=FILL_WITH_FILE_RPT "
        "else-option={fix}/octet20.bin ! filesink location={out}"
    ),
    # python3 script subplugins through the CLI (tensordec-python3.cc /
    # tensor_filter_python3.cc parity)
    "decoder_python3": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_decoder mode=python3 "
        "option1={fix}/double_decoder.py ! filesink location={out}"
    ),
    "filter_python3": (
        "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
        "tensor_converter ! tensor_filter framework=custom "
        "model={fix}/negate_filter.py ! filesink location={out}"
    ),
    # int8 PTQ serving path (models/quantize.py; the *_quant.tflite slot):
    # calibration is seeded, so the quantized logits are deterministic
    "filter_int8": (
        "videotestsrc pattern=gradient num-frames=2 width=96 height=96 ! "
        "tensor_converter ! tensor_filter framework=jax "
        'model=zoo:mobilenet_v2 custom="quantize:int8,size:96,'
        'num_classes:16" ! filesink location={out}'
    ),
    # weight-only int8 LLM generation through a filter stage
    "filter_lm_int8w": (
        "tensorsrc dimensions=16:1 types=int32 num-frames=1 ! "
        "tensor_filter framework=jax model=zoo:transformer_lm "
        'custom="vocab:512,d_model:64,n_heads:4,n_layers:2,generate:6,'
        'quantize:int8w,seqlen:16" ! filesink location={out}'
    ),
    # fused on-device cascade (zoo:face_composite): detect→crop+resize→
    # landmark as one XLA program, landmarks + detections to file
    "composite_fused": (
        "videotestsrc pattern=gradient num-frames=2 width=128 height=128 ! "
        "tensor_converter ! tensor_filter framework=jax "
        'model=zoo:face_composite custom="threshold:0.0" ! '
        "filesink location={out}"
    ),
    # DEVICE-RESIDENT crop cascade (r3): tensor_crop out-size= keeps the
    # whole element cascade in HBM with a static downstream spec
    "composite_device_crop": (
        "videotestsrc pattern=gradient num-frames=2 width=128 height=128 ! "
        "tensor_converter ! tee name=t "
        "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
        'custom="output:regions,threshold:0.0,frame_size:128:128" ! '
        "crop.sink_1 "
        "t. ! queue ! crop.sink_0 "
        "tensor_crop name=crop out-size=112:112 max-crops=16 ! "
        "tensor_filter framework=jax model=zoo:face_landmark "
        'custom="batch:16" ! filesink location={out}'
    ),
    # device-born source must be byte-identical to the host pattern
    # (videotestsrc device=true; the pipeline_fps bench's source)
    "videotestsrc_device": (
        "videotestsrc pattern=gradient device=true num-frames=3 "
        "width=8 height=8 ! tensor_converter ! "
        "tensor_transform mode=typecast option=uint8 ! "
        "filesink location={out}"
    ),
    # device-computed decode (image_labeling argmax fused into the filter
    # program — [N] uint32 indices on the wire, never [N,V] logits)
    "decoder_label_fused": (
        "videotestsrc pattern=gradient num-frames=2 width=96 height=96 ! "
        "tensor_converter ! tensor_filter framework=jax "
        'model=zoo:mobilenet_v2 custom="size:96,num_classes:16" ! '
        "tensor_decoder mode=image_labeling ! filesink location={out}"
    ),
}

# "expect fail" golden cases (reference gstTest "expect fail" flags): the
# CLI must exit non-zero with a diagnostic, not hang or dump raw output
FAIL_PIPELINES = {
    "unknown_element": "videotestsrc num-frames=1 ! no_such_element ! fakesink",
    "filter_without_converter": (
        "videotestsrc num-frames=1 ! "
        "tensor_filter framework=jax model=zoo:add ! fakesink"
    ),
    "bad_mesh": (
        "videotestsrc num-frames=1 width=64 height=64 ! tensor_converter ! "
        "tensor_filter framework=jax model=zoo:mobilenet_v2 "
        'custom="size:64,mesh:dp999" ! fakesink'
    ),
    "dangling_bang": "videotestsrc num-frames=1 ! tensor_converter !",
    "demux_pick_out_of_range": (
        "videotestsrc num-frames=1 width=4 height=4 ! tensor_converter ! "
        "tensor_demux tensorpick=3 ! fakesink"
    ),
}


def _env():
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    return {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags}


FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "fixtures")


def _run(pipeline: str, out_path: str) -> None:
    proc = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         pipeline.format(out=out_path, fix=FIXTURE_DIR), "-q"],
        capture_output=True, text=True, timeout=300, env=_env(),
    )
    assert proc.returncode == 0, f"pipeline failed:\n{proc.stderr}"


@pytest.mark.parametrize("name", sorted(PIPELINES))
def test_golden(name, tmp_path):
    golden = os.path.join(GOLDEN_DIR, f"{name}.raw")
    assert os.path.isfile(golden), f"missing golden {golden} (run --regen)"
    out = tmp_path / "dump.raw"
    _run(PIPELINES[name], str(out))
    actual = out.read_bytes()
    expected = open(golden, "rb").read()
    assert len(actual) == len(expected), (
        f"{name}: size {len(actual)} != golden {len(expected)}"
    )
    assert actual == expected, f"{name}: byte mismatch vs golden"


@pytest.mark.parametrize("name", sorted(FAIL_PIPELINES))
def test_expect_fail(name):
    proc = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu.cli", FAIL_PIPELINES[name], "-q"],
        capture_output=True, text=True, timeout=300, env=_env(),
    )
    assert proc.returncode != 0, f"{name}: expected failure, got rc=0"
    # diagnostic, not a bare traceback (CLI catches and reports)
    assert "Traceback" not in (proc.stderr or ""), (
        f"{name}: CLI dumped a traceback:\n{proc.stderr[-600:]}"
    )
    assert "nns-launch:" in (proc.stderr or "")


if __name__ == "__main__":
    if "--regen" in sys.argv:
        force = "--force" in sys.argv
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        for name, pipe in sorted(PIPELINES.items()):
            path = os.path.join(GOLDEN_DIR, f"{name}.raw")
            if os.path.exists(path) and not force:
                print(f"keep  {path}")
                continue
            _run(pipe, path)
            print(f"wrote {path} ({os.path.getsize(path)} bytes)")
    else:
        print(__doc__)
