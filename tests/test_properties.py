"""Property-based tests (hypothesis) for the codec/parse layers — the
fuzz-adjacent coverage the reference gets from years of fielded inputs:
any valid value must round-trip bit-exactly through dim-strings, the
flexible-tensor wire header, the sparse encoding, and the edge message
codec."""

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed; property tests are optional"
)
from hypothesis import given, settings, strategies as st

from nnstreamer_tpu.tensors.meta import decode_frame_tensors, encode_frame_tensors
from nnstreamer_tpu.tensors.spec import DType, TensorSpec, TensorsSpec

_DTYPES = ["uint8", "int8", "uint16", "int16", "uint32", "int32",
           "float32", "float64", "int64", "uint64"]

_dims = st.lists(st.integers(1, 8), min_size=1, max_size=4)
_dtype = st.sampled_from(_DTYPES)


@st.composite
def _arrays(draw):
    shape = tuple(draw(_dims))
    dt = np.dtype(draw(_dtype))
    if dt.kind == "f":
        a = draw(
            st.lists(
                st.floats(-1e6, 1e6, allow_nan=False, width=32),
                min_size=int(np.prod(shape)), max_size=int(np.prod(shape)),
            )
        )
        return np.asarray(a, dt).reshape(shape)
    info = np.iinfo(dt)
    a = draw(
        st.lists(
            st.integers(max(info.min, -(2**31)), min(info.max, 2**31 - 1)),
            min_size=int(np.prod(shape)), max_size=int(np.prod(shape)),
        )
    )
    return np.asarray(a, dt).reshape(shape)


@settings(max_examples=60, deadline=None)
@given(dims=_dims, dtype=_dtype)
def test_dim_string_roundtrip(dims, dtype):
    spec = TensorSpec(tuple(dims), DType.from_any(dtype))
    parsed = TensorSpec.from_dim_string(spec.dim_string, dtype)
    assert parsed.shape == spec.shape
    assert parsed.dtype == spec.dtype


@settings(max_examples=40, deadline=None)
@given(arrays=st.lists(_arrays(), min_size=1, max_size=4))
def test_flex_header_roundtrip(arrays):
    blob = encode_frame_tensors(tuple(arrays))
    back = decode_frame_tensors(blob)
    assert len(back) == len(arrays)
    for a, b in zip(arrays, back):
        assert b.dtype == a.dtype
        np.testing.assert_array_equal(np.asarray(b).reshape(a.shape), a)


@settings(max_examples=40, deadline=None)
@given(arrays=st.lists(_arrays(), min_size=1, max_size=3))
def test_edge_message_roundtrip(arrays):
    from nnstreamer_tpu.edge.serialize import decode_message, encode_message
    from nnstreamer_tpu.tensors.frame import Frame

    frame = Frame(tuple(arrays), pts=123, duration=7)
    back = decode_message(encode_message(frame))
    assert back.pts == 123 and back.duration == 7
    for a, b in zip(arrays, back.tensors):
        np.testing.assert_array_equal(np.asarray(b).reshape(a.shape), a)


@settings(max_examples=30, deadline=None)
@given(
    shape=st.tuples(st.integers(1, 6), st.integers(1, 6)),
    density=st.floats(0.0, 1.0),
)
def test_sparse_roundtrip(shape, density):
    from nnstreamer_tpu.tensors.sparse import sparse_decode, sparse_encode

    rng = np.random.default_rng(0)
    a = (rng.random(shape) < density).astype(np.float32) * rng.random(shape).astype(
        np.float32
    )
    blob = sparse_encode(a)
    back, consumed = sparse_decode(blob)
    assert consumed == len(blob)
    np.testing.assert_array_equal(np.asarray(back).reshape(shape), a)
