"""Element-layer migration & crash recovery (elements/llm_serve.py,
docs/llm-serving.md "Migration & recovery"): the serversink props
(migrate-to / checkpoint-every-tokens / checkpoint-dir), the drain
contract (NACK ``draining``, settle prefills, migrate-or-resume), the
CTRL handshake through a real query serversrc, and checkpoint/restart
resume that re-runs no completed prefill work.

Runtime note (same floor as tests/test_kv_migrate.py): every
_LlmServer builds its own ContinuousBatcher — ~2.3s params init +
~2.2s pump-program compile each on CPU. The checkpoint/restart test
NEEDS two servers (the second construction IS the restart under
test), so this file cannot go below two batcher builds; everything
else shares servers or runs model-free.
"""

import threading
import time

import jax
import numpy as np
import pytest

from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.tensors.frame import Frame

OPTS = {
    "vocab": "211", "d_model": "32", "n_heads": "2", "n_layers": "1",
    "seed": "5",
}
N_HEADS = 2


def _mk(**kw):
    from nnstreamer_tpu.elements.llm_serve import _LlmServer

    base = dict(
        model="zoo:transformer_lm", options=dict(OPTS), n_slots=2,
        max_len=64, prompt_len=16, default_new=10, kv_layout="paged",
        block_size=16, kv_blocks=0,
    )
    base.update(kw)
    return _LlmServer(**base)


def _alone(prompt, n_new):
    params = tfm.init_params(
        jax.random.PRNGKey(5), vocab=211, d_model=32, n_heads=2,
        n_layers=1,
    )
    toks = dec.generate(
        params, np.asarray(prompt, np.int32)[None, :], N_HEADS, n_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _pump_until(srv, cond, timeout=120.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        srv.pump()


def _prompt(seed, n=6):
    return np.random.default_rng(seed).integers(1, 211, (n,)).astype(
        np.int32
    )


# -- prop validation / typed plane refusal (model-free: both raise
#    before any batcher or plane is built) ------------------------------


def test_migration_props_need_paged_layout():
    from nnstreamer_tpu.elements.llm_serve import _LlmServer

    for bad in (
        dict(migrate_to="peer:7000"),
        dict(checkpoint_dir="/tmp/nowhere"),
        dict(checkpoint_every_tokens=4),
    ):
        with pytest.raises(ElementError, match="kv-layout=paged"):
            _LlmServer(
                model="zoo:transformer_lm", options={}, n_slots=1,
                max_len=32, prompt_len=8, default_new=4,
                kv_layout="slot", **bad,
            )


def test_plane_refuses_migration_surface_typed():
    """Plane-shared batchers refuse migration/checkpointing with the
    plane's own typed error — at element construction (before a plane
    ref is even acquired) and on the LlmPlane surface itself."""
    from nnstreamer_tpu.elements.llm_serve import _LlmServer
    from nnstreamer_tpu.serving_plane.llm import LlmPlane, LlmPlaneError

    for bad in (
        dict(migrate_to="peer:7000"),
        dict(checkpoint_dir="/tmp/nowhere"),
        dict(checkpoint_every_tokens=2),
    ):
        with pytest.raises(LlmPlaneError, match="refused"):
            _LlmServer(
                model="zoo:transformer_lm", options={}, n_slots=1,
                max_len=32, prompt_len=8, default_new=4,
                kv_layout="paged", plane="mig-pl", **bad,
            )
    pl = LlmPlane("mig-pl0", cb=None)
    with pytest.raises(LlmPlaneError, match="private kv-layout=paged"):
        pl.refuse_migration("migrate_span")


# -- the CTRL handshake over a real query serversrc (model-free) --------


class _Handler:
    """A fake LLM server: records what the handshake delivers."""

    def __init__(self):
        self.probed, self.adopted = [], []
        self.refuse = False

    def migration_probe(self, tokens):
        self.probed.append([int(t) for t in tokens])
        return 32

    def migration_adopt(self, span_bytes):
        if self.refuse:
            from nnstreamer_tpu.kv.migrate import SpanStateError

            raise SpanStateError("draining")
        self.adopted.append(bytes(span_bytes))
        return 77


def test_migration_ctrl_handshake_over_wire():
    from nnstreamer_tpu.edge import query as q

    h = _Handler()
    q.register_migration_handler(9, h)
    src = q.TensorQueryServerSrc("mig-wire-src", port=0, id="mig-w1")
    src.start()
    stop = threading.Event()

    def _pump():
        while not stop.is_set():
            src.generate()

    t = threading.Thread(target=_pump, daemon=True)
    t.start()
    try:
        assert q.probe_migration(
            "127.0.0.1", src.bound_port, [1, 2, 3], llm_id=9
        ) == 32
        assert h.probed[-1] == [1, 2, 3]
        assert q.send_migration(
            "127.0.0.1", src.bound_port, b"span-bytes", llm_id=9
        ) == 77
        assert h.adopted == [b"span-bytes"]
        # singleton fallback: a mismatched llm_id still reaches the
        # process's only handler (migrate-to never guesses peer ids)
        assert q.probe_migration(
            "127.0.0.1", src.bound_port, [5], llm_id=123
        ) == 32
        # a refusing handler surfaces as MigrationRefused, reason
        # carrying the span-taxonomy type — the sender's fallback cue
        h.refuse = True
        with pytest.raises(q.MigrationRefused, match="SpanStateError"):
            q.send_migration(
                "127.0.0.1", src.bound_port, b"x", llm_id=9
            )
        q.unregister_migration_handler(9)
        with pytest.raises(
            q.MigrationRefused, match="no-migration-handler"
        ):
            q.probe_migration("127.0.0.1", src.bound_port, [1], llm_id=9)
        # a DRAINING serversrc refuses before consulting any handler:
        # spans must not land on an endpoint that is itself leaving
        q.register_migration_handler(9, h)
        src.drain()
        with pytest.raises(q.MigrationRefused, match="draining"):
            q.probe_migration("127.0.0.1", src.bound_port, [1], llm_id=9)
    finally:
        q.unregister_migration_handler(9)
        stop.set()
        t.join(timeout=2)
        src.stop()


# -- drain: NACK + resume fallback, finish in place ---------------------


def test_drain_resume_fallback_and_draining_refusal():
    """drain(migrate_to=<unreachable>) falls back to local re-prefill
    resume — generated tokens survive, and the finished stream is
    bitwise identical to the uninterrupted run. While draining, new
    submits are refused with the typed ``draining`` error (the edge
    path NACKs with retry-after instead — test_fleet soak)."""
    srv = _mk(srv_id="mig-d1")
    try:
        prompt = _prompt(3)
        srv.submit(Frame((prompt,), meta={"req": "d1", "frame_id": "f-d1"}))
        rid = next(iter(srv._pending))
        _pump_until(
            srv,
            lambda: len(srv.cb.partials([rid]).get(rid) or ()) >= 3,
            what="3 decoded tokens",
        )
        # port 1: nothing listens — connection refused, instantly
        summary = srv.drain(migrate_to="127.0.0.1:1")
        assert summary["resumed"] == 1 and summary["migrated"] == 0
        assert srv.draining
        with pytest.raises(ElementError, match="draining"):
            srv.submit(Frame((prompt,), meta={}))
        # a second drain with no peer keeps the resumed request local
        assert srv.drain()["kept"] == 1
        _pump_until(srv, lambda: srv._out, what="drained generation")
        toks, meta = srv.pop()
        assert meta["req"] == "d1" and meta["frame_id"] == "f-d1"
        assert [int(t) for t in toks] == _alone(prompt, 10)
    finally:
        srv.release_plane()


# -- checkpoint / hard-kill / restart resume ----------------------------


def test_checkpoint_crash_restart_resumes_bitwise(tmp_path):
    """Periodic atomic span checkpoints: a server that vanishes without
    drain (hard kill) is replaced by a fresh one pointing at the same
    checkpoint-dir, which ADOPTS the in-flight generation — no prefill
    re-run (the landed KV re-enters the arena directly) — and finishes
    it bitwise identical to the uninterrupted run, hop-local meta
    stripped and identity meta intact."""
    ckpt = str(tmp_path / "spans")
    prompt = _prompt(11)
    srv1 = _mk(
        srv_id="ck1", checkpoint_every_tokens=2, checkpoint_dir=ckpt,
    )
    try:
        srv1.submit(Frame((prompt,), meta={
            "req": "c1", "frame_id": "f-c1", "client_id": 42,
        }))
        rid = next(iter(srv1._pending))
        _pump_until(
            srv1,
            lambda: len(srv1.cb.partials([rid]).get(rid) or ()) >= 5,
            what="5 decoded tokens",
        )
        files = sorted((tmp_path / "spans").glob("req-*.span"))
        assert files, "no checkpoint written by the cadence tick"
    finally:
        # hard kill: NO drain, no extraction — the process is simply
        # gone; only the checkpoint files survive
        srv1.release_plane()
    srv2 = _mk(
        srv_id="ck2", checkpoint_every_tokens=2, checkpoint_dir=ckpt,
    )
    try:
        assert srv2._pending, "restart did not adopt the checkpoint"
        # adopted straight into decode: nothing queued for prefill
        assert (srv2.cb.stats().get("kv_prefill_queue") or 0) == 0
        _pump_until(srv2, lambda: srv2._out, what="resumed generation")
        toks, meta = srv2.pop()
        assert meta["req"] == "c1" and meta["frame_id"] == "f-c1"
        assert "client_id" not in meta  # hop-local: never crosses hosts
        assert [int(t) for t in toks] == _alone(prompt, 10)
        # finished: its checkpoint file is reaped (no ghost on restart)
        assert not sorted((tmp_path / "spans").glob("req-*.span"))
        assert srv2.cb.stats().get("kv_migrations_in", 0) >= 1
    finally:
        srv2.release_plane()
