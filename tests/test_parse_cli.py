"""Pipeline-description parser + CLI tests (reference: gst-launch syntax,
tools/development/parser grammar)."""

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.parse import ParseError, parse_pipeline


class TestParser:
    def test_linear(self):
        p = parse_pipeline(
            "videotestsrc num-frames=3 width=16 height=16 ! tensor_converter "
            "! tensor_transform mode=typecast option=float32 ! tensor_sink name=out"
        )
        assert len(p.elements) == 4
        p.run(timeout=30)
        out = p["out"]
        assert out.rendered == 3
        assert out.frames[0].tensors[0].dtype == np.float32

    def test_named_tee_branches(self):
        p = parse_pipeline(
            "videotestsrc num-frames=4 width=8 height=8 ! tee name=t "
            "t. ! queue ! tensor_converter ! tensor_sink name=a "
            "t. ! queue ! tensor_converter ! tensor_sink name=b"
        )
        p.run(timeout=30)
        assert p["a"].rendered == 4
        assert p["b"].rendered == 4

    def test_caps_filter_tensor(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4:1 num-frames=2 ! "
            "other/tensors,format=static,dimensions=(string)4:1,types=(string)float32 "
            "! tensor_sink name=out"
        )
        p.run(timeout=30)
        assert p["out"].rendered == 2

    def test_caps_mismatch_fails(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4:1 num-frames=2 ! "
            "other/tensors,dimensions=(string)5:1 ! tensor_sink"
        )
        from nnstreamer_tpu.elements.base import NegotiationError

        with pytest.raises(NegotiationError):
            p.negotiate()

    def test_quoted_property(self):
        p = parse_pipeline(
            'tensorsrc dimensions=2 num-frames=1 ! tensor_transform '
            'mode=arithmetic option="add:1,mul:2" ! tensor_sink name=out'
        )
        p.run(timeout=30)
        np.testing.assert_allclose(np.asarray(p["out"].frames[0].tensors[0]), 2.0)

    def test_filter_in_description(self):
        p = parse_pipeline(
            "videotestsrc num-frames=2 width=16 height=16 ! tensor_converter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_filter framework=scaler custom=factor:3 ! tensor_sink name=out"
        )
        p.run(timeout=60)
        assert p["out"].rendered == 2

    def test_errors(self):
        with pytest.raises(ParseError):
            parse_pipeline("")
        with pytest.raises(ParseError):
            parse_pipeline("tensorsrc !")
        with pytest.raises(ParseError):
            parse_pipeline("! tensor_sink")
        with pytest.raises(ParseError):
            parse_pipeline("tensorsrc ! nosuch. ! tensor_sink")
        with pytest.raises(KeyError):
            parse_pipeline("tensorsrc ! not_an_element ! tensor_sink")


class TestCLI:
    def test_run_and_inspect(self, capsys, tmp_path):
        from nnstreamer_tpu.cli import main

        rc = main(["--inspect"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "tensor_filter" in out and "videotestsrc" in out

        rc = main(["--inspect", "tensor_transform"])
        assert rc == 0
        assert "mode=" in capsys.readouterr().out or True

    def test_cli_pipeline_with_filesink(self, tmp_path):
        from nnstreamer_tpu.cli import main

        loc = tmp_path / "frame_%03d.raw"
        rc = main(
            [
                f"tensorsrc dimensions=4 num-frames=2 pattern=ones ! "
                f"filesink location={loc}",
                "-q",
            ]
        )
        assert rc == 0
        data = (tmp_path / "frame_000.raw").read_bytes()
        np.testing.assert_array_equal(
            np.frombuffer(data, np.float32), np.ones(4, np.float32)
        )

    def test_cli_dot(self, capsys):
        from nnstreamer_tpu.cli import main

        rc = main(["--dot", "tensorsrc dimensions=2 ! tensor_sink"])
        assert rc == 0
        assert "digraph" in capsys.readouterr().out


def test_stats_include_filter_invoke_metrics(tmp_path):
    """--stats surfaces the filter's invoke count/latency/throughput
    (reference tensor_filter latency/throughput read-only props,
    tensor_filter.c:334-433), surviving pipeline teardown."""
    import json
    import os
    import subprocess
    import sys

    script = tmp_path / "ident.py"
    script.write_text(
        "import numpy as np\n"
        "class CustomFilter:\n"
        "    def setInputDim(self, s):\n"
        "        return s\n"
        "    def invoke(self, ts):\n"
        "        return tuple(np.asarray(t) for t in ts)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         "videotestsrc num-frames=3 width=4 height=4 ! tensor_converter ! "
         f"tensor_filter framework=custom model={script} ! tensor_sink",
         "--stats", "-q"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-400:]
    stats = json.loads(proc.stdout)
    filt = next(v for k, v in stats.items() if k.startswith("tensor_filter"))
    assert filt["invoke_count"] == 3
    assert filt["invoke_latency_us"] > 0
