"""Speculative decoding tests (models/speculative.py).

The invariant: greedy speculative output is byte-identical to
decode.generate on the target model alone, for any draft model — the
draft only changes how fast tokens are certified, never which tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.speculative import speculative_generate

N_HEADS = 4


@pytest.fixture(scope="module")
def target():
    return tfm.init_params(
        jax.random.PRNGKey(0), vocab=211, d_model=64, n_heads=N_HEADS,
        n_layers=3,
    )


@pytest.fixture(scope="module")
def draft():
    # smaller and differently seeded: realistic partial agreement
    return tfm.init_params(
        jax.random.PRNGKey(9), vocab=211, d_model=32, n_heads=2, n_layers=1,
    )


def _prompt(n, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(1, 211, (1, n)), jnp.int32
    )


def _alone(params, prompt, n_new):
    return np.asarray(dec.generate(params, prompt, N_HEADS, n_new))


@pytest.mark.parametrize("k", [2, 4])
def test_matches_target_alone(target, draft, k):
    prompt = _prompt(12, 1)
    toks, accept_lens = speculative_generate(
        target, draft, prompt, N_HEADS, 16, draft_n_heads=2, k=k
    )
    np.testing.assert_array_equal(
        np.asarray(toks), _alone(target, prompt, 16)
    )
    assert len(accept_lens) >= 1


def test_self_draft_accepts_everything(target):
    """Draft == target: every proposal matches, so each round certifies
    the full k-1 lookahead (the acceptance-path sanity check)."""
    prompt = _prompt(8, 2)
    toks, accept_lens = speculative_generate(
        target, target, prompt, N_HEADS, 12, k=4
    )
    np.testing.assert_array_equal(
        np.asarray(toks), _alone(target, prompt, 12)
    )
    # all but possibly the final (truncated) round accept fully
    assert all(a == 3 for a in accept_lens[:-1])


def test_single_token(target, draft):
    prompt = _prompt(5, 3)
    toks, _ = speculative_generate(
        target, draft, prompt, N_HEADS, 1, draft_n_heads=2, k=2
    )
    np.testing.assert_array_equal(np.asarray(toks), _alone(target, prompt, 1))


def test_validation(target, draft):
    with pytest.raises(ValueError, match="B=1"):
        speculative_generate(
            target, draft, jnp.zeros((2, 4), jnp.int32), N_HEADS, 4
        )
    with pytest.raises(ValueError, match="k must be"):
        speculative_generate(target, draft, _prompt(4), N_HEADS, 4, k=1)


class TestNgramSpeculation:
    def test_matches_target_alone(self, target):
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        prompt = _prompt(14, 7)
        toks, lens = ngram_speculative_generate(target, prompt, N_HEADS, 15)
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, prompt, 15)
        )
        assert lens  # at least one verify round ran

    def test_repetitive_prompt_accepts_lookups(self, target):
        """A strongly periodic context makes prompt-lookup proposals
        correct when the model itself continues the pattern; regardless,
        output equals the solo run."""
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        pattern = np.asarray([7, 11, 13, 7, 11, 13, 7, 11, 13, 7, 11],
                             np.int32)[None, :]
        toks, lens = ngram_speculative_generate(
            target, jnp.asarray(pattern), N_HEADS, 12
        )
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, jnp.asarray(pattern), 12)
        )
        # the proposal/acceptance path must actually fire: at least one
        # lookup must be accepted on this periodic context (seeded, so
        # deterministic)
        assert max(lens) > 0

    def test_single_token_and_validation(self, target):
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        prompt = _prompt(5, 8)
        toks, _ = ngram_speculative_generate(target, prompt, N_HEADS, 1)
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, prompt, 1)
        )
        with pytest.raises(ValueError, match="B=1"):
            ngram_speculative_generate(
                target, jnp.zeros((2, 4), jnp.int32), N_HEADS, 4
            )


class TestBatcherSpeculation:
    """spec_step(): prompt-lookup speculation batched over serving slots
    (serving.batched_verify_step) — exact greedy equivalence, multi-token
    acceptance on repetitive contexts, graceful fallbacks."""

    def _params(self):
        return tfm.init_params(
            jax.random.PRNGKey(3), vocab=97, d_model=64, n_heads=4,
            n_layers=2,
        )

    def _serve(self, cb, prompts, budget, spec=True, k=4):
        from nnstreamer_tpu.models.serving import ContinuousBatcher  # noqa

        rids = [cb.submit(p, budget) for p in prompts]
        while any(cb.result(r) is None for r in rids):
            if spec:
                cb.spec_step(k=k)
            else:
                cb.step()
        return [cb.result(r) for r in rids]

    def test_spec_matches_plain_steps(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        rng = np.random.default_rng(5)
        prompts = [
            rng.integers(1, 97, (n,)).astype(np.int32) for n in (6, 11, 4)
        ]
        plain = self._serve(
            ContinuousBatcher(params, 4, n_slots=4, max_len=96,
                              prompt_len=16),
            prompts, 12, spec=False,
        )
        spec = self._serve(
            ContinuousBatcher(params, 4, n_slots=4, max_len=96,
                              prompt_len=16),
            prompts, 12, spec=True,
        )
        assert spec == plain

    def test_spec_accepts_on_repetitive_context(self):
        """A looping context makes n-gram proposals land: the accepted
        counter must exceed zero and the output still match plain."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8],
                             np.int32)
        plain = self._serve(
            ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                              prompt_len=16),
            [pattern], 20, spec=False,
        )
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                               prompt_len=16)
        spec = self._serve(cb, [pattern], 20, spec=True)
        assert spec == plain
        st = cb.stats()
        assert st["spec_rounds"] > 0
        # the model is random-weight, so self-looping isn't guaranteed —
        # but proposals must at least have been scored; if any landed,
        # rounds < tokens
        if st["spec_accepted_tokens"] > 0:
            assert st["steps"] < st["tokens_emitted"]

    def test_spec_falls_back_for_sampling_and_windowed(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        rng = np.random.default_rng(6)
        p = rng.integers(1, 97, (6,)).astype(np.int32)
        # sampling slot → plain-step path, still completes + deterministic
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=64,
                               prompt_len=16)
        rid = cb.submit(p, 6, temperature=0.8, seed=1)
        while cb.result(rid) is None:
            cb.spec_step()
        assert cb.stats()["spec_rounds"] == 0
        # windowed ring → plain-step path
        cbw = ContinuousBatcher(params, 4, n_slots=1, max_len=32,
                                prompt_len=16, windowed=True)
        rid = cbw.submit(p, 8)
        while cbw.result(rid) is None:
            cbw.spec_step()
        assert cbw.stats()["spec_rounds"] == 0

    def test_spec_with_int8_cache_matches_plain_int8(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 97, (8,)).astype(np.int32)]
        plain = self._serve(
            ContinuousBatcher(params, 4, n_slots=2, max_len=64,
                              prompt_len=16, cache_dtype="int8"),
            prompts, 8, spec=False,
        )
        spec = self._serve(
            ContinuousBatcher(params, 4, n_slots=2, max_len=64,
                              prompt_len=16, cache_dtype="int8"),
            prompts, 8, spec=True,
        )
        assert spec == plain

    def test_spec_respects_stop_token_and_budget_edge(self):
        """A request whose budget ends mid-accepted-chunk truncates
        exactly at the budget (no overshoot into req.tokens)."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([5, 6, 5, 6, 5, 6, 5], np.int32)
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                               prompt_len=16)
        rid = cb.submit(pattern, 3)
        while cb.result(rid) is None:
            cb.spec_step(k=6)
        assert len(cb.result(rid)) == 3

    def test_spec_stop_token_mid_chunk(self):
        """A stop token landing INSIDE an accepted chunk truncates the
        request exactly at the stop token (no overshoot), identically to
        plain stepping with the same stop token."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([5, 6, 5, 6, 5, 6, 5], np.int32)
        plain_cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                                     prompt_len=16)
        # discover the greedy stream first, then pick token 2 as stop
        probe = plain_cb.submit(pattern, 8)
        while plain_cb.result(probe) is None:
            plain_cb.step()
        stream = plain_cb.result(probe)
        stop = stream[2]

        def run(spec):
            cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                                   prompt_len=16)
            rid = cb.submit(pattern, 8, stop_token=stop)
            while cb.result(rid) is None:
                cb.spec_step(k=6) if spec else cb.step()
            return cb.result(rid)

        a, b = run(False), run(True)
        assert a == b
        assert b[-1] == stop and stop not in b[:-1] or len(b) == 8

    def test_spec_pallas_batcher_falls_back(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=64,
                               prompt_len=16, attn_impl="pallas")
        rid = cb.submit(np.asarray([5, 6, 5, 6, 5], np.int32), 6)
        while cb.result(rid) is None:
            cb.spec_step()
        assert cb.stats()["spec_rounds"] == 0  # plain-path fallback
