"""Speculative decoding tests (models/speculative.py).

The invariant: greedy speculative output is byte-identical to
decode.generate on the target model alone, for any draft model — the
draft only changes how fast tokens are certified, never which tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.speculative import speculative_generate

N_HEADS = 4


@pytest.fixture(scope="module")
def target():
    return tfm.init_params(
        jax.random.PRNGKey(0), vocab=211, d_model=64, n_heads=N_HEADS,
        n_layers=3,
    )


@pytest.fixture(scope="module")
def draft():
    # smaller and differently seeded: realistic partial agreement
    return tfm.init_params(
        jax.random.PRNGKey(9), vocab=211, d_model=32, n_heads=2, n_layers=1,
    )


def _prompt(n, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(1, 211, (1, n)), jnp.int32
    )


def _alone(params, prompt, n_new):
    return np.asarray(dec.generate(params, prompt, N_HEADS, n_new))


@pytest.mark.parametrize("k", [2, 4])
def test_matches_target_alone(target, draft, k):
    prompt = _prompt(12, 1)
    toks, accept_lens = speculative_generate(
        target, draft, prompt, N_HEADS, 16, draft_n_heads=2, k=k
    )
    np.testing.assert_array_equal(
        np.asarray(toks), _alone(target, prompt, 16)
    )
    assert len(accept_lens) >= 1


def test_self_draft_accepts_everything(target):
    """Draft == target: every proposal matches, so each round certifies
    the full k-1 lookahead (the acceptance-path sanity check)."""
    prompt = _prompt(8, 2)
    toks, accept_lens = speculative_generate(
        target, target, prompt, N_HEADS, 12, k=4
    )
    np.testing.assert_array_equal(
        np.asarray(toks), _alone(target, prompt, 12)
    )
    # all but possibly the final (truncated) round accept fully
    assert all(a == 3 for a in accept_lens[:-1])


def test_single_token(target, draft):
    prompt = _prompt(5, 3)
    toks, _ = speculative_generate(
        target, draft, prompt, N_HEADS, 1, draft_n_heads=2, k=2
    )
    np.testing.assert_array_equal(np.asarray(toks), _alone(target, prompt, 1))


def test_validation(target, draft):
    with pytest.raises(ValueError, match="B=1"):
        speculative_generate(
            target, draft, jnp.zeros((2, 4), jnp.int32), N_HEADS, 4
        )
    with pytest.raises(ValueError, match="k must be"):
        speculative_generate(target, draft, _prompt(4), N_HEADS, 4, k=1)


class TestNgramSpeculation:
    def test_matches_target_alone(self, target):
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        prompt = _prompt(14, 7)
        toks, lens = ngram_speculative_generate(target, prompt, N_HEADS, 15)
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, prompt, 15)
        )
        assert lens  # at least one verify round ran

    def test_repetitive_prompt_accepts_lookups(self, target):
        """A strongly periodic context makes prompt-lookup proposals
        correct when the model itself continues the pattern; regardless,
        output equals the solo run."""
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        pattern = np.asarray([7, 11, 13, 7, 11, 13, 7, 11, 13, 7, 11],
                             np.int32)[None, :]
        toks, lens = ngram_speculative_generate(
            target, jnp.asarray(pattern), N_HEADS, 12
        )
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, jnp.asarray(pattern), 12)
        )
        # the proposal/acceptance path must actually fire: at least one
        # lookup must be accepted on this periodic context (seeded, so
        # deterministic)
        assert max(lens) > 0

    def test_single_token_and_validation(self, target):
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        prompt = _prompt(5, 8)
        toks, _ = ngram_speculative_generate(target, prompt, N_HEADS, 1)
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, prompt, 1)
        )
        with pytest.raises(ValueError, match="B=1"):
            ngram_speculative_generate(
                target, jnp.zeros((2, 4), jnp.int32), N_HEADS, 4
            )
