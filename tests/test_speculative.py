"""Speculative decoding tests (models/speculative.py).

The invariant: greedy speculative output is byte-identical to
decode.generate on the target model alone, for any draft model — the
draft only changes how fast tokens are certified, never which tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.speculative import speculative_generate

N_HEADS = 4


@pytest.fixture(scope="module")
def target():
    return tfm.init_params(
        jax.random.PRNGKey(0), vocab=211, d_model=64, n_heads=N_HEADS,
        n_layers=3,
    )


@pytest.fixture(scope="module")
def draft():
    # smaller and differently seeded: realistic partial agreement
    return tfm.init_params(
        jax.random.PRNGKey(9), vocab=211, d_model=32, n_heads=2, n_layers=1,
    )


def _prompt(n, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(1, 211, (1, n)), jnp.int32
    )


def _alone(params, prompt, n_new):
    return np.asarray(dec.generate(params, prompt, N_HEADS, n_new))


@pytest.mark.parametrize("k", [2, 4])
def test_matches_target_alone(target, draft, k):
    prompt = _prompt(12, 1)
    toks, accept_lens = speculative_generate(
        target, draft, prompt, N_HEADS, 16, draft_n_heads=2, k=k
    )
    np.testing.assert_array_equal(
        np.asarray(toks), _alone(target, prompt, 16)
    )
    assert len(accept_lens) >= 1


def test_self_draft_accepts_everything(target):
    """Draft == target: every proposal matches, so each round certifies
    the full k-1 lookahead (the acceptance-path sanity check)."""
    prompt = _prompt(8, 2)
    toks, accept_lens = speculative_generate(
        target, target, prompt, N_HEADS, 12, k=4
    )
    np.testing.assert_array_equal(
        np.asarray(toks), _alone(target, prompt, 12)
    )
    # all but possibly the final (truncated) round accept fully
    assert all(a == 3 for a in accept_lens[:-1])


def test_single_token(target, draft):
    prompt = _prompt(5, 3)
    toks, _ = speculative_generate(
        target, draft, prompt, N_HEADS, 1, draft_n_heads=2, k=2
    )
    np.testing.assert_array_equal(np.asarray(toks), _alone(target, prompt, 1))


def test_validation(target, draft):
    with pytest.raises(ValueError, match="B=1"):
        speculative_generate(
            target, draft, jnp.zeros((2, 4), jnp.int32), N_HEADS, 4
        )
    with pytest.raises(ValueError, match="k must be"):
        speculative_generate(target, draft, _prompt(4), N_HEADS, 4, k=1)


class TestNgramSpeculation:
    def test_matches_target_alone(self, target):
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        prompt = _prompt(14, 7)
        toks, lens = ngram_speculative_generate(target, prompt, N_HEADS, 15)
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, prompt, 15)
        )
        assert lens  # at least one verify round ran

    def test_repetitive_prompt_accepts_lookups(self, target):
        """A strongly periodic context makes prompt-lookup proposals
        correct when the model itself continues the pattern; regardless,
        output equals the solo run."""
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        pattern = np.asarray([7, 11, 13, 7, 11, 13, 7, 11, 13, 7, 11],
                             np.int32)[None, :]
        toks, lens = ngram_speculative_generate(
            target, jnp.asarray(pattern), N_HEADS, 12
        )
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, jnp.asarray(pattern), 12)
        )
        # the proposal/acceptance path must actually fire: at least one
        # lookup must be accepted on this periodic context (seeded, so
        # deterministic)
        assert max(lens) > 0

    def test_single_token_and_validation(self, target):
        from nnstreamer_tpu.models.speculative import (
            ngram_speculative_generate,
        )

        prompt = _prompt(5, 8)
        toks, _ = ngram_speculative_generate(target, prompt, N_HEADS, 1)
        np.testing.assert_array_equal(
            np.asarray(toks), _alone(target, prompt, 1)
        )
        with pytest.raises(ValueError, match="B=1"):
            ngram_speculative_generate(
                target, jnp.zeros((2, 4), jnp.int32), N_HEADS, 4
            )


class TestBatcherSpeculation:
    """spec_step(): prompt-lookup speculation batched over serving slots
    (serving.batched_verify_step) — exact greedy equivalence, multi-token
    acceptance on repetitive contexts, graceful fallbacks."""

    def _params(self):
        return tfm.init_params(
            jax.random.PRNGKey(3), vocab=97, d_model=64, n_heads=4,
            n_layers=2,
        )

    def _serve(self, cb, prompts, budget, spec=True, k=4):
        from nnstreamer_tpu.models.serving import ContinuousBatcher  # noqa

        rids = [cb.submit(p, budget) for p in prompts]
        while any(cb.result(r) is None for r in rids):
            if spec:
                cb.spec_step(k=k)
            else:
                cb.step()
        return [cb.result(r) for r in rids]

    def test_spec_matches_plain_steps(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        rng = np.random.default_rng(5)
        prompts = [
            rng.integers(1, 97, (n,)).astype(np.int32) for n in (6, 11, 4)
        ]
        plain = self._serve(
            ContinuousBatcher(params, 4, n_slots=4, max_len=96,
                              prompt_len=16),
            prompts, 12, spec=False,
        )
        spec = self._serve(
            ContinuousBatcher(params, 4, n_slots=4, max_len=96,
                              prompt_len=16),
            prompts, 12, spec=True,
        )
        assert spec == plain

    def test_spec_accepts_on_repetitive_context(self):
        """A looping context makes n-gram proposals land: the accepted
        counter must exceed zero and the output still match plain."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8],
                             np.int32)
        plain = self._serve(
            ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                              prompt_len=16),
            [pattern], 20, spec=False,
        )
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                               prompt_len=16)
        spec = self._serve(cb, [pattern], 20, spec=True)
        assert spec == plain
        st = cb.stats()
        assert st["spec_rounds"] > 0
        # the model is random-weight, so self-looping isn't guaranteed —
        # but proposals must at least have been scored; if any landed,
        # rounds < tokens
        if st["spec_accepted_tokens"] > 0:
            assert st["steps"] < st["tokens_emitted"]

    def test_spec_sampling_slots_speculate(self):
        """Sampling slots now speculate (r4): rejection-sampling
        acceptance — runs are deterministic per seed, and a
        near-zero temperature (≈ delta distribution) reproduces the
        greedy stream exactly through the acceptance path."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8], np.int32)

        def run(temp, seed):
            cb = ContinuousBatcher(params, 4, n_slots=2, max_len=96,
                                   prompt_len=16)
            rid = cb.submit(pattern, 10, temperature=temp, seed=seed)
            # a greedy repetitive neighbor guarantees lookups land, so
            # rounds go through the verify path WITH a sampling slot
            # active — the exact case that used to force a whole-batch
            # plain-step fallback
            rg = cb.submit(pattern, 10)
            while cb.result(rid) is None or cb.result(rg) is None:
                cb.spec_step(k=4, ngram=1)
            return cb.result(rid), cb.stats()

        a, st = run(0.8, 11)
        b, _ = run(0.8, 11)
        assert a == b  # deterministic per (seed, fill, draw)
        assert st["spec_rounds"] > 0  # no more sampling fallback
        # temp → 0: the filtered distribution is a point mass at the
        # argmax, so rejection acceptance degenerates to greedy — the
        # stream must equal plain greedy decoding exactly
        tiny, _ = run(1e-6, 12)
        greedy = self._serve(
            ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                              prompt_len=16),
            [pattern], 10, spec=False,
        )[0]
        assert tiny == greedy

    def test_spec_windowed_matches_sliding_reference(self):
        """Windowed rings now speculate (r4): verify runs against the
        pre-write ring + fresh chunk K/V and only accepted columns
        commit, so the stream matches the exact sliding-window
        reference through many ring wraps."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        W = 16
        pattern = np.asarray([7, 8, 9, 7, 8, 9, 7, 8, 9, 7], np.int32)
        cbw = ContinuousBatcher(params, 4, n_slots=1, max_len=W,
                                prompt_len=16, windowed=True)
        rid = cbw.submit(pattern, 30)  # wraps the ring repeatedly
        while cbw.result(rid) is None:
            cbw.spec_step(k=4)
        assert cbw.stats()["spec_rounds"] > 0
        from tests.test_serving import _sliding_reference

        assert cbw.result(rid) == _sliding_reference(
            params, pattern, 30, W
        )

    def test_spec_mixed_batch_greedy_slot_unaffected(self):
        """A greedy slot sharing spec rounds with a sampling slot emits
        exactly its solo-greedy stream (per-slot acceptance isolation)."""
        from nnstreamer_tpu.models import decode as dec
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        g_prompt = np.asarray([7, 8, 9, 7, 8, 9, 7], np.int32)
        s_prompt = np.asarray([3, 4, 3, 4, 3], np.int32)
        cb = ContinuousBatcher(params, 4, n_slots=2, max_len=96,
                               prompt_len=16)
        rg = cb.submit(g_prompt, 10)
        rs = cb.submit(s_prompt, 10, temperature=0.9, seed=5)
        while cb.result(rg) is None or cb.result(rs) is None:
            cb.spec_step(k=4)
        alone = dec.generate(
            params, np.asarray(g_prompt)[None], 4, 10
        )
        assert cb.result(rg) == [int(t) for t in np.asarray(alone)[0]]

    def test_spec_with_int8_cache_matches_plain_int8(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 97, (8,)).astype(np.int32)]
        plain = self._serve(
            ContinuousBatcher(params, 4, n_slots=2, max_len=64,
                              prompt_len=16, cache_dtype="int8"),
            prompts, 8, spec=False,
        )
        spec = self._serve(
            ContinuousBatcher(params, 4, n_slots=2, max_len=64,
                              prompt_len=16, cache_dtype="int8"),
            prompts, 8, spec=True,
        )
        assert spec == plain

    def test_spec_respects_stop_token_and_budget_edge(self):
        """A request whose budget ends mid-accepted-chunk truncates
        exactly at the budget (no overshoot into req.tokens)."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([5, 6, 5, 6, 5, 6, 5], np.int32)
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                               prompt_len=16)
        rid = cb.submit(pattern, 3)
        while cb.result(rid) is None:
            cb.spec_step(k=6)
        assert len(cb.result(rid)) == 3

    def test_spec_stop_token_mid_chunk(self):
        """A stop token landing INSIDE an accepted chunk truncates the
        request exactly at the stop token (no overshoot), identically to
        plain stepping with the same stop token."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([5, 6, 5, 6, 5, 6, 5], np.int32)
        plain_cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                                     prompt_len=16)
        # discover the greedy stream first, then pick token 2 as stop
        probe = plain_cb.submit(pattern, 8)
        while plain_cb.result(probe) is None:
            plain_cb.step()
        stream = plain_cb.result(probe)
        stop = stream[2]

        def run(spec):
            cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                                   prompt_len=16)
            rid = cb.submit(pattern, 8, stop_token=stop)
            while cb.result(rid) is None:
                cb.spec_step(k=6) if spec else cb.step()
            return cb.result(rid)

        a, b = run(False), run(True)
        assert a == b
        assert b[-1] == stop and stop not in b[:-1] or len(b) == 8

    def test_spec_pallas_batcher_speculates(self):
        """Pallas batchers now speculate (r4): a server pumped
        exclusively by spec_step certifies every token with the same
        XLA verify forward, so the stream is impl-independent."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([5, 6, 5, 6, 5], np.int32)
        outs = {}
        for impl in ("xla", "pallas"):
            cb = ContinuousBatcher(params, 4, n_slots=1, max_len=64,
                                   prompt_len=16, attn_impl=impl)
            rid = cb.submit(pattern, 6)
            while cb.result(rid) is None:
                cb.spec_step(ngram=1)
            outs[impl] = cb.result(rid)
            assert cb.stats()["spec_rounds"] > 0
        assert outs["xla"] == outs["pallas"]

    def test_spec_windowed_int8_matches_plain(self):
        """windowed × int8 × speculation: the verify forward attends the
        quantize→dequantize roundtrip of its own chunk K/V (what a plain
        int8 step attends), so greedy spec stays byte-identical to plain
        int8 ring stepping."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.asarray([7, 8, 9, 7, 8, 9, 7, 8], np.int32)

        def run(spec):
            cb = ContinuousBatcher(params, 4, n_slots=1, max_len=32,
                                   prompt_len=16, windowed=True,
                                   cache_dtype="int8")
            rid = cb.submit(pattern, 20)
            while cb.result(rid) is None:
                cb.spec_step(k=4) if spec else cb.step()
            return cb.result(rid)

        assert run(True) == run(False)

    def test_spec_accepted_in_pallas_windowed_server(self):
        """The production-shaped configuration (Pallas fast kernel +
        sliding-window ring) pumped by speculate=k actually ACCEPTS
        speculated tokens on a repetitive stream (VERDICT r3 done
        criterion: spec_accepted_tokens > 0, not a silent fallback)."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.tile(np.asarray([11, 12, 13], np.int32), 5)
        cb = ContinuousBatcher(params, 4, n_slots=2, max_len=32,
                               prompt_len=16, windowed=True,
                               attn_impl="pallas")
        rid = cb.submit(pattern, 24)
        while cb.result(rid) is None:
            cb.spec_step(k=4)
        st = cb.stats()
        assert st["spec_rounds"] > 0
        assert st["spec_accepted_tokens"] > 0
        assert st["tokens_emitted"] > st["steps"]  # multi-token rounds

    def test_acceptance_rate_floors(self):
        """Repeatable workloads with DOCUMENTED acceptance floors
        (VERDICT r4 #5): a silent proposer regression (the r3
        zero-sentinel class) degrades acceptance while every
        equivalence test still passes — these floors catch it.
        - draft == target proposes the target's own greedy tokens:
          acceptance is ~1.0 by construction; floor 0.9.
        - prompt-lookup on a cyclic prompt (fixed seed): measured 0.33
          on this workload; floor 0.2."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pattern = np.tile(np.asarray([11, 12, 13], np.int32), 5)

        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=64,
                               prompt_len=16, draft_params=params,
                               draft_n_heads=N_HEADS)
        rid = cb.submit(pattern, 32)
        while cb.result(rid) is None:
            cb.spec_step(k=4)
        st = cb.stats()
        assert st["spec_acceptance_rate"] >= 0.9, st
        assert st["tokens_per_step"] > 2.0  # multi-token rounds dominate

        cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                               prompt_len=16)
        rid = cb.submit(pattern, 32)
        while cb.result(rid) is None:
            cb.spec_step(k=4)
        st = cb.stats()
        assert st["spec_columns"] > 0
        assert st["spec_acceptance_rate"] >= 0.2, st

    def test_pallas_no_proposal_stays_on_verify_program(self, monkeypatch):
        """When ngram lookup proposes NOTHING, a Pallas batcher must not
        fall back to the kernel-certified plain step (mixing accumulation
        orders within one spec-pumped generation — r4 advisor): it runs a
        width-2 all-sentinel verify instead, so spec_rounds advances while
        spec_columns stays 0 (sentinels are not proposals). An XLA
        batcher keeps the cheaper plain-step fallback (same math there),
        and both end on the same tokens."""
        from nnstreamer_tpu.models import serving

        monkeypatch.setattr(serving, "ngram_lookup", lambda *a, **k: None)
        params = self._params()
        prompt = np.arange(1, 9, dtype=np.int32)
        outs = {}
        for impl in ("xla", "pallas"):
            cb = serving.ContinuousBatcher(
                params, N_HEADS, n_slots=1, max_len=32, prompt_len=16,
                attn_impl=impl,
            )
            rid = cb.submit(prompt, 10)
            while cb.result(rid) is None:
                cb.spec_step(k=4)
            outs[impl] = cb.result(rid)
            st = cb.stats()
            if impl == "pallas":
                assert st["spec_rounds"] > 0
                assert st["spec_columns"] == 0
                assert st["spec_accepted_tokens"] == 0
            else:
                assert st["spec_rounds"] == 0
        assert outs["xla"] == outs["pallas"]

    def test_rejection_sampler_matches_target_distribution(self):
        """Unit-level distribution check of spec_accept's point-mass
        rejection sampling: over many independent slots (same logits,
        different keys), the FIRST emitted token's empirical
        distribution must match the filtered target distribution —
        whether the proposal is likely, unlikely, or absent."""
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models.serving import (
            _filtered_logits, spec_accept,
        )

        rng = np.random.default_rng(0)
        n, v, k = 4000, 8, 3
        base_logits = jnp.asarray(rng.standard_normal((v,)), jnp.float32)
        logits = jnp.broadcast_to(base_logits, (n, k, v))
        temp = jnp.ones((n,), jnp.float32)
        topk = jnp.zeros((n,), jnp.int32)
        topp = jnp.ones((n,), jnp.float32)
        keys = jax.vmap(jax.random.PRNGKey)(jnp.arange(n, dtype=jnp.uint32))
        pos = jnp.zeros((n,), jnp.int32)
        target = np.asarray(
            jax.nn.softmax(
                _filtered_logits(base_logits[None], temp[:1], topk[:1],
                                 topp[:1])[0]
            )
        )
        for prop in (int(np.argmax(target)), int(np.argmin(target)), -1):
            toks = jnp.broadcast_to(
                jnp.asarray([1, prop, 2], jnp.int32), (n, k)
            )
            m, final = spec_accept(
                logits, toks, temp, topk, topp, keys, pos, True
            )
            m, final = np.asarray(m), np.asarray(final)
            first = np.where(m >= 2, prop, final)
            emp = np.bincount(first, minlength=v) / n
            np.testing.assert_allclose(emp, target, atol=0.035)


class TestDraftBatcherSpeculation:
    """Draft-model speculation over slots (r4): one small model proposes
    k-1 tokens for every active slot per round (batched draft forwards),
    verified by the shared target verify + point-mass acceptance."""

    def _params(self, seed=3, layers=2):
        return tfm.init_params(
            jax.random.PRNGKey(seed), vocab=97, d_model=64, n_heads=4,
            n_layers=layers,
        )

    def _draft_params(self):
        # smaller net, same vocab — the real deployment shape
        return tfm.init_params(
            jax.random.PRNGKey(9), vocab=97, d_model=32, n_heads=2,
            n_layers=1,
        )

    def test_draft_spec_matches_plain_steps(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        rng = np.random.default_rng(21)
        prompts = [
            rng.integers(1, 97, (n,)).astype(np.int32) for n in (5, 20, 3)
        ]

        def run(draft):
            kw = {}
            if draft:
                kw = dict(draft_params=self._draft_params(),
                          draft_n_heads=2)
            cb = ContinuousBatcher(params, 4, n_slots=4, max_len=96,
                                   prompt_len=16, **kw)
            rids = [cb.submit(p, 10) for p in prompts]
            while any(cb.result(r) is None for r in rids):
                cb.spec_step(k=4) if draft else cb.step()
            return [cb.result(r) for r in rids], cb.stats()

        plain, _ = run(False)
        spec, st = run(True)
        assert spec == plain
        assert st["spec_rounds"] > 0  # a draft always proposes

    def test_self_draft_accepts_everything(self):
        """Draft == target: every proposal is the target's own greedy
        choice, so every round commits all k columns (the sanity bound
        on the acceptance plumbing)."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        p = np.random.default_rng(22).integers(1, 97, (6,)).astype(np.int32)
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                               prompt_len=16, draft_params=params,
                               draft_n_heads=4)
        rid = cb.submit(p, 12)
        while cb.result(rid) is None:
            cb.spec_step(k=4)
        st = cb.stats()
        assert cb.result(rid) == _alone_97(params, p, 12)
        # 12 tokens in 3 rounds of k=4 (1 at submit + 11 over rounds,
        # each committing 4): acceptance must be perfect
        assert st["spec_accepted_tokens"] == st["spec_rounds"] * 3

    def test_draft_spec_with_sampling_slot(self):
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        p = np.random.default_rng(23).integers(1, 97, (5,)).astype(np.int32)

        def run():
            cb = ContinuousBatcher(params, 4, n_slots=2, max_len=64,
                                   prompt_len=16,
                                   draft_params=self._draft_params(),
                                   draft_n_heads=2)
            rs = cb.submit(p, 8, temperature=0.7, seed=4)
            rg = cb.submit(p, 8)
            while cb.result(rs) is None or cb.result(rg) is None:
                cb.spec_step(k=3)
            return cb.result(rs), cb.result(rg)

        s1, g1 = run()
        s2, g2 = run()
        assert (s1, g1) == (s2, g2)  # deterministic per seed
        assert g1 == _alone_97(params, p, 8)  # greedy slot exact

    def test_draft_windowed_matches_plain_ring(self):
        """Draft speculation on a windowed ring (r4): the draft proposes
        against its pre-write ring and commits only accepted columns —
        the stream stays byte-identical to plain ring stepping through
        many wraps."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        p = np.random.default_rng(31).integers(1, 97, (6,)).astype(np.int32)

        def run(draft):
            kw = (
                dict(draft_params=self._draft_params(), draft_n_heads=2)
                if draft else {}
            )
            cb = ContinuousBatcher(params, 4, n_slots=2, max_len=16,
                                   prompt_len=16, windowed=True, **kw)
            rid = cb.submit(p, 30)  # wraps the W=16 ring repeatedly
            while cb.result(rid) is None:
                cb.spec_step(k=4) if draft else cb.step()
            return cb.result(rid), cb.stats()

        plain, _ = run(False)
        spec, st = run(True)
        assert spec == plain
        assert st["spec_rounds"] > 0

    def test_self_draft_windowed_accepts_everything(self):
        """Draft == target on a ring: perfect acceptance proves the
        draft ring stays position-synced through wrapped commits."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        p = np.random.default_rng(32).integers(1, 97, (4,)).astype(np.int32)
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=16,
                               prompt_len=16, windowed=True,
                               draft_params=params, draft_n_heads=4)
        rid = cb.submit(p, 24)
        while cb.result(rid) is None:
            cb.spec_step(k=4)
        st = cb.stats()
        assert st["spec_accepted_tokens"] == st["spec_rounds"] * 3
        from tests.test_serving import _sliding_reference

        assert cb.result(rid) == _sliding_reference(params, p, 24, 16)

    def test_draft_spec_with_prefix(self):
        """Draft admission prefills the FULL context (prefix + prompt),
        so prefixed requests speculate correctly too."""
        from nnstreamer_tpu.models.serving import ContinuousBatcher

        params = self._params()
        pfx = np.random.default_rng(24).integers(1, 97, (10,)).astype(np.int32)
        tail = np.random.default_rng(25).integers(1, 97, (4,)).astype(np.int32)
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=96,
                               prompt_len=16, draft_params=params,
                               draft_n_heads=4)
        pid = cb.register_prefix(pfx)
        rid = cb.submit(tail, 8, prefix=pid)
        while cb.result(rid) is None:
            cb.spec_step(k=4)
        assert cb.result(rid) == _alone_97(
            params, np.concatenate([pfx, tail]), 8
        )
        # self-draft over the full context: perfect acceptance proves
        # the draft cache saw the prefix
        st = cb.stats()
        assert st["spec_accepted_tokens"] == st["spec_rounds"] * 3


def _alone_97(params, prompt, n_new):
    toks = dec.generate(params, np.asarray(prompt)[None], 4, n_new)
    return [int(t) for t in np.asarray(toks)[0]]


def test_spec_windowed_gqa_matches_plain():
    """Grouped-query attention composes with windowed speculation: the
    ring verify's concat attention is GQA-aware (KV < H heads)."""
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    params = tfm.init_params(
        jax.random.PRNGKey(13), vocab=97, d_model=64, n_heads=4,
        n_layers=2, n_kv_heads=2,
    )
    pattern = np.tile(np.asarray([5, 9, 11], np.int32), 4)

    def run(spec):
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=32,
                               prompt_len=16, windowed=True)
        rid = cb.submit(pattern, 24)
        while cb.result(rid) is None:
            cb.spec_step(k=4, ngram=1) if spec else cb.step()
        return cb.result(rid)

    assert run(True) == run(False)


def test_spec_windowed_int8_prefix_composes():
    """The deepest composition: int8 ring cache × registered prefix ×
    speculation — byte-identical to plain int8 ring stepping of the
    same prefixed request."""
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    params = tfm.init_params(
        jax.random.PRNGKey(14), vocab=97, d_model=64, n_heads=4,
        n_layers=2,
    )
    pfx = np.tile(np.asarray([3, 4, 5, 6], np.int32), 4)  # 16 = bucket
    tail = np.asarray([3, 4, 5], np.int32)

    def run(spec):
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=32,
                               prompt_len=16, windowed=True,
                               cache_dtype="int8")
        pid = cb.register_prefix(pfx)
        rid = cb.submit(tail, 20, prefix=pid)
        while cb.result(rid) is None:
            cb.spec_step(k=4, ngram=1) if spec else cb.step()
        return cb.result(rid)

    assert run(True) == run(False)


def test_draft_windowed_int8_composes():
    """draft proposer × windowed ring × int8 target cache: byte-equal
    to plain int8 ring stepping (the draft's own ring stays float; only
    the target cache is quantized)."""
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    params = tfm.init_params(
        jax.random.PRNGKey(33), vocab=97, d_model=64, n_heads=4, n_layers=2
    )
    draft = tfm.init_params(
        jax.random.PRNGKey(34), vocab=97, d_model=32, n_heads=2, n_layers=1
    )
    p = np.random.default_rng(35).integers(1, 97, (5,)).astype(np.int32)

    def run(spec):
        kw = dict(draft_params=draft, draft_n_heads=2) if spec else {}
        cb = ContinuousBatcher(params, 4, n_slots=1, max_len=16,
                               prompt_len=16, windowed=True,
                               cache_dtype="int8", **kw)
        rid = cb.submit(p, 20)
        while cb.result(rid) is None:
            cb.spec_step(k=3) if spec else cb.step()
        return cb.result(rid)

    assert run(True) == run(False)


class TestScannedNgramGenerate:
    """speculative.ngram_generate_scanned: the whole propose→verify→
    accept loop as ONE compiled program (device while_loop + on-device
    mining) — byte-identical to decode.generate and to the host-looped
    reference, with only the finished token tensor crossing to host."""

    def _params(self):
        return tfm.init_params(
            jax.random.PRNGKey(3), vocab=211, d_model=32, n_heads=2,
            n_layers=2,
        )

    def test_matches_greedy_and_host_loop(self):
        from nnstreamer_tpu.models.speculative import (
            ngram_generate_scanned, ngram_speculative_generate,
        )

        params = self._params()
        rng = np.random.default_rng(0)
        for seed, rep in ((1, True), (2, False)):
            base = rng.integers(1, 211, (5,))
            prompt = (
                np.tile(base, 4) if rep
                else rng.integers(1, 211, (14,))
            )[None, :].astype(np.int32)
            ref = dec.generate(params, jnp.asarray(prompt), 2, 12)
            host, _ = ngram_speculative_generate(params, prompt, 2, 12)
            scan, _ = ngram_generate_scanned(params, prompt, 2, 12)
            np.testing.assert_array_equal(np.asarray(ref),
                                          np.asarray(scan))
            np.testing.assert_array_equal(np.asarray(host),
                                          np.asarray(scan))

    def test_repetitive_prompt_accepts(self):
        from nnstreamer_tpu.models.speculative import (
            ngram_generate_scanned,
        )

        params = self._params()
        base = np.random.default_rng(5).integers(1, 211, (4,))
        prompt = np.tile(base, 6)[None, :].astype(np.int32)
        _, acc = ngram_generate_scanned(params, prompt, 2, 16, k=4, g=1)
        assert int(acc) > 0  # mining works inside the program

    def test_zoo_decode_ngram_wired_to_scanned(self):
        from nnstreamer_tpu.models import zoo
        from nnstreamer_tpu.models.speculative import (
            ngram_generate_scanned,
        )

        m = zoo.get(
            "transformer_lm", vocab="211", d_model="32", n_heads="2",
            n_layers="2", seqlen="20", generate="8", decode="ngram",
        )
        prompt = np.random.default_rng(1).integers(
            1, 211, (1, 20)
        ).astype(np.int32)
        # zoo params = seed 0 with the same dims: exact token equality
        # pins the wiring (any other strategy would still match shape)
        zoo_params = tfm.init_params(
            jax.random.PRNGKey(0), vocab=211, d_model=32, n_heads=2,
            n_layers=2,
        )
        want, _ = ngram_generate_scanned(zoo_params, prompt, 2, 8)
        out = np.asarray(jax.jit(m.fn)(jnp.asarray(prompt)))
        np.testing.assert_array_equal(out, np.asarray(want))
