"""nns-lint static analyzer: the bad-pipeline table (every diagnostic
code), multi-error collection, the never-executes guarantee, exit codes,
and the docs/examples lint-clean sweep."""

import ast
import os
import re

import pytest

from nnstreamer_tpu.analysis import Severity, lint
from nnstreamer_tpu.pipeline.parse import ParseError, parse_pipeline, scan_description

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLEAN = (
    "tensorsrc dimensions=4 num-frames=2 ! "
    "tensor_transform mode=typecast option=float32 ! tensor_sink"
)

# (description, expected diagnostic codes — subset of what's reported)
BAD_PIPELINES = [
    ("tensorsrc ! frobnicator ! tensor_sink", {"NNS-E004"}),
    (
        "tensorsrc dimensions=4 ! "
        "other/tensors,dimensions=(string)8,types=(string)float32 ! "
        "tensor_sink",
        {"NNS-E003"},
    ),
    (
        "tensor_transform mode=typecast option=float32 ! tensor_sink",
        {"NNS-E001"},
    ),
    (
        # b feeds back into a: cycle
        "tensor_transform name=a mode=typecast option=float32 ! "
        "tensor_transform name=b mode=typecast option=float32 ! a.",
        {"NNS-E002"},
    ),
    ("tensorsrc frobnicate=1 ! tensor_sink", {"NNS-W101"}),
    (
        "videotestsrc width=banana ! tensor_converter ! tensor_sink",
        {"NNS-E005"},
    ),
    (
        "tensorsrc ! tensor_filter framework=jax model=/no/such/model.pt ! "
        "tensor_sink",
        {"NNS-W102"},
    ),
    (
        "tensorsrc ! tensor_filter framework=nosuchfw model=/no/x.foo ! "
        "tensor_sink",
        {"NNS-E006"},
    ),
    ("tensorsrc ! tensor_decoder mode=nosuchmode ! tensor_sink", {"NNS-E007"}),
    (
        "videotestsrc ! tensor_converter mode=nosuchsub ! tensor_sink",
        {"NNS-E008"},
    ),
    ("tensorsrc !", {"NNS-E009"}),
    (
        # two tee branches into a mux with no queues: deadlock topology
        "videotestsrc num-frames=2 ! tee name=t "
        "t. ! tensor_converter ! mux.sink_0 "
        "t. ! tensor_converter ! mux.sink_1 "
        "tensor_mux name=mux ! tensor_sink",
        {"NNS-W103"},
    ),
    (
        # second chain is an island: unreachable + unlinked input
        "tensorsrc dimensions=4 ! tensor_sink "
        "tensor_transform name=x mode=typecast option=float32 ! "
        "tensor_sink name=s2",
        {"NNS-W104", "NNS-E001"},
    ),
    (
        # a source whose output goes nowhere
        "tensorsrc name=a dimensions=4 "
        "tensorsrc name=b dimensions=4 ! tensor_sink",
        {"NNS-W105"},
    ),
    (
        # on-error=route with no dead-letter consumer: silent drop
        "tensorsrc dimensions=4 ! "
        "tensor_transform mode=typecast option=float32 on-error=route ! "
        "tensor_sink",
        {"NNS-W107"},
    ),
]


class TestBadPipelineTable:
    @pytest.mark.parametrize(
        "description,expected",
        BAD_PIPELINES,
        ids=[", ".join(sorted(e)) for _, e in BAD_PIPELINES],
    )
    def test_expected_codes_reported(self, description, expected):
        result = lint(description)
        assert expected <= set(result.codes), (
            f"wanted {sorted(expected)} in {result.codes}:\n{result.render()}"
        )
        assert result.exit_code != 0

    def test_at_least_eight_distinct_codes_covered(self):
        seen = set()
        for _, expected in BAD_PIPELINES:
            seen |= expected
        assert len(seen) >= 8, sorted(seen)

    def test_clean_pipeline_is_clean(self):
        result = lint(CLEAN)
        assert result.codes == []
        assert result.exit_code == 0

    def test_routed_error_pad_is_clean(self):
        # a LINKED error pad raises no W107 and no W105 for the extra pad
        result = lint(
            "tensorsrc dimensions=4 ! "
            "tensor_transform name=t mode=typecast option=float32 "
            "on-error=route ! tensor_sink "
            "t.src_1 ! tensor_sink name=dlq"
        )
        assert result.codes == [], result.render()

    def test_unrouted_error_pad_reports_w107_not_w105(self):
        result = lint(
            "tensorsrc dimensions=4 ! "
            "tensor_transform mode=typecast option=float32 "
            "on-error=route ! tensor_sink"
        )
        assert "NNS-W107" in result.codes
        assert "NNS-W105" not in result.codes, result.render()

    def test_queued_tee_branches_are_clean(self):
        result = lint(
            "videotestsrc num-frames=2 ! tee name=t "
            "t. ! queue ! tensor_converter ! mux.sink_0 "
            "t. ! queue ! tensor_converter ! mux.sink_1 "
            "tensor_mux name=mux ! tensor_sink"
        )
        assert "NNS-W103" not in result.codes, result.render()

    def test_restricted_element_distinguished(self, monkeypatch):
        import nnstreamer_tpu.config as config_mod

        monkeypatch.setenv(
            "NNS_TPU_COMMON_RESTRICTED_ELEMENTS", "tensorsrc,tensor_sink"
        )
        config_mod.reload_conf()
        try:
            result = lint(
                "tensorsrc dimensions=4 ! tensor_transform mode=typecast "
                "option=float32 ! tensor_sink"
            )
            assert "NNS-E010" in result.codes, result.render()
            # a NONEXISTENT element still reports unknown, not restricted
            result = lint("tensorsrc dimensions=4 ! frobnicator ! tensor_sink")
            assert "NNS-E004" in result.codes
            assert "NNS-E010" not in [
                d.code for d in result.diagnostics
                if d.element == "frobnicator"
            ]
        finally:
            monkeypatch.delenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS")
            config_mod.reload_conf()


class TestCollection:
    def test_multiple_errors_one_run(self):
        result = lint(
            "tensorsrc bogus=1 ! frobnicator ! "
            "tensor_decoder mode=nope ! tensor_sink"
        )
        assert {"NNS-W101", "NNS-E004", "NNS-E007"} <= set(result.codes)
        assert len(result.diagnostics) >= 3

    def test_every_caps_mismatch_reported_not_first_only(self):
        # two INDEPENDENT mismatches (parallel chains): both must surface
        result = lint(
            "tensorsrc name=s1 dimensions=4 ! "
            "other/tensors,dimensions=(string)8 ! tensor_sink name=k1 "
            "tensorsrc name=s2 dimensions=2 ! "
            "other/tensors,dimensions=(string)9 ! tensor_sink name=k2"
        )
        mismatches = [d for d in result.diagnostics if d.code == "NNS-E003"]
        assert len(mismatches) >= 2, result.render()

    def test_diagnostics_are_structured(self):
        result = lint("tensorsrc ! tensor_decoder mode=nope ! tensor_sink")
        (d,) = [x for x in result.diagnostics if x.code == "NNS-E007"]
        assert d.severity is Severity.ERROR
        assert d.element and d.element.startswith("tensor_decoder")
        assert "nope" in d.message
        assert d.hint  # actionable advice present
        assert d.slug == "unknown-decoder"


class TestReviewRegressions:
    def test_out_of_range_pad_ref_is_diagnosed_not_crash(self):
        result = lint(
            "videotestsrc num-frames=2 ! tensor_converter ! m.sink_5 "
            "tensor_mux name=m ! tensor_sink"
        )
        assert "NNS-E001" in result.codes, result.render()
        assert any(
            d.element == "m" and "sink pad 5" in d.message
            for d in result.diagnostics
        ), result.render()

    def test_lint_does_not_close_started_pipeline_resources(self, tmp_path):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline as pp

        out = tmp_path / "out.bin"
        p = pp(
            "tensorsrc dimensions=4 num-frames=2 ! "
            f"filesink name=fs location={out}"
        )
        p.negotiate()
        p["fs"].start()  # opens the file like the executor would
        try:
            assert lint(p).exit_code == 0
            assert not p["fs"]._file.closed, (
                "lint closed a started sink's file handle"
            )
        finally:
            p["fs"].stop()

    def test_dot_paints_unnamed_element_diagnostics(self):
        from nnstreamer_tpu.analysis import annotated_dot

        result = lint(
            "videotestsrc numframes=2 ! tensor_converter ! tensor_sink"
        )
        assert "NNS-W101" in result.codes
        dot = annotated_dot(result)
        assert "NNS-W101" in dot and "fillcolor" in dot, dot

    def test_unknown_element_diagnostic_matches_its_node(self):
        from nnstreamer_tpu.analysis import annotated_dot

        result = lint("tensorsrc dimensions=4 ! frobnicator ! tensor_sink")
        dot = annotated_dot(result)
        assert "NNS-E004" in dot, dot

    def test_uppercase_enum_value_lints_clean_and_runs(self):
        desc = (
            "videotestsrc pattern=RANDOM num-frames=1 ! tensor_converter ! "
            "tensor_sink name=out"
        )
        assert lint(desc).exit_code == 0
        p = parse_pipeline(desc)
        p.run(timeout=60)
        assert p["out"].rendered == 1

    def test_unrecognized_bool_is_warning_not_error(self):
        # runtime _parse_bool silently reads 'maybe' as false, so --check
        # must not hard-fail a pipeline that actually runs
        result = lint(
            "tensorsrc dimensions=4 silent=maybe num-frames=1 ! tensor_sink"
        )
        assert "NNS-W106" in result.codes, result.render()
        assert result.exit_code == 1

    def test_ctor_resource_failure_is_not_bad_property_value(self):
        result = lint(
            "videofilesrc location=/no/such/clip.mp4 ! tensor_converter ! "
            "tensor_sink"
        )
        assert "NNS-E011" in result.codes, result.render()
        assert "NNS-E005" not in result.codes, result.render()

    def test_restricted_probe_does_not_execute_plugin_files(
        self, monkeypatch, tmp_path
    ):
        # a restricted (non-whitelisted) name must never trigger plugin
        # file execution — neither registry.get phrasing its error nor
        # the linter classifying restricted-vs-unknown
        import nnstreamer_tpu.config as config_mod
        from nnstreamer_tpu import registry

        trap = tmp_path / "nns_element_evilplugin.py"
        trap.write_text("raise SystemExit('plugin executed during probe')\n")
        monkeypatch.setenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS", "tensorsrc")
        monkeypatch.setenv("NNS_TPU_ELEMENT_PLUGIN_PATHS", str(tmp_path))
        config_mod.reload_conf()
        try:
            with pytest.raises(KeyError, match="no element subplugin"):
                registry.get(registry.KIND_ELEMENT, "evilplugin")
            result = lint("evilplugin ! tensor_sink")  # must not SystemExit
            assert "NNS-E004" in result.codes, result.render()
        finally:
            monkeypatch.delenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS")
            monkeypatch.delenv("NNS_TPU_ELEMENT_PLUGIN_PATHS")
            config_mod.reload_conf()

    def test_llm_serversink_negotiate_not_dry_run(self):
        # LlmServerSink.negotiate() loads a model and registers a server
        # in the module-global table — lint must skip it entirely
        from nnstreamer_tpu.elements import llm_serve

        before = dict(llm_serve._table)
        result = lint(
            "appsrc dimensions=4 ! tensor_llm_serversink id=lint-probe"
        )
        assert "lint-probe" not in llm_serve._table
        assert dict(llm_serve._table) == before
        assert result.exit_code == 0, result.render()

    def test_unknown_source_position_does_not_claim_no_source(self):
        result = lint("frobnicator ! tensor_sink")
        assert "NNS-E004" in result.codes
        assert not any(
            "no source element" in d.message for d in result.diagnostics
        ), result.render()

    def test_dot_carries_dry_run_specs(self):
        from nnstreamer_tpu.analysis import annotated_dot

        result = lint(CLEAN)
        assert "Tensor[" in annotated_dot(result)

    def test_lint_does_not_shift_default_element_numbering(self):
        from nnstreamer_tpu.elements.base import Element

        before = dict(Element._instance_counters)
        lint(CLEAN)
        assert dict(Element._instance_counters) == before
        # the advertised pre-flight workflow: lint, then parse and
        # address elements by their gst-style default names
        p = parse_pipeline("tensorsrc dimensions=4 num-frames=1 ! tensor_sink")
        names = {e.name for e in p.elements}
        lint("tensorsrc dimensions=4 num-frames=1 ! tensor_sink")
        p2 = parse_pipeline("tensorsrc dimensions=4 num-frames=1 ! tensor_sink")
        n0 = sorted(int(n.replace("tensorsrc", ""))
                    for n in names if n.startswith("tensorsrc"))
        n2 = sorted(int(e.name.replace("tensorsrc", ""))
                    for e in p2.elements if e.name.startswith("tensorsrc"))
        assert n2[0] == n0[0] + 1  # one parse apart, lint in between free


class TestNeverExecutes:
    def test_lint_never_starts_elements(self, monkeypatch):
        from nnstreamer_tpu.elements.base import Element
        from nnstreamer_tpu.pipeline.graph import Pipeline

        def boom(self, *a, **k):
            raise AssertionError("lint must not start anything")

        monkeypatch.setattr(Element, "start", boom)
        monkeypatch.setattr(Pipeline, "start", boom)
        result = lint(CLEAN)
        assert result.exit_code == 0

    def test_lint_pipeline_object_does_not_mutate_it(self):
        p = parse_pipeline(CLEAN)
        result = lint(p)
        assert result.exit_code == 0
        assert all(not e.out_specs for e in p.elements)
        assert not p._negotiated

    def test_linted_pipeline_still_runs(self):
        p = parse_pipeline(CLEAN + " name=out")
        assert lint(p).exit_code == 0
        p.run(timeout=60)
        assert p["out"].rendered == 2


class TestCliAndDot:
    def test_launch_check_exit_codes(self, capsys):
        from nnstreamer_tpu.cli import main

        assert main(["--check", CLEAN]) == 0
        assert main(["--check", "tensorsrc frobnicate=1 ! tensor_sink"]) == 1
        rc = main(["--check", "tensorsrc ! tensor_decoder mode=nope ! tensor_sink"])
        assert rc == 2
        out = capsys.readouterr().out
        assert "NNS-E007" in out  # codes are printed

    def test_nns_lint_cli(self, capsys):
        from nnstreamer_tpu.analysis.cli import main

        assert main([CLEAN]) == 0
        assert main(["tensorsrc ! frobnicator ! tensor_sink"]) == 2
        assert "NNS-E004" in capsys.readouterr().out

    def test_nns_lint_json(self, capsys):
        import json

        from nnstreamer_tpu.analysis.cli import main

        assert main(["--json", "tensorsrc ! frobnicator ! tensor_sink"]) == 2
        data = json.loads(capsys.readouterr().out)
        assert data["exit_code"] == 2
        assert any(d["code"] == "NNS-E004" for d in data["diagnostics"])

    def test_self_check_passes(self):
        from nnstreamer_tpu.analysis.selfcheck import self_check

        assert self_check() == []

    def test_dot_annotation(self):
        from nnstreamer_tpu.analysis import annotated_dot

        result = lint(
            "tensorsrc dimensions=4 ! "
            "other/tensors,dimensions=(string)8 ! tensor_sink"
        )
        dot = annotated_dot(result)
        assert "NNS-E003" in dot
        assert "fillcolor" in dot
        # clean pipeline: plain dot, no paint
        clean = annotated_dot(lint(CLEAN))
        assert "fillcolor" not in clean


class TestSatelliteFixes:
    def test_caps_annotation_stripping_beyond_string_int_fraction(self):
        from nnstreamer_tpu.pipeline.parse import _parse_caps

        media, fields = _parse_caps(
            "other/tensors,num_tensors=(uint)4,fixed=(boolean)true,"
            "dimensions=(string)4,framerate=(fraction)30/1"
        )
        assert fields["num_tensors"] == "4"
        assert fields["fixed"] == "true"
        assert fields["dimensions"] == "4"
        assert fields["framerate"] == "30/1"

    def test_restricted_error_says_whether_element_exists(self, monkeypatch):
        import nnstreamer_tpu.config as config_mod
        from nnstreamer_tpu import registry

        monkeypatch.setenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS", "tensorsrc")
        config_mod.reload_conf()
        try:
            with pytest.raises(KeyError, match="exists but is restricted"):
                registry.get(registry.KIND_ELEMENT, "tensor_converter")
            with pytest.raises(KeyError, match="no element subplugin"):
                registry.get(registry.KIND_ELEMENT, "frobnicator")
        finally:
            monkeypatch.delenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS")
            config_mod.reload_conf()

    def test_unknown_ctor_keyword_raises_parse_error(self):
        # an element with a strict constructor (no **props catch-all, the
        # plugin-element case) must surface as ParseError naming element
        # and property, not a bare TypeError from cls(**props)
        from nnstreamer_tpu import registry
        from nnstreamer_tpu.elements.base import Source

        class StrictSrc(Source):
            FACTORY_NAME = "strictsrc"

            def __init__(self, name=None, width=1):
                super().__init__(name)
                self.width = int(width)

        registry.register(registry.KIND_ELEMENT, "strictsrc", StrictSrc)
        try:
            with pytest.raises(ParseError, match=r"strictsrc.*bogus"):
                parse_pipeline("strictsrc bogus=2 ! tensor_sink")
        finally:
            registry.unregister(registry.KIND_ELEMENT, "strictsrc")


# -- W113/W116/W120: one code per severed device chain -----------------------

class TestChainSplitCodeDeferral:
    """The resident-handoff pass emits exactly ONE code per boundary:
    W116 for fusable decoders (one-property fix), W120 for host-path
    tensor ops (the chain-granular diagnostic nns-xray shares), W113
    only for host elements outside the tensor-op surface — pinned both
    ways so the three can never double-report."""

    HOST_SPLIT = (
        "videotestsrc device=true num-frames=4 width=16 height=16 ! "
        "tensor_converter ! tensor_filter framework=scaler ! "
        "tensor_filter name=hostop framework=hostscaler ! "
        "tensor_filter framework=scaler ! fakesink"
    )

    def test_host_tensor_op_fires_w120_not_w113_or_w116(self):
        codes = [d.code for d in lint(self.HOST_SPLIT).diagnostics]
        assert "NNS-W120" in codes
        assert "NNS-W113" not in codes
        assert "NNS-W116" not in codes

    def test_fusable_decoder_keeps_w116_not_w120(self):
        r = lint(
            "tensorsrc dimensions=25:10 types=float32 num-frames=4 ! "
            "tensor_filter framework=scaler ! "
            "tensor_decoder mode=bounding_boxes option1=yolov5 ! "
            "tensor_filter framework=scaler ! fakesink"
        )
        codes = [d.code for d in r.diagnostics]
        assert "NNS-W116" in codes
        assert "NNS-W120" not in codes

    def test_non_tensor_op_host_element_keeps_w113(self):
        from nnstreamer_tpu import registry
        from nnstreamer_tpu.elements.base import Element

        class HostPassthru(Element):
            def negotiate(self, in_specs):
                return list(in_specs)

            def host_process(self, frame):
                return frame

        registry.register(registry.KIND_ELEMENT, "hostpassthru", HostPassthru)
        try:
            r = lint(
                "videotestsrc device=true width=16 height=16 ! "
                "tensor_converter ! tensor_filter framework=scaler ! "
                "hostpassthru ! tensor_filter framework=scaler ! fakesink"
            )
            codes = [d.code for d in r.diagnostics]
            assert "NNS-W113" in codes
            assert "NNS-W120" not in codes
        finally:
            registry.unregister(registry.KIND_ELEMENT, "hostpassthru")


# -- the docs/examples sweep -------------------------------------------------

def _is_pipelineish(text):
    if " ! " not in text:
        return False
    try:
        items = scan_description(text)
    except (ParseError, ValueError):
        return False
    n_elems = sum(1 for it in items if it[0] in ("element", "caps"))
    n_bangs = sum(1 for it in items if it[0] == "bang")
    return n_elems >= 2 and n_bangs >= 1


def _candidate_pipelines_from_text(text):
    """Yield parseable pipeline strings: double-quoted launch strings
    (doc code blocks) plus paragraph-joined docstring blocks."""
    seen = set()
    flat = " ".join(
        line.strip().rstrip("\\").strip() for line in text.splitlines()
    )
    for m in re.finditer(r'"([^"]+ ! [^"]+)"', flat):
        cand = m.group(1).strip()
        if cand not in seen and _is_pipelineish(cand):
            seen.add(cand)
            yield cand
    for para in re.split(r"\n\s*\n", text):
        joined = " ".join(
            line.strip().rstrip("\\").strip()
            for line in para.strip().splitlines()
        )
        joined = joined.strip().strip('"').replace('\\"', '"')
        if joined not in seen and _is_pipelineish(joined):
            seen.add(joined)
            yield joined


def _embedded_pipeline_strings():
    found = []
    ex_dir = os.path.join(REPO, "examples")
    for fn in sorted(os.listdir(ex_dir)):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(ex_dir, fn)) as f:
            tree = ast.parse(f.read())
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                for cand in _candidate_pipelines_from_text(node.value):
                    found.append((fn, cand))
    for doc in ("elements.md", "linting.md", "batching.md",
                "fault-tolerance.md", "sanitizer.md", "observability.md",
                "edge-serving.md", "resilience.md", "streaming.md",
                "serving-plane.md", "llm-serving.md", "on-device-ops.md",
                "chain-analysis.md"):
        with open(os.path.join(REPO, "docs", doc)) as f:
            for cand in _candidate_pipelines_from_text(f.read()):
                found.append((doc, cand))
    return found


class TestDocumentedPipelinesLintClean:
    def test_sweep_finds_pipelines(self):
        found = _embedded_pipeline_strings()
        assert len(found) >= 5, found  # examples + docs must carry strings

    @pytest.mark.parametrize(
        "source,description",
        _embedded_pipeline_strings(),
        ids=[f"{s}:{d[:40]}" for s, d in _embedded_pipeline_strings()],
    )
    def test_documented_pipeline_lints_clean(self, source, description):
        result = lint(description)
        assert result.exit_code == 0, (
            f"{source}: {description!r}\n{result.render()}"
        )
