"""Live KV-span migration tests (nnstreamer_tpu/kv/migrate.py,
docs/llm-serving.md "Migration & recovery").

The headline invariant: a greedy generation extracted mid-decode and
adopted on a SECOND paged batcher (fresh BlockPool) continues
bitwise-identical to the uninterrupted run — for fp and int8 cache
dtypes (int8 ships the quantized bytes + scales verbatim, never a
dequantize round trip). Around it: the span codec's failure taxonomy
(CRC corruption, truncation, stripped-payload coverage), warm
migrations shipping measurably fewer bytes than cold (asserted via
kv/migrate.tally, not vibes), the deadline-aware re-prefill fallback,
and the shrunk-pool restore refusal (PoolCapacityError before any
arena state moves).

Budget note: pump-program compiles are the file's real cost, and the
acceptance criterion itself demands TWO compiled batchers (source and
destination), so the fp pair is module-scoped and reused across the
migration, warm-bytes, and fallback tests, every drain uses pump width
1 (one compiled program per batcher), and the cells needing their own
configurations (int8 pair, tight pool, shrunk-pool restore) are marked
`slow`. The tier-1 remainder sits at the two-compile floor; the
fleet-level kill/restart soak lives in tests/test_llm_fleet_soak.py,
also slow.
"""

import dataclasses

import jax
import numpy as np
import pytest

from nnstreamer_tpu.kv import migrate
from nnstreamer_tpu.kv.blocks import PoolCapacityError
from nnstreamer_tpu.kv.migrate import (
    BlockRecord,
    RequestSpan,
    SpanCapacityError,
    SpanCorruptError,
    SpanFormatError,
    SpanPayloadMissingError,
    SpanStateError,
    block_crc,
    decode_span,
    encode_span,
)
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

N_HEADS = 2


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(11), vocab=211, d_model=32, n_heads=N_HEADS,
        n_layers=1,
    )


@pytest.fixture(scope="module")
def obs_reg():
    from nnstreamer_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.enable()
    yield reg
    obs_metrics.disable()


def _mk(params, **kw):
    base = dict(n_slots=2, max_len=64, prompt_len=16,
                kv_layout="paged", block_size=16)
    base.update(kw)
    return ContinuousBatcher(params, N_HEADS, **base)


@pytest.fixture(scope="module")
def src(params, obs_reg):
    return _mk(params)


@pytest.fixture(scope="module")
def dst(params, obs_reg):
    return _mk(params)


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(1, 211, (n,)).astype(
        np.int32
    )


def _drain(cb, rids):
    # pump width 1 everywhere: ONE compiled pump program per batcher
    # (each distinct width compiles its own) — compile count, not token
    # count, is this file's cost
    while any(cb.result(r) is None for r in rids):
        cb.step_pump(1)
    return [cb.result(r) for r in rids]


def _settle_prefills(cb):
    while cb.stats()["kv_prefill_queue"] > 0:
        cb.step_pump(1)


def _mid_decode(cb, prompt, budget, min_tokens=3):
    """Submit, settle the prefill, decode a few tokens: the request is
    actively decoding — the only extractable state."""
    rid = cb.submit(prompt, budget)
    _settle_prefills(cb)
    while len(cb.partials([rid]).get(rid, [])) < min_tokens:
        cb.step_pump(1)
    return rid


# -- span codec (host only, no device work) --------------------------------

def _toy_span(n_tokens=20, block_size=8, stripped=()):
    """A hand-built fp span: 2 leaves (k, v), tiny per-block payload."""
    rng = np.random.default_rng(0)
    leaves = [("float32", (2, block_size, 1, 4)),
              ("float32", (2, block_size, 1, 4))]
    prompt = rng.integers(1, 100, (n_tokens - 3,)).astype(np.int32)
    tokens = [7, 8, 9, 5]  # n_kv = n_tokens, pending token 5 unwritten
    n_blocks = -(-n_tokens // block_size)
    blocks = []
    for i in range(n_blocks):
        payload = [
            rng.standard_normal((2, block_size, 1, 4)).astype(
                np.float32
            ).tobytes()
            for _ in leaves
        ]
        rec = BlockRecord(min(block_size, n_tokens - i * block_size),
                          block_crc(payload), payload)
        if i in stripped:
            rec = BlockRecord(rec.n_tokens, rec.crc, None)
        blocks.append(rec)
    return RequestSpan(
        block_size=block_size, leaves=leaves, cache_dtype="float32",
        rid=3, prompt=prompt, tokens=tokens, fill0=n_tokens - 3,
        budget=10, temperature=0.0, top_k=0, top_p=1.0, stop_token=None,
        key=np.asarray([1, 2], np.uint32), deadline_s=1.5, preemptions=1,
        prefix_hashes=[11, 22], blocks=blocks,
        meta={"client_id": 4},
    )


def test_span_roundtrip():
    span = _toy_span()
    got = decode_span(encode_span(span))
    assert got.block_size == span.block_size
    assert got.leaves == span.leaves
    assert got.tokens == span.tokens and got.fill0 == span.fill0
    assert got.n_kv == span.n_kv
    assert np.array_equal(got.prompt, span.prompt)
    assert np.array_equal(got.key, span.key)
    assert got.deadline_s == span.deadline_s
    assert got.preemptions == 1 and got.prefix_hashes == [11, 22]
    assert got.meta == {"client_id": 4}
    for a, b in zip(got.blocks, span.blocks):
        assert (a.n_tokens, a.crc, a.payload) == (
            b.n_tokens, b.crc, b.payload
        )


def test_span_corruption_and_format_rejected():
    span = _toy_span()
    wire = encode_span(span)
    # flip one payload byte (past the header): CRC catches it
    bad = bytearray(wire)
    bad[-1] ^= 0xFF
    with pytest.raises(SpanCorruptError, match="CRC mismatch"):
        decode_span(bytes(bad))
    with pytest.raises(SpanFormatError, match="bad magic"):
        decode_span(b"not a span at all")
    with pytest.raises(SpanFormatError, match="truncated"):
        decode_span(wire[:-5])
    with pytest.raises(SpanFormatError, match="trailing"):
        decode_span(wire + b"xx")
    v = dataclasses.replace(span, version=99)
    with pytest.raises(SpanFormatError, match="version"):
        decode_span(encode_span(v))


def test_strip_shared_halves_payload_and_survives_roundtrip():
    span = _toy_span(n_tokens=20, block_size=8)  # 2 full + 1 partial
    warm = span.strip_shared(16)
    assert warm.blocks[0].payload is None
    assert warm.blocks[1].payload is None
    assert warm.blocks[2].payload is not None  # partial never strips
    assert warm.payload_bytes() < span.payload_bytes()
    assert len(encode_span(warm)) < len(encode_span(span))
    got = decode_span(encode_span(warm))
    assert got.blocks[0].payload is None
    assert got.blocks[2].payload == span.blocks[2].payload
    # a block boundary short of a full block strips nothing
    assert span.strip_shared(7).payload_bytes() == span.payload_bytes()


# -- bitwise migration, fp and int8 ----------------------------------------

def test_migrate_greedy_bitwise_fp(src, dst):
    """Extract mid-decode, adopt on a second batcher with a fresh pool:
    the combined stream equals the uninterrupted run byte for byte."""
    p = _prompt(21, 1)
    [ref] = _drain(src, [src.submit(p, 9)])
    rid = _mid_decode(src, p, 9)
    out0 = src.stats()["kv_migrations_out"]
    span = src.extract_request(rid)
    assert src.stats()["kv_migrations_out"] == out0 + 1
    assert src.result(rid) is None  # gone from the source
    assert span.cache_dtype == "float32"
    in0 = dst.stats()["kv_migrations_in"]
    new_rid = dst.adopt_request(span)
    assert dst.stats()["kv_migrations_in"] == in0 + 1
    assert _drain(dst, [new_rid]) == [ref]
    # the source's ledger shows the hand-off as terminal
    assert src.requests()[rid]["state"] == "migrated"


@pytest.mark.slow
def test_migrate_greedy_bitwise_int8(params, obs_reg):
    a = _mk(params, cache_dtype="int8", n_slots=2)
    b = _mk(params, cache_dtype="int8", n_slots=2)
    p = _prompt(18, 2)
    [ref] = _drain(a, [a.submit(p, 8)])
    rid = _mid_decode(a, p, 8)
    span = a.extract_request(rid)
    assert span.cache_dtype == "int8"
    assert len(span.leaves) == 4  # k8, k_scale, v8, v_scale
    assert _drain(b, [b.adopt_request(span)]) == [ref]


def test_warm_migration_ships_fewer_bytes(src, dst):
    """A destination already holding the prompt's full blocks strips
    them: fewer bytes on the wire, same continued stream."""
    p = _prompt(37, 3)  # 2 full blocks + partial at block_size=16
    [ref] = _drain(src, [src.submit(p, 8)])
    _drain(dst, [dst.submit(p, 8)])  # seed dst's prefix index
    rid = _mid_decode(src, p, 8)
    span = src.extract_request(rid)
    shared = dst.probe_prefix(span.kv_tokens)
    assert shared >= 32  # at least the prompt's full blocks
    migrate.tally.reset()
    cold = encode_span(span)
    warm = encode_span(span.strip_shared(shared))
    snap = migrate.tally.snapshot()
    assert snap["spans_out"] == 2
    assert snap["bytes_out"] == len(cold) + len(warm)
    assert len(warm) < len(cold)
    hits0 = dst.stats()["kv_prefix_hits"]
    new_rid = dst.adopt_request(decode_span(warm))
    assert dst.stats()["kv_prefix_hits"] > hits0
    assert _drain(dst, [new_rid]) == [ref]


def test_resume_from_span_parity(src, dst):
    """No peer accepted: re-prefill from the span's token stream alone
    still reproduces the uninterrupted stream (known_first pins the
    pending token — no re-sampling)."""
    p = _prompt(19, 4)
    [ref] = _drain(src, [src.submit(p, 9)])
    rid = _mid_decode(src, p, 9)
    span = src.extract_request(rid)
    span = decode_span(encode_span(span))
    res0 = dst.stats()["request_resumes"]
    new_rid = dst.resume_from_span(span)
    assert dst.stats()["request_resumes"] == res0 + 1
    assert _drain(dst, [new_rid]) == [ref]


def test_migration_metrics_emitted(obs_reg):
    """Both-ways obs check: the counters the earlier tests drove exist
    under their cataloged names with the documented labels."""
    def val(name, **labels):
        m = obs_reg.find(name, **labels)
        return 0 if m is None else m.value

    assert val("nns_kv_migrations_total", direction="out") >= 2
    assert val("nns_kv_migrations_total", direction="in") >= 2
    assert val("nns_request_resumes_total", kind="reprefill") >= 1
    assert val("nns_kv_span_bytes_total", direction="out") > 0
    assert val("nns_kv_span_bytes_total", direction="in") > 0


# -- refusal taxonomy ------------------------------------------------------

def test_extract_refusals(params, src):
    with pytest.raises(SpanStateError, match="not extractable"):
        src.extract_request(10**9)  # unknown rid
    p = _prompt(8, 5)
    rid = src.submit(p, 4)  # queued: no KV span yet
    with pytest.raises(SpanStateError, match="settle the prefill"):
        src.extract_request(rid)
    _drain(src, [rid])
    with pytest.raises(SpanStateError):  # finished: nothing live
        src.extract_request(rid)
    flat = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                             prompt_len=16)
    with pytest.raises(SpanStateError, match="paged"):
        flat.extract_request(0)
    assert flat.probe_prefix(p) == 0  # non-paged probe: never warm


def test_adopt_refusals(src, dst):
    rid = _mid_decode(src, _prompt(20, 6), 8)
    span = src.extract_request(rid)
    with pytest.raises(SpanFormatError, match="block_size"):
        dst.adopt_request(dataclasses.replace(span, block_size=32))
    with pytest.raises(SpanFormatError, match="geometry"):
        dst.adopt_request(dataclasses.replace(
            span, leaves=[("float32", (9, 9))]
        ))
    with pytest.raises(SpanCapacityError, match="max_len"):
        dst.adopt_request(dataclasses.replace(span, budget=1000))
    # stripped blocks the destination does not share are unadoptable
    # (dst has never seen this prompt, so nothing covers the strip)
    stripped = span.strip_shared(len(span.blocks) * span.block_size)
    with pytest.raises(SpanPayloadMissingError, match="prefix index"):
        dst.adopt_request(stripped)
    # the full span still lands afterwards (refusal mutated nothing)
    assert len(_drain(dst, [dst.adopt_request(span)])) == 1


@pytest.mark.slow
def test_adopt_capacity_refusal(params, obs_reg, src):
    tight = _mk(params, kv_blocks=6, n_slots=2, max_len=64)
    rid = _mid_decode(src, _prompt(50, 7), 8)
    span = src.extract_request(rid)  # needs 4 blocks; tight has 6
    r2 = tight.submit(_prompt(50, 9), 8)  # pins 4 of the 6 blocks
    _settle_prefills(tight)
    with pytest.raises(SpanCapacityError, match="blocks"):
        tight.adopt_request(span)
    _drain(tight, [r2])
    # the refused span is intact and adoptable elsewhere
    assert len(_drain(src, [src.resume_from_span(span)])) == 1


# -- shrunk-pool restore refusal (satellite bugfix) ------------------------

@pytest.mark.slow
def test_restore_shrunk_pool_raises_typed_capacity_error(params, obs_reg):
    big = _mk(params, kv_blocks=12, n_slots=2, max_len=64)
    rid = big.submit(_prompt(20, 10), 6)
    _settle_prefills(big)
    big.step_pump(2)
    snap = big.snapshot()
    small = _mk(params, kv_blocks=8, n_slots=2, max_len=64)
    with pytest.raises(PoolCapacityError) as ei:
        small.restore(snap)
    err = ei.value
    assert err.needed == 12 and err.have == 8
    assert isinstance(err.evictable, list)
    # refused BEFORE any state moved: the target still serves
    assert len(_drain(small, [small.submit(_prompt(10, 11), 3)])) == 1
    # and the source batcher can still finish from its own state
    assert _drain(big, [rid])[0] is not None


# -- SLO ledger migration state --------------------------------------------

def test_slo_ledger_migrated_terminal():
    from nnstreamer_tpu.kv.sched import SLOLedger

    led = SLOLedger()
    rec = led.submit(5, deadline_s=2.0)
    rec.preemptions = 3
    assert led.record(5) is rec and led.record(6) is None
    led.migrated(5)
    assert rec.state == "migrated" and rec.t_done is not None
    led.migrated(6)  # unknown rid: no-op
