"""Packaging smoke tests (reference L7: debian/, packaging/, meson install).

Builds a wheel from the checkout and checks the artifact contains the
package and the nns-launch console script."""

import os
import subprocess
import sys
import zipfile

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def wheel_path(tmp_path_factory):
    out = tmp_path_factory.mktemp("wheel")
    proc = subprocess.run(
        [
            sys.executable, "-m", "pip", "wheel", REPO,
            "--no-deps", "--no-build-isolation", "-w", str(out), "-q",
        ],
        capture_output=True, text=True, timeout=300,
    )
    if proc.returncode != 0:
        pytest.skip(f"pip wheel unavailable: {proc.stderr[-400:]}")
    wheels = [f for f in os.listdir(out) if f.endswith(".whl")]
    assert len(wheels) == 1, f"expected one wheel, got {wheels}"
    return os.path.join(out, wheels[0])


def test_wheel_contains_package_and_console_script(wheel_path):
    with zipfile.ZipFile(wheel_path) as z:
        names = z.namelist()
        assert any(n == "nnstreamer_tpu/__init__.py" for n in names)
        assert any(n.endswith("proto/nns_tensors.proto") for n in names)
        entry = next(n for n in names if n.endswith("entry_points.txt"))
        text = z.read(entry).decode()
    assert "nns-launch = nnstreamer_tpu.cli:main" in text


def test_wheel_has_no_test_or_bench_files(wheel_path):
    with zipfile.ZipFile(wheel_path) as z:
        names = z.namelist()
    assert not any(n.startswith(("tests/", "bench")) for n in names)
