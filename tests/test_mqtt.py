"""MQTT layer tests: protocol codec, in-process broker, mqttsink/mqttsrc
pipelines, SNTP sync (reference: gst/mqtt/*, tests gated on a local broker
via tests/check_broker.sh — our in-repo broker makes them unconditional)."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge import ntp
from nnstreamer_tpu.edge.mqtt import MqttBroker, MqttClient, topic_matches
from nnstreamer_tpu.edge.mqtt_elems import MqttSink, MqttSrc
from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.tensors.frame import Frame


@pytest.fixture
def broker():
    b = MqttBroker()
    yield b
    b.close()


@pytest.fixture(autouse=True)
def _reset_ntp():
    yield
    ntp.reset()


class TestTopicMatch:
    def test_exact_and_wildcards(self):
        assert topic_matches("a/b", "a/b")
        assert not topic_matches("a/b", "a/c")
        assert topic_matches("a/+", "a/b")
        assert not topic_matches("a/+", "a/b/c")
        assert topic_matches("a/#", "a/b/c")
        assert topic_matches("#", "anything/at/all")
        assert not topic_matches("a/#/b", "a/x/b")  # '#' must be last


class TestClientBroker:
    def test_pub_sub_roundtrip(self, broker):
        sub = MqttClient(port=broker.port, client_id="sub").connect()
        pub = MqttClient(port=broker.port, client_id="pub").connect()
        try:
            sub.subscribe("nns/test")
            time.sleep(0.1)  # SUBACK settle
            pub.publish("nns/test", b"hello tensors")
            got = sub.recv(timeout=5)
            assert got == ("nns/test", b"hello tensors")
        finally:
            sub.close()
            pub.close()

    def test_wildcard_subscription(self, broker):
        sub = MqttClient(port=broker.port).connect()
        pub = MqttClient(port=broker.port).connect()
        try:
            sub.subscribe("nns/+/stream")
            time.sleep(0.1)
            pub.publish("nns/cam0/stream", b"x")
            pub.publish("nns/other/topic", b"y")  # not matched
            assert sub.recv(timeout=5) == ("nns/cam0/stream", b"x")
            assert sub.recv(timeout=0.3) is None
        finally:
            sub.close()
            pub.close()

    def test_large_payload(self, broker):
        sub = MqttClient(port=broker.port).connect()
        pub = MqttClient(port=broker.port).connect()
        try:
            sub.subscribe("big")
            time.sleep(0.1)
            blob = bytes(range(256)) * 4096  # 1 MiB: exercises varint length
            pub.publish("big", blob)
            got = sub.recv(timeout=10)
            assert got is not None and got[1] == blob
        finally:
            sub.close()
            pub.close()

    def test_connect_refused(self):
        with pytest.raises(OSError):
            MqttClient(port=1, client_id="x").connect(timeout=1)


class TestMqttElements:
    def test_pipeline_pub_sub(self, broker):
        n = 4
        src_pipe = Pipeline().chain(
            VideoTestSrc(width=8, height=8, **{"num-frames": n}),
            TensorConverter(),
            MqttSink(port=broker.port, **{"pub-topic": "nns/t"}),
        )
        sink = TensorSink()
        recv_pipe = Pipeline().chain(
            MqttSrc(port=broker.port, **{"sub-topic": "nns/t"}), sink
        )
        recv_ex = recv_pipe.start()
        time.sleep(0.3)  # subscription settles before publishing starts
        src_pipe.run(timeout=30)
        assert recv_ex.wait(timeout=30)
        recv_pipe.stop()
        assert sink.rendered == n
        f = sink.frames[0]
        assert f.tensors[0].shape == (1, 8, 8, 3)
        assert "mqtt_sent_time" in f.meta and "mqtt_transit_s" in f.meta

    def test_sink_requires_topic(self):
        with pytest.raises(ValueError, match="pub-topic"):
            MqttSink()

    def test_src_requires_topic(self):
        with pytest.raises(ValueError, match="sub-topic"):
            MqttSrc()

    def test_unreachable_broker_errors(self):
        s = MqttSink(port=1, **{"pub-topic": "x"})
        with pytest.raises(ElementError, match="cannot reach"):
            s.start()


class _FakeSntpServer(threading.Thread):
    """Answers one SNTP query with a fixed clock offset."""

    def __init__(self, offset_s: float) -> None:
        super().__init__(daemon=True)
        self.offset = offset_s
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.bind(("127.0.0.1", 0))
        self.port = self.sock.getsockname()[1]
        self.sock.settimeout(5)

    def run(self) -> None:
        try:
            data, addr = self.sock.recvfrom(48)
        except OSError:
            return
        now = time.time() + self.offset + ntp.NTP_UNIX_DELTA
        resp = bytearray(48)
        resp[0] = (4 << 3) | 4  # VN=4, mode=server
        resp[24:32] = data[40:48]  # originate := client transmit
        for off in (32, 40):  # receive + transmit timestamps
            struct.pack_into(">I", resp, off, int(now))
            struct.pack_into(">I", resp, off + 4, int((now % 1) * (1 << 32)))
        self.sock.sendto(bytes(resp), addr)
        self.sock.close()


class TestNtp:
    def test_offset_measured(self):
        srv = _FakeSntpServer(offset_s=5.0)
        srv.start()
        off = ntp.query_offset("127.0.0.1", port=srv.port, timeout=5)
        assert abs(off - 5.0) < 0.5

    def test_sync_installs_walltime_offset(self):
        srv = _FakeSntpServer(offset_s=-3.0)
        srv.start()
        assert ntp.sync(["127.0.0.1"], port=srv.port, timeout=5)
        assert ntp.is_synced()
        assert abs((ntp.walltime() - time.time()) + 3.0) < 0.5

    def test_sync_unreachable_returns_false(self):
        assert not ntp.sync(["127.0.0.1"], port=1, timeout=0.3)
        assert not ntp.is_synced()


class TestEdgeMqttConnectType:
    def test_edgesink_edgesrc_over_mqtt(self, broker):
        from nnstreamer_tpu.edge.pubsub import EdgeSink, EdgeSrc

        n = 3
        send = Pipeline().chain(
            VideoTestSrc(width=8, height=8, **{"num-frames": n}),
            TensorConverter(),
            EdgeSink(port=broker.port, **{"connect-type": "MQTT", "topic": "e/t"}),
        )
        sink = TensorSink()
        recv = Pipeline().chain(
            EdgeSrc(**{"connect-type": "MQTT", "dest-port": broker.port,
                       "topic": "e/t"}),
            sink,
        )
        ex = recv.start()
        time.sleep(0.3)
        send.run(timeout=30)
        assert ex.wait(timeout=30)
        recv.stop()
        assert sink.rendered == n

    def test_unknown_connect_type_rejected(self):
        from nnstreamer_tpu.edge.pubsub import EdgeSink

        with pytest.raises(ValueError, match="connect-type"):
            EdgeSink(**{"connect-type": "AITT"})


class TestBrokerQoS:
    """QoS 1/2 PUBLISH from external 3.1.1 clients: the broker must strip
    the packet id before fan-out and acknowledge (round-1 advisory fix)."""

    def _raw_connect(self, broker):
        from nnstreamer_tpu.edge import mqtt as m

        sock = socket.create_connection(("127.0.0.1", broker.port), timeout=5)
        var = (
            m._string("MQTT") + bytes([4]) + bytes([0x02])
            + __import__("struct").pack(">H", 60)
        )
        sock.sendall(m._packet(m.CONNECT, 0, var + m._string("raw-qos")))
        ptype, _, payload = m._read_packet(sock)
        assert ptype == m.CONNACK and payload[1] == 0
        return sock

    @pytest.mark.parametrize("qos", [1, 2])
    def test_qos_publish_stripped_and_acked(self, broker, qos):
        import struct

        from nnstreamer_tpu.edge import mqtt as m

        sub = MqttClient(port=broker.port).connect()
        sub.subscribe("qos/t")
        time.sleep(0.1)
        sock = self._raw_connect(broker)
        body = m._string("qos/t") + struct.pack(">H", 77) + b"payload!"
        sock.sendall(m._packet(m.PUBLISH, qos << 1, body))
        # broker acknowledges: PUBACK for qos1, PUBREC for qos2
        ptype, _, ack = m._read_packet(sock)
        assert ptype == (m.PUBACK if qos == 1 else m.PUBREC)
        assert struct.unpack(">H", ack[:2])[0] == 77
        if qos == 2:
            sock.sendall(m._packet(m.PUBREL, 2, struct.pack(">H", 77)))
            ptype, _, comp = m._read_packet(sock)
            assert ptype == m.PUBCOMP
        # subscriber receives the CLEAN payload (no packet-id bytes)
        got = sub.recv(timeout=5)
        assert got == ("qos/t", b"payload!")
        sock.close()
        sub.close()
