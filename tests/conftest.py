"""Test environment: force a virtual 8-device CPU platform before jax import.

Multi-chip hardware is not available in CI; sharding paths are validated on
a virtual CPU mesh (xla_force_host_platform_device_count), mirroring the
reference's dummy-device strategy (edgetpu device_type:dummy,
tests/nnstreamer_filter_edgetpu/unittest_edgetpu.cc:30).
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# A TPU-attach site hook may have force-set jax_platforms to the hardware
# backend via jax.config.update (which outranks the env var); pin it back so
# the suite always runs on the virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
