"""Model-zoo family tests: SSD, PoseNet, DeepLab-v3, face pipeline.

Mirrors the reference's model-fixture coverage (tests/test_models/models/:
ssd_mobilenet_v2_coco, posenet_mobilenet, deeplabv3_257) — but as
constructively-seeded jax models verified by shape inference (eval_shape;
the analogue of getModelInfo) plus targeted real forwards feeding the
matching decoder subplugins end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu import registry
from nnstreamer_tpu.models import zoo
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


def _dec(name):
    return registry.get(registry.KIND_DECODER, name)()


def _shapes(m, batch=1):
    dummies = [jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype) for t in m.input_spec]
    out = jax.eval_shape(m.fn, *dummies)
    if not isinstance(out, (tuple, list)):
        out = (out,)
    return [o.shape for o in out]


def test_zoo_has_model_families():
    names = zoo.available()
    for name in (
        "add", "mobilenet_v2", "ssd_mobilenet_v2", "ssd_mobilenet_v2_pp",
        "posenet", "deeplab_v3", "face_detect", "face_landmark",
    ):
        assert name in names


# ---------------------------------------------------------------- SSD

def test_ssd_anchor_count_and_format(tmp_path):
    from nnstreamer_tpu.decoders.bounding_box import load_box_priors
    from nnstreamer_tpu.models import ssd_mobilenet

    anchors = ssd_mobilenet.generate_anchors()
    assert anchors.shape == (4, 1917)  # the reference model's anchor count
    assert np.all(anchors[2:] > 0)  # h, w positive
    path = tmp_path / "box-priors.txt"
    ssd_mobilenet.write_box_priors(str(path))
    loaded = load_box_priors(str(path))
    np.testing.assert_allclose(loaded, anchors, atol=1e-6)


def test_ssd_output_shapes():
    m = zoo.get("ssd_mobilenet_v2")
    assert _shapes(m) == [(1, 1917, 4), (1, 1917, 91)]


def test_ssd_pp_output_shapes():
    m = zoo.get("ssd_mobilenet_v2_pp", max_out="10")
    assert _shapes(m) == [(10, 4), (10,), (10,), (1,)]


def test_ssd_feeds_bounding_box_decoder(tmp_path):
    from nnstreamer_tpu.models import ssd_mobilenet

    priors = tmp_path / "box-priors.txt"
    ssd_mobilenet.write_box_priors(str(priors))
    m = zoo.get("ssd_mobilenet_v2")
    img = jnp.asarray(
        np.random.default_rng(0).integers(0, 255, (1, 300, 300, 3), np.uint8)
    )
    loc, cls = jax.jit(m.fn)(img)
    d = _dec("bounding_boxes")
    spec = TensorsSpec.from_strings("4:1917:1,91:1917:1", "float32,float32")
    opts = {
        "option1": "mobilenet-ssd",
        "option3": f"{priors}:0.5",
        "option4": "64:64",
        "option5": "300:300",
    }
    media = d.negotiate(spec, opts)
    assert (media.width, media.height) == (64, 64)
    out = d.decode(Frame((np.asarray(loc[0]), np.asarray(cls[0]))), opts)
    assert out.tensors[0].shape == (64, 64, 4)  # RGBA canvas, random dets ok


def test_ssd_pp_on_device_nms(tmp_path):
    m = zoo.get("ssd_mobilenet_v2_pp", max_out="10", threshold="0.0001")
    img = jnp.asarray(
        np.random.default_rng(1).integers(0, 255, (1, 300, 300, 3), np.uint8)
    )
    boxes, classes, scores, num = jax.jit(m.fn)(img)
    boxes, scores, num = np.asarray(boxes), np.asarray(scores), float(num[0])
    assert boxes.shape == (10, 4) and 0 <= num <= 10
    # rows beyond num are zeroed; scores sorted descending among valid
    valid = scores > 0
    assert valid.sum() == num
    s = scores[valid]
    assert np.all(s[:-1] >= s[1:]) if s.size > 1 else True
    d = _dec("bounding_boxes")
    spec = TensorsSpec.from_strings("4:10:1,10:1,10:1,1:1")
    opts = {"option1": "mobilenet-ssd-postprocess", "option4": "32:32"}
    d.negotiate(spec, opts)
    out = d.decode(
        Frame((boxes, np.asarray(classes), scores, np.asarray([num], np.float32))),
        opts,
    )
    assert out.meta["detections"].shape[0] == int(num)


# ---------------------------------------------------------------- PoseNet

def test_posenet_output_shapes():
    m = zoo.get("posenet")
    assert _shapes(m) == [(1, 9, 9, 17), (1, 9, 9, 34), (1, 9, 9, 32), (1, 9, 9, 32)]


def test_posenet_feeds_pose_decoder():
    m = zoo.get("posenet")
    img = jnp.asarray(
        np.random.default_rng(2).integers(0, 255, (1, 257, 257, 3), np.uint8)
    )
    heat, offs, _, _ = jax.jit(m.fn)(img)
    d = _dec("pose_estimation")
    spec = TensorsSpec.from_strings("17:9:9:1,34:9:9:1", "float32,float32")
    opts = {"option1": "64:64", "option2": "257:257", "option4": "heatmap-offset"}
    media = d.negotiate(spec, opts)
    assert media.format == "RGBA"
    out = d.decode(Frame((np.asarray(heat), np.asarray(offs))), opts)
    kpts = out.meta["keypoints"]
    assert kpts.shape == (17, 3)


# ---------------------------------------------------------------- DeepLab

def test_deeplab_output_shape():
    m = zoo.get("deeplab_v3")
    assert _shapes(m) == [(1, 257, 257, 21)]


def test_deeplab_feeds_image_segment_decoder():
    m = zoo.get("deeplab_v3")
    img = jnp.asarray(
        np.random.default_rng(3).integers(0, 255, (1, 257, 257, 3), np.uint8)
    )
    seg = jax.jit(m.fn)(img)
    d = _dec("image_segment")
    spec = TensorsSpec.from_strings("21:257:257:1")
    opts = {"option1": "tflite-deeplab"}
    d.negotiate(spec, opts)
    out = d.decode(Frame((np.asarray(seg),)), opts)
    assert out.tensors[0].shape == (257, 257, 4)


# ---------------------------------------------------------------- Face pair

def test_face_detect_ov_rows():
    m = zoo.get("face_detect")
    img = jnp.asarray(
        np.random.default_rng(4).integers(0, 255, (1, 128, 128, 3), np.uint8)
    )
    det = np.asarray(jax.jit(m.fn)(img))
    assert det.shape == (16, 7)
    assert np.all(det[:-1, 2] >= det[1:, 2])  # top-k confidence order
    assert np.all(det[:, 3:] >= 0) and np.all(det[:, 3:] <= 1)
    assert np.all(det[:, 5] >= det[:, 3]) and np.all(det[:, 6] >= det[:, 4])


def test_face_detect_regions_feed_crop():
    from nnstreamer_tpu.elements.control import TensorCrop

    m = zoo.get(
        "face_detect", output="regions", threshold="0.0", frame_size="128:128"
    )
    img_np = np.random.default_rng(5).integers(0, 255, (1, 128, 128, 3), np.uint8)
    regions = np.asarray(jax.jit(m.fn)(jnp.asarray(img_np)))
    assert regions.shape == (16, 4) and regions.dtype == np.int32
    crop = TensorCrop()
    outs = crop.receive(0, Frame((img_np,)))
    assert outs == []
    outs = crop.receive(1, Frame((regions,)))
    assert len(outs) == 1
    crops = outs[0][1].tensors
    assert len(crops) >= 1
    for c in crops:
        assert c.ndim == 4 and c.shape[0] == 1 and c.shape[3] == 3


def test_face_landmark_crop_size_agnostic():
    m = zoo.get("face_landmark")
    out1 = jax.jit(m.fn)(
        jnp.asarray(np.random.default_rng(6).integers(0, 255, (1, 112, 112, 3), np.uint8))
    )
    out2 = m.fn(
        jnp.asarray(np.random.default_rng(7).integers(0, 255, (1, 80, 72, 3), np.uint8))
    )
    for out in (np.asarray(out1), np.asarray(out2)):
        assert out.shape == (1, 136)
        assert np.all(out >= 0) and np.all(out <= 1)


def test_face_composite_detect_crop_landmark():
    """The BASELINE composite config, element-level: detect → regions →
    crop → landmark per crop."""
    from nnstreamer_tpu.elements.control import TensorCrop

    det_m = zoo.get("face_detect", output="regions", threshold="0.0")
    lmk_m = zoo.get("face_landmark")
    img_np = np.random.default_rng(8).integers(0, 255, (1, 128, 128, 3), np.uint8)
    regions = np.asarray(jax.jit(det_m.fn)(jnp.asarray(img_np)))
    crop = TensorCrop()
    crop.receive(0, Frame((img_np,)))
    outs = crop.receive(1, Frame((regions[:2],)))
    crops = outs[0][1].tensors
    assert crops
    lm = np.asarray(lmk_m.fn(jnp.asarray(crops[0])))
    assert lm.shape == (1, 136)


# ---------------------------------------------------------------- ViT

def test_vit_output_shape():
    m = zoo.get("vit", size="64", patch="16", d_model="64", n_heads="4",
                n_layers="2", num_classes="10")
    assert _shapes(m) == [(1, 10)]


def test_vit_forward_finite():
    m = zoo.get("vit", size="64", patch="16", d_model="64", n_heads="4",
                n_layers="2", num_classes="10")
    img = jnp.asarray(
        np.random.default_rng(10).integers(0, 255, (1, 64, 64, 3), np.uint8)
    )
    out = np.asarray(jax.jit(m.fn)(img))
    assert np.all(np.isfinite(out))


def test_vit_patch_divisibility_enforced():
    with pytest.raises(ValueError, match="divisible"):
        zoo.get("vit", size="65", patch="16")


# bench.py runs every model below in bfloat16 on the real chip; a dtype
# promotion anywhere in a scan carry (the round-2 rmsnorm bug: bf16 * f32
# weight → f32 carry) is a trace-time error, so eval_shape catches it
# without compiling.
@pytest.mark.parametrize(
    "name,options",
    [
        ("mobilenet_v2", {}),
        ("mobilenet_v2", dict(quantize="int8", size="96", num_classes="16")),
        ("ssd_mobilenet_v2", {}),
        ("ssd_mobilenet_v2_pp", {}),
        ("posenet", {}),
        ("deeplab_v3", {}),
        ("face_detect", {}),
        ("face_composite", {}),
        ("vit", dict(size="64", patch="16", d_model="64", n_heads="4",
                     n_layers="2")),
        ("transformer_lm", dict(vocab="512", d_model="64", n_heads="4",
                                n_layers="2")),
        ("transformer_lm", dict(vocab="512", d_model="64", n_heads="4",
                                n_layers="2", generate="4", seqlen="16")),
    ],
)
def test_zoo_traces_in_bfloat16(name, options):
    m = zoo.get(name, compute_dtype="bfloat16", **options)
    dummies = [
        jax.ShapeDtypeStruct(t.shape, t.dtype.np_dtype) for t in m.input_spec
    ]
    jax.eval_shape(m.fn, *dummies)
