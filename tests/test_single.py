"""Single-shot API tests (reference: tests/nnstreamer_filter_single/
unittest_filter_single.cc and custom filter tests)."""

import numpy as np
import pytest

from nnstreamer_tpu.backends import register_custom_easy, unregister_custom_easy
from nnstreamer_tpu.backends.base import BackendError
from nnstreamer_tpu.single import SingleShot
from nnstreamer_tpu.tensors.spec import DType, TensorsSpec


def spec(dims, types):
    return TensorsSpec.from_strings(dims, types)


class TestFakeBackends:
    def test_passthrough(self):
        with SingleShot(framework="passthrough", input_spec=spec("4:3", "float32")) as s:
            x = np.arange(12, dtype=np.float32).reshape(3, 4)
            (out,) = s.invoke(x)
            np.testing.assert_array_equal(np.asarray(out), x)
            assert s.input_spec == s.output_spec

    def test_scaler(self):
        with SingleShot(
            framework="scaler", custom="factor:3", input_spec=spec("4", "float32")
        ) as s:
            (out,) = s.invoke(np.ones(4, np.float32))
            np.testing.assert_allclose(np.asarray(out), 3 * np.ones(4))

    def test_average(self):
        with SingleShot(
            framework="average", input_spec=spec("3:8:8:1", "float32")
        ) as s:
            x = np.random.default_rng(0).random((1, 8, 8, 3)).astype(np.float32)
            (out,) = s.invoke(x)
            assert out.shape == (1, 1, 1, 3)
            np.testing.assert_allclose(
                np.asarray(out)[0, 0, 0], x.mean(axis=(0, 1, 2)), rtol=1e-5
            )

    def test_framecounter_stateful(self):
        with SingleShot(
            framework="framecounter", input_spec=spec("2", "float32")
        ) as s:
            for i in range(3):
                (out,) = s.invoke(np.zeros(2, np.float32))
                assert out[0] == i

    def test_stats_recorded(self):
        with SingleShot(framework="passthrough", input_spec=spec("2", "float32")) as s:
            for _ in range(5):
                s.invoke(np.zeros(2, np.float32))
            assert s.backend.stats.total_invoke_num == 5
            assert s.latency_us >= 0.0


class TestCustomEasy:
    def test_roundtrip(self):
        register_custom_easy(
            "negate", lambda ts: tuple(-t for t in ts), traceable=True
        )
        try:
            with SingleShot(
                framework="custom-easy",
                model="negate",
                input_spec=spec("3", "float32"),
            ) as s:
                (out,) = s.invoke(np.array([1.0, -2.0, 3.0], np.float32))
                np.testing.assert_allclose(np.asarray(out), [-1.0, 2.0, -3.0])
                assert s.backend.traceable_fn() is not None
        finally:
            assert unregister_custom_easy("negate")

    def test_unregistered_raises(self):
        with pytest.raises(BackendError):
            SingleShot(framework="custom-easy", model="nope_xyz").open()


class TestCustomScript:
    def test_script_filter(self, tmp_path):
        script = tmp_path / "doubler.py"
        script.write_text(
            "from nnstreamer_tpu.tensors.spec import TensorsSpec\n"
            "class CustomFilter:\n"
            "    TRACEABLE = False\n"
            "    def setInputDim(self, in_spec):\n"
            "        return in_spec\n"
            "    def invoke(self, tensors):\n"
            "        return tuple(t * 2 for t in tensors)\n"
        )
        with SingleShot(
            framework="custom", model=str(script), input_spec=spec("4", "float32")
        ) as s:
            (out,) = s.invoke(np.ones(4, np.float32))
            np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_auto_detect_py_is_custom(self, tmp_path):
        script = tmp_path / "ident.py"
        script.write_text(
            "class CustomFilter:\n"
            "    def setInputDim(self, s):\n"
            "        return s\n"
            "    def invoke(self, ts):\n"
            "        return ts\n"
        )
        s = SingleShot(model=str(script), input_spec=spec("2", "float32"))
        assert s.props.framework == "custom"
        with s:
            s.invoke(np.zeros(2, np.float32))

    def test_bad_protocol(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("class CustomFilter:\n    pass\n")
        with pytest.raises(BackendError):
            SingleShot(framework="custom", model=str(script)).open()


class TestJaxBackend:
    def test_zoo_add(self):
        with SingleShot(framework="jax", model="zoo:add", custom="const:5,dims:3") as s:
            (out,) = s.invoke(np.zeros(3, np.float32))
            np.testing.assert_allclose(np.asarray(out), 5.0)

    def test_script_model(self, tmp_path):
        script = tmp_path / "model.py"
        script.write_text(
            "import jax.numpy as jnp\n"
            "from nnstreamer_tpu.tensors.spec import TensorsSpec\n"
            "def get_model(options):\n"
            "    def fn(x):\n"
            "        return jnp.stack([x.sum(), x.max()])\n"
            "    return fn, TensorsSpec.from_strings('4', 'float32')\n"
        )
        with SingleShot(framework="jax", model=str(script)) as s:
            assert s.output_spec[0].shape == (2,)
            (out,) = s.invoke(np.array([1, 2, 3, 4], np.float32))
            np.testing.assert_allclose(np.asarray(out), [10.0, 4.0])

    def test_shape_inference_no_execution(self):
        s = SingleShot(framework="jax", model="zoo:add", custom="dims:7:2").open()
        assert s.input_spec[0].shape == (2, 7)
        assert s.output_spec[0].shape == (2, 7)
        s.close()

    def test_reload(self):
        with SingleShot(framework="jax", model="zoo:add", custom="const:1,dims:2") as s:
            s.reload_model("zoo:add")
            (out,) = s.invoke(np.zeros(2, np.float32))
            np.testing.assert_allclose(np.asarray(out), 1.0)


class TestMobileNetV2:
    def test_forward_shapes(self):
        with SingleShot(
            framework="jax", model="zoo:mobilenet_v2", custom="size:64"
        ) as s:
            assert s.input_spec[0].shape == (1, 64, 64, 3)
            img = np.random.default_rng(0).integers(
                0, 255, (1, 64, 64, 3), dtype=np.uint8
            )
            (logits,) = s.invoke(img)
            assert logits.shape == (1, 1001)
            assert np.isfinite(np.asarray(logits)).all()

    def test_deterministic_params(self):
        from nnstreamer_tpu.models import zoo

        a = zoo.get("mobilenet_v2", size="32")
        b = zoo.get("mobilenet_v2", size="32")
        import jax

        la = jax.tree_util.tree_leaves(a.params)
        lb = jax.tree_util.tree_leaves(b.params)
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
