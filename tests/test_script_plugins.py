"""Python-script subplugin tests (reference: python converter/decoder/filter
tests with scripts under tests/test_models/models/*.py)."""

import numpy as np

from nnstreamer_tpu import registry
from nnstreamer_tpu.tensors.frame import Frame


CONVERTER_SCRIPT = """
import numpy as np

class CustomConverter:
    def convert(self, tensors):
        # raw bytes -> two uint8 tensors split in half
        data = np.asarray(tensors[0], np.uint8).reshape(-1)
        h = data.size // 2
        return (data[:h], data[h:])
"""

DECODER_SCRIPT = """
import numpy as np

class CustomDecoder:
    def decode(self, tensors):
        return (np.concatenate([np.asarray(t).reshape(-1) for t in tensors]),)
"""


def test_python_script_converter(tmp_path):
    p = tmp_path / "conv.py"
    p.write_text(CONVERTER_SCRIPT)
    conv = registry.get(registry.KIND_CONVERTER, "python3")()
    props = {"script": str(p)}
    out = conv.convert(Frame((np.arange(10, dtype=np.uint8),)), props)
    assert out.num_tensors == 2
    np.testing.assert_array_equal(out.tensors[0], np.arange(5, dtype=np.uint8))


def test_python_script_decoder(tmp_path):
    p = tmp_path / "dec.py"
    p.write_text(DECODER_SCRIPT)
    dec = registry.get(registry.KIND_DECODER, "python3")()
    opts = {"option1": str(p)}
    out = dec.decode(
        Frame((np.ones(3, np.float32), np.zeros(2, np.float32))), opts
    )
    assert out.tensors[0].shape == (5,)


def test_custom_script_mode_alias(tmp_path):
    """tensor_converter mode=custom-script:<path.py> — the reference's
    spelling — routes to the python3 converter subplugin."""
    from nnstreamer_tpu.elements.converter import TensorConverter
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.sources import TensorSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline

    p = tmp_path / "conv.py"
    p.write_text(CONVERTER_SCRIPT)
    src = TensorSrc(dimensions="10", **{"input-type": "uint8", "num-frames": 2})
    conv = TensorConverter(mode=f"custom-script:{p}")
    sink = TensorSink()
    Pipeline().chain(src, conv, sink).run(timeout=30)
    assert sink.rendered == 2
    assert sink.frames[0].num_tensors == 2
