"""tensor_src_iio tests with a fake sysfs tree (the reference's mock-sysfs
strategy, tests/nnstreamer_source/*)."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.iio import TensorSrcIIO, scan_devices
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.tensors.frame import EOS_FRAME


def _fake_device(tmp_path, n=0, name="accel_3d", channels=("accel_x", "accel_y")):
    d = tmp_path / f"iio:device{n}"
    d.mkdir(parents=True)
    (d / "name").write_text(name + "\n")
    for i, c in enumerate(channels):
        (d / f"in_{c}_raw").write_text(f"{100 + i}\n")
        (d / f"in_{c}_scale").write_text("0.5\n")
        (d / f"in_{c}_offset").write_text("2\n")
    (d / "sampling_frequency").write_text("100\n")
    return d


def test_scan_devices(tmp_path):
    _fake_device(tmp_path, 0, "accel_3d")
    _fake_device(tmp_path, 1, "gyro_3d", channels=("anglvel_x",))
    devs = scan_devices(str(tmp_path))
    assert set(devs) == {"accel_3d", "gyro_3d"}


def test_capture_applies_scale_offset(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "device": "accel_3d",
           "frequency": 1000, "num-frames": 2}
    )
    spec = src.output_spec()
    assert spec[0].shape == (1, 2)
    f = None
    while f is None:
        f = src.generate()
    data = np.asarray(f.tensors[0])
    # (raw + offset) * scale = (100+2)*0.5, (101+2)*0.5
    np.testing.assert_allclose(data, [[51.0, 51.5]])


def test_channel_selection_and_order(tmp_path):
    _fake_device(tmp_path, 0, channels=("accel_x", "accel_y", "accel_z"))
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "channels": "accel_z,accel_x",
           "frequency": 1000, "num-frames": 1}
    )
    assert src.output_spec()[0].shape == (1, 2)
    assert src._channels == ["accel_z", "accel_x"]


def test_missing_device_errors(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(**{"base-dir": str(tmp_path), "device": "nope"})
    with pytest.raises(ElementError, match="not found"):
        src.output_spec()


def test_eos_after_num_frames(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "frequency": 10000, "num-frames": 1}
    )
    src.output_spec()
    f = None
    while f is None:
        f = src.generate()
    assert src.generate() is EOS_FRAME


def test_pipeline_end_to_end(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "frequency": 500, "num-frames": 3}
    )
    sink = TensorSink()
    Pipeline().chain(src, sink).run(timeout=30)
    assert sink.rendered == 3
    assert sink.frames[0].tensors[0].shape == (1, 2)
