"""tensor_src_iio tests with a fake sysfs tree (the reference's mock-sysfs
strategy, tests/nnstreamer_source/*)."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.iio import TensorSrcIIO, scan_devices
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.tensors.frame import EOS_FRAME


def _fake_device(tmp_path, n=0, name="accel_3d", channels=("accel_x", "accel_y")):
    d = tmp_path / f"iio:device{n}"
    d.mkdir(parents=True)
    (d / "name").write_text(name + "\n")
    for i, c in enumerate(channels):
        (d / f"in_{c}_raw").write_text(f"{100 + i}\n")
        (d / f"in_{c}_scale").write_text("0.5\n")
        (d / f"in_{c}_offset").write_text("2\n")
    (d / "sampling_frequency").write_text("100\n")
    return d


def test_scan_devices(tmp_path):
    _fake_device(tmp_path, 0, "accel_3d")
    _fake_device(tmp_path, 1, "gyro_3d", channels=("anglvel_x",))
    devs = scan_devices(str(tmp_path))
    assert set(devs) == {"accel_3d", "gyro_3d"}


def test_capture_applies_scale_offset(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "device": "accel_3d",
           "frequency": 1000, "num-frames": 2}
    )
    spec = src.output_spec()
    assert spec[0].shape == (1, 2)
    f = None
    while f is None:
        f = src.generate()
    data = np.asarray(f.tensors[0])
    # (raw + offset) * scale = (100+2)*0.5, (101+2)*0.5
    np.testing.assert_allclose(data, [[51.0, 51.5]])


def test_channel_selection_and_order(tmp_path):
    _fake_device(tmp_path, 0, channels=("accel_x", "accel_y", "accel_z"))
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "channels": "accel_z,accel_x",
           "frequency": 1000, "num-frames": 1}
    )
    assert src.output_spec()[0].shape == (1, 2)
    assert src._channels == ["accel_z", "accel_x"]


def test_missing_device_errors(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(**{"base-dir": str(tmp_path), "device": "nope"})
    with pytest.raises(ElementError, match="not found"):
        src.output_spec()


def test_eos_after_num_frames(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "frequency": 10000, "num-frames": 1}
    )
    src.output_spec()
    f = None
    while f is None:
        f = src.generate()
    assert src.generate() is EOS_FRAME


def test_pipeline_end_to_end(tmp_path):
    _fake_device(tmp_path, 0)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path), "frequency": 500, "num-frames": 3}
    )
    sink = TensorSink()
    Pipeline().chain(src, sink).run(timeout=30)
    assert sink.rendered == 3
    assert sink.frames[0].tensors[0].shape == (1, 2)


# ------------------------------------------------- buffered chardev mode

def _fake_buffered_device(tmp_path, records, n=0, name="accel_3d"):
    """Fake sysfs tree with scan_elements + a regular-file 'chardev'.

    Channels: accel_x le:s12/16>>0 (index 0), accel_y be:u10/16>>2
    (index 1), temp le:s8/8>>0 (index 2) — mixed widths exercise the
    alignment/padding layout. ``records`` is a list of (x_raw, y_raw,
    t_raw) integer triples packed as the kernel would.
    """
    d = tmp_path / "sys" / f"iio:device{n}"
    scan = d / "scan_elements"
    scan.mkdir(parents=True)
    (d / "name").write_text(name + "\n")
    (d / "buffer").mkdir()
    (d / "buffer" / "length").write_text("16\n")
    (d / "buffer" / "enable").write_text("0\n")
    for c, idx, t in (
        ("accel_x", 0, "le:s12/16>>0"),
        ("accel_y", 1, "be:u10/16>>2"),
        ("temp", 2, "le:s8/8>>0"),
    ):
        (scan / f"in_{c}_en").write_text("0\n")
        (scan / f"in_{c}_index").write_text(f"{idx}\n")
        (scan / f"in_{c}_type").write_text(t + "\n")
        (d / f"in_{c}_scale").write_text("1.0\n")
        (d / f"in_{c}_offset").write_text("0\n")
    dev = tmp_path / "dev"
    dev.mkdir()
    blob = b""
    for x, y, t in records:
        # layout: u16@0 (x), u16@2 (y), u8@4 (temp), record padded to 6
        blob += int(x).to_bytes(2, "little")
        blob += int(y).to_bytes(2, "big")
        blob += int(t).to_bytes(1, "little")
        blob += b"\x00"  # pad to 2-byte alignment
    (dev / f"iio:device{n}").write_bytes(blob)
    return d, dev


def _capture_buffered(tmp_path, records, **extra):
    _fake_buffered_device(tmp_path, records)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path / "sys"), "dev-dir": str(tmp_path / "dev"),
           "mode": "buffer", "frequency": 100,
           "num-frames": len(records), **extra}
    )
    spec = src.output_spec()
    frames = []
    while True:
        f = src.generate()
        if f is EOS_FRAME:
            break
        if f is not None:
            frames.append(f)
    src.stop()
    return spec, frames


def test_buffered_capture_decodes_packed_records(tmp_path):
    # x: s12 → 0x801 = -2047; y: u10 stored <<2 → raw word 40<<2; t: s8 -5
    records = [(0x801 & 0xFFFF, 40 << 2, (-5) & 0xFF), (100, 3 << 2, 7)]
    spec, frames = _capture_buffered(tmp_path, records)
    assert spec[0].shape == (1, 3)
    assert len(frames) == 2
    np.testing.assert_allclose(
        np.asarray(frames[0].tensors[0]), [[-2047.0, 40.0, -5.0]]
    )
    np.testing.assert_allclose(
        np.asarray(frames[1].tensors[0]), [[100.0, 3.0, 7.0]]
    )
    # pts is integer nanoseconds at the configured frequency
    assert frames[0].pts == 0 and frames[1].pts == 10_000_000


def test_buffered_channel_enable_written(tmp_path):
    d, _ = _fake_buffered_device(tmp_path, [(1, 4, 1)])
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path / "sys"), "dev-dir": str(tmp_path / "dev"),
           "mode": "buffer", "channels": "accel_x,temp", "num-frames": 0}
    )
    src.output_spec()
    scan = d / "scan_elements"
    assert (scan / "in_accel_x_en").read_text() == "1"
    assert (scan / "in_accel_y_en").read_text() == "0"
    assert (scan / "in_temp_en").read_text() == "1"
    assert (d / "buffer" / "enable").read_text() == "1"


def test_buffered_subset_repacks_layout(tmp_path):
    """Disabling accel_y changes the record layout: x u16@0, temp u8@2,
    record size 2-aligned = 4... the element must compute the packed
    layout of ONLY the enabled channels."""
    d = tmp_path / "sys" / "iio:device0"
    scan = d / "scan_elements"
    scan.mkdir(parents=True)
    (d / "name").write_text("dev\n")
    (d / "buffer").mkdir()
    for c, idx, t in (("a", 0, "le:u16/16>>0"), ("b", 1, "le:u8/8>>0")):
        (scan / f"in_{c}_en").write_text("0\n")
        (scan / f"in_{c}_index").write_text(f"{idx}\n")
        (scan / f"in_{c}_type").write_text(t + "\n")
    dev = tmp_path / "dev"
    dev.mkdir()
    blob = (500).to_bytes(2, "little") + (9).to_bytes(1, "little") + b"\x00"
    (dev / "iio:device0").write_bytes(blob)
    src = TensorSrcIIO(
        **{"base-dir": str(tmp_path / "sys"), "dev-dir": str(tmp_path / "dev"),
           "mode": "buffer", "frequency": 100, "num-frames": 1}
    )
    src.output_spec()
    f = None
    while f is None or f is EOS_FRAME:
        f = src.generate()
    np.testing.assert_allclose(np.asarray(f.tensors[0]), [[500.0, 9.0]])
    src.stop()
    # teardown disabled the buffer
    assert (d / "buffer" / "enable").read_text() == "0"


def test_bad_type_string_rejected(tmp_path):
    from nnstreamer_tpu.elements.iio import ChannelFormat

    with pytest.raises(ElementError, match="bad IIO channel type"):
        ChannelFormat("xx:s12/16>>0")
