"""Query connect-type=MQTT / HYBRID loopback tests.

Reference: tensor_query_common.c:35-42 connect types; loopback strategy of
tests/nnstreamer_edge/query/runTest.sh (server + client on localhost, the
broker in-process via the in-tree MqttBroker)."""

import threading

import numpy as np
import pytest

from nnstreamer_tpu.edge.mqtt import MqttBroker
from nnstreamer_tpu.edge.query import (
    TensorQueryClient,
    TensorQueryServerSink,
    TensorQueryServerSrc,
)
from nnstreamer_tpu.tensors.frame import Frame


def _echo_server(src, sink, scale, stop_evt):
    while not stop_evt.is_set():
        frame = src.generate()
        if frame is None:
            continue
        out = frame.with_tensors([np.asarray(t) * scale for t in frame.tensors])
        sink.render(out)


def _roundtrip(connect_type, broker, srv_id, topic, n_clients=1):
    props = {"connect-type": connect_type, "topic": topic}
    src = TensorQueryServerSrc(
        f"qsrc-{srv_id}", host="127.0.0.1", port=broker.port, id=srv_id, **props
    )
    sink = TensorQueryServerSink(f"qsink-{srv_id}", id=srv_id)
    src.output_spec()
    src.start()
    stop_evt = threading.Event()
    t = threading.Thread(
        target=_echo_server, args=(src, sink, 3.0, stop_evt), daemon=True
    )
    t.start()
    clients = [
        TensorQueryClient(
            f"qc-{srv_id}-{i}",
            **{"dest-host": "127.0.0.1", "dest-port": broker.port,
               "timeout": 10, **props},
        )
        for i in range(n_clients)
    ]
    try:
        for c in clients:
            c.start()
        for i, c in enumerate(clients):
            val = 10.0 * (i + 1)
            reply = c.process(Frame((np.full((2, 2), val, np.float32),), pts=7))
            assert reply is not None
            np.testing.assert_allclose(
                np.asarray(reply.tensors[0]), np.full((2, 2), val * 3.0)
            )
            assert reply.pts == 7
        # second round trip per client on the same connection
        for i, c in enumerate(clients):
            reply = c.process(Frame((np.ones(3, np.float32) * (i + 1),)))
            np.testing.assert_allclose(
                np.asarray(reply.tensors[0]), np.full(3, 3.0 * (i + 1))
            )
    finally:
        stop_evt.set()
        for c in clients:
            c.stop()
        t.join(timeout=2)
        src.stop()


@pytest.fixture()
def broker():
    b = MqttBroker()
    yield b
    b.close()


def test_query_mqtt_roundtrip(broker):
    _roundtrip("MQTT", broker, "m1", "q/mqtt1")


def test_query_mqtt_two_clients_demux(broker):
    _roundtrip("MQTT", broker, "m2", "q/mqtt2", n_clients=2)


def test_query_hybrid_roundtrip(broker):
    _roundtrip("HYBRID", broker, "h1", "q/hyb1")


def test_query_hybrid_two_clients_demux(broker):
    _roundtrip("HYBRID", broker, "h2", "q/hyb2", n_clients=2)


def test_hybrid_discovery_fails_without_server(broker):
    from nnstreamer_tpu.edge.query_transports import HybridClientTransport
    from nnstreamer_tpu.edge.transport import TransportError

    tr = HybridClientTransport("q/nobody")
    tr.DISCOVERY_TIMEOUT = 0.8
    with pytest.raises(TransportError, match="whois"):
        tr.connect("127.0.0.1", broker.port)


def test_unknown_connect_type_rejected():
    from nnstreamer_tpu.elements.base import NegotiationError

    src = TensorQueryServerSrc("bad", **{"connect-type": "AITT"})
    with pytest.raises(NegotiationError, match="AITT"):
        src.output_spec()
