"""Tracing subsystem tests (reference §5.1 analogue: GstShark/NNShark/
HawkTracer chrome-trace workflows, brought in-tree)."""

import json

import numpy as np

from nnstreamer_tpu import trace
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline


def teardown_function(_fn):
    trace.disable()


def test_span_and_counter_events():
    t = trace.Tracer()
    with t.span("work", "element", frame=1):
        pass
    t.instant("mark")
    t.counter("queue_depth", q0=3)
    evs = t.events()
    assert [e["ph"] for e in evs] == ["X", "i", "C"]
    assert evs[0]["name"] == "work" and evs[0]["dur"] >= 0
    assert evs[2]["args"] == {"q0": 3}


def test_pipeline_records_per_element_spans(tmp_path):
    tracer = trace.enable()
    tracer.clear()
    src = VideoTestSrc(width=8, height=8, **{"num-frames": 3})
    sink = TensorSink()
    p = Pipeline().chain(src, TensorConverter(), sink)
    p.run(timeout=30)
    names = {e["name"] for e in tracer.events()}
    assert any("videotestsrc" in n or "src" in n for n in names)
    assert any("sink" in n for n in names)
    out = tmp_path / "trace.json"
    tracer.save(str(out))
    doc = json.loads(out.read_text())
    assert doc["traceEvents"] and all("ts" in e for e in doc["traceEvents"])


def test_disabled_by_default():
    trace.disable()
    assert trace.get() is None
    src = VideoTestSrc(width=8, height=8, **{"num-frames": 1})
    sink = TensorSink()
    Pipeline().chain(src, TensorConverter(), sink).run(timeout=30)
    assert trace.get() is None
