"""nns-san: race-lint table tests over the seeded-violations fixture,
graph deadlock/capacity diagnostics, --strict, the catalog self-check,
and runtime-sanitizer runs that catch an injected spec violation, a
frame-accounting leak, a lock-order cycle, and a leaked thread that a
plain run misses.

Wall-time discipline: tiny frame counts, no unbounded sleeps — this file
sits mid-alphabet and the tier-1 suite brushes its 870s budget.
"""

import os
import threading
from collections import Counter

import numpy as np
import pytest

from nnstreamer_tpu import registry
from nnstreamer_tpu.analysis import lint
from nnstreamer_tpu.analysis.racecheck import run_race_lint
from nnstreamer_tpu.elements.base import HostElement
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.pipeline.sanitize import (
    LockOrderGraph,
    SpecViolationError,
    poison_like,
    sanitize_enabled,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "seeded_races.py")

# the seeded fixture documents these exact counts in its docstring; a
# check that silently stops matching fails here
EXPECTED_SEEDED = {
    "NNS-R001": 2, "NNS-R002": 1, "NNS-R003": 1,
    "NNS-R004": 1, "NNS-R005": 1, "NNS-R006": 3,
}


# ------------------------------------------------------------------ static
class TestRaceLint:
    def test_seeded_fixture_yields_every_expected_code(self):
        report = run_race_lint([FIXTURE])
        got = Counter(d.code for d in report.diagnostics)
        assert dict(got) == EXPECTED_SEEDED, report.render()
        # R003/R006 are errors: the seeded file fails hard
        assert report.exit_code == 2

    def test_findings_anchor_to_file_and_line(self):
        report = run_race_lint([FIXTURE])
        for d in report.diagnostics:
            path, _, line = d.element.rpartition(":")
            assert path.endswith("seeded_races.py") and line.isdigit(), d

    def test_waiver_comment_suppresses_single_line(self, tmp_path):
        bad = (
            "import threading\n"
            "import time\n"
            "_lock = threading.Lock()\n"
            "def f():\n"
            "    with _lock:\n"
            "        time.sleep(1.0)  # nns-san: ok - startup only\n"
        )
        p = tmp_path / "w.py"
        p.write_text(bad)
        assert run_race_lint([str(p)]).diagnostics == []
        p.write_text(bad.replace("  # nns-san: ok - startup only", ""))
        codes = [d.code for d in run_race_lint([str(p)]).diagnostics]
        assert codes == ["NNS-R002"]

    def test_condition_wait_is_not_flagged(self, tmp_path):
        ok = (
            "import threading\n"
            "_cv = threading.Condition()\n"
            "def f(pred):\n"
            "    with _cv:\n"
            "        _cv.wait()\n"
        )
        p = tmp_path / "c.py"
        p.write_text(ok)
        assert run_race_lint([str(p)]).diagnostics == []

    # the package-is-clean gate lives in tests/test_style.py (the same
    # assertion tools/check_style.py enforces on whole-tree runs)


class TestDeadlockPass:
    def test_w108_nonpositive_queue_size(self):
        r = lint("tensorsrc dimensions=4 ! tensor_sink queue-size=0")
        assert "NNS-W108" in r.codes, r.render()

    def test_w108_batch_starved_channel(self):
        r = lint(
            "tensorsrc dimensions=4 ! tensor_transform mode=typecast "
            "option=float32 batching=true max-batch=8 queue-size=4 ! "
            "tensor_sink"
        )
        assert "NNS-W108" in r.codes, r.render()

    def test_w108_models_eliminated_queue_depth(self):
        # the executor replaces the consumer channel with an eliminated
        # upstream queue's depth — the pass must use the EFFECTIVE depth
        starved = lint(
            "tensorsrc dimensions=4 ! queue max-size-buffers=4 ! "
            "tensor_transform mode=typecast option=float32 "
            "batching=true max-batch=8 ! tensor_sink"
        )
        assert "NNS-W108" in starved.codes, starved.render()
        widened = lint(
            "tensorsrc dimensions=4 ! queue max-size-buffers=32 ! "
            "tensor_transform mode=typecast option=float32 "
            "batching=true max-batch=16 queue-size=8 ! tensor_sink"
        )
        assert "NNS-W108" not in widened.codes, widened.render()

    def test_w109_unqueued_demux_join(self):
        desc_unqueued = (
            "tensorsrc dimensions=4,4 num-tensors=2 ! tensor_demux name=d "
            "d.src_0 ! mux.sink_0 d.src_1 ! mux.sink_1 "
            "tensor_mux name=mux ! tensor_sink"
        )
        r = lint(desc_unqueued)
        assert "NNS-W109" in r.codes, r.render()
        queued = desc_unqueued.replace("! mux.sink", "! queue ! mux.sink")
        r = lint(queued)
        assert "NNS-W109" not in r.codes, r.render()

    def test_w110_skewed_sync_join(self):
        # tensor_if defaults to else=SKIP: one branch drops data-
        # dependently, the other never does — the mux can starve
        r = lint(
            "tensorsrc dimensions=4 ! tee name=t "
            "t. ! queue ! tensor_if supplied-value=0.5 ! mux.sink_0 "
            "t. ! queue ! mux.sink_1 "
            "tensor_mux name=mux ! tensor_sink"
        )
        assert "NNS-W110" in r.codes, r.render()

    def test_w110_quiet_for_nosync_and_symmetric(self):
        nosync = lint(
            "tensorsrc dimensions=4 ! tee name=t "
            "t. ! queue ! tensor_if supplied-value=0.5 ! mux.sink_0 "
            "t. ! queue ! mux.sink_1 "
            "tensor_mux name=mux sync-mode=nosync ! tensor_sink"
        )
        assert "NNS-W110" not in nosync.codes, nosync.render()
        symmetric = lint(
            "tensorsrc dimensions=4 ! tee name=t "
            "t. ! queue ! tensor_if supplied-value=0.5 ! mux.sink_0 "
            "t. ! queue ! tensor_if supplied-value=0.5 ! mux.sink_1 "
            "tensor_mux name=mux ! tensor_sink"
        )
        assert "NNS-W110" not in symmetric.codes, symmetric.render()


class TestCliAndSelfCheck:
    def test_nns_san_race_json(self, capsys):
        import json

        from nnstreamer_tpu.analysis.san_cli import main

        rc = main(["--json", "--race", FIXTURE])
        assert rc == 2
        data = json.loads(capsys.readouterr().out)
        assert set(EXPECTED_SEEDED) == {
            d["code"] for d in data["diagnostics"]
        }

    def test_nns_san_race_package_is_clean(self, capsys):
        from nnstreamer_tpu.analysis.san_cli import main

        rc = main(["--race", os.path.join(REPO, "nnstreamer_tpu")])
        assert rc == 0, capsys.readouterr().out

    def test_nns_san_deadlock_filters_to_graph_codes(self, capsys):
        import json

        from nnstreamer_tpu.analysis.lint import DEADLOCK_CODES
        from nnstreamer_tpu.analysis.san_cli import main

        # unknown property + undersized channel: --deadlock must report
        # only the graph-shape finding
        rc = main(["--json", "--deadlock",
                   "tensorsrc dimensions=4 bogus=1 ! tensor_sink "
                   "queue-size=-2"])
        assert rc == 1
        data = json.loads(capsys.readouterr().out)
        codes = {d["code"] for d in data["diagnostics"]}
        assert codes == {"NNS-W108"}
        assert codes <= DEADLOCK_CODES

    def test_nns_san_self_check_passes(self, capsys):
        from nnstreamer_tpu.analysis.san_cli import main

        assert main(["--self-check"]) == 0, capsys.readouterr().out

    def test_nns_lint_strict_promotes_warnings(self):
        from nnstreamer_tpu.analysis.cli import main

        warn_only = "tensorsrc frobnicate=1 ! tensor_sink"
        assert main([warn_only]) == 1
        assert main(["--strict", warn_only]) == 2
        clean = (
            "tensorsrc dimensions=4 num-frames=2 ! tensor_transform "
            "mode=typecast option=float32 ! tensor_sink"
        )
        assert main(["--strict", clean]) == 0

    def test_nns_san_strict(self):
        from nnstreamer_tpu.analysis.san_cli import main

        assert main(["--strict", "--deadlock",
                     "tensorsrc dimensions=4 ! tensor_sink "
                     "queue-size=0"]) == 2


# ----------------------------------------------------------------- runtime
CHAOS_CORRUPT = (
    "tensorsrc dimensions=4 num-frames=9 ! "
    "tensor_chaos corrupt-every-n=3 ! tensor_sink name=out"
)


class TestRuntimeSanitizer:
    def test_config_knob_and_env(self, monkeypatch):
        assert not sanitize_enabled()
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        assert sanitize_enabled()
        monkeypatch.setenv("NNS_TPU_SANITIZE", "0")
        assert not sanitize_enabled()

    def test_plain_run_misses_corruption_sanitized_catches(
        self, monkeypatch
    ):
        # plain: the shape-truncated frames flow to the sink unnoticed
        p = parse_pipeline(CHAOS_CORRUPT)
        ex = p.run(timeout=60)
        assert p["out"].rendered == 9 and not ex.errors
        # sanitized: the stream fails AT the corruption point
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        p = parse_pipeline(CHAOS_CORRUPT)
        with pytest.raises(SpecViolationError) as ei:
            p.run(timeout=60)
        assert "spec" in str(ei.value)
        san = p._executor.sanitizer
        assert "NNS-S001" in san.codes

    def test_sanitized_chaos_drop_run_stays_balanced(self, monkeypatch):
        # an on-error=drop chaos run accounts every frame: no findings
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=60 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=fail_rate:0.2,seed:7 on-error=drop ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert ex.sanitizer.codes == [], [
            str(d) for d in ex.sanitizer.findings()
        ]
        s = ex.stats()["f"]
        assert s["san_offered"] == 60
        assert s["san_delivered"] + s["error_dropped"] == 60
        assert ex.leaked_threads == []

    def test_accounting_leak_detected_only_when_sanitized(
        self, monkeypatch
    ):
        class LeakyHost(HostElement):
            """Declares 1:1 but silently eats every 3rd frame."""

            FACTORY_NAME = "leakyhost"
            SAN_ONE_TO_ONE = True

            def __init__(self, name=None, **props):
                super().__init__(name, **props)
                self._n = 0

            def negotiate(self, in_specs):
                return [in_specs[0]]

            def process(self, frame):
                self._n += 1
                return None if self._n % 3 == 0 else frame

        registry.register(registry.KIND_ELEMENT, "leakyhost", LeakyHost)
        try:
            desc = (
                "tensorsrc dimensions=4 num-frames=9 ! leakyhost ! "
                "tensor_sink name=out"
            )
            ex = parse_pipeline(desc).run(timeout=60)  # plain: silent
            assert not ex.errors and ex.sanitizer is None
            monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
            p = parse_pipeline(desc)
            ex = p.run(timeout=60)
            assert "NNS-S002" in ex.sanitizer.codes, [
                str(d) for d in ex.sanitizer.findings()
            ]
            (leak,) = [
                d for d in ex.sanitizer.findings()
                if d.code == "NNS-S002"
            ]
            assert "3 frame(s) leaked" in leak.message
        finally:
            registry.unregister(registry.KIND_ELEMENT, "leakyhost")

    def test_thread_leak_reported_at_shutdown(self, monkeypatch):
        stop_ev = threading.Event()

        class ThreadLeaker(HostElement):
            """start() spawns a service thread; stop() forgets it."""

            FACTORY_NAME = "threadleaker"

            def negotiate(self, in_specs):
                return [in_specs[0]]

            def start(self):
                t = threading.Thread(
                    target=self._loop, name="leaky-service", daemon=True
                )
                t.start()

            def _loop(self):
                while not stop_ev.wait(0.02):
                    pass

            def process(self, frame):
                return frame

        registry.register(
            registry.KIND_ELEMENT, "threadleaker", ThreadLeaker
        )
        try:
            monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
            p = parse_pipeline(
                "tensorsrc dimensions=4 num-frames=3 ! threadleaker ! "
                "tensor_sink name=out"
            )
            ex = p.run(timeout=60)
            assert "leaky-service" in ex.leaked_threads
            assert "NNS-S004" in ex.sanitizer.codes
        finally:
            stop_ev.set()
            registry.unregister(registry.KIND_ELEMENT, "threadleaker")

    def test_watchdog_thread_joined_on_stop(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_WATCHDOG_TIMEOUT_MS", "5000")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=5 ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert ex._watchdog is not None
        assert not ex._watchdog.is_alive()
        assert "nns-watchdog" not in ex.leaked_threads

    def test_lock_order_cycle_detected(self):
        cycles = []
        g = LockOrderGraph(on_cycle=cycles.append)
        la, lb = "lock-A", "lock-B"

        def order(first, second):
            g.acquired(first)
            g.acquired(second)
            g.released(second)
            g.released(first)

        t1 = threading.Thread(target=order, args=(la, lb))
        t1.start()
        t1.join(timeout=5)
        assert cycles == []  # one order alone is fine
        t2 = threading.Thread(target=order, args=(lb, la))
        t2.start()
        t2.join(timeout=5)
        assert len(cycles) == 1 and "lock-A" in cycles[0]

    def test_executor_lock_cycle_lands_in_report(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=2 ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        san = ex.sanitizer
        a, b = san.lock("test-A"), san.lock("test-B")

        def order(first, second):
            with first:
                with second:
                    pass

        t1 = threading.Thread(target=order, args=(a, b))
        t1.start()
        t1.join(timeout=5)
        t2 = threading.Thread(target=order, args=(b, a))
        t2.start()
        t2.join(timeout=5)
        assert san.codes == ["NNS-S003"], [
            str(d) for d in san.findings()
        ]

    def test_poison_values_are_obviously_wrong(self):
        f = poison_like(np.zeros((2, 3), np.float32))
        assert f.shape == (2, 3) and np.isnan(f).all()
        i = poison_like(np.zeros((4,), np.int32))
        assert (i == np.iinfo(np.int32).max).all()

    def test_batched_pad_poison_does_not_leak_into_frames(
        self, monkeypatch
    ):
        # 5 frames, max-batch=4: the bucket padding (poisoned under the
        # sanitizer) must never reach a delivered frame
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=5 pattern=counter ! "
            "tensor_transform mode=typecast option=float32 "
            "batching=true max-batch=4 batch-timeout-ms=2 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=120)
        assert ex.sanitizer.codes == []
        vals = [np.asarray(f.tensors[0]) for f in p["out"].frames]
        assert len(vals) == 5
        assert all(np.isfinite(v).all() for v in vals)
