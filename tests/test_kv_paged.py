"""nns-kv paged KV-cache tests (nnstreamer_tpu/kv/, docs/llm-serving.md).

The load-bearing invariant: paged decode is a *layout*, not a different
decoder — gather → identical batched step → scatter must produce
byte-identical token streams to the contiguous slot layout on the same
request trace (greedy and sampling, fp and int8). On top of that: the
BlockPool's refcount/prefix-index/copy-on-write discipline, chunked
prefill's TTFT bound, preemption→re-prefill, block-table
snapshot/restore, and the NNS-W115 lint.

Budget note: slots are isolated by construction (a request's stream
never depends on batch composition — the continuous-batching invariant
test_serving pins), so ONE module-scoped slot reference and ONE paged
batcher serve most tests here; per-test batchers exist only where the
configuration itself differs (int8, tight pool, restore target). Keeps
the compile count — the file's real cost — low. The widest
parity-matrix cells (long-prompt chunked, eviction, sharing
degradation, snapshot/restore) are marked `slow` under the tier-1
DOTS budget; the fp greedy+sampling and int8 bitwise cells stay
tier-1, and tests/test_kv_block_attn.py pins the block-native
formulation these now run by default against the gather oracle.
"""

import jax
import numpy as np
import pytest

from nnstreamer_tpu.kv.blocks import BlockPool, NoBlocksError
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

N_HEADS = 4


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(7), vocab=257, d_model=64, n_heads=N_HEADS,
        n_layers=2,
    )


@pytest.fixture(scope="module")
def obs_reg():
    from nnstreamer_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.enable()
    yield reg
    obs_metrics.disable()


@pytest.fixture(scope="module")
def slot_ref(params):
    """Shared slot-layout reference, drained per-token (one compiled
    step program for the whole module)."""
    return ContinuousBatcher(params, N_HEADS, n_slots=4, max_len=96,
                             prompt_len=16)


@pytest.fixture(scope="module")
def paged_cb(params, obs_reg):
    """Shared paged batcher (obs registry active, so the SLO metrics
    test can read what the other tests emitted)."""
    return _mk(params)


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(1, 257, (n,)).astype(np.int32)


def _rep_prompt(n, seed, period=6):
    base = np.random.default_rng(seed).integers(1, 257, (period,))
    return np.tile(base, -(-n // period))[:n].astype(np.int32)


def _mk(params, paged=True, **kw):
    base = dict(n_slots=4, max_len=96, prompt_len=16)
    if paged:
        base.update(kv_layout="paged", block_size=16)
    base.update(kw)
    return ContinuousBatcher(params, N_HEADS, **base)


def _drain(cb, rids, pump=0):
    while any(cb.result(r) is None for r in rids):
        cb.step_pump(pump) if pump else cb.step()
    return [cb.result(r) for r in rids]


def _ref_streams(slot_ref, subs):
    rids = [slot_ref.submit(p, n, **kw) for p, n, kw in subs]
    return _drain(slot_ref, rids)


# -- BlockPool (host accounting, no device work) ---------------------------

def test_pool_alloc_free_refcount_and_exhaustion():
    pool = BlockPool(4, 16)
    a = pool.alloc(3)
    assert pool.in_use() == 3 and len(set(a)) == 3 and 0 not in a
    pool.adopt(a[0])  # second reference
    pool.free([a[0]])
    assert pool.in_use() == 3  # still referenced once
    pool.free(a)
    assert pool.in_use() == 0
    pool.alloc(4)
    with pytest.raises(NoBlocksError):
        pool.alloc(1)
    with pytest.raises(ValueError):
        pool.free([a[1], a[1], a[1]])  # more frees than references


def test_pool_prefix_index_full_and_partial_match():
    pool = BlockPool(8, 4)
    toks = np.arange(10, dtype=np.int32)  # 2 full blocks + partial(2)
    blocks = pool.alloc(3)
    pool.register(toks, blocks)
    m = pool.match(toks)
    assert m.full == blocks[:2] and m.partial_block == blocks[2]
    assert m.n_partial == 2 and m.n_tokens == 10
    # longer query: partial entry is a prefix of the remainder
    m2 = pool.match(np.arange(16, dtype=np.int32))
    assert m2.n_tokens == 10 and m2.partial_block == blocks[2]
    # diverging content stops the walk with verification, not hashes
    bad = toks.copy()
    bad[5] = 99
    m3 = pool.match(bad)
    assert m3.full == blocks[:1] and m3.n_tokens == 4


def test_pool_cached_tier_reclaim_unindexes():
    pool = BlockPool(2, 4)
    toks = np.arange(8, dtype=np.int32)
    blocks = pool.alloc(2)
    pool.register(toks, blocks)
    pool.free(blocks)  # refcount 0, but indexed → cached, still matchable
    assert pool.match(toks).n_tokens == 8
    got = pool.alloc(2)  # reclaims LRU-cached blocks
    assert sorted(got) == sorted(blocks)
    assert pool.match(toks).n_tokens == 0  # reclaimed = unindexed
    assert pool.snapshot()["index"] == []


def test_pool_cow_counts_and_snapshot_roundtrip():
    pool = BlockPool(6, 4)
    toks = np.arange(6, dtype=np.int32)
    blocks = pool.alloc(2)
    pool.register(toks, blocks)
    b = pool.cow()
    assert b not in blocks and pool.cow_copies == 1
    snap = pool.snapshot()
    pool2 = BlockPool(6, 4)
    pool2.restore(snap)
    assert pool2.match(toks).n_tokens == 6
    assert pool2.in_use() == pool.in_use()
    assert pool2.cow_copies == 1


# -- bitwise parity with the contiguous slot layout ------------------------

def test_paged_parity_greedy_and_sampling(slot_ref, paged_cb):
    """One batch mixing greedy and sampled requests: paged pumps equal
    slot per-token steps byte for byte."""
    subs = [
        (_prompt(5, 1), 8, {}),
        (_prompt(9, 2), 7, {}),
        (_prompt(6, 3), 8, dict(temperature=0.8, top_k=40, seed=5)),
    ]
    rb = [paged_cb.submit(p, n, **kw) for p, n, kw in subs]
    assert _ref_streams(slot_ref, subs) == _drain(paged_cb, rb, pump=4)


@pytest.mark.slow
def test_paged_long_prompt_chunked_prefill_parity(slot_ref, paged_cb):
    """A prompt spanning several prefill buckets admits chunk by chunk
    and still yields the slot layout's exact stream."""
    p = _rep_prompt(60, 12)
    rb = paged_cb.submit(p, 8)
    assert _ref_streams(slot_ref, [(p, 8, {})]) == _drain(
        paged_cb, [rb], pump=4
    )


def test_paged_spec_pump_parity(slot_ref, paged_cb):
    """Device n-gram speculation over the gathered view: streams equal
    the slot layout's plain steps, and proposals actually land."""
    prompts = [_rep_prompt(12, 50 + s, period=4) for s in range(3)]
    acc0 = paged_cb.stats()["spec_accepted_tokens"]
    rb = [paged_cb.submit(p, 10) for p in prompts]
    while any(paged_cb.result(r) is None for r in rb):
        paged_cb.spec_pump(rounds=2, k=3, ngram=1)
    assert _ref_streams(slot_ref, [(p, 10, {}) for p in prompts]) == [
        paged_cb.result(r) for r in rb
    ]
    assert paged_cb.stats()["spec_accepted_tokens"] > acc0


def test_paged_int8_parity(params):
    a = _mk(params, paged=False, cache_dtype="int8", n_slots=2)
    b = _mk(params, cache_dtype="int8", n_slots=2)
    p = _prompt(6, 41)
    ra, rb = a.submit(p, 7), b.submit(p, 7)
    assert _drain(a, [ra], pump=4) == _drain(b, [rb], pump=4)


# -- prefix sharing / copy-on-write ----------------------------------------

def test_prefix_share_refcount_and_stream_parity(slot_ref, paged_cb):
    """Identical leading blocks are adopted (prefix hits), a mid-block
    extension copies-on-write, and neither sharer's stream changes
    (the unshared reference is the slot layout — parity already pinned
    above, so equality here isolates the SHARING as a no-op on
    streams)."""
    st0 = paged_cb.stats()
    p1 = _rep_prompt(24, 5, period=24)            # 1 full + 1 partial
    p2 = np.concatenate([p1, _rep_prompt(8, 2)])  # extends p1 mid-block
    r1 = paged_cb.submit(p1, 4)
    _drain(paged_cb, [r1], pump=4)
    r2 = paged_cb.submit(p2, 4)
    _drain(paged_cb, [r2], pump=4)
    st = paged_cb.stats()
    assert st["kv_prefix_hits"] >= st0["kv_prefix_hits"] + 2
    assert st["kv_cow_copies"] >= st0["kv_cow_copies"] + 1
    assert st["kv_prefix_hit_tokens"] >= st0["kv_prefix_hit_tokens"] + 16
    ref = _ref_streams(slot_ref, [(p1, 4, {}), (p2, 4, {})])
    assert [paged_cb.result(r1), paged_cb.result(r2)] == ref


def test_register_prefix_paged_matches_slot(slot_ref, paged_cb):
    sysp = _rep_prompt(32, 9, period=32)
    pida = slot_ref.register_prefix(sysp)
    pidb = paged_cb.register_prefix(sysp)
    hits0 = paged_cb.stats()["kv_prefix_hits"]
    user = _prompt(7, 3)
    ra = slot_ref.submit(user, 6, prefix=pida)
    rb = paged_cb.submit(user, 6, prefix=pidb)
    assert _drain(slot_ref, [ra]) == _drain(paged_cb, [rb], pump=4)
    assert paged_cb.stats()["kv_prefix_hits"] >= hits0 + 2
    assert paged_cb.unregister_prefix(pidb)
    assert not paged_cb.unregister_prefix(pidb)
    slot_ref.unregister_prefix(pida)


# -- chunked prefill TTFT bound --------------------------------------------

def test_chunked_prefill_interleaves_decode(paged_cb):
    """While a 4-bucket prompt prefills, an already-decoding request
    keeps emitting EVERY pump — the decode stall is bounded by one
    chunk, not by the whole foreign prefill."""
    ra = paged_cb.submit(_prompt(6, 11), 20)
    for _ in range(3):
        paged_cb.step_pump(1)
    rb = paged_cb.submit(_rep_prompt(60, 13), 4)  # 60 tokens = 4 buckets
    pumps_while_prefilling = 0
    while paged_cb.stats()["kv_prefill_queue"] > 0:
        before = len(paged_cb.partials([ra])[ra])
        out = paged_cb.step_pump(1)
        if paged_cb.result(ra) is None:
            # the decoding request advanced in the SAME pump that
            # carried a foreign prefill chunk
            assert len(paged_cb.partials([ra])[ra]) > before, out
            pumps_while_prefilling += 1
    assert pumps_while_prefilling >= 2  # the long prompt really chunked
    _drain(paged_cb, [ra, rb], pump=4)


# -- preemption / eviction → re-prefill ------------------------------------

@pytest.mark.slow
def test_eviction_reprefill_parity(params, slot_ref):
    """A pool too small for three full streams preempts and re-prefills
    — and every stream still equals the slot reference byte for byte."""
    tight = _mk(params, n_slots=3, kv_blocks=9)
    prompts = [_rep_prompt(20, 70 + s) for s in range(3)]
    rt = [tight.submit(p, 40) for p in prompts]
    got = _drain(tight, rt, pump=4)
    assert got == _ref_streams(slot_ref, [(p, 40, {}) for p in prompts])
    assert tight.stats()["kv_preemptions"] > 0
    assert tight.stats()["kv_blocks_in_use"] == 0  # all freed at finish


@pytest.mark.slow
def test_sharing_degradation_unblocks_queue(params, slot_ref):
    """A prefix hit whose copy-on-write block makes the job UNaffordable
    (adopting the partial pulls a block from the pool AND still needs a
    fresh copy) must degrade to unshared staging and complete — and must
    NOT re-adopt the released prefix on the restart, which would restore
    the exact pre-degrade state and livelock the queue head."""
    b = _mk(params, n_slots=2, kv_blocks=6)  # exactly one max_len stream
    pa = _rep_prompt(72, 7)                  # 4 full blocks + partial(8)
    _drain(b, [b.submit(pa, 2)], pump=4)     # ...then cached, indexed
    pb = np.concatenate([pa, _rep_prompt(23, 8)])  # 95 tokens, 6 blocks
    rb = b.submit(pb, 1)
    for _ in range(60):
        b.step_pump(2)
        if b.result(rb) is not None:
            break
    assert b.result(rb) is not None, "degraded admission never completed"
    assert b.result(rb) == _ref_streams(slot_ref, [(pb, 1, {})])[0]


# -- snapshot / restore -----------------------------------------------------

@pytest.mark.slow
def test_snapshot_restore_block_tables(params, paged_cb):
    """Mid-decode snapshot → fresh batcher → restore: identical
    continuation, pool accounting included (PR-7 warm-restart
    discipline at the batcher level)."""
    prompts = [_rep_prompt(20, 80 + s) for s in range(3)]
    rids = [paged_cb.submit(p, 10) for p in prompts]
    while paged_cb.stats()["kv_prefill_queue"] > 0:  # admit everyone
        paged_cb.step_pump(1)
    paged_cb.step_pump(4)  # some mid-stream decode state
    snap = paged_cb.snapshot()
    assert snap["layout"] == "paged" and "pool" in snap
    ref = {r: t for r, t in zip(rids, _drain(paged_cb, rids, pump=4))}
    b2 = _mk(params)
    b2.restore(snap)
    assert {r: t for r, t in zip(rids, _drain(b2, rids, pump=4))} == ref
    # the restored pool kept the prefix index: resubmitting an already-
    # seen prompt hits it
    hits0 = b2.stats()["kv_prefix_hits"]
    _drain(b2, [b2.submit(prompts[0], 4)], pump=4)
    assert b2.stats()["kv_prefix_hits"] > hits0


# -- configuration / guards ------------------------------------------------

def test_paged_rejects_unsupported_combinations(params):
    with pytest.raises(ValueError, match="windowed"):
        ContinuousBatcher(params, N_HEADS, max_len=32, prompt_len=16,
                          windowed=True, kv_layout="paged")
    with pytest.raises(ValueError, match="block_size"):
        ContinuousBatcher(params, N_HEADS, max_len=96, prompt_len=16,
                          kv_layout="paged", block_size=7)
    with pytest.raises(ValueError, match="kv_blocks"):
        ContinuousBatcher(params, N_HEADS, max_len=96, prompt_len=16,
                          kv_layout="paged", block_size=16, kv_blocks=2)
    with pytest.raises(ValueError, match="kv_layout"):
        ContinuousBatcher(params, N_HEADS, kv_layout="virtual")


def test_w115_oversized_static_kv_cache_both_ways():
    from nnstreamer_tpu.analysis import lint

    head = ("tensorsrc dimensions=4 types=int32 num-frames=1 ! "
            "tensor_llm_serversink id=91 n-slots=64 max-len=2048 ")
    r_bad = lint(head + "kv-memory-bound=64M")
    assert "NNS-W115" in r_bad.codes
    assert r_bad.exit_code == 1  # warning, not error
    # paged layout resolves it; no declared bound stays silent
    assert "NNS-W115" not in lint(
        head + "kv-memory-bound=64M kv-layout=paged"
    ).codes
    assert "NNS-W115" not in lint(head.rstrip()).codes
    # a bound the static cache fits under is fine too
    assert "NNS-W115" not in lint(head + "kv-memory-bound=64G").codes


def test_requests_view_and_nns_top_render(paged_cb):
    """The SLO ledger feeds requests() and the nns-top --requests
    table (state, blocks, TTFT/TPOT, deadline)."""
    from nnstreamer_tpu.obs.nns_top import render_requests

    rid = paged_cb.submit(_prompt(6, 33), 4, deadline_s=60.0)
    _drain(paged_cb, [rid], pump=4)
    row = paged_cb.requests()[rid]
    assert row["state"] == "done" and row["tokens"] == 4
    assert row["ttft_ms"] is not None and row["tpot_ms"] is not None
    assert row["deadline_s"] is not None
    snap = {"nodes": {"llmsrv": {
        "serving_requests": {str(rid): row},
        "serving_kv_blocks_in_use": 0,
        "serving_kv_blocks": 24,
        "serving_kv_prefix_hits": 3,
        "serving_kv_attn": "block",
        "serving_kv_migrations_out": 2,
        "serving_kv_migrations_in": 1,
        "serving_request_resumes": 1,
    }}}
    out = render_requests(snap)
    assert str(rid) in out and "done" in out and "prefix-hits=3" in out
    # migration & recovery footer (docs/llm-serving.md)
    assert "migrations=2out/1in" in out and "resumes=1" in out
    # the footer names the active decode formulation (block-native by
    # default; gather would additionally show its dispatch count)
    assert "kv-attn=block" in out
    assert "TTFT" in out.splitlines()[0]
    assert "LLM serving" in render_requests({"nodes": {}})


def test_paged_slo_metrics_emit_through_obs(obs_reg, paged_cb):
    """The four cataloged nns_kv_*/nns_request_* metrics were emitted
    by the module's shared batcher (constructed with the registry
    active) as the tests above exercised it."""
    assert obs_reg.find("nns_kv_blocks_in_use") is not None
    hits = obs_reg.find("nns_kv_prefix_hits_total")
    assert hits is not None and hits.value > 0
    assert obs_reg.find("nns_request_ttft_ms").count >= 2
    assert obs_reg.find("nns_request_tpot_ms").count >= 2


@pytest.mark.slow
def test_many_request_churn_soak(params):
    """Churn soak: 24 requests of mixed shapes through a tight pool
    with a shared system prompt — every stream equals its solo slot-
    layout reference, the pool balances to zero, and sharing actually
    happened."""
    rng = np.random.default_rng(0)
    sysp = _rep_prompt(16, 99, period=16)
    b = ContinuousBatcher(params, N_HEADS, n_slots=6, max_len=96,
                          prompt_len=16, kv_layout="paged",
                          block_size=16, kv_blocks=24)
    ref = _mk(params, paged=False, n_slots=1)
    expects = {}
    pending = []
    for i in range(24):
        user = _prompt(int(rng.integers(2, 20)), 200 + i)
        prompt = np.concatenate([sysp, user]) if i % 2 else user
        budget = int(rng.integers(2, 14))
        rid = b.submit(prompt, budget)
        if rid is None:
            b.step_pump(int(rng.integers(1, 6)))
            rid = b.submit(prompt, budget)
        if rid is None:
            continue
        pending.append(rid)
        r = ref.submit(prompt, budget)
        expects[rid] = _drain(ref, [r])[0]
        if i % 3 == 0:
            b.step_pump(int(rng.integers(1, 8)))
    while any(b.result(r) is None for r in pending):
        b.step_pump(4)
    assert {r: b.result(r) for r in pending} == expects
    st = b.stats()
    assert st["kv_blocks_in_use"] == 0
    assert st["kv_prefix_hits"] > 0
