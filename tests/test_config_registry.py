"""Config layering and subplugin registry tests.

Mirrors reference coverage of nnstreamer_conf (env > ini > default) and
nnstreamer_subplugin register/get.
"""

import os
import textwrap

import pytest

from nnstreamer_tpu import registry
from nnstreamer_tpu.config import Config, conf, reload_conf


class TestConfig:
    def test_defaults(self):
        c = Config(ini_path="/nonexistent")
        assert c.get("edge", "default_port") == "3000"
        assert c.get_int("edge", "timeout_sec") == 10

    def test_ini_overrides_default(self, tmp_path):
        ini = tmp_path / "conf.ini"
        ini.write_text("[edge]\ndefault_port = 4000\n")
        c = Config(ini_path=str(ini))
        assert c.get_int("edge", "default_port") == 4000

    def test_env_overrides_ini(self, tmp_path, monkeypatch):
        ini = tmp_path / "conf.ini"
        ini.write_text("[edge]\ndefault_port = 4000\n")
        monkeypatch.setenv("NNS_TPU_EDGE_DEFAULT_PORT", "5000")
        c = Config(ini_path=str(ini))
        assert c.get_int("edge", "default_port") == 5000

    def test_env_disabled(self, tmp_path, monkeypatch):
        ini = tmp_path / "conf.ini"
        ini.write_text("[common]\nenable_envvar = false\n[edge]\ndefault_port = 4000\n")
        monkeypatch.setenv("NNS_TPU_EDGE_DEFAULT_PORT", "5000")
        c = Config(ini_path=str(ini))
        assert c.get_int("edge", "default_port") == 4000

    def test_bool_and_list(self, tmp_path):
        ini = tmp_path / "conf.ini"
        ini.write_text("[jax]\nflagx = yes\nitems = a, b ,c\n")
        c = Config(ini_path=str(ini))
        assert c.get_bool("jax", "flagx") is True
        assert c.get_list("jax", "items") == ["a", "b", "c"]


class TestRegistry:
    def test_register_get_unregister(self):
        sentinel = object()
        registry.register("filter", "TmpTest", sentinel)
        assert registry.get("filter", "tmptest") is sentinel
        with pytest.raises(ValueError):
            registry.register("filter", "tmptest", object())
        assert registry.unregister("filter", "tmptest")
        with pytest.raises(KeyError):
            registry.get("filter", "tmptest_gone_xyz")

    def test_bad_kind(self):
        with pytest.raises(ValueError):
            registry.register("nope", "x", object())

    def test_search_path_loading(self, tmp_path, monkeypatch):
        plugin = tmp_path / "nns_filter_fromdisk.py"
        plugin.write_text(
            textwrap.dedent(
                """
                from nnstreamer_tpu import registry
                registry.register("filter", "fromdisk", "DISK_IMPL", replace=True)
                """
            )
        )
        monkeypatch.setenv("NNS_TPU_FILTER_PLUGIN_PATHS", str(tmp_path))
        reload_conf("/nonexistent")
        try:
            assert registry.get("filter", "fromdisk") == "DISK_IMPL"
        finally:
            registry.unregister("filter", "fromdisk")
            reload_conf()

    def test_builtin_backends_available(self):
        names = registry.available("filter")
        assert "passthrough" in names
        assert "jax" in names


class TestElementRestriction:
    def test_restricted_elements_enforced(self, monkeypatch):
        import nnstreamer_tpu.config as config_mod
        from nnstreamer_tpu import registry

        monkeypatch.setenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS",
                           "videotestsrc,tensor_sink")
        config_mod.reload_conf()
        try:
            assert registry.get(registry.KIND_ELEMENT, "videotestsrc")
            with pytest.raises(KeyError, match="restricted"):
                registry.get(registry.KIND_ELEMENT, "tensor_converter")
        finally:
            monkeypatch.delenv("NNS_TPU_COMMON_RESTRICTED_ELEMENTS")
            config_mod.reload_conf()

    def test_empty_restriction_allows_all(self):
        from nnstreamer_tpu import registry

        assert registry.get(registry.KIND_ELEMENT, "tensor_converter")
