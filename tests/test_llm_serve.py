"""tensor_llm_serversink/src element tests: continuous-batching LLM
serving through the pipeline surface (elements/llm_serve.py).

The invariant chain: prompts in, per-request generations out with meta
preserved, tokens byte-identical to decode.generate run alone."""

import threading

import jax
import numpy as np
import pytest

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.pipeline.parse import parse_pipeline

MODEL_OPTS = "vocab:211,d_model:32,n_heads:2,n_layers:2,seed:5"
N_HEADS = 2


def _params():
    return tfm.init_params(
        jax.random.PRNGKey(5), vocab=211, d_model=32, n_heads=2, n_layers=2
    )


def _alone(prompt, n_new):
    toks = dec.generate(
        _params(), np.asarray(prompt, np.int32)[None, :], N_HEADS, n_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


def test_llm_serve_pipeline_roundtrip():
    """appsrc prompts → llm server pair → appsink generations. Meta rides
    through; tokens match solo generation for every request."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    rng = np.random.default_rng(0)
    prompts = {
        f"req{i}": rng.integers(1, 211, (4 + 3 * i,)).astype(np.int32)
        for i in range(3)
    }

    src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
    sink = LlmServerSink(
        **{"id": "t0", "model": "zoo:transformer_lm", "custom": MODEL_OPTS,
           "n-slots": 2, "max-len": 64, "prompt-len": 16,
           "max-new-tokens": 6}
    )
    out_src = LlmServerSrc(**{"id": "t0"})
    out_sink = AppSink()
    p = Pipeline().chain(src, sink)
    p.chain(out_src, out_sink)
    p.start()
    try:
        for name, prompt in prompts.items():
            src.push(Frame((prompt,), meta={"req": name}))
        src.end_of_stream()
        results = {}
        while len(results) < len(prompts):
            f = out_sink.pop(timeout=120)
            assert f is not None, "serving pipeline drained early"
            results[f.meta["req"]] = [int(t) for t in np.asarray(f.tensors[0])[0]]
    finally:
        p.stop()
    for name, prompt in prompts.items():
        assert results[name] == _alone(prompt, 6), f"{name} diverged"


def test_llm_serve_paged_kv_layout_matches_solo():
    """kv-layout=paged through the element surface (docs/llm-serving.md):
    generations stay byte-identical to solo decode, and the batcher's
    paged/SLO stats surface through serving_stats (requests view +
    kv_* counters for nns-top --requests)."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    rng = np.random.default_rng(1)
    prompts = {
        f"req{i}": rng.integers(1, 211, (5 + 2 * i,)).astype(np.int32)
        for i in range(3)
    }
    src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
    sink = LlmServerSink(
        **{"id": "pg0", "model": "zoo:transformer_lm",
           "custom": MODEL_OPTS, "n-slots": 4, "max-len": 64,
           "prompt-len": 16, "max-new-tokens": 5, "pump": 4,
           "kv-layout": "paged", "block-size": 16, "kv-blocks": 12}
    )
    out_src = LlmServerSrc(**{"id": "pg0"})
    out_sink = AppSink()
    p = Pipeline().chain(src, sink)
    p.chain(out_src, out_sink)
    p.start()
    try:
        for name, prompt in prompts.items():
            src.push(Frame((prompt,), meta={"req": name,
                                            "deadline_ms": 60000}))
        src.end_of_stream()
        results = {}
        while len(results) < len(prompts):
            f = out_sink.pop(timeout=120)
            assert f is not None, "serving pipeline drained early"
            results[f.meta["req"]] = [
                int(t) for t in np.asarray(f.tensors[0])[0]
            ]
        st = out_src.serving_stats()
    finally:
        p.stop()
    for name, prompt in prompts.items():
        assert results[name] == _alone(prompt, 5), f"{name} diverged"
    assert st["kv_blocks"] == 12 and st["kv_blocks_in_use"] == 0
    reqs = st["requests"]
    assert len(reqs) == 3
    assert all(r["state"] == "done" for r in reqs.values())
    assert all(r.get("deadline_s") is not None for r in reqs.values())


def test_llm_serve_cli_parses():
    """Both elements resolve from a pipeline description (the reference's
    pairing-by-id pattern, like tensor_repo)."""
    p = parse_pipeline(
        "tensorsrc dimensions=4:1 types=int32 num-frames=2 pattern=ones ! "
        f'tensor_llm_serversink id=c1 custom="{MODEL_OPTS}" '
        "max-new-tokens=3 n-slots=2 max-len=32 prompt-len=8 "
        "tensor_llm_serversrc id=c1 ! tensor_sink name=out"
    )
    from nnstreamer_tpu import registry

    sink = p["out"]
    p.run(timeout=300)
    assert sink.rendered == 2
    for f in sink.frames:
        assert f.tensors[0].shape == (1, 3)


def test_src_without_sink_errors():
    from nnstreamer_tpu.elements.base import ElementError
    from nnstreamer_tpu.elements.llm_serve import LlmServerSrc

    src = LlmServerSrc(**{"id": "nosuch"})
    with pytest.raises(ElementError, match="no serversink"):
        src.generate()


def test_stop_releases_server_and_id_is_reusable():
    """Stopping a pipeline (drained or not) removes the server from the
    global table; a later pipeline reusing the id gets a fresh server
    with its own props."""
    from nnstreamer_tpu.elements import llm_serve

    for run in range(2):  # second run reuses id=r0
        p = parse_pipeline(
            "tensorsrc dimensions=4:1 types=int32 num-frames=1 pattern=ones"
            f' ! tensor_llm_serversink id=r0 custom="{MODEL_OPTS}" '
            "max-new-tokens=2 n-slots=1 max-len=16 prompt-len=8 "
            "tensor_llm_serversrc id=r0 ! tensor_sink name=out"
        )
        p.run(timeout=120)
        assert p["out"].rendered == 1
        assert "r0" not in llm_serve._table, f"run {run}: server leaked"


def test_sampling_meta_rides_through():
    """temperature/seed in frame meta select sampled generation; same
    seed → same tokens across two server runs."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    prompt = np.random.default_rng(50).integers(1, 211, (6,)).astype(np.int32)
    outs = []
    for run in range(2):
        src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
        sink = LlmServerSink(
            **{"id": f"s{run}", "custom": MODEL_OPTS, "n-slots": 1,
               "max-len": 48, "prompt-len": 16, "max-new-tokens": 8}
        )
        out_sink = AppSink()
        p = Pipeline().chain(src, sink)
        p.chain(LlmServerSrc(**{"id": f"s{run}"}), out_sink)
        p.start()
        try:
            src.push(Frame((prompt,), meta={"temperature": 0.9, "seed": 11}))
            src.end_of_stream()
            f = out_sink.pop(timeout=120)
            outs.append([int(t) for t in np.asarray(f.tensors[0])[0]])
        finally:
            p.stop()
    assert outs[0] == outs[1]


def test_serving_stats_in_cli_stats():
    """--stats surfaces the batcher's token counters under the source
    node (executor.stats serving_ prefix)."""
    import json
    import subprocess
    import sys

    r = subprocess.run(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         "tensorsrc dimensions=4:1 types=int32 num-frames=2 pattern=ones ! "
         f'tensor_llm_serversink id=cs1 custom="{MODEL_OPTS}" '
         "max-new-tokens=3 n-slots=2 max-len=32 prompt-len=8 "
         "tensor_llm_serversrc id=cs1 ! tensor_sink",
         "--stats", "-q"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-500:]
    stats = json.loads(r.stdout)
    src = next(v for k, v in stats.items() if "serversrc" in k)
    assert src["serving_tokens_emitted"] == 4  # 2 reqs × (3-1 stepped)
    assert src["serving_steps"] >= 2


def test_token_streaming_mode():
    """serversrc stream=true: one frame per NEW token (stream/done/
    token_index meta + request meta), then a done frame with the full
    generation; the streamed tokens concatenate to exactly the done
    frame's tokens, which match solo generation."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    prompt = np.asarray([5, 9, 2, 44], np.int32)
    src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
    sink = LlmServerSink(
        **{"id": "stream0", "model": "zoo:transformer_lm",
           "custom": MODEL_OPTS, "n-slots": 1, "max-len": 32,
           "prompt-len": 8, "max-new-tokens": 5}
    )
    out_src = LlmServerSrc(**{"id": "stream0", "stream": "true"})  # src-side
    out_sink = AppSink()
    p = Pipeline().chain(src, sink)
    p.chain(out_src, out_sink)
    p.start()
    try:
        src.push(Frame((prompt,), meta={"req": "s"}))
        src.end_of_stream()
        streamed, done = [], None
        while done is None:
            f = out_sink.pop(timeout=120)
            assert f is not None, "stream drained early"
            assert f.meta["stream"] is True and f.meta["req"] == "s"
            toks = [int(t) for t in np.asarray(f.tensors[0])[0]]
            if f.meta["done"]:
                done = toks
            else:
                assert f.meta["token_index"] == len(streamed)
                assert len(toks) == 1
                streamed.append(toks[0])
        assert streamed == done
        assert len(done) == 5
    finally:
        p.stop()


def test_stream_prop_on_sink_covers_early_finishers():
    """stream=true on the SINK configures streaming at server creation —
    requests that finish during the sink's backpressure pumps (before any
    src exists) still get per-token + done framing."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    sink = LlmServerSink(
        **{"id": "stream1", "model": "zoo:transformer_lm",
           "custom": MODEL_OPTS, "n-slots": 1, "max-len": 32,
           "prompt-len": 8, "max-new-tokens": 3, "stream": "true"}
    )
    sink.negotiate([TensorsSpec(format=TensorFormat.FLEXIBLE)])
    srv = sink._server
    assert srv.stream is True
    sink.render(Frame((np.asarray([7, 8, 9], np.int32),), meta={"req": "x"}))
    # drive to completion with NO src attached (the early-finisher case)
    while not srv._out or not any(m.get("done") for _, m in list(srv._out)):
        srv.pump()
    frames = list(srv._out)
    assert all(m.get("stream") is True for _, m in frames)
    done = [t for t, m in frames if m.get("done")]
    streamed = [t[0] for t, m in frames if not m.get("done")]
    assert len(done) == 1 and streamed == done[0]
    sink.stop()
    src_el = LlmServerSrc(**{"id": "stream1"})
    src_el.stop()


def test_speculate_prop_matches_plain_serving():
    """tensor_llm_serversink speculate=4 pumps via spec_step — same
    tokens as the non-speculative pipeline (exact greedy equivalence),
    with spec rounds visible in the serving stats."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    prompt = np.asarray([3, 4, 3, 4, 3, 4, 3], np.int32)

    def run(srv_id, extra):
        src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
        sink = LlmServerSink(
            **{"id": srv_id, "model": "zoo:transformer_lm",
               "custom": MODEL_OPTS, "n-slots": 1, "max-len": 64,
               "prompt-len": 16, "max-new-tokens": 8, **extra}
        )
        out_src = LlmServerSrc(**{"id": srv_id})
        out_sink = AppSink()
        p = Pipeline().chain(src, sink)
        p.chain(out_src, out_sink)
        p.start()
        try:
            src.push(Frame((prompt,), meta={"req": "x"}))
            src.end_of_stream()
            f = out_sink.pop(timeout=120)
            stats = out_src.serving_stats() or {}
            return [int(t) for t in np.asarray(f.tensors[0])[0]], stats
        finally:
            p.stop()

    plain, _ = run("specA", {})
    spec, stats = run("specB", {"speculate": 4})
    assert spec == plain
    assert stats.get("spec_rounds", 0) > 0


def test_speculate_model_prop_draft_speculation():
    """speculate-model=zoo:... plugs a draft model into the speculate=k
    pump (draft_-prefixed keys in the custom dict configure it) — same
    tokens as plain serving, with spec rounds in the stats."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    prompt = np.asarray([3, 4, 3, 4, 3, 4, 3], np.int32)
    draft_opts = (
        MODEL_OPTS
        + ",draft_d_model:32,draft_n_layers:1,draft_n_heads:2,draft_seed:9"
    )

    def run(srv_id, extra):
        src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
        sink = LlmServerSink(
            **{"id": srv_id, "model": "zoo:transformer_lm",
               "custom": draft_opts, "n-slots": 1, "max-len": 64,
               "prompt-len": 16, "max-new-tokens": 8, **extra}
        )
        out_src = LlmServerSrc(**{"id": srv_id})
        out_sink = AppSink()
        p = Pipeline().chain(src, sink)
        p.chain(out_src, out_sink)
        p.start()
        try:
            src.push(Frame((prompt,), meta={"req": "x"}))
            src.end_of_stream()
            f = out_sink.pop(timeout=120)
            stats = out_src.serving_stats() or {}
            return [int(t) for t in np.asarray(f.tensors[0])[0]], stats
        finally:
            p.stop()

    plain, _ = run("draftA", {})
    spec, stats = run(
        "draftB",
        {"speculate": 4, "speculate-model": "zoo:transformer_lm"},
    )
    assert spec == plain
    assert stats.get("spec_rounds", 0) > 0


def test_speculate_auto_adapts_and_matches_plain():
    """speculate=auto: the pump picks its own chunk width from the
    measured acceptance EMA — same tokens as plain serving, k stays in
    the documented [2, 8] band."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    prompt = np.asarray([3, 4, 3, 4, 3, 4, 3], np.int32)

    def run(srv_id, extra):
        src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
        sink = LlmServerSink(
            **{"id": srv_id, "model": "zoo:transformer_lm",
               "custom": MODEL_OPTS, "n-slots": 1, "max-len": 64,
               "prompt-len": 16, "max-new-tokens": 8, **extra}
        )
        out_src = LlmServerSrc(**{"id": srv_id})
        out_sink = AppSink()
        p = Pipeline().chain(src, sink)
        p.chain(out_src, out_sink)
        p.start()
        try:
            src.push(Frame((prompt,), meta={"req": "x"}))
            src.end_of_stream()
            f = out_sink.pop(timeout=120)
            srv = sink._server
            return [int(t) for t in np.asarray(f.tensors[0])[0]], srv
        finally:
            p.stop()

    plain, _ = run("autoA", {})
    spec, srv = run("autoB", {"speculate": "auto"})
    assert spec == plain
    assert 2 <= srv._spec_k <= 8


def test_speculate_auto_converges_above_floor_and_surfaces_stats():
    """VERDICT r4 #5: on a high-acceptance workload (draft == target —
    same zoo seed/config — proposes the target's own greedy tokens)
    speculate=auto must CONVERGE to k > 2, and the --stats surface must
    carry the acceptance telemetry (spec_k, spec_acceptance_ema,
    spec_acceptance_rate) so a silent proposer regression is visible."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    draft_opts = MODEL_OPTS + "," + ",".join(
        "draft_" + kv for kv in MODEL_OPTS.split(",")
    )
    src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
    sink = LlmServerSink(
        **{"id": "autoconv", "model": "zoo:transformer_lm",
           "custom": draft_opts, "n-slots": 1, "max-len": 96,
           "prompt-len": 16, "max-new-tokens": 48,
           "speculate": "auto",
           "speculate-model": "zoo:transformer_lm"}
    )
    out_src = LlmServerSrc(**{"id": "autoconv"})
    out_sink = AppSink()
    p = Pipeline().chain(src, sink)
    p.chain(out_src, out_sink)
    p.start()
    try:
        src.push(Frame((np.asarray([3, 4, 5, 6], np.int32),),
                       meta={"req": "conv"}))
        src.end_of_stream()
        f = out_sink.pop(timeout=240)
        assert f is not None
        srv = sink._server
        st = srv.stats()
    finally:
        p.stop()
    assert st["spec_k"] > 2, st  # converged off the floor
    assert st["spec_acceptance_ema"] > 0.5, st
    assert st["spec_acceptance_rate"] >= 0.9, st
    assert srv._spec_k > 2


def test_speculate_auto_with_pump_matches_plain():
    """speculate=auto + pump=N: adaptive-k speculation rides the
    scanned spec_pump (rounds=⌈N/k⌉, one readback per pump) and the
    stream still equals plain serving; the acceptance EMA keeps
    adapting from the pump's packed telemetry."""
    from nnstreamer_tpu.elements.llm_serve import LlmServerSink, LlmServerSrc
    from nnstreamer_tpu.elements.sink import AppSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline
    from nnstreamer_tpu.tensors.frame import Frame
    from nnstreamer_tpu.tensors.spec import TensorFormat, TensorsSpec

    prompt = np.asarray([3, 4, 3, 4, 3, 4, 3], np.int32)

    def run(srv_id, extra):
        src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
        sink = LlmServerSink(
            **{"id": srv_id, "model": "zoo:transformer_lm",
               "custom": MODEL_OPTS, "n-slots": 1, "max-len": 64,
               "prompt-len": 16, "max-new-tokens": 10, **extra}
        )
        out_src = LlmServerSrc(**{"id": srv_id})
        out_sink = AppSink()
        p = Pipeline().chain(src, sink)
        p.chain(out_src, out_sink)
        p.start()
        try:
            src.push(Frame((prompt,), meta={"req": "x"}))
            src.end_of_stream()
            f = out_sink.pop(timeout=180)
            assert f is not None, "server emitted EOS before the reply"
            srv = sink._server
            return [int(t) for t in np.asarray(f.tensors[0])[0]], srv
        finally:
            p.stop()

    plain, _ = run("autopA", {})
    spec, srv = run("autopB", {"speculate": "auto", "pump": "8"})
    assert spec == plain
    st = srv.stats()
    assert st["spec_rounds"] > 0 and st["spec_columns"] > 0
    # the controller actually consumed the pump's packed telemetry:
    # the EMA moved off its 0.5 prior (initial-k [2,8] band checks are
    # tautological — every update clamps into it)
    assert srv._acc_ema != 0.5
