"""Real media ingress tests: videofilesrc (encoded video + still image),
v4l2src error paths. Reference analogue: v4l2src/decodebin feeding
tensor_converter's video path (gsttensor_converter.c:1046-1270).

The clip fixture is generated at test time (OpenCV mp4v) rather than
checked in — codecs are lossy and encoder bytes are not stable across
builds, so assertions are on structure + content proximity, the same
posture as the reference's camera tests."""

import numpy as np
import pytest

cv2 = pytest.importorskip("cv2", reason="media sources are cv2-gated")

from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.elements.media import V4l2Src, VideoFileSrc
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.frame import EOS_FRAME

W, H, N_FRAMES = 64, 48, 6


@pytest.fixture(scope="module")
def clip(tmp_path_factory):
    """mp4v clip: frame i is a solid level i*30 (lossy-codec friendly)."""
    path = str(tmp_path_factory.mktemp("media") / "clip.mp4")
    w = cv2.VideoWriter(
        path, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, (W, H)
    )
    assert w.isOpened(), "image's OpenCV build cannot encode mp4v"
    for i in range(N_FRAMES):
        w.write(np.full((H, W, 3), i * 30, np.uint8))
    w.release()
    return path


@pytest.fixture(scope="module")
def still(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("media") / "img.png")
    img = np.zeros((H, W, 3), np.uint8)
    img[:, :, 2] = 200  # red in BGR order (png is lossless)
    assert cv2.imwrite(path, img)
    return path


def test_videofilesrc_decodes_clip(clip):
    src = VideoFileSrc(location=clip)
    assert src.output_spec().width == W and src.output_spec().height == H
    src.start()
    frames = []
    while True:
        f = src.generate()
        if f is EOS_FRAME:
            break
        if f is not None:
            frames.append(f)
    src.stop()
    assert len(frames) == N_FRAMES
    for i, f in enumerate(frames):
        img = np.asarray(f.tensors[0])
        assert img.shape == (H, W, 3) and img.dtype == np.uint8
        assert img.flags["C_CONTIGUOUS"]  # stride handling: tight layout
        # mp4v is lossy; solid frames survive within a few code levels
        assert abs(float(img.mean()) - i * 30) < 6, (i, img.mean())
    # pts synthesized from the container fps (10/1)
    assert frames[1].pts == 100_000_000


def test_videofilesrc_through_pipeline(clip):
    """nns-launch-style: videofilesrc ! tensor_converter ! tensor_filter !
    tensor_sink — the VERDICT's done-criterion pipeline."""
    p = parse_pipeline(
        f"videofilesrc location={clip} ! tensor_converter ! "
        "tensor_filter framework=passthrough ! tensor_sink name=out"
    )
    p.run(timeout=120)
    sink = p["out"]
    assert sink.rendered == N_FRAMES
    assert np.asarray(sink.frames[0].tensors[0]).shape == (1, H, W, 3)


def test_videofilesrc_loop_caps_at_num_frames(clip):
    src = VideoFileSrc(location=clip, loop="true", **{"num-frames": 10})
    src.start()
    n = 0
    while True:
        f = src.generate()
        if f is EOS_FRAME:
            break
        if f is not None:
            n += 1
    src.stop()
    assert n == 10  # 6-frame clip looped past EOF, capped by num-frames


def test_videofilesrc_still_image(still):
    src = VideoFileSrc(location=still, format="RGB")
    src.start()
    f = src.generate()
    assert src.generate() is EOS_FRAME  # stills emit once by default
    img = np.asarray(f.tensors[0])
    assert img.shape == (H, W, 3)
    assert img[0, 0, 0] == 200 and img[0, 0, 2] == 0  # BGR→RGB converted
    src.stop()


def test_videofilesrc_gray8(clip):
    src = VideoFileSrc(location=clip, format="GRAY8")
    assert src.output_spec().channels_per_pixel == 1
    src.start()
    f = src.generate()
    assert np.asarray(f.tensors[0]).shape == (H, W, 1)
    src.stop()


def test_videofilesrc_missing_file_raises(tmp_path):
    with pytest.raises(ElementError, match="cannot"):
        VideoFileSrc(location=str(tmp_path / "nope.mp4"))


def test_v4l2src_missing_device_raises():
    with pytest.raises(ElementError, match="cannot open camera"):
        V4l2Src(device="/dev/video99")


def test_decode_ahead_preserves_order_and_pts(clip):
    """The decode-ahead thread (r4) must be sequence-invisible: same
    frames, same order, same PTS as synchronous decode."""

    def run(depth):
        src = VideoFileSrc(location=clip, **{"decode-ahead": depth})
        src.start()
        out = []
        while True:
            f = src.generate()
            if f is EOS_FRAME:
                break
            if f is not None:
                out.append((f.pts, int(np.asarray(f.tensors[0])[0, 0, 0])))
        src.stop()
        return out

    sync = run(0)
    ahead = run(8)
    assert ahead == sync
    assert len(ahead) == N_FRAMES
    assert [p for p, _ in ahead] == sorted(p for p, _ in ahead)


def test_decode_ahead_stop_mid_stream_does_not_hang(clip):
    """Stopping while the decoder is parked on a full queue must join
    cleanly (the executor calls stop() on teardown)."""
    import time

    src = VideoFileSrc(location=clip, loop=True, **{"decode-ahead": 2})
    src.start()
    f = src.generate()
    while f is None:
        f = src.generate()
    time.sleep(0.2)  # let the decoder fill + park on the bounded queue
    t0 = time.monotonic()
    src.stop()
    assert time.monotonic() - t0 < 5.0
    assert src._ahead is None


def test_wedged_stop_reopens_fresh_capture(clip):
    """After a stop() whose decode thread failed to join (wedged native
    read), a restart must open a FRESH capture — reusing the leaked
    handle would put two native readers on one OpenCV capture, the race
    stop() exists to avoid (r4 advisor). The orphan keeps the handle it
    bound at thread creation."""
    src = VideoFileSrc(location=clip, loop=True, **{"decode-ahead": 2})
    src.start()
    old_cap = src._cap
    orphan = src._ahead
    real_stop = orphan.stop
    orphan.stop = lambda: False  # simulate the wedged join
    src.stop()
    assert src._cap is None  # our reference dropped, handle to the orphan
    src.start()
    assert src._cap is not None and src._cap is not old_cap
    f = src.generate()  # the fresh capture actually decodes
    while f is None:
        f = src.generate()
    assert f is not EOS_FRAME
    src.stop()
    real_stop()  # join the "orphan" for real and release its handle
    old_cap.release()
