"""Developer-tool tests (reference tools/development/: codegen, confchk,
pipeline→pbtxt parser; SURVEY.md §2.5)."""

import subprocess
import sys

import numpy as np
import pytest

from nnstreamer_tpu.tools import codegen, confchk, pbtxt


class TestCodegen:
    def test_filter_scaffold_is_loadable(self, tmp_path):
        path = codegen.generate("filter", "my_op", str(tmp_path))
        from nnstreamer_tpu.single import SingleShot

        data = np.arange(6, dtype=np.float32).reshape(2, 3)
        with SingleShot(framework="custom", model=path) as s:
            out = s.invoke(data)
            np.testing.assert_array_equal(np.asarray(out[0]), data)

    def test_decoder_scaffold_registers(self, tmp_path):
        path = codegen.generate("decoder", "my_dec", str(tmp_path))
        import importlib.util

        spec = importlib.util.spec_from_file_location("my_dec_plugin", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        from nnstreamer_tpu import registry

        assert registry.get(registry.KIND_DECODER, "my_dec")
        registry.unregister(registry.KIND_DECODER, "my_dec")

    def test_converter_scaffold_registers(self, tmp_path):
        path = codegen.generate("converter", "my_conv", str(tmp_path))
        import importlib.util

        spec = importlib.util.spec_from_file_location("my_conv_plugin", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        from nnstreamer_tpu import registry

        assert registry.get(registry.KIND_CONVERTER, "my_conv")
        registry.unregister(registry.KIND_CONVERTER, "my_conv")

    def test_rejects_bad_name(self, tmp_path):
        with pytest.raises(ValueError):
            codegen.generate("filter", "bad-name", str(tmp_path))
        with pytest.raises(ValueError):
            codegen.generate("nope", "ok_name", str(tmp_path))

    def test_refuses_overwrite(self, tmp_path):
        codegen.generate("filter", "dup", str(tmp_path))
        with pytest.raises(FileExistsError):
            codegen.generate("filter", "dup", str(tmp_path))


class TestConfchk:
    def test_clean_default_config(self):
        info, warnings, errors = confchk.check()
        assert not errors
        assert any("[edge] default_port" in m for m in info)

    def test_flags_unknown_keys(self, tmp_path):
        ini = tmp_path / "bad.ini"
        ini.write_text("[filter]\nbogus_key = 1\n\n[nosuchsection]\nx = y\n")
        _, warnings, _ = confchk.check(str(ini))
        assert any("bogus_key" in m for m in warnings)
        assert any("nosuchsection" in m for m in warnings)

    def test_flags_missing_plugin_dir(self, tmp_path, monkeypatch):
        ini = tmp_path / "paths.ini"
        ini.write_text("[filter]\nplugin_paths = /definitely/not/here\n")
        _, _, errors = confchk.check(str(ini))
        assert any("/definitely/not/here" in m for m in errors)


class TestPbtxt:
    def test_linear_pipeline(self):
        out = pbtxt.to_pbtxt(
            "videotestsrc num-frames=2 ! tensor_converter ! tensor_sink"
        )
        assert out.count("node {") == 3
        assert 'calculator: "videotestsrc"' in out
        assert 'calculator: "tensor_converter"' in out
        # converter consumes the source's stream and produces its own
        assert 'input_stream:' in out and 'output_stream:' in out

    def test_props_serialized(self):
        out = pbtxt.to_pbtxt("videotestsrc width=32 height=24 ! tensor_converter ! tensor_sink")
        assert 'option: "width=32"' in out

    def test_cli_entry(self):
        proc = subprocess.run(
            [sys.executable, "-m", "nnstreamer_tpu.tools.pbtxt",
             "videotestsrc ! tensor_converter ! tensor_sink"],
            capture_output=True, text=True, timeout=120,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
        )
        assert proc.returncode == 0
        assert 'calculator: "tensor_converter"' in proc.stdout
