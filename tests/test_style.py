"""Style gate (the reference's gst-indent/pre-commit role, SURVEY.md §2.5):
the in-tree checker must pass over the whole tree."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tree_is_style_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_style.py"), REPO],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, f"style problems:\n{proc.stdout}"
