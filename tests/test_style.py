"""Style gate (the reference's gst-indent/pre-commit role, SURVEY.md §2.5):
the in-tree checker must pass over the whole tree, and every registered
builtin element's PROPERTIES schema must cover the properties its code
reads (nns-lint --self-check)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_tree_is_style_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_style.py"),
         "--no-self-check", REPO],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, f"style problems:\n{proc.stdout}"


def test_element_property_schemas_cover_code():
    """nns-lint --self-check: an element property readable by code but
    absent from PROPERTIES would be invisible to the linter — fail the
    gate (in-process; tools/check_style.py runs the same check)."""
    from nnstreamer_tpu.analysis.selfcheck import self_check

    problems = self_check()
    assert not problems, "\n".join(problems)


def test_race_lint_clean_on_package():
    """nns-san --race over nnstreamer_tpu/ must report ZERO findings:
    regressions in the repo's concurrency idioms (unlocked shared
    counters, silent service-loop swallows, _Chan pairing violations)
    fail the suite from now on (tools/check_style.py runs the same
    gate on whole-tree runs)."""
    from nnstreamer_tpu.analysis.racecheck import run_race_lint

    report = run_race_lint([os.path.join(REPO, "nnstreamer_tpu")])
    assert not report.diagnostics, report.render()


def test_obs_metric_catalog_covers_code():
    """nns-obs self-check: every metric the package emits is cataloged
    in obs.metrics.METRIC_CATALOG, every cataloged metric has an
    emitter, and docs/observability.md documents every name
    (tools/check_style.py runs the same gate on whole-tree runs)."""
    from nnstreamer_tpu.analysis.selfcheck import obs_self_check

    problems = obs_self_check()
    assert not problems, "\n".join(problems)


def test_san_diagnostic_catalog_covers_code():
    """nns-san --self-check: every emitted code is cataloged, every
    cataloged code has an emitter, slugs stay unique, and the sanitizer
    doc covers the NNS-R/NNS-S codes."""
    from nnstreamer_tpu.analysis.selfcheck import san_self_check

    problems = san_self_check()
    assert not problems, "\n".join(problems)


def test_xray_chain_codes_wired_both_ways():
    """nns-xray --self-check: every chain diagnostic (NNS-W120..W124)
    is cataloged, has an emitter in analysis/xray.py, and is documented
    in docs/chain-analysis.md AND docs/linting.md; conversely the chain
    doc mentions no unknown codes (tools/check_style.py runs the same
    gate on whole-tree runs)."""
    from nnstreamer_tpu.analysis.selfcheck import xray_self_check

    problems = xray_self_check()
    assert not problems, "\n".join(problems)


def test_kscope_kernel_codes_and_registry_wired_both_ways():
    """nns-kscope --self-check wiring: every kernel diagnostic
    (NNS-W127..W129) is cataloged, has an emitter in
    analysis/kernels.py, and is documented in docs/kernel-analysis.md
    AND docs/linting.md; every public ops/pallas kernel entry point has
    a KernelSpec of the same name and vice versa; and the registered
    dispatch ops equal ops/dispatch.KNOWN_OPS both ways
    (tools/check_style.py runs the same gate on whole-tree runs)."""
    from nnstreamer_tpu.analysis.selfcheck import kscope_self_check

    problems = kscope_self_check()
    assert not problems, "\n".join(problems)


def test_disagg_codes_wired_both_ways():
    """nns-disagg --self-check wiring: NNS-W130 is cataloged, has an
    emitter in analysis/lint.py, and is documented in docs/linting.md
    AND docs/llm-serving.md; both disagg metrics are in METRIC_CATALOG
    with live emitters (tools/check_style.py runs the same gate on
    whole-tree runs)."""
    from nnstreamer_tpu.analysis.selfcheck import disagg_self_check

    problems = disagg_self_check()
    assert not problems, "\n".join(problems)


@pytest.mark.slow
def test_documented_pipelines_xray_clean():
    """Every pipeline string embedded in examples/ and docs/ must xray
    clean of the chain diagnostics W120-W124 — a shipped snippet firing
    one is either a bad example or a false positive
    (tools/check_style.py runs the same gate on whole-tree runs; slow:
    it compiles ~20 documented pipelines, and tier-1 seconds displace
    passing dots at the truncated tail of the 870 s budget)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_style", os.path.join(REPO, "tools", "check_style.py")
    )
    check_style = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(check_style)
    assert check_style.documented_pipeline_strings(), "sweep found nothing"
    problems = check_style.run_xray_docs_gate()
    assert not problems, "\n".join(problems)
