"""Audio model family tests (models/audio.py, zoo:kws).

The converter's audio path existed without a native zoo model; these
run real inference over it end to end: audiotestsrc → converter →
filter zoo:kws → image_labeling decode → sink.
"""

import jax
import jax.numpy as jnp
import numpy as np


def test_logits_shape_and_norm():
    from nnstreamer_tpu.models import zoo

    m = zoo.get("kws", samples="1024", num_classes="5", width="16")
    pcm = np.random.default_rng(0).integers(
        -(2 ** 15), 2 ** 15, (1024, 1)
    ).astype(np.int16)
    out = np.asarray(jax.jit(m.fn)(jnp.asarray(pcm)))
    assert out.shape == (1, 5)
    assert np.isfinite(out).all()
    # int16 normalization happened (raw PCM magnitudes would blow the
    # activations up by ~3e4)
    assert np.abs(out).max() < 1e3


def test_stereo_mono_mix_matches_manual():
    from nnstreamer_tpu.models import audio

    params = audio.init_params(jax.random.PRNGKey(0), num_classes=3,
                               width=16)
    rng = np.random.default_rng(1)
    st = rng.integers(-1000, 1000, (512, 2)).astype(np.int16)
    mono = st.astype(np.float32).mean(axis=-1, keepdims=True) / 32768.0
    a = np.asarray(audio.apply(params, jnp.asarray(st)))
    b = np.asarray(audio.apply(params, jnp.asarray(mono)))
    np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_pipeline_audio_end_to_end():
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    desc = (
        "audiotestsrc samples-per-buffer=1024 num-buffers=3 "
        "channels=1 ! tensor_converter ! "
        "tensor_filter framework=jax model=zoo:kws "
        'custom="samples:1024,num_classes:5,width:16" ! '
        "tensor_decoder mode=image_labeling ! tensor_sink"
    )
    ex = parse_pipeline(desc).run(timeout=300)
    sink = next(
        n.elem for n in ex.nodes
        if isinstance(getattr(n, "elem", None), TensorSink)
    )
    assert sink.rendered == 3
    # image_labeling emits the argmax label index
    lab = np.asarray(sink.frames[0].tensors[0]).reshape(-1)
    assert 0 <= int(lab[0]) < 5


def test_bf16_finite():
    from nnstreamer_tpu.models import zoo

    m = zoo.get("kws", samples="512", num_classes="3", width="16",
                compute_dtype="bfloat16")
    pcm = jnp.zeros((512, 1), jnp.int16)
    out = np.asarray(jax.jit(m.fn)(pcm))
    assert out.shape == (1, 3) and np.isfinite(out).all()
