"""Async serving-plane submits (serving_plane/plane.py tickets +
the executor's plane window ring, docs/serving-plane.md): in-order
delivery at every ring depth, bitwise parity with blocking submits,
per-stream fault isolation of failed in-flight windows with totals
balance 0, a clean sanitizer latch, the LLM-through-plane path
(serving_plane/llm.py: greedy parity + the zero-gather pin), the
progress-scaled stall grant, and the NNS-W118 lint — plus the 8-stream
churn soak (slow).

Budget discipline: pipeline tests ride the scaler backend (no jit
compiles at all); the LLM test uses the smallest transformer config
and is the only cell that compiles."""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.backends.base import FilterProps
from nnstreamer_tpu.backends.fakes import ScalerBackend
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.serving_plane import plane as plane_mod
from nnstreamer_tpu.serving_plane.plane import (
    ModelPlane,
    PlaneClosedError,
    PlaneConfig,
)
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


def _spec(dims="4"):
    return TensorsSpec.from_strings(dims, "float32")


def _scaler(factor=3.0):
    b = ScalerBackend()
    b.open(FilterProps(
        framework="scaler", model=(), custom=f"factor:{factor}",
        input_spec=_spec(),
    ))
    return b


def _run_streams(descs, timeout=60):
    pipes = [parse_pipeline(d) for d in descs]
    execs = [None] * len(pipes)
    errors = []

    def drive(i):
        try:
            execs[i] = pipes[i].run(timeout=timeout)
        except Exception as exc:  # noqa: BLE001 — assert below
            errors.append((i, exc))

    ts = [
        threading.Thread(target=drive, args=(i,))
        for i in range(len(pipes))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    return pipes, execs


def _sink_values(pipe):
    sink = next(e for e in pipe.elements if isinstance(e, TensorSink))
    return [float(np.asarray(f.tensors[0])[0]) for f in sink.frames]


# ---------------------------------------------------------------------------
# ticket API: order, parity, accounting
# ---------------------------------------------------------------------------

class TestTickets:
    @pytest.mark.parametrize("depth", [1, 2, 3])
    def test_in_order_delivery_at_depth(self, depth):
        """Tickets redeemed oldest-first return each window's outputs
        in submission order at every ring depth (FIFO is structural:
        the plane pops each stream's queue left-to-right)."""
        plane = ModelPlane(
            "ord", PlaneConfig(max_batch=8, timeout_ms=0.5),
            [_scaler(2.0)],
        )
        try:
            s = plane.attach(f"d{depth}")
            ring = []
            got = []
            for j in range(12):
                w = [(np.full(4, float(j), np.float32),)]
                ring.append((j, plane.submit_window_async(s, w)))
                while len(ring) >= depth:
                    jj, req = ring.pop(0)
                    (out,) = plane.wait_window(s, req)
                    got.append((jj, float(np.asarray(out[0])[0])))
            while ring:
                jj, req = ring.pop(0)
                (out,) = plane.wait_window(s, req)
                got.append((jj, float(np.asarray(out[0])[0])))
            assert got == [(j, 2.0 * j) for j in range(12)]
            assert s.admitted == 12 and s.served == 12
            assert s.inflight == 0 and plane._inflight_total == 0
        finally:
            plane.close()

    def test_async_bitwise_parity_with_sync(self):
        """The same windows through async tickets and blocking submits
        produce bitwise-identical outputs (same program, same stacking
        — the ticket layer adds no math)."""
        plane = ModelPlane(
            "par", PlaneConfig(max_batch=8, timeout_ms=0.5),
            [_scaler(1.5)],
        )
        try:
            s1, s2 = plane.attach("sync"), plane.attach("async")
            rng = np.random.default_rng(7)
            windows = [
                [(rng.standard_normal(4).astype(np.float32),)]
                for _ in range(10)
            ]
            sync_outs = [
                plane.submit_window(s1, list(w)) for w in windows
            ]
            reqs = [
                plane.submit_window_async(s2, list(w)) for w in windows
            ]
            async_outs = [plane.wait_window(s2, r) for r in reqs]
            for a, b in zip(sync_outs, async_outs):
                assert np.array_equal(
                    np.asarray(a[0][0]), np.asarray(b[0][0])
                )
        finally:
            plane.close()

    def test_inflight_counters_and_gauge(self):
        """stream.inflight / the plane total track submitted-not-yet-
        collected tickets (the nns_plane_inflight_windows surface)."""
        plane = ModelPlane(
            "infl", PlaneConfig(max_batch=4, timeout_ms=0.0),
            [_scaler(1.0)],
        )
        try:
            s = plane.attach("s0")
            reqs = [
                plane.submit_window_async(
                    s, [(np.zeros(4, np.float32),)]
                )
                for _ in range(3)
            ]
            assert s.inflight == 3 and plane._inflight_total == 3
            assert plane.stats()["inflight"] == 3
            for r in reqs:
                plane.wait_window(s, r)
            assert s.inflight == 0 and plane._inflight_total == 0
            assert s.snapshot()["inflight"] == 0
        finally:
            plane.close()


# ---------------------------------------------------------------------------
# the stall grant (the plane.py "one more full window" fix)
# ---------------------------------------------------------------------------

class TestStallGrant:
    def test_wedged_service_thread_surfaces_fast_at_depth(self):
        """A wedged program (no dispatch progress) surfaces after at
        most ~2× submit_timeout_s even with a deep ring — depth must
        not scale the grant without progress (the masking the fix
        removes)."""

        class WedgeProgram:
            mode = "single"
            n_traces = 0

            def invoke(self, windows):
                time.sleep(1.0)
                return [w for w in windows]

            def invoke_one(self, w):
                return self.invoke([w])[0]

        plane = ModelPlane(
            "wedge",
            PlaneConfig(max_batch=4, timeout_ms=0.0,
                        submit_timeout_s=0.1),
            backends=[], program=WedgeProgram(),
        )
        s = plane.attach("s0")
        reqs = [
            plane.submit_window_async(s, [(np.zeros(4, np.float32),)])
            for _ in range(3)
        ]
        t0 = time.monotonic()
        with pytest.raises(PlaneClosedError):
            plane.wait_window(s, reqs[0])
        dt = time.monotonic() - t0
        # one unconditional extension only: ~2×0.1s, NOT (1+ahead)×
        assert dt < 1.0, f"wedge took {dt:.2f}s to surface"
        for r in reqs[1:]:
            with pytest.raises(PlaneClosedError):
                plane.wait_window(s, r)
        # the service thread is parked in the wedged program; close()
        # reaps what it can and the daemon thread dies with the sleep
        plane.close(join_timeout=0.1)

    def test_slow_but_progressing_plane_scales_the_grant(self):
        """A dispatch slower than submit_timeout_s but making progress
        must NOT fail a deep ring's tail ticket: the grant scales with
        the windows ahead while dispatches keep landing (the fixed
        2×timeout grant would false-positive here)."""

        class SlowProgram:
            mode = "single"
            n_traces = 0

            def invoke(self, windows):
                time.sleep(0.17)
                return [w for w in windows]

            def invoke_one(self, w):
                return self.invoke([w])[0]

        plane = ModelPlane(
            "slow",
            PlaneConfig(max_batch=1, timeout_ms=0.0,
                        submit_timeout_s=0.12),
            backends=[], program=SlowProgram(),
        )
        try:
            s = plane.attach("s0")
            reqs = [
                plane.submit_window_async(
                    s, [(np.zeros(4, np.float32),)]
                )
                for _ in range(3)
            ]
            # the LAST ticket waits ~3×0.17s ≈ 0.51s > 2×0.12s: only
            # the progress-scaled grant lets it complete
            for r in reqs:
                out = plane.wait_window(s, r)
                assert out is not None
            assert s.served == 3
        finally:
            plane.close()


# ---------------------------------------------------------------------------
# executor integration: pipelines with ring-depth
# ---------------------------------------------------------------------------

class TestPipelines:
    def test_async_pipeline_parity_and_order(self):
        """ring-depth=3 streams deliver every frame, in order, with
        values bitwise-equal to a blocking (depth 1) run of the same
        description."""
        def run(extra, plane):
            descs = [
                "tensorsrc dimensions=4 pattern=counter num-frames=30 ! "
                "tensor_filter framework=scaler custom=factor:2.0 "
                f"plane={plane} plane-max-batch=8 plane-timeout-ms=0.5 "
                f"{extra} ! tensor_sink"
                for _ in range(3)
            ]
            return _run_streams(descs)

        async_pipes, async_execs = run("ring-depth=3", "as1")
        sync_pipes, _ = run("", "bs1")
        want = [2.0 * j for j in range(30)]
        for pa, ps in zip(async_pipes, sync_pipes):
            assert _sink_values(pa) == want
            assert _sink_values(ps) == want
        for ex in async_execs:
            tot = ex.totals()
            assert tot["produced"] == tot["rendered"] == 30
            assert tot["balance"] == 0
        assert plane_mod.get("as1") is None  # refcount drained

    def test_async_fault_isolation_totals_balance(self):
        """One stream's poisoned frames fail their in-flight windows;
        the window splits per frame through THAT stream's on-error=drop
        gate (all 20 dropped with accounting, balance 0) while the
        healthy async stream delivers everything."""

        class MarkerProgram:
            mode = "single"
            n_traces = 0

            def invoke(self, windows):
                outs = []
                for (x,) in windows:
                    if float(np.asarray(x)[0]) >= 90.0:
                        raise RuntimeError("poisoned window")
                    outs.append((np.asarray(x),))
                return outs

            def invoke_one(self, w):
                return self.invoke([w])[0]

        cfg = PlaneConfig(max_batch=8, timeout_ms=1.0)
        plane = ModelPlane("fa1", cfg, backends=[_scaler(1.0)],
                           program=MarkerProgram())
        entry = {"plane": plane, "sig": None, "refs": 0, "cfg": cfg,
                 "open_lock": threading.Lock()}
        plane_mod._planes["fa1"] = entry

        def acquire_patch(name, sig, cfg2, opener, cfg_explicit=True,
                          _orig=plane_mod.acquire):
            if name == "fa1":
                with plane_mod._registry_lock:
                    entry["refs"] += 1
                return plane
            return _orig(name, sig, cfg2, opener,
                         cfg_explicit=cfg_explicit)

        orig = plane_mod.acquire
        plane_mod.acquire = acquire_patch
        try:
            descs = [
                "tensorsrc dimensions=4 pattern=counter num-frames=20 ! "
                "tensor_filter framework=scaler plane=fa1 "
                "plane-max-batch=8 ring-depth=2 ! tensor_sink",
                "tensorsrc dimensions=4 pattern=counter num-frames=20 ! "
                "tensor_transform mode=arithmetic option=add:90.0 ! "
                "tensor_filter framework=scaler plane=fa1 "
                "plane-max-batch=8 ring-depth=2 on-error=drop "
                "name=poisoned ! tensor_sink",
            ]
            pipes, execs = _run_streams(descs)
            assert _sink_values(pipes[0]) == [float(j) for j in range(20)]
            assert len(_sink_values(pipes[1])) == 0
            tot = execs[1].totals()
            assert tot["dropped"].get("on-error-drop") == 20
            assert tot["balance"] == 0
            healthy_tot = execs[0].totals()
            assert healthy_tot["balance"] == 0
        finally:
            plane_mod.acquire = orig
            plane_mod._planes.pop("fa1", None)
            plane.close()

    def test_sanitizer_latch_clean_async(self, monkeypatch):
        """Clean EOS through async rings latches the sanitizer's
        offered == delivered accounting on every stream."""
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        descs = [
            "tensorsrc dimensions=4 pattern=counter num-frames=15 ! "
            "tensor_filter framework=scaler custom=factor:2.0 "
            "plane=sas1 plane-max-batch=4 ring-depth=3 ! tensor_sink"
            for _ in range(2)
        ]
        pipes, execs = _run_streams(descs)
        for ex in execs:
            assert ex.sanitizer is not None
            assert not ex.errors
            assert ex.totals()["balance"] == 0
        for p in pipes:
            assert len(_sink_values(p)) == 15

    def test_ring_depth_resolves_from_plane_inflight_config(
        self, monkeypatch
    ):
        """[plane] inflight (env NNS_TPU_PLANE_INFLIGHT) is the
        per-stream default; the element ring-depth property wins."""
        from nnstreamer_tpu.elements.filter import TensorFilter

        monkeypatch.setenv("NNS_TPU_PLANE_INFLIGHT", "2")
        f = TensorFilter(framework="scaler", plane="cfg1")
        assert f.plane_inflight == 2
        g = TensorFilter(
            framework="scaler", plane="cfg1", **{"ring-depth": "4"}
        )
        assert g.plane_inflight == 4
        monkeypatch.delenv("NNS_TPU_PLANE_INFLIGHT")
        h = TensorFilter(framework="scaler", plane="cfg1")
        assert h.plane_inflight == 1  # blocking default


# ---------------------------------------------------------------------------
# LLM pumps through a plane (serving_plane/llm.py)
# ---------------------------------------------------------------------------

class TestLlmPlane:
    def test_greedy_parity_and_zero_gather(self):
        """Two serversink/serversrc pairs share one plane-managed paged
        batcher: every generation matches solo greedy decode bitwise,
        SLO request rows stay per stream, and the block-native decode
        path stays gather-free through the plane."""
        from nnstreamer_tpu.elements.llm_serve import (
            LlmServerSink,
            LlmServerSrc,
        )
        from nnstreamer_tpu.elements.sink import AppSink
        from nnstreamer_tpu.elements.sources import AppSrc
        from nnstreamer_tpu.models import decode as dec
        from nnstreamer_tpu.pipeline.graph import Pipeline
        from nnstreamer_tpu.serving_plane import llm as llm_plane
        from nnstreamer_tpu.tensors.spec import TensorFormat

        opts = "vocab:127,d_model:16,n_heads:2,n_layers:1,seed:9"

        rng = np.random.default_rng(11)
        # ONE prompt length: the solo-decode reference compiles one
        # program instead of one per length (tier-1 budget)
        prompts = {
            f"s{k}r{i}": rng.integers(1, 127, (6,)).astype(np.int32)
            for k in range(2) for i in range(2)
        }
        pipes, ends = [], {}
        for k in range(2):
            src = AppSrc(spec=TensorsSpec(format=TensorFormat.FLEXIBLE))
            sink = LlmServerSink(**{
                "id": f"tpl{k}", "model": "zoo:transformer_lm",
                "custom": opts, "n-slots": 2, "max-len": 16,
                "prompt-len": 8, "max-new-tokens": 4, "pump": 2,
                "plane": "test_llm", "block-size": 8, "kv-blocks": 8,
            })
            osrc = LlmServerSrc(**{"id": f"tpl{k}"})
            osink = AppSink()
            p = Pipeline().chain(src, sink)
            p.chain(osrc, osink)
            p.start()
            pipes.append(p)
            ends[k] = (src, osink, osrc)
        results, stats = {}, {}
        try:
            pl = llm_plane.get("test_llm")
            assert pl is not None and len(pl._sched) == 2
            # greedy oracle off the SHARED batcher's own params (same
            # seed; avoids a second model init for the reference)
            params = pl.cb.params

            def alone(prompt, n):
                toks = dec.generate(
                    params, np.asarray(prompt, np.int32)[None, :], 2, n
                )
                return [int(t) for t in np.asarray(toks)[0]]

            for k, (src, _, _) in ends.items():
                for name, pr in prompts.items():
                    if name.startswith(f"s{k}"):
                        src.push(Frame(
                            (pr,),
                            meta={"req": name, "deadline_ms": 60000},
                        ))
                src.end_of_stream()
            for k, (_, osink, osrc) in ends.items():
                for _ in range(2):
                    f = osink.pop(timeout=120)
                    assert f is not None, "llm plane drained early"
                    results[f.meta["req"]] = [
                        int(t) for t in np.asarray(f.tensors[0])[0]
                    ]
                stats[k] = osrc.serving_stats()
        finally:
            for p in pipes:
                p.stop()
        for name, pr in prompts.items():
            assert results[name] == alone(pr, 4), f"{name} diverged"
        for k in range(2):
            st = stats[k]
            # zero-gather pin: block-native decode through the plane
            assert st["kv_attn"] == "block"
            assert st.get("kv_gather_dispatches", 0) == 0
            # per-stream SLO ledgers: each src reports ONLY its own
            reqs = st["requests"]
            assert len(reqs) == 2
            assert all(
                r.get("deadline_s") is not None for r in reqs.values()
            )
            assert st["stream_served"] == 2
        assert llm_plane.get("test_llm") is None  # refcount drained

    def test_plane_rejects_incompatible_modes(self):
        from nnstreamer_tpu.elements.base import ElementError
        from nnstreamer_tpu.elements.llm_serve import _LlmServer

        kw = dict(
            model="zoo:transformer_lm",
            options={"vocab": "127", "d_model": "16", "n_heads": "2",
                     "n_layers": "1"},
            n_slots=2, max_len=32, prompt_len=16, default_new=4,
        )
        with pytest.raises(ElementError, match="kv-layout=paged"):
            _LlmServer(**kw, plane="bad1", kv_layout="slot")
        with pytest.raises(ElementError, match="speculate"):
            _LlmServer(**kw, plane="bad2", kv_layout="paged",
                       speculate=4)
        with pytest.raises(ElementError, match="stream"):
            _LlmServer(**kw, plane="bad3", kv_layout="paged",
                       stream=True)


# ---------------------------------------------------------------------------
# NNS-W118 (both ways)
# ---------------------------------------------------------------------------

class TestW118:
    def test_fires_on_multi_stream_depth1(self):
        from nnstreamer_tpu.analysis.lint import lint

        desc = (
            "tensorsrc dimensions=4 num-frames=1 ! tensor_filter "
            "framework=scaler custom=factor:2.0 plane=w1 ! tensor_sink "
            "tensorsrc dimensions=4 num-frames=1 ! tensor_filter "
            "framework=scaler custom=factor:2.0 plane=w1 ! tensor_sink"
        )
        r = lint(desc)
        assert "NNS-W118" in [d.code for d in r.report.diagnostics]

    def test_fires_on_ring_depth_without_batching(self):
        from nnstreamer_tpu.analysis.lint import lint

        r = lint(
            "tensorsrc dimensions=4 num-frames=1 ! tensor_filter "
            "framework=scaler custom=factor:2.0 plane=w2 ring-depth=3 "
            "batching=false ! tensor_sink"
        )
        assert "NNS-W118" in [d.code for d in r.report.diagnostics]

    def test_silent_with_ring_and_single_stream(self):
        from nnstreamer_tpu.analysis.lint import lint

        # single stream at depth 1: nothing to overlap across — silent
        r = lint(
            "tensorsrc dimensions=4 num-frames=1 ! tensor_filter "
            "framework=scaler custom=factor:2.0 plane=w3 ! tensor_sink"
        )
        assert "NNS-W118" not in [d.code for d in r.report.diagnostics]
        # multi-stream with rings armed: the fixed shape — silent
        desc = (
            "tensorsrc dimensions=4 num-frames=1 ! tensor_filter "
            "framework=scaler custom=factor:2.0 plane=w4 ring-depth=2 "
            "! tensor_sink "
            "tensorsrc dimensions=4 num-frames=1 ! tensor_filter "
            "framework=scaler custom=factor:2.0 plane=w4 ring-depth=2 "
            "! tensor_sink"
        )
        r = lint(desc)
        assert "NNS-W118" not in [d.code for d in r.report.diagnostics]


# ---------------------------------------------------------------------------
# the churn soak
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_8stream_async_churn():
    """8 async streams (mixed ring depths and weights) × 200 frames
    under sustained load: every stream's frames arrive, in order, with
    the in-flight rings engaged and the accounting balanced."""
    n, N = 8, 200
    descs = [
        f"tensorsrc dimensions=16 pattern=counter num-frames={N} ! "
        "tensor_filter framework=scaler custom=factor:2.0 plane=asoak "
        f"plane-max-batch=16 ring-depth={1 + (i % 3)} "
        f"plane-weight={1.0 + (i % 2)} max-batch=2 ! tensor_sink"
        for i in range(n)
    ]
    pipes, execs = _run_streams(descs, timeout=300)
    for p in pipes:
        sink = next(e for e in p.elements if isinstance(e, TensorSink))
        vals = [float(np.asarray(f.tensors[0])[0]) for f in sink.frames]
        assert vals == [2.0 * j for j in range(N)]
    for ex in execs:
        tot = ex.totals()
        assert tot["produced"] == tot["rendered"] == N
        assert tot["balance"] == 0
    assert plane_mod.get("asoak") is None
