"""Mesh-sharded tensor_filter: pjit over a named mesh through the public
filter surfaces (custom="mesh:...", accelerator mesh clause, programmatic
set_shardings), with output parity against the unsharded run.

Reference analogue: the accelerator-selection machinery of
tensor_filter_common.c:451- ; here the accelerator *is* a device mesh and
partitioning is GSPMD's job. Runs on the virtual 8-CPU mesh (conftest).
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.base import BackendError
from nnstreamer_tpu.single import SingleShot

MODEL_OPTS = "size:64,batch:8,num_classes:16"


def _frames(batch=8, size=64, n=2):
    rng = np.random.default_rng(7)
    return [
        rng.integers(0, 255, (batch, size, size, 3), np.uint8) for _ in range(n)
    ]


@pytest.fixture(scope="module")
def unsharded_outs():
    frames = _frames()
    with SingleShot(
        framework="jax", model="zoo:mobilenet_v2", custom=MODEL_OPTS
    ) as s:
        return [np.asarray(s.invoke(f)[0]) for f in frames]


@pytest.mark.parametrize("mesh", ["dp2tp4", "dp8", "tp4"])
def test_mesh_custom_option_parity(mesh, unsharded_outs):
    frames = _frames()
    with SingleShot(
        framework="jax",
        model="zoo:mobilenet_v2",
        custom=f"{MODEL_OPTS},mesh:{mesh}",
    ) as s:
        for f, ref in zip(frames, unsharded_outs):
            out = np.asarray(s.invoke(f)[0])
            assert out.shape == ref.shape
            # resharded reductions reorder float adds; parity is numeric,
            # not bitwise
            np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_accelerator_mesh_clause_parity(unsharded_outs):
    frames = _frames()
    with SingleShot(
        framework="jax",
        model="zoo:mobilenet_v2",
        custom=MODEL_OPTS,
        accelerator="true:tpu:mesh=dp4tp2",
    ) as s:
        out = np.asarray(s.invoke(frames[0])[0])
        np.testing.assert_allclose(out, unsharded_outs[0], rtol=2e-4, atol=2e-4)


def test_sharded_params_actually_sharded():
    """tp>1 must shard real weight arrays across devices, not replicate."""
    with SingleShot(
        framework="jax",
        model="zoo:mobilenet_v2",
        custom=f"{MODEL_OPTS},mesh:tp4",
    ) as s:
        b = s.backend
        assert b._params_explicit
        import jax

        leaves = jax.tree_util.tree_leaves(b._placed_params)
        sharded = [
            l for l in leaves
            if hasattr(l, "sharding") and not l.sharding.is_fully_replicated
        ]
        assert sharded, "no parameter leaf is sharded under mesh:tp4"
        # a sharded leaf's per-device shard is smaller than the full array
        l = max(sharded, key=lambda x: x.size)
        shard_sizes = {sh.data.size for sh in l.addressable_shards}
        assert all(sz < l.size for sz in shard_sizes)


def test_set_shardings_programmatic():
    """The parallel layer's programmatic entry compiles and runs."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from nnstreamer_tpu.backends.jax_backend import JaxBackend
    from nnstreamer_tpu.backends.base import FilterProps
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axes=("dp",))
    be = JaxBackend()
    be.open(
        FilterProps(
            framework="jax",
            model=("zoo:mobilenet_v2",),
            custom=MODEL_OPTS,
        )
    )
    ref = np.asarray(be.invoke((_frames(n=1)[0],))[0])
    be.set_shardings([NamedSharding(mesh, P("dp"))])
    out = np.asarray(be.invoke((_frames(n=1)[0],))[0])
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_mesh_in_pipeline():
    """TP inference inside a running pipeline: sharded filter stage, host
    sink; parity with the unsharded pipeline run."""
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    results = {}
    for tag, extra in (("plain", ""), ("sharded", ",mesh:tp4")):
        p = parse_pipeline(
            "videotestsrc pattern=gradient num-frames=3 width=64 height=64 ! "
            "tensor_converter ! "
            "tensor_filter framework=jax model=zoo:mobilenet_v2 "
            f'custom="size:64,num_classes:16{extra}" ! '
            "tensor_sink"
        )
        p.run(timeout=300)
        sink = next(e for e in p.elements if isinstance(e, TensorSink))
        results[tag] = [np.asarray(f.tensors[0]) for f in sink.frames]
    assert len(results["plain"]) == len(results["sharded"]) == 3
    for a, b in zip(results["plain"], results["sharded"]):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4)


def test_bad_mesh_spec_rejected():
    with pytest.raises(BackendError):
        SingleShot(
            framework="jax",
            model="zoo:mobilenet_v2",
            custom=f"{MODEL_OPTS},mesh:bogus",
        ).open()


def test_mesh_too_many_devices_rejected():
    with pytest.raises(BackendError):
        SingleShot(
            framework="jax",
            model="zoo:mobilenet_v2",
            custom=f"{MODEL_OPTS},mesh:dp64",
        ).open()


def test_device_and_mesh_exclusive():
    with pytest.raises(BackendError):
        SingleShot(
            framework="jax",
            model="zoo:mobilenet_v2",
            custom=f"{MODEL_OPTS},mesh:dp2,device:0",
        ).open()
