"""Overload-resilient multi-tenant query serving (docs/edge-serving.md).

Admission caps → structured NACKs, per-client weighted-fair scheduling,
token-bucket rate limiting with honored retry-after hints, deadline-aware
shedding at executor dequeue (with the frame-accounting invariant intact),
the chaos harness's network-fault modes, the shm query transport, and the
NNS-W111 lint. The real multi-client soak (2× offered load + injected
connection faults + a slow-loris) is marked ``slow`` — the tier-1 portion
here stays fast.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge.admission import (
    AdmissionConfig,
    AdmissionController,
)
from nnstreamer_tpu.edge.query import (
    TensorQueryClient,
    TensorQueryServerSink,
    TensorQueryServerSrc,
)
from nnstreamer_tpu.edge.serialize import (
    Nack,
    decode_message,
    encode_message,
    encode_nack,
)
from nnstreamer_tpu.edge.transport import PyTransport
from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.tensors.frame import Frame


def _frame(val: float = 0.0, **meta) -> Frame:
    return Frame((np.full(4, val, np.float32),), meta=meta)


def _req(val: float = 0.0) -> bytes:
    return encode_message(_frame(val))


def _echo_server(src, sink, stop_evt, scale=2.0):
    while not stop_evt.is_set():
        frame = src.generate()
        if frame is None:
            continue
        sink.render(
            frame.with_tensors([np.asarray(t) * scale for t in frame.tensors])
        )


# ------------------------------------------------------------------ wire
def test_nack_wire_roundtrip():
    n = decode_message(encode_nack("overload", 75.5, frame_id="a.b.3"))
    assert isinstance(n, Nack)
    assert n.reason == "overload"
    assert n.retry_after_ms == 75.5
    assert n.frame_id == "a.b.3"
    # reasons without hints decode too
    n2 = decode_message(encode_nack("malformed"))
    assert n2.reason == "malformed" and n2.retry_after_ms == 0.0


# ------------------------------------------------- controller unit tests
def test_admission_global_and_per_client_caps():
    ctrl = AdmissionController(
        AdmissionConfig(max_inflight=3, per_client_inflight=2)
    )
    assert ctrl.offer("a", _frame()).ok
    assert ctrl.offer("a", _frame()).ok
    d = ctrl.offer("a", _frame())
    assert not d.ok and d.reason == "client-backpressure"
    assert ctrl.offer("b", _frame()).ok
    d = ctrl.offer("b", _frame())  # global cap (3) before b's own (2)
    assert not d.ok and d.reason == "overload" and d.retry_after_ms > 0
    # release returns budget; same client admits again
    ctrl.release("a")
    assert ctrl.offer("b", _frame()).ok
    snap = ctrl.snapshot()
    assert snap["rejected_by_reason"] == {
        "client-backpressure": 1, "overload": 1
    }


def test_admission_max_clients_and_client_gone():
    ctrl = AdmissionController(AdmissionConfig(max_clients=2))
    assert ctrl.offer("a", _frame()).ok
    assert ctrl.offer("b", _frame()).ok
    d = ctrl.offer("c", _frame())
    assert not d.ok and d.reason == "max-clients"
    ctrl.client_gone("a")  # slot freed (queued request flushed too)
    assert ctrl.offer("c", _frame()).ok
    assert ctrl.snapshot()["inflight"] == 2  # a's queued request flushed


def test_admission_token_bucket_deterministic():
    ctrl = AdmissionController(AdmissionConfig(rate=10.0, burst=2))
    t0 = 1000.0
    assert ctrl.offer("a", _frame(), now=t0).ok
    assert ctrl.offer("a", _frame(), now=t0).ok
    d = ctrl.offer("a", _frame(), now=t0)  # bucket drained
    assert not d.ok and d.reason == "rate"
    # the hint reflects the actual refill deficit: 1 token at 10/s = 100 ms
    assert 50.0 <= d.retry_after_ms <= 150.0
    # 100 ms later one token refilled
    assert ctrl.offer("a", _frame(), now=t0 + 0.1).ok
    assert not ctrl.offer("a", _frame(), now=t0 + 0.1).ok


def test_fair_share_hot_client_and_priority():
    ctrl = AdmissionController(AdmissionConfig(max_inflight=100))
    for i in range(6):
        assert ctrl.offer("hot", _frame(i)).ok
    assert ctrl.offer("cold", _frame(100.0)).ok
    assert ctrl.offer("cold", _frame(101.0)).ok
    order = [
        float(np.asarray(ctrl.next_ready().tensors[0])[0]) for _ in range(4)
    ]
    # round-robin: the cold client is served within the first rounds,
    # never starved behind the hot client's backlog
    assert 100.0 in order[:2] and 101.0 in order[:4], order
    # strict priority: class 0 preempts the class-1 backlog
    assert ctrl.offer("vip", _frame(7.0, priority=0)).ok
    got = ctrl.next_ready()
    assert float(np.asarray(got.tensors[0])[0]) == 7.0


# ------------------------------------------- server-level NACK round trips
def test_server_nacks_over_per_client_budget():
    src = TensorQueryServerSrc(
        "ov-src1", port=0, id="ov1", **{"per-client-inflight": 2}
    )
    src.start()
    raw = PyTransport()
    try:
        raw.connect("127.0.0.1", src.bound_port)
        for i in range(3):
            raw.send(0, _req(float(i)))
        time.sleep(0.2)  # let the reader thread enqueue all three
        # one generate() drains the transport: 2 admitted, 1 NACKed
        frame = src.generate()
        assert frame is not None and frame.meta.get("client_id") == 1
        assert frame.meta.get("admit_t") is not None
        got = raw.recv(timeout=2)
        assert got is not None
        nack = decode_message(got[1])
        assert isinstance(nack, Nack)
        assert nack.reason == "client-backpressure"
        stats = src.admission_stats()
        assert stats["admitted"] == 2 and stats["rejected"] == 1
    finally:
        raw.close()
        src.stop()


def test_server_nacks_malformed_request():
    src = TensorQueryServerSrc(
        "ov-src2", port=0, id="ov2", **{"max-inflight": 4}
    )
    src.start()
    raw = PyTransport()
    try:
        raw.connect("127.0.0.1", src.bound_port)
        raw.send(0, b"\x02\x00")  # truncated edge header
        time.sleep(0.2)
        assert src.generate() is None
        got = raw.recv(timeout=2)
        nack = decode_message(got[1])
        assert isinstance(nack, Nack) and nack.reason == "malformed"
        assert src.admission_stats()["malformed"] == 1
    finally:
        raw.close()
        src.stop()


def test_connection_cap_rejects_with_nack():
    src = TensorQueryServerSrc(
        "ov-src3", port=0, id="ov3", **{"max-clients": 1}
    )
    src.start()
    c1 = PyTransport()
    c2 = PyTransport()
    try:
        c1.connect("127.0.0.1", src.bound_port)
        c1.send(0, _req())
        time.sleep(0.1)
        assert src.generate() is not None  # c1 is established
        c2.connect("127.0.0.1", src.bound_port)  # over the cap
        got = c2.recv(timeout=2)
        assert got is not None
        nack = decode_message(got[1])
        assert isinstance(nack, Nack) and nack.reason == "max-clients"
        # the over-cap socket is closed after the NACK
        got = c2.recv(timeout=2)
        assert got is not None and got[1] == b""
        assert src.admission_stats()["rejected_conns"] == 1
    finally:
        c1.close()
        c2.close()
        src.stop()


def test_client_honors_retry_after_nack():
    """Rate-limited server: the client retries on the NACK's hint and the
    request eventually completes — no timeout, no raise."""
    src = TensorQueryServerSrc(
        "ov-src4", port=0, id="ov4", **{"rate": 10.0, "rate-burst": 1}
    )
    sink = TensorQueryServerSink("ov-sink4", id="ov4")
    src.start()
    stop_evt = threading.Event()
    t = threading.Thread(
        target=_echo_server, args=(src, sink, stop_evt), daemon=True
    )
    t.start()
    client = TensorQueryClient(
        "ov-c4",
        **{"dest-port": src.bound_port, "timeout": 5, "retry-max": 6},
    )
    try:
        client.start()
        # burst=1: back-to-back requests exhaust the bucket, forcing at
        # least one NACK+retry on the later ones
        for i in range(3):
            reply = client.process(_frame(float(i)))
            np.testing.assert_allclose(
                np.asarray(reply.tensors[0]), np.full(4, 2.0 * i)
            )
        stats = src.admission_stats()
        assert stats["rejected_by_reason"].get("rate", 0) >= 1
    finally:
        stop_evt.set()
        client.stop()
        t.join(timeout=2)
        src.stop()


def test_client_rejected_after_retry_budget():
    """A server whose budget never frees: the client raises a typed
    rejection (terminal outcome), not a timeout."""
    src = TensorQueryServerSrc(
        "ov-src5", port=0, id="ov5", **{"max-inflight": 1}
    )
    src.start()
    # a parked request holds the only budget unit forever (no sink loop)
    raw = PyTransport()
    try:
        raw.connect("127.0.0.1", src.bound_port)
        raw.send(0, _req())
        time.sleep(0.2)
        assert src.generate() is not None
        client = TensorQueryClient(
            "ov-c5",
            **{"dest-port": src.bound_port, "timeout": 5, "retry-max": 1,
               "retry-backoff-ms": 5},
        )
        client.start()
        done = threading.Event()

        def poll():  # keep draining the transport so NACKs flow
            while not done.is_set():
                src.generate()

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        with pytest.raises(ElementError, match="rejected.*overload"):
            client.process(_frame())
        done.set()
        client.stop()
        poller.join(timeout=5)
    finally:
        raw.close()
        src.stop()


def test_fault_policy_drop_releases_budget_and_nacks():
    """An admitted request dropped by on-error=drop must release its
    in-flight budget (no permanent pinning) and NACK the client with the
    terminal `failed` reason — never a silent client-side timeout."""
    from nnstreamer_tpu.elements.chaos import TensorChaos
    from nnstreamer_tpu.pipeline.graph import Pipeline

    src = TensorQueryServerSrc(
        "ov-src9", port=0, id="ov9",
        **{"max-inflight": 2, "per-client-inflight": 2},
    )
    bad = TensorChaos("bad9", **{"fail-every-n": 1, "on-error": "drop"})
    sink = TensorQueryServerSink("ov-sink9", id="ov9")
    p = Pipeline("dropall").chain(src, bad, sink)
    p.negotiate()
    ex = p.start()
    client = TensorQueryClient(
        "ov-c9", **{"dest-port": src.bound_port, "timeout": 5}
    )
    try:
        client.start()
        # more requests than the in-flight budget: without the release
        # on disposal the 3rd+ request would be NACKed 'overload'
        for _ in range(4):
            with pytest.raises(ElementError, match="failed the request"):
                client.process(_frame())
        stats = src.admission_stats()
        assert stats["inflight"] == 0, stats  # budget fully returned
        assert not stats["rejected_by_reason"], stats
    finally:
        client.stop()
        p.stop()
    assert not ex.errors, ex.errors


def test_legacy_server_survives_malformed_request():
    """Without admission bounds the serversrc must still NACK garbage
    instead of crashing the serving pipeline for every client."""
    src = TensorQueryServerSrc("ov-src10", port=0, id="ov10")
    src.start()
    raw = PyTransport()
    try:
        raw.connect("127.0.0.1", src.bound_port)
        raw.send(0, b"\x02\x00")  # truncated edge header
        time.sleep(0.2)
        assert src.generate() is None  # consumed, not raised
        nack = decode_message(raw.recv(timeout=2)[1])
        assert isinstance(nack, Nack) and nack.reason == "malformed"
        # the server keeps serving well-formed requests afterwards
        raw.send(0, _req(5.0))
        time.sleep(0.2)
        frame = src.generate()
        assert frame is not None
        assert float(np.asarray(frame.tensors[0])[0]) == 5.0
    finally:
        raw.close()
        src.stop()


def test_conn_nack_retry_recovers_after_slot_frees():
    """A connection-level max-clients NACK closes the socket; the client
    must reconnect for the retry (not resend into the dead socket) and
    succeed once the slot frees."""
    src = TensorQueryServerSrc(
        "ov-src12", port=0, id="ov12", **{"max-clients": 1}
    )
    sink = TensorQueryServerSink("ov-sink12", id="ov12")
    src.start()
    stop_evt = threading.Event()
    t = threading.Thread(
        target=_echo_server, args=(src, sink, stop_evt), daemon=True
    )
    t.start()
    holder = PyTransport()
    try:
        holder.connect("127.0.0.1", src.bound_port)
        holder.send(0, _req())
        holder.recv(timeout=5)  # established + served: holds the slot
        threading.Timer(0.3, holder.close).start()  # slot frees mid-retry
        client = TensorQueryClient(
            "ov-c12",
            **{"dest-port": src.bound_port, "timeout": 5, "retry-max": 10,
               "retry-backoff-ms": 30},
        )
        client.start()
        reply = client.process(_frame(21.0))
        np.testing.assert_allclose(
            np.asarray(reply.tensors[0]), np.full(4, 42.0)
        )
        client.stop()
    finally:
        stop_evt.set()
        holder.close()
        t.join(timeout=2)
        src.stop()


def test_route_dead_letter_reply_releases_budget_once():
    """on-error=route with the dead-letter pad replying through the
    serversink: the budget is released at disposal and NOT again at the
    reply — exact accounting, no cap drift, and the client still gets a
    terminal (error-meta) reply."""
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    p = parse_pipeline(
        "tensor_query_serversrc name=qs port=0 id=ovr per-client-inflight=2"
        " ! tensor_chaos name=cx fail-every-n=1 on-error=route"
        " ! tensor_query_serversink id=ovr"
        "  cx.src_1 ! tensor_query_serversink id=ovr"
    )
    ex = p.start()
    qs = p["qs"]
    client = TensorQueryClient(
        "ov-c13", **{"dest-port": qs.bound_port, "timeout": 5}
    )
    try:
        client.start()
        for i in range(4):
            reply = client.process(_frame(float(i)))
            assert reply.meta.get("error") is True
            assert reply.meta.get("error_element") == "cx"
        stats = qs.admission_stats()
        assert stats["admitted"] == 4
        assert stats["released"] == 4  # exactly once per request
        assert stats["inflight"] == 0
    finally:
        client.stop()
        p.stop()
    assert not ex.errors, ex.errors


def test_admission_idle_client_eviction():
    """Broker transports never emit disconnects: fully-idle clients are
    evicted when the max-clients cap is hit, instead of pinning slots
    forever."""
    ctrl = AdmissionController(
        AdmissionConfig(max_clients=2, idle_evict_s=30.0)
    )
    t0 = 1000.0
    assert ctrl.offer("a", _frame(), now=t0).ok
    assert ctrl.offer("b", _frame(), now=t0).ok
    # drain and release both: fully idle, but within the horizon
    for _ in range(2):
        ctrl.next_ready()
    ctrl.release("a")
    ctrl.release("b")
    d = ctrl.offer("c", _frame(), now=t0 + 5.0)
    assert not d.ok and d.reason == "max-clients"
    # past the idle horizon both slots reclaim
    assert ctrl.offer("c", _frame(), now=t0 + 31.0).ok


# --------------------------------------------------- deadline shedding
def test_deadline_shed_in_pipeline_accounting(monkeypatch):
    """Expired frames are dropped at dequeue BEFORE the fused program
    runs; accounting (totals + the sanitizer's offered == delivered +
    dropped + routed latch) stays exact under shedding."""
    monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline

    now = time.monotonic()
    frames = []
    for i in range(10):
        if i % 2:
            meta = {"deadline_ms": 60000.0, "admit_t": now}
        else:  # already expired at admission
            meta = {"deadline_ms": 50.0, "admit_t": now - 1.0}
        frames.append(Frame((np.full(4, float(i), np.float32),), meta=meta))
    src = AppSrc("a0", iterable=frames, spec=frames[0].spec())
    filt = TensorFilter(
        "shed-filt", framework="passthrough", input="4", inputtype="float32"
    )
    sink = TensorSink("out")
    p = Pipeline("shed").chain(src, filt, sink)
    p.negotiate()
    ex = p.start()
    assert ex.wait(timeout=30)
    p.stop()
    assert not ex.errors, ex.errors
    assert len(sink.frames) == 5  # only unexpired frames survive
    totals = ex.totals()
    assert totals["dropped"].get("deadline-shed") == 5
    assert totals["balance"] == 0
    assert ex.stats()["shed-filt"]["deadline_shed"] == 5
    assert not ex.sanitizer.codes  # NNS-S002 did NOT fire under shedding
    assert not ex.leaked_threads


def test_deadline_shed_nacks_edge_client():
    """A queued request whose SLO expires behind a slow frame is shed and
    the client receives a terminal `deadline` NACK — never a silent
    timeout."""
    from nnstreamer_tpu.elements.chaos import TensorChaos
    from nnstreamer_tpu.pipeline.graph import Pipeline

    src = TensorQueryServerSrc(
        "ov-src6", port=0, id="ov6", **{"max-inflight": 8}
    )
    slow = TensorChaos("slow6", **{"delay-ms": 300.0})
    sink = TensorQueryServerSink("ov-sink6", id="ov6")
    p = Pipeline("dl").chain(src, slow, sink)
    p.negotiate()
    ex = p.start()
    c1 = TensorQueryClient(
        "ov-c6a", **{"dest-port": src.bound_port, "timeout": 10}
    )
    c2 = TensorQueryClient(
        "ov-c6b",
        **{"dest-port": src.bound_port, "timeout": 10, "deadline-ms": 80},
    )
    try:
        c1.start()
        c2.start()
        # c1's request occupies the slow element for ~300 ms; c2's
        # 80 ms-deadline request queues behind it and must be shed
        t1 = threading.Thread(
            target=lambda: c1.process(_frame(1.0)), daemon=True
        )
        t1.start()
        time.sleep(0.1)
        with pytest.raises(ElementError, match="shed.*deadline"):
            c2.process(_frame(2.0))
        t1.join(timeout=10)
        assert not t1.is_alive()
    finally:
        c1.stop()
        c2.stop()
        p.stop()
    assert sum(
        s.get("deadline_shed", 0) for s in ex.stats().values()
    ) == 1
    assert not ex.errors, ex.errors


# ------------------------------------------------------ chaos net faults
def test_chaos_drop_and_truncate_all_requests_complete():
    src = TensorQueryServerSrc(
        "ov-src7", port=0, id="ov7", **{"max-inflight": 8}
    )
    sink = TensorQueryServerSink("ov-sink7", id="ov7")
    src.start()
    stop_evt = threading.Event()
    t = threading.Thread(
        target=_echo_server, args=(src, sink, stop_evt), daemon=True
    )
    t.start()
    client = TensorQueryClient(
        "ov-c7",
        **{"dest-port": src.bound_port, "timeout": 5, "retry-max": 4,
           "retry-backoff-ms": 5, "chaos-drop-every-n": 3,
           "chaos-truncate-every-n": 4},
    )
    try:
        client.start()
        for i in range(10):
            reply = client.process(_frame(float(i)))
            np.testing.assert_allclose(
                np.asarray(reply.tensors[0]), np.full(4, 2.0 * i)
            )
        # the truncation schedule fired and produced structured NACKs
        assert src.admission_stats().get("malformed", 0) >= 1
    finally:
        stop_evt.set()
        client.stop()
        t.join(timeout=2)
        src.stop()


# --------------------------------------------------------- shm transport
def _shm_available() -> bool:
    from nnstreamer_tpu.edge._build import build_native

    return build_native("nns_shm.cpp") is not None


@pytest.mark.skipif(not _shm_available(), reason="no C++ toolchain")
def test_shm_query_transport_parity_with_tcp():
    """connect-type=SHM serves the same request/reply semantics as TCP
    (values, pts, frame_id meta), minus the sockets."""
    results = {}
    for ct in ("TCP", "SHM"):
        src = TensorQueryServerSrc(
            f"ov-src8{ct}", port=0, id=f"ov8{ct}",
            **{"connect-type": ct, "max-inflight": 4},
        )
        sink = TensorQueryServerSink(f"ov-sink8{ct}", id=f"ov8{ct}")
        src.start()
        stop_evt = threading.Event()
        t = threading.Thread(
            target=_echo_server, args=(src, sink, stop_evt), daemon=True
        )
        t.start()
        client = TensorQueryClient(
            f"ov-c8{ct}",
            **{"dest-port": src.bound_port, "timeout": 5,
               "connect-type": ct},
        )
        try:
            client.start()
            got = []
            for i in range(4):
                r = client.process(
                    Frame((np.full(4, float(i), np.float32),), pts=i * 10)
                )
                got.append((
                    float(np.asarray(r.tensors[0])[0]), r.pts,
                    r.meta.get("frame_id") is not None,
                ))
            results[ct] = got
        finally:
            stop_evt.set()
            client.stop()
            t.join(timeout=2)
            src.stop()
    assert results["SHM"] == results["TCP"]


# ----------------------------------------------------------------- lint
def test_lint_warns_unbounded_query_server():
    from nnstreamer_tpu.analysis.lint import lint

    bare = lint(
        "tensor_query_serversrc port=5001 ! tensor_query_serversink"
    )
    assert "NNS-W111" in bare.report.codes
    bounded = lint(
        "tensor_query_serversrc port=5001 max-inflight=8 ! "
        "tensor_query_serversink"
    )
    assert "NNS-W111" not in bounded.report.codes


# ------------------------------------------------------------- the soak
@pytest.mark.slow
def test_overload_soak_two_x_capacity_with_faults(monkeypatch):
    """The standing chaos soak (docs/edge-serving.md): N concurrent
    clients at ~2× the admitted capacity against a bounded server with
    backend latency spikes, injected connection drops, and a slow-loris
    connection. Every request reaches a terminal outcome (completed,
    NACKed, or shed — no silent timeouts), accepted-request p99 stays
    bounded, and the run ends with zero leaked threads and zero
    stall-watchdog firings."""
    import socket as socket_mod

    monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.pipeline.executor import Executor
    from nnstreamer_tpu.pipeline.graph import Pipeline

    src = TensorQueryServerSrc(
        "soak-src", port=0, id="soak",
        **{"max-clients": 12, "max-inflight": 8, "per-client-inflight": 2,
           "retry-after-ms": 20},
    )
    filt = TensorFilter(
        framework="faulty", input="4", inputtype="float32",
        custom="latency_spike_ms:40,spike_every_n:7",
    )
    sink = TensorQueryServerSink("soak-sink", id="soak")
    p = Pipeline("soak").chain(src, filt, sink)
    p.negotiate()
    plan = p.compile_plan()
    ex = Executor(plan)
    # watchdog armed well above the worst single invoke (40 ms spike)
    ex.watchdog_timeout_ms = 5000.0
    ex.start()

    n_clients, n_requests = 6, 25
    outcomes = []          # (kind, latency_s)
    outcomes_mu = threading.Lock()

    def run_client(idx: int) -> None:
        props = {
            "dest-port": src.bound_port, "timeout": 8, "retry-max": 8,
            "retry-backoff-ms": 10, "deadline-ms": 4000,
        }
        if idx % 3 == 0:  # a third of the fleet drops connections
            props["chaos-drop-every-n"] = 5
        client = TensorQueryClient(f"soak-c{idx}", **props)
        client.start()
        try:
            for i in range(n_requests):
                t0 = time.perf_counter()
                try:
                    reply = client.process(_frame(float(i)))
                    assert reply is not None
                    kind = "completed"
                except ElementError as exc:
                    msg = str(exc)
                    if "deadline" in msg:
                        kind = "shed"
                    elif "rejected" in msg:
                        kind = "nacked"
                    else:
                        kind = f"error:{msg[:60]}"
                with outcomes_mu:
                    outcomes.append((kind, time.perf_counter() - t0))
        finally:
            client.stop()

    # slow-loris: connects, sends half a length prefix, stalls. It must
    # neither crash the acceptor nor consume admission budget.
    loris = socket_mod.create_connection(
        ("127.0.0.1", src.bound_port), timeout=5
    )
    loris.sendall(b"\xff\xff\xff")

    threads = [
        threading.Thread(target=run_client, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "client thread hung"
    loris.close()
    ex.stop()

    # every request reached a terminal outcome, none of them a timeout
    # or an unexpected transport error
    assert len(outcomes) == n_clients * n_requests
    kinds = {}
    for kind, _ in outcomes:
        kinds[kind] = kinds.get(kind, 0) + 1
    unexpected = {
        k: v for k, v in kinds.items()
        if k not in ("completed", "shed", "nacked")
    }
    assert not unexpected, (unexpected, kinds)
    assert kinds.get("completed", 0) >= n_clients * n_requests // 2, kinds

    # accepted-request p99 stays bounded (spikes are 40 ms; generous
    # ceiling absorbs scheduler noise, not queueing collapse)
    lats = sorted(lat for kind, lat in outcomes if kind == "completed")
    p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
    assert p99 < 3.0, f"p99 {p99:.3f}s — latency collapsed under load"

    assert not ex.stalled, "stall watchdog fired during the soak"
    assert not ex.errors, ex.errors
    assert not ex.leaked_threads, ex.leaked_threads
    # the server actually exercised its admission machinery
    stats = src.admission_stats()
    assert stats["admitted"] >= kinds.get("completed", 0)
    # offered == delivered + dropped + routed holds per interior node
    # under shedding (sources have no input channel, so their offered
    # count is structurally 0; forced stop leaves bounded in-flight,
    # never a negative balance)
    checked = 0
    for name, row in ex.stats().items():
        if not row.get("san_offered"):
            continue
        checked += 1
        balance = (
            row["san_offered"] - row["san_delivered"]
            - row["san_routed"] - row.get("deadline_shed", 0)
            - row.get("error_dropped", 0)
        )
        assert balance >= 0, (name, row)
    assert checked >= 2  # the filter node and the serversink saw frames
