"""Native shared-memory ring transport tests (native/nns_shm.cpp via
edge/shm.py) — the same-host zero-socket fast path of the among-device
layer. Includes a true cross-process producer (subprocess), wraparound
coverage, and the edgesink/edgesrc connect-type=SHM loopback."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from nnstreamer_tpu.edge.shm import ShmTransport, segment_name
from nnstreamer_tpu.edge.transport import TransportError

def _shm_available() -> bool:
    try:
        from nnstreamer_tpu.edge import shm as _shm

        _shm._get_lib()
        return True
    except Exception:  # build failed or sanitizer .so can't dlopen
        return False


pytestmark = pytest.mark.skipif(
    not _shm_available(),
    reason="native shm lib unavailable (toolchain, or sanitizer build "
           "without LD_PRELOAD)",
)


def _pair(port, capacity=64 * 1024):
    prod = ShmTransport(capacity=capacity)
    bound = prod.listen("", port)
    cons = ShmTransport()
    cons.connect("", bound)
    return prod, cons


def test_roundtrip_and_order(tmp_path):
    prod, cons = _pair(41001)
    try:
        for i in range(32):
            prod.send(0, bytes([i]) * (i + 1))
        for i in range(32):
            cid, payload = cons.recv(timeout=2)
            assert payload == bytes([i]) * (i + 1)
    finally:
        cons.close()
        prod.close()


def test_wraparound_many_messages():
    """Messages much larger than capacity/N force repeated wrap markers."""
    prod, cons = _pair(41002, capacity=8 * 1024)
    msgs = [os.urandom(700) for _ in range(200)]
    errs = []

    def pump():
        try:
            for m in msgs:
                prod.send(0, m, timeout=5)
        except Exception as exc:  # noqa: BLE001
            errs.append(exc)

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        got = [cons.recv(timeout=5)[1] for _ in range(len(msgs))]
        assert got == msgs
        t.join(timeout=5)
        assert not errs
    finally:
        cons.close()
        prod.close()


def test_reader_count_and_timeout():
    prod = ShmTransport()
    port = prod.listen("", 41003)
    try:
        assert prod.peer_count() == 0
        cons = ShmTransport()
        cons.connect("", port)
        assert prod.peer_count() == 1
        assert cons.recv(timeout=0.05) is None  # empty ring times out
        cons.close()
        assert prod.peer_count() == 0
    finally:
        prod.close()


def test_close_drains_then_eos():
    prod, cons = _pair(41004)
    prod.send(0, b"last")
    prod.close()  # marks closed + unlinks
    assert cons.recv(timeout=2) == (0, b"last")
    assert cons.recv(timeout=2) == (0, b"")  # closed + drained
    cons.close()


def test_large_message_grows_reader_buffer():
    prod, cons = _pair(41005, capacity=32 * 1024 * 1024)
    big = os.urandom(9 * 1024 * 1024)  # > initial 4 MB reader buffer
    prod.send(0, big, timeout=10)
    got = cons.recv(timeout=10)
    assert got[1] == big
    cons.close()
    prod.close()


def test_oversized_message_rejected():
    prod, cons = _pair(41006, capacity=8 * 1024)
    with pytest.raises(TransportError):
        prod.send(0, b"x" * (64 * 1024), timeout=1)
    cons.close()
    prod.close()


def test_connect_without_producer_fails():
    t = ShmTransport()
    with pytest.raises(TransportError, match="producer"):
        t.connect("", 49999)


def test_cross_process_consumer():
    """A different PROCESS reads the ring this one writes (the real
    deployment shape: two pipelines on one host). Messages are queued
    before the child spawns, so the test is race-free."""
    port = 41007
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prod = ShmTransport()
    prod.listen("", port)
    prod.send(0, b"hello")
    prod.send(0, b"world")
    child = subprocess.run(
        [sys.executable, "-c", (
            f"import sys; sys.path.insert(0, {repo!r})\n"
            "from nnstreamer_tpu.edge.shm import ShmTransport\n"
            "t = ShmTransport()\n"
            f"t.connect('', {port})\n"
            "print(t.recv(timeout=10)[1].decode())\n"
            "print(t.recv(timeout=10)[1].decode())\n"
            "t.close()\n"
        )],
        capture_output=True, text=True, timeout=60,
    )
    assert child.returncode == 0, child.stderr[-400:]
    assert child.stdout.split() == ["hello", "world"]
    prod.close()


def test_edgesink_edgesrc_shm_pipeline():
    """connect-type=SHM end to end through the pipeline elements."""
    from nnstreamer_tpu.edge.pubsub import EdgeSink, EdgeSrc
    from nnstreamer_tpu.tensors.frame import EOS_FRAME, Frame

    sink = EdgeSink(**{"connect-type": "SHM", "port": 41008})
    sink.start()
    src = EdgeSrc(**{"connect-type": "SHM", "dest-port": sink.bound_port})
    src.start()
    try:
        frames = [
            Frame((np.full((2, 2), i, np.float32),), pts=i * 1000)
            for i in range(5)
        ]
        for f in frames:
            sink.render(f)
        got = []
        while len(got) < 5:
            f = src.generate()
            if f is not None and f is not EOS_FRAME:
                got.append(f)
        for sent, rcv in zip(frames, got):
            np.testing.assert_array_equal(
                np.asarray(sent.tensors[0]), np.asarray(rcv.tensors[0])
            )
            assert rcv.pts == sent.pts
        sink.on_eos()
        f = None
        while f is None:
            f = src.generate()
        assert f is EOS_FRAME
    finally:
        src.stop()
        sink.stop()


def test_live_producer_name_not_clobbered():
    """Second producer on the same port must fail (TCP EADDRINUSE
    analogue); after the first closes cleanly the name is reclaimable."""
    a = ShmTransport()
    port = a.listen("", 41009)
    b = ShmTransport()
    with pytest.raises(TransportError, match="live producer"):
        b.listen("", port)
    a.close()  # marks closed + unlinks → name free again
    c = ShmTransport()
    assert c.listen("", port) == port
    c.close()


def test_oversized_message_error_names_capacity():
    prod = ShmTransport(capacity=8 * 1024)
    prod.listen("", 41010)
    with pytest.raises(TransportError, match="capacity"):
        prod.send(0, b"x" * (5 * 1024))
    prod.close()


def test_edgesink_oversized_frame_fails_loudly():
    """A frame that can NEVER fit the ring is a pipeline error with the
    remedy in the message, not an eternal silent drop."""
    from nnstreamer_tpu.edge.pubsub import EdgeSink
    from nnstreamer_tpu.elements.base import ElementError
    from nnstreamer_tpu.tensors.frame import Frame

    sink = EdgeSink(**{"connect-type": "SHM", "port": 41011,
                       "shm-capacity": 64 * 1024})
    sink.start()
    try:
        big = Frame((np.zeros(128 * 1024, np.uint8),))
        with pytest.raises(ElementError, match="shm-capacity"):
            sink.render(big)
    finally:
        sink.stop()


def test_shm_close_during_traffic_stress():
    """Teardown race: producer closes mid-stream while the consumer is
    blocked in recv — must end with EOS (-1 → (0, b'')) or a clean
    timeout, never a crash/hang. Build with NNS_EDGE_SANITIZE=thread to
    run the ring under TSAN (same story as the edge transport stress)."""
    for round_i in range(6):
        prod, cons = _pair(41020 + round_i, capacity=16 * 1024)
        stop = threading.Event()
        got = []
        sent = 0

        def consume(c=cons, out=got, st=stop):
            while not st.is_set():
                r = c.recv(timeout=0.2)
                if r is None:
                    continue
                if r[1] == b"":
                    return  # closed + drained
                out.append(r[1])

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        for i in range(50):
            try:
                prod.send(0, os.urandom(256), timeout=1)
                sent += 1
            except TransportError:
                break
        prod.close()  # mark closed + unlink while consumer mid-recv
        stop.set()
        t.join(timeout=5)
        assert not t.is_alive(), "consumer hung through producer close"
        # the stress is only meaningful if traffic actually flowed
        assert sent >= 10, f"round {round_i}: only {sent} sends succeeded"
        assert got, f"round {round_i}: consumer received nothing"
        cons.close()
