"""Seeded concurrency violations for the nns-san race lint.

This file is SCANNED by tests/test_sanitizer.py (never imported at
runtime): every rule the race lint implements must fire here, so a check
that silently stops matching fails the suite. One section per code.

Expected findings:
- NNS-R001 x2 (UnlockedCounter.count, both write sites)
- NNS-R002 x1 (SleepyLock.slow)
- NNS-R003 x1 (swallow_everything)
- NNS-R004 x1 (service_loop)
- NNS-R005 x1 (fire_and_forget)
- NNS-R006 x3 (BrokenChan: unchecked append, park without re-check,
  unchecked popleft)
"""

import threading
import time
from collections import deque


class UnlockedCounter:
    """NNS-R001: a thread-spawning class read-modify-writes a shared
    counter from two methods with no lock at either site."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.worker = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while True:
            self.count += 1  # writer 1: the service thread

    def bump(self):
        self.count += 1  # writer 2: whoever calls the public API


class SleepyLock:
    """NNS-R002: unbounded blocking call while holding a lock."""

    def __init__(self):
        self._lock = threading.Lock()

    def slow(self):
        with self._lock:
            time.sleep(1.0)


def swallow_everything(fn):
    """NNS-R003: bare except with no re-raise eats KeyboardInterrupt."""
    try:
        fn()
    except:  # the violation under test
        return None


def service_loop(q):
    """NNS-R004: a service loop that silently eats every failure."""
    while True:
        try:
            q.step()
        except Exception:
            continue


def fire_and_forget(fn):
    """NNS-R005: thread with neither daemon=True nor a join."""
    worker = threading.Thread(target=fn)
    worker.start()
    return worker


class BrokenChan:
    """NNS-R006: the _Chan Dekker pairing, violated on both sides."""

    def __init__(self):
        self._d = deque()
        self._data = threading.Event()
        self._get_waiting = False
        self._put_waiting = False

    def put(self, item):
        # mover side: no waiting-flag check after the deque op — a
        # parked consumer sleeps out its full beat
        self._d.append(item)

    def get(self):
        d = self._d
        while not d:
            self._get_waiting = True
            # waiter side: parks without re-checking the deque after
            # advertising the flag — a push in between is missed
            self._data.wait(0.05)
            self._get_waiting = False
        return d.popleft()
