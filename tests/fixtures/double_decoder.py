"""Golden-test python3 decoder: doubles every tensor value (uint8 wrap)."""
import numpy as np


class CustomDecoder:
    def negotiate(self, in_spec, options):
        return in_spec  # tensors in, tensors out

    def decode(self, tensors):
        return tuple(np.asarray(t) * 2 for t in tensors)
