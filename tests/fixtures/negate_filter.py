"""Golden-test python3 custom filter: bitwise-not of uint8 frames."""
import numpy as np


class CustomFilter:
    def setInputDim(self, in_spec):
        return in_spec

    def invoke(self, tensors):
        return tuple(255 - np.asarray(t) for t in tensors)
