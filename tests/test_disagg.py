"""Disaggregated prefill/decode serving (serving_plane/disagg.py,
docs/llm-serving.md "Disaggregated serving") and prefix-aware fleet
routing (edge/fleet.py, docs/edge-serving.md "Prefix-aware routing").

The headline invariants: a request prefilled on a ``role=prefill``
server and decoded on its ``role=decode`` peer finishes **bitwise
identical** to the solo run with **zero decode-side re-prefill** (the
``kv_prefill_chunks`` counter pins it), delivery stays at-most-once
(the decode server parks finished handoffs instead of emitting — the
prefill side owns DELIVER under the unchanged ``frame_id``), and a
refusing peer falls back to local decode with no token lost. On the
client: repeat-prefix requests route to the endpoint that last served
the longest matching prompt prefix, falling back to the least-loaded
healthy rotation.

Budget note: each _LlmServer builds its own ContinuousBatcher (~4.5 s
params init + pump compile on CPU). The fp handoff test and the int8
warm-handoff test each need exactly the two-build floor (prefill +
decode ARE the subject); everything else is model-free. The
2-prefill x 2-decode soak with a mid-traffic decode drain (4 builds)
is marked ``slow``.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge.fleet import (
    FleetEndpoints,
    PrefixRouter,
    ReplyDeduper,
    prefix_route_keys,
)
from nnstreamer_tpu.edge.serialize import ROUTE_META_KEY
from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.serving_plane.disagg import parse_decode_peers
from nnstreamer_tpu.tensors.frame import Frame

OPTS = {
    "vocab": "211", "d_model": "32", "n_heads": "2", "n_layers": "1",
    "seed": "5",
}
N_HEADS = 2


def _mk(**kw):
    from nnstreamer_tpu.elements.llm_serve import _LlmServer

    base = dict(
        model="zoo:transformer_lm", options=dict(OPTS), n_slots=2,
        max_len=64, prompt_len=16, default_new=10, kv_layout="paged",
        block_size=16, kv_blocks=0,
    )
    base.update(kw)
    return _LlmServer(**base)


def _alone(prompt, n_new):
    import jax

    from nnstreamer_tpu.models import decode as dec
    from nnstreamer_tpu.models import transformer as tfm

    params = tfm.init_params(
        jax.random.PRNGKey(5), vocab=211, d_model=32, n_heads=2,
        n_layers=1,
    )
    toks = dec.generate(
        params, np.asarray(prompt, np.int32)[None, :], N_HEADS, n_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _pump_until(srv, cond, timeout=120.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        assert time.monotonic() < deadline, f"timed out waiting: {what}"
        srv.pump()


def _prompt(seed, n=6):
    return np.random.default_rng(seed).integers(1, 211, (n,)).astype(
        np.int32
    )


# -- decode-peers grammar / role prop validation (model-free) -----------


def test_parse_decode_peers():
    assert parse_decode_peers("h1:9001,h2:9002/3") == [
        ("h1", 9001, 0), ("h2", 9002, 3),
    ]
    assert parse_decode_peers(" h:5001 , ", default_llm_id=7) == [
        ("h", 5001, 7),
    ]
    for bad in ("", "noport", "h:", "h:0", "h:x", "h:1/abc", "a:1,a:1"):
        with pytest.raises(ValueError):
            parse_decode_peers(bad)


def test_role_props_validation():
    """role= fails loudly at construction — before any model load."""
    from nnstreamer_tpu.elements.llm_serve import _LlmServer
    from nnstreamer_tpu.serving_plane.llm import LlmPlaneError

    def mk(**kw):
        base = dict(
            model="zoo:transformer_lm", options={}, n_slots=1,
            max_len=32, prompt_len=8, default_new=4, kv_layout="paged",
        )
        base.update(kw)
        return _LlmServer(**base)

    with pytest.raises(ElementError, match="prefill or decode"):
        mk(role="both")
    with pytest.raises(ElementError, match="role=prefill"):
        mk(role="decode", decode_peers="h:1")
    with pytest.raises(ElementError, match="kv-layout=paged"):
        mk(role="prefill", kv_layout="slot")
    with pytest.raises(LlmPlaneError, match="role= refused"):
        mk(role="decode", plane="dg-pl")
    with pytest.raises(ElementError, match="decode-peers"):
        mk(role="prefill", decode_peers="nonsense")


# -- prefix keys + router (model-free units) ----------------------------


def test_prefix_route_keys_block_math():
    toks = list(range(40))
    keys = prefix_route_keys(toks)  # 40 tokens / block 16 -> 2 full
    assert len(keys) == 2 and all(len(k) == 8 for k in keys)
    # keys are a rolling chain: a shared prefix shares its key prefix
    assert prefix_route_keys(toks[:32]) == keys
    assert prefix_route_keys(toks[:16]) == keys[:1]
    assert prefix_route_keys(toks[:15]) == []  # no full block
    # a differing token in block 0 changes EVERY key downstream
    other = [99] + toks[1:]
    assert prefix_route_keys(other)[0] != keys[0]


def test_prefix_router_longest_match_wins():
    r = PrefixRouter(capacity=16)
    deep = prefix_route_keys(list(range(48)))   # 3 keys
    r.note(deep[:1], "a:1")                     # a holds 1 block
    r.note(deep, "b:2")                         # b holds all 3
    assert r.best(deep) == ("b:2", 3)
    # a prompt matching only the first block routes to the deepest
    # holder OF THAT PREFIX (b recorded the chain, latest depth wins)
    assert r.best(deep[:1])[1] == 1
    # unknown prefix: no preference
    assert r.best(prefix_route_keys([7] * 32)) is None
    assert r.best([]) is None
    # latest note wins for the same depth
    r.note(deep, "c:3")
    assert r.best(deep) == ("c:3", 3)
    # bounded: FIFO eviction keeps the index from growing forever
    small = PrefixRouter(capacity=16)
    for i in range(40):
        small.note([f"{i:08x}"], "x:1")
    assert len(small) <= 16


def test_plan_least_loaded_fallback():
    """With no prefix preference the healthy rotation is stably
    re-ordered by live inflight depth — ties keep round-robin."""
    f = FleetEndpoints([("a", 1), ("b", 2), ("c", 3)], clock=lambda: 0.0)
    a, b, c = f.endpoints
    assert [e.addr for e in f.plan()] == ["a:1", "b:2", "c:3"]
    b.inflight = 3
    a.inflight = 1
    # rotation starts at b this turn, but load reorders: c (0), a (1),
    # b (3) — the loaded endpoint stops collecting new requests
    assert [e.addr for e in f.plan()] == ["c:3", "a:1", "b:2"]
    b.inflight = a.inflight = c.inflight = 0
    # idle fleet: pure round-robin again (stable sort keeps rotation)
    assert [e.addr for e in f.plan()][0] == "c:3"


def test_reply_dedup_at_most_once():
    """The PR-15 deduper delivers each frame_id exactly once — the
    invariant the disagg DELIVER-ownership design leans on."""
    d = ReplyDeduper(capacity=16)
    assert d.claim("f-1") and not d.claim("f-1")
    assert d.duplicates == 1


# -- the CTRL wire: advert piggyback, capacity NACK, fetch (model-free) --


class _FakeDecode:
    """A fake decode-role LLM server behind a real serversrc."""

    def __init__(self):
        self.adopt_exc = None
        self.done = {7: [1, 2, 3]}
        self.pending = {8}

    def migration_probe(self, tokens):
        return 16

    def migration_advert(self):
        return {"role": "decode", "free_slots": 2, "free_blocks": 40}

    def migration_adopt(self, span_bytes):
        if self.adopt_exc is not None:
            raise self.adopt_exc
        return 7

    def disagg_fetch(self, rid):
        from nnstreamer_tpu.kv.migrate import SpanStateError

        if rid in self.done:
            return self.done.pop(rid)
        if rid in self.pending:
            return None
        raise SpanStateError(f"rid {rid} unknown")


def test_disagg_ctrl_wire_roundtrip():
    from nnstreamer_tpu.edge import query as q

    h = _FakeDecode()
    q.register_migration_handler(31, h)
    src = q.TensorQueryServerSrc("dg-wire-src", port=0, id="dg-w1")
    src.start()
    stop = threading.Event()
    t = threading.Thread(
        target=lambda: [src.generate() for _ in iter(stop.is_set, True)],
        daemon=True,
    )
    t.start()
    try:
        # probe ack piggybacks the decode advert: one roundtrip answers
        # "how warm" (shared_tokens) AND "how full" (the advert)
        shared, advert = q.probe_migration_full(
            "127.0.0.1", src.bound_port, [1, 2, 3], llm_id=31
        )
        assert shared == 16
        assert advert["role"] == "decode"
        assert advert["free_blocks"] == 40 and advert["free_slots"] == 2
        # capacity refusal rides the wire as a typed retry-after NACK
        # instead of raising through the serversrc service thread
        from nnstreamer_tpu.kv.blocks import PoolCapacityError

        h.adopt_exc = PoolCapacityError("pool full", 8, 2)
        with pytest.raises(
            q.MigrationRefused, match="PoolCapacityError"
        ) as ei:
            q.send_migration("127.0.0.1", src.bound_port, b"x", llm_id=31)
        assert ei.value.retry_after_ms > 0  # the admission retry hint
        # fetch: finished tokens exactly once, None while decoding,
        # refused for an rid the peer never saw
        assert q.fetch_handoff(
            "127.0.0.1", src.bound_port, 7, llm_id=31
        ) == [1, 2, 3]
        with pytest.raises(q.MigrationRefused, match="SpanStateError"):
            q.fetch_handoff("127.0.0.1", src.bound_port, 7, llm_id=31)
        assert q.fetch_handoff(
            "127.0.0.1", src.bound_port, 8, llm_id=31
        ) is None
        # a DRAINING serversrc refuses new spans but still serves
        # fetches: results must LEAVE a draining decode server
        src.drain()
        with pytest.raises(q.MigrationRefused, match="draining"):
            q.probe_migration_full(
                "127.0.0.1", src.bound_port, [1], llm_id=31
            )
        h.done[9] = [4, 5]
        assert q.fetch_handoff(
            "127.0.0.1", src.bound_port, 9, llm_id=31
        ) == [4, 5]
    finally:
        q.unregister_migration_handler(31)
        stop.set()
        t.join(timeout=2)
        src.stop()


# -- prefix-aware routing end to end (sockets, no model) ----------------


class _EchoServer:
    """serversrc/serversink pair echoing tensors (and meta) back."""

    def __init__(self, name: str, srv_id: str):
        from nnstreamer_tpu.edge.query import (
            TensorQueryServerSink,
            TensorQueryServerSrc,
        )

        self.src = TensorQueryServerSrc(name, port=0, id=srv_id)
        self.sink = TensorQueryServerSink(f"{name}k", id=srv_id)
        self.src.start()
        self.port = self.src.bound_port
        self.served = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while not self._stop.is_set():
            f = self.src.generate()
            if f is None:
                continue
            self.served += 1
            self.sink.render(f)

    def stop(self):
        self._stop.set()
        self._t.join(timeout=2)
        self.src.stop()


def test_prefix_route_client_prefers_prefix_holder():
    from nnstreamer_tpu.edge.query import TensorQueryClient

    a = _EchoServer("pfx-a", "pfxa")
    b = _EchoServer("pfx-b", "pfxb")
    client = TensorQueryClient(
        "pfx-c1",
        **{"hosts": f"127.0.0.1:{a.port},127.0.0.1:{b.port}",
           "timeout": 3, "retry-max": 3, "retry-backoff-ms": 5,
           "prefix-route": True},
    )
    prompt = np.arange(1, 33, dtype=np.int32)  # two full route blocks
    try:
        client.start()
        r = client.process(Frame((prompt,), meta={"req": "warmup"}))
        # the prefix keys rode the wire and echoed back (scalar meta)
        assert ROUTE_META_KEY in r.meta
        assert r.meta[ROUTE_META_KEY] == ".".join(
            prefix_route_keys(prompt)
        )
        st = client.fleet_stats()
        assert st["prefix_index"] >= 1
        owner = a if a.served else b
        base = owner.served
        # repeats of the same prompt stick to the learned endpoint
        # even as round-robin would have alternated
        for _ in range(4):
            client.process(Frame((prompt,), meta={}))
        st = client.fleet_stats()
        assert st["prefix_hits"] >= 4
        assert owner.served == base + 4
        # a float frame has no prompt: routes by load alone, no stamp
        r2 = client.process(Frame((np.ones(4, np.float32),), meta={}))
        assert ROUTE_META_KEY not in r2.meta
    finally:
        client.stop()
        a.stop()
        b.stop()


# -- prefill -> decode handoff: bitwise, zero re-prefill, fallback ------


class _DecodeHost:
    """A decode-role _LlmServer behind a real query serversrc, with a
    CTRL pump thread and a batcher pump thread (the real deployment
    shape, minus the client-facing data path)."""

    def __init__(self, name: str, srv_id: str, **kw):
        from nnstreamer_tpu.edge.query import TensorQueryServerSrc

        self.srv = _mk(srv_id=srv_id, role="decode", **kw)
        self.src = TensorQueryServerSrc(name, port=0, id=f"dg-{name}")
        self.src.start()
        self.port = self.src.bound_port
        self._stop = threading.Event()
        self._tc = threading.Thread(target=self._ctrl, daemon=True)
        self._tp = threading.Thread(target=self._pump, daemon=True)
        self._tc.start()
        self._tp.start()

    def _ctrl(self):
        while not self._stop.is_set():
            self.src.generate()

    def _pump(self):
        while not self._stop.is_set():
            try:
                self.srv.pump()
            except Exception:  # noqa: BLE001 — teardown race
                pass
            time.sleep(0.001)

    def stop(self):
        self._stop.set()
        self._tc.join(timeout=2)
        self._tp.join(timeout=2)
        self.src.stop()
        self.srv.release_plane()


def test_disagg_fp_handoff_bitwise_zero_reprefill():
    """The tentpole pin: prefill on A, decode on B, bitwise == solo,
    B's prefill-chunk counter NEVER moves (zero re-prefill), delivery
    stays with A under the original frame_id — and when B refuses
    (draining), A decodes locally with no token lost."""
    host = _DecodeHost("dg-b1", "52")
    A = _mk(
        srv_id="51", role="prefill",
        decode_peers=f"127.0.0.1:{host.port}/52",
    )
    try:
        p1, p2 = _prompt(31), _prompt(32)
        A.submit(Frame((p1,), meta={"req": "r1", "frame_id": "f-1"}))
        A.submit(Frame((p2,), meta={"req": "r2", "frame_id": "f-2"}))
        _pump_until(A, lambda: len(A._out) >= 2, what="2 relayed")
        got = {}
        for _ in range(2):
            toks, meta = A.pop()
            got[meta["req"]] = ([int(t) for t in toks], meta)
        assert got["r1"][0] == _alone(p1, 10)
        assert got["r2"][0] == _alone(p2, 10)
        # DELIVER ownership: original identity meta, emitted by A only
        assert got["r1"][1]["frame_id"] == "f-1"
        assert got["r2"][1]["frame_id"] == "f-2"
        assert not host.srv._out and not host.srv._disagg_done
        bst = host.srv.cb.stats()
        assert bst["kv_prefill_chunks"] == 0  # the zero-re-prefill pin
        assert bst["kv_migrations_in"] == 2
        ast = A.stats()
        assert ast["disagg_role"] == "prefill"
        assert ast["disagg"]["counts"]["handoff"] == 2
        assert ast["disagg"]["counts"]["relayed"] == 2
        # refusal fallback: a draining decode serversrc NACKs the
        # probe; the span re-enters A's OWN arena and finishes locally
        host.src.drain()
        p3 = _prompt(33)
        A.submit(Frame((p3,), meta={"req": "r3", "frame_id": "f-3"}))
        _pump_until(A, lambda: A._out, what="local-fallback generation")
        toks, meta = A.pop()
        assert [int(t) for t in toks] == _alone(p3, 10)
        assert meta["frame_id"] == "f-3"
        assert A.stats()["disagg"]["counts"].get("local", 0) >= 1
        # terminal: nothing outstanding anywhere, A drains clean
        assert A._disagg.idle()
        A.eos = True
        assert A.drained
    finally:
        A.release_plane()
        host.stop()


def test_disagg_int8_warm_handoff_bitwise():
    """int8 arenas hand off bitwise too — and a decode peer already
    holding the prompt's blocks (the solo oracle ran THERE) makes it a
    warm handoff: the span ships stripped, the peer still re-prefills
    nothing."""
    host = _DecodeHost("dg-b2", "62", cache_dtype="int8")
    A = _mk(
        srv_id="61", role="prefill", cache_dtype="int8",
        decode_peers=f"127.0.0.1:{host.port}/62",
    )
    try:
        prompt = _prompt(41, n=16)  # one full KV block: warm-shareable
        # solo oracle on the decode server itself (its pump thread
        # drives it) — this also seeds its prefix cache
        host.srv.submit(Frame((prompt,), meta={"req": "ref"}))
        deadline = time.monotonic() + 120.0
        while not host.srv._out:
            assert time.monotonic() < deadline, "solo oracle timed out"
            time.sleep(0.005)
        ref_toks, _ = host.srv.pop()
        assert host.srv.cb.probe_prefix([int(t) for t in prompt]) == 16
        base_chunks = host.srv.cb.stats()["kv_prefill_chunks"]
        A.submit(Frame((prompt,), meta={"req": "h1", "frame_id": "f-h"}))
        _pump_until(A, lambda: A._out, what="relayed int8 generation")
        toks, meta = A.pop()
        assert [int(t) for t in toks] == [int(t) for t in ref_toks]
        assert meta["frame_id"] == "f-h"
        bst = host.srv.cb.stats()
        assert bst["kv_prefill_chunks"] == base_chunks  # warm: no chunk
        assert bst["kv_migrations_in"] == 1
        assert A.stats()["disagg"]["counts"]["handoff"] == 1
    finally:
        A.release_plane()
        host.stop()


# -- the 2x2 soak with a mid-traffic decode drain (slow) ----------------


@pytest.mark.slow
def test_disagg_soak_two_by_two_mid_drain():
    """2 prefill x 2 decode under rolling traffic while one decode
    server drains mid-stream: every request terminates, bitwise == the
    solo run, nothing outstanding at the end."""
    d1 = _DecodeHost("dgs-d1", "71")
    d2 = _DecodeHost("dgs-d2", "72")
    peers = f"127.0.0.1:{d1.port}/71,127.0.0.1:{d2.port}/72"
    a1 = _mk(srv_id="73", role="prefill", decode_peers=peers)
    a2 = _mk(srv_id="74", role="prefill", decode_peers=peers)
    prefills = [a1, a2]
    try:
        expect = {}
        for i in range(4):
            p = _prompt(100 + i)
            expect[f"s-{i}"] = _alone(p, 10)
            prefills[i % 2].submit(
                Frame((p,), meta={"req": f"s-{i}", "frame_id": f"sf-{i}"})
            )
        deadline = time.monotonic() + 240.0

        def _pump_all_until(n):
            while sum(len(a._out) for a in prefills) < n:
                assert time.monotonic() < deadline, "soak timed out"
                for a in prefills:
                    a.pump()

        _pump_all_until(2)
        # mid-traffic drain: d1 refuses new spans but keeps serving
        # fetches for handoffs already decoding there
        d1.src.drain()
        for i in range(4, 8):
            p = _prompt(100 + i)
            expect[f"s-{i}"] = _alone(p, 10)
            prefills[i % 2].submit(
                Frame((p,), meta={"req": f"s-{i}", "frame_id": f"sf-{i}"})
            )
        _pump_all_until(8)
        got = {}
        for a in prefills:
            while a._out:
                toks, meta = a.pop()
                got[meta["req"]] = [int(t) for t in toks]
        assert got == expect  # all terminal, all bitwise == solo
        for a in prefills:
            assert a._disagg.idle()
            a.eos = True
            assert a.drained
        # the drained decode server kept serving its in-flight: its
        # parked queue is empty once every fetch landed
        assert not d1.srv._disagg_done
    finally:
        a1.release_plane()
        a2.release_plane()
        d1.stop()
        d2.stop()
