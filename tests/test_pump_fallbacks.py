"""spec_pump fallback-path contract tests.

spec_pump promises {rid: ALL tokens emitted this pump} even on the
paths that route through host spec_step rounds (windowed draft
batchers; no-verify-room tails) — spec_step itself reports only the
last token per request, so the fallback reconstructs the full emission
from req.tokens growth (serving._spec_fallback_rounds).
"""

import jax
import numpy as np
import pytest

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

N_HEADS = 4


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(7), vocab=257, d_model=64, n_heads=N_HEADS,
        n_layers=2,
    )


@pytest.fixture(scope="module")
def draft_params():
    return tfm.init_params(
        jax.random.PRNGKey(11), vocab=257, d_model=32, n_heads=N_HEADS,
        n_layers=1,
    )


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(1, 257, (n,)).astype(np.int32)


def test_windowed_draft_fallback_returns_full_emission(
    params, draft_params
):
    """A windowed DRAFT batcher routes spec_pump through per-round host
    spec_steps (ring verify-then-commit needs each round's acceptance);
    the return must still carry EVERY token those rounds emitted, and
    the stream must equal the per-token reference."""
    kw = dict(
        windowed=True, max_len=32, prompt_len=16,
        draft_params=draft_params, draft_n_heads=N_HEADS,
    )
    a = ContinuousBatcher(params, N_HEADS, n_slots=2, **kw)
    b = ContinuousBatcher(params, N_HEADS, n_slots=2, **kw)
    p = _prompt(10, 3)
    ra = a.submit(p, 9)
    rb = b.submit(p, 9)
    while a.result(ra) is None:
        a.step()
    collected = []
    while b.result(rb) is None:
        out = b.spec_pump(rounds=3, k=3)
        collected.extend(out.get(rb, []))
    # all pump-emitted tokens reported, in order, matching the stream
    # (token 0 is the prefill's, emitted at submit, not by a pump)
    assert collected == b.result(rb)[1:]
    assert a.result(ra) == b.result(rb)


def test_no_room_tail_fallback_returns_full_emission(params):
    """A non-windowed batcher whose cache is too full for any k≥2
    verify chunk falls back to the shrinking-k host round; the return
    contract (all emitted tokens) must hold there too."""
    a = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=16,
                          prompt_len=16)
    b = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=16,
                          prompt_len=16)
    p = _prompt(12, 5)
    ra = a.submit(p, 4)  # 12 + 4 = max_len: rounds at k=4 never fit
    rb = b.submit(p, 4)
    while a.result(ra) is None:
        a.step()
    collected = []
    while b.result(rb) is None:
        out = b.spec_pump(rounds=4, k=4)
        collected.extend(out.get(rb, []))
    assert collected == b.result(rb)[1:]
    assert a.result(ra) == b.result(rb)
