"""Fused on-device composite (detect→crop+resize→landmark in one XLA
program) — ops/image.crop_and_resize + models/face_pipeline.apply_composite
+ zoo:face_composite. The TPU-first redesign of the tensor_crop cascade."""

import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.models import zoo
from nnstreamer_tpu.ops.image import crop_and_resize
from nnstreamer_tpu.single import SingleShot


def test_crop_and_resize_identity_box():
    """Cropping the full image at native size is the identity."""
    img = jnp.asarray(
        np.random.default_rng(0).standard_normal((8, 6, 3)), jnp.float32
    )
    out = crop_and_resize(img, jnp.asarray([[0.0, 0.0, 6.0, 8.0]]), 8, 6)
    np.testing.assert_allclose(np.asarray(out[0]), np.asarray(img), atol=1e-5)


def test_crop_and_resize_matches_manual_bilinear():
    """2x upsample of a 2x2 gradient against hand-computed samples."""
    img = jnp.asarray([[[0.0], [1.0]], [[2.0], [3.0]]], jnp.float32)
    out = np.asarray(crop_and_resize(img, jnp.asarray([[0.0, 0.0, 2.0, 2.0]]), 4, 4))[:, :, :, 0]
    # sample centers at 0.25-spaced grid minus 0.5 → bilinear of corners
    assert out.shape == (1, 4, 4)
    # corners clamp to the corner pixels
    assert out[0, 0, 0] == 0.0 and out[0, 3, 3] == 3.0
    # exact center of the image = mean of all four
    center = crop_and_resize(img, jnp.asarray([[0.5, 0.5, 1.5, 1.5]]), 1, 1)
    np.testing.assert_allclose(float(center[0, 0, 0, 0]), 1.5, atol=1e-5)


def test_crop_and_resize_subpixel_region():
    img = jnp.asarray(
        np.random.default_rng(1).standard_normal((16, 16, 2)), jnp.float32
    )
    out = crop_and_resize(img, jnp.asarray([[2.5, 3.5, 9.5, 12.5]]), 7, 5)
    assert out.shape == (1, 7, 5, 2)
    assert np.all(np.isfinite(np.asarray(out)))
    # values stay within the sampled region's range (bilinear is convex)
    region = np.asarray(img[3:14, 2:11])
    assert np.asarray(out).min() >= region.min() - 1e-5
    assert np.asarray(out).max() <= region.max() + 1e-5


def test_fused_composite_one_program():
    m = zoo.get("face_composite", threshold="0.0")
    img = jnp.asarray(
        np.random.default_rng(2).integers(0, 255, (1, 128, 128, 3), np.uint8)
    )
    lmk, det = jax.jit(m.fn)(img)
    lmk, det = np.asarray(lmk), np.asarray(det)
    assert lmk.shape == (16, 136) and det.shape == (16, 7)
    assert np.all(np.isfinite(lmk)) and np.all(np.isfinite(det))
    assert np.all(lmk >= 0) and np.all(lmk <= 1)
    assert np.all(det[:-1, 2] >= det[1:, 2])  # top-k order preserved


def test_fused_composite_threshold_masks_landmarks():
    m = zoo.get("face_composite", threshold="1.1")  # nothing passes
    img = jnp.asarray(
        np.random.default_rng(3).integers(0, 255, (1, 128, 128, 3), np.uint8)
    )
    lmk, det = m.fn(img)
    assert np.all(np.asarray(lmk) == 0.0)


def test_fused_composite_through_filter_surface():
    """zoo:face_composite behind tensor_filter is traceable (fusable)."""
    with SingleShot(
        framework="jax", model="zoo:face_composite", custom="threshold:0.0"
    ) as s:
        outs = s.invoke(
            np.random.default_rng(4).integers(0, 255, (1, 128, 128, 3), np.uint8)
        )
        assert len(outs) == 2
        assert np.asarray(outs[0]).shape == (16, 136)
        assert s.backend.traceable_fn() is not None


def test_fused_composite_deterministic():
    m = zoo.get("face_composite", threshold="0.0")
    img = jnp.asarray(
        np.random.default_rng(5).integers(0, 255, (1, 128, 128, 3), np.uint8)
    )
    a = jax.jit(m.fn)(img)
    b = jax.jit(m.fn)(img)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
