"""Compiled whole-chain resident programs (pipeline/chain_program.py,
docs/chain-analysis.md "Compiled chains").

An eligible multi-segment chain compiles into ONE jitted program and the
executor serves it from a single ChainNode — one XLA dispatch per
unrolled window, not one per node per frame. The per-node path is the
parity ORACLE: everything here compares the compiled stream bitwise
against chain_mode=off (no ULP tolerance — the program is a literal
unroll, not a vmap). Tier-1 keeps runs tiny (8x8 tensors, 11 frames);
the chaos x unroll soak is marked `slow`.
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.chain_program import ChainProgram, decide_chain
from nnstreamer_tpu.pipeline.device_faults import (
    DeviceFaultError,
    DeviceOOMError,
)
from nnstreamer_tpu.pipeline.executor import ChainNode
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.frame import Frame

# 3 fused segments joined by device-passthrough queues = one chain; 11
# frames with unroll 4 forces a partial (EOS-flushed) trailing window.
# The constants are FMA-proof on purpose (x+1, *2, +0.5 stay exact in
# float32 for counter data), so bitwise comparison is legitimate.
DESC = (
    "tensorsrc dimensions=8:8 pattern=counter num-frames=11 ! "
    "tensor_transform mode=arithmetic option=add:1.0 ! queue ! "
    "tensor_transform mode=arithmetic option=mul:2.0 ! queue ! "
    "tensor_transform mode=arithmetic option=add:0.5 ! tensor_sink"
)


def _run(monkeypatch, desc, mode, sanitize=False, unroll=None):
    from nnstreamer_tpu.elements.sink import TensorSink

    monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_MODE", mode)
    if unroll is not None:
        monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_UNROLL", str(unroll))
    monkeypatch.setenv("NNS_TPU_SANITIZE", "1" if sanitize else "0")
    ex = parse_pipeline(desc).run(timeout=300)
    sink = next(
        n.elem for n in ex.nodes
        if isinstance(getattr(n, "elem", None), TensorSink)
    )
    frames = [[np.asarray(t) for t in f.tensors] for f in sink.frames]
    return frames, ex


def _chain_nodes(ex):
    return [n for n in ex.nodes if isinstance(n, ChainNode)]


def _assert_bitwise(a, b):
    assert len(a) == len(b)
    for fa, fb in zip(a, b):
        assert len(fa) == len(fb)
        for ta, tb in zip(fa, fb):
            assert ta.dtype == tb.dtype
            np.testing.assert_array_equal(ta, tb)


def _plan_and_chain(desc):
    p = parse_pipeline(desc)
    p.negotiate()
    plan = p.compile_plan()
    chains = plan.chains()
    assert chains, "pipeline grew no chain"
    return plan, chains[0]


class TestCompiledParity:
    def test_bitwise_parity_and_windowed_launches(self, monkeypatch):
        """The flagship pin: compiled output is bitwise-identical to the
        per-node oracle, all 11 frames arrive (EOS flushes the 3-frame
        tail window), and the stream dispatched one launch per WINDOW —
        3-4 launches for 11 frames at unroll 4, never one per frame."""
        compiled, ex_on = _run(monkeypatch, DESC, "auto")
        oracle, ex_off = _run(monkeypatch, DESC, "off")
        nodes = _chain_nodes(ex_on)
        assert len(nodes) == 1  # three segments, ONE service thread
        assert not _chain_nodes(ex_off)  # the oracle keeps FusedNodes
        assert len(compiled) == 11
        _assert_bitwise(compiled, oracle)
        n = nodes[0]
        # ceil(11/4)=3 windows when the queue keeps up; one extra
        # collect on a slow scheduler is tolerated, per-frame is not
        assert 3 <= n.program.launches <= 4
        assert not n.fallback_latched
        assert n.fallback_windows == 0
        s = ex_on.stats()[n.name]
        assert s["chain_segments"] == 3
        assert s["chain_unroll"] == 4
        assert s["chain_launches"] == n.program.launches

    def test_crosscheck_reports_zero_interior_bytes(self, monkeypatch):
        """The resident-program invariant from both sides: the cost
        model predicts zero bytes across interior member boundaries and
        the executor's structural measurement agrees."""
        _, ex = _run(monkeypatch, DESC, "auto")
        rows = ex.transfer_crosscheck()["chains"]
        assert len(rows) == 1
        assert rows[0]["launches"] >= 1
        assert rows[0]["predicted_interior"] == 0
        assert rows[0]["measured_interior"] == 0

    def test_sanitized_run_is_clean(self, monkeypatch):
        """Window padding under the sanitizer uses poison rows; a clean
        run must deliver every frame and latch zero findings (poison
        can never leak into a delivered frame)."""
        frames, ex = _run(monkeypatch, DESC, "auto", sanitize=True)
        assert len(frames) == 11
        assert _chain_nodes(ex)
        assert ex.sanitizer.codes == [], [
            str(d) for d in ex.sanitizer.findings()
        ]


class TestWindowProgram:
    def test_one_dispatch_per_window(self, monkeypatch):
        """The launch-count pin at program level: each process_window
        call is exactly one XLA dispatch, padded windows report the
        dispatched bucket width, and every row matches the oracle
        bitwise."""
        monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_MODE", "auto")
        plan, chain = _plan_and_chain(DESC)
        d = decide_chain(plan, chain)
        assert d.compiles, d.reason
        assert d.unroll == 4
        prog = ChainProgram(chain, d.unroll)
        prog.build()
        sig = chain.segments[0]._negotiated_sig()
        frames = [
            Frame(tuple(
                np.full(shape, i, dtype) for shape, dtype in sig
            ))
            for i in range(7)
        ]
        outs, rows, launched = prog.process_window(frames[:4])
        assert launched and rows == 4 and len(outs) == 4
        assert prog.launches == 1
        # EOS tail: 3 frames pad up to the 4-bucket, still ONE dispatch
        outs2, rows2, launched2 = prog.process_window(frames[4:])
        assert launched2 and rows2 == 4 and len(outs2) == 3
        assert prog.launches == 2
        for f, out in zip(frames, outs + outs2):
            want = prog.process_frame_fallback(f)
            for ta, tb in zip(out.tensors, want.tensors):
                np.testing.assert_array_equal(
                    np.asarray(ta), np.asarray(tb)
                )

    def test_trickle_window_uses_small_bucket(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_MODE", "auto")
        plan, chain = _plan_and_chain(DESC)
        prog = ChainProgram(chain, 4)
        sig = chain.segments[0]._negotiated_sig()
        frame = Frame(tuple(
            np.full(shape, 5, dtype) for shape, dtype in sig
        ))
        outs, rows, launched = prog.process_window([frame])
        assert launched and rows == 1 and len(outs) == 1
        assert prog.launches == 1


class TestFallbackLadder:
    def test_oom_shrinks_window_and_recovers(self, monkeypatch):
        """A window OOM shrinks one bucket rung and RETRIES (never
        drops): output stays bitwise-identical, nothing latches."""
        oracle, _ = _run(monkeypatch, DESC, "off")
        state = {"calls": 0}
        real = ChainProgram.process_window

        def flaky(self, frames, donate=False):
            state["calls"] += 1
            if state["calls"] == 1:
                raise DeviceOOMError("injected window OOM")
            return real(self, frames, donate)

        monkeypatch.setattr(ChainProgram, "process_window", flaky)
        compiled, ex = _run(monkeypatch, DESC, "auto")
        _assert_bitwise(compiled, oracle)
        n = _chain_nodes(ex)[0]
        assert not n.fallback_latched
        assert n.bucket_governor is not None
        assert n.bucket_governor.ooms == 1
        # shrunk windows mean MORE launches than the 3 healthy ones
        assert n.program.launches > 3

    def test_device_fault_latches_parity_fallback(self, monkeypatch):
        """Any non-OOM device fault latches the sticky per-node
        fallback: the whole stream still arrives, bitwise-identical,
        and the sanitizer's frame accounting stays balanced."""
        oracle, _ = _run(monkeypatch, DESC, "off")

        def broken(self, frames, donate=False):
            raise DeviceFaultError("injected chain fault")

        monkeypatch.setattr(ChainProgram, "process_window", broken)
        compiled, ex = _run(monkeypatch, DESC, "auto", sanitize=True)
        _assert_bitwise(compiled, oracle)
        n = _chain_nodes(ex)[0]
        assert n.fallback_latched
        assert n.fallback_windows >= 1
        assert n.program.launches == 0
        s = ex.stats()[n.name]
        assert s["chain_fallback_windows"] == n.fallback_windows
        assert s["device_degraded"] == 1
        assert ex.sanitizer.codes == [], [
            str(d) for d in ex.sanitizer.findings()
        ]


class TestDecision:
    def test_single_segment_not_eligible(self):
        plan, chain = _plan_and_chain(
            "tensorsrc dimensions=4 num-frames=1 ! "
            "tensor_transform mode=arithmetic option=add:1.0 ! "
            "tensor_sink"
        )
        d = decide_chain(plan, chain)
        assert not d.eligible
        assert "single segment" in d.reason

    def test_flexible_head_not_eligible(self):
        plan, chain = _plan_and_chain(
            "videotestsrc device=true num-frames=1 width=16 height=16 ! "
            "tensor_converter ! queue ! "
            "tensor_transform mode=typecast option=float32 ! fakesink"
        )
        d = decide_chain(plan, chain)
        assert not d.eligible
        assert "flexible input spec" in d.reason

    def test_mode_off_is_eligible_but_not_compiled(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_MODE", "off")
        plan, chain = _plan_and_chain(DESC)
        d = decide_chain(plan, chain)
        assert d.eligible and d.mode == "off" and not d.compiles

    def test_no_fuse_oracle_disables_compilation(self, monkeypatch):
        monkeypatch.setenv("NNS_NO_FUSE", "1")
        plan, chain = _plan_and_chain(DESC)
        d = decide_chain(plan, chain)
        assert not d.eligible
        assert "NNS_NO_FUSE" in d.reason


class TestW125Lint:
    def test_w125_fires_only_when_configured_off(self, monkeypatch):
        """Both ways: chain_mode=off on an eligible chain fires
        NNS-W125 and the compiled column says why; auto compiles and
        stays silent."""
        from nnstreamer_tpu.analysis.xray import xray

        monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_MODE", "off")
        r_off = xray(DESC)
        assert "NNS-W125" in r_off.codes
        assert [c.compiled for c in r_off.chains] == [
            "no: chain_mode=off"
        ]
        monkeypatch.setenv("NNS_TPU_EXECUTOR_CHAIN_MODE", "auto")
        r_on = xray(DESC)
        assert "NNS-W125" not in r_on.codes
        assert [c.compiled for c in r_on.chains] == ["yes (unroll 4)"]


@pytest.mark.slow
def test_chaos_by_unroll_soak(monkeypatch):
    """Chaos x unroll grid: inject an OOM or a transient fault into
    every 3rd window across the bucket ladder — every configuration
    must deliver the full bitwise-identical stream (shrunk, latched, or
    healthy; never dropped)."""
    oracle, _ = _run(monkeypatch, DESC, "off")
    real = ChainProgram.process_window
    for unroll in (1, 2, 4, 8):
        for exc_cls in (DeviceOOMError, DeviceFaultError):
            state = {"calls": 0}

            def chaotic(self, frames, donate=False,
                        _state=state, _exc=exc_cls):
                _state["calls"] += 1
                if _state["calls"] % 3 == 0:
                    raise _exc("soak-injected")
                return real(self, frames, donate)

            monkeypatch.setattr(
                ChainProgram, "process_window", chaotic
            )
            compiled, ex = _run(
                monkeypatch, DESC, "auto",
                sanitize=True, unroll=unroll,
            )
            _assert_bitwise(compiled, oracle)
            assert ex.sanitizer.codes == [], (
                unroll, exc_cls.__name__,
                [str(d) for d in ex.sanitizer.findings()],
            )
