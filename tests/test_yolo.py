"""YOLOv5-family zoo model tests (models/yolo.py).

The decoder's ``yolov5`` mode existed without a native zoo model; these
close the loop: the model's decoded prediction tensor feeds the
bounding-box decoder (and ops/detection.yolov5_postprocess) end to end
through the pipeline, fused.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import yolo, zoo


def test_prediction_layout_and_ranges():
    """[B, n_rows, 5+C]; coords/size normalized, scores sigmoided."""
    m = zoo.get("yolov5", size="160", num_classes="7", width="16")
    x = np.random.default_rng(0).integers(0, 255, (1, 160, 160, 3),
                                          np.uint8)
    out = np.asarray(jax.jit(m.fn)(jnp.asarray(x)))
    assert out.shape == (1, yolo.n_rows(160), 12)
    # xy in (-0.5, 1.5)·stride-ish but normalized around [0,1]; scores
    # strictly in (0,1) from the sigmoid
    assert np.all(out[..., 4:] > 0) and np.all(out[..., 4:] < 1)
    assert np.all(out[..., 2:4] > 0)  # wh strictly positive
    assert np.isfinite(out).all()


def test_rows_cover_every_level():
    assert yolo.n_rows(320) == (40 * 40 + 20 * 20 + 10 * 10) * 3


def test_postprocess_consumes_model_output():
    """ops/detection.yolov5_postprocess accepts the model's rows and
    packs [max_out, 6] detections."""
    from nnstreamer_tpu.ops import detection as det

    m = zoo.get("yolov5", size="160", num_classes="7", width="16")
    x = np.zeros((1, 160, 160, 3), np.uint8)
    pred = jax.jit(m.fn)(jnp.asarray(x))[0]
    packed = np.asarray(
        det.yolov5_postprocess(pred, conf_threshold=0.0, max_out=8)
    )
    assert packed.shape == (8, 6)
    assert np.isfinite(packed).all()


def test_pipeline_decoder_yolov5_end_to_end():
    """videotestsrc → converter → filter zoo:yolov5 → decoder
    mode=yolov5 → sink: the whole detect+decode graph through the
    pipeline surface (fused where traceable)."""
    from nnstreamer_tpu.pipeline.parse import parse_pipeline
    from nnstreamer_tpu.elements.sink import TensorSink

    desc = (
        "videotestsrc pattern=gradient num-frames=2 width=160 "
        "height=160 ! tensor_converter ! "
        "tensor_filter framework=jax model=zoo:yolov5 "
        'custom="size:160,num_classes:7,width:16" ! '
        "tensor_decoder mode=bounding_boxes option1=yolov5 "
        "option4=160:160 option5=160:160 ! tensor_sink"
    )
    ex = parse_pipeline(desc).run(timeout=300)
    sink = next(
        n.elem for n in ex.nodes if isinstance(getattr(n, "elem", None),
                                               TensorSink)
    )
    assert sink.rendered == 2
    # bounding-box decoder emits an RGBA overlay of the input size
    img = np.asarray(sink.frames[0].tensors[0])
    assert img.shape[-1] == 4 and img.shape[-3:-1] == (160, 160)


def test_bf16_matches_f32_topology():
    """bfloat16 compute runs the same topology (shape/finite parity —
    value tolerance is loose, it is a different precision)."""
    kw = dict(size="96", num_classes="3", width="16")
    a = zoo.get("yolov5", **kw)
    b = zoo.get("yolov5", compute_dtype="bfloat16", **kw)
    x = jnp.zeros((1, 96, 96, 3), jnp.uint8)
    oa = np.asarray(jax.jit(a.fn)(x))
    ob = np.asarray(jax.jit(b.fn)(x))
    assert oa.shape == ob.shape
    assert np.isfinite(ob).all()
    np.testing.assert_allclose(oa, ob, atol=0.15)
