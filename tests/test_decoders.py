"""Decoder subplugin tests (reference: tests/nnstreamer_decoder*,
nnstreamer_decoder_boundingbox, _pose, _image_segment SSAT suites +
unittest_plugins.cc decoder cases)."""

import numpy as np
import pytest

from nnstreamer_tpu import registry
from nnstreamer_tpu.elements.base import NegotiationError
from nnstreamer_tpu.ops import detection as det
from nnstreamer_tpu.ops import heatmap as hm
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


def _dec(name):
    cls = registry.get(registry.KIND_DECODER, name)
    return cls()


# ---------------------------------------------------------------- ops level
def test_nms_suppresses_overlaps():
    boxes = np.array(
        [[0.0, 0.0, 0.5, 0.5], [0.01, 0.01, 0.51, 0.51], [0.6, 0.6, 0.9, 0.9]],
        np.float32,
    )
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    idx, kept = det.nms(boxes, scores, iou_threshold=0.5, max_out=3)
    idx = np.asarray(idx)
    assert idx[0] == 0  # best kept
    assert 1 not in idx.tolist()  # overlap suppressed
    assert 2 in idx.tolist()  # disjoint kept


def test_nms_keeps_all_below_iou():
    boxes = np.array([[0, 0, 0.1, 0.1], [0.5, 0.5, 0.6, 0.6]], np.float32)
    scores = np.array([0.5, 0.9], np.float32)
    idx, kept = det.nms(boxes, scores, 0.5, max_out=4)
    assert sorted(i for i in np.asarray(idx).tolist() if i >= 0) == [0, 1]
    assert np.asarray(kept)[0] == pytest.approx(0.9)  # ranked by score


def test_ssd_decode_boxes_identity_prior():
    # zero offsets → box equals the prior
    priors = np.array([[0.5], [0.5], [0.2], [0.4]], np.float32)  # yc,xc,h,w
    loc = np.zeros((1, 4), np.float32)
    out = np.asarray(det.ssd_decode_boxes(loc, priors))
    np.testing.assert_allclose(out[0], [0.3, 0.4, 0.7, 0.6], atol=1e-6)


def test_pose_heatmap_argmax():
    heat = np.full((9, 9, 2), -5.0, np.float32)
    heat[3, 4, 0] = 5.0
    heat[7, 1, 1] = 5.0
    kp = np.asarray(hm.pose_keypoints_from_heatmap(heat))
    assert (kp[0, 0], kp[0, 1]) == (4, 3)
    assert (kp[1, 0], kp[1, 1]) == (1, 7)
    assert kp[0, 2] > 0.9  # sigmoid(5)


def test_segment_argmax_and_depth():
    seg = np.zeros((4, 4, 3), np.float32)
    seg[..., 1] = 1.0
    lab = np.asarray(hm.segment_argmax(seg, num_labels=3))
    assert lab.dtype == np.uint8 and (lab == 1).all()
    depth = np.linspace(0, 1, 16, dtype=np.float32).reshape(4, 4)
    gray = np.asarray(hm.depth_normalize(depth))
    assert gray[0, 0] == 0 and gray[-1, -1] == 255


# -------------------------------------------------------------- bounding box
def _priors_file(tmp_path, n=16):
    yc = np.linspace(0.1, 0.9, n)
    xc = np.linspace(0.1, 0.9, n)
    rows = [yc, xc, np.full(n, 0.2), np.full(n, 0.2)]
    p = tmp_path / "box-priors.txt"
    p.write_text("\n".join(" ".join(f"{v:.6f}" for v in r) for r in rows))
    return str(p), np.asarray(rows, np.float32)


def test_bbox_mobilenet_ssd(tmp_path):
    path, priors = _priors_file(tmp_path)
    n = priors.shape[1]
    labels = tmp_path / "labels.txt"
    labels.write_text("background\ncat\ndog\n")
    d = _dec("bounding_boxes")
    spec = TensorsSpec.from_strings(f"4:{n}:1,3:{n}:1", "float32,float32")
    opts = {
        "option1": "mobilenet-ssd",
        "option2": str(labels),
        "option3": f"{path}:0.5",
        "option4": "64:64",
        "option5": "300:300",
    }
    media = d.negotiate(spec, opts)
    assert (media.width, media.height, media.format) == (64, 64, "RGBA")
    # one hot detection at prior 5, class 1 ("cat")
    loc = np.zeros((n, 4), np.float32)
    scores = np.full((n, 3), -10.0, np.float32)
    scores[5, 1] = 8.0
    out = d.decode(Frame((loc, scores)), opts)
    dets = out.meta["detections"]
    assert dets.shape[0] == 1
    assert int(dets[0, 4]) == 1 and dets[0, 5] > 0.9
    assert out.tensors[0].shape == (64, 64, 4)
    assert out.tensors[0].any()  # something was drawn


def test_bbox_ssd_postprocess():
    d = _dec("bounding_boxes")
    spec = TensorsSpec.from_strings("4:10:1,10:1,10:1,1:1")
    opts = {"option1": "mobilenet-ssd-postprocess", "option3": "0:1:2:3,50",
            "option4": "32:32"}
    d.negotiate(spec, opts)
    loc = np.zeros((10, 4), np.float32)
    loc[0] = [0.1, 0.2, 0.5, 0.6]  # ymin,xmin,ymax,xmax
    cls = np.zeros(10, np.float32)
    sco = np.zeros(10, np.float32)
    sco[0] = 0.9
    out = d.decode(Frame((loc, cls, sco, np.array([1.0], np.float32))), opts)
    dets = out.meta["detections"]
    assert dets.shape[0] == 1
    np.testing.assert_allclose(dets[0, :4], [0.2, 0.1, 0.6, 0.5], atol=1e-6)


def test_bbox_yolov5_normalized_default():
    # reference convention: coords already normalized [0,1]
    d = _dec("bounding_boxes")
    n, c = 12, 7  # 2 classes
    spec = TensorsSpec.from_strings(f"{c}:{n}:1")
    opts = {"option1": "yolov5", "option4": "32:32", "option5": "320:320"}
    d.negotiate(spec, opts)
    pred = np.zeros((n, c), np.float32)
    pred[3] = [0.5, 0.5, 0.2, 0.2, 0.99, 0.1, 0.95]  # class 1
    out = d.decode(Frame((pred,)), opts)
    dets = out.meta["detections"]
    assert dets.shape[0] == 1
    assert int(dets[0, 4]) == 1
    np.testing.assert_allclose(dets[0, :4], [0.4, 0.4, 0.6, 0.6], atol=1e-3)


def test_bbox_yolov5_pixel_mode():
    d = _dec("bounding_boxes")
    n, c = 12, 7
    spec = TensorsSpec.from_strings(f"{c}:{n}:1")
    opts = {"option1": "yolov5", "option3": "0.3:0.6:pixel",
            "option4": "32:32", "option5": "320:320"}
    d.negotiate(spec, opts)
    pred = np.zeros((n, c), np.float32)
    pred[3] = [160, 160, 64, 64, 0.99, 0.1, 0.95]  # pixel coords
    out = d.decode(Frame((pred,)), opts)
    dets = out.meta["detections"]
    assert dets.shape[0] == 1
    np.testing.assert_allclose(dets[0, :4], [0.4, 0.4, 0.6, 0.6], atol=1e-3)


def test_bbox_ov_person():
    d = _dec("bounding_boxes")
    spec = TensorsSpec.from_strings("7:8:1:1")
    opts = {"option1": "ov-person-detection", "option4": "32:32"}
    d.negotiate(spec, opts)
    pred = np.zeros((8, 7), np.float32)
    pred[2] = [0, 1, 0.95, 0.1, 0.1, 0.4, 0.5]
    out = d.decode(Frame((pred,)), opts)
    dets = out.meta["detections"]
    assert dets.shape[0] == 1 and dets[0, 5] == pytest.approx(0.95)


def test_bbox_mp_palm_anchors():
    a = det.generate_mp_palm_anchors(input_size=64, strides=(8, 16, 16, 16))
    assert a.shape[1] == 4
    assert ((a >= 0) & (a <= 1)).all()


def test_bbox_bad_mode():
    d = _dec("bounding_boxes")
    with pytest.raises(NegotiationError):
        d.negotiate(TensorsSpec.from_strings("4:4:1"), {"option1": "nope"})


def test_bbox_tensor_count_mismatch():
    d = _dec("bounding_boxes")
    with pytest.raises(NegotiationError):
        d.negotiate(
            TensorsSpec.from_strings("4:4:1"),
            {"option1": "mobilenet-ssd-postprocess"},
        )


# ---------------------------------------------------------------------- pose
def test_pose_decoder(tmp_path):
    lab = tmp_path / "pose.txt"
    lab.write_text("nose 1\nleftEye 0\n")
    d = _dec("pose_estimation")
    spec = TensorsSpec.from_strings("2:9:9:1")
    opts = {"option1": "64:48", "option2": "257:257", "option3": str(lab)}
    media = d.negotiate(spec, opts)
    assert (media.width, media.height) == (64, 48)
    heat = np.full((1, 9, 9, 2), -5.0, np.float32)
    heat[0, 4, 4, 0] = 5.0
    heat[0, 2, 6, 1] = 5.0
    out = d.decode(Frame((heat,)), opts)
    kp = out.meta["keypoints"]
    assert kp.shape == (2, 3)
    assert kp[0, 0] == pytest.approx(4 / 8 * 64)
    assert kp[0, 1] == pytest.approx(4 / 8 * 48)
    assert out.tensors[0].shape == (48, 64, 4)


def test_pose_offset_mode():
    d = _dec("pose_estimation")
    spec = TensorsSpec.from_strings("1:9:9:1,2:9:9:1")
    opts = {"option1": "90:90", "option2": "90:90", "option4": "heatmap-offset"}
    d.negotiate(spec, opts)
    heat = np.full((1, 9, 9, 1), -5.0, np.float32)
    heat[0, 4, 4, 0] = 5.0
    offs = np.zeros((1, 9, 9, 2), np.float32)
    offs[0, 4, 4, 0] = 2.0  # y offset px
    offs[0, 4, 4, 1] = 3.0  # x offset px
    out = d.decode(Frame((heat, offs)), opts)
    kp = out.meta["keypoints"]
    # grid 4/8 * 89 + offset
    assert kp[0, 0] == pytest.approx((4 / 8 * 89 + 3), rel=1e-3)
    assert kp[0, 1] == pytest.approx((4 / 8 * 89 + 2), rel=1e-3)


# ------------------------------------------------------------- image segment
def test_image_segment_deeplab():
    d = _dec("image_segment")
    spec = TensorsSpec.from_strings("21:16:16:1")
    opts = {"option1": "tflite-deeplab"}
    media = d.negotiate(spec, opts)
    assert (media.width, media.height) == (16, 16)
    seg = np.zeros((1, 16, 16, 21), np.float32)
    seg[0, :8, :, 15] = 9.0  # top half = class 15
    out = d.decode(Frame((seg,)), opts)
    lab = out.meta["label_map"]
    assert (lab[:8] == 15).all() and (lab[8:] == 0).all()
    rgba = out.tensors[0]
    assert rgba.shape == (16, 16, 4)
    assert (rgba[:8, :, 3] == 255).all() and (rgba[8:, :, 3] == 0).all()


def test_image_segment_snpe_depth():
    d = _dec("image_segment")
    spec = TensorsSpec.from_strings("8:8", types="float32")
    opts = {"option1": "snpe-depth"}
    d.negotiate(spec, opts)
    depth = np.linspace(0, 2, 64, dtype=np.float32).reshape(8, 8)
    out = d.decode(Frame((depth,)), opts)
    assert out.tensors[0][0, 0, 0] == 0 and out.tensors[0][-1, -1, 0] == 255


# --------------------------------------------------------- byte-stream codecs
def test_octet_stream_decoder():
    d = _dec("octet_stream")
    a = np.arange(4, dtype=np.uint8)
    b = np.arange(2, dtype=np.float32)
    out = d.decode(Frame((a, b)), {})
    assert out.tensors[0].tobytes() == a.tobytes() + b.tobytes()


def _roundtrip(dec_name, conv_name, tensors):
    d = _dec(dec_name)
    blob_frame = d.decode(Frame(tuple(tensors)), {})
    conv = registry.get(registry.KIND_CONVERTER, conv_name)()
    back = conv.convert(Frame((blob_frame.tensors[0],)), {})
    assert len(back.tensors) == len(tensors)
    for orig, got in zip(tensors, back.tensors):
        assert got.dtype == orig.dtype
        np.testing.assert_array_equal(np.asarray(got), orig)


def test_protobuf_roundtrip():
    _roundtrip(
        "protobuf", "protobuf",
        [np.arange(12, dtype=np.float32).reshape(3, 4),
         np.arange(6, dtype=np.uint8).reshape(2, 3)],
    )


def test_flatbuf_roundtrip():
    _roundtrip(
        "flatbuf", "flatbuf",
        [np.arange(12, dtype=np.int16).reshape(4, 3),
         np.linspace(0, 1, 5).astype(np.float64)],
    )


def test_decoder_inventory_complete():
    """Every decoder subplugin the reference ships has a counterpart
    (SURVEY.md §2.3 decoder list)."""
    have = set(registry.available(registry.KIND_DECODER))
    for name in (
        "bounding_boxes", "direct_video", "flatbuf", "flexbuf",
        "image_labeling", "image_segment", "octet_stream",
        "pose_estimation", "protobuf",
    ):
        assert name in have, name


def test_flatbuf_carries_stream_rate():
    from fractions import Fraction

    d = _dec("flatbuf")
    spec = TensorsSpec.from_strings("4:1", "float32").with_rate(Fraction(30, 1))
    d.negotiate(spec, {})
    out = d.decode(Frame((np.zeros(4, np.float32),)), {})
    from nnstreamer_tpu.converters.flatbuf import decode_flatbuf

    _, rate = decode_flatbuf(out.tensors[0].tobytes())
    assert rate == (30, 1)


def test_protobuf_carries_stream_rate():
    from fractions import Fraction

    from nnstreamer_tpu.proto import nns_tensors_pb2 as pb

    d = _dec("protobuf")
    spec = TensorsSpec.from_strings("4:1", "float32").with_rate(Fraction(25, 1))
    d.negotiate(spec, {})
    out = d.decode(Frame((np.zeros(4, np.float32),)), {})
    msg = pb.Tensors.FromString(out.tensors[0].tobytes())
    assert (msg.fr.rate_n, msg.fr.rate_d) == (25, 1)


def test_font_decoder_renders_text():
    d = _dec("font")
    spec = TensorsSpec.from_strings("16:1")
    media = d.negotiate(spec, {"option1": "64:32"})
    assert (media.width, media.height) == (64, 32)
    text = np.frombuffer(b"hi nns\0\0\0\0\0\0\0\0\0\0", np.uint8).reshape(16, 1)
    out = d.decode(Frame((text,)), {})
    assert out.tensors[0].shape == (32, 64, 4)
    assert out.meta["text"] == "hi nns"
    assert out.tensors[0].any()
