"""Fault-tolerance layer (pipeline/faults.py, docs/fault-tolerance.md):
per-element error policies end-to-end under chaos injection — drop/retry/
route accounting over a 200-frame stream, backoff timing bounds, dead-letter
routing + error meta, batch-split retry, the stall watchdog, the filter's
circuit-breaker fallback, the failed-batcher latch, and edge reconnect.

Wall-time discipline: every sleep-bearing scenario is bounded (< ~2 s) —
the tier-1 suite brushes its budget and this file sits early in the
alphabet.
"""

import socket
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.faults import (
    FaultPolicy,
    PipelineStallError,
    backoff_s,
    resolve_fault_policy,
)
from nnstreamer_tpu.pipeline.parse import parse_pipeline

N_FRAMES = 200
CHAOS_FILTER = (
    "tensor_filter name=f framework=faulty custom=fail_rate:0.2,seed:7"
)


def _chaos_pipeline(policy_props, tail=""):
    return parse_pipeline(
        f"tensorsrc dimensions=4 num-frames={N_FRAMES} pattern=counter ! "
        f"{CHAOS_FILTER} {policy_props} ! tensor_sink name=out {tail}"
    )


# ---------------------------------------------------------------- policies
class TestPolicies:
    def test_drop_completes_with_exact_accounting(self):
        p = _chaos_pipeline("on-error=drop")
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        delivered = len(p["out"].frames)
        assert s["error_dropped"] > 0
        # dropped + routed + delivered == offered
        assert delivered + s["error_dropped"] + s["error_routed"] == N_FRAMES
        totals = ex.totals()
        assert totals["balance"] == 0
        assert totals["dropped"]["on-error-drop"] == s["error_dropped"]

    def test_retry_delivers_every_frame(self):
        p = _chaos_pipeline("on-error=retry retry-max=8 retry-backoff-ms=0.5")
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert len(p["out"].frames) == N_FRAMES
        assert s["error_retries"] > 0
        assert s["error_dropped"] == 0 and s["error_routed"] == 0

    def test_route_dead_letters_to_error_pad(self):
        p = _chaos_pipeline(
            "on-error=route", tail="f.src_1 ! tensor_sink name=dlq"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        main, dlq = p["out"].frames, p["dlq"].frames
        assert len(dlq) > 0
        assert len(main) + len(dlq) == N_FRAMES
        s = ex.stats()["f"]
        assert s["error_routed"] == len(dlq)
        assert len(main) + s["error_dropped"] + s["error_routed"] == N_FRAMES
        # routed frames reach the sink, so pipeline totals stay balanced
        assert ex.totals()["balance"] == 0
        # error frames carry the original tensors + structured error meta
        err = dlq[0]
        assert err.meta["error"] is True
        assert err.meta["error_element"] == "f"
        assert err.meta["error_type"] == "BackendError"
        assert "injected failure" in err.meta["error_msg"]
        assert err.tensors[0].shape == main[0].tensors[0].shape

    def test_stop_fails_fast_with_original_exception(self):
        from nnstreamer_tpu.backends.base import BackendError

        p = parse_pipeline(
            f"tensorsrc dimensions=4 num-frames=20 pattern=counter ! "
            "tensor_filter framework=faulty custom=fail_every_n:5 "
            "on-error=stop ! tensor_sink"
        )
        with pytest.raises(BackendError, match="injected failure"):
            p.run(timeout=30)

    def test_default_is_stop(self):
        from nnstreamer_tpu.backends.base import BackendError

        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=20 pattern=counter ! "
            "tensor_filter framework=faulty custom=fail_every_n:5 ! "
            "tensor_sink"
        )
        with pytest.raises(BackendError):
            p.run(timeout=30)

    def test_retry_exhaustion_degrades_to_drop_not_crash(self):
        # a permanently failing element: retry budget runs out per frame,
        # the frame drops, the pipeline survives
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=10 pattern=counter ! "
            "tensor_filter name=f framework=faulty custom=fail_rate:1.0 "
            "on-error=retry retry-max=1 retry-backoff-ms=0.2 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        assert len(p["out"].frames) == 0
        assert ex.stats()["f"]["error_dropped"] == 10

    def test_retry_exhaustion_routes_when_error_pad_linked(self):
        # a retry element also grows the error pad: exhausted frames land
        # in the dead-letter sink instead of vanishing
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=10 pattern=counter ! "
            "tensor_filter name=f framework=faulty custom=fail_rate:1.0 "
            "on-error=retry retry-max=1 retry-backoff-ms=0.2 ! "
            "tensor_sink name=out f.src_1 ! tensor_sink name=dlq"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        assert len(p["out"].frames) == 0
        assert len(p["dlq"].frames) == 10
        s = ex.stats()["f"]
        assert s["error_routed"] == 10 and s["error_dropped"] == 0


# ------------------------------------------------------------------ backoff
class TestBackoff:
    def test_backoff_bounds_exponential_jittered_capped(self):
        import random

        policy = FaultPolicy(
            on_error="retry", retry_max=10, backoff_ms=10.0,
            backoff_cap_ms=50.0,
        )
        rng = random.Random(1)
        for attempt in range(8):
            full = min(10.0 * 2 ** attempt, 50.0) / 1000.0
            for _ in range(16):
                d = backoff_s(attempt, policy, rng)
                assert 0.5 * full <= d <= full

    def test_observed_backoff_within_configured_bounds(self):
        # every 4th invoke fails once: each failing frame retries exactly
        # once with attempt-0 backoff in [0.5, 1.0] x 5 ms
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter name=f framework=faulty custom=fail_every_n:4 "
            "on-error=retry retry-max=3 retry-backoff-ms=5 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        s = ex.stats()["f"]
        assert len(p["out"].frames) == 40
        assert s["error_retries"] > 0
        per_retry_ms = s["error_backoff_ms"] / s["error_retries"]
        assert 2.5 <= per_retry_ms <= 5.0


# -------------------------------------------------------------- batch split
class TestBatchSplit:
    def test_host_batched_window_splits_per_frame(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=60 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=fail_every_n:7,batchable:true batching=true "
            "max-batch=8 on-error=drop ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        delivered = len(p["out"].frames)
        # one bad frame never discards its batchmates
        assert delivered + s["error_dropped"] == 60
        assert 0 < s["error_dropped"] < 60

    def test_fused_batch_split_reruns_per_frame(self):
        from nnstreamer_tpu.pipeline.executor import Executor

        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter framework=scaler custom=factor:2.0 "
            "batching=true max-batch=8 batch-timeout-ms=5 on-error=drop ! "
            "tensor_sink name=out"
        )
        plan = p.compile_plan()
        (seg,) = plan.segments
        orig = seg.process_batch
        calls = {"n": 0}

        def flaky(frames, cfg):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("injected batch failure")
            return orig(frames, cfg)

        seg.process_batch = flaky
        ex = Executor(plan)
        ex.start()
        assert ex.wait(30)
        ex.stop()
        assert not ex.errors
        # the failed batch re-ran per-frame: nothing was lost with it
        assert len(p["out"].frames) == 40
        vals = sorted(int(f.tensors[0][0]) for f in p["out"].frames)
        assert vals == sorted(range(0, 80, 2))  # counter pattern x2.0


# ----------------------------------------------------------------- watchdog
class TestStallWatchdog:
    def test_hang_becomes_typed_stall_error(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_WATCHDOG_TIMEOUT_MS", "200")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=30 pattern=counter ! "
            "tensor_chaos hang-on-frame=5 hang-ms=1200 ! tensor_sink"
        )
        with pytest.raises(PipelineStallError) as ei:
            p.run(timeout=10)
        exc = ei.value
        assert exc.timeout_ms == 200
        assert any("tensor_chaos" in name for name in exc.snapshot)
        # the snapshot localizes the hang: the chaos node has queued input
        chaos = next(s for n, s in exc.snapshot.items() if "chaos" in n)
        assert sum(chaos["queued"]) > 0

    def test_no_false_positive_on_retry_backoff(self, monkeypatch):
        # a node parked in legitimate retry backoff LONGER than the
        # watchdog timeout is recovering, not hung
        monkeypatch.setenv("NNS_TPU_EXECUTOR_WATCHDOG_TIMEOUT_MS", "150")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=12 pattern=counter ! "
            "tensor_filter name=f framework=faulty custom=fail_every_n:4 "
            "on-error=retry retry-max=2 retry-backoff-ms=250 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors and not ex.stalled
        assert len(p["out"].frames) == 12

    def test_no_false_positive_on_healthy_pipeline(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_WATCHDOG_TIMEOUT_MS", "200")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=50 pattern=counter ! "
            "tensor_transform mode=typecast option=float32 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors and not ex.stalled
        assert len(p["out"].frames) == 50


# -------------------------------------------------------- fallback breaker
class TestFallbackCircuitBreaker:
    def test_swap_then_recover(self):
        # primary fails its first 3 invokes then heals; retry absorbs the
        # pre-open failures, the fallback serves while open, a probe
        # closes the circuit again — every frame is delivered
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter name=f framework=faulty custom=fail_first_n:3 "
            "on-error=retry retry-max=4 retry-backoff-ms=0.5 "
            "fallback-framework=passthrough fallback-after=3 "
            "fallback-probe-every=8 ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        assert len(p["out"].frames) == 40
        s = ex.stats()["f"]
        assert s["cb_circuit_opens"] == 1
        assert s["cb_circuit_closes"] == 1
        assert 0 < s["cb_fallback_invokes"] <= 8
        assert s["cb_fallback_active"] == 0  # recovered

    def test_fallback_is_fusion_barrier(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=2 pattern=counter ! "
            "tensor_filter framework=scaler custom=factor:2.0 "
            "fallback-framework=passthrough ! tensor_sink"
        )
        plan = p.compile_plan()
        assert plan.segments == []  # degradable filter runs host-path


# ------------------------------------------------------------ chaos element
class TestChaosElement:
    def test_corruption_drives_downstream_policy(self):
        # tensor_chaos truncates every 4th frame's tensors; the strict
        # faulty backend rejects them; the filter's drop policy skips them
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=32 pattern=counter ! "
            "tensor_chaos corrupt-every-n=4 ! "
            "tensor_filter name=f framework=faulty "
            "custom=strict_shapes:true on-error=drop ! tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        assert len(p["out"].frames) == 24  # 32 - 8 corrupted
        assert ex.stats()["f"]["error_dropped"] == 8

    def test_chaos_own_policy_routes(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=20 pattern=counter ! "
            "tensor_chaos name=c fail-every-n=5 on-error=route ! "
            "tensor_sink name=out c.src_1 ! tensor_sink name=dlq"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        assert len(p["out"].frames) == 16
        assert len(p["dlq"].frames) == 4
        assert p["dlq"].frames[0].meta["error_type"] == "ElementError"


# ------------------------------------------------------------ config layer
class TestConfigDefaults:
    def test_executor_default_policy_from_env(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_ON_ERROR", "drop")
        monkeypatch.setenv("NNS_TPU_EXECUTOR_RETRY_MAX", "5")
        policy = resolve_fault_policy([])
        assert policy.on_error == "drop" and policy.retry_max == 5

    def test_element_property_outranks_config(self, monkeypatch):
        from nnstreamer_tpu.elements.transform import TensorTransform

        monkeypatch.setenv("NNS_TPU_EXECUTOR_ON_ERROR", "drop")
        t = TensorTransform(
            mode="typecast", option="float32", **{"on-error": "retry"}
        )
        assert resolve_fault_policy([t]).on_error == "retry"

    def test_bad_on_error_value_rejected(self):
        from nnstreamer_tpu.elements.transform import TensorTransform

        with pytest.raises(ValueError, match="on-error"):
            TensorTransform(
                mode="typecast", option="float32",
                **{"on-error": "explode"},
            )


# ----------------------------------------------------------- failed batcher
class TestBatcherFailureLatch:
    def test_failed_pump_latches_typed_error(self):
        import jax

        from nnstreamer_tpu.models import transformer as tfm
        from nnstreamer_tpu.models.serving import (
            BatcherFailedError,
            ContinuousBatcher,
        )

        params = tfm.init_params(
            jax.random.PRNGKey(0), vocab=67, d_model=32, n_heads=2,
            n_layers=1,
        )
        b = ContinuousBatcher(
            params, n_heads=2, n_slots=2, max_len=32, prompt_len=8
        )
        rid = b.submit(np.array([1, 2, 3], np.int32), max_new_tokens=4)
        assert rid is not None

        def boom(*a, **k):
            raise RuntimeError("device launch failed mid-flight")

        b._step_greedy = boom
        b._step_sampling = boom
        with pytest.raises(RuntimeError, match="mid-flight"):
            b.step()
        # donated state is gone: every later call reports the latch, not
        # a cryptic deleted-buffer error
        with pytest.raises(BatcherFailedError, match="mid-flight"):
            b.step()
        with pytest.raises(BatcherFailedError):
            b.submit(np.array([4, 5], np.int32), max_new_tokens=2)
        with pytest.raises(BatcherFailedError):
            b.step_pump(2)


# ------------------------------------------------------------ edge reconnect
class TestEdgeReconnect:
    def test_client_start_retries_until_server_up(self):
        from nnstreamer_tpu.edge.query import TensorQueryClient
        from nnstreamer_tpu.edge.transport import PyTransport

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        server = PyTransport()

        def delayed():
            time.sleep(0.3)
            server.listen("127.0.0.1", port)
            got = server.recv(timeout=5)
            if got is not None:
                server.send(got[0], got[1])  # echo

        t = threading.Thread(target=delayed, daemon=True)
        t.start()
        c = TensorQueryClient(
            "c", **{"dest-port": port, "timeout": 5, "retry-max": 8,
                    "retry-backoff-ms": 30}
        )
        c.negotiate([None])
        try:
            c.start()  # server is down for the first ~0.3 s
            from nnstreamer_tpu.tensors.frame import Frame

            f = Frame((np.arange(4, dtype=np.float32),))
            reply = c.process(f)
            np.testing.assert_allclose(
                np.asarray(reply.tensors[0]), f.tensors[0]
            )
        finally:
            c.stop()
            server.close()
            t.join(timeout=2)

    def test_no_retry_fails_fast(self):
        from nnstreamer_tpu.edge.query import TensorQueryClient
        from nnstreamer_tpu.elements.base import ElementError

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        c = TensorQueryClient("c", **{"dest-port": port, "timeout": 1})
        c.negotiate([None])
        t0 = time.monotonic()
        with pytest.raises(ElementError, match="cannot reach"):
            c.start()
        assert time.monotonic() - t0 < 2.0
