"""Pipeline runtime tests: graph building, negotiation, fusion, executor.

Mirrors reference coverage in tests/nnstreamer_plugins/unittest_plugins.cc
(programmatic pipelines with appsrc/appsink) and the SSAT pipeline tests.
"""

import numpy as np
import pytest

from nnstreamer_tpu.elements.base import NegotiationError
from nnstreamer_tpu.elements.sources import AppSrc, TensorSrc, VideoTestSrc
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.transform import TensorTransform
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import AppSink, FakeSink, TensorSink
from nnstreamer_tpu.elements.flow import Queue, Tee
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.tensors.spec import DType, TensorsSpec


def run_chain(*elems, timeout=30):
    p = Pipeline().chain(*elems)
    p.run(timeout=timeout)
    return p


class TestBasicChain:
    def test_video_to_sink(self):
        src = VideoTestSrc(width=32, height=24, **{"num-frames": 5})
        conv = TensorConverter()
        sink = TensorSink()
        run_chain(src, conv, sink)
        assert sink.rendered == 5
        assert sink.eos_seen
        assert sink.frames[0].tensors[0].shape == (1, 24, 32, 3)
        assert sink.frames[0].tensors[0].dtype == np.uint8

    def test_deterministic_source(self):
        def collect():
            src = VideoTestSrc(width=8, height=8, **{"num-frames": 3})
            conv = TensorConverter()
            sink = TensorSink()
            run_chain(src, conv, sink)
            return [np.asarray(f.tensors[0]) for f in sink.frames]

        a, b = collect(), collect()
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    def test_pts_synthesized(self):
        src = VideoTestSrc(width=8, height=8, **{"num-frames": 3}, framerate="10/1")
        conv = TensorConverter()
        sink = TensorSink()
        run_chain(src, conv, sink)
        pts = [f.pts for f in sink.frames]
        assert pts == [0, 100_000_000, 200_000_000]

    def test_frames_per_tensor_batching(self):
        src = VideoTestSrc(width=8, height=8, **{"num-frames": 6})
        conv = TensorConverter(**{"frames-per-tensor": 3})
        sink = TensorSink()
        run_chain(src, conv, sink)
        assert sink.rendered == 2
        assert sink.frames[0].tensors[0].shape == (3, 8, 8, 3)

    def test_partial_batch_dropped(self):
        src = VideoTestSrc(width=8, height=8, **{"num-frames": 5})
        conv = TensorConverter(**{"frames-per-tensor": 3})
        sink = TensorSink()
        run_chain(src, conv, sink)
        assert sink.rendered == 1

    def test_frames_per_tensor_device_frames_batch_on_device(self):
        """Device-born frames batch via jnp.stack INSIDE the converter
        (one async device op) — never through np.asarray, which would
        cost a D2H round trip per frame on the chained-device path the
        batching exists to accelerate. Values must match the host path
        exactly."""
        import jax

        outs = {}
        for dev in (False, True):
            src = VideoTestSrc(
                width=8, height=8, device=str(dev).lower(),
                **{"num-frames": 6},
            )
            conv = TensorConverter(**{"frames-per-tensor": 3})
            sink = TensorSink()
            run_chain(src, conv, sink)
            assert sink.rendered == 2
            t = sink.frames[0].tensors[0]
            if dev:
                # the converter's OUTPUT stays device-resident; the
                # sink's to_host materializes it (egress boundary)
                assert sink.frames[0].tensors[0].shape == (3, 8, 8, 3)
            outs[dev] = np.asarray(t)
        np.testing.assert_array_equal(outs[False], outs[True])


class TestTransform:
    def _run(self, mode, option, data, dims="4", types="float32"):
        src = AppSrc(iterable=[(data,)], spec=TensorsSpec.from_strings(dims, types))
        tr = TensorTransform(mode=mode, option=option)
        sink = TensorSink()
        run_chain(src, tr, sink)
        return np.asarray(sink.frames[0].tensors[0])

    def test_typecast(self):
        out = self._run("typecast", "uint8", np.array([1.7, 2.2, 3.9, 4.0], np.float32))
        assert out.dtype == np.uint8
        np.testing.assert_array_equal(out, [1, 2, 3, 4])

    def test_arithmetic_chain(self):
        out = self._run(
            "arithmetic",
            "typecast:float32,add:-127.5,div:127.5",
            np.array([0, 127.5, 255, 51], np.float32),
        )
        np.testing.assert_allclose(out, [-1.0, 0.0, 1.0, -0.6], atol=1e-6)

    def test_transpose(self):
        # reference option 1:0:2:3 swaps the two innermost dims
        data = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)
        src = AppSrc(iterable=[(data,)], spec=TensorsSpec.from_strings("4:3:2:1", "float32"))
        tr = TensorTransform(mode="transpose", option="1:0:2:3")
        sink = TensorSink()
        run_chain(src, tr, sink)
        out = np.asarray(sink.frames[0].tensors[0])
        np.testing.assert_array_equal(out, data.transpose(0, 1, 3, 2))

    def test_dimchg(self):
        # dimchg 0:2 moves innermost (channels) to position 2: NHWC→NCHW-ish
        data = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)
        src = AppSrc(iterable=[(data,)], spec=TensorsSpec.from_strings("4:3:2:1", "float32"))
        tr = TensorTransform(mode="dimchg", option="0:2")
        sink = TensorSink()
        run_chain(src, tr, sink)
        out = np.asarray(sink.frames[0].tensors[0])
        assert out.shape == (1, 4, 2, 3)

    def test_clamp(self):
        out = self._run("clamp", "0:1", np.array([-2.0, 0.5, 3.0, 1.0], np.float32))
        np.testing.assert_allclose(out, [0.0, 0.5, 1.0, 1.0])

    def test_stand_default(self):
        x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        out = self._run("stand", "default", x)
        np.testing.assert_allclose(out.mean(), 0.0, atol=1e-6)
        np.testing.assert_allclose(out.std(), 1.0, atol=1e-4)

    def test_bad_mode_raises(self):
        with pytest.raises(ValueError):
            TensorTransform(mode="nonsense")


class TestFilterInPipeline:
    def test_fused_chain_filter(self):
        src = VideoTestSrc(width=16, height=16, **{"num-frames": 4})
        conv = TensorConverter()
        tr = TensorTransform(mode="typecast", option="float32")
        filt = TensorFilter(framework="scaler", custom="factor:0.5")
        sink = TensorSink()
        p = Pipeline().chain(src, conv, tr, filt, sink)
        plan = p.compile_plan()
        # converter + transform + filter fuse into ONE segment (the
        # converter's HWC→NHWC reshape is traceable since r3)
        assert any(len(seg.ops) == 3 for seg in plan.segments)
        p.run(timeout=60)
        assert sink.rendered == 4

    def test_filter_output_parity_with_single(self):
        from nnstreamer_tpu.single import SingleShot

        data = np.random.default_rng(0).random((1, 8, 8, 3)).astype(np.float32)
        src = AppSrc(iterable=[(data,)], spec=TensorsSpec.from_strings("3:8:8:1", "float32"))
        filt = TensorFilter(framework="average")
        sink = TensorSink()
        run_chain(src, filt, sink)
        with SingleShot(
            framework="average",
            input_spec=TensorsSpec.from_strings("3:8:8:1", "float32"),
        ) as s:
            (want,) = s.invoke(data)
        np.testing.assert_allclose(
            np.asarray(sink.frames[0].tensors[0]), np.asarray(want), rtol=1e-6
        )

    def test_input_output_combination(self):
        data = np.ones((1, 4), np.float32)
        extra = np.full((1, 2), 7.0, np.float32)
        src = AppSrc(
            iterable=[(data, extra)],
            spec=TensorsSpec.from_strings("4:1,2:1", "float32,float32"),
        )
        filt = TensorFilter(
            framework="scaler",
            custom="factor:2",
            **{"input-combination": "i0", "output-combination": "o0,i1"},
        )
        sink = TensorSink()
        run_chain(src, filt, sink)
        f = sink.frames[0]
        assert f.num_tensors == 2
        np.testing.assert_allclose(np.asarray(f.tensors[0]), 2.0)
        np.testing.assert_allclose(np.asarray(f.tensors[1]), 7.0)


class TestTeeAndQueue:
    def test_tee_two_branches(self):
        src = TensorSrc(dimensions="4", **{"num-frames": 5})
        tee = Tee(name="t")
        s1, s2 = TensorSink(name="s1"), TensorSink(name="s2")
        q1, q2 = Queue(), Queue()
        p = Pipeline()
        p.chain(src, tee)
        p.link(tee, q1).link(q1, s1)
        p.link(tee, q2).link(q2, s2)
        p.run(timeout=30)
        assert s1.rendered == 5 and s2.rendered == 5

    def test_queue_splits_fusion(self):
        src = TensorSrc(dimensions="4", **{"num-frames": 2})
        t1 = TensorTransform(mode="arithmetic", option="add:1")
        q = Queue()
        t2 = TensorTransform(mode="arithmetic", option="mul:3")
        sink = TensorSink()
        p = Pipeline().chain(src, t1, q, t2, sink)
        plan = p.compile_plan()
        assert all(len(seg.ops) == 1 for seg in plan.segments)
        p.run(timeout=30)
        np.testing.assert_allclose(np.asarray(sink.frames[0].tensors[0]), 3.0)
        np.testing.assert_allclose(np.asarray(sink.frames[1].tensors[0]), 6.0)


class TestNegotiationErrors:
    def test_filter_on_media_link(self):
        src = VideoTestSrc(width=8, height=8)
        filt = TensorFilter(framework="passthrough")
        p = Pipeline().chain(src, filt, FakeSink())
        with pytest.raises(NegotiationError, match="tensor_converter"):
            p.negotiate()

    def test_unlinked_pad(self):
        p = Pipeline()
        p.add(TensorTransform(mode="typecast", option="uint8"))
        with pytest.raises(NegotiationError):
            p.negotiate()

    def test_cycle_detected(self):
        a = TensorTransform(mode="typecast", option="float32")
        b = TensorTransform(mode="typecast", option="float32")
        p = Pipeline().link(a, b).link(b, a)
        with pytest.raises(NegotiationError, match="cycle"):
            p.negotiate()


class TestErrorPropagation:
    def test_runtime_error_surfaces(self):
        def boom(frame, options):
            raise RuntimeError("decoder exploded")

        from nnstreamer_tpu.elements.decoder import (
            TensorDecoder,
            register_custom_decoder,
            unregister_custom_decoder,
        )

        register_custom_decoder("boom", boom)
        try:
            src = TensorSrc(dimensions="2", **{"num-frames": 2})
            dec = TensorDecoder(mode="custom-code", option1="boom")
            p = Pipeline().chain(src, dec, FakeSink())
            with pytest.raises(RuntimeError, match="decoder exploded"):
                p.run(timeout=30)
        finally:
            unregister_custom_decoder("boom")


class TestCustomConverter:
    def test_custom_code_converter(self):
        """mode=custom-code:<name> runs a registered in-process callable
        (reference nnstreamer_converter_custom_register)."""
        from nnstreamer_tpu.elements.converter import (
            register_custom_converter,
            unregister_custom_converter,
        )

        def flatten(frame, props):
            img = np.asarray(frame.tensors[0])
            return frame.with_tensors((img.reshape(1, -1).astype(np.int32),))

        register_custom_converter("flat", flatten)
        try:
            src = VideoTestSrc(width=8, height=8, **{"num-frames": 3})
            conv = TensorConverter(mode="custom-code:flat")
            sink = TensorSink()
            run_chain(src, conv, sink)
            assert sink.rendered == 3
            assert sink.frames[0].tensors[0].shape == (1, 8 * 8 * 3)
            assert sink.frames[0].tensors[0].dtype == np.int32
        finally:
            unregister_custom_converter("flat")

    def test_unregistered_custom_converter_fails_negotiation(self):
        src = VideoTestSrc(width=8, height=8, **{"num-frames": 1})
        conv = TensorConverter(mode="custom-code:nope")
        p = Pipeline().chain(src, conv, FakeSink())
        with pytest.raises(NegotiationError, match="not registered"):
            p.negotiate()


class TestAppSink:
    def test_pop_api(self):
        src = TensorSrc(dimensions="3", **{"num-frames": 3})
        sink = AppSink()
        p = Pipeline().chain(src, sink)
        p.start()
        seen = 0
        while True:
            f = sink.pop(timeout=30)
            if f is None:
                break
            seen += 1
        p.stop()
        assert seen == 3


class TestSinkSyncWindow:
    def test_sync_window_preserves_count_and_order(self):
        def collect(window):
            src = VideoTestSrc(width=8, height=8, **{"num-frames": 7})
            conv = TensorConverter()
            sink = TensorSink(**{"sync-window": window})
            run_chain(src, conv, sink)
            assert sink.eos_seen
            return [np.asarray(f.tensors[0]) for f in sink.frames]

        ref = collect(1)
        windowed = collect(4)
        assert len(windowed) == len(ref) == 7
        for a, b in zip(ref, windowed):
            np.testing.assert_array_equal(a, b)

    def test_sync_window_flushes_partial_window_at_eos(self):
        src = VideoTestSrc(width=8, height=8, **{"num-frames": 3})
        conv = TensorConverter()
        sink = TensorSink(**{"sync-window": 16})  # window larger than stream
        run_chain(src, conv, sink)
        assert sink.rendered == 3
        assert sink.eos_seen


class TestDevicePlacement:
    def test_two_filters_on_different_devices(self):
        """SURVEY §7 build order 5: per-stage chip placement; inter-stage
        hop is a device_put over the interconnect (ICI on TPU; the CPU
        mesh validates placement semantics)."""
        import jax

        from nnstreamer_tpu.single import SingleShot

        devs = jax.devices()
        assert len(devs) >= 2
        with SingleShot(
            framework="jax", model="zoo:add", custom="const:1,dims:4,device:0"
        ) as s0, SingleShot(
            framework="jax", model="zoo:add", custom="const:2,dims:4,device:1"
        ) as s1:
            x = np.ones((4,), np.float32)
            mid = s0.invoke(x)[0]
            assert list(mid.devices()) == [devs[0]]
            out = s1.invoke(mid)[0]
            assert list(out.devices()) == [devs[1]]
            np.testing.assert_allclose(np.asarray(out), x + 3)

    def test_pipeline_stage_placement(self):
        import jax

        src = TensorSrc(dimensions="8", types="float32", **{"num-frames": 2})
        f0 = TensorFilter(framework="jax", model="zoo:add",
                          custom="const:1,device:0")
        f1 = TensorFilter(framework="jax", model="zoo:add",
                          custom="const:1,device:1")
        sink = TensorSink()
        run_chain(src, f0, Queue(), f1, sink)
        assert sink.rendered == 2

    def test_device_out_of_range(self):
        from nnstreamer_tpu.backends.base import BackendError
        from nnstreamer_tpu.single import SingleShot

        with pytest.raises(Exception, match="out of range"):
            SingleShot(framework="jax", model="zoo:add",
                       custom="dims:4,device:99").open()


class TestDeviceResidentPath:
    """r3: device-born sources and device-computed decodes — the
    zero-host-copy pipeline spine behind the pipeline_fps bench."""

    def test_sink_window_batch_fetch_matches_per_frame(self):
        """sync-window sinks batch-fetch the window in ONE stacked
        transfer (executor SinkNode flush); rendered values must be
        byte-identical to the sync-window=1 per-frame path, partial
        final windows included."""
        def run(window):
            src = VideoTestSrc(
                width=8, height=8, device=True, **{"num-frames": 5}
            )
            conv = TensorConverter()
            tr = TensorTransform(mode="arithmetic", option="add:3")
            sink = TensorSink(**{"sync-window": window})
            p = Pipeline().chain(src, conv, tr, sink)
            p.run(timeout=60)
            assert sink.rendered == 5
            return [np.asarray(f.tensors[0]) for f in sink.frames]

        a, b = run(1), run(4)  # 4: one full window + partial flush
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)

    @pytest.mark.parametrize("pattern", ["gradient", "counter", "solid"])
    def test_videotestsrc_device_matches_host(self, pattern):
        """device=true frames are byte-identical to the host pattern
        (golden tests stay valid whichever side generates)."""
        kw = {"num-frames": 3, "width": 8, "height": 6, "pattern": pattern}
        host = VideoTestSrc(**kw)
        dev = VideoTestSrc(device=True, **kw)
        host.start()
        dev.start()
        for _ in range(3):
            a, b = host.generate(), dev.generate()
            np.testing.assert_array_equal(
                np.asarray(a.tensors[0]), np.asarray(b.tensors[0])
            )

    def test_decoder_fuses_into_filter_segment(self):
        """tensor_decoder mode=image_labeling (no labels file) is
        traceable: conv+filter+decoder compile to ONE segment, and the
        fused argmax matches the host decode path."""
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        def build(device):
            src = VideoTestSrc(
                width=16, height=16, device=device, **{"num-frames": 4}
            )
            conv = TensorConverter()
            tr = TensorTransform(mode="typecast", option="float32")
            filt = TensorFilter(framework="scaler", custom="factor:0.5")
            dec = TensorDecoder(mode="image_labeling")
            sink = TensorSink()
            p = Pipeline().chain(src, conv, tr, filt, dec, sink)
            return p, sink

        p, sink = build(device=True)
        plan = p.compile_plan()
        assert any(len(seg.ops) == 4 for seg in plan.segments)
        p.run(timeout=60)
        fused_out = [np.asarray(f.tensors[0]) for f in sink.frames]

        # host reference: same logits through the subplugin's decode()
        p2, sink2 = build(device=False)
        dec2 = p2["tensor_decoder1"] if "tensor_decoder1" in getattr(
            p2, "_by_name", {}
        ) else next(
            e for e in p2.elements if e.FACTORY_NAME == "tensor_decoder"
        )
        dec2._traceable_fn = None  # force the host path
        p2.run(timeout=60)
        host_out = [np.asarray(f.tensors[0]) for f in sink2.frames]
        assert len(fused_out) == len(host_out) == 4
        for a, b in zip(fused_out, host_out):
            assert a.dtype == np.uint32
            np.testing.assert_array_equal(a, b)


def test_sink_collects_e2e_latency_for_stamped_frames():
    """videotestsrc stamp-wall=true → SinkNode records one e2e latency
    per rendered frame (the bench's pipeline_p50_e2e_ms source)."""
    from nnstreamer_tpu.pipeline.executor import SinkNode

    src = VideoTestSrc(width=8, height=8,
                       **{"num-frames": 5, "stamp-wall": "true"})
    conv = TensorConverter()
    sink = TensorSink()
    p = Pipeline().chain(src, conv, sink)
    ex = p.run(timeout=30)
    node = next(n for n in ex.nodes if isinstance(n, SinkNode))
    assert len(node.latencies) == 5
    assert all(l >= 0 for l in node.latencies)
    # unstamped pipelines collect nothing
    p2 = Pipeline().chain(
        VideoTestSrc(width=8, height=8, **{"num-frames": 2}),
        TensorConverter(), TensorSink(),
    )
    ex2 = p2.run(timeout=30)
    node2 = next(n for n in ex2.nodes if isinstance(n, SinkNode))
    assert not node2.latencies


class TestForwardingElimination:
    """tee and queue do no per-frame work; the executor wires their
    producers straight to their consumers (r4) — same frames, fewer
    threads and hops."""

    def test_tee_and_queue_leave_no_nodes(self):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=6 ! tee name=t "
            "t. ! queue ! tensor_filter framework=passthrough ! m.sink_0 "
            "t. ! queue ! tensor_filter framework=scaler "
            "custom=factor:2.0 ! m.sink_1 "
            "tensor_mux name=m sync-mode=nosync ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        names = {n.name for n in ex.nodes}
        assert not any("tee" in n or "queue" in n for n in names)
        # src, 2 fused filters, mux, sink
        assert len(ex.nodes) == 5
        sink = p["out"]
        assert sink.rendered == 6
        # branch 0 passthrough vs branch 1 scaled ×2 of the same frame
        for f in sink.frames:
            a, b = np.asarray(f.tensors[0]), np.asarray(f.tensors[1])
            np.testing.assert_allclose(b, a * 2.0)

    def test_queue_sizes_rewritten_channel(self):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        p = parse_pipeline(
            "tensorsrc dimensions=2 num-frames=3 ! "
            "queue max-size-buffers=7 ! "
            "tensor_filter framework=passthrough ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        fused = next(n for n in ex.nodes if "filter" in n.name)
        assert fused.in_queues[0]._max == 7
        assert p["out"].rendered == 3

    def test_queue_chain_keeps_tighter_depth(self):
        """q1 ! q2 collapses to ONE channel honoring the tighter of the
        two depths (r4 advisor: taking q2's size unconditionally dropped
        q1's bound and silently widened the channel)."""
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        for chain, want in (
            ("queue max-size-buffers=3 ! queue max-size-buffers=9", 3),
            ("queue max-size-buffers=9 ! queue max-size-buffers=3", 3),
        ):
            p = parse_pipeline(
                "tensorsrc dimensions=2 num-frames=3 ! "
                f"{chain} ! "
                "tensor_filter framework=passthrough ! tensor_sink name=out"
            )
            ex = p.run(timeout=60)
            fused = next(n for n in ex.nodes if "filter" in n.name)
            assert fused.in_queues[0]._max == want
            assert p["out"].rendered == 3

    def test_queue_chain_depth_elimination_order_invariant(self):
        """Element ADD order (= elimination order) must not change the
        collapsed depth: when the downstream queue is eliminated first,
        its bound rides the outgoing-link override and the upstream
        queue's pass must still combine with it, not overwrite it."""
        from nnstreamer_tpu.elements.flow import Queue
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.sources import TensorSrc
        from nnstreamer_tpu.pipeline.graph import Pipeline

        for q1_size, q2_size in ((9, 3), (3, 9)):
            src = TensorSrc(dimensions="2", **{"num-frames": "3"})
            q1 = Queue(**{"max-size-buffers": str(q1_size)})
            q2 = Queue(**{"max-size-buffers": str(q2_size)})
            sink = TensorSink(name="out")
            p = Pipeline()
            p.add(src, q2, q1, sink)  # downstream queue added FIRST
            p.link(src, q1)
            p.link(q1, q2)
            p.link(q2, sink)
            ex = p.run(timeout=60)
            sink_node = next(n for n in ex.nodes if "out" in n.name)
            assert sink_node.in_queues[0]._max == 3
            assert sink.rendered == 3

    def test_queue_still_splits_fusion(self):
        """An explicit queue between traceable ops must keep forcing a
        segment split (its planning role) even though its node is gone."""
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        p = parse_pipeline(
            "tensorsrc dimensions=2 num-frames=2 ! "
            "tensor_filter framework=passthrough ! queue ! "
            "tensor_filter framework=passthrough ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        from nnstreamer_tpu.pipeline.executor import FusedNode

        fused = [n for n in ex.nodes if isinstance(n, FusedNode)]
        assert len(fused) == 2  # split held
        assert p["out"].rendered == 2


def test_chan_stress_no_loss_no_deadlock():
    """Hammer the SPSC channel's park/wake edges (Dekker flags +
    low-water hysteresis) from two threads with adversarial sizes:
    every item must arrive, in order, without deadlock."""
    import threading

    from nnstreamer_tpu.pipeline.executor import _Chan

    for maxsize in (1, 2, 3, 64):
        ch = _Chan(maxsize)
        stop = threading.Event()
        N = 20000
        got = []

        def consume():
            while len(got) < N:
                got.append(ch.get(stop))

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        for i in range(N):
            ch.put(i, stop)
        t.join(timeout=60)
        assert not t.is_alive(), f"consumer deadlocked at maxsize={maxsize}"
        assert got == list(range(N)), f"loss/reorder at maxsize={maxsize}"
