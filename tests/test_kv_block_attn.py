"""Block-native paged attention tests (kv/block_attn.py,
ops/pallas/paged_attention.py, docs/llm-serving.md).

The load-bearing invariants on top of test_kv_paged.py's slot-parity
matrix (which now runs the block-native default): block↔gather-oracle
byte-identical streams, the Pallas block-table kernel against its jnp
online-softmax reference in interpret mode (>1-block fills, int8
scales, scratch predication), the in-place single-block write leaving
shared/CoW blocks untouched, the zero-gather steady-state dispatch pin,
and the NNS-W117 lint. Kept lean under the tier-1 DOTS budget: one
tiny model, two shared batchers for every batcher-level test, greedy
step() drains (the pump/spec/sampling compiles already ride
test_kv_paged's block-default batchers), function-level kernel cells.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

N_HEADS = 2


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(3), vocab=127, d_model=32, n_heads=N_HEADS,
        n_layers=2,
    )


@pytest.fixture(scope="module")
def obs_reg():
    from nnstreamer_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.enable()
    yield reg
    obs_metrics.disable()


def _mk(params, **kw):
    base = dict(n_slots=2, max_len=64, prompt_len=16,
                kv_layout="paged", block_size=16)
    base.update(kw)
    return ContinuousBatcher(params, N_HEADS, **base)


@pytest.fixture(scope="module")
def block_cb(params, obs_reg):
    return _mk(params)  # kv_attn="auto" → block-native


@pytest.fixture(scope="module")
def gather_cb(params, obs_reg):
    return _mk(params, kv_attn="gather")


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(1, 127, (n,)).astype(np.int32)


def _drain(cb, rids):
    # per-token step() drains: the pump/spec scan programs are already
    # block-native-covered by test_kv_paged (block is the default) —
    # skipping them here keeps this file's compile bill inside the
    # tier-1 budget
    while any(cb.result(r) is None for r in rids):
        cb.step()
    return [cb.result(r) for r in rids]


# -- batcher-level parity + the zero-gather pin ----------------------------

def test_block_vs_gather_parity(block_cb, gather_cb):
    """Two greedy requests with multi-block prompts: the block-native
    default and the gather oracle emit byte-identical streams. The full
    parity matrix against the SLOT layout — sampling, int8, prefix
    sharing, eviction — is pinned by test_kv_paged.py, whose batchers
    run kv_attn="block" by default; this cell is the oracle↔block
    equivalence (greedy keeps the compile bill to one step program per
    batcher)."""
    # bucket-sized prompts (≤ prompt_len) keep the chunked-prefill
    # programs out of this file's compile bill; multi-block reads and
    # the cross-boundary width-1 write still happen — lane 1 decodes
    # from fill 13 into block 2
    subs = [(_prompt(5, 1), 6), (_prompt(13, 2), 5)]
    assert block_cb.stats()["kv_attn"] == "block"
    assert gather_cb.stats()["kv_attn"] == "gather"
    rb = [block_cb.submit(p, n) for p, n in subs]
    rg = [gather_cb.submit(p, n) for p, n in subs]
    assert _drain(block_cb, rb) == _drain(gather_cb, rg)


def test_zero_gather_dispatch_and_obs_counter(obs_reg, block_cb, gather_cb):
    """The steady-state regression pin: a block-native batcher NEVER
    dispatches a gather/scatter program (counter stays 0 across every
    step/pump the parity test ran), while the oracle counts one per
    launch — mirrored to nns_kv_gather_dispatch_total so operators see
    when the materialized-view round trip is being paid."""
    st_b, st_g = block_cb.stats(), gather_cb.stats()
    assert st_b["kv_gather_dispatches"] == 0
    assert st_g["kv_gather_dispatches"] > 0
    c = obs_reg.find("nns_kv_gather_dispatch_total")
    assert c is not None and c.value == st_g["kv_gather_dispatches"]
    # and the pin survives more pumped decode on the block batcher
    r = block_cb.submit(_prompt(4, 9), 5)
    _drain(block_cb, [r])
    assert block_cb.stats()["kv_gather_dispatches"] == 0
    assert obs_reg.find("nns_kv_gather_dispatch_total").value == c.value


def test_in_place_write_leaves_shared_blocks_untouched(block_cb):
    """The width-1 in-place block update only touches the decoding
    request's privately-owned blocks: a registered (pinned, shared)
    prefix's arena blocks are bitwise unchanged by a sharer's decode."""
    sysp = _prompt(32, 7)  # 2 full blocks, pinned by registration
    pid = block_cb.register_prefix(sysp)
    blocks = list(block_cb._prefixes_paged[pid][1])
    assert len(blocks) == 2

    def read(b):
        ks, vs = block_cb._read_block(
            block_cb._cache, jnp.asarray(b, jnp.int32)
        )
        return np.asarray(ks).copy(), np.asarray(vs).copy()

    before = [read(b) for b in blocks]
    r = block_cb.submit(_prompt(3, 8), 4, prefix=pid)
    _drain(block_cb, [r])
    after = [read(b) for b in blocks]
    for (k0, v0), (k1, v1) in zip(before, after):
        assert (k0 == k1).all() and (v0 == v1).all()
    assert block_cb.unregister_prefix(pid)


# -- Pallas block-table kernel vs the jnp online-softmax reference ---------

def _rand_case(seed, B=3, H=4, KV=2, D=16, bs=8, nb=4, N=14):
    """Random arena + tables with >1-block fills, scratch-mapped table
    tails, and NONZERO scratch content (block 0) so masking — not
    initialization — is what keeps dead columns at exact zero weight."""
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((B, 1, H, D)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((N + 1, bs, KV, D)), jnp.float32)
    cv = jnp.asarray(rng.standard_normal((N + 1, bs, KV, D)), jnp.float32)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, N + 1))[: B * nb]
        .reshape(B, nb).astype(np.int32)
    )
    # lane 0: ALL-scratch table at pos 0 (nothing live but the fresh
    # token — the @pl.when predication case, asserted exactly below);
    # lane 1: >1-block fill with a scratch-mapped tail
    tables = tables.at[0, :].set(0).at[1, 3:].set(0)
    pos = jnp.asarray([0, 2 * bs + 3, nb * bs - 1], jnp.int32)
    fk = jnp.asarray(rng.standard_normal((B, 1, KV, D)), jnp.float32)
    fv = jnp.asarray(rng.standard_normal((B, 1, KV, D)), jnp.float32)
    return q, ck, cv, tables, pos, fk, fv


def _exact(q, ck, cv, tables, pos, fk, fv):
    """The batcher's exact formulation: take → write fresh at pos →
    full masked softmax ≤ pos (bitwise the gathered view's math)."""
    b, nb = tables.shape
    bs = ck.shape[1]
    vk = jnp.take(ck, tables, axis=0).reshape(
        b, nb * bs, ck.shape[2], ck.shape[3]
    )
    vv = jnp.take(cv, tables, axis=0).reshape(
        b, nb * bs, cv.shape[2], cv.shape[3]
    )
    dus = jax.vmap(
        lambda c, n, p: jax.lax.dynamic_update_slice(c, n, (p, 0, 0))
    )
    vk, vv = dus(vk, fk, pos), dus(vv, fv, pos)
    mask = jnp.arange(nb * bs)[None, :] <= pos[:, None]
    return tfm.cache_attention(q, vk, vv, mask[:, None, :])


def test_kernel_interpret_parity_fp():
    from nnstreamer_tpu.kv.block_attn import paged_attention_ref
    from nnstreamer_tpu.ops.pallas.paged_attention import (
        paged_decode_attention,
    )

    q, ck, cv, tables, pos, fk, fv = _rand_case(0)
    ex = _exact(q, ck, cv, tables, pos, fk, fv)
    ref = paged_attention_ref(q, ck, cv, tables, pos, (fk, fv))
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ex), atol=2e-5)
    out = paged_decode_attention(
        q, ck, cv, tables, pos, fk, fv, interpret=True
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # lane 0 (pos=0, all-scratch table): the one live column is the
    # fresh token, so its softmax weight is exactly 1 and arbitrary
    # scratch content contributes exact zeros — for kernel AND ref
    B, KV, D = fv.shape[0], fv.shape[2], fv.shape[3]
    want0 = np.broadcast_to(
        np.asarray(fv)[0, :, :, None, :], (1, KV, 2, D)
    ).reshape(1, 4, D)
    for got in (out, ref):
        np.testing.assert_allclose(np.asarray(got)[0], want0, atol=1e-5)


def test_kernel_interpret_parity_int8_scales():
    from nnstreamer_tpu.kv.block_attn import paged_attention_ref
    from nnstreamer_tpu.models.serving import dequantize_kv, quantize_kv
    from nnstreamer_tpu.ops.pallas.paged_attention import (
        paged_decode_attention,
    )

    q, ck, cv, tables, pos, fk, fv = _rand_case(1)
    k8, ks = quantize_kv(ck)
    v8, vs = quantize_kv(cv)
    ex = _exact(q, dequantize_kv(k8, ks), dequantize_kv(v8, vs),
                tables, pos, fk, fv)
    ref = paged_attention_ref(
        q, k8, v8, tables, pos, (fk, fv), k_scale=ks, v_scale=vs
    )
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ex), atol=2e-5)
    out = paged_decode_attention(
        q, k8, v8, tables, pos, fk, fv, k_scale=ks, v_scale=vs,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_block_attention_impl_dispatch():
    from nnstreamer_tpu.kv import block_attn as kvb

    q, ck, cv, tables, pos, fk, fv = _rand_case(3)
    jnp_out = kvb.block_attention(q, ck, cv, tables, pos, (fk, fv),
                                  impl="jnp")
    pl_out = kvb.block_attention(q, ck, cv, tables, pos, (fk, fv),
                                 impl="pallas")  # interpret off-TPU
    np.testing.assert_allclose(
        np.asarray(pl_out), np.asarray(jnp_out), atol=2e-5
    )
    with pytest.raises(ValueError, match="impl"):
        kvb.block_attention(q, ck, cv, tables, pos, (fk, fv), impl="cuda")


# -- configuration / lint ---------------------------------------------------

def test_kv_attn_validation(params):
    with pytest.raises(ValueError, match="kv_attn"):
        ContinuousBatcher(params, N_HEADS, kv_attn="virtual")
    with pytest.raises(ValueError, match="slot"):
        ContinuousBatcher(params, N_HEADS, kv_attn="block")  # slot layout
    with pytest.raises(ValueError, match="block-native"):
        _mk(params, kv_attn="gather", attn_impl="pallas")


def test_w117_paged_gather_materializes_cache_both_ways():
    from nnstreamer_tpu.analysis import lint

    head = ("tensorsrc dimensions=4 types=int32 num-frames=1 ! "
            "tensor_llm_serversink id=92 n-slots=64 max-len=2048 "
            "kv-layout=paged ")
    r_bad = lint(head + "kv-attn=gather kv-memory-bound=64M")
    assert "NNS-W117" in r_bad.codes
    assert r_bad.exit_code == 1  # warning, not error
    # the block-native default has no gathered view; no declared bound
    # stays silent; a bound the arena+view fit under is fine
    assert "NNS-W117" not in lint(head + "kv-memory-bound=64M").codes
    assert "NNS-W117" not in lint(head + "kv-attn=gather").codes
    assert "NNS-W117" not in lint(
        head + "kv-attn=gather kv-memory-bound=64G"
    ).codes
    # and W115 never fires on a paged layout
    assert "NNS-W115" not in r_bad.codes
