"""Failure-injection sweep (reference §5.3: invalid models/dims/properties
golden-failure cases — gstTest "expect fail" flags). Every bad input must
produce a *typed, descriptive* error, never a hang or a silent wrong
answer."""

import numpy as np
import pytest

from nnstreamer_tpu import registry
from nnstreamer_tpu.backends.base import BackendError
from nnstreamer_tpu.elements.base import ElementError, NegotiationError
from nnstreamer_tpu.tensors.spec import TensorsSpec


class TestParseFailures:
    def test_unknown_element(self):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        with pytest.raises(Exception, match="nosuchelement"):
            parse_pipeline("nosuchelement ! tensor_sink")

    def test_bad_dim_string(self):
        with pytest.raises(Exception):
            TensorsSpec.from_strings("4:x:1")

    def test_empty_pipeline(self):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        with pytest.raises(Exception):
            parse_pipeline("")


class TestModelFailures:
    def test_unknown_zoo_model(self):
        from nnstreamer_tpu.single import SingleShot

        with pytest.raises(Exception, match="unknown zoo model"):
            SingleShot(framework="jax", model="zoo:nope").open()

    def test_missing_model_file(self):
        from nnstreamer_tpu.single import SingleShot

        with pytest.raises(Exception, match="not found"):
            SingleShot(framework="custom", model="/no/such/script.py").open()

    def test_unknown_framework(self):
        from nnstreamer_tpu.single import SingleShot

        with pytest.raises(Exception, match="no filter subplugin"):
            SingleShot(framework="nosuchfw", model="x").open()

    def test_invoke_shape_mismatch(self):
        from nnstreamer_tpu.single import SingleShot

        with SingleShot(framework="jax", model="zoo:add", custom="dims:4") as s:
            with pytest.raises(BackendError, match="shape"):
                s.invoke(np.zeros((5,), np.float32))


class TestDecoderFailures:
    def test_unknown_mode(self):
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        d = TensorDecoder(mode="nosuchmode")
        with pytest.raises(Exception, match="nosuchmode"):
            d.negotiate([TensorsSpec.from_strings("4:1")])

    def test_bbox_wrong_tensor_count(self):
        cls = registry.get(registry.KIND_DECODER, "bounding_boxes")
        with pytest.raises(NegotiationError, match="expected"):
            cls().negotiate(
                TensorsSpec.from_strings("4:1"),
                {"option1": "mobilenet-ssd-postprocess"},
            )

    def test_pose_wrong_tensor_count(self):
        cls = registry.get(registry.KIND_DECODER, "pose_estimation")
        with pytest.raises(NegotiationError, match="expected"):
            cls().negotiate(
                TensorsSpec.from_strings("17:9:9:1,34:9:9:1,32:9:9:1"),
                {"option4": "heatmap-only"},
            )


class TestElementFailures:
    def test_mux_over_tensor_limit(self):
        from nnstreamer_tpu.elements.routing import TensorMux
        from nnstreamer_tpu.tensors.spec import NNS_TENSOR_SIZE_LIMIT

        mux = TensorMux()
        mux.set_pad_counts(3, 1)
        specs = [
            TensorsSpec.from_strings(",".join(["4"] * 6), ",".join(["float32"] * 6))
            for _ in range(3)
        ]
        with pytest.raises(NegotiationError, match="exceeds limit"):
            mux.negotiate(specs)

    def test_filter_needs_tensor_input(self):
        from nnstreamer_tpu.elements.base import MediaSpec
        from nnstreamer_tpu.elements.filter import TensorFilter

        f = TensorFilter(framework="passthrough")
        with pytest.raises(NegotiationError, match="tensor_converter"):
            f.negotiate([MediaSpec("video", width=8, height=8, format="RGB")])

    def test_pipeline_error_propagates(self):
        """A failing element poisons the pipeline with its error (reference
        GST_FLOW_ERROR → pipeline error message), not a hang."""
        from nnstreamer_tpu.backends.custom import register_custom_easy, unregister_custom_easy
        from nnstreamer_tpu.elements.filter import TensorFilter
        from nnstreamer_tpu.elements.sink import TensorSink
        from nnstreamer_tpu.elements.sources import TensorSrc
        from nnstreamer_tpu.pipeline.graph import Pipeline

        def boom(tensors):
            raise RuntimeError("injected failure")

        register_custom_easy("boom_fn", boom)
        try:
            src = TensorSrc(dimensions="4", types="float32", **{"num-frames": 2})
            filt = TensorFilter(framework="custom-easy", model="boom_fn")
            sink = TensorSink()
            with pytest.raises(Exception, match="injected failure"):
                Pipeline().chain(src, filt, sink).run(timeout=60)
        finally:
            unregister_custom_easy("boom_fn")
