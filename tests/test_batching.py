"""Adaptive micro-batching (pipeline/batching.py + FusedSegment batched
variants): order/metadata preservation, EOS mid-batch flush, trickle
timeout flush, bucket padding with a bounded jit-trace count, batched ==
per-frame bitwise parity, host-backend batching capability gating, and
the observability surface (read-only tensor_filter props, executor
stats, bench smoke mode)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import AppSrc
from nnstreamer_tpu.pipeline.batching import (
    BatchConfig,
    default_buckets,
    resolve_batch_config,
)
from nnstreamer_tpu.pipeline.executor import FusedNode
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _sink_arrays(ex):
    sink = next(
        n.elem for n in ex.nodes
        if isinstance(getattr(n, "elem", None), TensorSink)
    )
    return [[np.asarray(t) for t in f.tensors] for f in sink.frames], sink


def _fused_seg(ex):
    return next(n.seg for n in ex.nodes if isinstance(n, FusedNode))


# ---------------------------------------------------------------------------
# config resolution / buckets
# ---------------------------------------------------------------------------

def test_default_buckets_ladder():
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(6) == (1, 2, 4, 6)
    assert default_buckets(1) == (1,)


def test_bucket_for_rounds_up():
    cfg = BatchConfig(True, 8, 1.0, (1, 2, 4, 8))
    assert [cfg.bucket_for(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]


def test_element_props_override_executor_default():
    f = TensorFilter(
        framework="scaler", custom="factor:2.0", input="4",
        batching="true", max_batch="4", batch_timeout_ms="0.5",
    )
    cfg = resolve_batch_config([f])
    assert cfg.active and cfg.max_batch == 4
    assert cfg.timeout_ms == 0.5
    assert cfg.buckets == (1, 2, 4)
    # unset element + default config → disabled
    f2 = TensorFilter(framework="scaler", custom="factor:2.0", input="4")
    assert not resolve_batch_config([f2]).enabled


def test_executor_env_default_enables(monkeypatch):
    monkeypatch.setenv("NNS_TPU_EXECUTOR_BATCHING", "true")
    monkeypatch.setenv("NNS_TPU_EXECUTOR_MAX_BATCH", "6")
    f = TensorFilter(framework="scaler", custom="factor:2.0", input="4")
    cfg = resolve_batch_config([f])
    assert cfg.enabled and cfg.max_batch == 6
    assert cfg.buckets == (1, 2, 4, 6)


# ---------------------------------------------------------------------------
# parity: batched == per-frame, order + metadata intact
# ---------------------------------------------------------------------------

def _run_chain(batch_props, n=14):
    desc = (
        f"videotestsrc pattern=gradient device=true num-frames={n} "
        "width=16 height=16 ! tensor_converter ! "
        "tensor_transform mode=typecast option=float32 ! "
        f"tensor_filter framework=scaler custom=factor:0.5 {batch_props} ! "
        "tensor_decoder mode=image_labeling ! tensor_sink"
    )
    ex = parse_pipeline(desc).run(timeout=300)
    frames, _ = _sink_arrays(ex)
    return frames, _fused_seg(ex)


def test_batched_parity_transform_filter_decode_bitwise():
    """Acceptance bar: for a fused transform→filter→decode chain the
    batched per-frame results are BITWISE identical to batching=false,
    in order (CPU)."""
    base, seg_u = _run_chain("batching=false")
    batched, seg_b = _run_chain("batching=true max-batch=4 batch-timeout-ms=5")
    assert len(base) == len(batched) == 14
    for fa, fb in zip(base, batched):
        assert len(fa) == len(fb)
        for ta, tb in zip(fa, fb):
            assert ta.dtype == tb.dtype and ta.shape == tb.shape
            np.testing.assert_array_equal(ta, tb)
    assert seg_b.batch_stats.frames == 14
    assert seg_b.batch_stats.avg_batch_size >= 1.0


def _push_later(src, frames, delay=0.0, gap=0.0):
    def pump():
        if delay:
            time.sleep(delay)
        for f in frames:
            src.push(f)
            if gap:
                time.sleep(gap)
        src.end_of_stream()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def test_order_and_metadata_preserved_under_batching():
    src = AppSrc(dimensions="4", types="float32")
    filt = TensorFilter(
        framework="scaler", custom="factor:2.0",
        batching="true", max_batch="4", batch_timeout_ms="10",
    )
    sink = TensorSink()
    p = Pipeline().chain(src, filt, sink)
    n = 11
    frames = [
        Frame(
            (np.full((4,), i, np.float32),),
            pts=i * 1_000_000, duration=1_000_000,
            meta={"idx": i},
        )
        for i in range(n)
    ]
    ex = p.start()
    _push_later(src, frames)
    assert ex.wait(60)
    p.stop()
    assert len(sink.frames) == n
    for i, f in enumerate(sink.frames):
        assert f.meta["idx"] == i          # order AND metadata
        assert f.pts == i * 1_000_000      # timestamps ride along
        assert f.duration == 1_000_000
        np.testing.assert_array_equal(
            np.asarray(f.tensors[0]), np.full((4,), 2.0 * i, np.float32)
        )


def test_eos_mid_batch_flushes_partial_window():
    """EOS arriving while a batch is open: the partial window flushes
    (nothing dropped, order kept), then EOS propagates."""
    src = AppSrc(dimensions="4", types="float32")
    filt = TensorFilter(
        framework="scaler", custom="factor:2.0",
        batching="true", max_batch="8", batch_timeout_ms="50",
    )
    sink = TensorSink()
    p = Pipeline().chain(src, filt, sink)
    frames = [Frame((np.full((4,), i, np.float32),)) for i in range(5)]
    ex = p.start()
    _push_later(src, frames, delay=0.05)
    assert ex.wait(60)
    p.stop()
    assert len(sink.frames) == 5
    assert sink.eos_seen
    for i, f in enumerate(sink.frames):
        np.testing.assert_array_equal(
            np.asarray(f.tensors[0]), np.full((4,), 2.0 * i, np.float32)
        )


def test_timeout_flush_with_trickle_source():
    """Trickle-fed (inter-frame gap >> batch-timeout-ms): every frame
    must flush after at most the timeout — small batches, bounded added
    latency, and the straggler wait shows up in batch_wait_ms."""
    src = AppSrc(dimensions="4", types="float32")
    filt = TensorFilter(
        framework="scaler", custom="factor:2.0",
        batching="true", max_batch="8", batch_timeout_ms="5",
    )
    sink = TensorSink()
    p = Pipeline().chain(src, filt, sink)
    n = 4
    frames = [Frame((np.full((4,), i, np.float32),)) for i in range(n)]
    ex = p.start()
    t0 = time.perf_counter()
    _push_later(src, frames, gap=0.03)
    assert ex.wait(60)
    elapsed = time.perf_counter() - t0
    p.stop()
    assert len(sink.frames) == n
    stats = filt.batch_stats
    assert stats is not None and stats.frames == n
    # trickle: batches stay small (the timeout flushed them, the cap
    # did not), and the run did not serialize behind full timeouts
    assert stats.avg_batch_size < 8
    assert elapsed < 10.0


# ---------------------------------------------------------------------------
# buckets / trace counting / stale-cache fix
# ---------------------------------------------------------------------------

def _make_segment():
    desc = (
        "tensorsrc dimensions=4 num-frames=1 ! "
        "tensor_transform mode=arithmetic option=add:1.0 ! "
        "tensor_filter framework=scaler custom=factor:2.0 input=4 ! "
        "tensor_sink"
    )
    p = parse_pipeline(desc)
    plan = p.compile_plan()
    seg = next(s for s in plan.segments if len(s.ops) >= 2)
    return seg


def test_bucket_padding_bounds_traces():
    """Batch sizes are padded up the bucket ladder, so the segment
    compiles at most O(log max-batch) batched variants — asserted via
    the segment's jit-trace counter — and padded results equal the
    per-frame oracle exactly."""
    seg = _make_segment()
    cfg = BatchConfig(True, 8, 0.0, default_buckets(8))
    rng = np.random.default_rng(0)
    frames = [
        Frame((rng.standard_normal(4).astype(np.float32),))
        for _ in range(8)
    ]
    oracle = [np.asarray(seg.process(f).tensors[0]) for f in frames]
    for n in (1, 2, 3, 5, 7, 8):
        outs, bucket = seg.process_batch(frames[:n], cfg)
        assert bucket == cfg.bucket_for(n) and bucket >= n
        assert len(outs) == n
        for got, want in zip(outs, oracle):
            np.testing.assert_array_equal(np.asarray(got.tensors[0]), want)
    # buckets hit: 1,2,4,8 (batched) + the per-frame program = 5 traces
    assert seg.n_traces <= len(cfg.buckets) + 1
    # repeat sizes: fully cached, no new traces
    before = seg.n_traces
    seg.process_batch(frames[:3], cfg)
    seg.process_batch(frames[:5], cfg)
    assert seg.n_traces == before


def test_segment_cache_keyed_by_shapes_dtypes():
    """Regression (stale jit cache): the compiled-program cache keys on
    (arity, shapes, dtypes) — a renegotiated signature gets a FRESH
    program (with freshly collected op fns) instead of silently reusing
    the old one."""
    seg = _make_segment()
    f4 = Frame((np.arange(4, dtype=np.float32),))
    out4 = seg.process(f4)
    np.testing.assert_allclose(
        np.asarray(out4.tensors[0]), (np.arange(4) + 1.0) * 2.0
    )
    n_after_first = seg.n_traces
    # renegotiated shape → distinct cache entry, correct result
    f8 = Frame((np.arange(8, dtype=np.float32),))
    out8 = seg.process(f8)
    assert np.asarray(out8.tensors[0]).shape == (8,)
    np.testing.assert_allclose(
        np.asarray(out8.tensors[0]), (np.arange(8) + 1.0) * 2.0
    )
    assert seg.n_traces == n_after_first + 1
    # same signature again: cached, no new trace
    seg.process(Frame((np.zeros((4,), np.float32),)))
    assert seg.n_traces == n_after_first + 1


def test_process_batch_heterogeneous_window_falls_back():
    """A window mixing signatures (flexible stream / renegotiation
    boundary) cannot share one stacked invoke: process_batch falls back
    to per-frame programs with identical semantics."""
    seg = _make_segment()
    cfg = BatchConfig(True, 8, 0.0, default_buckets(8))
    mixed = [
        Frame((np.arange(4, dtype=np.float32),)),
        Frame((np.arange(8, dtype=np.float32),)),
    ]
    outs, bucket = seg.process_batch(mixed, cfg)
    assert bucket == 2 and len(outs) == 2
    np.testing.assert_allclose(
        np.asarray(outs[0].tensors[0]), (np.arange(4) + 1.0) * 2.0
    )
    np.testing.assert_allclose(
        np.asarray(outs[1].tensors[0]), (np.arange(8) + 1.0) * 2.0
    )


def test_fn_version_tick_invalidates_same_shape_cache():
    """Regression (same-shape hot swap): reload_model ticks the op's
    fn_version, which is part of the compiled-program cache key — the
    segment must recollect make_fn() and recompile instead of serving
    the old weights from the signature-matched entry."""
    seg = _make_segment()
    f = Frame((np.arange(4, dtype=np.float32),))
    out1 = seg.process(f)
    np.testing.assert_allclose(
        np.asarray(out1.tensors[0]), (np.arange(4) + 1.0) * 2.0
    )
    filt = seg.ops[-1]
    # simulate a same-shape model swap: backend fn changes, shapes don't
    filt.backend._factor = 3.0
    filt.fn_version += 1  # what reload_model() does
    before = seg.n_traces
    out2 = seg.process(f)
    np.testing.assert_allclose(
        np.asarray(out2.tensors[0]), (np.arange(4) + 1.0) * 3.0
    )
    assert seg.n_traces == before + 1


def test_host_bad_batching_property_fails_at_plan_time():
    """A bad batching property on a host-backend (non-traceable) filter
    must fail compile_plan() like it does for fused filters — not poison
    the pipeline from inside a node thread after startup."""
    desc = (
        "videotestsrc num-frames=4 width=8 height=8 ! tensor_converter ! "
        "tensor_filter framework=hostscaler custom=factor:2.0 "
        "batching=true max-batch=notanint ! tensor_sink"
    )
    p = parse_pipeline(desc)
    with pytest.raises(ValueError, match=r"max-batch.*notanint"):
        p.compile_plan()


def test_bad_batching_property_names_element_and_prop():
    f = TensorFilter(
        framework="scaler", custom="factor:2.0", input="4",
        batching="true", max_batch="notanint",
    )
    with pytest.raises(ValueError, match=r"max-batch.*notanint"):
        resolve_batch_config([f])
    f2 = TensorFilter(
        framework="scaler", custom="factor:2.0", input="4",
        batching="true", batch_buckets="2;4",
    )
    with pytest.raises(ValueError, match=r"batch-buckets"):
        resolve_batch_config([f2])


# ---------------------------------------------------------------------------
# host path: batchable capability gating
# ---------------------------------------------------------------------------

def test_host_batchable_backend_batches():
    desc = (
        "videotestsrc pattern=gradient device=false num-frames=10 "
        "width=8 height=8 ! tensor_converter ! "
        "tensor_filter framework=hostscaler custom=factor:3.0 "
        "batching=true max-batch=4 batch-timeout-ms=10 ! tensor_sink"
    )
    p = parse_pipeline(desc)
    filt = next(
        e for e in p.elements if isinstance(e, TensorFilter)
    )
    ex = p.run(timeout=300)
    frames, _ = _sink_arrays(ex)
    assert len(frames) == 10
    stats = filt.batch_stats
    assert stats is not None and stats.frames == 10
    # read-only observability properties next to latency/throughput
    assert filt.avg_batch_size >= 1.0
    assert filt.pad_waste_pct == 0.0  # host path never pads
    assert filt.latency_us >= 0.0
    node_stats = ex.stats()[filt.name]
    assert "avg_batch_size" in node_stats
    assert "batch_wait_ms" in node_stats


def test_host_heterogeneous_window_falls_back_per_frame():
    """Mixed-shape window on the host batched path: per-frame fallback
    (parity with FusedSegment.process_batch), not an np.stack crash."""
    f = TensorFilter(
        framework="hostscaler", custom="factor:2.0", input="4",
        batching="true", max_batch="8",
    )
    f.fix_negotiation([TensorsSpec.from_strings("4", "float32")])
    mixed = [
        Frame((np.arange(4, dtype=np.float32),)),
        Frame((np.arange(8, dtype=np.float32),)),
    ]
    outs = f.host_process_batch(mixed)
    assert len(outs) == 2
    np.testing.assert_array_equal(
        np.asarray(outs[0].tensors[0]), np.arange(4, dtype=np.float32) * 2
    )
    np.testing.assert_array_equal(
        np.asarray(outs[1].tensors[0]), np.arange(8, dtype=np.float32) * 2
    )
    f.stop()


def test_host_non_batchable_backend_keeps_per_frame():
    """framecounter is host-bound and did NOT declare batchable: with
    batching=true it must keep per-frame invokes (and stay correct —
    it is stateful, exactly why the capability flag exists)."""
    desc = (
        "tensorsrc dimensions=2 num-frames=6 ! "
        "tensor_filter framework=framecounter input=2 "
        "batching=true max-batch=4 ! tensor_sink"
    )
    ex = parse_pipeline(desc).run(timeout=300)
    frames, _ = _sink_arrays(ex)
    assert len(frames) == 6
    counts = [int(np.asarray(f[0]).ravel()[0]) for f in frames]
    assert counts == [0, 1, 2, 3, 4, 5]


# ---------------------------------------------------------------------------
# tracing + bench smoke
# ---------------------------------------------------------------------------

def test_batch_assembly_trace_spans():
    from nnstreamer_tpu import trace

    trace.enable().clear()
    try:
        _run_chain("batching=true max-batch=4 batch-timeout-ms=5", n=8)
        events = trace.get().events()
        spans = [e for e in events if e.get("cat") == "batch"]
        assert spans, "no batch-assembly spans recorded"
        args = spans[0]["args"]
        assert {"batch", "bucket", "wait_ms", "pad_waste_pct"} <= set(args)
        assert args["batch"] >= 1 and args["bucket"] >= args["batch"]
    finally:
        trace.disable()


def test_bench_batched_smoke_mode():
    """bench.py --pipeline batched --smoke: one JSON line with the
    batched-vs-unbatched fps cells (CPU, small frame count)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--pipeline", "batched", "--smoke"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO,
    )
    assert proc.returncode in (0, None), proc.stderr[-800:]
    line = [
        ln for ln in proc.stdout.strip().splitlines()
        if ln.startswith("{")
    ][-1]
    rec = json.loads(line)
    assert rec["metric"] == "mobilenet_style_pipeline_batched_vs_unbatched_fps"
    assert rec["batched_fps"] and rec["unbatched_fps"]
    assert rec["speedup"] is not None
    # batching must never be a catastrophic loss on the smoke config
    # (the ≥1.5× target is the bench's headline; a hard CI assert at
    # that level would flake on loaded runners — floor it at parity-ish)
    assert rec["speedup"] > 0.8
    assert rec["segment_traces"] <= 5  # per-frame + ≤4 buckets
