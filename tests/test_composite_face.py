"""Composite face→crop→landmark pipeline, end-to-end across devices.

BASELINE config #5: face detector on one chip feeds bounding regions to
tensor_crop; crops stream to a landmark model on a second chip. Reference
building blocks: gsttensor_crop.c + tensordec-boundingbox.c composition and
the query-offload examples. Here the stages are pinned to different devices
of the virtual 8-CPU mesh (custom="device:N") and the hop rides device
transfer, not host TCP.
"""

import numpy as np
import pytest

from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.pipeline.parse import parse_pipeline

COMPOSITE = (
    "videotestsrc pattern=gradient num-frames={n} width=128 height=128 ! "
    "tensor_converter ! tee name=t "
    "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
    'custom="output:regions,threshold:0.0,frame_size:128:128{det_dev}" ! '
    "crop.sink_1 "
    "t. ! queue ! crop.sink_0 "
    "tensor_crop name=crop ! "
    "tensor_filter framework=jax model=zoo:face_landmark "
    'custom="{lmk_dev}" invoke-dynamic=true input-combination=0 ! '
    "tensor_sink name=out"
)


def _run_composite(n=2, det_dev=",device:0", lmk_dev="device:1"):
    p = parse_pipeline(COMPOSITE.format(n=n, det_dev=det_dev, lmk_dev=lmk_dev))
    p.run(timeout=240)
    sink = next(e for e in p.elements if isinstance(e, TensorSink))
    return [np.asarray(f.tensors[0]) for f in sink.frames]


def test_composite_multichip_e2e():
    outs = _run_composite()
    assert len(outs) == 2
    for lm in outs:
        assert lm.shape == (1, 136)
        assert np.all(np.isfinite(lm))
        assert np.all(lm >= 0) and np.all(lm <= 1)


def test_composite_deterministic_golden():
    """Same pipeline, two fresh runs → bit-identical landmark streams (the
    SSAT golden-compare property, held in-process)."""
    a = _run_composite()
    b = _run_composite()
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_composite_device_pinning_matches_unpinned():
    """Placement is a scheduling choice, not a numeric one."""
    pinned = _run_composite()
    unpinned = _run_composite(det_dev="", lmk_dev="")
    for x, y in zip(pinned, unpinned):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)


# ---- device-resident crop (out-size=) — the r3 cascade-cliff fix ----

DEVICE_COMPOSITE = (
    "videotestsrc pattern=gradient num-frames={n} width=128 height=128 ! "
    "tensor_converter ! tee name=t "
    "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
    'custom="output:regions,threshold:0.0,frame_size:128:128" ! '
    "crop.sink_1 "
    "t. ! queue ! crop.sink_0 "
    "tensor_crop name=crop out-size=112:112 max-crops=16 ! "
    "tensor_filter framework=jax model=zoo:face_landmark "
    'custom="batch:16" ! tensor_sink name=out'
)


def test_device_crop_static_cascade():
    """out-size= crop: static [16,112,112,3] spec, landmark runs all
    crops as one batch, outputs finite landmarks per crop slot."""
    p = parse_pipeline(DEVICE_COMPOSITE.format(n=3))
    p.run(timeout=240)
    sink = next(e for e in p.elements if isinstance(e, TensorSink))
    assert len(sink.frames) == 3
    for f in sink.frames:
        lm = np.asarray(f.tensors[0])
        assert lm.shape == (16, 136)
        assert np.all(np.isfinite(lm))


def test_device_crop_no_host_readback():
    """The device crop path must keep everything in device buffers: with
    a device-born source and a discarding sink, the whole cascade runs
    under a device->host transfer guard — any per-frame readback (the r2
    cliff's cause) raises."""
    import jax

    desc = DEVICE_COMPOSITE.format(n=2).replace(
        "videotestsrc ", "videotestsrc device=true "
    ).replace("tensor_sink name=out", "fakesink")
    with jax.transfer_guard_device_to_host("disallow"):
        p = parse_pipeline(desc)
        p.run(timeout=240)


def test_device_crop_matches_ops_reference():
    """The element cascade (detect -> device crop -> landmark through the
    executor) computes exactly what the underlying ops compute when
    invoked directly — the pipeline adds plumbing, not numerics."""
    import jax
    import jax.numpy as jnp

    from nnstreamer_tpu.elements.sources import VideoTestSrc
    from nnstreamer_tpu.models import zoo
    from nnstreamer_tpu.ops.image import crop_and_resize

    p = parse_pipeline(DEVICE_COMPOSITE.format(n=1))
    p.run(timeout=240)
    sink = next(e for e in p.elements if isinstance(e, TensorSink))
    elem_lm = np.asarray(sink.frames[0].tensors[0])  # [16, 136]

    src = VideoTestSrc(width=128, height=128, **{"num-frames": 1})
    src.start()
    img = np.asarray(src.generate().tensors[0])[None]
    det = zoo.get(
        "face_detect", output="regions", threshold="0.0",
        frame_size="128:128",
    )
    regions = jax.jit(det.fn)(jnp.asarray(img)).astype(jnp.float32)
    xyxy = jnp.concatenate(
        [regions[:, :2], regions[:, :2] + regions[:, 2:4]], axis=-1
    )
    crops = crop_and_resize(jnp.asarray(img[0], jnp.float32), xyxy, 112, 112)
    crops_u8 = jnp.clip(jnp.round(crops), 0, 255).astype(jnp.uint8)
    lmk = zoo.get("face_landmark", batch="16")
    want = np.asarray(jax.jit(lmk.fn)(crops_u8))
    # separately-jitted programs may fuse float math differently; the
    # tolerance covers compiler reassociation, nothing else
    np.testing.assert_allclose(elem_lm, want, atol=1e-4)
