"""Composite face→crop→landmark pipeline, end-to-end across devices.

BASELINE config #5: face detector on one chip feeds bounding regions to
tensor_crop; crops stream to a landmark model on a second chip. Reference
building blocks: gsttensor_crop.c + tensordec-boundingbox.c composition and
the query-offload examples. Here the stages are pinned to different devices
of the virtual 8-CPU mesh (custom="device:N") and the hop rides device
transfer, not host TCP.
"""

import numpy as np
import pytest

from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.pipeline.parse import parse_pipeline

COMPOSITE = (
    "videotestsrc pattern=gradient num-frames={n} width=128 height=128 ! "
    "tensor_converter ! tee name=t "
    "t. ! queue ! tensor_filter framework=jax model=zoo:face_detect "
    'custom="output:regions,threshold:0.0,frame_size:128:128{det_dev}" ! '
    "crop.sink_1 "
    "t. ! queue ! crop.sink_0 "
    "tensor_crop name=crop ! "
    "tensor_filter framework=jax model=zoo:face_landmark "
    'custom="{lmk_dev}" invoke-dynamic=true input-combination=0 ! '
    "tensor_sink name=out"
)


def _run_composite(n=2, det_dev=",device:0", lmk_dev="device:1"):
    p = parse_pipeline(COMPOSITE.format(n=n, det_dev=det_dev, lmk_dev=lmk_dev))
    p.run(timeout=240)
    sink = next(e for e in p.elements if isinstance(e, TensorSink))
    return [np.asarray(f.tensors[0]) for f in sink.frames]


def test_composite_multichip_e2e():
    outs = _run_composite()
    assert len(outs) == 2
    for lm in outs:
        assert lm.shape == (1, 136)
        assert np.all(np.isfinite(lm))
        assert np.all(lm >= 0) and np.all(lm <= 1)


def test_composite_deterministic_golden():
    """Same pipeline, two fresh runs → bit-identical landmark streams (the
    SSAT golden-compare property, held in-process)."""
    a = _run_composite()
    b = _run_composite()
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_composite_device_pinning_matches_unpinned():
    """Placement is a scheduling choice, not a numeric one."""
    pinned = _run_composite()
    unpinned = _run_composite(det_dev="", lmk_dev="")
    for x, y in zip(pinned, unpinned):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)
