"""Multi-host (DCN analogue) loopback: TWO REAL PROCESSES join via
jax.distributed, build one global mesh, and run a psum across process
boundaries — the single-machine stand-in for a pod slice (SURVEY.md §5.8;
the reference's equivalent is its multi-process query/edge loopback
tests). CPU backend, 2 virtual devices per process → 4 global."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from nnstreamer_tpu.parallel import multihost
from nnstreamer_tpu.parallel.mesh import make_mesh

pid = int(sys.argv[1])
multihost.initialize(
    coordinator_address={coord!r}, num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, len(jax.devices())
assert multihost.is_primary() == (pid == 0)

mesh = make_mesh(4, axes=("dp",))
sh = NamedSharding(mesh, P("dp"))

# global array: each process contributes its local shard
global_shape = (8, 4)
local = np.arange(8 * 4, dtype=np.float32).reshape(global_shape)
arrs = [
    jax.device_put(local[idx], d)
    for d, idx in sh.addressable_devices_indices_map(global_shape).items()
]
x = jax.make_array_from_single_device_arrays(global_shape, sh, arrs)

@jax.jit
def total(v):
    return jnp.sum(v)

# the reduction crosses the process boundary (devices live on 2 procs)
t = total(x)
expected = float(np.arange(32, dtype=np.float32).sum())
assert float(t) == expected, (float(t), expected)
print(f"proc{{pid}} ok", flush=True)
multihost.shutdown()
"""


def test_two_process_mesh_psum(tmp_path):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO, coord=coord))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=180)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"proc{i} failed:\n{err[-800:]}"
        assert f"proc{i} ok" in out


def _spawn_phase(phase, coord, workdir, nprocs=2, dpp=2, timeout=300):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "nnstreamer_tpu.parallel._multihost_worker",
             phase, str(i), str(nprocs), coord, workdir, str(dpp)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO,
        )
        for i in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"{phase} proc{i} failed:\n{err[-1200:]}"
        assert f"proc{i} {phase} ok" in out, out
    return outs


def _free_coord():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return coord


def test_checkpoint_resume_across_host_restart(tmp_path):
    """The pod-restart drill (SURVEY §5.4 across §5.8): two processes
    train one sharded step and checkpoint from ALL hosts; a brand-new
    process set restores the state straight onto the mesh shardings,
    reproduces the recorded eval loss, and keeps training."""
    workdir = str(tmp_path)
    _spawn_phase("fresh", _free_coord(), workdir)
    # the simulated restart: completely new processes + new coordinator
    _spawn_phase("resume", _free_coord(), workdir)
