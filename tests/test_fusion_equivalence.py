"""Fused-vs-unfused pipeline equivalence (NNS_NO_FUSE oracle).

The planner fuses consecutive traceable elements into one XLA program
(pipeline/graph.py compile_plan); NNS_NO_FUSE=1 keeps every element its
own program — the reference-faithful per-element mode. The two
executions compute the same function: integer results are byte-equal;
float results may differ by a few ULPs (XLA contracts a*b+c into FMA
inside one program — compiler-legal rounding, the standard XLA
semantics), so floats compare at a tight few-ULP tolerance. Random
chains fuzz the invariant.
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.parse import parse_pipeline


def _run(desc, no_fuse):
    from nnstreamer_tpu.elements.sink import TensorSink

    old = os.environ.get("NNS_NO_FUSE")
    os.environ["NNS_NO_FUSE"] = "1" if no_fuse else "0"
    try:
        ex = parse_pipeline(desc).run(timeout=300)
    finally:
        if old is None:
            os.environ.pop("NNS_NO_FUSE", None)
        else:
            os.environ["NNS_NO_FUSE"] = old
    sink = next(
        n.elem for n in ex.nodes
        if isinstance(getattr(n, "elem", None), TensorSink)
    )
    n_segs = sum(
        1 for n in ex.nodes if type(n).__name__ == "FusedNode"
    )
    return [
        [np.asarray(t) for t in f.tensors] for f in sink.frames
    ], n_segs


def _assert_equal(a, b):
    assert len(a) == len(b)
    for fa, fb in zip(a, b):
        assert len(fa) == len(fb)
        for ta, tb in zip(fa, fb):
            if np.issubdtype(ta.dtype, np.integer):
                np.testing.assert_array_equal(ta, tb)
            else:
                # FMA contraction inside the fused program: a few ULPs
                # of compiler-legal rounding, nothing more (float32
                # eps ≈ 1.2e-7; atol covers contraction at magnitudes
                # the uint8-derived pipelines produce)
                np.testing.assert_allclose(ta, tb, rtol=1e-6, atol=1e-6)


def test_no_fuse_splits_segments_and_matches():
    """The flagship chain: fused runs as ONE program, unfused as one
    per element — outputs identical."""
    desc = (
        "videotestsrc pattern=gradient device=true num-frames=3 "
        "width=32 height=32 ! tensor_converter ! "
        "tensor_transform mode=typecast option=float32 ! "
        "tensor_filter framework=scaler custom=factor:0.5 ! "
        "tensor_sink"
    )
    fused, n_f = _run(desc, False)
    unfused, n_u = _run(desc, True)
    assert n_u > n_f  # the knob actually split the segment
    _assert_equal(fused, unfused)


@pytest.mark.parametrize("seed", list(range(4)))
def test_random_chain_fusion_equivalence(seed):
    """Random transform chains: whatever the element sequence, fusion
    is a schedule — fused and per-element outputs are byte-equal."""
    rng = np.random.default_rng(seed)
    stages = []
    for _ in range(int(rng.integers(1, 5))):
        kind = int(rng.integers(0, 4))
        if kind == 0:
            c = round(float(rng.uniform(0.5, 3.0)), 2)
            stages.append(
                f"tensor_transform mode=arithmetic option=add:{c}"
            )
        elif kind == 1:
            c = round(float(rng.uniform(0.25, 2.0)), 2)
            stages.append(
                f"tensor_transform mode=arithmetic option=mul:{c}"
            )
        elif kind == 2:
            stages.append("tensor_transform mode=typecast option=float32")
        else:
            lo, hi = sorted(
                round(float(x), 1) for x in rng.uniform(0, 200, 2)
            )
            stages.append(
                f"tensor_transform mode=clamp option={lo}:{hi}"
            )
    mid = " ! ".join(stages)
    desc = (
        f"videotestsrc pattern=gradient device="
        f"{'true' if rng.integers(0, 2) else 'false'} num-frames=2 "
        f"width=16 height=16 ! tensor_converter ! {mid} ! "
        "tensor_filter framework=scaler custom=factor:0.5 ! tensor_sink"
    )
    fused, _ = _run(desc, False)
    unfused, _ = _run(desc, True)
    _assert_equal(fused, unfused)
