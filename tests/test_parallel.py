"""Parallel-layer tests on the virtual 8-device CPU mesh: ring attention,
Ulysses, MoE expert parallelism, pipeline parallelism, dp×sp×ep LM step.

The reference has no collective backend (SURVEY.md §2.6); these validate
the genuinely-new TPU-native scaling layer. Numeric checks compare every
sharded path against its single-device dense reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.parallel import lm, moe
from nnstreamer_tpu.parallel import pipeline_parallel as pp
from nnstreamer_tpu.parallel import ring_attention as ra
from nnstreamer_tpu.parallel import ulysses
from nnstreamer_tpu.parallel.mesh import make_mesh


def _qkv(rng, b=2, t=64, h=8, d=16):
    return tuple(
        jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32) for _ in range(3)
    )


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        mesh = make_mesh(8, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(0))
        out_ring = ra.make_ring_attention(mesh, "sp", causal=causal)(q, k, v)
        out_dense = ra.dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out_ring, out_dense, atol=2e-5)

    def test_grad_flows(self):
        # the ring loop is a scan over ppermute — reverse-differentiable
        mesh = make_mesh(4, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(1), t=16, h=2, d=8)
        ring = ra.make_ring_attention(mesh, "sp", causal=True)

        g = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2))(q, k, v)
        gd = jax.grad(
            lambda q, k, v: jnp.sum(ra.dense_attention(q, k, v, causal=True) ** 2)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gd), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_kv_chunked_matches_dense(self, causal):
        """kv_chunk bounds the in-shard score tensor; numerics must match
        the unchunked ring and the dense reference."""
        mesh = make_mesh(8, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(3))
        out_c = ra.make_ring_attention(mesh, "sp", causal=causal, kv_chunk=2)(
            q, k, v
        )
        out_dense = ra.dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out_c, out_dense, atol=2e-5)

    def test_kv_chunk_must_divide(self):
        with pytest.raises(ValueError, match="chunk"):
            b = jnp.zeros((1, 6, 1, 4), jnp.float32)
            ra._online_block_chunked(
                b, b, b, jnp.ones((6, 6), bool),
                jnp.full((1, 1, 6), ra.NEG_INF), jnp.zeros((1, 1, 6)),
                jnp.zeros((1, 6, 1, 4)), 0.5, chunk=4,
            )

    def test_kv_chunked_grad_matches_dense(self):
        """Backward through the chunked nested scan must equal dense."""
        mesh = make_mesh(4, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(5), t=16, h=2, d=8)
        ring = ra.make_ring_attention(mesh, "sp", causal=True, kv_chunk=2)
        g = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2))(q, k, v)
        gd = jax.grad(
            lambda q, k, v: jnp.sum(ra.dense_attention(q, k, v, causal=True) ** 2)
        )(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gd), atol=2e-5)

    def test_kv_chunk_rejects_nonpositive(self):
        mesh = make_mesh(8, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(4), t=16, h=1, d=4)
        with pytest.raises(ValueError, match="positive divisor"):
            ra.make_ring_attention(mesh, "sp", kv_chunk=0)(q, k, v)

    def test_kv_chunk_rejected_for_ulysses(self):
        mesh = make_mesh(8, axes=("dp", "sp"), shape=(2, 4))
        with pytest.raises(ValueError, match="ring"):
            lm._make_attn_fn(mesh, "ulysses", "dp", "sp", kv_chunk=4)

    def test_fully_masked_rows_are_zero(self):
        # row 0 of a causal block attends only to itself; a remote-only
        # shard sees fully-masked blocks and must contribute exact zeros
        mesh = make_mesh(8, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(2), t=8, h=1, d=4)
        out = ra.make_ring_attention(mesh, "sp", causal=True)(q, k, v)
        assert np.all(np.isfinite(np.asarray(out)))


class TestUlysses:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_dense(self, causal):
        mesh = make_mesh(8, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(3))  # 8 heads % 8 devices
        out_u = ulysses.make_ulysses_attention(mesh, "sp", causal=causal)(q, k, v)
        out_d = ra.dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(out_u, out_d, atol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = make_mesh(8, axes=("sp",))
        q, k, v = _qkv(np.random.default_rng(4), h=6)
        with pytest.raises(Exception):
            ulysses.make_ulysses_attention(mesh, "sp")(q, k, v)


class TestMoE:
    def test_ep_matches_dense(self):
        rng = np.random.default_rng(5)
        mp = moe.init_moe_params(
            jax.random.PRNGKey(1), d_model=32, d_ff=64, n_experts=8, n_layers=1
        )
        mp0 = jax.tree.map(lambda a: a[0], mp)
        x = jnp.asarray(rng.standard_normal((2, 16, 32)), jnp.float32)
        y_dense = moe.moe_ffn_dense(x, mp0, top_k=2)
        mesh = make_mesh(8, axes=("ep",))
        from nnstreamer_tpu.parallel.mesh import shard_map

        f = jax.jit(
            shard_map(
                functools.partial(moe.moe_ffn_local, axis_name="ep", top_k=2),
                mesh=mesh,
                in_specs=(P(), {"gate": P(), "w_in": P("ep"), "w_out": P("ep")}),
                out_specs=P(),
                check_vma=False,
            )
        )
        y_ep = f(x, mp0)
        np.testing.assert_allclose(y_ep, y_dense, atol=1e-5)

    def test_topk_gate_sparsity(self):
        mp = moe.init_moe_params(
            jax.random.PRNGKey(2), d_model=8, d_ff=16, n_experts=4, n_layers=1
        )
        x = jnp.asarray(np.random.default_rng(6).standard_normal((1, 5, 8)), jnp.float32)
        probs = np.asarray(moe.gate_probs(x, mp["gate"][0], top_k=2))
        nonzero = (probs > 0).sum(axis=-1)
        assert np.all(nonzero == 2)
        np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-6)


class TestPipelineParallel:
    def test_matches_sequential(self):
        mesh = make_mesh(8, axes=("pp",))
        rng = np.random.default_rng(7)
        stack = tfm.init_params(
            jax.random.PRNGKey(3), vocab=32, d_model=32, n_heads=4, n_layers=8
        )["blocks"]
        xs = jnp.asarray(rng.standard_normal((16, 12, 32)), jnp.float32)
        positions = jnp.arange(12)

        def stage(x_mb, sp_):
            return tfm.apply_layers(sp_, x_mb, 4, positions)

        y_seq = tfm.apply_layers(stack, xs, 4, positions)
        y_pp = pp.make_pipeline_forward(mesh, stage, n_microbatches=4)(stack, xs)
        np.testing.assert_allclose(y_pp, y_seq, atol=2e-4)

    def test_rejects_ragged_microbatch(self):
        mesh = make_mesh(4, axes=("pp",))
        stack = tfm.init_params(
            jax.random.PRNGKey(4), vocab=16, d_model=16, n_heads=2, n_layers=4
        )["blocks"]
        xs = jnp.zeros((10, 4, 16), jnp.float32)
        with pytest.raises(Exception):
            pp.make_pipeline_forward(
                mesh, lambda x, p: tfm.apply_layers(p, x, 2, jnp.arange(4)),
                n_microbatches=3,
            )(stack, xs)


class TestLMTrainStep:
    def test_dp_sp_ep_step_decreases_loss(self):
        mesh = make_mesh(8, axes=("dp", "sp", "ep"), shape=(2, 2, 2))
        params = lm.init_lm_params(
            jax.random.PRNGKey(0), vocab=64, d_model=32, n_heads=4,
            n_layers=2, n_experts=4,
        )
        step, params = lm.make_lm_train_step(mesh, params, n_heads=4, ep_axis="ep")
        toks = jnp.asarray(
            np.random.default_rng(8).integers(0, 64, (4, 17)), jnp.int32
        )
        params, loss1 = step(params, toks)
        params, loss2 = step(params, toks)
        assert np.isfinite(float(loss1)) and float(loss2) < float(loss1)

    def test_sequence_parallel_forward_matches_dense(self):
        mesh = make_mesh(8, axes=("dp", "sp", "ep"), shape=(2, 2, 2))
        params = lm.init_lm_params(
            jax.random.PRNGKey(1), vocab=64, d_model=32, n_heads=4, n_layers=2
        )
        attn = lm._make_attn_fn(mesh, "ring", "dp", "sp")
        x = jnp.asarray(np.random.default_rng(9).integers(0, 64, (4, 16)), jnp.int32)
        dense = tfm.apply(params, x, 4)
        ring = jax.jit(lambda t: tfm.apply(params, t, 4, attn_fn=attn))(x)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=1e-4)

    def test_kv_chunked_train_step_matches_unchunked(self):
        """kv_chunk is a memory knob, not a numerics knob: the sequence-
        parallel forward with chunked in-shard attention equals dense."""
        mesh = make_mesh(8, axes=("dp", "sp", "ep"), shape=(2, 2, 2))
        params = lm.init_lm_params(
            jax.random.PRNGKey(3), vocab=64, d_model=32, n_heads=4, n_layers=2
        )
        attn = lm._make_attn_fn(mesh, "ring", "dp", "sp", kv_chunk=4)
        x = jnp.asarray(
            np.random.default_rng(11).integers(0, 64, (4, 16)), jnp.int32
        )
        dense = tfm.apply(params, x, 4)
        ring = jax.jit(lambda t: tfm.apply(params, t, 4, attn_fn=attn))(x)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=1e-4)
        step, sparams = lm.make_lm_train_step(
            mesh, params, n_heads=4, kv_chunk=4
        )
        toks = jnp.asarray(
            np.random.default_rng(12).integers(0, 64, (4, 17)), jnp.int32
        )
        _, loss = step(sparams, toks)
        assert np.isfinite(float(loss))

    def test_ulysses_attn_kind(self):
        mesh = make_mesh(8, axes=("dp", "sp"), shape=(2, 4))
        params = lm.init_lm_params(
            jax.random.PRNGKey(2), vocab=32, d_model=32, n_heads=4, n_layers=1
        )
        step, params = lm.make_lm_train_step(mesh, params, n_heads=4, attn="ulysses")
        toks = jnp.asarray(np.random.default_rng(10).integers(0, 32, (2, 17)), jnp.int32)
        _, loss = step(params, toks)
        assert np.isfinite(float(loss))


def test_zoo_transformer_lm():
    from nnstreamer_tpu.models import zoo

    m = zoo.get("transformer_lm", vocab="64", d_model="32", n_heads="4",
                n_layers="1", seqlen="8")
    out = jax.eval_shape(
        m.fn, jax.ShapeDtypeStruct((1, 8), jnp.int32)
    )
    assert out.shape == (1, 8, 64)
