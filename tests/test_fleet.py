"""Edge fleet resilience (docs/edge-serving.md "Running a fleet").

Tier-1 block (fast, deterministic — fake clocks where timing matters):
the FleetEndpoints selector (rotation, consecutive-failure ejection,
backoff re-probe, draining), frame_id reply dedup, hedging determinism,
client failover against live endpoint death, graceful drain (NACK path,
drain flush, rolling restart with zero lost requests), the re-resolve/
``unresolvable`` reconnect bugfix, the NNS-W119 lint both ways, and the
shm transport coverage ROADMAP calls unloved (ring wraparound through
the query server pair, reconnect after server restart).

The standing fleet chaos soak — 3 servers × 6 clients at ~2× admission
capacity under ChaosTransport faults while the harness kills one
server, drains another, and restarts both — is marked ``slow``.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.edge.fleet import (
    FleetEndpoints,
    HedgeTimer,
    ReplyDeduper,
    RttWindow,
    parse_hosts,
)
from nnstreamer_tpu.edge.query import (
    TensorQueryClient,
    TensorQueryServerSink,
    TensorQueryServerSrc,
    request_drain,
)
from nnstreamer_tpu.edge.serialize import (
    Ctrl,
    Nack,
    decode_message,
    encode_ctrl,
    encode_message,
)
from nnstreamer_tpu.edge.transport import PyTransport, UnresolvableError
from nnstreamer_tpu.elements.base import ElementError
from nnstreamer_tpu.tensors.frame import Frame


def _frame(val: float = 0.0, **meta) -> Frame:
    return Frame((np.full(4, val, np.float32),), meta=meta)


class FakeClock:
    def __init__(self, t: float = 1000.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class _EchoServer:
    """serversrc/serversink pair with a background echo loop (×2)."""

    def __init__(self, name: str, srv_id: str, port: int = 0, **props):
        props.setdefault("max-inflight", 8)
        self.src = TensorQueryServerSrc(name, port=port, id=srv_id, **props)
        self.sink = TensorQueryServerSink(f"{name}k", id=srv_id)
        self.src.start()
        self.port = self.src.bound_port
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            f = self.src.generate()
            if f is None:
                continue
            self.sink.render(
                f.with_tensors([np.asarray(t) * 2.0 for t in f.tensors])
            )

    def stop(self) -> None:
        self._stop.set()
        self._t.join(timeout=2)
        self.src.stop()


# ------------------------------------------------------------- selector units
def test_parse_hosts():
    assert parse_hosts("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_hosts(" h:5001 , ") == [("h", 5001)]
    for bad in ("", "noport", "h:", "h:0", "h:x", "a:1,a:1"):
        with pytest.raises(ValueError):
            parse_hosts(bad)


def test_selector_round_robin_and_ejection():
    clk = FakeClock()
    f = FleetEndpoints(
        [("a", 1), ("b", 2), ("c", 3)], eject_after=2,
        probe_backoff_ms=100.0, clock=clk,
    )
    assert [e.addr for e in f.plan()][:1] == ["a:1"]
    assert [e.addr for e in f.plan()][:1] == ["b:2"]  # rotation advanced
    a = f.endpoints[0]
    f.record_fail(a)
    assert a.healthy  # one failure is not ejection
    f.record_fail(a)
    assert not a.healthy and a.state() == "ejected"
    # benched: not in any plan until the backoff elapses
    for _ in range(4):
        assert a not in f.plan()
    clk.advance(0.2)  # > 100 ms jittered backoff
    assert f.plan()[0] is a  # prepended as the re-probe
    f.record_ok(a)
    assert a.healthy and a.consec_fails == 0


def test_selector_backoff_doubles_and_all_benched():
    clk = FakeClock()
    f = FleetEndpoints(
        [("a", 1)], eject_after=1, probe_backoff_ms=100.0, clock=clk,
    )
    a = f.endpoints[0]
    f.record_fail(a)
    first = a.retry_at - clk.t
    assert 0.05 <= first <= 0.1  # jitter in [0.5, 1.0]x of 100 ms
    assert f.plan() == []  # nothing healthy, nothing due
    assert f.next_retry_in() > 0
    clk.advance(first + 0.001)
    assert f.plan() == [a]  # due: every benched endpoint gets a shot
    f.record_fail(a)  # probe failed: backoff doubled
    second = a.retry_at - clk.t
    assert second > first * 1.2


def test_selector_draining_benches_for_hint():
    clk = FakeClock()
    f = FleetEndpoints([("a", 1), ("b", 2)], clock=clk)
    a, b = f.endpoints
    f.mark_draining(a, 500.0)
    assert a.state() == "draining"
    assert all(p is b for p in f.plan())  # only b while a drains
    clk.advance(0.6)
    assert a in f.plan()  # hint elapsed: re-probe allowed
    f.record_ok(a)
    assert a.state() == "healthy"


def test_reply_deduper_bounded():
    d = ReplyDeduper(capacity=16)
    assert d.claim("x") and not d.claim("x")
    assert d.duplicates == 1
    for i in range(40):
        d.claim(i)
    assert not d.seen("x")  # evicted by the FIFO bound
    assert d.seen(39)


def test_hedge_timer_deterministic():
    clk = FakeClock()
    h = HedgeTimer(80.0, clock=clk)
    h.arm()
    assert not h.due()
    clk.advance(0.079)
    assert not h.due()
    clk.advance(0.002)
    assert h.due()
    h.fire()
    assert not h.due()  # one hedge per request
    # off and adaptive modes
    off = HedgeTimer(0.0, clock=clk)
    off.arm()
    clk.advance(10.0)
    assert not off.due()
    rtts = RttWindow()
    auto = HedgeTimer(-1.0, clock=clk, rtts=rtts, adaptive_floor_ms=50.0)
    assert auto.threshold_s() == 0.05  # floor until enough samples
    for _ in range(20):
        rtts.record(0.2)
    assert auto.threshold_s() == pytest.approx(0.2)


# ------------------------------------------------------- client fleet paths
def test_fleet_round_robin_and_failover_on_death():
    a = _EchoServer("fl-a", "fl1a")
    b = _EchoServer("fl-b", "fl1b")
    client = TensorQueryClient(
        "fl-c1",
        **{"hosts": f"127.0.0.1:{a.port},127.0.0.1:{b.port}",
           "timeout": 3, "retry-max": 4, "retry-backoff-ms": 5},
    )
    try:
        client.start()
        for i in range(4):
            r = client.process(_frame(float(i)))
            assert float(np.asarray(r.tensors[0])[0]) == 2.0 * i
        st = client.fleet_stats()
        assert all(e["served"] >= 1 for e in st["endpoints"].values())
        a.stop()  # endpoint death mid-fleet
        for i in range(8):  # enough rotations for 3 consecutive fails
            r = client.process(_frame(float(i)))
            assert float(np.asarray(r.tensors[0])[0]) == 2.0 * i
        st = client.fleet_stats()
        assert st["failovers"] >= 1
        assert st["duplicate_replies"] == 0
        states = {k: v["state"] for k, v in st["endpoints"].items()}
        assert states[f"127.0.0.1:{a.port}"] == "ejected"
    finally:
        client.stop()
        b.stop()


def test_fleet_reprobe_readmits_restarted_server():
    a = _EchoServer("fl2-a", "fl2a")
    b = _EchoServer("fl2-b", "fl2b")
    port_a = a.port
    client = TensorQueryClient(
        "fl-c2",
        **{"hosts": f"127.0.0.1:{port_a},127.0.0.1:{b.port}",
           "timeout": 3, "retry-max": 4, "retry-backoff-ms": 5},
    )
    a2 = None
    try:
        client.start()
        client.process(_frame(1.0))
        a.stop()
        for _ in range(4):  # ejects a
            client.process(_frame(1.0))
        a2 = _EchoServer("fl2-a2", "fl2a2", port=port_a)  # rolling restart
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline:
            client.process(_frame(1.0))
            st = client.fleet_stats()["endpoints"][f"127.0.0.1:{port_a}"]
            if st["state"] == "healthy":
                break
            time.sleep(0.02)
        assert st["state"] == "healthy", st  # re-probe re-admitted it
    finally:
        client.stop()
        b.stop()
        if a2 is not None:
            a2.stop()


def test_hedged_request_first_reply_wins_and_dedup():
    """Server A strags (replies after 250 ms), B echoes instantly: the
    hedge wins on B, and A's late duplicate reply — arriving during a
    LATER request's wait — is dropped by the frame_id dedup, never
    delivered as the wrong answer."""
    def slow_server(tr, delay):
        def loop():
            while True:
                got = tr.recv(timeout=0.1)
                if got is None:
                    continue
                cid, payload = got
                if not payload:
                    return
                f = decode_message(payload)
                if not isinstance(f, Frame):
                    continue

                def reply(cid=cid, f=f):
                    time.sleep(delay)
                    try:
                        tr.send(cid, encode_message(f.with_tensors(
                            [np.asarray(t) * 2.0 for t in f.tensors]
                        )))
                    except Exception:  # noqa: BLE001 — test teardown
                        pass

                threading.Thread(target=reply, daemon=True).start()
        threading.Thread(target=loop, daemon=True).start()

    A = PyTransport()
    B = PyTransport()
    pa, pb = A.listen("127.0.0.1", 0), B.listen("127.0.0.1", 0)
    slow_server(A, 0.25)
    slow_server(B, 0.0)
    client = TensorQueryClient(
        "fl-c3",
        **{"hosts": f"127.0.0.1:{pa},127.0.0.1:{pb}",
           "timeout": 3, "hedge-after-ms": 40},
    )
    try:
        client.start()
        vals = []
        for i in range(3):
            r = client.process(_frame(float(i + 1)))
            vals.append(float(np.asarray(r.tensors[0])[0]))
        time.sleep(0.35)  # let every late A reply land
        client.process(_frame(9.0))
        assert vals == [2.0, 4.0, 6.0]  # every reply matched ITS request
        st = client.fleet_stats()
        assert st["hedges"] >= 1, st
        assert st["duplicate_replies"] >= 1, st
    finally:
        client.stop()
        A.close()
        B.close()


# ------------------------------------------------------------- graceful drain
def test_drain_nacks_new_finishes_inflight():
    """drain(): already-admitted requests complete (zero loss), new
    submits NACK `draining`, the readiness flag flips, and drained()
    latches once the reply path catches up."""
    src = TensorQueryServerSrc(
        "dr-src", port=0, id="dr1", **{"max-inflight": 4}
    )
    sink = TensorQueryServerSink("dr-sink", id="dr1")
    src.start()
    raw = PyTransport()
    try:
        assert src.state == "ready"
        assert src.admission_stats()["readiness"] == "ready"
        raw.connect("127.0.0.1", src.bound_port)
        raw.send(0, encode_message(_frame(3.0, frame_id="req-1")))
        time.sleep(0.15)
        admitted = src.generate()  # in flight now
        assert admitted is not None
        src.drain()
        assert src.state == "draining"
        assert not src.drained()  # one admitted request still in flight
        # a NEW submit is refused with the draining reason + hint
        raw.send(0, encode_message(_frame(4.0, frame_id="req-2")))
        time.sleep(0.15)
        assert src.generate() is None
        nack = decode_message(raw.recv(timeout=2)[1])
        assert isinstance(nack, Nack) and nack.reason == "draining"
        assert nack.retry_after_ms > 0 and nack.frame_id == "req-2"
        # the in-flight request still completes: zero accepted loss
        sink.render(admitted)
        got = decode_message(raw.recv(timeout=2)[1])
        assert isinstance(got, Frame)
        assert got.meta.get("frame_id") == "req-1"
        assert src.drained()
        stats = src.admission_stats()
        assert stats["readiness"] == "draining"
        assert stats["drain_nacked"] == 1
        assert stats["inflight"] == 0
    finally:
        raw.close()
        src.stop()
    assert src.state == "dead"


def test_drain_flush_queued_releases_budget():
    """drain(flush_queued=True): the queued-but-unserved admitted
    backlog is NACKed `draining` and its budget released — the ledger
    (admitted == released + in-flight) stays exact."""
    src = TensorQueryServerSrc(
        "dr2-src", port=0, id="dr2", **{"max-inflight": 8}
    )
    sink = TensorQueryServerSink("dr2-sink", id="dr2")
    src.start()
    raw = PyTransport()
    try:
        raw.connect("127.0.0.1", src.bound_port)
        for i in range(3):
            raw.send(0, encode_message(_frame(float(i), frame_id=f"q{i}")))
        time.sleep(0.2)
        executing = src.generate()  # admits all 3, serves ONE
        assert executing is not None
        src.drain(flush_queued=True)
        reasons = []
        for _ in range(2):  # the two queued requests re-route NOW
            msg = decode_message(raw.recv(timeout=2)[1])
            assert isinstance(msg, Nack)
            reasons.append(msg.reason)
        assert reasons == ["draining", "draining"]
        stats = src.admission_stats()
        assert stats["inflight"] == 1  # only the executing request
        assert not src.drained()
        sink.render(executing)
        assert src.drained()
    finally:
        raw.close()
        src.stop()


def test_drain_control_message_over_the_wire():
    """request_drain() flips a live server to draining without touching
    the process — the rolling-restart trigger an operator (or the soak
    harness) uses."""
    src = TensorQueryServerSrc("dr3-src", port=0, id="dr3")
    src.start()
    try:
        assert isinstance(decode_message(encode_ctrl("drain")), Ctrl)
        request_drain("127.0.0.1", src.bound_port)
        deadline = time.monotonic() + 2
        while src.state != "draining" and time.monotonic() < deadline:
            src.generate()
            time.sleep(0.01)
        assert src.state == "draining"
        # legacy (no admission bounds) path still NACKs new submits
        raw = PyTransport()
        try:
            raw.connect("127.0.0.1", src.bound_port)
            raw.send(0, encode_message(_frame(1.0)))
            got = None
            deadline = time.monotonic() + 3
            while got is None and time.monotonic() < deadline:
                # the queue also carries the drain connection's close
                # event; keep pumping until the NACK lands
                assert src.generate() is None
                got = raw.recv(timeout=0.1)
            assert got is not None
            nack = decode_message(got[1])
            assert isinstance(nack, Nack) and nack.reason == "draining"
        finally:
            raw.close()
    finally:
        src.stop()


def test_rolling_restart_loses_zero_requests():
    """The acceptance pin: drain → restart a fleet server under a live
    request stream; every request completes (failover rides the
    draining NACKs), none lost, and the restarted server rejoins."""
    a = _EchoServer("rr-a", "rr1a")
    b = _EchoServer("rr-b", "rr1b")
    port_a = a.port
    client = TensorQueryClient(
        "rr-c",
        **{"hosts": f"127.0.0.1:{port_a},127.0.0.1:{b.port}",
           "timeout": 3, "retry-max": 6, "retry-backoff-ms": 5},
    )
    a2 = None
    try:
        client.start()
        results = []
        for i in range(4):
            results.append(client.process(_frame(float(i))))
        a.src.drain()          # rolling restart step 1: drain
        deadline = time.monotonic() + 3
        while not a.src.drained() and time.monotonic() < deadline:
            time.sleep(0.01)   # the last reply's budget release races
        assert a.src.drained()
        for i in range(4, 8):  # new submits re-route via draining NACKs
            results.append(client.process(_frame(float(i))))
        a.stop()               # step 2: stop
        a2 = _EchoServer("rr-a2", "rr2a", port=port_a)  # step 3: restart
        for i in range(8, 12):
            results.append(client.process(_frame(float(i))))
        # ZERO lost: every request got its own reply, in order
        assert [float(np.asarray(r.tensors[0])[0]) for r in results] == [
            2.0 * i for i in range(12)
        ]
        assert client.fleet_stats()["duplicate_replies"] == 0
    finally:
        client.stop()
        b.stop()
        if a2 is not None:
            a2.stop()


# ------------------------------------------- unresolvable reconnect bugfix
def test_unresolvable_host_fails_fast_with_distinct_reason():
    """A gone hostname must NOT burn the whole retry-max budget: the
    failure is terminal with a distinct `unresolvable` reason on the
    first attempt."""
    client = TensorQueryClient(
        "ur-c",
        **{"dest-host": "nns-no-such-host.invalid", "dest-port": 9,
           "timeout": 1, "retry-max": 50, "retry-backoff-ms": 200},
    )
    t0 = time.monotonic()
    with pytest.raises(ElementError, match="unresolvable"):
        client.start()
    # 50 retries at 200 ms backoff would take >5 s; fail-fast must not
    assert time.monotonic() - t0 < 3.0


def test_fleet_marks_unresolvable_endpoint_and_serves_on():
    b = _EchoServer("ur-b", "ur1b")
    client = TensorQueryClient(
        "ur-c2",
        **{"hosts": f"nns-no-such-host.invalid:9,127.0.0.1:{b.port}",
           "timeout": 3, "retry-max": 2, "retry-backoff-ms": 5},
    )
    try:
        client.start()
        r = client.process(_frame(5.0))
        assert float(np.asarray(r.tensors[0])[0]) == 10.0
        eps = client.fleet_stats()["endpoints"]
        assert eps["nns-no-such-host.invalid:9"]["unresolvable"]
        assert eps["nns-no-such-host.invalid:9"]["state"] == "ejected"
    finally:
        client.stop()
        b.stop()


def test_resolve_target_unresolvable():
    from nnstreamer_tpu.edge.transport import resolve_target

    assert resolve_target("127.0.0.1", 80) == ("127.0.0.1", 80)
    with pytest.raises(UnresolvableError):
        resolve_target("nns-no-such-host.invalid", 80)


# ----------------------------------------------------------------- the lint
def test_lint_w119_single_endpoint_no_failover_both_ways():
    from nnstreamer_tpu.analysis.lint import lint

    risky = lint(
        "tensorsrc dimensions=4 num-frames=4 ! "
        "tensor_query_client dest-port=5001 deadline-ms=200 ! tensor_sink"
    )
    assert "NNS-W119" in risky.report.codes
    # any of the three remedies silences it
    for fix in (
        "retry-max=3",
        "hosts=127.0.0.1:5001,127.0.0.1:5002",
    ):
        ok = lint(
            "tensorsrc dimensions=4 num-frames=4 ! "
            f"tensor_query_client dest-port=5001 deadline-ms=200 {fix} ! "
            "tensor_sink"
        )
        assert "NNS-W119" not in ok.report.codes, fix
    # no deadline stamped → no SLO promise → no warning
    plain = lint(
        "tensorsrc dimensions=4 num-frames=4 ! "
        "tensor_query_client dest-port=5001 ! tensor_sink"
    )
    assert "NNS-W119" not in plain.report.codes


def test_lint_w126_llm_drain_loses_generations_both_ways():
    from nnstreamer_tpu.analysis.lint import lint

    base = (
        "tensor_query_serversrc id=w6 port=5097 max-clients=4 "
        "retry-after-ms=25 ! "
        "tensor_llm_serversink id=w6l model=zoo:transformer_lm "
        "kv-layout=paged block-size=16 kv-blocks=64{extra}"
    )
    risky = lint(base.format(extra=""))
    assert "NNS-W126" in risky.report.codes
    # any of the three remedies silences it: a migration peer, a
    # checkpoint dir, or a plane (which refuses migration by design —
    # the drain story is the plane's, not this server's)
    for fix in (
        " migrate-to=127.0.0.1:7001",
        " checkpoint-dir=/var/nns/spans",
        " plane=lp0",
    ):
        ok = lint(base.format(extra=fix))
        assert "NNS-W126" not in ok.report.codes, fix
    # retry-after-ms left at its default → no drain contract tuned →
    # quiet (the docs' plain serving example must not warn)
    plain = lint(base.format(extra="").replace("retry-after-ms=25 ", ""))
    assert "NNS-W126" not in plain.report.codes


# -------------------------------------------------------------- nns-top
def test_nns_top_fleet_view_renders_endpoints_and_readiness():
    """`nns-top --fleet` renders the client's per-endpoint health rows
    (from the executor's `fleet_*` stats keys) plus each server's drain
    readiness footer."""
    from nnstreamer_tpu.obs.nns_top import render_fleet

    snap = {"nodes": {
        "edge-c0": {
            "fleet_endpoints": {
                "10.0.0.1:5001": {
                    "state": "healthy", "score": 1.0, "inflight": 1,
                    "served": 340, "fails": 2, "failovers": 2,
                },
                "10.0.0.2:5001": {
                    "state": "draining", "score": 0.8, "inflight": 0,
                    "served": 120, "fails": 0, "failovers": 1,
                    "unresolvable": False,
                },
            },
            "fleet_healthy": 1, "fleet_failovers": 3,
            "fleet_hedges": 5, "fleet_duplicate_replies": 1,
        },
        "qsrc": {"adm_readiness": "draining", "adm_drain_nacked": 4},
    }}
    out = render_fleet(snap)
    assert "10.0.0.1:5001" in out and "healthy" in out
    assert "draining" in out and "failovers=3" in out
    assert "hedges=5" in out and "dup-replies=1" in out
    assert "server qsrc: draining drain-nacked=4" in out
    empty = render_fleet({"nodes": {}})
    assert "no fleet client" in empty


def test_executor_stats_carry_fleet_rows():
    """A fleet client inside a real pipeline surfaces its endpoint
    health through Executor.stats() (`fleet_*` keys — what the obs
    endpoint and nns-top --fleet read)."""
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    b = _EchoServer("ex-b", "exfl1")
    p = parse_pipeline(
        "tensorsrc name=s dimensions=4 types=float32 num-frames=3 ! "
        f"tensor_query_client name=qc hosts=127.0.0.1:{b.port} "
        "timeout=5 ! tensor_sink name=out"
    )
    try:
        ex = p.run(timeout=30)
        assert not ex.errors, ex.errors
        row = ex.stats()["qc"]
        eps = row["fleet_endpoints"]
        assert eps[f"127.0.0.1:{b.port}"]["served"] == 3
        assert row["fleet_healthy"] == 1
        assert len(p["out"].frames) == 3
    finally:
        b.stop()


# ------------------------------------------------- shm transport (unloved)
def _shm_available() -> bool:
    try:
        from nnstreamer_tpu.edge import shm as _shm

        _shm._get_lib()
        return True
    except Exception:  # noqa: BLE001 — toolchain/sanitizer build absent
        return False


@pytest.mark.skipif(not _shm_available(), reason="no C++ toolchain")
def test_shm_query_pair_ring_wraparound():
    """Many messages much larger than capacity/N through the SHM query
    server pair force repeated ring wrap markers on BOTH rings; order
    and content must survive."""
    import os

    from nnstreamer_tpu.edge.query_transports import (
        ShmClientTransport,
        ShmServerTransport,
    )

    srv = ShmServerTransport(capacity=8 * 1024)
    port = srv.listen("", 42101)
    cli = ShmClientTransport()
    cli.connect("", port)
    msgs = [os.urandom(700) for _ in range(64)]
    errs = []

    def echo():
        try:
            for _ in range(len(msgs)):
                got = srv.recv(timeout=5)
                srv.send(got[0], got[1][::-1])
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errs.append(exc)

    t = threading.Thread(target=echo, daemon=True)
    t.start()
    try:
        for m in msgs:
            cli.send(0, m)
            got = cli.recv(timeout=5)
            assert got is not None and got[1] == m[::-1]
        t.join(timeout=5)
        assert not errs
    finally:
        cli.close()
        srv.close()


@pytest.mark.skipif(not _shm_available(), reason="no C++ toolchain")
def test_shm_server_restart_client_reconnects():
    """ShmServerTransport restart on the same port: the old segments are
    torn down (marked closed + unlinked), a new server claims the names,
    and a reconnecting client resumes request/reply."""
    from nnstreamer_tpu.edge.query_transports import (
        ShmClientTransport,
        ShmServerTransport,
    )

    port = 42111
    srv = ShmServerTransport(capacity=64 * 1024)
    assert srv.listen("", port) == port
    cli = ShmClientTransport()
    cli.connect("", port)
    cli.send(0, b"gen-1")
    got = srv.recv(timeout=5)
    assert got is not None and got == (1, b"gen-1")
    srv.send(1, b"ack-1")
    assert cli.recv(timeout=5)[1] == b"ack-1"
    srv.close()
    # the client sees EOS on the reply ring once the server is gone
    assert cli.recv(timeout=5)[1] == b""
    cli.close()
    # restart: same port must be claimable again (no stale-name wedge)
    srv2 = ShmServerTransport(capacity=64 * 1024)
    assert srv2.listen("", port) == port
    cli2 = ShmClientTransport()
    cli2.connect("", port)
    try:
        cli2.send(0, b"gen-2")
        got = srv2.recv(timeout=5)
        assert got is not None and got[1] == b"gen-2"
        srv2.send(1, b"ack-2")
        assert cli2.recv(timeout=5)[1] == b"ack-2"
    finally:
        cli2.close()
        srv2.close()


# ------------------------------------------------------------- standing soak
@pytest.mark.slow
def test_fleet_chaos_soak_kill_drain_restart(monkeypatch):
    """The standing fleet soak (docs/edge-serving.md "Running a fleet"):
    3 admission-bounded echo servers × 6 fleet clients at ~2× aggregate
    admission capacity, a third of the fleet injecting ChaosTransport
    drops and truncations, while the harness HARD-KILLS one server,
    gracefully DRAINS another, and restarts both. Invariants: every
    request reaches a terminal outcome (reply or terminal NACK — no
    silent timeouts), per-node ``offered == delivered + dropped +
    routed`` latches green under the sanitizer, failover p99 stays
    bounded, and no server leaks threads."""
    monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    def start_server(tag: str, port: int = 0):
        p = parse_pipeline(
            f"tensor_query_serversrc name={tag}-src port={port} id={tag} "
            "max-inflight=4 per-client-inflight=2 retry-after-ms=10 ! "
            "tensor_filter framework=passthrough input=4 "
            "inputtype=float32 ! "
            f"tensor_query_serversink id={tag}"
        )
        ex = p.start()
        return p, ex, p[f"{tag}-src"]

    servers = {}
    execs = []
    for i in range(3):
        p, ex, src = start_server(f"soakf{i}")
        servers[i] = (p, ex, src)
        execs.append(ex)
    ports = {i: servers[i][2].bound_port for i in range(3)}
    hosts = ",".join(f"127.0.0.1:{ports[i]}" for i in range(3))

    n_clients, n_requests = 6, 40
    pace_s = 0.02  # ~2x the 3-server aggregate admission capacity, and
    #                the stream must still be LIVE through the whole
    #                kill/drain/restart choreography below
    outcomes = []
    mu = threading.Lock()

    def run_client(idx: int) -> None:
        props = {
            "hosts": hosts, "timeout": 8, "retry-max": 10,
            "retry-backoff-ms": 10,
        }
        if idx % 3 == 0:  # a third of the fleet injects wire faults
            props["chaos-drop-every-n"] = 7
            props["chaos-truncate-every-n"] = 11
        if idx % 2 == 0:
            props["hedge-after-ms"] = 250
        client = TensorQueryClient(f"soakf-c{idx}", **props)
        client.start()
        try:
            for i in range(n_requests):
                t0 = time.perf_counter()
                try:
                    reply = client.process(_frame(float(i)))
                    assert reply is not None
                    kind = "completed"
                except ElementError as exc:
                    msg = str(exc)
                    if "rejected" in msg or "accepted" in msg:
                        kind = "nacked"
                    else:
                        kind = f"error:{msg[:80]}"
                with mu:
                    outcomes.append((kind, time.perf_counter() - t0))
                time.sleep(pace_s)
        finally:
            with mu:
                outcomes.append(("stats", client.fleet_stats()))
            client.stop()

    threads = [
        threading.Thread(target=run_client, args=(i,), daemon=True)
        for i in range(n_clients)
    ]
    for t in threads:
        t.start()

    # the chaos choreography, against live traffic:
    time.sleep(0.3)
    p0, ex0, _src0 = servers[0]
    p0.stop()                       # HARD kill server 0
    time.sleep(0.3)
    p1, ex1, src1 = servers[1]
    src1.drain(flush_queued=True)   # graceful drain server 1
    deadline = time.monotonic() + 5
    while not src1.drained() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert src1.drained(), src1.admission_stats()
    assert ex1.drain(timeout=10)    # quiesce at a frame boundary
    p1.stop()
    time.sleep(0.3)
    # restart both on their old ports: the fleet re-probes them in
    p0b, ex0b, _ = start_server("soakf0b", port=ports[0])
    p1b, ex1b, _ = start_server("soakf1b", port=ports[1])
    execs += [ex0b, ex1b]

    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "client thread hung"

    kinds = {}
    lats = []
    fleet_stats = []
    for kind, val in outcomes:
        if kind == "stats":
            fleet_stats.append(val)
            continue
        kinds[kind] = kinds.get(kind, 0) + 1
        if kind == "completed":
            lats.append(val)
    # every request terminal; nothing timed out or errored unexpectedly
    assert sum(kinds.values()) == n_clients * n_requests, kinds
    unexpected = {
        k: v for k, v in kinds.items() if k not in ("completed", "nacked")
    }
    assert not unexpected, (unexpected, kinds)
    assert kinds.get("completed", 0) >= n_clients * n_requests * 3 // 4, kinds
    # failover p99 bounded: the kill/drain/restart gap never queues into
    # latency collapse (generous ceiling absorbs scheduler noise)
    lats.sort()
    p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))]
    assert p99 < 5.0, f"p99 {p99:.3f}s — failover gap collapsed"
    # the fleet actually exercised failover, and duplicates never
    # reached a caller (at-most-once held under kill + chaos + hedging)
    assert sum(s["failovers"] for s in fleet_stats) >= 1, fleet_stats
    # surviving/restarted pipelines: accounting + thread hygiene
    for p, ex, _src in (servers[2], (p0b, ex0b, None), (p1b, ex1b, None)):
        p.stop()
    for ex in execs:
        assert not ex.errors, ex.errors
        # the sanitizer's cross-process sweep sees the OTHER still-
        # running servers' threads (several executors share this test
        # process); the per-executor invariant is that none of its OWN
        # node threads outlived its shutdown
        own = {n.name for n in ex.nodes}
        assert not (set(ex.leaked_threads) & own), (
            ex.leaked_threads, own
        )
        for name, row in ex.stats().items():
            if not row.get("san_offered"):
                continue
            balance = (
                row["san_offered"] - row["san_delivered"]
                - row["san_routed"] - row.get("deadline_shed", 0)
                - row.get("error_dropped", 0)
            )
            assert balance >= 0, (name, row)
    # the global invariant: once every pipeline stopped, NO soak thread
    # survives anywhere in the process
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        stragglers = [
            t.name for t in threading.enumerate()
            if t.is_alive() and "soakf" in t.name
        ]
        if not stragglers:
            break
        time.sleep(0.05)
    assert not stragglers, stragglers
