"""nns-xray: chain compile-unit inference, the jaxpr lint walkers
(NNS-W120..W124), the static cost model verified against the runtime
TransferTally, the kernel dispatch table, and the CLI."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu import config as config_mod
from nnstreamer_tpu.analysis.costmodel import (
    configured_device_bound,
    plan_transfer_boundaries,
    predict_frame_transfers,
    spec_bytes,
)
from nnstreamer_tpu.analysis.diagnostics import LintReport
from nnstreamer_tpu.analysis.xray import (
    _segment_pass,
    cache_key_finding,
    donation_finding,
    dispatch_table,
    dtype_findings,
    host_callback_prims,
    xray,
)
from nnstreamer_tpu.pipeline.batching import BatchConfig
from nnstreamer_tpu.pipeline.parse import parse_pipeline

# one chain end to end: two device-capable segments joined across a
# queue (device-passthrough) — 16x16 RGB = 768 bytes/frame
ONE_CHAIN = (
    "videotestsrc device=true num-frames=4 width=16 height=16 ! "
    "tensor_converter ! tensor_filter framework=scaler ! queue ! "
    "tensor_filter framework=scaler ! fakesink"
)

# the control: a host-bound filter (hostscaler: numpy, no traceable_fn)
# severs the chain — every frame round-trips through host mid-stream
HOST_SPLIT = (
    "videotestsrc device=true num-frames=4 width=16 height=16 ! "
    "tensor_converter ! tensor_filter framework=scaler ! "
    "tensor_filter name=hostop framework=hostscaler ! "
    "tensor_filter framework=scaler ! fakesink"
)

FRAME_BYTES = 16 * 16 * 3

# a single fused segment with a STATIC tensor input spec (tensorsrc, not
# video caps), so _negotiated_sig() is concrete — the jaxpr-walker tests
# trace and mutate this one
SEG_DESC = (
    "tensorsrc dimensions=16 types=float32 num-frames=1 ! "
    "tensor_filter framework=scaler ! fakesink"
)


# ------------------------------------------------------- chain inference
class TestChains:
    def test_single_chain_through_queue(self):
        r = xray(ONE_CHAIN)
        assert not r.degraded and not r.errors
        assert len(r.chains) == 1
        assert len(r.chains[0].segments) == 2  # queue splits segments...
        assert r.codes == []  # ...but not the chain

    def test_plan_chains_partition_segments(self):
        plan = parse_pipeline(ONE_CHAIN).compile_plan()
        chains = plan.chains()
        members = [id(s) for ch in chains for s in ch.segments]
        assert sorted(members) == sorted(id(s) for s in plan.segments)
        assert len(members) == len(set(members))  # exactly one chain each

    def test_host_split_makes_two_chains(self):
        r = xray(HOST_SPLIT)
        assert len(r.chains) == 2
        assert "NNS-W120" in r.codes
        w120 = [d for d in r.diagnostics if d.code == "NNS-W120"]
        assert w120[0].element == "hostop"
        # the message names both severed chains
        assert all(c.name in w120[0].message for c in r.chains)

    @pytest.mark.slow
    def test_composite_face_cascade_is_one_chain(self):
        # the PR-12 detect->crop->landmark cascade: converter, detector,
        # crop-resize and landmark all land in ONE compile unit with
        # zero predicted host transfer (acceptance pin)
        desc = (
            "videotestsrc pattern=gradient num-frames=1 device=true "
            "width=128 height=128 ! tensor_converter ! "
            "tensor_filter framework=jax model=zoo:face_detect "
            'custom="output:regions+image,threshold:0.0,frame_size:128:128" '
            "! tensor_transform mode=crop-resize option=112:112 ! queue ! "
            "tensor_filter framework=jax model=zoo:face_landmark "
            'custom="batch:16" ! fakesink'
        )
        r = xray(desc)
        assert not r.degraded
        assert len(r.chains) == 1
        assert r.chains[0].n_ops == 4
        assert r.codes == []
        assert r.predicted == {"h2d": 0, "d2h": 0}
        assert r.predicted_tpu == {"h2d": 0, "d2h": 0}
        assert r.chains[0].cost.params_bytes > 0  # real opened weights


# ------------------------------------- cost model vs the runtime tally
class TestTransferPrediction:
    def test_zero_transfer_chain_predicts_and_measures_zero(self):
        r = xray(ONE_CHAIN)
        assert r.predicted == {"h2d": 0, "d2h": 0}
        assert r.boundaries == []
        ex = parse_pipeline(ONE_CHAIN).run(timeout=60)
        assert ex.transfer_totals() == {"h2d": 0, "d2h": 0}
        chk = ex.transfer_crosscheck()
        assert chk["delta"] == {"h2d": 0, "d2h": 0}

    def test_host_split_prediction_matches_measured_tally(self):
        r = xray(HOST_SPLIT)
        d2h = [b for b in r.boundaries if b.direction == "d2h"]
        assert len(d2h) == 1 and d2h[0].reason == "producer-fetch"
        assert d2h[0].bytes_per_frame == FRAME_BYTES
        assert r.predicted == {"h2d": 0, "d2h": FRAME_BYTES}
        ex = parse_pipeline(HOST_SPLIT).run(timeout=60)
        chk = ex.transfer_crosscheck()
        assert chk["measured"]["d2h"] == 4 * FRAME_BYTES
        assert chk["predicted"] == chk["measured"]
        assert chk["delta"] == {"h2d": 0, "d2h": 0}

    def test_reading_sink_is_a_sink_fetch_boundary(self):
        desc = ONE_CHAIN.replace("fakesink", "tensor_sink")
        r = xray(desc)
        d2h = [b for b in r.boundaries if b.direction == "d2h"]
        assert len(d2h) == 1 and d2h[0].reason == "sink-fetch"
        assert r.predicted["d2h"] == FRAME_BYTES

    def test_tpu_view_adds_source_staging(self):
        # a HOST source feeding a device segment: free on local CPU
        # (stage_frame is passthrough), one h2d staging per frame on TPU
        desc = ONE_CHAIN.replace("videotestsrc device=true ", "videotestsrc ")
        r = xray(desc)
        assert r.predicted["h2d"] == 0
        assert r.predicted_tpu["h2d"] == FRAME_BYTES

    def test_media_spec_bytes_estimate(self):
        p = parse_pipeline(ONE_CHAIN)
        src = next(e for e in p.elements if e.name.startswith("videotestsrc"))
        plan = p.compile_plan()
        assert plan is not None  # negotiation ran; src out spec is media
        assert spec_bytes(src.out_specs[0]) == FRAME_BYTES


# ------------------------------------------------- jaxpr lint walkers
class TestJaxprWalkers:
    def test_dtype_promotion_flagged(self):
        with jax.experimental.enable_x64():
            jaxpr = jax.make_jaxpr(
                lambda x: jnp.sin(x.astype(jnp.float64))
            )(jax.ShapeDtypeStruct((4,), jnp.float32))
            msgs = dtype_findings(jaxpr)
        assert msgs and "float64" in msgs[0]

    def test_clean_f32_math_unflagged(self):
        jaxpr = jax.make_jaxpr(lambda x: jnp.sin(x) * 2.0)(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        )
        assert dtype_findings(jaxpr) == []

    def test_wide_input_excuses_wide_math(self):
        with jax.experimental.enable_x64():
            jaxpr = jax.make_jaxpr(lambda x: x + 1.0)(
                jax.ShapeDtypeStruct((4,), jnp.float64)
            )
            assert dtype_findings(jaxpr) == []

    def test_declared_output_drift_flagged(self):
        jaxpr = jax.make_jaxpr(lambda x: (x * 2.0,))(
            jax.ShapeDtypeStruct((4,), jnp.float32)
        )
        msgs = dtype_findings(jaxpr, declared_out=(np.int8,))
        assert msgs and "int8" in msgs[0]

    def test_host_callback_prims_found(self):
        def f(x):
            return jax.pure_callback(
                lambda a: a, jax.ShapeDtypeStruct((4,), np.float32), x
            )

        jaxpr = jax.make_jaxpr(f)(jax.ShapeDtypeStruct((4,), jnp.float32))
        assert host_callback_prims(jaxpr) == ["pure_callback"]

    def test_callback_in_segment_fires_w120(self):
        plan = parse_pipeline(SEG_DESC).compile_plan()
        seg = plan.segments[0]
        sig = seg._negotiated_sig()
        assert sig is not None

        def with_callback(*tensors):
            out = jax.pure_callback(
                lambda a: a,
                jax.ShapeDtypeStruct(sig[0][0], sig[0][1]),
                tensors[0],
            )
            return (out,)

        seg._compose = lambda: with_callback
        report = LintReport()
        _segment_pass(seg, report, [])
        assert "NNS-W120" in report.codes


# ---------------------------------------------- W121 cache-key hazards
class TestCacheKeys:
    def _seg(self):
        return parse_pipeline(SEG_DESC).compile_plan().segments[0]

    def test_flexible_spec_under_batching_is_unbounded(self):
        seg = self._seg()
        seg._negotiated_sig = lambda: None
        seg.batch_config = BatchConfig(
            enabled=True, max_batch=8, buckets=(1, 2, 4, 8)
        )
        msg = cache_key_finding(seg)
        assert msg is not None and "unbounded" in msg
        report = LintReport()
        _segment_pass(seg, report, [])
        assert "NNS-W121" in report.codes

    def test_bucket_ladder_explosion_flagged(self):
        seg = self._seg()
        seg.donate = True
        seg.batch_config = BatchConfig(
            enabled=True, max_batch=40, buckets=tuple(range(1, 41))
        )
        msg = cache_key_finding(seg)
        assert msg is not None and "82" in msg

    def test_healthy_ladder_clean(self):
        seg = self._seg()
        seg.batch_config = BatchConfig(
            enabled=True, max_batch=8, buckets=(1, 2, 4, 8)
        )
        assert cache_key_finding(seg) is None


# --------------------------------------------- W123 defeated donation
class TestDonation:
    DESC = (
        "tensorsrc dimensions=512:512:3 types=uint8 num-frames=1 ! "
        "tensor_filter framework=scaler ! fakesink"
    )

    def _seg(self):
        return parse_pipeline(self.DESC).compile_plan().segments[0]

    def _arm(self, seg):
        # the donating batched path: stacked windows donate everywhere
        seg.donate = True
        seg.ring_depth = 2
        seg.batch_config = BatchConfig(
            enabled=True, max_batch=2, buckets=(2,)
        )

    def test_no_reusable_output_fires(self):
        seg = self._seg()
        self._arm(seg)
        # output dtype differs from every input: nothing aliasable
        seg._compose = lambda: (
            lambda *ts: tuple(t.astype(jnp.float32) * 0.5 for t in ts)
        )
        msg = donation_finding(seg)
        assert msg is not None and "donated" in msg
        report = LintReport()
        _segment_pass(seg, report, [])
        assert "NNS-W123" in report.codes

    def test_matching_output_is_reusable_and_clean(self):
        seg = self._seg()
        self._arm(seg)  # default compose preserves shape and dtype
        assert donation_finding(seg) is None

    def test_per_frame_path_never_donates_on_cpu(self):
        seg = self._seg()
        seg.donate = True
        seg.ring_depth = 2  # no batching: the CPU per-frame path
        seg._compose = lambda: (
            lambda *ts: tuple(t.astype(jnp.float32) for t in ts)
        )
        if jax.default_backend() == "cpu":
            assert donation_finding(seg) is None


# ------------------------------------------------ W124 resident bound
class TestResidentBound:
    def test_bound_breach_fires_w124(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_PLANE_MEMORY_PER_DEVICE", "1024")
        config_mod.reload_conf()
        try:
            assert configured_device_bound() == 1024
            r = xray(ONE_CHAIN)
            assert "NNS-W124" in r.codes
            w124 = [d for d in r.diagnostics if d.code == "NNS-W124"][0]
            assert "memory_per_device" in w124.message
        finally:
            monkeypatch.delenv("NNS_TPU_PLANE_MEMORY_PER_DEVICE")
            config_mod.reload_conf()

    def test_no_bound_no_finding(self):
        assert configured_device_bound() is None
        assert "NNS-W124" not in xray(ONE_CHAIN).codes


# -------------------------------------------------- dispatch counters
class TestDispatch:
    def test_tally_records_resolved_impl(self):
        from nnstreamer_tpu.ops import dispatch as disp
        from nnstreamer_tpu.ops.image import resize_bilinear

        before = disp.tally.snapshot()
        resize_bilinear(jnp.zeros((8, 8, 3), jnp.float32), 4, 4)
        engaged = disp.engaged_impls("resize_bilinear", before)
        want = "pallas" if jax.default_backend() == "tpu" else "jnp"
        assert engaged == [want]

    def test_dispatch_table_probes_every_dual_path_op(self):
        rows = {r["op"]: r for r in dispatch_table()}
        assert set(rows) == {
            "crop_and_resize", "resize_bilinear", "nms",
            "block_attention", "serving_attention",
        }
        here = "pallas" if jax.default_backend() == "tpu" else "jnp"
        for op in ("crop_and_resize", "resize_bilinear", "nms",
                   "block_attention"):
            assert rows[op]["auto_on_tpu"] == "pallas"
            # the record lands at the branch point, so even a probe
            # that fails numerically proves its dispatch
            assert rows[op]["measured"] == [here], rows[op]
        assert rows["serving_attention"]["auto_here"] in ("pallas", "xla")
        assert rows["serving_attention"]["measured"] == []

    def test_no_probe_skips_measurement(self):
        rows = dispatch_table(run=False)
        assert all(r["measured"] == [] and r["error"] is None for r in rows)


# ----------------------------------------------------------------- CLI
class TestCli:
    def test_clean_pipeline_exits_zero(self, capsys):
        from nnstreamer_tpu.analysis.xray_cli import main

        assert main([ONE_CHAIN]) == 0
        out = capsys.readouterr().out
        assert "compile units: 1" in out

    def test_warnings_exit_one_strict_two(self, capsys):
        from nnstreamer_tpu.analysis.xray_cli import main

        assert main([HOST_SPLIT]) == 1
        assert main(["--strict", HOST_SPLIT]) == 2

    def test_json_report(self, capsys):
        from nnstreamer_tpu.analysis.xray_cli import main

        assert main(["--json", HOST_SPLIT]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert len(doc["chains"]) == 2
        assert doc["predicted"] == {"h2d": 0, "d2h": FRAME_BYTES}
        assert any(d["code"] == "NNS-W120" for d in doc["diagnostics"])

    def test_dispatch_flag(self, capsys):
        from nnstreamer_tpu.analysis.xray_cli import main

        assert main(["--dispatch", "--no-probe"]) == 0
        out = capsys.readouterr().out
        assert "crop_and_resize" in out and "block_attention" in out

    def test_self_check_flag(self, capsys):
        from nnstreamer_tpu.analysis.xray_cli import main

        assert main(["--self-check"]) == 0
        assert "OK" in capsys.readouterr().out


# ----------------------------------------------------- degraded mode
class TestDegraded:
    def test_missing_model_degrades_not_diagnoses(self):
        r = xray(
            "videotestsrc ! tensor_converter ! "
            "tensor_filter framework=jax model=/does/not/exist.pkl ! "
            "fakesink"
        )
        assert r.degraded
        assert r.codes == []
        assert r.exit_code == 0
        assert any("compile_plan failed" in n for n in r.notes)

    def test_parse_failure_is_an_error(self):
        r = xray("videotestsrc ! ! fakesink")
        assert r.errors and r.exit_code == 2

    def test_crosscheck_flag_reads_env(self, monkeypatch):
        from nnstreamer_tpu.pipeline import transfer

        monkeypatch.setenv("NNS_XRAY_CROSSCHECK", "1")
        assert transfer.xray_crosscheck_enabled()
        monkeypatch.setenv("NNS_XRAY_CROSSCHECK", "0")
        assert not transfer.xray_crosscheck_enabled()
