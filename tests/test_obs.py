"""nns-obs tests: histogram/quantile math vs numpy ground truth,
exposition formats (Prometheus line format + JSON roundtrip), the HTTP
endpoint during a live pipeline, frame-id propagation over a loopback
query hop, and the merged multi-process chrome trace.

Kept fast (<5 s of work beyond the shared jax import) so the tier-1
870 s budget doesn't truncate later-alphabet test files.
"""

import json
import re
import socket
import threading
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu import trace
from nnstreamer_tpu.obs import expo, metrics as obs_metrics
from nnstreamer_tpu.obs import nns_top
from nnstreamer_tpu.obs.metrics import Histogram, MetricsRegistry


@pytest.fixture(autouse=True)
def _clean_obs():
    yield
    obs_metrics.disable()
    trace.disable()


# -- histogram math ----------------------------------------------------------

class TestHistogram:
    def test_quantiles_vs_numpy(self):
        rng = np.random.default_rng(7)
        vals = rng.lognormal(mean=5.0, sigma=1.2, size=8000)
        h = Histogram("nns_element_latency_us", {})
        for v in vals:
            h.observe(float(v))
        for q in (0.50, 0.95, 0.99):
            est = h.quantile(q)
            ref = float(np.quantile(vals, q))
            assert abs(est - ref) / ref < 0.05, (q, est, ref)
        assert h.count == len(vals)
        assert h.mean == pytest.approx(float(vals.mean()), rel=1e-9)
        assert h.min == pytest.approx(float(vals.min()))
        assert h.max == pytest.approx(float(vals.max()))

    def test_single_sample_reports_the_sample(self):
        h = Histogram("nns_element_latency_us", {})
        h.observe(123.0)
        # clamped to observed min/max, not a bucket edge
        assert h.quantile(0.5) == pytest.approx(123.0)
        assert h.quantile(0.99) == pytest.approx(123.0)

    def test_merge_and_json_roundtrip(self):
        a = Histogram("nns_element_latency_us", {"element": "f"})
        b = Histogram("nns_element_latency_us", {"element": "f"})
        for v in (5.0, 50.0, 500.0):
            a.observe(v)
        for v in (10.0, 100.0):
            b.observe(v)
        back = Histogram.from_dict(json.loads(json.dumps(a.to_dict())))
        assert back.count == a.count
        assert back.quantile(0.5) == pytest.approx(a.quantile(0.5))
        back.merge(b)
        assert back.count == 5
        assert back.min == 5.0 and back.max == 500.0

    def test_merge_ladder_mismatch_raises(self):
        a = Histogram("nns_element_latency_us", {})
        b = Histogram("nns_element_latency_us", {}, growth=2.0)
        with pytest.raises(ValueError, match="ladder"):
            a.merge(b)

    def test_registry_rejects_uncataloged_names(self):
        reg = MetricsRegistry()
        with pytest.raises(KeyError, match="METRIC_CATALOG"):
            reg.counter("nns_not_a_real_metric")

    def test_registry_merge_dict_sums_counters(self):
        a = MetricsRegistry()
        a.counter("nns_element_frames_total", element="x").inc(3)
        a.histogram("nns_element_latency_us", element="x").observe(9.0)
        snap = json.loads(json.dumps(a.to_dict()))
        b = MetricsRegistry()
        b.counter("nns_element_frames_total", element="x").inc(4)
        b.merge_dict(snap)
        assert b.find("nns_element_frames_total", element="x").value == 7
        h = b.find("nns_element_latency_us", element="x")
        assert h is not None and h.count == 1


# -- exposition --------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z0-9_]+=\"[^\"]*\""
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})? \S+$"
)


class TestExposition:
    def _registry(self):
        reg = MetricsRegistry()
        reg.counter("nns_element_frames_total", element="f").inc(12)
        h = reg.histogram("nns_element_latency_us", element="f")
        for v in (3.0, 30.0, 300.0, 3000.0):
            h.observe(v)
        return reg

    def test_prometheus_line_format(self):
        text = expo.to_prometheus(self._registry())
        assert text.endswith("\n")
        for line in text.strip().splitlines():
            if line.startswith("#"):
                assert re.match(r"^# (HELP|TYPE) nns_[a-z0-9_]+", line)
            else:
                assert _PROM_LINE.match(line), line

    def test_prometheus_histogram_buckets_cumulative(self):
        text = expo.to_prometheus(self._registry())
        counts = [
            int(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith("nns_element_latency_us_bucket")
        ]
        assert counts == sorted(counts)  # cumulative, monotone
        assert counts[-1] == 4  # the +Inf bucket carries the total
        assert "nns_element_latency_us_count{element=\"f\"} 4" in text

    def test_json_snapshot_roundtrips(self):
        doc = expo.snapshot(
            self._registry(), {"f": {"frames": 12}}, {"produced": 12},
            process="unit",
        )
        back = json.loads(json.dumps(doc))
        assert back["schema"] == "nns-obs/1"
        assert back["process"] == "unit"
        assert back["nodes"]["f"]["frames"] == 12
        reg = MetricsRegistry()
        reg.merge_dict(back)
        assert reg.find("nns_element_frames_total", element="f").value == 12

    def test_dump_json_atomic(self, tmp_path):
        path = tmp_path / "m.json"
        expo.dump_json(str(path), {"ok": 1})
        expo.dump_json(str(path), {"ok": 2})  # overwrite, no .tmp left
        assert json.loads(path.read_text()) == {"ok": 2}
        assert list(tmp_path.iterdir()) == [path]


# -- executor wiring ---------------------------------------------------------

class TestExecutorMetrics:
    def test_stats_gain_percentile_columns(self):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        obs_metrics.enable()
        p = parse_pipeline(
            "videotestsrc num-frames=40 width=8 height=8 ! "
            "tensor_converter ! tensor_sink"
        )
        ex = p.run(timeout=60)
        for name, row in ex.stats().items():
            assert row["frames"] == 40, name
            assert "fps" in row
            assert row["latency_p50_ms"] <= row["latency_p95_ms"] \
                <= row["latency_p99_ms"]
        sink_name, sink_row = next(
            (k, v) for k, v in ex.stats().items()
            if k.startswith("tensor_sink")
        )
        assert "queue_wait_p50_ms" in sink_row
        assert sink_row["queue_depth"] == [0]
        # the registry saw the same elements
        reg = obs_metrics.get()
        h = reg.find("nns_element_latency_us", element=sink_name)
        assert h is not None and h.count > 0

    def test_disabled_pipeline_records_nothing(self):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        obs_metrics.disable()
        p = parse_pipeline(
            "videotestsrc num-frames=4 width=8 height=8 ! "
            "tensor_converter ! tensor_sink"
        )
        ex = p.run(timeout=60)
        assert obs_metrics._registry is None
        sink_row = next(
            v for k, v in ex.stats().items() if k.startswith("tensor_sink")
        )
        assert "latency_p50_ms" not in sink_row

    def test_endpoint_serves_during_live_pipeline(self, monkeypatch):
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        monkeypatch.setenv("NNS_TPU_METRICS_PORT", str(port))
        p = parse_pipeline(
            "videotestsrc num-frames=40 is-live=true framerate=40/1 "
            "width=8 height=8 ! tensor_converter ! tensor_sink"
        )
        ex = p.start()
        try:
            url = f"http://127.0.0.1:{port}"
            deadline = 50
            while True:  # the server binds inside ex.start(); poll it up
                try:
                    with urllib.request.urlopen(
                        url + "/metrics", timeout=2
                    ) as r:
                        prom = r.read().decode()
                    break
                except OSError:
                    deadline -= 1
                    assert deadline > 0, "endpoint never came up"
            assert "nns_element_latency_us" in prom
            with urllib.request.urlopen(
                url + "/metrics.json", timeout=2
            ) as r:
                doc = json.loads(r.read())
            assert any(
                k.startswith("videotestsrc") for k in doc["nodes"]
            )
            assert ex.wait(30)
        finally:
            ex.stop()
        # server thread shut down with the executor
        assert not any(
            t.name == "nns-obs-http" for t in threading.enumerate()
        )
        assert ex._metrics_server is None

    def test_launch_stats_prints_percentiles(self, capsys):
        from nnstreamer_tpu import cli

        rc = cli.main([
            "videotestsrc num-frames=20 width=8 height=8 ! "
            "tensor_converter ! tensor_sink",
            "--stats", "-q",
        ])
        assert rc == 0
        stats = json.loads(capsys.readouterr().out)
        row = next(
            v for k, v in stats.items() if k.startswith("tensor_sink")
        )
        assert {"latency_p50_ms", "latency_p95_ms",
                "latency_p99_ms"} <= set(row)

    def test_launch_metrics_one_shot_dump(self, tmp_path, capsys):
        from nnstreamer_tpu import cli

        out = tmp_path / "m.json"
        rc = cli.main([
            "videotestsrc num-frames=8 width=8 height=8 ! "
            "tensor_converter ! tensor_sink",
            "--metrics", str(out), "-q",
        ])
        assert rc == 0
        doc = json.loads(out.read_text())
        assert doc["schema"] == "nns-obs/1"
        sink_row = next(
            v for k, v in doc["nodes"].items()
            if k.startswith("tensor_sink")
        )
        assert sink_row["frames"] == 8
        assert any(
            m["name"] == "nns_element_latency_us" for m in doc["metrics"]
        )
        # nns-top renders the snapshot file
        table = nns_top.render(doc)
        assert "tensor_sink" in table and "P99ms" in table


# -- nns-top -----------------------------------------------------------------

class TestNnsTop:
    SNAP = {
        "process": "pid1",
        "nodes": {
            "filter0": {
                "frames": 100, "fps": 50.0, "latency_p50_ms": 2.0,
                "latency_p99_ms": 9.5, "queue_wait_p50_ms": 0.4,
                "queue_depth": [3], "avg_batch_size": 6.2,
                "pad_waste_pct": 9.4, "errors": 2, "error_retries": 5,
                "cb_opens": 1, "cb_open": True, "san_spec_violations": 1,
            },
            "_totals_like": {"frames": 1},
        },
        "totals": {"produced": 100, "rendered": 98,
                   "dropped": {"x": 2}, "balance": 0},
    }

    def test_render_columns_and_notes(self):
        out = nns_top.render(self.SNAP)
        assert "filter0" in out
        assert "9.50" in out       # p99
        assert "retry=5" in out
        assert "cb=OPEN(1)" in out
        assert "san_spec_violations=1" in out
        assert "_totals_like" not in out  # underscore rows are footer
        assert "produced=100" in out and "dropped=2" in out

    def test_render_diffs_fps_between_polls(self):
        prev = {"nodes": {"filter0": {"frames": 50}}}
        out = nns_top.render(self.SNAP, prev, interval_s=2.0)
        assert "25.0" in out  # (100-50)/2s beats the cumulative 50.0


# -- distributed correlation -------------------------------------------------

class TestWireMeta:
    def test_meta_rides_the_wire(self):
        from nnstreamer_tpu.edge.serialize import (
            decode_message, encode_message,
        )
        from nnstreamer_tpu.tensors.frame import Frame

        f = Frame(
            (np.arange(4, dtype=np.float32),), pts=7,
            meta={"frame_id": "abc.1", "client_id": 9,
                  "wall_t0": 123.0, "score": 0.5},
        )
        back = decode_message(encode_message(f))
        assert back.meta["frame_id"] == "abc.1"
        assert back.meta["score"] == 0.5
        # per-hop-local keys never cross
        assert "client_id" not in back.meta
        assert "wall_t0" not in back.meta
        assert back.pts == 7
        np.testing.assert_array_equal(back.tensors[0], f.tensors[0])

    def test_metaless_frames_stay_lean(self):
        from nnstreamer_tpu.edge.serialize import (
            _HDR, decode_message, encode_message,
        )
        from nnstreamer_tpu.tensors.frame import Frame

        f = Frame((np.zeros(2, dtype=np.float32),))
        data = encode_message(f)
        assert data[_HDR.size - 4] == 0  # flags clear: no blob
        assert decode_message(data).meta == {}

    def test_frame_id_propagates_over_loopback_query_hop(self):
        from nnstreamer_tpu.edge.query import (
            TensorQueryClient, TensorQueryServerSrc, TensorQueryServerSink,
        )
        from nnstreamer_tpu.pipeline.graph import Pipeline
        from nnstreamer_tpu.tensors.frame import Frame

        tracer = trace.enable()
        tracer.clear()
        src = TensorQueryServerSrc(port=0, id="obs-t")
        sink = TensorQueryServerSink(id="obs-t")
        server = Pipeline().chain(src, sink)  # echo server
        ex = server.start()
        try:
            client = TensorQueryClient(
                **{"dest-port": src.bound_port, "timeout": 10.0}
            )
            client.negotiate([None])
            client.start()
            try:
                reply = client.process(
                    Frame((np.ones(3, dtype=np.float32),))
                )
            finally:
                client.stop()
            fid = reply.meta.get("frame_id")
            assert fid, "client must stamp and recover a frame_id"
            # both halves of the hop traced the same frame identity
            edge_evs = [
                e for e in tracer.events() if e.get("cat") == "edge"
            ]
            tagged = {
                e["name"] for e in edge_evs
                if e.get("args", {}).get("frame_id") == fid
            }
            assert any("client" in n for n in tagged)
            assert any("serversrc" in n for n in tagged)
            assert any("serversink" in n for n in tagged)
        finally:
            ex.stop()


def _trace_echo_server(port_q, stop_q, trace_path):
    """Child-process body for the two-process merged-trace test (module
    level so multiprocessing can target it)."""
    from nnstreamer_tpu import trace as trace_mod
    from nnstreamer_tpu.edge.query import (
        TensorQueryServerSink, TensorQueryServerSrc,
    )
    from nnstreamer_tpu.pipeline.graph import Pipeline

    tracer = trace_mod.enable()
    tracer.set_process("obs-test-server")
    src = TensorQueryServerSrc(port=0, id="obs-2p")
    sink = TensorQueryServerSink(id="obs-2p")
    ex = Pipeline().chain(src, sink).start()
    port_q.put(src.bound_port)
    stop_q.get()
    ex.stop()
    tracer.save(trace_path)


@pytest.mark.slow
def test_two_process_query_trace_merges_into_one_timeline(tmp_path):
    """The acceptance-criteria walkthrough, for real: a client pipeline
    and a separate server PROCESS each record a chrome trace over a
    loopback query hop; trace.merge() folds them into one Perfetto
    document where both processes' edge events share the frame_id."""
    import multiprocessing as mp

    from nnstreamer_tpu.edge.query import TensorQueryClient
    from nnstreamer_tpu.tensors.frame import Frame

    server_path = str(tmp_path / "server.json")
    port_q: mp.Queue = mp.Queue()
    stop_q: mp.Queue = mp.Queue()
    proc = mp.Process(
        target=_trace_echo_server,
        args=(port_q, stop_q, server_path), daemon=True,
    )
    proc.start()
    try:
        port = port_q.get(timeout=60)
        tracer = trace.enable()
        tracer.clear()
        tracer.set_process("obs-test-client")
        client = TensorQueryClient(**{"dest-port": port})
        client.negotiate([None])
        client.start()
        try:
            reply = client.process(Frame((np.ones(2, dtype=np.float32),)))
        finally:
            client.stop()
        fid = reply.meta["frame_id"]
        client_path = str(tmp_path / "client.json")
        tracer.save(client_path)
        stop_q.put(None)
        proc.join(timeout=60)
        assert proc.exitcode == 0
        docs = [
            json.loads(open(client_path).read()),
            json.loads(open(server_path).read()),
        ]
        merged = trace.merge(docs)
        procs = {
            e["args"]["name"] for e in merged["traceEvents"]
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert {"obs-test-client", "obs-test-server"} <= procs
        edge_pids = {
            e["pid"] for e in merged["traceEvents"]
            if e.get("cat") == "edge"
            and e.get("args", {}).get("frame_id") == fid
        }
        assert len(edge_pids) == 2  # BOTH processes saw this frame
    finally:
        if proc.is_alive():
            proc.terminate()


class TestTracer:
    def test_stable_tids_and_thread_names(self):
        t = trace.Tracer(process="unit")
        with t.span("main-span"):
            pass

        def worker():
            with t.span("worker-span"):
                pass

        th = threading.Thread(target=worker, name="svc-thread")
        th.start()
        th.join()
        evs = t.events()
        tids = {e["name"]: e["tid"] for e in evs}
        assert tids["main-span"] != tids["worker-span"]
        assert all(0 < tid < 100 for tid in tids.values())
        doc = t.to_chrome_trace()
        meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        names = {e["args"]["name"] for e in meta}
        assert "unit" in names and "svc-thread" in names

    def test_bounded_buffer_drops_oldest(self):
        t = trace.Tracer(max_events=50)
        for i in range(130):
            t.instant(f"e{i}")
        evs = t.events()
        assert len(evs) == 50
        assert t.dropped_events == 80
        assert evs[0]["name"] == "e80"  # oldest dropped, newest kept
        assert t.to_chrome_trace()["otherData"]["dropped_events"] == 80

    def test_save_is_atomic(self, tmp_path):
        t = trace.Tracer()
        t.instant("x")
        path = tmp_path / "trace.json"
        t.save(str(path))
        t.instant("y")
        t.save(str(path))  # overwrite via rename
        doc = json.loads(path.read_text())
        assert len([e for e in doc["traceEvents"] if e["ph"] == "i"]) == 2
        assert list(tmp_path.iterdir()) == [path]

    def test_merge_aligns_two_processes(self):
        client = trace.Tracer(process="client", pid=111)
        server = trace.Tracer(process="server", pid=111)  # pid collision
        # server booted 2s after the client (wall anchors disagree)
        server._wall_t0 = client._wall_t0 + 2.0
        client.complete("request", "edge", client._t0, 0.001)
        server.complete("serve", "element", server._t0, 0.001)
        merged = trace.merge(
            [client.to_chrome_trace(), server.to_chrome_trace()]
        )
        evs = {
            e["name"]: e for e in merged["traceEvents"]
            if e["ph"] == "X"
        }
        # the server span lands ~2s after the client span on ONE axis
        delta_us = evs["serve"]["ts"] - evs["request"]["ts"]
        assert 1.9e6 < delta_us < 2.1e6
        assert evs["serve"]["pid"] != evs["request"]["pid"]
        assert merged["otherData"]["merged_processes"] == [
            "client", "server"
        ]
