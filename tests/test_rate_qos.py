"""tensor_rate upstream QoS: producers skip work for frames the rate
limiter would drop (reference gsttensor_rate.c:27-36 — QoS events sent
upstream so elements save compute; here the hint is pulled from a shared
RateQoS published by the rate element)."""

import numpy as np

from nnstreamer_tpu.backends.custom import (
    register_custom_easy,
    unregister_custom_easy,
)
from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.sources import VideoTestSrc
from nnstreamer_tpu.elements.windowing import RateQoS, TensorRate
from nnstreamer_tpu.pipeline.graph import Pipeline


def _run_rate_pipeline(qos: str):
    """videotestsrc 20fps → filter(counting) → tensor_rate 5fps → sink."""
    calls = {"n": 0}

    def counting(tensors):
        calls["n"] += 1
        return tuple(np.asarray(t) * 2 for t in tensors)

    name = f"qos_counting_{qos}"
    register_custom_easy(name, counting)
    try:
        src = VideoTestSrc(width=4, height=4, **{"num-frames": 20},
                           framerate="20/1")
        conv = TensorConverter()
        filt = TensorFilter(framework="custom-easy", model=name)
        rate = TensorRate(framerate="5/1", qos=qos)
        sink = TensorSink()
        p = Pipeline().chain(src, conv, filt, rate, sink)
        p.run(timeout=60)
        return calls["n"], sink.rendered, rate
    finally:
        unregister_custom_easy(name)


def test_upstream_skips_dropped_frames():
    calls, rendered, rate = _run_rate_pipeline("true")
    # 20 frames at 20fps → 5fps keeps every 4th: 5 outputs
    assert rendered == 5
    # the filter must NOT have computed all 20 frames
    assert calls < 20, f"filter ran {calls}/20 — no upstream skip happened"
    assert rate.qos.skipped_upstream == 20 - calls
    # every kept output slot still needs one compute
    assert calls >= rendered


def test_qos_disabled_computes_everything():
    calls, rendered, rate = _run_rate_pipeline("false")
    assert rendered == 5
    assert calls == 20
    assert rate.qos.skipped_upstream == 0


def test_output_parity_with_and_without_qos():
    """Skipping producer work must not change what the sink sees."""

    def run(qos):
        src = VideoTestSrc(width=4, height=4, **{"num-frames": 12},
                           framerate="12/1", pattern="counter")
        conv = TensorConverter()
        rate = TensorRate(framerate="4/1", qos=qos)
        sink = TensorSink()
        p = Pipeline().chain(src, conv, rate, sink)
        p.run(timeout=60)
        return [(f.pts, np.asarray(f.tensors[0]).tobytes()) for f in sink.frames]

    np.testing.assert_equal(run("true"), run("false"))


def test_rateqos_would_drop_semantics():
    q = RateQoS()
    assert not q.would_drop(0, 100)  # no hint yet
    q.next_ts = 1000
    assert q.would_drop(0, 100)       # entirely before next slot
    assert q.would_drop(900, 100)     # ends exactly at the slot boundary
    assert not q.would_drop(950, 100)  # covers the slot
    assert not q.would_drop(1000, 100)
    assert not q.would_drop(None, 100)  # untimed frames always pass
    q.enabled = False
    assert not q.would_drop(0, 100)
