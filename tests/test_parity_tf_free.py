"""Parity tests that need NO tensorflow: golden-logits drift detection,
the params:<npz> overlay path, and the torch backend slot.

Split out of tests/test_parity.py, whose module-level
``importorskip("tensorflow")`` would otherwise disable drift detection on
any image without tensorflow — defeating the golden-logits test's whole
purpose (it exists precisely so model-math drift fails even where the
cross-engine comparison can't run).
"""

import numpy as np
import pytest

import jax

from nnstreamer_tpu.models import zoo
from nnstreamer_tpu.single import SingleShot


def _img(shape, seed=0):
    return np.random.default_rng(seed).integers(0, 255, shape, np.uint8)


# -- golden logits: drift detection that needs no tensorflow ---------------

# First 8 logits of zoo:mobilenet_v2 (seed 0, size 96, num_classes 16) on
# the deterministic image below — recorded from the float32 CPU path. If
# the model math, init, or preprocessing drifts, this fails.
_GOLDEN_LOGITS = np.array(
    [0.10145831, 3.574911, -1.5670481, 3.147415,
     0.32970887, -1.3878971, 5.6172085, -1.5150919], np.float32
)


def test_mobilenet_golden_logits():
    m = zoo.get("mobilenet_v2", size="96", num_classes="16")
    img = _img((1, 96, 96, 3))
    out = np.asarray(jax.jit(m.fn)(img))[0, :8]
    np.testing.assert_allclose(out, _GOLDEN_LOGITS, rtol=5e-4, atol=5e-5)


# -- params overlay: the real-weights loading path -------------------------

def test_params_npz_overlay(tmp_path):
    base = zoo.get("mobilenet_v2", size="96", num_classes="16")
    leaves, _ = jax.tree_util.tree_flatten(base.params)
    # overlay: replace the classifier weight (largest trailing leaf set)
    # with a known constant and check the output becomes exactly the bias
    # structure it implies
    w_idx = next(
        i for i, l in enumerate(leaves) if tuple(l.shape) == (1280, 16)
    )
    # tree_flatten orders dict keys alphabetically: classifier {"b","w"}
    # flattens bias immediately before weight
    b_idx = w_idx - 1
    assert tuple(leaves[b_idx].shape) == (16,)
    overlay = {
        f"p{w_idx}": np.zeros((1280, 16), np.float32),
        f"p{b_idx}": np.arange(16, dtype=np.float32),
    }
    path = tmp_path / "w.npz"
    np.savez(path, **overlay)
    m = zoo.get(
        "mobilenet_v2", size="96", num_classes="16", params=str(path)
    )
    out = np.asarray(jax.jit(m.fn)(_img((1, 96, 96, 3))))
    np.testing.assert_allclose(out[0], np.arange(16, dtype=np.float32),
                               rtol=1e-5, atol=1e-5)


# -- torch backend (tensor_filter_pytorch.cc slot) -------------------------

def test_torch_backend_roundtrip(tmp_path):
    torch = pytest.importorskip("torch")
    from nnstreamer_tpu.tensors.spec import TensorsSpec

    class Scale(torch.nn.Module):
        def forward(self, x):
            return x * 2.0 + 1.0

    path = str(tmp_path / "scale.pt")
    torch.jit.script(Scale()).save(path)
    spec = TensorsSpec.from_strings("4:2", "float32")
    with SingleShot(framework="torch", model=path, input_spec=spec) as s:
        (out,) = s.invoke(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(out, np.full((2, 4), 3.0))


def test_torch_framework_autodetect(tmp_path):
    torch = pytest.importorskip("torch")
    from nnstreamer_tpu.tensors.spec import TensorsSpec

    class Neg(torch.nn.Module):
        def forward(self, x):
            return -x

    path = str(tmp_path / "neg.pt")
    torch.jit.script(Neg()).save(path)
    spec = TensorsSpec.from_strings("3", "float32")
    with SingleShot(model=path, input_spec=spec) as s:
        (out,) = s.invoke(np.arange(3, dtype=np.float32))
    np.testing.assert_allclose(out, -np.arange(3, dtype=np.float32))
