"""Device-plane resilience (pipeline/device_faults.py, parallel/
replicas.py, docs/resilience.md): fault classification, the OOM
degrade-and-reprobe ladder, the compile/dispatch fallback circuit,
replica failover with exact frame accounting, and the warm-restart
drain/snapshot/resume round-trip — all driven by the deterministic
chaos injectors (FaultyBackend device modes, tensor_chaos
device-fault-kind).

Wall-time discipline: the tier-1 portion stays under ~5 s (tiny frame
counts, ladder-rung jit programs only); the mixed-fault soak is marked
``slow``.
"""

import os
import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline.device_faults import (
    BucketGovernor,
    DeviceCircuit,
    DeviceCompileError,
    DeviceFaultError,
    DeviceLostError,
    DeviceOOMError,
    ReplicaExhaustedError,
    classify_device_fault,
    resolve_device_policy,
)
from nnstreamer_tpu.pipeline.executor import Executor
from nnstreamer_tpu.pipeline.parse import parse_pipeline


@pytest.fixture(autouse=True)
def _sanitize(monkeypatch):
    """Every pipeline in this file runs under the runtime sanitizer:
    the degradation paths must keep offered == delivered + dropped +
    routed latched per node, or the run fails at EOS."""
    monkeypatch.setenv("NNS_TPU_SANITIZE", "1")


# ------------------------------------------------------------- classifier
class _FakeXlaRuntimeError(Exception):
    pass


# the classifier matches on the class NAME (jaxlib moves the class path
# between releases)
_FakeXlaRuntimeError.__name__ = "XlaRuntimeError"


class TestClassifier:
    def test_typed_faults_classify_by_kind(self):
        assert classify_device_fault(DeviceOOMError("x")) == "oom"
        assert classify_device_fault(DeviceCompileError("x")) == "compile"
        assert classify_device_fault(DeviceLostError("x")) == "device_lost"
        assert classify_device_fault(DeviceFaultError("x")) == "transient"

    def test_ordinary_errors_are_not_device_faults(self):
        for exc in (ValueError("bad input"), RuntimeError("user code"),
                    KeyError("k")):
            assert classify_device_fault(exc) is None

    @pytest.mark.parametrize("msg,kind", [
        ("RESOURCE_EXHAUSTED: out of memory allocating 2.1G", "oom"),
        ("Compilation failure: unsupported HLO", "compile"),
        ("failed to connect to TPU driver", "device_lost"),
        ("INTERNAL: something ephemeral", "transient"),
        # OOM *during* compilation is memory pressure, not a broken
        # program: shrinking helps, recompiling the same thing doesn't
        ("compilation failure: ran out of memory while allocating", "oom"),
    ])
    def test_xla_message_sniffing(self, msg, kind):
        assert classify_device_fault(_FakeXlaRuntimeError(msg)) == kind


# -------------------------------------------------------- bucket governor
class TestBucketGovernor:
    def _gov(self, ladder=(1, 2, 4, 8), cooldown=10.0):
        clock = [0.0]
        g = BucketGovernor(ladder, cooldown_s=cooldown,
                           clock=lambda: clock[0])
        return g, clock

    def test_oom_halves_to_next_rung_and_remembers(self):
        g, _ = self._gov()
        assert g.cap() == 8 and not g.degraded
        assert g.on_oom(8) == 4
        assert g.cap() == 4 and g.degraded
        assert g.on_oom(4) == 2
        assert g.cap() == 2
        assert g.snapshot()["ceiling"] == 2
        assert g.ooms == 2

    def test_bucket_one_oom_returns_none(self):
        g, _ = self._gov()
        g.on_oom(2)
        assert g.on_oom(1) is None  # nothing left to shrink

    def test_zero_cooldown_disables_reprobe_no_livelock(self):
        """cooldown <= 0 must mean NEVER re-probe: a zero cooldown that
        offered the probe rung on every cap() call would livelock the
        service loop (dispatch at probe width -> OOM -> retry at probe
        width, forever)."""
        g, clock = self._gov(cooldown=0.0)
        assert g.on_oom(8) == 4
        for _ in range(5):
            assert g.cap() == 4     # never the probe rung
            clock[0] += 1000.0
        assert g.cap() == 4

    def test_reprobe_after_cooldown_reclaims_one_rung(self):
        g, clock = self._gov(cooldown=10.0)
        g.on_oom(8)                 # ceiling 4
        assert g.cap() == 4         # cooldown not elapsed: no probe
        clock[0] = 11.0
        assert g.cap() == 8         # probe window: one rung up
        assert g.on_ok(8) is True   # probe confirmed
        assert g.ceiling == 8 and not g.degraded
        assert g.reprobes == 1

    def test_failed_probe_pushes_cooldown_out(self):
        g, clock = self._gov(cooldown=10.0)
        g.on_oom(8)
        clock[0] = 11.0
        assert g.cap() == 8         # probing
        g.on_oom(8)                 # probe OOMs: stay at 4
        assert g.ceiling == 4
        assert g.cap() == 4         # cooldown re-armed at t=11
        clock[0] = 22.0
        assert g.cap() == 8         # next probe window

    def test_narrow_dispatch_during_probe_does_not_confirm(self):
        g, clock = self._gov(cooldown=10.0)
        g.on_oom(8)
        clock[0] = 11.0
        assert g.on_ok(2) is False  # narrower than the ceiling: no-op
        assert g.ceiling == 4

    def test_non_ladder_width_snaps_to_rung(self):
        """The host path dispatches arbitrary widths (no bucket
        padding): a success between rungs must not set a non-ladder
        ceiling — cap()'s ladder walk crashed on ceiling=3."""
        g, clock = self._gov(cooldown=10.0)
        g.on_oom(4)                 # ceiling 2
        clock[0] = 11.0
        assert g.cap() == 4         # probe window open
        assert g.on_ok(3) is False  # rung(3) == 2 == ceiling: no-op
        assert g.ceiling == 2
        assert g.cap() == 4         # ladder walk still intact
        assert g.on_ok(6) is True   # 6 rows confirm rung 4
        assert g.ceiling == 4

    def test_restore_rearms_ceiling_and_cooldown(self):
        g, clock = self._gov()
        g.on_oom(8)
        g.on_oom(4)
        snap = g.snapshot()
        g2, clock2 = self._gov()
        g2.restore(snap)
        assert g2.ceiling == 2 and g2.degraded
        assert g2.ooms == snap["ooms"]
        assert g2.cap() == 2        # cooldown armed: no instant probe
        clock2[0] = 11.0
        assert g2.cap() == 4        # but it can still recover


# --------------------------------------------------------- device circuit
class TestDeviceCircuit:
    def test_compile_opens_immediately(self):
        c = DeviceCircuit(after=3)
        assert c.record_fault("compile") is True
        assert c.open and c.opens == 1

    def test_transient_opens_after_consecutive(self):
        c = DeviceCircuit(after=3)
        assert c.record_fault("transient") is False
        c.record_ok()  # success resets the streak
        assert c.record_fault("transient") is False
        assert c.record_fault("transient") is False
        assert c.record_fault("transient") is True
        assert c.kinds == {"transient": 4}

    def test_probe_cadence_and_close(self):
        c = DeviceCircuit(after=1, probe_every=3)
        c.record_fault("device_lost")
        assert [c.should_probe() for _ in range(6)] == [
            False, False, True, False, False, True
        ]
        c.close()
        assert not c.open and c.closes == 1

    def test_snapshot_restore_round_trip(self):
        c = DeviceCircuit(after=1)
        c.record_fault("compile")
        c.eager_invokes = 7
        c2 = DeviceCircuit(after=1)
        c2.restore(c.snapshot())
        assert c2.open and c2.faults == 1
        assert c2.kinds == {"compile": 1} and c2.eager_invokes == 7


# ----------------------------------------------------------------- policy
class TestPolicyResolution:
    def test_defaults(self):
        pol = resolve_device_policy([])
        assert pol["oom-policy"] == "degrade"
        assert pol["device-fallback"] is True
        assert pol["device-fallback-after"] == 3

    def test_element_overrides_and_env(self, monkeypatch):
        from nnstreamer_tpu.elements.filter import TensorFilter

        monkeypatch.setenv("NNS_TPU_EXECUTOR_DEVICE_FALLBACK_AFTER", "7")
        f = TensorFilter(framework="passthrough", input="4",
                         **{"oom-policy": "stop",
                            "device-fallback": "false"})
        pol = resolve_device_policy([f])
        assert pol["oom-policy"] == "stop"
        assert pol["device-fallback"] is False
        assert pol["device-fallback-after"] == 7

    def test_invalid_oom_policy_raises(self):
        from nnstreamer_tpu.elements.filter import TensorFilter

        f = TensorFilter(framework="passthrough", input="4",
                         **{"oom-policy": "panic"})
        with pytest.raises(ValueError, match="oom-policy"):
            resolve_device_policy([f])


# ------------------------------------------------------------ replica set
class TestReplicaSet:
    def test_round_robin_over_healthy(self):
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        seen = []
        rs = ReplicaSet([lambda x, i=i: seen.append(i) or x
                         for i in range(3)])
        for v in range(6):
            rs.dispatch(v)
        assert seen == [0, 1, 2, 0, 1, 2]

    def test_failover_then_bench_then_probe_recovery(self):
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        state = {"dead": True}

        def flaky(x):
            if state["dead"]:
                raise DeviceLostError("gone")
            return ("r0", x)

        rs = ReplicaSet([flaky, lambda x: ("r1", x)],
                        unhealthy_after=2, probe_every=4)
        outs = [rs.dispatch(i) for i in range(6)]
        # every frame reached SOME replica (failover, never loss)
        assert all(o[0] == "r1" for o in outs)
        assert rs.healthy_count == 1
        assert rs.failovers >= 2
        state["dead"] = False          # the device comes back
        outs = [rs.dispatch(i) for i in range(8)]
        assert rs.healthy_count == 2   # a probe re-admitted replica 0
        assert any(o[0] == "r0" for o in outs)

    def test_non_device_error_propagates_unclassified(self):
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        def bad(x):
            raise ValueError("bad input")

        rs = ReplicaSet([bad, lambda x: x])
        with pytest.raises(ValueError):
            rs.dispatch(1)
        assert rs.healthy_count == 2   # says nothing about health

    def test_exhaustion_raises_with_cause(self):
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        def dead(x):
            raise DeviceOOMError("oom")

        rs = ReplicaSet([dead, dead], unhealthy_after=1)
        with pytest.raises(ReplicaExhaustedError) as ei:
            rs.dispatch(1)
        assert isinstance(ei.value.__cause__, DeviceOOMError)
        assert rs.exhaustions == 1

    def test_recovery_not_starved_by_permanently_dead_low_index(self):
        """Replica 0 dead for good, replica 1 benched but recovered:
        with nothing healthy the plan must rotate over EVERY benched
        replica — always probing sick[0] exhausted forever although
        replica 1 would serve."""
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        calls = {"r1": 0}

        def dead(x):
            raise DeviceLostError("gone for good")

        def flaky(x):
            calls["r1"] += 1
            if calls["r1"] == 1:
                raise DeviceLostError("one-off")
            return ("r1", x)

        rs = ReplicaSet([dead, flaky], unhealthy_after=1, probe_every=4)
        with pytest.raises(ReplicaExhaustedError):
            rs.dispatch(0)               # benches both
        assert rs.healthy_count == 0
        assert rs.dispatch(1) == ("r1", 1)   # r1 re-admitted, frame served
        assert rs.healthy_count == 1
        assert rs.dispatch(2) == ("r1", 2)

    def test_fresh_bench_waits_full_probe_cadence(self):
        """The probe counter must only accumulate while something is
        benched: healthy dispatches idling it high would probe a
        just-benched (still dead) replica on the very next frame."""
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        state = {"dead": False}
        calls = {"r0": 0}

        def flaky(x):
            calls["r0"] += 1
            if state["dead"]:
                raise DeviceLostError("gone")
            return ("r0", x)

        rs = ReplicaSet([flaky, lambda x: ("r1", x)],
                        unhealthy_after=1, probe_every=4)
        for v in range(20):            # long healthy stretch
            rs.dispatch(v)
        state["dead"] = True
        rs.dispatch(100)               # faults, benches r0, fails over
        assert rs.healthy_count == 1
        benched_at = calls["r0"]
        rs.dispatch(101)               # next frame: NO immediate probe
        assert calls["r0"] == benched_at
        for v in range(4):             # cadence elapses -> probe fires
            rs.dispatch(v)
        assert calls["r0"] == benched_at + 1

    def test_probe_rotates_across_benched_replicas(self):
        """With a healthy survivor, periodic recovery probes alternate
        across the benched replicas instead of pinning the lowest
        index."""
        from nnstreamer_tpu.parallel.replicas import ReplicaSet

        probed = []

        def sick_a(x):
            probed.append("a")
            raise DeviceLostError("a")

        def sick_b(x):
            probed.append("b")
            raise DeviceLostError("b")

        rs = ReplicaSet([sick_a, sick_b, lambda x: x],
                        unhealthy_after=1, probe_every=2)
        for v in range(8):
            rs.dispatch(v)
        # both benched replicas saw probes after the initial bench
        assert set(probed[2:]) == {"a", "b"}


# ----------------------------------------------- OOM degrade (pipelines)
class TestOOMDegrade:
    def test_fused_batched_oom_shrinks_bucket_and_completes(self):
        """Acceptance: injected OOM → the batch bucket shrinks to the
        rung the device fits, every frame still arrives, and the
        sanitizer's per-node accounting latch stays green."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=100 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,oom_above_rows:2 "
            "batching=true max-batch=8 batch-timeout-ms=2 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert s["frames"] == 100
        assert len(p["out"].frames) == 100      # degrade, never drop
        assert s["oom_events"] >= 1
        assert s["batch_ceiling"] == 2          # the rung that fits
        assert s["device_degraded"] == 1
        assert ex.totals()["balance"] == 0
        # in order, too: OOM retries must not reorder the stream
        vals = [int(f.tensors[0][0]) for f in p["out"].frames]
        assert vals == sorted(vals)

    def test_host_batched_oom_rides_the_same_ladder(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=60 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=batchable:true,oom_above_rows:2 "
            "batching=true max-batch=8 batch-timeout-ms=2 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert len(p["out"].frames) == 60
        assert s["oom_events"] >= 1 and s["batch_ceiling"] == 2
        assert ex.totals()["balance"] == 0

    def test_oom_policy_stop_keeps_fail_fast(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter name=f framework=faulty oom-policy=stop "
            "device-fallback=false "
            "custom=traceable:true,oom_above_rows:2 "
            "batching=true max-batch=8 batch-timeout-ms=2 ! "
            "tensor_sink name=out"
        )
        with pytest.raises(DeviceOOMError):
            p.run(timeout=60)


# ------------------------------------------- compile/dispatch fallback
class TestCompileFallback:
    def test_compile_failure_serves_eager_and_surfaces_degraded(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=50 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,compile_fail:true ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert len(p["out"].frames) == 50       # eager path served all
        assert s["device_degraded"] == 1
        assert s["device_fault_kinds"].get("compile", 0) >= 1
        assert s["device_eager_invokes"] == 50
        assert s["device_circuit_opens"] == 1

    def test_compile_failure_at_build_opens_circuit_before_frames(self):
        """The batched warmup is the only thing that compiles at build —
        a deterministic compile fault there must escape the
        warmup-is-an-optimization swallow and open the circuit at
        PAUSED state, not stall mid-stream (an EOS-only pipeline shows
        the fault was recorded with zero frames served)."""
        p = parse_pipeline(
            "tensorsrc name=src dimensions=4 num-frames=0 ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,compile_fail:true "
            "batching=true max-batch=4 batch-timeout-ms=2 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        s = ex.stats()["f"]
        assert s["device_degraded"] == 1
        assert s["device_fault_kinds"].get("compile", 0) >= 1
        assert s["frames"] == 0

    def test_probe_closes_circuit_when_compile_recovers(self, monkeypatch):
        monkeypatch.setenv("NNS_TPU_EXECUTOR_DEVICE_PROBE_EVERY", "8")
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=60 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,compile_fail:true,compile_fail_first_n:1 "
            "! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert len(p["out"].frames) == 60
        assert s["device_degraded"] == 0        # recovered
        assert s["device_eager_invokes"] == 8   # exactly one probe beat
        assert s["device_circuit_opens"] == 1

    def test_fallback_off_propagates_to_error_policy(self):
        """device-fallback=false: the typed fault is an ordinary element
        error — PR-3 policies (here: drop) dispose of the frames."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=30 pattern=counter ! "
            "tensor_chaos name=c device-fault-kind=device_lost "
            "device-fault-every-n=5 on-error=drop ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["c"]
        assert s["error_dropped"] == 6          # frames 5,10,...,30
        assert len(p["out"].frames) == 24
        assert ex.totals()["balance"] == 0

    def test_chaos_device_fault_needs_kind(self):
        from nnstreamer_tpu.elements.chaos import TensorChaos

        with pytest.raises(ValueError, match="device-fault-kind"):
            TensorChaos(**{"device-fault-every-n": "5"})


# -------------------------------------------------------- replica failover
class TestReplicaFailover:
    def test_one_replica_lost_stream_survives_with_exact_accounting(self):
        """Acceptance: device loss in a 2-replica setup → every frame
        reaches a terminal outcome (here: delivered via the surviving
        replica) and throughput recovers on the survivor."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=100 pattern=counter ! "
            "tensor_filter name=f framework=faulty replicas=2 "
            "replica-unhealthy-after=2 "
            "custom=device_lost_at:3,only_replica:0 ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert len(p["out"].frames) == 100      # no frame lost to the
        assert ex.totals()["balance"] == 0      # dying replica
        assert s["rep_healthy"] == 1
        assert s["rep_failovers"] >= 1
        # the survivor carried the load after the bench
        assert s["rep_served"][1] > 90

    def test_exhaustion_disposes_through_error_policy(self):
        """offered == delivered + dropped + routed must hold when BOTH
        replicas die: ReplicaExhaustedError falls to on-error=drop and
        every undeliverable frame is accounted, none lost."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter name=f framework=faulty replicas=2 "
            "replica-unhealthy-after=1 custom=device_lost_at:5 "
            "on-error=drop ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        delivered = len(p["out"].frames)
        assert s["rep_healthy"] == 0
        assert delivered + s["error_dropped"] + s["error_routed"] == 40
        assert ex.totals()["balance"] == 0

    def test_exhaustion_routes_to_dead_letter(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter name=f framework=faulty replicas=2 "
            "replica-unhealthy-after=1 custom=device_lost_at:5 "
            "on-error=route ! tensor_sink name=out "
            "f.src_1 ! tensor_sink name=dlq"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        main, dlq = p["out"].frames, p["dlq"].frames
        assert len(main) + len(dlq) == 40
        assert len(dlq) > 0
        assert dlq[0].meta["error_type"] == "ReplicaExhaustedError"
        assert ex.totals()["balance"] == 0

    def test_partial_replica_open_failure_closes_opened_tail(self):
        """A replica that fails to open mid-build must not leak the
        replicas already opened before it: a retried first frame would
        otherwise stack a fresh copy of every model arena per attempt.
        Replica 0 (== self.backend) stays up — stop() owns it."""
        from nnstreamer_tpu.elements.filter import TensorFilter

        f = TensorFilter(framework="framecounter", replicas="3",
                         input="4", inputtype="float32")
        opened, closed = [], []
        orig = f._open_backend

        def tracked(custom_extra=""):
            if len(opened) == 2:  # replicas 0 and 1 already up
                raise RuntimeError("replica 2 open failed")
            b = orig(custom_extra)
            opened.append(b)
            real_close = b.close
            b.close = lambda: (closed.append(b), real_close())
            return b

        f._open_backend = tracked
        with pytest.raises(RuntimeError, match="replica 2"):
            f._ensure_replicas()
        assert closed == [opened[1]]
        assert f._replica_set is None and f._replica_backends == []
        f.stop()
        assert opened[0] in closed  # stop() still closes replica 0

    def test_replicas_reject_fallback_circuit(self):
        """replicas=N dispatches before the fallback circuit is ever
        consulted — accepting fallback-framework beside it would
        silently never open the fallback backend."""
        from nnstreamer_tpu.elements.filter import TensorFilter

        with pytest.raises(ValueError, match="fallback"):
            TensorFilter(framework="framecounter", replicas="2",
                         **{"fallback-framework": "passthrough"},
                         input="4", inputtype="float32")

    def test_replicas_reject_shared_key(self):
        from nnstreamer_tpu.elements.filter import TensorFilter

        with pytest.raises(ValueError, match="replicas"):
            TensorFilter(framework="passthrough", replicas="2",
                         **{"shared-tensor-filter-key": "k"})


# ------------------------------------------------- warm restart round-trip
class TestWarmRestart:
    DESC = (
        "tensorsrc name=src dimensions=4 num-frames={n} pattern=counter ! "
        "tensor_filter name=f framework=framecounter ! tensor_sink name=out"
    )

    def test_drain_snapshot_resume_in_place(self):
        """Acceptance: drain() parks the graph at a frame boundary,
        snapshot() captures exact per-element state, resume() restarts
        frame flow — nothing lost, nothing duplicated."""
        p = parse_pipeline(self.DESC.format(n=3000))
        ex = p.start()
        time.sleep(0.1)
        assert ex.drain(timeout=15) is True
        snap = ex.snapshot()
        mid = len(p["out"].frames)
        # frame-boundary consistency: the counter equals frames seen
        assert snap["elements"]["f"]["backend"]["count"] == mid
        assert snap["nodes"]["f"]["frames"] == mid
        ex.resume()
        assert ex.wait(60), ex.errors
        assert not ex.errors
        vals = [int(f.tensors[0][0]) for f in p["out"].frames]
        assert vals == list(range(3000))   # contiguous across the pause
        assert ex.totals()["balance"] == 0

    def test_warm_restart_into_fresh_executor(self, tmp_path):
        """Drain, persist the snapshot (atomic-replace file), rebuild
        the pipeline from scratch, restore before start: per-element
        state and node stats continue exactly where the old process
        stopped."""
        p1 = parse_pipeline(self.DESC.format(n=5000))
        ex1 = p1.start()
        time.sleep(0.1)
        assert ex1.drain(timeout=15) is True
        path = str(tmp_path / "warm.json")
        snap = ex1.save_snapshot(path)
        n1 = snap["elements"]["f"]["backend"]["count"]
        assert n1 > 0
        ex1.stop()

        p2 = parse_pipeline(self.DESC.format(n=20))
        ex2 = Executor(p2.compile_plan())
        ex2.restore(Executor.read_snapshot(path))
        ex2.start()
        assert ex2.wait(30), ex2.errors
        vals = [int(f.tensors[0][0]) for f in p2["out"].frames]
        assert vals == list(range(n1, n1 + 20))     # counter continued
        assert ex2.stats()["f"]["frames"] == n1 + 20  # stats carried

    def test_restart_remembers_oom_ceiling(self, tmp_path):
        """A restarted pipeline must not re-discover the OOM boundary by
        OOMing again: the restored governor starts at the safe rung."""
        desc = (
            "tensorsrc name=src dimensions=4 num-frames={n} "
            "pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,oom_above_rows:2 "
            "batching=true max-batch=8 batch-timeout-ms=2 ! "
            "tensor_sink name=out"
        )
        p1 = parse_pipeline(desc.format(n=60))
        ex1 = p1.run(timeout=60)
        assert not ex1.errors
        s1 = ex1.stats()["f"]
        assert s1["oom_events"] >= 1 and s1["batch_ceiling"] == 2
        snap = ex1.snapshot()

        p2 = parse_pipeline(desc.format(n=60))
        ex2 = Executor(p2.compile_plan())
        ex2.restore(snap)
        ex2.start()
        assert ex2.wait(60), ex2.errors
        s2 = ex2.stats()["f"]
        assert len(p2["out"].frames) == 60
        # restored ooms counter carried over, and NO new OOM happened:
        # the remembered ceiling kept every dispatch inside capacity
        assert s2["oom_events"] == s1["oom_events"]
        assert s2["batch_ceiling"] == 2

    def test_restore_before_first_frame_keeps_replica_health(self):
        """Executor.restore on a fresh executor runs before the first
        frame — the replica set builds lazily AFTER that, so the health
        snapshot must stash and apply when the set comes up, never
        silently drop (a restarted pipeline would re-serve the benched
        replica and re-discover its sickness frame by frame)."""
        from nnstreamer_tpu.elements.filter import TensorFilter

        f = TensorFilter(framework="faulty", replicas="2",
                         input="4", inputtype="float32")
        f.state_restore({"replica_set": {"healthy": [False, True],
                                         "failovers": 7}})
        rs = f._ensure_replicas()
        assert [r.healthy for r in rs.replicas] == [False, True]
        assert rs.failovers == 7
        f.stop()

    def test_replica_backend_state_rides_the_snapshot(self):
        """Replicas 1..N-1 are independent stateful backend copies —
        snapshot/restore must carry each one's state, not just replica
        0's (a warm-restarted 2-replica framecounter would otherwise
        alternate a warm and a reset count, round-robin)."""
        from nnstreamer_tpu.elements.filter import TensorFilter

        f1 = TensorFilter(framework="framecounter", replicas="2",
                          input="4", inputtype="float32")
        f1._ensure_replicas()
        f1.backend._count = 5
        f1._replica_backends[1]._count = 3
        snap = f1.state_snapshot()
        f1.stop()
        assert snap["replica_backends"] == [{"count": 3}]

        f2 = TensorFilter(framework="framecounter", replicas="2",
                          input="4", inputtype="float32")
        f2.state_restore(snap)      # before first frame: stashes
        f2._ensure_replicas()       # lazily built set applies it
        assert f2.backend._count == 5
        assert f2._replica_backends[1]._count == 3
        f2.stop()

    def test_restore_section_survives_until_target_builds(self):
        """restore() on a started executor can land before the service
        loop has built the governor (_build_resilience runs inside
        run()): the governor/circuit sections must stay stashed for the
        loop's own post-build apply, never be consumed into the void."""
        p = parse_pipeline(
            "tensorsrc name=src dimensions=4 num-frames=10 ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true batching=true max-batch=8 ! "
            "tensor_sink name=out"
        )
        ex = Executor(p.compile_plan())
        n = next(nd for nd in ex.nodes if nd.name == "f")
        n.restore_state({"frames": 4, "governor": {
            "ceiling": 2, "max": 8, "ooms": 3, "reprobes": 0}})
        n._apply_pending_restore()          # the race: governor not built
        assert n._pending_restore is not None
        assert "governor" in n._pending_restore
        from nnstreamer_tpu.pipeline.device_faults import BucketGovernor

        n.bucket_governor = BucketGovernor([1, 2, 4, 8])
        n._apply_pending_restore()          # the loop's post-build call
        assert n.bucket_governor.ceiling == 2
        assert n.bucket_governor.ooms == 3
        assert n._pending_restore is None

    def test_drain_settle_outlasts_slow_invokes(self):
        """A slow invoke in flight must not masquerade as quiescence:
        the settle window auto-sizes past the slowest observed invoke,
        so after drain() returns True NOTHING is still running and the
        snapshot really is frame-boundary consistent."""
        p = parse_pipeline(
            "tensorsrc name=src dimensions=4 num-frames=400 ! "
            "tensor_chaos name=c delay-ms=80 delay-every-n=1 ! "
            "tensor_sink name=out"
        )
        ex = p.start()
        time.sleep(0.9)            # several delayed invokes observed
        assert ex.drain(timeout=30) is True
        mid = ex.snapshot()["nodes"]["c"]["frames"]
        time.sleep(0.3)            # an in-flight invoke would land here
        assert ex.snapshot()["nodes"]["c"]["frames"] == mid
        assert len(p["out"].frames) == mid
        ex.resume()
        ex.stop()

    def test_drain_timeout_returns_false_and_pipeline_survives(self):
        p = parse_pipeline(
            "tensorsrc name=src dimensions=4 num-frames=60 "
            "pattern=counter ! "
            "tensor_chaos name=c delay-ms=20 delay-every-n=1 ! "
            "tensor_sink name=out"
        )
        ex = p.start()
        # 60 frames * 20 ms can't settle in 0.2 s: drain times out
        assert ex.drain(timeout=0.2) is False
        ex.resume()
        assert ex.wait(60), ex.errors
        assert len(p["out"].frames) == 60


# ----------------------------------------------------------- lint NNS-W112
class TestReplicaLint:
    def test_w112_flags_replicas_without_failover_policy(self):
        from nnstreamer_tpu.analysis.lint import lint

        bare = lint(
            "tensorsrc dimensions=4 num-frames=10 ! "
            "tensor_filter framework=faulty replicas=2 ! tensor_sink"
        )
        assert "NNS-W112" in bare.report.codes

    def test_w112_quiet_with_policy_or_single_instance(self):
        from nnstreamer_tpu.analysis.lint import lint

        with_policy = lint(
            "tensorsrc dimensions=4 num-frames=10 ! "
            "tensor_filter framework=faulty replicas=2 on-error=drop ! "
            "tensor_sink"
        )
        assert "NNS-W112" not in with_policy.report.codes
        single = lint(
            "tensorsrc dimensions=4 num-frames=10 ! "
            "tensor_filter framework=faulty ! tensor_sink"
        )
        assert "NNS-W112" not in single.report.codes


# ------------------------------------------------ persistent compile cache
class TestCompileCache:
    def _reinit(self, monkeypatch, cache_dir):
        from nnstreamer_tpu.backends import jax_backend

        monkeypatch.setenv("NNS_TPU_COMPILE_CACHE_DIR", str(cache_dir))
        monkeypatch.setattr(jax_backend, "_cache_initialized", False)
        jax_backend._init_persistent_cache()

    def test_env_var_enables_cache_dir(self, monkeypatch, tmp_path):
        import jax

        self._reinit(monkeypatch, tmp_path / "xla")
        # the setup appends a per-machine subdir (arch-hostname) so one
        # shared cache dir serves heterogeneous hosts safely
        assert jax.config.jax_compilation_cache_dir.startswith(
            str(tmp_path / "xla")
        )
        # corruption tolerance: a bad entry logs + recompiles, never
        # raises (jax_raise_persistent_cache_errors forced off)
        assert jax.config.jax_raise_persistent_cache_errors is False

    def test_corrupt_cache_entry_never_crashes(self, monkeypatch, tmp_path):
        cache = tmp_path / "xla"
        cache.mkdir()
        # seed the directory with garbage "entries" before any compile
        (cache / "jit_f-deadbeef").write_bytes(b"\x00garbage\xff" * 16)
        (cache / "truncated").write_bytes(b"")
        self._reinit(monkeypatch, cache)
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=10 pattern=counter ! "
            "tensor_filter framework=passthrough ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        assert len(p["out"].frames) == 10


# ------------------------------------------------------------------- soak
@pytest.mark.slow
def test_mixed_device_chaos_soak():
    """Standing chaos soak: OOM pressure on a fused batched segment,
    periodic transient device faults from tensor_chaos under a retry
    policy, and a 2-replica stage losing one replica — 2000 frames,
    exact accounting, sanitizer latch green."""
    p = parse_pipeline(
        "tensorsrc dimensions=4 num-frames=2000 pattern=counter ! "
        "tensor_chaos name=c device-fault-kind=transient "
        "device-fault-every-n=97 on-error=retry retry-max=4 "
        "retry-backoff-ms=0.2 ! "
        "tensor_filter name=rep framework=faulty replicas=2 "
        "replica-unhealthy-after=2 "
        "custom=device_lost_at:40,only_replica:1 ! "
        "tensor_filter name=f framework=faulty "
        "custom=traceable:true,oom_above_rows:4 "
        "batching=true max-batch=16 batch-timeout-ms=1 ! "
        "tensor_sink name=out"
    )
    ex = p.run(timeout=300)
    assert not ex.errors
    s = ex.stats()
    assert len(p["out"].frames) == 2000
    assert ex.totals()["balance"] == 0
    assert s["f"]["oom_events"] >= 1
    assert s["f"]["batch_ceiling"] == 4
    assert s["rep"]["rep_healthy"] == 1
    assert s["rep"]["rep_failovers"] >= 1
    assert s["c"]["error_retries"] >= 20
    vals = [int(f.tensors[0][0]) for f in p["out"].frames]
    assert vals == sorted(vals)
