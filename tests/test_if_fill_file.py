"""tensor_if FILL_WITH_FILE / FILL_WITH_FILE_RPT actions (reference
gsttensor_if.h:79-90 action set)."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.control import TensorIf
from nnstreamer_tpu.tensors.frame import Frame


def _if(action, option, operator="LT"):
    # predicate false for positive averages → else branch runs
    return TensorIf(
        **{"compared-value": "TENSOR_AVERAGE_VALUE", "compared-value-option": "0",
           "operator": operator, "supplied-value": "0",
           "then": "PASSTHROUGH", "else": action, "else-option": option}
    )


def test_fill_with_file_exact(tmp_path):
    path = tmp_path / "fill.bin"
    data = np.arange(12, dtype=np.uint8)
    path.write_bytes(data.tobytes())
    elem = _if("FILL_WITH_FILE", str(path))
    out = elem.process(Frame((np.ones((3, 4), np.uint8),)))
    np.testing.assert_array_equal(
        np.asarray(out.tensors[0]), data.reshape(3, 4)
    )


def test_fill_with_file_zero_pads_short_file(tmp_path):
    path = tmp_path / "short.bin"
    path.write_bytes(b"\x07\x08")
    elem = _if("FILL_WITH_FILE", str(path))
    out = elem.process(Frame((np.ones(5, np.uint8),)))
    np.testing.assert_array_equal(
        np.asarray(out.tensors[0]), [7, 8, 0, 0, 0]
    )


def test_fill_with_file_rpt_cycles(tmp_path):
    path = tmp_path / "cycle.bin"
    path.write_bytes(b"\x01\x02\x03")
    elem = _if("FILL_WITH_FILE_RPT", str(path))
    out = elem.process(Frame((np.zeros(7, np.uint8) + 9,)))
    np.testing.assert_array_equal(
        np.asarray(out.tensors[0]), [1, 2, 3, 1, 2, 3, 1]
    )


def test_fill_with_file_typed(tmp_path):
    """File bytes reinterpret as the tensor dtype."""
    path = tmp_path / "f32.bin"
    vals = np.asarray([1.5, -2.0], np.float32)
    path.write_bytes(vals.tobytes())
    elem = _if("FILL_WITH_FILE", str(path))
    out = elem.process(Frame((np.zeros(2, np.float32),)))
    np.testing.assert_array_equal(np.asarray(out.tensors[0]), vals)


def test_missing_file_raises_cleanly(tmp_path):
    elem = _if("FILL_WITH_FILE", str(tmp_path / "nope.bin"))
    with pytest.raises(RuntimeError, match="cannot read fill file"):
        elem.process(Frame((np.ones(4, np.uint8),)))


def test_missing_option_raises():
    elem = _if("FILL_WITH_FILE", "")
    with pytest.raises(RuntimeError, match="needs then/else-option"):
        elem.process(Frame((np.ones(4, np.uint8),)))
