"""Routing/sync element tests (reference: tests/nnstreamer_mux, _demux,
_merge, _split, nnstreamer_repo*, tensor_if, tensor_rate, _sparse,
nnstreamer_aggregator SSAT suites)."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.base import NegotiationError
from nnstreamer_tpu.elements.sources import AppSrc, TensorSrc
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.elements.routing import (
    Join,
    SyncCombiner,
    TensorDemux,
    TensorMerge,
    TensorMux,
    TensorSplit,
)
from nnstreamer_tpu.elements.windowing import TensorAggregator, TensorRate
from nnstreamer_tpu.elements.control import (
    TensorCrop,
    TensorIf,
    TensorRepoSink,
    TensorRepoSrc,
    register_if_condition,
    unregister_if_condition,
)
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec


def tsrc(dims, n, pattern="counter", rate=None, name=None, types="float32"):
    props = {"num-frames": n, "pattern": pattern}
    if rate:
        props["framerate"] = rate
    return TensorSrc(name=name, dimensions=dims, types=types, **props)


class TestMux:
    def test_two_pads(self):
        a, b = tsrc("2", 3), tsrc("3", 3)
        mux = TensorMux(**{"sync-mode": "nosync"})
        sink = TensorSink()
        p = Pipeline()
        p.link(a, mux).link(b, mux).link(mux, sink)
        p.run(timeout=30)
        assert sink.rendered == 3
        f = sink.frames[0]
        assert f.num_tensors == 2
        assert f.tensors[0].shape == (2,) and f.tensors[1].shape == (3,)

    def test_slowest_policy_drops_fast_pad(self):
        comb = SyncCombiner("slowest", "", 2)
        # pad0 at 10Hz (100ms), pad1 at 20Hz (50ms)
        f = lambda pts: Frame((np.zeros(1),), pts=pts * 1_000_000)
        assert comb.push(1, f(0)) == []
        assert comb.push(1, f(50)) == []
        # base=100 but pad1's head (50) might still be bettered → waits
        assert comb.push(0, f(100)) == []
        # once pad1 shows a successor newer than base, 0 is dropped and 50
        # (closest-not-newer) pairs with 100
        groups = comb.push(1, f(150))
        assert len(groups) == 1
        assert [fr.pts for fr in groups[0]] == [100_000_000, 50_000_000]

    def test_refresh_policy(self):
        """Live SYNC_REFRESH (r4, reference non-waiting collect pads):
        after PTS-merged priming, a new frame on ANY pad emits a group
        immediately, other pads reusing their last frame — a fast pad is
        never gated on a slow one and nothing queues after priming."""
        comb = SyncCombiner("refresh", "", 2)
        f = lambda pts: Frame((np.zeros(1),), pts=pts)
        assert comb.push(0, f(0)) == []  # priming: pad1 not yet delivered
        g = comb.push(1, f(0))
        assert len(g) == 1
        assert [fr.pts for fr in g[0]] == [0, 0]
        # primed: pad1's new frame emits immediately with pad0's stale 0
        g = comb.push(1, f(10))
        assert len(g) == 1
        assert [fr.pts for fr in g[0]] == [0, 10]
        # pad0 delivers pts 5 → emits with pad1's newest (10); refresh is
        # arrival-driven, not timeline-merged, once live
        g = comb.push(0, f(5))
        assert len(g) == 1
        assert [fr.pts for fr in g[0]] == [5, 10]
        # nothing queued after priming: EOS has nothing to release
        assert comb.mark_eos(0) == []

    def test_refresh_fast_pad_never_gated(self):
        """The r3 regression case: a fast pad with a stalled slow peer
        must keep emitting (and must not queue unboundedly)."""
        comb = SyncCombiner("refresh", "", 2)
        f = lambda pts: Frame((np.zeros(1),), pts=pts)
        comb.push(0, f(0))
        comb.push(1, f(0))  # primed
        for k in range(1, 50):  # slow pad silent from here on
            g = comb.push(0, f(k * 10))
            assert len(g) == 1
            assert [fr.pts for fr in g[0]] == [k * 10, 0]
        assert all(not q for q in comb.queues)  # nothing buffered

    def test_refresh_priming_is_pts_merged(self):
        """Pre-priming frames queue and drain deterministically in PTS
        order regardless of arrival interleaving (golden-test guarantee;
        divergence from the reference's arrival-order pre-roll is
        documented in docs/PARITY.md)."""
        comb = SyncCombiner("refresh", "", 2)
        f = lambda pts: Frame((np.zeros(1),), pts=pts)
        # pad0 races ahead with 3 frames before pad1's first
        assert comb.push(0, f(0)) == []
        assert comb.push(0, f(10)) == []
        assert comb.push(0, f(20)) == []
        g = comb.push(1, f(0))
        assert [[fr.pts for fr in grp] for grp in g] == [
            [0, 0], [10, 0], [20, 0]
        ]

    def test_mux_in_description(self):
        p = parse_pipeline(
            "tensorsrc name=s1 dimensions=2 num-frames=2 ! mux.sink_0 "
            "tensorsrc name=s2 dimensions=2 num-frames=2 ! mux.sink_1 "
            "tensor_mux name=mux sync-mode=nosync ! tensor_sink name=out"
        )
        p.run(timeout=30)
        assert p["out"].rendered == 2
        assert p["out"].frames[0].num_tensors == 2


class TestDemux:
    def test_default_split(self):
        src = tsrc("2,3", 2, types="float32,float32")
        demux = TensorDemux()
        s1, s2 = TensorSink(name="d1"), TensorSink(name="d2")
        p = Pipeline()
        p.chain(src, demux)
        p.link(demux, s1, src_pad=0).link(demux, s2, src_pad=1)
        p.run(timeout=30)
        assert s1.frames[0].tensors[0].shape == (2,)
        assert s2.frames[0].tensors[0].shape == (3,)

    def test_tensorpick_reorder_group(self):
        src = tsrc("2,3,4", 1, types="float32,float32,float32")
        demux = TensorDemux(tensorpick="2,0:1")
        s1, s2 = TensorSink(), TensorSink()
        p = Pipeline()
        p.chain(src, demux)
        p.link(demux, s1, src_pad=0).link(demux, s2, src_pad=1)
        p.run(timeout=30)
        assert s1.frames[0].tensors[0].shape == (4,)
        assert s2.frames[0].num_tensors == 2


class TestMergeSplit:
    def test_merge_linear(self):
        a, b = tsrc("2:4", 2), tsrc("2:4", 2)
        merge = TensorMerge(mode="linear", option="1")  # ref dim 1 of 2:4
        sink = TensorSink()
        p = Pipeline()
        p.link(a, merge).link(b, merge).link(merge, sink)
        p.run(timeout=30)
        # dims "2:4" → canonical (4,2); ref dim 1 → canonical axis 0
        assert sink.frames[0].tensors[0].shape == (8, 2)

    def test_split_roundtrip(self):
        src = tsrc("4:2", 1)  # canonical (2,4)
        split = TensorSplit(tensorseg="1:2,3:2")  # canonical (2,1),(2,3) split axis 1
        s1, s2 = TensorSink(), TensorSink()
        p = Pipeline()
        p.chain(src, split)
        p.link(split, s1, src_pad=0).link(split, s2, src_pad=1)
        p.run(timeout=30)
        assert s1.frames[0].tensors[0].shape == (2, 1)
        assert s2.frames[0].tensors[0].shape == (2, 3)

    def test_split_bad_seg(self):
        src = tsrc("4:2", 1)
        split = TensorSplit(tensorseg="1:2,1:2")
        p = Pipeline()
        p.chain(src, split)
        p.link(split, TensorSink(), src_pad=0).link(split, TensorSink(), src_pad=1)
        with pytest.raises(NegotiationError, match="tile"):
            p.negotiate()


class TestJoin:
    def test_forwards_everything(self):
        a, b = tsrc("2", 2), tsrc("2", 3)
        join = Join()
        sink = TensorSink()
        p = Pipeline()
        p.link(a, join).link(b, join).link(join, sink)
        p.run(timeout=30)
        assert sink.rendered == 5


class TestAggregator:
    def test_tumbling_window(self):
        src = tsrc("3:1", 6)  # canonical (1,3)
        agg = TensorAggregator(**{"frames-out": 3})
        sink = TensorSink()
        Pipeline().chain(src, agg, sink).run(timeout=30)
        assert sink.rendered == 2
        assert sink.frames[0].tensors[0].shape == (3, 3)
        np.testing.assert_array_equal(
            np.asarray(sink.frames[0].tensors[0])[:, 0], [0, 1, 2]
        )

    def test_sliding_window(self):
        src = tsrc("1:1", 5)
        agg = TensorAggregator(**{"frames-out": 3, "frames-flush": 1})
        sink = TensorSink()
        Pipeline().chain(src, agg, sink).run(timeout=30)
        assert sink.rendered == 3  # windows [0-2],[1-3],[2-4]
        np.testing.assert_array_equal(
            np.asarray(sink.frames[1].tensors[0]).ravel(), [1, 2, 3]
        )

    def test_frames_dim(self):
        src = tsrc("4:1", 4)  # canonical (1,4)
        agg = TensorAggregator(**{"frames-out": 2, "frames-dim": "0"})
        sink = TensorSink()
        Pipeline().chain(src, agg, sink).run(timeout=30)
        # ref dim 0 = innermost = canonical last axis
        assert sink.frames[0].tensors[0].shape == (1, 8)


class TestRate:
    def test_downsample(self):
        src = tsrc("1", 10, rate="10/1")
        rate = TensorRate(framerate="5/1")
        sink = TensorSink()
        Pipeline().chain(src, rate, sink).run(timeout=30)
        assert sink.rendered == 5
        assert sink.frames[0].duration == 200_000_000

    def test_upsample_duplicates(self):
        src = tsrc("1", 4, rate="5/1")
        rate = TensorRate(framerate="10/1")
        sink = TensorSink()
        Pipeline().chain(src, rate, sink).run(timeout=30)
        assert sink.rendered == 8
        assert rate.dup == 4


class TestIf:
    def test_average_value_branch(self):
        frames = [np.full((4,), v, np.float32) for v in (1.0, 5.0, 2.0, 9.0)]
        src = AppSrc(iterable=[(f,) for f in frames], spec=TensorsSpec.from_strings("4", "float32"))
        tif = TensorIf(
            **{
                "compared-value": "TENSOR_AVERAGE_VALUE",
                "compared-value-option": "0",
                "operator": "GT",
                "supplied-value": "3",
                "then": "PASSTHROUGH",
                "else": "SKIP",
            }
        )
        sink = TensorSink()
        Pipeline().chain(src, tif, sink).run(timeout=30)
        assert sink.rendered == 2
        vals = [float(np.asarray(f.tensors[0])[0]) for f in sink.frames]
        assert vals == [5.0, 9.0]

    def test_fill_zero_and_range(self):
        frames = [np.full((2,), v, np.float32) for v in (1.0, 5.0)]
        src = AppSrc(iterable=[(f,) for f in frames], spec=TensorsSpec.from_strings("2", "float32"))
        tif = TensorIf(
            **{
                "compared-value": "A_VALUE",
                "compared-value-option": "0,0",
                "operator": "RANGE_INCLUSIVE",
                "supplied-value": "0:3",
                "then": "PASSTHROUGH",
                "else": "FILL_ZERO",
            }
        )
        sink = TensorSink()
        Pipeline().chain(src, tif, sink).run(timeout=30)
        np.testing.assert_array_equal(np.asarray(sink.frames[0].tensors[0]), 1.0)
        np.testing.assert_array_equal(np.asarray(sink.frames[1].tensors[0]), 0.0)

    def test_custom_condition(self):
        register_if_condition("even_seq", lambda f: float(np.asarray(f.tensors[0])[0]) % 2 == 0)
        try:
            frames = [np.full((1,), v, np.float32) for v in (0, 1, 2, 3)]
            src = AppSrc(iterable=[(f,) for f in frames],
                         spec=TensorsSpec.from_strings("1", "float32"))
            tif = TensorIf(
                **{"compared-value": "CUSTOM", "compared-value-option": "even_seq"}
            )
            sink = TensorSink()
            Pipeline().chain(src, tif, sink).run(timeout=30)
            assert sink.rendered == 2
        finally:
            unregister_if_condition("even_seq")


class TestCrop:
    def test_crop_by_boxes(self):
        img = np.arange(1 * 8 * 8 * 1, dtype=np.float32).reshape(1, 8, 8, 1)
        boxes = np.array([[0, 0, 4, 4], [2, 2, 3, 3]], np.uint32)
        raw = AppSrc(iterable=[(img,)], spec=TensorsSpec.from_strings("1:8:8:1", "float32"))
        info = AppSrc(iterable=[(boxes,)], spec=TensorsSpec.from_strings("4:2", "uint32"))
        crop = TensorCrop()
        sink = TensorSink()
        p = Pipeline()
        p.link(raw, crop, dst_pad=0).link(info, crop, dst_pad=1).link(crop, sink)
        p.run(timeout=30)
        f = sink.frames[0]
        assert f.num_tensors == 2
        assert f.tensors[0].shape == (1, 4, 4, 1)
        assert f.tensors[1].shape == (1, 3, 3, 1)
        np.testing.assert_array_equal(np.asarray(f.tensors[0]), img[:, 0:4, 0:4, :])


class TestRepo:
    def test_feedback_loop(self):
        # reposrc → scaler(add via custom-easy) → reposink closes the loop
        from nnstreamer_tpu.backends import register_custom_easy, unregister_custom_easy
        from nnstreamer_tpu.elements.filter import TensorFilter

        register_custom_easy("inc", lambda ts: tuple(np.asarray(t) + 1 for t in ts))
        try:
            src = TensorRepoSrc(dimensions="1", types="float32", **{"slot-index": 7})
            filt = TensorFilter(framework="custom-easy", model="inc")
            tee = __import__("nnstreamer_tpu.elements.flow", fromlist=["Tee"]).Tee()
            reposink = TensorRepoSink(**{"slot-index": 7})
            out = TensorSink(**{"max-stored": 10})
            p = Pipeline()
            p.chain(src, filt, tee)
            p.link(tee, reposink)
            p.link(tee, out)
            p.start()
            import time

            deadline = time.monotonic() + 15
            while out.rendered < 5 and time.monotonic() < deadline:
                time.sleep(0.02)
            p.stop()
            vals = [int(np.asarray(f.tensors[0])[0]) for f in out.frames[:5]]
            # state threads through the loop: strictly consecutive increments
            assert len(vals) >= 2
            assert all(b - a == 1 for a, b in zip(vals, vals[1:]))
        finally:
            unregister_custom_easy("inc")
            from nnstreamer_tpu.elements.control import REPO

            REPO.reset(7)


class TestSparseElements:
    def test_enc_dec_roundtrip(self):
        data = np.zeros((4, 4), np.float32)
        data[1, 2] = 7.0
        src = AppSrc(iterable=[(data,)], spec=TensorsSpec.from_strings("4:4", "float32"))
        p = Pipeline()
        from nnstreamer_tpu.elements.sparse_elems import TensorSparseDec, TensorSparseEnc

        enc, dec, sink = TensorSparseEnc(), TensorSparseDec(), TensorSink()
        p.chain(src, enc, dec, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(np.asarray(sink.frames[0].tensors[0]), data)

    def test_enc_compresses(self):
        data = np.zeros((64, 64), np.float32)
        data[0, 0] = 1
        src = AppSrc(iterable=[(data,)], spec=TensorsSpec.from_strings("64:64", "float32"))
        from nnstreamer_tpu.elements.sparse_elems import TensorSparseEnc

        enc, sink = TensorSparseEnc(), TensorSink()
        Pipeline().chain(src, enc, sink).run(timeout=30)
        assert sink.frames[0].tensors[0].nbytes < data.nbytes


def test_tensor_if_repeats_previous_output_not_input():
    """REPEAT_PREVIOUS_FRAME resends the previous *output* frame
    (gsttensor_if.h action semantics)."""
    elem = TensorIf(
        "if0",
        **{
            "compared-value": "A_VALUE",
            "compared-value-option": "0:0:0:0,0",
            "operator": "GE",
            "supplied-value": "10",
            "then": "PASSTHROUGH",
            "else": "REPEAT_PREVIOUS_FRAME",
        },
    )
    a = Frame((np.full((1, 1, 1, 1), 20.0, np.float32),))  # passes
    b = Frame((np.full((1, 1, 1, 1), 5.0, np.float32),))  # fails → repeat A
    c = Frame((np.full((1, 1, 1, 1), 1.0, np.float32),))  # fails → repeat A
    out_a = elem.process(a)
    out_b = elem.process(b)
    out_c = elem.process(c)
    assert float(np.asarray(out_a.tensors[0]).ravel()[0]) == 20.0
    assert float(np.asarray(out_b.tensors[0]).ravel()[0]) == 20.0
    # C must re-emit the last *output* (A), not the failed input B
    assert float(np.asarray(out_c.tensors[0]).ravel()[0]) == 20.0


def test_aggregator_concat_false_stacks():
    agg = TensorAggregator("agg0", **{"frames-out": 3, "concat": "false"})
    spec = TensorsSpec.from_strings("4:2:1", "float32")
    (out_spec,) = agg.negotiate([spec])
    assert out_spec[0].shape == (3, 1, 2, 4)
    outs = []
    for i in range(3):
        r = agg.process(Frame((np.full((1, 2, 4), float(i), np.float32),)))
        if r is not None:
            outs.append(r)
    assert len(outs) == 1
    assert outs[0].tensors[0].shape == (3, 1, 2, 4)
    assert float(np.asarray(outs[0].tensors[0])[2, 0, 0, 0]) == 2.0


def test_basepad_slack_window():
    """basepad's DURATION option pairs frames within the slack window
    instead of waiting (synchronization-policies-at-mux-merge.md)."""
    comb = SyncCombiner("basepad", "0:10", 2)
    base = Frame((np.zeros(1, np.float32),), pts=100)
    near = Frame((np.zeros(1, np.float32),), pts=95)  # within slack 10
    comb.push(1, near)
    groups = comb.push(0, base)
    assert len(groups) == 1
    assert groups[0][0].pts == 100 and groups[0][1].pts == 95
