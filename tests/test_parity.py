"""Output-parity tests: the BASELINE demand that the TPU path matches the
tensorflow-lite CPU path, made falsifiable in-tree.

Strategy (reference parity target: tensor_filter_tensorflow_lite.cc):
- convert the SAME jax model (same seeded weights) to a .tflite flatbuffer
  via jax2tf + TFLiteConverter, execute it with the in-tree tflite backend
  (TFLite/XNNPACK CPU kernels — an engine that shares no code with XLA),
  and compare outputs;
- the tf-free parity tests (golden logits, params overlay, torch
  backend) live in tests/test_parity_tf_free.py so drift detection
  survives a tensorflow-less image.

Skips cleanly when tensorflow is absent (an optional extra, like the
reference's meson-gated subplugins).
"""

import numpy as np
import pytest

import jax

from nnstreamer_tpu.models import zoo
from nnstreamer_tpu.single import SingleShot

tf = pytest.importorskip("tensorflow", reason="tflite parity needs tensorflow")


def _to_tflite(fn, in_shape, in_dtype, path):
    from jax.experimental import jax2tf

    tf_fn = tf.function(
        jax2tf.convert(fn, native_serialization=False),
        input_signature=[tf.TensorSpec(in_shape, in_dtype)],
        autograph=False,
    )
    conv = tf.lite.TFLiteConverter.from_concrete_functions(
        [tf_fn.get_concrete_function()]
    )
    blob = conv.convert()
    with open(path, "wb") as f:
        f.write(blob)
    return path


def _img(shape, seed=0):
    return np.random.default_rng(seed).integers(0, 255, shape, np.uint8)


def test_mobilenet_tflite_parity(tmp_path):
    """Image-labeling config: jax/XLA vs TFLite CPU kernels, same weights."""
    m = zoo.get("mobilenet_v2", size="96", num_classes="16")
    path = _to_tflite(m.fn, (1, 96, 96, 3), tf.uint8, tmp_path / "m.tflite")
    img = _img((1, 96, 96, 3))
    with SingleShot(framework="tflite", model=str(path)) as s:
        tfl = np.asarray(s.invoke(img)[0])
    ref = np.asarray(jax.jit(m.fn)(img))
    np.testing.assert_allclose(tfl, ref, rtol=1e-3, atol=1e-4)


def test_posenet_tflite_parity_multi_output(tmp_path):
    """PoseNet config: 4-tensor output parity across engines."""
    m = zoo.get("posenet")
    path = _to_tflite(m.fn, (1, 257, 257, 3), tf.uint8, tmp_path / "p.tflite")
    img = _img((1, 257, 257, 3), seed=1)
    with SingleShot(framework="tflite", model=str(path)) as s:
        tfl = s.invoke(img)
    refs = jax.jit(m.fn)(img)
    assert len(tfl) == len(refs) == 4
    # TFLite may reorder outputs vs the jax tuple (and two displacement
    # tensors share a shape): greedily match each ref to one unused tflite
    # output that agrees with it
    remaining = [np.asarray(t) for t in tfl]
    for r in refs:
        r = np.asarray(r)
        hit = next(
            (
                i
                for i, t in enumerate(remaining)
                if t.shape == r.shape
                and np.allclose(t, r, rtol=1e-3, atol=1e-4)
            ),
            None,
        )
        assert hit is not None, f"no tflite output matches ref shape {r.shape}"
        remaining.pop(hit)


def test_tflite_framework_autodetect(tmp_path):
    """model=*.tflite auto-selects the tflite backend (reference extension
    detection, tensor_filter_common.c:1155-1218)."""
    m = zoo.get("add", dims="4")
    path = _to_tflite(m.fn, (4,), tf.float32, tmp_path / "add.tflite")
    with SingleShot(model=str(path)) as s:
        (out,) = s.invoke(np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(out), np.full(4, 3.0))
