"""Output-parity tests: the BASELINE demand that the TPU path matches the
tensorflow-lite CPU path, made falsifiable in-tree.

Strategy (reference parity target: tensor_filter_tensorflow_lite.cc):
- convert the SAME jax model (same seeded weights) to a .tflite flatbuffer
  via jax2tf + TFLiteConverter, execute it with the in-tree tflite backend
  (TFLite/XNNPACK CPU kernels — an engine that shares no code with XLA),
  and compare outputs;
- pin golden logits for the flagship model so pure math drift fails even
  where tensorflow isn't installed;
- exercise the params:<npz> overlay (the real-weights loading path) and the
  torch backend (tensor_filter_pytorch.cc slot).

Skips cleanly when tensorflow/torch are absent (they are optional extras,
like the reference's meson-gated subplugins).
"""

import os

import numpy as np
import pytest

import jax

from nnstreamer_tpu.models import zoo
from nnstreamer_tpu.single import SingleShot

tf = pytest.importorskip("tensorflow", reason="tflite parity needs tensorflow")


def _to_tflite(fn, in_shape, in_dtype, path):
    from jax.experimental import jax2tf

    tf_fn = tf.function(
        jax2tf.convert(fn, native_serialization=False),
        input_signature=[tf.TensorSpec(in_shape, in_dtype)],
        autograph=False,
    )
    conv = tf.lite.TFLiteConverter.from_concrete_functions(
        [tf_fn.get_concrete_function()]
    )
    blob = conv.convert()
    with open(path, "wb") as f:
        f.write(blob)
    return path


def _img(shape, seed=0):
    return np.random.default_rng(seed).integers(0, 255, shape, np.uint8)


def test_mobilenet_tflite_parity(tmp_path):
    """Image-labeling config: jax/XLA vs TFLite CPU kernels, same weights."""
    m = zoo.get("mobilenet_v2", size="96", num_classes="16")
    path = _to_tflite(m.fn, (1, 96, 96, 3), tf.uint8, tmp_path / "m.tflite")
    img = _img((1, 96, 96, 3))
    with SingleShot(framework="tflite", model=str(path)) as s:
        tfl = np.asarray(s.invoke(img)[0])
    ref = np.asarray(jax.jit(m.fn)(img))
    np.testing.assert_allclose(tfl, ref, rtol=1e-3, atol=1e-4)


def test_posenet_tflite_parity_multi_output(tmp_path):
    """PoseNet config: 4-tensor output parity across engines."""
    m = zoo.get("posenet")
    path = _to_tflite(m.fn, (1, 257, 257, 3), tf.uint8, tmp_path / "p.tflite")
    img = _img((1, 257, 257, 3), seed=1)
    with SingleShot(framework="tflite", model=str(path)) as s:
        tfl = s.invoke(img)
    refs = jax.jit(m.fn)(img)
    assert len(tfl) == len(refs) == 4
    # TFLite may reorder outputs vs the jax tuple (and two displacement
    # tensors share a shape): greedily match each ref to one unused tflite
    # output that agrees with it
    remaining = [np.asarray(t) for t in tfl]
    for r in refs:
        r = np.asarray(r)
        hit = next(
            (
                i
                for i, t in enumerate(remaining)
                if t.shape == r.shape
                and np.allclose(t, r, rtol=1e-3, atol=1e-4)
            ),
            None,
        )
        assert hit is not None, f"no tflite output matches ref shape {r.shape}"
        remaining.pop(hit)


def test_tflite_framework_autodetect(tmp_path):
    """model=*.tflite auto-selects the tflite backend (reference extension
    detection, tensor_filter_common.c:1155-1218)."""
    m = zoo.get("add", dims="4")
    path = _to_tflite(m.fn, (4,), tf.float32, tmp_path / "add.tflite")
    with SingleShot(model=str(path)) as s:
        (out,) = s.invoke(np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(out), np.full(4, 3.0))


# -- golden logits: drift detection that needs no tensorflow ---------------

# First 8 logits of zoo:mobilenet_v2 (seed 0, size 96, num_classes 16) on
# the deterministic image below — recorded from the float32 CPU path. If
# the model math, init, or preprocessing drifts, this fails.
_GOLDEN_LOGITS = np.array(
    [0.10145831, 3.574911, -1.5670481, 3.147415,
     0.32970887, -1.3878971, 5.6172085, -1.5150919], np.float32
)


def test_mobilenet_golden_logits():
    m = zoo.get("mobilenet_v2", size="96", num_classes="16")
    img = _img((1, 96, 96, 3))
    out = np.asarray(jax.jit(m.fn)(img))[0, :8]
    np.testing.assert_allclose(out, _GOLDEN_LOGITS, rtol=5e-4, atol=5e-5)


# -- params overlay: the real-weights loading path -------------------------

def test_params_npz_overlay(tmp_path):
    base = zoo.get("mobilenet_v2", size="96", num_classes="16")
    leaves, _ = jax.tree_util.tree_flatten(base.params)
    # overlay: replace the classifier weight (largest trailing leaf set)
    # with a known constant and check the output becomes exactly the bias
    # structure it implies
    w_idx = next(
        i for i, l in enumerate(leaves) if tuple(l.shape) == (1280, 16)
    )
    # tree_flatten orders dict keys alphabetically: classifier {"b","w"}
    # flattens bias immediately before weight
    b_idx = w_idx - 1
    assert tuple(leaves[b_idx].shape) == (16,)
    overlay = {
        f"p{w_idx}": np.zeros((1280, 16), np.float32),
        f"p{b_idx}": np.arange(16, dtype=np.float32),
    }
    path = tmp_path / "w.npz"
    np.savez(path, **overlay)
    m = zoo.get(
        "mobilenet_v2", size="96", num_classes="16", params=str(path)
    )
    out = np.asarray(jax.jit(m.fn)(_img((1, 96, 96, 3))))
    np.testing.assert_allclose(out[0], np.arange(16, dtype=np.float32),
                               rtol=1e-5, atol=1e-5)


# -- torch backend (tensor_filter_pytorch.cc slot) -------------------------

def test_torch_backend_roundtrip(tmp_path):
    torch = pytest.importorskip("torch")
    from nnstreamer_tpu.tensors.spec import TensorsSpec

    class Scale(torch.nn.Module):
        def forward(self, x):
            return x * 2.0 + 1.0

    path = str(tmp_path / "scale.pt")
    torch.jit.script(Scale()).save(path)
    spec = TensorsSpec.from_strings("4:2", "float32")
    with SingleShot(framework="torch", model=path, input_spec=spec) as s:
        (out,) = s.invoke(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(out, np.full((2, 4), 3.0))


def test_torch_framework_autodetect(tmp_path):
    torch = pytest.importorskip("torch")
    from nnstreamer_tpu.tensors.spec import TensorsSpec

    class Neg(torch.nn.Module):
        def forward(self, x):
            return -x

    path = str(tmp_path / "neg.pt")
    torch.jit.script(Neg()).save(path)
    spec = TensorsSpec.from_strings("3", "float32")
    with SingleShot(model=path, input_spec=spec) as s:
        (out,) = s.invoke(np.arange(3, dtype=np.float32))
    np.testing.assert_allclose(out, -np.arange(3, dtype=np.float32))
