"""nns-kscope (analysis/kernels.py): hand-computed VMEM residency,
both-ways NNS-W127/W128 on synthetic specs, the NNS-W129 lint pass,
engagement proof (including the forced-fallback drill), the registry
differential sweep, the CLI, and bench.py's pallas-evidence warnings."""

import dataclasses
import importlib.util
import json
import os

import numpy as np
import pytest

from nnstreamer_tpu.analysis import lint
from nnstreamer_tpu.analysis.kernels import (
    analyze,
    analyze_case,
    differential_sweep,
    engage,
)
from nnstreamer_tpu.ops.pallas import registry as kreg
from nnstreamer_tpu.ops.pallas._compat import DISABLE_ENV, pallas_ok

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(blocks, grid, scratch=(), prefetch=(), flops=0, cases=None):
    """A synthetic KernelSpec (NOT registered) for analyzer unit tests."""
    plan = kreg.LaunchPlan(
        grid=grid, blocks=tuple(blocks), scratch=tuple(scratch),
        prefetch=tuple(prefetch), flops=flops,
    )
    return kreg.KernelSpec(
        name="synthetic", module=__name__, ops=("nms",),
        dtypes=("float32",),
        cases=cases or (kreg.ShapeCase("only", {}),),
        plan=lambda params: plan,
        run_case=lambda params: (0.0, 0.0, 1e-6),
        probe=lambda: None,
    )


class TestVmemModel:
    """The residency arithmetic, checked by hand: one varying input
    (double-buffered), one constant input (single-buffered, fetched
    once), the output, scratch, and SMEM prefetch."""

    def _case(self, bound=None):
        # every index map also receives the scalar-prefetch arrays, as
        # under pltpu.PrefetchScalarGridSpec
        blocks = [
            kreg.BlockDesc("x", "in", (32, 128), (8, 128), "float32",
                           lambda i, pos: (i, 0)),
            kreg.BlockDesc("w", "in", (8, 128), (8, 128), "float32",
                           lambda i, pos: (0, 0)),
            kreg.BlockDesc("o", "out", (32, 128), (8, 128), "float32",
                           lambda i, pos: (i, 0)),
        ]
        spec = _spec(
            blocks, grid=(4,),
            scratch=(kreg.ScratchDesc("acc", (8, 128)),),
            prefetch=(kreg.PrefetchDesc(
                "pos", (4,), make=lambda: np.zeros((4,), np.int32)),),
            flops=1000,
        )
        return analyze_case(spec, "only", bound=bound)

    def test_hand_computed_bytes(self):
        r = self._case(bound=1 << 24)
        by = {b.name: b for b in r.blocks}
        # 8*128*4 B per buffer; varying blocks double-buffer
        assert by["x"].block_bytes == 4096
        assert by["x"].buffers == 2 and by["x"].vmem_bytes == 8192
        assert by["w"].buffers == 1 and by["w"].vmem_bytes == 4096
        assert by["o"].buffers == 2
        # fetches by index-map transition: x/o once per step, w once
        assert by["x"].fetches == 4 and by["w"].fetches == 1
        assert r.scratch_bytes == 8 * 128 * 4
        assert r.vmem_bytes == 8192 + 4096 + 8192 + 4096
        assert r.smem_bytes == 4 * 4  # (4,) int32 prefetch lives in SMEM
        assert r.cost.hbm_read_bytes == 4 * 4096 + 4096
        assert r.cost.hbm_write_bytes == 4 * 4096
        assert r.cost.flops == 1000
        assert not r.over_budget and not r.misaligned and not r.hazards

    def test_row_shape(self):
        row = self._case(bound=1 << 24).to_row()
        for key in ("kernel", "case", "grid", "vmem_bytes", "over_budget",
                    "hbm_read_bytes", "flops", "arithmetic_intensity",
                    "misaligned", "hazards"):
            assert key in row
        assert row["over_budget"] is False and row["misaligned"] == []

    def test_w127_fires_when_over_bound_and_only_then(self):
        spec = _spec(
            [kreg.BlockDesc("x", "in", (32, 128), (8, 128), "float32",
                            lambda i: (i, 0))],
            grid=(4,),
        )
        _, rep = analyze([spec], bound=8191)  # 2 buffers x 4096 B > bound
        assert [d.code for d in rep.diagnostics] == ["NNS-W127"]
        _, rep = analyze([spec], bound=8192)
        assert rep.diagnostics == []


class TestAlignment:
    def _one(self, array, block, dtype="float32"):
        spec = _spec(
            [kreg.BlockDesc("x", "in", array, block, dtype,
                            lambda i: tuple(0 for _ in block))],
            grid=(1,),
        )
        return analyze_case(spec, "only", bound=1 << 30)

    def test_lane_misalignment_flagged(self):
        r = self._one((8, 256), (8, 100))
        assert any("lane" in p for p in r.blocks[0].problems)

    def test_sublane_misalignment_by_dtype(self):
        # f32 sublane 8: 5 rows of a 40-row axis misaligns
        assert self._one((40, 128), (5, 128)).misaligned
        # int8 sublane 32: 16 rows misaligns; f32 16 rows is fine
        assert self._one((64, 128), (16, 128), "int8").misaligned
        assert not self._one((64, 128), (16, 128)).misaligned

    def test_whole_axis_and_unit_dims_exempt(self):
        assert not self._one((8, 100), (8, 100)).misaligned
        assert not self._one((8, 100), (1, 100)).misaligned
        bf16 = self._one((32, 256), (16, 128), "bfloat16")
        assert not bf16.misaligned  # bf16 sublane is exactly 16

    def test_w128_fires_on_misalignment_and_only_then(self):
        bad = _spec(
            [kreg.BlockDesc("x", "in", (8, 256), (8, 100), "float32",
                            lambda i: (0, 0))],
            grid=(1,),
        )
        _, rep = analyze([bad], bound=1 << 30)
        assert [d.code for d in rep.diagnostics] == ["NNS-W128"]


class TestIndexMapHazards:
    def test_out_of_bounds_pick(self):
        spec = _spec(
            [kreg.BlockDesc("x", "in", (16, 128), (8, 128), "float32",
                            lambda i: (i, 0))],   # 2 blocks, grid walks 4
            grid=(4,),
        )
        r = analyze_case(spec, "only", bound=1 << 30)
        assert any("outside" in p for p in r.blocks[0].problems)

    def test_arity_mismatch_and_raise(self):
        spec = _spec(
            [
                kreg.BlockDesc("short", "in", (8, 128), (8, 128), "float32",
                               lambda i: (0,)),
                kreg.BlockDesc("boom", "in", (8, 128), (8, 128), "float32",
                               lambda i: (1 // 0, 0)),
            ],
            grid=(2,),
        )
        r = analyze_case(spec, "only", bound=1 << 30)
        by = {b.name: b for b in r.blocks}
        assert any("coordinates" in p for p in by["short"].problems)
        assert any("raised" in p for p in by["boom"].problems)

    def test_prefetch_shape_drift_is_a_hazard(self):
        spec = _spec(
            [kreg.BlockDesc("x", "in", (8, 128), (8, 128), "float32",
                            lambda i, tbl: (0, 0))],
            grid=(1,),
            prefetch=(kreg.PrefetchDesc(
                "tbl", (4,), make=lambda: np.zeros((5,), np.int32)),),
        )
        r = analyze_case(spec, "only", bound=1 << 30)
        assert any("drifts" in h for h in r.hazards)
        _, rep = analyze([spec], bound=1 << 30)
        assert "NNS-W128" in [d.code for d in rep.diagnostics]

    def test_index_maps_get_real_prefetch_values(self):
        """make() values (not zeros) feed the maps — a block-table map
        that would go OOB on zeros stays clean on the real table."""
        spec = _spec(
            [kreg.BlockDesc("kv", "in", (4, 128), (1, 128), "float32",
                            lambda i, tbl: (int(tbl[i]), 0))],
            grid=(2,),
            prefetch=(kreg.PrefetchDesc(
                "tbl", (2,), make=lambda: np.asarray([3, 1], np.int32)),),
        )
        r = analyze_case(spec, "only", bound=1 << 30)
        assert not r.blocks[0].problems and not r.hazards
        assert r.blocks[0].fetches == 2


class TestRegistryAnalysis:
    def test_every_registered_case_is_clean(self):
        """The acceptance invariant: the shipped registry has no
        over-VMEM case, no misaligned tile, no index-map hazard."""
        reports, rep = analyze()
        assert rep.diagnostics == [], rep.render()
        names = {r.kernel for r in reports}
        assert names == set(kreg.names())
        assert len(reports) >= len(names)  # every kernel swept >=1 case

    def test_largest_case_has_headroom_but_not_10x(self):
        """The grid includes realistic near-budget shapes — the analyzer
        is exercised in the regime where the answer matters."""
        reports, _ = analyze()
        biggest = max(r.vmem_bytes for r in reports)
        assert biggest > 4 << 20, "no case within 4x of the 16 MiB bound"

    def test_supports_dtype(self):
        assert kreg.supports_dtype("resize_bilinear", "uint8")
        assert not kreg.supports_dtype("resize_bilinear", np.float64)
        assert kreg.supports_dtype("no_such_kernel", np.float64)


class TestDegrade:
    def test_unsupported_dtype_degrades_with_logged_reason(self, caplog):
        with caplog.at_level("WARNING", logger="nnstreamer_tpu.ops.pallas"):
            ok, reason = pallas_ok("resize_bilinear", "float64")
        assert not ok and "float64" in reason
        assert any("fallback" in r.message for r in caplog.records)

    def test_kill_switch_degrades_everything(self, monkeypatch):
        monkeypatch.setenv(DISABLE_ENV, "1")
        ok, reason = pallas_ok("flash_attention", "float32")
        assert not ok and DISABLE_ENV in reason

    def test_healthy_request_passes(self, monkeypatch):
        monkeypatch.delenv(DISABLE_ENV, raising=False)
        assert pallas_ok("decode_attention", "float32") == (True, "")


class TestEngage:
    def test_healthy_kernel_engages_pallas_only(self, monkeypatch):
        monkeypatch.delenv(DISABLE_ENV, raising=False)
        (row,) = engage([kreg.get("resize_bilinear")])
        assert row["ok"] and row["impls"] == ["pallas"]
        assert row["error"] is None

    def test_forced_fallback_fails_the_row(self, monkeypatch):
        monkeypatch.setenv(DISABLE_ENV, "1")
        (row,) = engage([kreg.get("resize_bilinear")])
        assert not row["ok"] and "pallas" not in row["impls"]


class TestDifferentialSweep:
    def test_one_case_parity(self):
        spec = kreg.get("resize_bilinear")
        narrow = dataclasses.replace(spec, cases=(spec.cases[0],))
        (row,) = differential_sweep([narrow], full=True)
        assert row["ok"], row["error"]
        assert row["max_err"] <= 1e-5

    def test_failure_becomes_a_row_not_a_raise(self):
        spec = _spec(
            [kreg.BlockDesc("x", "in", (8, 128), (8, 128), "float32",
                            lambda i: (0, 0))],
            grid=(1,),
            cases=(kreg.ShapeCase("only", {}, tier1=True),),
        )
        broken = dataclasses.replace(
            spec, run_case=lambda params: (np.ones(3), np.zeros(3), 1e-6)
        )
        (row,) = differential_sweep([broken])
        assert not row["ok"] and "AssertionError" in row["error"]

    @pytest.mark.slow
    def test_full_registry_sweep(self):
        rows = differential_sweep(full=True)
        bad = [r for r in rows if not r["ok"]]
        assert not bad, bad
        assert len(rows) == sum(len(s.cases) for s in kreg.all_specs())


class TestPallasRequestLint:
    """NNS-W129: requested pallas that would silently dispatch jnp."""

    RESIZE = (
        "videotestsrc width=64 height=48 num-buffers=1 ! tensor_converter ! "
        "tensor_transform mode=resize option=24:32 impl=pallas ! tensor_sink"
    )
    LLM = "appsrc dimensions=4 ! tensor_llm_serversink id=lint-probe attn-impl=pallas"

    def test_healthy_requests_are_quiet(self, monkeypatch):
        monkeypatch.delenv(DISABLE_ENV, raising=False)
        assert lint(self.RESIZE).codes == []
        assert lint(self.LLM).codes == []

    def test_unsupported_dtype_flagged(self, monkeypatch):
        monkeypatch.delenv(DISABLE_ENV, raising=False)
        bad = (
            "videotestsrc width=64 height=48 num-buffers=1 ! "
            "tensor_converter ! tensor_transform mode=typecast "
            "option=float64 ! tensor_transform mode=resize option=24:32 "
            "impl=pallas ! tensor_sink"
        )
        result = lint(bad)
        assert result.codes == ["NNS-W129"]
        assert result.exit_code == 1

    def test_mode_with_no_kernel_flagged(self):
        nokernel = (
            "tensorsrc dimensions=4 num-frames=1 ! tensor_transform "
            "mode=typecast option=float32 impl=pallas ! tensor_sink"
        )
        assert lint(nokernel).codes == ["NNS-W129"]

    def test_kill_switch_flags_both_element_kinds(self, monkeypatch):
        monkeypatch.setenv(DISABLE_ENV, "1")
        assert lint(self.RESIZE).codes == ["NNS-W129"]
        assert lint(self.LLM).codes == ["NNS-W129"]


class TestCli:
    def _main(self, argv):
        from nnstreamer_tpu.analysis.kscope_cli import main

        return main(argv)

    def test_json_report_clean(self, capsys):
        assert self._main(["--json", "--kernel", "nms"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["exit_code"] == 0 and data["diagnostics"] == []
        assert {r["kernel"] for r in data["cases"]} == {"nms"}

    def test_unknown_kernel_exits_2(self, capsys):
        assert self._main(["--kernel", "nope"]) == 2
        assert "registered" in capsys.readouterr().err

    def test_strict_promotes_warnings(self, monkeypatch, capsys):
        monkeypatch.setattr(
            "nnstreamer_tpu.analysis.kernels.configured_vmem_bound",
            lambda: 1,
        )
        assert self._main(["--quiet", "--kernel", "nms"]) == 1
        assert self._main(["--quiet", "--strict", "--kernel", "nms"]) == 2
        capsys.readouterr()

    def test_engage_json(self, monkeypatch, capsys):
        monkeypatch.delenv(DISABLE_ENV, raising=False)
        assert self._main(
            ["--engage", "--kernel", "resize_bilinear", "--json"]) == 0
        (row,) = json.loads(capsys.readouterr().out)
        assert row["impls"] == ["pallas"]

    def test_engage_nonzero_on_forced_fallback(self, monkeypatch, capsys):
        monkeypatch.setenv(DISABLE_ENV, "1")
        assert self._main(["--engage", "--kernel", "resize_bilinear"]) == 1
        assert "FELL BACK" in capsys.readouterr().out

    def test_self_check_single_kernel(self, capsys):
        assert self._main(
            ["--self-check", "--kernel", "resize_bilinear", "--quiet"]) == 0
        assert "OK" in capsys.readouterr().out


class TestBenchPallasEvidence:
    """bench.py --gate pallas-tally warnings (pure helper, synthetic
    records)."""

    @pytest.fixture(scope="class")
    def bench(self):
        spec = importlib.util.spec_from_file_location(
            "bench_mod", os.path.join(REPO, "bench.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _rec(self, platform, dispatch):
        cell = {"value": 1.0}
        if dispatch is not None:
            cell["dispatch"] = dispatch
        return {"platform": platform,
                "cells": {"composite_face_fps": cell}}

    def test_fallback_only_tpu_evidence_warns(self, bench):
        warns = bench._pallas_tally_warnings(
            self._rec("tpu", {"crop_and_resize:jnp": 3}))
        assert len(warns) == 1 and "crop_and_resize" in warns[0]
        assert "nns-kscope --engage" in warns[0]

    def test_engaged_or_inapplicable_records_stay_quiet(self, bench):
        assert bench._pallas_tally_warnings(
            self._rec("tpu", {"crop_and_resize:pallas": 2,
                              "crop_and_resize:jnp": 1})) == []
        assert bench._pallas_tally_warnings(
            self._rec("cpu", {"crop_and_resize:jnp": 3})) == []
        # pre-capture-tpu reference: no dispatch evidence either way
        assert bench._pallas_tally_warnings(self._rec("tpu", None)) == []

    def test_gated_cells_reference_real_kernels(self, bench):
        for ops in bench.PALLAS_CELLS.values():
            for op in ops:
                assert kreg.find(op) is not None
