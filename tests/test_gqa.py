"""Grouped-query attention tests: KV heads < query heads across the whole
family — forward, KV-cache decode, serving, speculative, Pallas kernel.

GQA's contract here: the kv head count is carried by wqkv's width alone
(transformer.n_kv_heads_of), so every consumer picks it up with no API
change, and the KV cache shrinks by n_heads/n_kv_heads.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm

H, KV = 8, 2


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(2), vocab=127, d_model=64, n_heads=H,
        n_layers=2, n_kv_heads=KV,
    )


def _toks(n, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(1, 127, (1, n)), jnp.int32
    )


def test_forward_shapes_and_finite(params):
    logits = tfm.apply(params, _toks(10), H)
    assert logits.shape == (1, 10, 127)
    assert np.isfinite(np.asarray(logits)).all()


def test_kv_cache_is_grouped(params):
    ck, cv = dec.init_cache(params, 1, 32, H)
    assert ck.shape == (2, 1, 32, KV, 64 // H)  # KV heads, not H


def test_generate_matches_dense_argmax_chain(params):
    """KV-cache greedy decode == full-forward argmax chain (the same
    invariant test_decode checks for MHA, under GQA)."""
    prompt = _toks(6, 1)
    got = np.asarray(dec.generate(params, prompt, H, 5))[0]
    seq = np.asarray(prompt)[0].tolist()
    for _ in range(5):
        logits = tfm.apply(params, jnp.asarray(seq)[None, :], H)
        nxt = int(jnp.argmax(logits[0, -1]))
        seq.append(nxt)
    np.testing.assert_array_equal(got, seq[-5:])


def test_serving_with_gqa(params):
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    cb = ContinuousBatcher(params, H, n_slots=2, max_len=32, prompt_len=8)
    prompt = np.asarray(_toks(5, 2))[0]
    rid = cb.submit(prompt, 4)
    while cb.result(rid) is None:
        cb.step()
    alone = [int(t) for t in np.asarray(
        dec.generate(params, prompt[None, :], H, 4))[0]]
    assert cb.result(rid) == alone


def test_speculative_with_gqa_draft(params):
    from nnstreamer_tpu.models.speculative import speculative_generate

    draft = tfm.init_params(
        jax.random.PRNGKey(7), vocab=127, d_model=32, n_heads=4,
        n_layers=1, n_kv_heads=1,  # MQA draft
    )
    prompt = _toks(7, 3)
    toks, _ = speculative_generate(
        params, draft, prompt, H, 8, draft_n_heads=4, k=3
    )
    np.testing.assert_array_equal(
        np.asarray(toks), np.asarray(dec.generate(params, prompt, H, 8))
    )


def test_pallas_kernel_reads_grouped_cache(params):
    from nnstreamer_tpu.ops.pallas.decode_attention import decode_attention

    rng = np.random.default_rng(4)
    b, s_len, hd = 2, 32, 16
    q = jnp.asarray(rng.standard_normal((b, 1, H, hd)), jnp.float32)
    ck = jnp.asarray(rng.standard_normal((b, s_len, KV, hd)), jnp.float32)
    cv = jnp.asarray(rng.standard_normal((b, s_len, KV, hd)), jnp.float32)
    pos = jnp.asarray([3, 30], jnp.int32)
    out = decode_attention(q, ck, cv, pos, block_k=16, interpret=True)

    ckr = tfm.repeat_kv(ck, H)
    cvr = tfm.repeat_kv(cv, H)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ckr) / (hd ** 0.5)
    mask = jnp.arange(s_len)[None, :] <= pos[:, None]
    s = jnp.where(mask[:, None, None, :], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), cvr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gqa_with_int8w_weights(params):
    from nnstreamer_tpu.models.quantize import quantize_lm_weights

    qp = quantize_lm_weights(params)
    prompt = _toks(6, 5)
    toks = dec.generate(qp, prompt, H, 4)
    assert np.asarray(toks).shape == (1, 4)
    # cache stays grouped under quantized weights too
    ck, _ = dec.init_cache(qp, 1, 16, H)
    assert ck.shape[3] == KV
