"""Multi-step pump tests (models/serving.py step_pump / spec_pump).

The pumps exist to amortize host↔device round trips: N decode steps (or
R whole speculative rounds) per compiled program, ONE device→host read
per pump. The load-bearing invariant is EXACT stream equality with the
per-token paths — a pump is a batching of the step loop, never a
different decoder. Role-match: the per-buffer invoke loop of
gst/nnstreamer/tensor_filter/tensor_filter.c batched along the token
axis.
"""

import jax
import numpy as np
import pytest

from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

N_HEADS = 4


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(7), vocab=257, d_model=64, n_heads=N_HEADS,
        n_layers=2,
    )


@pytest.fixture(scope="module")
def draft_params():
    return tfm.init_params(
        jax.random.PRNGKey(11), vocab=257, d_model=32, n_heads=N_HEADS,
        n_layers=1,
    )


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(1, 257, (n,)).astype(np.int32)


def _rep_prompt(n, seed, period=6):
    """Repetitive prompt: the n-gram miner's best case."""
    base = np.random.default_rng(seed).integers(1, 257, (period,))
    return np.tile(base, -(-n // period))[:n].astype(np.int32)


def _drain_steps(cb, rids):
    while any(cb.result(r) is None for r in rids):
        cb.step()


def _drain_pump(cb, rids, n):
    while any(cb.result(r) is None for r in rids):
        cb.step_pump(n)


def _drain_spec_pump(cb, rids, rounds, k, ngram=2):
    while any(cb.result(r) is None for r in rids):
        cb.spec_pump(rounds=rounds, k=k, ngram=ngram)


def _tokens(cb, rids):
    return [cb.result(r) for r in rids]


def _twin(params, **kw):
    return ContinuousBatcher(
        params, N_HEADS, n_slots=4, max_len=96, prompt_len=16, **kw
    )


@pytest.mark.parametrize("n", [1, 3, 8, 64])
def test_step_pump_matches_per_token_steps(params, n):
    """A pump of n is exactly n per-token steps, for any n (including
    n past every budget — idle lanes emit -1 and are dropped)."""
    prompts = [_prompt(5 + s, 100 + s) for s in range(4)]
    a, b = _twin(params), _twin(params)
    ra = [a.submit(p, 9) for p in prompts]
    rb = [b.submit(p, 9) for p in prompts]
    _drain_steps(a, ra)
    _drain_pump(b, rb, n)
    assert _tokens(a, ra) == _tokens(b, rb)


def test_step_pump_stop_token_deactivates_on_device(params):
    """The stop token ends a stream INSIDE the scan — tokens after it
    in the same pump are discarded, exactly like per-token stepping."""
    prompts = [_prompt(5, 7)]
    a, b = _twin(params), _twin(params)
    # pick the 3rd greedy token as the stop token so it triggers mid-pump
    ra = [a.submit(prompts[0], 12)]
    _drain_steps(a, ra)
    stop = _tokens(a, ra)[0][2]
    a2, b2 = _twin(params), _twin(params)
    r2 = [a2.submit(prompts[0], 12, stop_token=stop)]
    r3 = [b2.submit(prompts[0], 12, stop_token=stop)]
    _drain_steps(a2, r2)
    _drain_pump(b2, r3, 8)
    assert _tokens(a2, r2) == _tokens(b2, r3)
    assert _tokens(b2, r3)[0][-1] == stop


def test_step_pump_staggered_admissions_join_next_pump(params):
    """Requests submitted between pumps join at the next pump and still
    produce their solo-greedy stream."""
    a, b = _twin(params), _twin(params)
    p0, p1 = _prompt(5, 1), _prompt(7, 2)
    ra0, rb0 = a.submit(p0, 10), b.submit(p0, 10)
    for _ in range(2):
        a.step()
    b.step_pump(2)
    ra1, rb1 = a.submit(p1, 6), b.submit(p1, 6)
    _drain_steps(a, [ra0, ra1])
    _drain_pump(b, [rb0, rb1], 4)
    assert _tokens(a, [ra0, ra1]) == _tokens(b, [rb0, rb1])


def test_step_pump_sampling_stream_deterministic(params):
    """Sampling slots: the per-(seed, position) key discipline makes a
    pumped stream identical to the per-token stream."""
    p = _prompt(6, 3)
    a, b = _twin(params), _twin(params)
    ra = a.submit(p, 8, temperature=0.8, top_k=40, seed=5)
    rb = b.submit(p, 8, temperature=0.8, top_k=40, seed=5)
    _drain_steps(a, [ra])
    _drain_pump(b, [rb], 8)
    assert a.result(ra) == b.result(rb)


@pytest.mark.parametrize("rounds", [1, 2, 4])
def test_spec_pump_greedy_exact(params, rounds):
    """Greedy speculation is exact by construction: spec_pump streams
    equal plain per-token streams whatever the round batching."""
    prompts = [_rep_prompt(12, 50 + s) for s in range(4)]
    a, b = _twin(params), _twin(params)
    ra = [a.submit(p, 12) for p in prompts]
    rb = [b.submit(p, 12) for p in prompts]
    _drain_steps(a, ra)
    _drain_spec_pump(b, rb, rounds, k=4)
    assert _tokens(a, ra) == _tokens(b, rb)
    st = b.stats()
    assert st["spec_rounds"] >= rounds


def test_spec_pump_acceptance_telemetry_rides_packed_readback(params):
    """Acceptance counters update from the pump's packed vector — no
    separate transfer — and a repetitive context actually accepts."""
    p = _rep_prompt(24, 9, period=4)
    b = _twin(params)
    rb = b.submit(p, 16)
    _drain_spec_pump(b, [rb], 4, k=4, ngram=1)
    st = b.stats()
    assert st["spec_columns"] > 0
    assert st["spec_accepted_tokens"] >= 0
    assert st["tokens_per_step"] >= 1.0  # never worse than plain steps


def test_spec_pump_sampling_exact_vs_host_rounds(params):
    """Sampling speculation: device-mined proposals differ from host
    mining only in WHERE the mining ran — acceptance is the same
    program, so a pumped sampling stream must remain a valid
    deterministic stream (same seed ⇒ same stream on repeat runs)."""
    p = _rep_prompt(16, 21, period=5)
    outs = []
    for _ in range(2):
        b = _twin(params)
        rb = b.submit(p, 10, temperature=0.7, seed=3)
        _drain_spec_pump(b, [rb], 3, k=3, ngram=1)
        outs.append(b.result(rb))
    assert outs[0] == outs[1]


def test_spec_pump_windowed_ring_exact(params):
    """Windowed ring + device n-gram proposals: streams equal the
    windowed per-token stream (verify-then-commit never clobbers the
    ring with rejected columns)."""
    prompts = [_rep_prompt(10, 70 + s) for s in range(3)]
    kw = dict(windowed=True, max_len=32, prompt_len=16)
    a = ContinuousBatcher(params, N_HEADS, n_slots=4, **kw)
    b = ContinuousBatcher(params, N_HEADS, n_slots=4, **kw)
    ra = [a.submit(p, 10) for p in prompts]
    rb = [b.submit(p, 10) for p in prompts]
    _drain_steps(a, ra)
    _drain_spec_pump(b, rb, 3, k=3)
    assert _tokens(a, ra) == _tokens(b, rb)


def test_spec_pump_draft_inscan_exact(params, draft_params):
    """Draft-model proposals mined IN-SCAN (k draft steps per round
    inside the pump program) produce the plain greedy stream."""
    prompts = [_prompt(8, 80 + s) for s in range(4)]
    a = _twin(params)
    b = _twin(params, draft_params=draft_params, draft_n_heads=N_HEADS)
    ra = [a.submit(p, 10) for p in prompts]
    rb = [b.submit(p, 10) for p in prompts]
    _drain_steps(a, ra)
    _drain_spec_pump(b, rb, 3, k=3)
    assert _tokens(a, ra) == _tokens(b, rb)
    assert b.stats()["spec_columns"] > 0  # a draft always proposes


def test_step_pump_draft_cache_stays_synced(params, draft_params):
    """step_pump on a draft batcher advances the draft cache in-scan
    (the pump form of advance_one): a spec_pump AFTER a step_pump still
    produces the exact stream — no holes in the draft cache."""
    p = _prompt(6, 31)
    a = _twin(params)
    b = _twin(params, draft_params=draft_params, draft_n_heads=N_HEADS)
    ra = a.submit(p, 12)
    rb = b.submit(p, 12)
    _drain_steps(a, [ra])
    b.step_pump(4)  # first 4 tokens via plain pump
    _drain_spec_pump(b, [rb], 2, k=3)  # rest speculated
    assert a.result(ra) == b.result(rb)


def test_pump_int8_cache_matches_per_token(params):
    """int8 KV cache + pump: quantization happens inside the scan just
    as inside the step — streams match the int8 per-token path."""
    p = _prompt(6, 41)
    a = _twin(params, cache_dtype="int8")
    b = _twin(params, cache_dtype="int8")
    ra = a.submit(p, 8)
    rb = b.submit(p, 8)
    _drain_steps(a, [ra])
    _drain_pump(b, [rb], 8)
    assert a.result(ra) == b.result(rb)


def test_pump_mesh_sharded_slots_match_unsharded(params):
    """Pumps under a slot-sharded mesh (SPMD decode) equal the
    unsharded pumped streams."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axes=("dp",))
    prompts = [_prompt(5 + s, 90 + s) for s in range(8)]
    outs = {}
    for label, kw in (("plain", {}), ("mesh", dict(mesh=mesh))):
        cb = ContinuousBatcher(
            params, N_HEADS, n_slots=8, max_len=64, prompt_len=16, **kw
        )
        rids = [cb.submit(p, 8) for p in prompts]
        _drain_pump(cb, rids, 8)
        outs[label] = _tokens(cb, rids)
    assert outs["plain"] == outs["mesh"]


def test_pump_mesh_pallas_spec_pump_compose(params):
    """The full stack in one server: mesh + pallas step pumps and a
    spec pump on the same batcher keep the exact greedy stream."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axes=("dp",))
    prompts = [_rep_prompt(10, 60 + s) for s in range(8)]
    a = ContinuousBatcher(
        params, N_HEADS, n_slots=8, max_len=64, prompt_len=16
    )
    b = ContinuousBatcher(
        params, N_HEADS, n_slots=8, max_len=64, prompt_len=16,
        mesh=mesh, attn_impl="pallas",
    )
    ra = [a.submit(p, 8) for p in prompts]
    rb = [b.submit(p, 8) for p in prompts]
    _drain_steps(a, ra)
    while any(b.result(r) is None for r in rb):
        b.step_pump(2)
        b.spec_pump(rounds=2, k=3)
    assert _tokens(a, ra) == _tokens(b, rb)


def test_spec_pump_room_clamp_falls_back_near_max_len(params):
    """When the cache is nearly full a wide pump cannot fit: spec_pump
    must clamp rounds / fall back to the shrinking-k host round and
    still finish the stream exactly."""
    p = _prompt(12, 55)
    a = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=32,
                          prompt_len=16)
    b = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=32,
                          prompt_len=16)
    ra = a.submit(p, 20)  # 12 + 20 = max_len exactly
    rb = b.submit(p, 20)
    _drain_steps(a, [ra])
    _drain_spec_pump(b, [rb], 8, k=4)
    assert a.result(ra) == b.result(rb)


def test_spec_pump_budget_tail_stays_on_warm_programs(params):
    """Regression for the BENCH_CPU_FULL_r05 spec×cb throughput
    collapse (8.0/4.8 vs 37.5 tok/s plain): ``rounds`` is a STATIC
    scan length, so clamping it by live request budgets compiled a
    fresh XLA program for every budget tail — warm-up built rounds=2/1
    programs, the measured drain then compiled rounds=4 inside the
    timed region and re-compiled its way down a 4→2→1 ladder as
    budgets shrank. Pin: after the first pump, draining uneven budget
    tails runs entirely on warm programs (zero new compiles), and per
    program launch spec emits at least as many tokens as a plain pump
    of the same depth — the "spec×cb ≥ plain-cb" cliff guard in
    deterministic launch-count terms rather than flaky wall-clock."""
    b = _twin(params)
    prompts = [_rep_prompt(12, 80 + s, period=4) for s in range(3)]
    # uneven budgets: with the bug, remaining.max() walks 11→…→1 and
    # each power-of-two floor below 4 is a brand-new program
    rids = [b.submit(p, 5 + 3 * s) for s, p in enumerate(prompts)]
    b.spec_pump(rounds=4, k=4, ngram=1)
    warm = b._spec_pump_greedy._cache_size()
    spec_launches = 1
    while any(b.result(r) is None for r in rids):
        b.spec_pump(rounds=4, k=4, ngram=1)
        spec_launches += 1
    assert b._spec_pump_greedy._cache_size() == warm, (
        "budget tail recompiled spec_pump: the static scan length must "
        "not depend on live budgets (slots idle out on device)"
    )
    assert warm == 1  # one (rounds=4, k=4) greedy program, ever
    # spec×cb ≥ plain-cb per launch: a spec pump certifies ≥ rounds
    # tokens per active stream (1 per round even at zero acceptance),
    # a plain pump of depth n emits exactly n — so spec must never
    # need more launches than plain step_pump(4) on the same load.
    a = _twin(params)
    ra = [a.submit(p, 5 + 3 * s) for s, p in enumerate(prompts)]
    plain_launches = 0
    while any(a.result(r) is None for r in ra):
        a.step_pump(4)
        plain_launches += 1
    assert spec_launches <= plain_launches
    assert _tokens(a, ra) == _tokens(b, rids)  # and byte-identical
    assert b.stats()["spec_accepted_tokens"] > 0  # non-trivial run


def test_steady_pumps_ship_no_host_state(params):
    """Regression beside the no-new-compiles pin above: the per-slot
    budget/stop/active pump state is CARRIED on device between pumps
    (the scan already computes next-pump values), so a steady pump-only
    drain must rebuild + re-ship host state ZERO times. It used to be
    recomputed and H2D-shipped on EVERY pump even when no slot changed.
    The cache invalidates exactly on submit (admission) and finish —
    both pinned here; jax's transfer guard additionally proves the
    steady-state pump launch performs no host→device transfer at all."""
    b = _twin(params)
    rids = [b.submit(_prompt(5 + s, 130 + s), 40) for s in range(3)]
    b.step_pump(4)  # admissions applied, state shipped once
    builds0 = b._host_state_builds
    with jax.transfer_guard_host_to_device("disallow"):
        for _ in range(3):
            b.step_pump(4)
    assert b._host_state_builds == builds0, (
        "steady pumps rebuilt host pump state"
    )
    # admission invalidates: exactly one rebuild at the next pump
    rids.append(b.submit(_prompt(4, 140), 30))
    b.step_pump(4)
    assert b._host_state_builds == builds0 + 1
    # a finishing request invalidates too (slot leaves the batch)
    b2 = _twin(params)
    r2 = [b2.submit(_prompt(5, 141), 3), b2.submit(_prompt(6, 142), 40)]
    b2.step_pump(4)  # request 0 finishes inside this pump
    n = b2._host_state_builds
    b2.step_pump(4)
    assert b2._host_state_builds == n + 1
    # and the carried state stays EXACT: drain to the per-token streams
    a = _twin(params)
    ra = [a.submit(_prompt(5 + s, 130 + s), 40) for s in range(3)]
    ra.append(a.submit(_prompt(4, 140), 30))
    _drain_steps(a, ra)
    _drain_pump(b, rids, 4)
    assert _tokens(a, ra) == _tokens(b, rids)


def test_ngram_device_proposer_mines_recent_context(params):
    """device_ngram_propose finds the most recent suffix match and
    proposes its continuation; -1 where nothing matches."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models.serving import device_ngram_propose

    hist = jnp.asarray(np.array([
        [5, 6, 7, 5, 6, 9, 5, 6] + [-1] * 8,   # pending 6 at pos 7
        [1, 2, 3, 4, 5, 6, 7, 8] + [-1] * 8,   # no repeat: nothing
    ], np.int32))
    pos = jnp.asarray(np.array([7, 7], np.int32))
    props = np.asarray(device_ngram_propose(hist, pos, k=3, g=2))
    # slot 0: latest earlier "5 6" ends at j=4 → proposes hist[5], hist[6]
    assert props[0].tolist() == [9, 5]
    assert props[1].tolist() == [-1, -1]


def test_spec_pump_windowed_ring_wrap_mines_exactly(params):
    """A windowed stream that OUTRUNS the ring (prompt+budget > W):
    hist mirrors the KV ring's a % H layout, so post-wrap device
    n-gram mining stays exact — streams equal the per-token windowed
    reference, and the repetitive workload still accepts proposals
    after the wrap."""
    kw = dict(windowed=True, max_len=16, prompt_len=16)
    a = ContinuousBatcher(params, N_HEADS, n_slots=2, **kw)
    b = ContinuousBatcher(params, N_HEADS, n_slots=2, **kw)
    p = _rep_prompt(12, 77, period=3)
    ra = a.submit(p, 24)  # 12 + 24 >> W=16: wraps mid-generation
    rb = b.submit(p, 24)
    _drain_steps(a, [ra])
    _drain_spec_pump(b, [rb], 3, k=3, ngram=1)
    assert a.result(ra) == b.result(rb)
    st = b.stats()
    # ACCEPTED > 0 pins the exact mining — garbage proposals from a
    # broken unroll would be offered (columns > 0) yet all rejected
    assert st["spec_accepted_tokens"] > 0


def test_ngram_device_proposer_wrap_unrolls_ring():
    """wrap=True: the miner unrolls the ring (token at absolute pos a
    lives at a % H) into stream order before matching — pinned with a
    hand-built wrapped history so a broken unroll cannot hide behind
    verification (wrong proposals are rejected, not exposed)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models.serving import device_ngram_propose

    # stream (period 5): [1,2,3,5,6]*2 + [1]; abs positions 0..10,
    # H=8 ⇒ ring cell a%8; pending token 1 at abs pos 10 (cell 2)
    hist = jnp.asarray(np.array(
        [[5, 6, 1, 5, 6, 1, 2, 3]], np.int32
    ))
    pos = jnp.asarray(np.array([10], np.int32))
    props = np.asarray(
        device_ngram_propose(hist, pos, k=3, g=2, wrap=True)
    )
    # last H tokens in order: [5,6,1,2,3,5,6,1]; suffix 2-gram (6,1)
    # recurs ending at index 2 → proposals are the following [2, 3]
    assert props[0].tolist() == [2, 3]
    # without wrap the same ring bytes mine garbage — the unroll is
    # what makes post-wrap mining exact
    raw = np.asarray(device_ngram_propose(hist, pos, k=3, g=2))
    assert raw[0].tolist() != [2, 3]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_pump_schedule_invariance(params, seed):
    """Greedy streams are SCHEDULE-INVARIANT: whatever interleaving of
    step / step_pump(n) / spec_pump(rounds, k) drains the batch —
    with staggered random submissions between operations — every
    request's tokens equal the plain per-token reference. The fuzz net
    over the whole pump surface."""
    rng = np.random.default_rng(seed)
    a = _twin(params)   # reference: plain steps only
    b = _twin(params)   # fuzzed: random pump schedule
    prompts = [
        _rep_prompt(int(rng.integers(4, 14)), 200 + seed * 10 + i,
                    period=int(rng.integers(2, 6)))
        for i in range(6)
    ]
    budgets = [int(rng.integers(2, 12)) for _ in prompts]
    ra, rb = [], []
    queue = list(zip(prompts, budgets))

    def submit_some(cb, rids, k):
        for _ in range(k):
            if len(rids) < len(prompts):
                p, n = queue[len(rids)]
                rid = cb.submit(p, n)
                if rid is None:
                    break
                rids.append(rid)

    submit_some(a, ra, 2)
    submit_some(b, rb, 2)
    while len(ra) < len(prompts) or any(
        a.result(r) is None for r in ra
    ):
        a.step()
        submit_some(a, ra, 1)
    ops = ("step", "pump", "spec")
    while len(rb) < len(prompts) or any(
        b.result(r) is None for r in rb
    ):
        op = ops[int(rng.integers(0, 3))]
        if op == "step":
            b.step()
        elif op == "pump":
            b.step_pump(int(rng.integers(1, 7)))
        else:
            b.spec_pump(rounds=int(rng.integers(1, 4)),
                        k=int(rng.integers(2, 5)),
                        ngram=int(rng.integers(1, 3)))
        submit_some(b, rb, int(rng.integers(0, 3)))
    assert _tokens(a, ra) == _tokens(b, rb)


@pytest.mark.parametrize("seed", [10, 11, 12])
def test_random_config_matrix_pump_equivalence(params, draft_params,
                                               seed):
    """Config-matrix fuzz: a random serving configuration (windowed ×
    int8 cache × pallas attention × draft model × per-request
    sampling) drained by pumps equals the SAME configuration drained
    per-token. Complements the explicit matrix tests with random
    combinations."""
    rng = np.random.default_rng(seed)
    kw = {}
    if rng.integers(0, 2):
        kw.update(windowed=True, max_len=32, prompt_len=16)
    else:
        kw.update(max_len=96, prompt_len=16)
    if rng.integers(0, 2):
        kw["cache_dtype"] = "int8"
    if rng.integers(0, 2):
        kw["attn_impl"] = "pallas"
    if rng.integers(0, 2):
        kw.update(draft_params=draft_params, draft_n_heads=N_HEADS)

    def mk():
        return ContinuousBatcher(params, N_HEADS, n_slots=2, **kw)

    a, b = mk(), mk()
    subs = []
    for i in range(3):
        p = _rep_prompt(int(rng.integers(4, 12)), 300 + seed * 7 + i,
                        period=int(rng.integers(2, 5)))
        s_kw = {}
        if rng.integers(0, 2):
            s_kw = dict(temperature=0.7, top_k=30, seed=int(i))
        subs.append((p, int(rng.integers(2, 9)), s_kw))
    # spec rounds on SAMPLING slots are distribution-exact, not
    # byte-identical (spec_accept keys per (seed, pos, draw)) — the
    # byte-equality fuzz may only use spec_pump on greedy workloads
    any_sampling = any(s for _, _, s in subs)
    ra = [a.submit(p, n, **s) for p, n, s in subs[:2]]
    rb = [b.submit(p, n, **s) for p, n, s in subs[:2]]
    while any(a.result(r) is None for r in ra):
        a.step()
    while any(b.result(r) is None for r in rb):
        if any_sampling or rng.integers(0, 2):
            b.step_pump(int(rng.integers(1, 6)))
        else:
            b.spec_pump(rounds=2, k=3, ngram=1)
    # late third submission joins a half-drained batch on both sides
    p, n, s_kw = subs[2]
    ra.append(a.submit(p, n, **s_kw))
    rb.append(b.submit(p, n, **s_kw))
    while any(a.result(r) is None for r in ra):
        a.step()
    while any(b.result(r) is None for r in rb):
        b.step_pump(3)
    assert _tokens(a, ra) == _tokens(b, rb)
