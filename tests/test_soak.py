"""Pipeline soak: sustained unbounded flow with bounded RSS and exact
frame accounting (VERDICT r4 #6).

The reference runs GStreamer pipelines indefinitely; the executor's
longest prior exercised run was seconds. This drives videotestsrc
(num-frames=-1) through converter ! filter ! rate ! decoder ! sink for
NNS_SOAK_SECONDS (default 60), asserting:

- RSS stays bounded after warmup (leaks in _Chan parking or Frame
  recycling would show as monotonic growth),
- the pipeline never deadlocks (rendered count strictly advances every
  sample window),
- every produced frame is accounted for: rendered + dropped-with-reason
  + bounded in-flight at forced stop (Executor.totals()).

Skip with ``-m "not soak"`` (or shrink via NNS_SOAK_SECONDS) when the
60 s wall cost is unwanted.
"""

import os
import time

import pytest

psutil = pytest.importorskip("psutil")


@pytest.mark.soak
def test_pipeline_soak_bounded_rss_and_exact_accounting():
    from nnstreamer_tpu.pipeline.parse import parse_pipeline

    dur = float(os.environ.get("NNS_SOAK_SECONDS", "60"))
    p = parse_pipeline(
        "videotestsrc pattern=gradient num-frames=-1 width=32 height=32 "
        "framerate=30/1 ! "
        "tensor_converter ! tensor_filter framework=passthrough ! "
        "tensor_rate framerate=15/1 ! "  # PTS dup/drop: ~half dropped
        "tensor_decoder mode=direct_video ! fakesink name=out"
    )
    ex = p.start()
    proc = psutil.Process()
    sink = p["out"]

    # warmup: let jit/compile/thread-spinup allocations land before the
    # leak baseline is taken
    t_end = time.monotonic() + dur
    time.sleep(min(10.0, dur / 3))
    rss0 = proc.memory_info().rss
    rendered_last = sink.rendered
    samples = []
    while time.monotonic() < t_end:
        time.sleep(5.0)
        samples.append(proc.memory_info().rss)
        assert not ex.errors, ex.errors
        # liveness: strictly advancing render count = no deadlock
        now_rendered = sink.rendered
        assert now_rendered > rendered_last, (
            f"pipeline stalled at {now_rendered} frames"
        )
        rendered_last = now_rendered
    p.stop()

    totals = ex.totals()
    assert totals["produced"] > 25 * dur  # ~30 fps source actually ran
    drops = sum(totals["dropped"].values())
    assert totals["dropped"].get("rate-drop", 0) > 0  # the rate did drop
    # exact accounting at forced stop: produced + dup = rendered + drops
    # + in-flight, where in-flight is bounded by the channel capacities
    in_flight_cap = sum(
        ch._max for n in ex.nodes for ch in n.in_queues
    ) + len(ex.nodes)  # +1 per node for the frame held in-hand
    balance = totals["balance"]
    assert 0 <= balance <= in_flight_cap, (totals, in_flight_cap)
    assert totals["rendered"] + drops > 0.8 * totals["produced"]

    # RSS bound: steady-state growth after warmup stays under 64 MiB
    # (flat in practice; the bound leaves headroom for allocator noise)
    rss_growth = max(samples) - rss0
    assert rss_growth < 64 * 1024 * 1024, (
        f"RSS grew {rss_growth / 1e6:.1f} MB over the soak"
    )

@pytest.mark.soak
def test_serving_pump_soak_bounded_rss():
    """Serving soak on the PUMP hot path: a continuous stream of
    requests drained via step_pump/spec_pump for NNS_SOAK_SECONDS,
    asserting bounded RSS (leaks in the donated-buffer chains, hist
    staging, or pending-insert queue would grow monotonically), live
    progress every window, and exact request accounting (submitted =
    finished + in-flight at stop)."""
    import jax
    import numpy as np

    from nnstreamer_tpu.models import transformer as tfm
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    dur = float(os.environ.get("NNS_SOAK_SECONDS", "60"))
    params = tfm.init_params(
        jax.random.PRNGKey(0), vocab=211, d_model=32, n_heads=2,
        n_layers=2,
    )
    cb = ContinuousBatcher(params, 2, n_slots=4, max_len=64,
                           prompt_len=16)
    rng = np.random.default_rng(0)
    proc = psutil.Process()

    submitted = finished = 0
    live = {}
    t_end = time.monotonic() + dur
    warm_until = time.monotonic() + min(10.0, dur / 3)
    rss0 = None
    samples = []
    last_sample = time.monotonic()
    tokens_last = 0
    spin = 0
    while time.monotonic() < t_end:
        while len(live) < 4:
            rid = cb.submit(
                rng.integers(1, 211, (int(rng.integers(3, 14)),)),
                int(rng.integers(2, 10)),
            )
            if rid is None:
                break
            live[rid] = True
            submitted += 1
        spin += 1
        if spin % 3:
            cb.step_pump(4)
        else:
            cb.spec_pump(rounds=2, k=3, ngram=1)
        for rid in [r for r in live if cb.result(r) is not None]:
            del live[rid]
            finished += 1
        now = time.monotonic()
        if rss0 is None and now >= warm_until:
            rss0 = proc.memory_info().rss
            tokens_last = cb.stats()["tokens_emitted"]
        elif rss0 is not None and now - last_sample >= 5.0:
            last_sample = now
            samples.append(proc.memory_info().rss)
            tok = cb.stats()["tokens_emitted"]
            assert tok > tokens_last, "serving stalled"
            tokens_last = tok

    assert submitted == finished + len(live)
    assert finished > 0 and cb.stats()["tokens_emitted"] > 0
    if rss0 is not None and samples:
        growth = max(samples) - rss0
        assert growth < 64 * 1024 * 1024, (
            f"RSS grew {growth / 1e6:.1f} MB over the serving soak"
        )
