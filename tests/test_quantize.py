"""int8 post-training quantization tests (models/quantize.py).

Reference slot: mobilenet_v2_1.0_224_quant.tflite executed by TFLite int8
kernels (ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc).
Here the quantized model is an XLA program whose 1x1 convs contract
s8 x s8 -> s32 (the MXU int8 path).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import quantize as qz
from nnstreamer_tpu.models import zoo


def _img(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 255, shape, np.uint8)
    )


@pytest.fixture(scope="module")
def pair():
    mq = zoo.get("mobilenet_v2", quantize="int8", size="96", num_classes="16")
    mf = zoo.get("mobilenet_v2", size="96", num_classes="16")
    return jax.jit(mq.fn), jax.jit(mf.fn), mq


def test_int8_close_to_fp32(pair):
    q_fn, f_fn, _ = pair
    for seed in range(3):
        img = _img((1, 96, 96, 3), seed)
        ql = np.asarray(q_fn(img))
        fl = np.asarray(f_fn(img))
        cos = (ql * fl).sum() / (np.linalg.norm(ql) * np.linalg.norm(fl))
        assert cos > 0.98, f"seed {seed}: cosine {cos}"
        assert ql.argmax() == fl.argmax(), f"seed {seed}: top-1 drifted"


def test_quantized_path_is_int8(pair):
    """The compiled program must actually contract in int8 — not silently
    dequantize to float (which would pass the parity test above)."""
    _, _, mq = pair
    jaxpr = str(jax.make_jaxpr(mq.fn)(jax.ShapeDtypeStruct((1, 96, 96, 3), jnp.uint8)))
    assert "i8[" in jaxpr
    assert "preferred_element_type=int32" in jaxpr


def test_weight_quantization_roundtrip():
    w = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 32, 64)) * 0.1
    q, scale = qz._quantize_w(w)
    assert q.dtype == jnp.int8 and q.shape == (32, 64)
    recon = q.astype(jnp.float32) * scale
    np.testing.assert_allclose(recon, w[0, 0], atol=float(scale.max()))


def test_bn_fold_matches_unfolded():
    from nnstreamer_tpu.models import nn

    key = jax.random.PRNGKey(1)
    w = nn.init_conv(key, 1, 1, 8, 16)
    bn = nn.init_bn(16)
    bn = {**bn, "mean": jnp.full((16,), 0.3), "var": jnp.full((16,), 2.0),
          "scale": jnp.full((16,), 1.5), "bias": jnp.full((16,), -0.1)}
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 5, 8))
    ref = nn.batch_norm(nn.conv2d(x, w), bn)
    wf, bf = qz.fold_bn(w, bn)
    np.testing.assert_allclose(nn.conv2d(x, wf) + bf, ref, rtol=1e-4, atol=1e-5)


def test_int8_through_single_shot():
    """zoo option plumbing: custom=quantize:int8 through the filter API."""
    from nnstreamer_tpu.single import SingleShot

    with SingleShot(
        framework="jax",
        model="zoo:mobilenet_v2",
        custom="quantize:int8,size:96,num_classes:16",
    ) as s:
        out = s.invoke(np.zeros((1, 96, 96, 3), np.uint8))
    assert out[0].shape == (1, 16)
    assert np.all(np.isfinite(np.asarray(out[0])))


# -- weight-only int8 for the transformer family ---------------------------

_LM_KW = dict(vocab="512", d_model="128", n_heads="4", n_layers="2")


def _toks(n=32, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 512, (1, n)), jnp.int32
    )


def test_lm_int8w_forward_close():
    mf = zoo.get("transformer_lm", **_LM_KW)
    mq = zoo.get("transformer_lm", quantize="int8w", **_LM_KW)
    toks = _toks()
    fl = np.asarray(jax.jit(mf.fn)(toks))
    ql = np.asarray(jax.jit(mq.fn)(toks))
    cos = (ql * fl).sum() / (np.linalg.norm(ql) * np.linalg.norm(fl))
    assert cos > 0.995, f"cosine {cos}"


def test_lm_int8w_weights_are_int8():
    mq = zoo.get("transformer_lm", quantize="int8w", **_LM_KW)
    blocks = mq.params["blocks"]
    for k in ("wqkv", "wo", "w_gate", "w_up", "w_down"):
        assert blocks[k]["w8"].dtype == jnp.int8
        # stacked [L, 1, cout] scales: one scale per layer per out-channel
        assert blocks[k]["scale"].shape[0] == blocks["ln1"].shape[0]
    assert mq.params["embed"]["w8"].dtype == jnp.int8
    # norms stay exact f32
    assert blocks["ln1"].dtype == jnp.float32


def test_lm_int8w_generate_deterministic():
    toks = _toks(16)
    a = zoo.get("transformer_lm", generate="8", quantize="int8w", **_LM_KW)
    b = zoo.get("transformer_lm", generate="8", quantize="int8w", **_LM_KW)
    np.testing.assert_array_equal(
        np.asarray(jax.jit(a.fn)(toks)), np.asarray(jax.jit(b.fn)(toks))
    )


def test_lm_int8w_bf16_traces():
    m = zoo.get(
        "transformer_lm", quantize="int8w", compute_dtype="bfloat16",
        generate="4", **_LM_KW,
    )
    jax.eval_shape(m.fn, jax.ShapeDtypeStruct((1, 16), jnp.int32))
