"""nns-plane serving plane (serving_plane/, docs/serving-plane.md):
cross-stream continuous batching with bitwise per-frame parity,
per-stream FIFO, weighted-fair scheduling with a starvation bound,
Hermes placement under memory bounds, replica failover through the
plane, per-stream fault/sanitizer accounting, the NNS-W114 lint, and
the observability surface (plane_* stats, nns-top --models)."""

import os
import threading

import numpy as np
import pytest

from nnstreamer_tpu.analysis import lint
from nnstreamer_tpu.backends.base import FilterProps
from nnstreamer_tpu.backends.fakes import ScalerBackend
from nnstreamer_tpu.elements.filter import TensorFilter
from nnstreamer_tpu.elements.sink import TensorSink
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.serving_plane import (
    ModelPlane,
    PlacementError,
    PlaneConfig,
    plan_placement,
    resolve_plane_config,
)
from nnstreamer_tpu.serving_plane import plane as plane_mod
from nnstreamer_tpu.serving_plane.scheduler import (
    PlaneStream,
    StreamScheduler,
)
from nnstreamer_tpu.serving_plane.sharding import (
    MeshShardedProgram,
    VmapProgram,
)
from nnstreamer_tpu.tensors.frame import Frame
from nnstreamer_tpu.tensors.spec import TensorsSpec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec(dims="4"):
    return TensorsSpec.from_strings(dims, "float32")


def _scaler(factor=3.0):
    b = ScalerBackend()
    b.open(FilterProps(
        framework="scaler", model=(), custom=f"factor:{factor}",
        input_spec=_spec(),
    ))
    return b


def _mlp_model(tmp_path, d=8, k=2.0):
    path = tmp_path / "mm.py"
    path.write_text(
        "import jax.numpy as jnp\n"
        "def get_model(options):\n"
        f"    return (lambda x: x * {k}), None\n"
    )
    return str(path)


class _Req:
    def __init__(self, frames):
        self.frames = frames


# ---------------------------------------------------------------------------
# scheduler: weighted-fair collection
# ---------------------------------------------------------------------------

class TestScheduler:
    def test_starvation_bound(self):
        """A flooded stream cannot keep a backlogged light stream out
        of ANY collection cycle: every round credits every backlogged
        stream, so the lights land in the very next batch."""
        sched = StreamScheduler()
        hot, l1, l2 = PlaneStream("hot"), PlaneStream("l1"), PlaneStream("l2")
        for s in (hot, l1, l2):
            sched.add(s)
        for i in range(64):
            hot.q.append(_Req([i]))
        l1.q.append(_Req(["a"]))
        l2.q.append(_Req(["b"]))
        batch = sched.collect(8)
        sids = [s.sid for s, _ in batch]
        assert "l1" in sids and "l2" in sids
        assert len(batch) == 8

    def test_weights_proportional(self):
        """weight=2 earns two slots per round where weight=1 earns one."""
        sched = StreamScheduler()
        a, b = PlaneStream("a", weight=1.0), PlaneStream("b", weight=2.0)
        sched.add(a)
        sched.add(b)
        for i in range(32):
            a.q.append(_Req([i]))
            b.q.append(_Req([i]))
        batch = sched.collect(9)
        counts = {"a": 0, "b": 0}
        for s, _ in batch:
            counts[s.sid] += 1
        assert counts["b"] == 2 * counts["a"]

    def test_fifo_per_stream(self):
        sched = StreamScheduler()
        a = PlaneStream("a")
        sched.add(a)
        for i in range(5):
            a.q.append(_Req([i]))
        batch = sched.collect(3)
        assert [r.frames[0] for _, r in batch] == [0, 1, 2]
        batch = sched.collect(3)
        assert [r.frames[0] for _, r in batch] == [3, 4]

    def test_window_atomic_under_frame_limit(self):
        """A request is a window: collection counts FRAMES and never
        splits a window, stopping before one that would overflow."""
        sched = StreamScheduler()
        a, b = PlaneStream("a"), PlaneStream("b")
        sched.add(a)
        sched.add(b)
        a.q.append(_Req([1, 2, 3]))
        b.q.append(_Req([4, 5, 6]))
        batch = sched.collect(4)
        # 3 frames taken; the second 3-frame window would overflow 4
        assert sum(len(r.frames) for _, r in batch) == 3
        assert sched.backlog == 3

    def test_fractional_weight_stays_work_conserving(self):
        """A lone backlogged stream with weight < 1 still fills the
        batch: weights scale RELATIVE share, never absolute pacing."""
        sched = StreamScheduler()
        slow = PlaneStream("slow", weight=0.1)
        sched.add(slow)
        for i in range(8):
            slow.q.append(_Req([i]))
        batch = sched.collect(4)
        assert len(batch) == 4

    def test_idle_stream_banks_no_credit(self):
        sched = StreamScheduler()
        a, b = PlaneStream("a"), PlaneStream("b")
        sched.add(a)
        sched.add(b)
        for i in range(8):
            a.q.append(_Req([i]))
        sched.collect(8)  # many rounds credit b while it idles
        assert b.deficit == 0.0


# ---------------------------------------------------------------------------
# plane core: parity, FIFO, fault isolation
# ---------------------------------------------------------------------------

class TestPlaneCore:
    def test_cross_stream_batch_parity_bitwise(self):
        """Batched cross-stream results must be bitwise identical to
        isolated per-frame invokes of the same backend."""
        iso = _scaler(3.0)
        shared = _scaler(3.0)
        plane = ModelPlane(
            "parity", PlaneConfig(max_batch=8, timeout_ms=1.0), [shared]
        )
        try:
            streams = [plane.attach(f"s{i}") for i in range(4)]
            frames = {
                i: [
                    np.arange(4, dtype=np.float32) + 10 * i + j
                    for j in range(6)
                ]
                for i in range(4)
            }
            outs = {}

            def drive(i, s):
                outs[i] = [
                    np.asarray(
                        plane.submit(s, Frame((x,))).tensors[0]
                    )
                    for x in frames[i]
                ]

            ts = [
                threading.Thread(target=drive, args=(i, s))
                for i, s in enumerate(streams)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(4):
                for x, got in zip(frames[i], outs[i]):
                    (want,) = iso.invoke((x,))
                    assert np.array_equal(got, np.asarray(want))
                    assert got.dtype == np.asarray(want).dtype
            assert plane.stats()["dispatches"] >= 1
        finally:
            plane.close()
            iso.close()

    def test_per_stream_fifo_order(self):
        shared = _scaler(1.0)
        plane = ModelPlane(
            "fifo", PlaneConfig(max_batch=4, timeout_ms=0.5), [shared]
        )
        try:
            streams = [plane.attach(f"s{i}") for i in range(3)]
            seqs = {}

            def drive(i, s):
                got = []
                for j in range(20):
                    x = np.full(4, 100 * i + j, np.float32)
                    got.append(
                        float(
                            np.asarray(
                                plane.submit(s, Frame((x,))).tensors[0]
                            )[0]
                        )
                    )
                seqs[i] = got

            ts = [
                threading.Thread(target=drive, args=(i, s))
                for i, s in enumerate(streams)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(3):
                assert seqs[i] == [100.0 * i + j for j in range(20)]
        finally:
            plane.close()

    def test_window_submission_round_trip(self):
        shared = _scaler(2.0)
        plane = ModelPlane(
            "win", PlaneConfig(max_batch=8, timeout_ms=0.5), [shared]
        )
        try:
            s = plane.attach("s0")
            windows = [
                (np.arange(4, dtype=np.float32) + j,) for j in range(5)
            ]
            outs = plane.submit_window(s, windows)
            assert len(outs) == 5
            for (x,), (y,) in zip(windows, outs):
                assert np.array_equal(np.asarray(y), x * 2.0)
            assert s.admitted == 5 and s.served == 5
        finally:
            plane.close()

    def test_fault_isolates_the_failing_stream(self):
        """A poisoned frame fails ITS stream's submit; batchmates from
        other streams still serve (the per-window split)."""

        class MarkerProgram:
            mode = "single"
            n_traces = 0

            def invoke(self, windows):
                outs = []
                for (x,) in windows:
                    if float(np.asarray(x)[0]) < 0:
                        raise RuntimeError("poisoned window")
                    outs.append((np.asarray(x) * 2.0,))
                return outs

            def invoke_one(self, w):
                return self.invoke([w])[0]

        plane = ModelPlane(
            "iso", PlaneConfig(max_batch=8, timeout_ms=2.0),
            backends=[], program=MarkerProgram(),
        )
        try:
            good, bad = plane.attach("good"), plane.attach("bad")
            results = {}

            def drive_good():
                results["good"] = [
                    np.asarray(
                        plane.submit(
                            good, Frame((np.full(4, j, np.float32),))
                        ).tensors[0]
                    )
                    for j in range(10)
                ]

            def drive_bad():
                errs = 0
                for j in range(10):
                    x = np.full(4, -1.0, np.float32)
                    try:
                        plane.submit(bad, Frame((x,)))
                    except RuntimeError:
                        errs += 1
                results["bad_errs"] = errs

            tg = threading.Thread(target=drive_good)
            tb = threading.Thread(target=drive_bad)
            tg.start(); tb.start(); tg.join(); tb.join()
            assert results["bad_errs"] == 10
            assert len(results["good"]) == 10
            for j, a in enumerate(results["good"]):
                assert np.array_equal(a, np.full(4, 2.0 * j, np.float32))
            assert bad.errors == 10 and good.errors == 0
        finally:
            plane.close()

    def test_close_gives_queued_requests_a_terminal_outcome(self):
        """A request queued at close time is either served or completed
        with PlaneClosedError — a waiter can never hang (the PR-6
        terminal-outcome discipline)."""
        shared = _scaler(1.0)
        plane = ModelPlane(
            "det", PlaneConfig(max_batch=8, timeout_ms=1.0), [shared]
        )
        s = plane.attach("s0")
        req = plane_mod._Req([(np.zeros(4, np.float32),)])
        with plane._cond:
            s.q.append(req)
        plane.close()
        assert req.done.wait(2.0)
        assert req.out is not None or isinstance(
            req.exc, plane_mod.PlaneClosedError
        )


# ---------------------------------------------------------------------------
# registry / config / property surface
# ---------------------------------------------------------------------------

class TestRegistryAndConfig:
    def test_refcounted_shared_backend(self):
        a = TensorFilter(framework="scaler", custom="factor:3", plane="rk1")
        b = TensorFilter(framework="scaler", custom="factor:3", plane="rk1")
        try:
            a.negotiate([_spec()])
            b.negotiate([_spec()])
            assert a.backend is b.backend
            assert plane_mod.get("rk1") is not None
            a.stop()
            assert plane_mod.get("rk1") is not None  # b still holds it
        finally:
            b.stop()
            a.stop()
        assert plane_mod.get("rk1") is None

    def test_signature_conflict_rejected(self):
        a = TensorFilter(framework="scaler", custom="factor:3", plane="rk2")
        a.negotiate([_spec()])
        try:
            b = TensorFilter(
                framework="scaler", custom="factor:9", plane="rk2"
            )
            with pytest.raises(ValueError, match="already bound"):
                b.negotiate([_spec()])
        finally:
            a.stop()

    def test_conflicting_modes_rejected(self):
        with pytest.raises(ValueError, match="shared-tensor-filter-key"):
            TensorFilter(framework="scaler", plane="x",
                         **{"shared-tensor-filter-key": "k"})
        with pytest.raises(ValueError, match="replicas"):
            TensorFilter(framework="scaler", plane="x", replicas=2)
        with pytest.raises(ValueError, match="fallback"):
            TensorFilter(framework="scaler", plane="x",
                         **{"fallback-framework": "passthrough"})

    def test_resolve_config_element_over_default(self, monkeypatch):
        f = TensorFilter(
            framework="scaler", plane="cfg",
            **{"plane-max-batch": "4", "plane-timeout-ms": "0.5",
               "plane-mode": "shard", "plane-devices": "2"},
        )
        cfg = resolve_plane_config([f])
        assert cfg.max_batch == 4 and cfg.timeout_ms == 0.5
        assert cfg.mode == "shard" and cfg.devices == 2
        monkeypatch.setenv("NNS_TPU_PLANE_MAX_BATCH", "16")
        f2 = TensorFilter(framework="scaler", plane="cfg2")
        assert resolve_plane_config([f2]).max_batch == 16

    def test_bad_plane_mode_rejected(self):
        # the filter resolves its plane config at CONSTRUCTION (to
        # window-match the local collector), so a bad mode fails there
        with pytest.raises(ValueError, match="plane-mode"):
            TensorFilter(framework="scaler", plane="m",
                         **{"plane-mode": "bogus"})

    def test_implicit_sharer_inherits_bound_config(self):
        """docs: 'the first attacher's resolved config binds the
        plane' — a later sharer with NO plane-* props inherits instead
        of colliding; explicitly conflicting knobs still fail."""
        a = TensorFilter(framework="scaler", custom="factor:3",
                         plane="inh1", **{"plane-max-batch": "32"})
        b = TensorFilter(framework="scaler", custom="factor:3",
                         plane="inh1")
        try:
            a.negotiate([_spec()])
            b.negotiate([_spec()])
            assert a.backend is b.backend
            assert b._plane.cfg.max_batch == 32  # inherited binding
            c = TensorFilter(framework="scaler", custom="factor:3",
                             plane="inh1", **{"plane-max-batch": "4"})
            with pytest.raises(ValueError, match="already bound"):
                c.negotiate([_spec()])
        finally:
            a.stop()
            b.stop()

    def test_device_pin_keeps_plane_batching(self, tmp_path):
        """plane= + device=N batches on chip N through the plane's own
        program — the pin is a FUSION barrier, not a batching barrier
        (without the plane_fn hook this silently degraded to a
        per-frame HostProgram loop)."""
        import jax

        from nnstreamer_tpu.serving_plane.sharding import (
            VmapProgram,
            build_plane_program,
        )

        model = _mlp_model(tmp_path)
        f = TensorFilter(framework="jax", model=model, input="4",
                         inputtype="float32", plane="pin1", device="1")
        try:
            f.negotiate([_spec()])
            prog = build_plane_program([f.backend], f._plane_cfg)
            assert isinstance(prog, VmapProgram)
            assert prog._device is jax.devices()[1]
            (out,) = prog.invoke(
                [(np.arange(4, dtype=np.float32),)]
            )[0]
            assert np.array_equal(
                np.asarray(out), np.arange(4, dtype=np.float32) * 2.0
            )
        finally:
            f.stop()

    def test_plane_defaults_local_batching_on(self):
        f = TensorFilter(framework="scaler", plane="d")
        from nnstreamer_tpu.pipeline.batching import resolve_batch_config

        cfg = resolve_batch_config([f])
        assert cfg.active  # local collector window-matched to the plane
        assert f.is_batch_capable()


# ---------------------------------------------------------------------------
# pipelines: executors sharing a plane, sanitizer accounting
# ---------------------------------------------------------------------------

def _run_streams(descs, timeout=60):
    pipes = [parse_pipeline(d) for d in descs]
    execs = [None] * len(pipes)
    errors = []

    def drive(i):
        try:
            execs[i] = pipes[i].run(timeout=timeout)
        except Exception as exc:  # noqa: BLE001 — assert below
            errors.append((i, exc))

    ts = [
        threading.Thread(target=drive, args=(i,))
        for i in range(len(pipes))
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors, errors
    return pipes, execs


class TestPipelines:
    def test_two_executors_one_plane(self):
        descs = [
            "tensorsrc dimensions=4 pattern=counter num-frames=25 ! "
            "tensor_filter framework=scaler custom=factor:2.0 "
            "plane=pp1 plane-max-batch=8 ! tensor_sink"
            for _ in range(2)
        ]
        pipes, execs = _run_streams(descs)
        for p in pipes:
            sink = next(
                e for e in p.elements if isinstance(e, TensorSink)
            )
            outs = [np.asarray(f.tensors[0]) for f in sink.frames]
            assert len(outs) == 25
            for j, a in enumerate(outs):
                assert np.array_equal(a, np.full(4, 2.0 * j, np.float32))
        rows = [
            row for ex in execs for row in ex.stats().values()
            if "plane_name" in row
        ]
        assert rows and rows[0]["plane_name"] == "pp1"
        assert rows[0]["plane_frames"] >= 25
        assert plane_mod.get("pp1") is None  # refcount drained

    def test_sanitizer_accounting_latch_per_stream(self, monkeypatch):
        """Clean EOS through a shared plane latches the sanitizer's
        offered == delivered accounting on every stream's filter node
        (and the run leaks no threads)."""
        monkeypatch.setenv("NNS_TPU_SANITIZE", "1")
        descs = [
            "tensorsrc dimensions=4 pattern=counter num-frames=15 ! "
            "tensor_filter framework=scaler custom=factor:2.0 "
            "plane=san1 plane-max-batch=4 ! tensor_sink"
            for _ in range(2)
        ]
        pipes, execs = _run_streams(descs)
        for ex in execs:
            assert ex.sanitizer is not None
            assert not ex.errors
            assert ex.totals()["balance"] == 0
            # NOTE: leaked_threads is not asserted — two sanitized
            # executors running concurrently legitimately see each
            # other's node threads in the external-thread diff

    def test_fault_policy_disposes_per_stream(self):
        """One stream feeds poisoned frames through a strict-shape
        chaos filter sharing the plane with a healthy stream: the
        poisoned stream's on-error=drop disposes ITS frames with
        accounting, the healthy stream delivers everything."""

        class MarkerProgram:
            mode = "single"
            n_traces = 0

            def invoke(self, windows):
                outs = []
                for (x,) in windows:
                    if float(np.asarray(x)[0]) >= 90.0:
                        raise RuntimeError("poisoned window")
                    outs.append((np.asarray(x),))
                return outs

            def invoke_one(self, w):
                return self.invoke([w])[0]

        # pre-register the plane with a marker program; filters attach
        # to it by name (the injected-program hook). A real backend
        # still rides along as the sharers' negotiation surface.
        cfg = PlaneConfig(max_batch=8, timeout_ms=1.0)
        plane = ModelPlane("fp1", cfg, backends=[_scaler(1.0)],
                           program=MarkerProgram())
        entry = {"plane": plane, "sig": None, "refs": 0,
                 "open_lock": threading.Lock()}
        plane_mod._planes["fp1"] = entry

        def acquire_patch(name, sig, cfg2, opener, cfg_explicit=True,
                          _orig=plane_mod.acquire):
            if name == "fp1":
                with plane_mod._registry_lock:
                    entry["refs"] += 1
                return plane
            return _orig(name, sig, cfg2, opener,
                         cfg_explicit=cfg_explicit)

        orig = plane_mod.acquire
        plane_mod.acquire = acquire_patch
        try:
            descs = [
                # healthy stream: counter frames 0..19 (< 90)
                "tensorsrc dimensions=4 pattern=counter num-frames=20 ! "
                "tensor_filter framework=scaler plane=fp1 "
                "plane-max-batch=8 ! tensor_sink",
                # poisoned stream: counter + 90 via a transform upstream
                "tensorsrc dimensions=4 pattern=counter num-frames=20 ! "
                "tensor_transform mode=arithmetic option=add:90.0 ! "
                "tensor_filter framework=scaler plane=fp1 "
                "plane-max-batch=8 on-error=drop name=poisoned ! "
                "tensor_sink",
            ]
            pipes, execs = _run_streams(descs)
            healthy_sink = next(
                e for e in pipes[0].elements if isinstance(e, TensorSink)
            )
            poisoned_sink = next(
                e for e in pipes[1].elements if isinstance(e, TensorSink)
            )
            assert len(healthy_sink.frames) == 20
            assert len(poisoned_sink.frames) == 0  # all dropped by policy
            tot = execs[1].totals()
            assert tot["dropped"].get("on-error-drop") == 20
            assert tot["balance"] == 0
        finally:
            plane_mod.acquire = orig
            plane_mod._planes.pop("fp1", None)
            plane.close()


# ---------------------------------------------------------------------------
# replica failover through the plane
# ---------------------------------------------------------------------------

class TestReplicas:
    def test_failover_through_plane(self):
        """mode=replicas over two chaos backends, one of which loses
        its device mid-run: every frame still serves (windows fail over
        whole), and the replica set records the failovers."""
        descs = [
            "tensorsrc dimensions=4 pattern=counter num-frames=30 ! "
            "tensor_filter framework=faulty "
            'custom="device_lost_at:3,only_replica:1" '
            "plane=rep1 plane-mode=replicas plane-devices=2 "
            "plane-max-batch=4 ! tensor_sink"
        ]
        pipes, execs = _run_streams(descs)
        sink = next(
            e for e in pipes[0].elements if isinstance(e, TensorSink)
        )
        assert len(sink.frames) == 30
        row = next(
            row for ex in execs for row in ex.stats().values()
            if "plane_name" in row
        )
        reps = row["plane_replicas"]
        assert reps["failovers"] >= 1
        assert reps["replicas"] == 2

    def test_exhaustion_raises_per_stream(self):
        """Both replicas dead: the stream's own error policy disposes
        (on-error=drop), the pipeline survives to EOS."""
        descs = [
            "tensorsrc dimensions=4 pattern=counter num-frames=10 ! "
            "tensor_filter framework=faulty "
            'custom="device_lost_at:1" '
            "plane=rep2 plane-mode=replicas plane-devices=2 "
            "plane-max-batch=2 on-error=drop "
            "retry-backoff-ms=1 ! tensor_sink"
        ]
        pipes, execs = _run_streams(descs)
        sink = next(
            e for e in pipes[0].elements if isinstance(e, TensorSink)
        )
        assert len(sink.frames) == 0
        assert execs[0].totals()["dropped"].get("on-error-drop") == 10


# ---------------------------------------------------------------------------
# mesh-sharded program
# ---------------------------------------------------------------------------

class TestSharded:
    def test_mesh_parity_with_single_device(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.parallel.mesh import make_mesh
        from nnstreamer_tpu.pipeline.batching import default_buckets

        w = jnp.asarray(
            np.random.default_rng(0).standard_normal((8, 8))
            .astype(np.float32)
        )

        def fn(tensors):
            (x,) = tensors
            return (x @ w,)

        single = VmapProgram(fn, default_buckets(8))
        mesh = make_mesh(4, axes=("dp",))
        sharded = MeshShardedProgram(fn, mesh, max_batch=8)
        windows = [
            (np.random.default_rng(i).standard_normal((8,))
             .astype(np.float32),)
            for i in range(6)
        ]
        a = single.invoke(list(windows))
        b = sharded.invoke(list(windows))
        for (x,), (y,) in zip(a, b):
            assert np.array_equal(np.asarray(x), np.asarray(y))

    def test_oversized_batch_chunks_to_ladder(self):
        """A batch wider than the top bucket (explicit local max-batch
        beyond the plane's) chunks instead of computing a negative pad —
        which on a mesh-sharded program crashed the jit with a
        non-divisible global batch."""
        from nnstreamer_tpu.parallel.mesh import make_mesh
        from nnstreamer_tpu.pipeline.batching import default_buckets

        def double(ts):
            (x,) = ts
            return (x * 2.0,)

        windows = [
            (np.full(4, float(j), np.float32),) for j in range(5)
        ]
        for prog in (
            VmapProgram(double, default_buckets(4)),
            MeshShardedProgram(
                double, make_mesh(2, axes=("dp",)), max_batch=4
            ),
        ):
            outs = prog.invoke(list(windows))
            assert len(outs) == 5
            for j, (y,) in enumerate(outs):
                assert np.array_equal(
                    np.asarray(y), np.full(4, 2.0 * j, np.float32)
                )

    def test_shard_bucket_ladder_multiple_of_mesh(self):
        from nnstreamer_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(4, axes=("dp",))
        prog = MeshShardedProgram(lambda ts: ts, mesh, max_batch=8)
        assert prog.buckets == (4, 8)
        assert prog.bucket_for(3) == 4 and prog.bucket_for(5) == 8


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------

class TestPlacement:
    def test_bound_respected(self):
        assert plan_placement([4, 4, 4, 4], 8, 2) == [0, 0, 1, 1]
        # chain locality: everything fits on one chip → one chip
        assert plan_placement([2, 2, 2], 8, 4) == [0, 0, 0]

    def test_no_fit_raises(self):
        with pytest.raises(PlacementError, match="over the per-device"):
            plan_placement([9], 8, 2)
        with pytest.raises(PlacementError, match="fits on no device"):
            plan_placement([4, 4, 4, 4, 4], 8, 2)

    def test_pins_are_hard_constraints(self):
        plan = plan_placement([2, 2, 2], 8, 4, pinned={1: 3})
        assert plan[1] == 3
        with pytest.raises(PlacementError, match="pinned"):
            plan_placement([8, 8], 8, 2, pinned={1: 0})

    def test_place_pipeline_splits_and_runs(self, tmp_path):
        model = _mlp_model(tmp_path)
        p = parse_pipeline(
            f"tensorsrc dimensions=4 pattern=counter num-frames=6 ! "
            f"tensor_filter framework=jax model={model} input=4 "
            f"inputtype=float32 name=f1 ! "
            f"tensor_filter framework=jax model={model} input=4 "
            f"inputtype=float32 name=f2 ! "
            f"tensor_sink"
        )
        from nnstreamer_tpu.serving_plane import place_pipeline

        # each stage ~32 activation bytes; a 50-byte bound forces the
        # second stage onto the next chip
        placement = place_pipeline(p, per_device_bytes=50, n_devices=2)
        assert placement == {"f1": 0, "f2": 1}
        assert p["f2"].backend._device is not None
        ex = p.run(timeout=60)
        sink = next(
            e for e in p.elements if isinstance(e, TensorSink)
        )
        outs = [np.asarray(f.tensors[0]) for f in sink.frames]
        assert len(outs) == 6
        for j, a in enumerate(outs):
            assert np.allclose(a, np.full(4, 4.0 * j, np.float32))

    def test_device_prop_pins_backend(self):
        f = TensorFilter(
            framework="scaler", custom="factor:2.0", device="1"
        )
        # rides the custom string into the backend open options
        assert "device:1" in f.fprops.custom

    def test_parse_bytes(self):
        from nnstreamer_tpu.serving_plane.placement import parse_bytes

        assert parse_bytes("256M") == 256 << 20
        assert parse_bytes("2K") == 2048
        assert parse_bytes("123") == 123


# ---------------------------------------------------------------------------
# lint + observability surface
# ---------------------------------------------------------------------------

class TestSurface:
    def test_w114_duplicate_model_fires(self, tmp_path):
        model = _mlp_model(tmp_path)
        r = lint(
            "tensorsrc dimensions=4 ! tee name=t "
            f"t. ! queue ! tensor_filter framework=jax model={model} "
            "input=4 inputtype=float32 name=a ! tensor_sink "
            f"t. ! queue ! tensor_filter framework=jax model={model} "
            "input=4 inputtype=float32 name=b ! tensor_sink"
        )
        assert "NNS-W114" in r.codes

    @pytest.mark.parametrize("fix", [
        "plane=p", "shared-tensor-filter-key=k",
    ])
    def test_w114_silent_with_sharing(self, fix, tmp_path):
        model = _mlp_model(tmp_path)
        r = lint(
            "tensorsrc dimensions=4 ! tee name=t "
            f"t. ! queue ! tensor_filter framework=jax model={model} "
            f"input=4 inputtype=float32 {fix} name=a ! tensor_sink "
            f"t. ! queue ! tensor_filter framework=jax model={model} "
            f"input=4 inputtype=float32 {fix} name=b ! tensor_sink"
        )
        assert "NNS-W114" not in r.codes

    def test_nns_top_models_view(self):
        from nnstreamer_tpu.obs.nns_top import render_models

        snap = {"nodes": {"f0": {
            "plane_name": "demo", "plane_mode": "single",
            "plane_devices": 1, "plane_streams": 3,
            "plane_queue_depth": 2, "plane_dispatches": 40,
            "plane_avg_batch": 5.5, "plane_occupancy_pct": 68.8,
            "plane_frames": 220,
            "plane_per_stream": {
                "s0": {"admitted": 80, "served": 78, "queued": 2,
                       "errors": 0, "weight": 1.0},
            },
        }, "f1": {"plane_name": "demo"}}}
        out = render_models(snap)
        assert "demo" in out and "s0" in out and "admitted=80" in out
        assert out.count("demo") == 1  # deduped across sharers
        assert "(no serving plane" in render_models({"nodes": {}})

    def test_plane_metrics_emitted(self, monkeypatch):
        from nnstreamer_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.enable()
        try:
            shared = _scaler(1.0)
            plane = ModelPlane(
                "met1", PlaneConfig(max_batch=4, timeout_ms=0.5),
                [shared],
            )
            s = plane.attach("s0")
            plane.submit(s, Frame((np.zeros(4, np.float32),)))
            plane.close()
            h = reg.find("nns_plane_batch_occupancy", plane="met1")
            assert h is not None and h.count >= 1
            c = reg.find(
                "nns_plane_stream_served_total", plane="met1", stream="s0"
            )
            assert c is not None and c.value == 1
        finally:
            obs_metrics.disable()


# ---------------------------------------------------------------------------
# the multi-stream × multi-chip soak
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_soak_multistream_multichip():
    """8 streams × a mesh-sharded plane over 4 virtual devices × a
    weighted mix, under sustained load: every stream's frames arrive,
    in order, with the plane's cross-stream batching engaged."""
    n, N = 8, 200
    descs = [
        f"tensorsrc dimensions=16 pattern=counter num-frames={N} ! "
        "tensor_filter framework=scaler custom=factor:2.0 plane=soak "
        "plane-mode=shard plane-devices=4 plane-max-batch=16 "
        f"plane-weight={1.0 + (i % 2)} ! tensor_sink"
        for i in range(n)
    ]
    pipes, execs = _run_streams(descs, timeout=300)
    for p in pipes:
        sink = next(e for e in p.elements if isinstance(e, TensorSink))
        outs = [np.asarray(f.tensors[0]) for f in sink.frames]
        assert len(outs) == N
        for j, a in enumerate(outs):
            assert np.array_equal(a, np.full(16, 2.0 * j, np.float32))
    row = next(
        row for ex in execs for row in ex.stats().values()
        if "plane_name" in row
    )
    assert row["plane_frames"] >= N
    assert plane_mod.get("soak") is None
