"""Two-process CLI loopback: server and client pipelines as separate
processes on localhost, golden-compared — the reference's
tests/nnstreamer_edge/query/runTest.sh strategy (gstTestBackground +
sleep-sync + compare)."""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    return {**os.environ, "JAX_PLATFORMS": "cpu", "XLA_FLAGS": flags,
            "PYTHONPATH": REPO}


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_port(port: int, timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.2).close()
            return
        except OSError:
            time.sleep(0.1)
    raise TimeoutError(f"server port {port} never opened")


def test_query_offload_two_processes(tmp_path):
    """client: testsrc → query_client → filesink; server: serversrc →
    scaler ×2 → serversink. Output must equal the local scaler result."""
    port = _free_port()
    server = subprocess.Popen(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         f"tensor_query_serversrc port={port} id=cli1 ! "
         'tensor_filter framework=scaler custom="factor:2.0" '
         "input=3:4:4:1 inputtype=uint8 ! "
         "tensor_query_serversink id=cli1",
         "--timeout", "60", "-q"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        _wait_port(port)
        out = tmp_path / "reply.raw"
        client = subprocess.run(
            [sys.executable, "-m", "nnstreamer_tpu.cli",
             "videotestsrc pattern=counter num-frames=3 width=4 height=4 ! "
             f"tensor_converter ! tensor_query_client dest-port={port} "
             f"timeout=30 ! filesink location={out}",
             "-q"],
            env=_env(), capture_output=True, text=True, timeout=120,
        )
        assert client.returncode == 0, client.stderr[-600:]
        got = np.frombuffer(out.read_bytes(), np.uint8).reshape(3, -1)
        # counter pattern: every pixel of frame i is i; scaler doubles
        # (uint8 math) → frame i is 2*i everywhere
        for i in range(3):
            assert (got[i] == np.uint8(2 * i)).all()
    finally:
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()


def test_edge_pubsub_two_processes(tmp_path):
    """edgesink publisher process → edgesrc subscriber process (TCP).
    Publisher starts first with wait-connection so no frame is lost."""
    port = _free_port()
    out = tmp_path / "sub.raw"
    pub = subprocess.Popen(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         "videotestsrc pattern=counter num-frames=2 width=4 height=4 ! "
         f"tensor_converter ! edgesink port={port} "
         "wait-connection=true connection-timeout=60",
         "-q"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        _wait_port(port)
        sub = subprocess.run(
            [sys.executable, "-m", "nnstreamer_tpu.cli",
             f"edgesrc dest-port={port} ! filesink location={out}",
             "--timeout", "60", "-q"],
            env=_env(), capture_output=True, text=True, timeout=120,
        )
        assert sub.returncode == 0, sub.stderr[-600:]
        assert pub.wait(timeout=30) == 0
        data = np.frombuffer(out.read_bytes(), np.uint8)
        assert data.size == 2 * 4 * 4 * 3
        assert (data[:48] == 0).all() and (data[48:] == 1).all()
    finally:
        if pub.poll() is None:
            pub.terminate()
            try:
                pub.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pub.kill()


def test_llm_query_offload_two_processes(tmp_path):
    """The among-device + LLM serving integration: a client pipeline
    offloads a token prompt over the query transport; the server
    pipeline generates via the continuous batcher and routes the reply
    back by client_id. Output must equal solo generation."""
    port = _free_port()
    model = "vocab:211,d_model:32,n_heads:2,n_layers:2,seed:5"
    server = subprocess.Popen(
        [sys.executable, "-m", "nnstreamer_tpu.cli",
         f"tensor_query_serversrc port={port} id=lq1 ! "
         f'tensor_llm_serversink id=ls1 custom="{model}" '
         "max-new-tokens=5 n-slots=2 max-len=32 prompt-len=8 "
         "tensor_llm_serversrc id=ls1 ! tensor_query_serversink id=lq1",
         "--timeout", "90", "-q"],
        env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        _wait_port(port)
        out = tmp_path / "tokens.raw"
        client = subprocess.run(
            [sys.executable, "-m", "nnstreamer_tpu.cli",
             "tensorsrc dimensions=6:1 types=int32 num-frames=1 "
             "pattern=ones ! "
             f"tensor_query_client dest-port={port} timeout=60 ! "
             f"filesink location={out}",
             "-q"],
            env=_env(), capture_output=True, text=True, timeout=180,
        )
        assert client.returncode == 0, client.stderr[-600:]
        got = np.frombuffer(out.read_bytes(), np.int32)
        assert got.shape == (5,)
        # reference: solo generation on the same prompt/model
        import jax
        import jax.numpy as jnp

        from nnstreamer_tpu.models import decode as dec
        from nnstreamer_tpu.models import transformer as tfm

        params = tfm.init_params(
            jax.random.PRNGKey(5), vocab=211, d_model=32, n_heads=2,
            n_layers=2,
        )
        want = dec.generate(
            params, jnp.ones((1, 6), jnp.int32), 2, 5
        )
        np.testing.assert_array_equal(got, np.asarray(want)[0])
    finally:
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()
