"""KV-cache decode tests: the cached path must reproduce the full forward
exactly (the equivalence the reference's repo-loop RNN tests establish for
recurrent state, tests/nnstreamer_repo_rnn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode, transformer as tfm

V, D, H, L = 64, 32, 4, 2


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), vocab=V, d_model=D,
                           n_heads=H, n_layers=L)


def test_prefill_matches_apply(params):
    toks = jnp.asarray(np.random.default_rng(0).integers(0, V, (2, 10)), jnp.int32)
    full = tfm.apply(params, toks, H)
    pre, cache, pos = decode.prefill(params, toks, H, max_len=16)
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full), atol=1e-5)
    assert int(pos) == 10
    assert cache[0].shape == (L, 2, 16, H, D // H)


def test_decode_step_matches_full_forward(params):
    """Feeding tokens one at a time through the cache must give the same
    last-position logits as running the growing sequence densely."""
    rng = np.random.default_rng(1)
    seq = jnp.asarray(rng.integers(0, V, (1, 8)), jnp.int32)
    _, cache, pos = decode.prefill(params, seq[:, :1], H, max_len=8)
    for i in range(1, 8):
        logits, cache, pos = decode.decode_step(params, seq[:, i], pos, cache, H)
        full = tfm.apply(params, seq[:, : i + 1], H)[:, -1]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full), atol=2e-4,
            err_msg=f"divergence at step {i}",
        )


def test_greedy_generate_matches_dense_argmax_chain(params):
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, V, (1, 4)), jnp.int32)
    out = decode.generate(params, prompt, H, max_new_tokens=6)
    assert out.shape == (1, 6)
    # reference chain: repeatedly run the dense model and take argmax
    seq = prompt
    expect = []
    for _ in range(6):
        logits = tfm.apply(params, seq, H)[:, -1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        expect.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert [int(x) for x in np.asarray(out)[0]] == expect


def test_sampled_generate_is_deterministic_per_key(params):
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    a = decode.generate(params, prompt, H, 5, temperature=1.0,
                        rng=jax.random.PRNGKey(7))
    b = decode.generate(params, prompt, H, 5, temperature=1.0,
                        rng=jax.random.PRNGKey(7))
    c = decode.generate(params, prompt, H, 5, temperature=1.0,
                        rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_generate_jits(params):
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    gen = jax.jit(
        lambda p, t: decode.generate(p, t, H, 4, max_len=8)
    )
    out = gen(params, prompt)
    assert out.shape == (1, 4)


def test_prompt_too_long_rejected(params):
    with pytest.raises(ValueError, match="max_len"):
        decode.prefill(params, jnp.zeros((1, 9), jnp.int32), H, max_len=8)


def test_lm_generation_pipeline():
    """LLM serving as a pipeline: prompt frames → tensor_filter in
    generate mode → generated-token frames."""
    import numpy as np

    from nnstreamer_tpu.elements.filter import TensorFilter
    from nnstreamer_tpu.elements.sink import TensorSink
    from nnstreamer_tpu.elements.sources import AppSrc
    from nnstreamer_tpu.pipeline.graph import Pipeline

    prompts = [np.asarray([[1, 2, 3, 4]], np.int32),
               np.asarray([[9, 8, 7, 6]], np.int32)]
    src = AppSrc(iterable=iter(prompts), dimensions="4:1", types="int32")
    filt = TensorFilter(
        framework="jax", model="zoo:transformer_lm",
        custom="vocab:32,d_model:32,n_heads:4,n_layers:1,generate:5,seqlen:4",
    )
    sink = TensorSink()
    Pipeline().chain(src, filt, sink).run(timeout=120)
    assert sink.rendered == 2
    for f in sink.frames:
        out = np.asarray(f.tensors[0])
        assert out.shape == (1, 5)
        assert out.dtype == np.int32
        assert np.all((out >= 0) & (out < 32))


class TestBeamSearch:
    def _seq_logprob(self, params, prompt, toks):
        """Total log-prob of generated toks under teacher forcing."""
        from nnstreamer_tpu.models import transformer as tfm

        full = jnp.concatenate([prompt, jnp.asarray(toks)], axis=1)
        logits = tfm.apply(params, full, H)
        lp = jax.nn.log_softmax(logits, axis=-1)
        t = prompt.shape[1]
        total = 0.0
        for i in range(toks.shape[1]):
            total += float(lp[0, t + i - 1, int(toks[0, i])])
        return total

    def test_width_one_is_greedy(self, params):
        from nnstreamer_tpu.models.decode import beam_search, generate

        prompt = jnp.asarray(
            np.random.default_rng(9).integers(1, V, (1, 7)), jnp.int32
        )
        toks, _ = beam_search(params, prompt, H, 6, beam_width=1)
        np.testing.assert_array_equal(
            np.asarray(toks), np.asarray(generate(params, prompt, H, 6))
        )

    def test_beam_never_worse_than_greedy(self, params):
        from nnstreamer_tpu.models.decode import beam_search, generate

        prompt = jnp.asarray(
            np.random.default_rng(10).integers(1, V, (1, 9)), jnp.int32
        )
        btoks, bscore = beam_search(params, prompt, H, 8, beam_width=4)
        gtoks = generate(params, prompt, H, 8)
        g_lp = self._seq_logprob(params, prompt, np.asarray(gtoks))
        b_lp = self._seq_logprob(params, prompt, np.asarray(btoks))
        assert b_lp >= g_lp - 1e-4
        assert abs(b_lp - bscore) < 1e-3  # reported score is the log-prob

    def test_b1_required(self, params):
        from nnstreamer_tpu.models.decode import beam_search

        with pytest.raises(ValueError, match="B=1"):
            beam_search(params, jnp.zeros((2, 4), jnp.int32), H, 4)


class TestZooDecodeStrategies:
    """decode:beam / decode:ngram reachable from the filter surface."""

    _KW = dict(vocab=str(V), d_model=str(D), n_heads=str(H),
               n_layers=str(L), seqlen="8", generate="5")

    def _toks(self):
        return jnp.asarray(
            np.random.default_rng(30).integers(1, V, (1, 8)), jnp.int32
        )

    def test_beam_via_zoo(self, params):
        from nnstreamer_tpu.models import zoo
        from nnstreamer_tpu.models.decode import beam_search

        m = zoo.get("transformer_lm", decode="beam", beam_width="3",
                    **self._KW)
        toks = self._toks()
        want, _ = beam_search(m.params, toks, H, 5, beam_width=3)
        np.testing.assert_array_equal(np.asarray(m.fn(toks)), np.asarray(want))

    def test_ngram_via_zoo_matches_greedy(self, params):
        from nnstreamer_tpu.models import zoo

        toks = self._toks()
        g = zoo.get("transformer_lm", **self._KW)
        n = zoo.get("transformer_lm", decode="ngram", **self._KW)
        np.testing.assert_array_equal(
            np.asarray(g.fn(toks)), np.asarray(n.fn(toks))
        )

    def test_unknown_strategy_rejected(self):
        from nnstreamer_tpu.models import zoo

        with pytest.raises(KeyError, match="decode strategy"):
            zoo.get("transformer_lm", decode="magic", **self._KW)
