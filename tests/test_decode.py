"""KV-cache decode tests: the cached path must reproduce the full forward
exactly (the equivalence the reference's repo-loop RNN tests establish for
recurrent state, tests/nnstreamer_repo_rnn)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode, transformer as tfm

V, D, H, L = 64, 32, 4, 2


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(jax.random.PRNGKey(0), vocab=V, d_model=D,
                           n_heads=H, n_layers=L)


def test_prefill_matches_apply(params):
    toks = jnp.asarray(np.random.default_rng(0).integers(0, V, (2, 10)), jnp.int32)
    full = tfm.apply(params, toks, H)
    pre, cache, pos = decode.prefill(params, toks, H, max_len=16)
    np.testing.assert_allclose(np.asarray(pre), np.asarray(full), atol=1e-5)
    assert int(pos) == 10
    assert cache[0].shape == (L, 2, 16, H, D // H)


def test_decode_step_matches_full_forward(params):
    """Feeding tokens one at a time through the cache must give the same
    last-position logits as running the growing sequence densely."""
    rng = np.random.default_rng(1)
    seq = jnp.asarray(rng.integers(0, V, (1, 8)), jnp.int32)
    _, cache, pos = decode.prefill(params, seq[:, :1], H, max_len=8)
    for i in range(1, 8):
        logits, cache, pos = decode.decode_step(params, seq[:, i], pos, cache, H)
        full = tfm.apply(params, seq[:, : i + 1], H)[:, -1]
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full), atol=2e-4,
            err_msg=f"divergence at step {i}",
        )


def test_greedy_generate_matches_dense_argmax_chain(params):
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, V, (1, 4)), jnp.int32)
    out = decode.generate(params, prompt, H, max_new_tokens=6)
    assert out.shape == (1, 6)
    # reference chain: repeatedly run the dense model and take argmax
    seq = prompt
    expect = []
    for _ in range(6):
        logits = tfm.apply(params, seq, H)[:, -1]
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        expect.append(int(nxt[0]))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    assert [int(x) for x in np.asarray(out)[0]] == expect


def test_sampled_generate_is_deterministic_per_key(params):
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    a = decode.generate(params, prompt, H, 5, temperature=1.0,
                        rng=jax.random.PRNGKey(7))
    b = decode.generate(params, prompt, H, 5, temperature=1.0,
                        rng=jax.random.PRNGKey(7))
    c = decode.generate(params, prompt, H, 5, temperature=1.0,
                        rng=jax.random.PRNGKey(8))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_generate_jits(params):
    prompt = jnp.asarray([[1, 2]], jnp.int32)
    gen = jax.jit(
        lambda p, t: decode.generate(p, t, H, 4, max_len=8)
    )
    out = gen(params, prompt)
    assert out.shape == (1, 4)


def test_prompt_too_long_rejected(params):
    with pytest.raises(ValueError, match="max_len"):
        decode.prefill(params, jnp.zeros((1, 9), jnp.int32), H, max_len=8)
