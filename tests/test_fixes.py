"""Regression tests for review findings: timeouts, re-run guard, flexbuf
roundtrip, audio batching, appsrc shutdown, decoder un-batching."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.decoder import TensorDecoder
from nnstreamer_tpu.elements.sink import FakeSink, TensorSink
from nnstreamer_tpu.elements.sources import AppSrc, AudioTestSrc, TensorSrc, VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.spec import TensorsSpec


def test_run_timeout_raises():
    src = VideoTestSrc(width=8, height=8, **{"num-frames": -1})
    p = Pipeline().chain(src, TensorConverter(), FakeSink())
    with pytest.raises(TimeoutError):
        p.run(timeout=0.3)


def test_rerun_completed_pipeline_raises():
    p = Pipeline().chain(TensorSrc(dimensions="2", **{"num-frames": 1}), TensorSink())
    p.run(timeout=30)
    with pytest.raises(RuntimeError, match="already ran"):
        p.run(timeout=30)


def test_appsrc_stop_without_eos_does_not_hang():
    src = AppSrc(spec=TensorsSpec.from_strings("2", "float32"))
    sink = TensorSink()
    p = Pipeline().chain(src, sink)
    p.start()
    src.push(np.zeros(2, np.float32))
    import time

    deadline = time.monotonic() + 10
    while sink.rendered < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    p.stop()  # no end_of_stream() sent; must not hang
    assert sink.rendered == 1


def test_flexbuf_roundtrip_through_pipeline(tmp_path):
    # encode: tensors → flexbuf bytes file
    p1 = parse_pipeline(
        f"tensorsrc dimensions=3:2 types=float32 num-frames=1 pattern=ones ! "
        f"tensor_decoder mode=flexbuf ! filesink location={tmp_path}/f.flex"
    )
    p1.run(timeout=30)
    # decode: flexbuf bytes → tensors
    p2 = parse_pipeline(
        f"filesrc location={tmp_path}/f.flex ! tensor_converter mode=flexbuf ! "
        f"tensor_sink name=out"
    )
    p2.run(timeout=30)
    out = p2["out"].frames[0]
    assert out.tensors[0].shape == (2, 3)
    np.testing.assert_array_equal(np.asarray(out.tensors[0]), 1.0)


def test_audio_frames_per_tensor_batches():
    src = AudioTestSrc(**{"num-buffers": 4, "samples-per-buffer": 100})
    conv = TensorConverter(**{"frames-per-tensor": 2})
    sink = TensorSink()
    Pipeline().chain(src, conv, sink).run(timeout=30)
    assert sink.rendered == 2
    assert sink.frames[0].tensors[0].shape == (200, 1)


def test_direct_video_unbatches():
    src = VideoTestSrc(width=8, height=8, **{"num-frames": 4})
    conv = TensorConverter(**{"frames-per-tensor": 2})
    dec = TensorDecoder(mode="direct_video")
    sink = TensorSink()
    Pipeline().chain(src, conv, dec, sink).run(timeout=30)
    assert sink.rendered == 4  # 2 batched tensors → 4 media frames
    assert sink.frames[0].tensors[0].shape == (8, 8, 3)


def test_combination_empty_token_clean_error():
    from nnstreamer_tpu.elements.filter import _parse_combination

    with pytest.raises(ValueError, match="empty token"):
        _parse_combination("o0,,i1")


def test_deterministic_element_names():
    from nnstreamer_tpu.elements.flow import Queue

    a, b = Queue(), Queue()
    assert a.name != b.name
    assert a.name.startswith("queue")


def test_platform_pin_falls_back_when_relay_dead(monkeypatch):
    """A requested remote-accelerator platform with an unreachable relay
    must fall back to CPU instead of blocking on attach forever."""
    from nnstreamer_tpu import platform_pin

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(platform_pin, "_relay_reachable", lambda: False)
    platform_pin.honor_jax_platforms_env()
    import os

    assert os.environ["JAX_PLATFORMS"] == "cpu"
