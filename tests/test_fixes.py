"""Regression tests for review findings: timeouts, re-run guard, flexbuf
roundtrip, audio batching, appsrc shutdown, decoder un-batching."""

import numpy as np
import pytest

from nnstreamer_tpu.elements.converter import TensorConverter
from nnstreamer_tpu.elements.decoder import TensorDecoder
from nnstreamer_tpu.elements.sink import FakeSink, TensorSink
from nnstreamer_tpu.elements.sources import AppSrc, AudioTestSrc, TensorSrc, VideoTestSrc
from nnstreamer_tpu.pipeline.graph import Pipeline
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.spec import TensorsSpec


def test_run_timeout_raises():
    src = VideoTestSrc(width=8, height=8, **{"num-frames": -1})
    p = Pipeline().chain(src, TensorConverter(), FakeSink())
    with pytest.raises(TimeoutError):
        p.run(timeout=0.3)


def test_rerun_completed_pipeline_raises():
    p = Pipeline().chain(TensorSrc(dimensions="2", **{"num-frames": 1}), TensorSink())
    p.run(timeout=30)
    with pytest.raises(RuntimeError, match="already ran"):
        p.run(timeout=30)


def test_appsrc_stop_without_eos_does_not_hang():
    src = AppSrc(spec=TensorsSpec.from_strings("2", "float32"))
    sink = TensorSink()
    p = Pipeline().chain(src, sink)
    p.start()
    src.push(np.zeros(2, np.float32))
    import time

    deadline = time.monotonic() + 10
    while sink.rendered < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    p.stop()  # no end_of_stream() sent; must not hang
    assert sink.rendered == 1


def test_flexbuf_roundtrip_through_pipeline(tmp_path):
    # encode: tensors → flexbuf bytes file
    p1 = parse_pipeline(
        f"tensorsrc dimensions=3:2 types=float32 num-frames=1 pattern=ones ! "
        f"tensor_decoder mode=flexbuf ! filesink location={tmp_path}/f.flex"
    )
    p1.run(timeout=30)
    # decode: flexbuf bytes → tensors
    p2 = parse_pipeline(
        f"filesrc location={tmp_path}/f.flex ! tensor_converter mode=flexbuf ! "
        f"tensor_sink name=out"
    )
    p2.run(timeout=30)
    out = p2["out"].frames[0]
    assert out.tensors[0].shape == (2, 3)
    np.testing.assert_array_equal(np.asarray(out.tensors[0]), 1.0)


def test_audio_frames_per_tensor_batches():
    src = AudioTestSrc(**{"num-buffers": 4, "samples-per-buffer": 100})
    conv = TensorConverter(**{"frames-per-tensor": 2})
    sink = TensorSink()
    Pipeline().chain(src, conv, sink).run(timeout=30)
    assert sink.rendered == 2
    assert sink.frames[0].tensors[0].shape == (200, 1)


def test_direct_video_unbatches():
    src = VideoTestSrc(width=8, height=8, **{"num-frames": 4})
    conv = TensorConverter(**{"frames-per-tensor": 2})
    dec = TensorDecoder(mode="direct_video")
    sink = TensorSink()
    Pipeline().chain(src, conv, dec, sink).run(timeout=30)
    assert sink.rendered == 4  # 2 batched tensors → 4 media frames
    assert sink.frames[0].tensors[0].shape == (8, 8, 3)


def test_combination_empty_token_clean_error():
    from nnstreamer_tpu.elements.filter import _parse_combination

    with pytest.raises(ValueError, match="empty token"):
        _parse_combination("o0,,i1")


def test_deterministic_element_names():
    from nnstreamer_tpu.elements.flow import Queue

    a, b = Queue(), Queue()
    assert a.name != b.name
    assert a.name.startswith("queue")


def test_platform_pin_falls_back_when_relay_dead(monkeypatch):
    """A requested remote-accelerator platform with an unreachable relay
    must fall back to CPU instead of blocking on attach forever."""
    from nnstreamer_tpu import platform_pin

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(platform_pin, "_relay_reachable", lambda: False)
    platform_pin.honor_jax_platforms_env()
    import os

    assert os.environ["JAX_PLATFORMS"] == "cpu"


def test_device_crop_clips_to_dtype_range():
    """Integer crop outputs clip to the DTYPE's range, not 0..255 —
    0..255 would wrap int8 on astype and clamp valid uint16 values
    (ADVICE r3)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.elements.control import TensorCrop
    from nnstreamer_tpu.tensors.spec import TensorsSpec

    for dt, lo, hi in (("int8", -128, 127), ("uint16", 0, 65535)):
        crop = TensorCrop(**{"out-size": "2:2", "max-crops": 1})
        crop.negotiate(
            [
                TensorsSpec.from_strings("3:8:8:1", dt),
                TensorsSpec.from_strings("4:1", "uint32"),
            ]
        )
        # a bright uint16 image must survive >255; a negative int8 image
        # must keep its sign (the old clip(0,255) floor zeroed it)
        fill = 300.0 if dt == "uint16" else -100.0
        img = jnp.full((1, 8, 8, 3), fill, dt)
        boxes = jnp.asarray([[0, 0, 4, 4]], jnp.float32)
        crops, _ = crop._jit_crop(img, boxes)
        assert crops.dtype == np.dtype(dt)
        vals = np.asarray(crops)
        if dt == "uint16":
            assert vals.max() == 300  # preserved, not clamped to 255
        else:
            assert vals.min() == -100  # preserved, not floored at 0


def test_ngram_lookup_distinguishes_no_match():
    """ngram_lookup returns None (not zeros) when the context tail has
    no earlier occurrence — spec_step uses this to skip wasted verify
    columns (ADVICE r3)."""
    from nnstreamer_tpu.models.speculative import ngram_lookup, ngram_propose

    ctx = np.asarray([5, 6, 7, 8], np.int32)  # tail [8] appears once only
    assert ngram_lookup(ctx, 3, 1) is None
    assert list(ngram_propose(ctx, 3, 1)) == [0, 0, 0]  # padded form
    rep = np.asarray([1, 2, 9, 1, 2], np.int32)  # tail [2] seen earlier
    got = ngram_lookup(rep, 2, 1)
    assert got is not None and list(got) == [9, 1]


def test_spec_context_includes_prefix_tokens():
    """submit(prefix=id) requests carry the PREFIX tokens in their
    spec_step proposal context (ADVICE r3: n-gram matches often live in
    the shared system prompt)."""
    import jax

    from nnstreamer_tpu.models import transformer as tfm
    from nnstreamer_tpu.models.serving import ContinuousBatcher

    params = tfm.init_params(
        jax.random.PRNGKey(0), vocab=64, d_model=32, n_heads=2, n_layers=1
    )
    cb = ContinuousBatcher(params, 2, n_slots=1, max_len=64, prompt_len=8)
    pfx_toks = np.asarray([3, 4, 5, 6, 7, 9, 11, 13], np.int32)
    pid = cb.register_prefix(pfx_toks)
    rid = cb.submit(np.asarray([1, 2], np.int32), 2, prefix=pid)
    (req,) = [r for r in cb._slots if r is not None] or [
        p.req for p in cb._pending
    ]
    assert list(req.prompt[: len(pfx_toks)]) == list(pfx_toks)
    while cb.result(rid) is None:
        cb.spec_step(k=3)


def test_chan_2deep_lockstep_stays_under_one_beat():
    """Regression (_Chan wake discipline): a 2-deep channel in strict
    producer/consumer lockstep must never eat a 50 ms wait beat — the
    consumer draining to the low-water mark between the producer's
    checks has to wake it (the Dekker advertise-then-recheck pairing).
    32 items through a full channel finish in well under one beat."""
    import threading
    import time

    from nnstreamer_tpu.pipeline.executor import _Chan

    stop = threading.Event()
    ch = _Chan(2)
    n = 32
    got = []

    def consume():
        while len(got) < n:
            got.append(ch.get(stop))

    t = threading.Thread(target=consume, daemon=True)
    t0 = time.perf_counter()
    t.start()
    for i in range(n):
        ch.put(i, stop)
    t.join(timeout=5)
    elapsed = time.perf_counter() - t0
    assert got == list(range(n))
    # a genuinely missed wake costs a 50 ms beat per parked put (~1.5 s
    # for 32 items through a 2-deep channel); the bound discriminates
    # that while absorbing loaded-runner scheduling noise
    assert elapsed < 0.5, f"missed wake: {elapsed*1000:.1f} ms for {n} items"


def test_chan_drain_wakes_parked_producer():
    """Regression (batch-collector interaction): drain() stops above the
    low-water mark and the consumer then computes for a whole batch — a
    parked producer must still be woken the moment space frees, not
    sleep out its 50 ms beat."""
    import threading
    import time

    from nnstreamer_tpu.pipeline.executor import _Chan

    stop = threading.Event()

    def attempt() -> float:
        ch = _Chan(8)
        for i in range(8):
            ch.put(i, stop)  # fill: next put parks
        put_done = threading.Event()

        def producer():
            ch.put(8, stop)
            put_done.set()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.01)  # let the producer park
        t0 = time.perf_counter()
        items = ch.drain(2)  # 8→6: above low-water (4), space freed
        assert items == [0, 1]
        assert put_done.wait(timeout=1.0)
        woke_ms = (time.perf_counter() - t0) * 1000
        t.join(timeout=1)
        return woke_ms

    # min-of-3: a missed wake is deterministic (every attempt sleeps the
    # full 50 ms beat), while scheduler noise on a loaded runner is not
    best = min(attempt() for _ in range(3))
    assert best < 40, f"producer slept a full beat: {best:.1f} ms"
