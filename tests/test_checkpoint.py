"""Checkpoint/resume tests: sharded save → sharded restore round-trip on
the virtual mesh (the training-state persistence the reference never
needed, SURVEY.md §5.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from nnstreamer_tpu.parallel import checkpoint as ckpt
from nnstreamer_tpu.parallel.mesh import make_mesh


def test_roundtrip_host(tmp_path):
    state = {"w": np.arange(8, dtype=np.float32), "step": np.int32(3)}
    path = str(tmp_path / "c1")
    ckpt.save(path, state)
    back = ckpt.restore(path)
    np.testing.assert_array_equal(back["w"], state["w"])
    assert int(back["step"]) == 3


def test_roundtrip_sharded(tmp_path):
    mesh = make_mesh(8, axes=("dp",))
    shard = NamedSharding(mesh, P("dp"))
    w = jax.device_put(jnp.arange(16, dtype=jnp.float32), shard)
    path = str(tmp_path / "c2")
    ckpt.save(path, {"w": w})
    back = ckpt.restore(path, like={"w": w}, shardings={"w": shard})
    assert back["w"].sharding == shard
    np.testing.assert_array_equal(np.asarray(back["w"]), np.arange(16))


def test_resume_training_state(tmp_path):
    """Save mid-training, restore, and verify the next step is identical
    to an uninterrupted run."""
    from nnstreamer_tpu.parallel import lm

    mesh = make_mesh(8, axes=("dp", "sp"), shape=(2, 4))
    params = lm.init_lm_params(
        jax.random.PRNGKey(0), vocab=32, d_model=32, n_heads=4, n_layers=1
    )
    step, params = lm.make_lm_train_step(mesh, params, n_heads=4)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 32, (2, 17)), jnp.int32)
    params, _ = step(params, toks)
    saved = jax.tree.map(lambda x: np.asarray(x), params)  # snapshot
    path = str(tmp_path / "c3")
    ckpt.save(path, params)

    params_cont, loss_cont = step(params, toks)  # uninterrupted

    p_shard = lm.param_shardings(mesh, saved, None)
    restored = ckpt.restore(path, like=saved, shardings=p_shard)
    params_res, loss_res = step(restored, toks)
    assert float(loss_res) == pytest.approx(float(loss_cont), abs=1e-6)


def test_latest_step(tmp_path):
    assert ckpt.latest_step(str(tmp_path)) is None
    for s in (1, 5, 3):
        ckpt.save(ckpt.step_path(str(tmp_path), s), {"x": np.zeros(1)})
    assert ckpt.latest_step(str(tmp_path)) == 5
