"""Continuous-batching server tests (models/serving.py).

The load-bearing invariant: a request served in a busy, staggered batch
produces exactly the greedy tokens models/decode.generate() produces for
it alone — slots are isolated despite sharing one cache array and one
compiled step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.models import decode as dec
from nnstreamer_tpu.models import transformer as tfm
from nnstreamer_tpu.models.serving import ContinuousBatcher

N_HEADS = 4


@pytest.fixture(scope="module")
def params():
    return tfm.init_params(
        jax.random.PRNGKey(7), vocab=257, d_model=64, n_heads=N_HEADS,
        n_layers=2,
    )


def _prompt(n, seed):
    return np.random.default_rng(seed).integers(1, 257, (n,)).astype(np.int32)


def _alone(params, prompt, n_new):
    toks = dec.generate(
        params, jnp.asarray(prompt)[None, :], N_HEADS, n_new
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _sliding_reference(params, prompt, n_new, W):
    """Greedy tokens under EXACT sliding-window attention: every token
    (prompt ingestion included) attends precisely the previous W
    positions, computed token-by-token on an UNBOUNDED cache with a
    banded mask — the ground truth the W-ring implementations must
    reproduce bit-exactly."""
    import functools

    from nnstreamer_tpu.models.serving import batched_decode_step

    def attn(q, ck, cv, pos):
        idx = jnp.arange(ck.shape[1])[None, :]
        mask = (idx <= pos[:, None]) & (idx > pos[:, None] - W)
        return tfm.cache_attention(q, ck, cv, mask[:, None, :])

    step = jax.jit(
        functools.partial(
            batched_decode_step, params, n_heads=N_HEADS, attn_fn=attn
        )
    )
    L, d = params["blocks"]["ln1"].shape
    kv = tfm.n_kv_heads_of(params["blocks"]["wqkv"], d, N_HEADS)
    hd = d // N_HEADS
    max_len = len(prompt) + n_new + 1
    cache = (
        jnp.zeros((L, 1, max_len, kv, hd)),
        jnp.zeros((L, 1, max_len, kv, hd)),
    )
    pos = jnp.asarray([0], jnp.int32)
    active = jnp.asarray([True])
    logits = None
    for t in prompt:
        logits, cache, pos = step(
            jnp.asarray([int(t)], jnp.int32), pos, active, cache
        )
    out = []
    for _ in range(n_new):
        tok = int(np.asarray(jnp.argmax(logits[0])))
        out.append(tok)
        logits, cache, pos = step(
            jnp.asarray([tok], jnp.int32), pos, active, cache
        )
    return out


def test_single_request_matches_generate(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                           prompt_len=16)
    prompt = _prompt(10, 0)
    rid = cb.submit(prompt, 8)
    while cb.result(rid) is None:
        assert cb.step()  # must make progress
    assert cb.result(rid) == _alone(params, prompt, 8)


def test_staggered_requests_are_isolated(params):
    """B joins mid-flight while A decodes; both match their solo runs."""
    cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                           prompt_len=16)
    pa, pb = _prompt(12, 1), _prompt(5, 2)
    ra = cb.submit(pa, 10)
    for _ in range(3):
        cb.step()
    rb = cb.submit(pb, 6)
    while cb.result(ra) is None or cb.result(rb) is None:
        cb.step()
    assert cb.result(ra) == _alone(params, pa, 10)
    assert cb.result(rb) == _alone(params, pb, 6)


def test_slot_reuse_after_finish(params):
    """A finishes, C takes its slot while B still runs; C is unpolluted
    by A's stale cache."""
    cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                           prompt_len=16)
    pa, pb, pc = _prompt(8, 3), _prompt(8, 4), _prompt(14, 5)
    ra = cb.submit(pa, 3)
    rb = cb.submit(pb, 12)
    assert cb.submit(_prompt(4, 9), 2) is None  # batch full
    while cb.result(ra) is None:
        cb.step()
    rc = cb.submit(pc, 7)
    assert rc is not None
    while cb.result(rb) is None or cb.result(rc) is None:
        cb.step()
    assert cb.result(ra) == _alone(params, pa, 3)
    assert cb.result(rb) == _alone(params, pb, 12)
    assert cb.result(rc) == _alone(params, pc, 7)


def test_validation(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                           prompt_len=16)
    with pytest.raises(ValueError, match="empty prompt"):
        cb.submit(np.zeros((0,), np.int32), 4)
    with pytest.raises(ValueError, match="overflow"):
        cb.submit(np.ones((16,), np.int32), 200)
    with pytest.raises(ValueError, match="max_new_tokens"):
        cb.submit(np.ones((4,), np.int32), 0)
    with pytest.raises(ValueError, match="prompt_len"):
        ContinuousBatcher(params, N_HEADS, max_len=8, prompt_len=16)


def test_budget_one_finishes_at_submit(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                           prompt_len=16)
    prompt = _prompt(6, 6)
    rid = cb.submit(prompt, 1)
    assert cb.result(rid) == _alone(params, prompt, 1)
    assert cb.n_free == 1
    assert cb.step() == {}


def test_done_pool_bounded(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                           prompt_len=8, keep_results=3)
    rids = []
    for seed in range(5):
        rids.append(cb.submit(_prompt(4, seed), 1))
    assert len(cb._done_pool) == 3
    assert cb.result(rids[0]) is None  # evicted (oldest)
    assert cb.result(rids[-1]) is not None


class TestInt8Cache:
    """cache_dtype="int8": 4x smaller KV cache (serving.quantize_kv)."""

    def test_step_logits_close_to_float_cache(self, params):
        from nnstreamer_tpu.models.serving import (
            batched_decode_step, insert_slot, quantize_kv, dequantize_kv,
        )

        prompt = _prompt(10, 11)
        logits_p, (ks, vs), _ = dec.prefill(
            params, jnp.asarray(prompt)[None, :], N_HEADS, 16
        )
        L, _, _, H, Dh = ks.shape
        shape = (L, 2, 32, H, Dh)
        fcache = (jnp.zeros(shape), jnp.zeros(shape))
        qcache = (
            (jnp.zeros(shape, jnp.int8), jnp.ones(shape[:-1])),
            (jnp.zeros(shape, jnp.int8), jnp.ones(shape[:-1])),
        )
        fcache = insert_slot(fcache, ks, vs, 0)
        qcache = insert_slot(qcache, ks, vs, 0)
        tok = jnp.asarray([3, 0], jnp.int32)
        pos = jnp.asarray([10, 0], jnp.int32)
        active = jnp.asarray([True, False])
        lf, _, _ = batched_decode_step(
            params, tok, pos, active, fcache, N_HEADS
        )
        lq, _, _ = batched_decode_step(
            params, tok, pos, active, qcache, N_HEADS
        )
        a, b = np.asarray(lf[0]), np.asarray(lq[0])
        cos = (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b))
        assert cos > 0.995, f"cosine {cos}"

    def test_quantize_roundtrip_error_bounded(self, params):
        from nnstreamer_tpu.models.serving import quantize_kv, dequantize_kv

        t = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 3, 16))
        q8, sc = quantize_kv(t)
        assert q8.dtype == jnp.int8 and sc.shape == (2, 4, 3)
        err = np.abs(np.asarray(dequantize_kv(q8, sc) - t))
        # symmetric int8: error ≤ half a quantization step per head
        assert (err <= np.asarray(sc)[..., None] * 0.5 + 1e-7).all()

    def test_end_to_end_int8_cache(self, params):
        cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                               prompt_len=16, cache_dtype="int8")
        pa, pb = _prompt(12, 12), _prompt(6, 13)
        ra = cb.submit(pa, 8)
        rb = cb.submit(pb, 8)
        while cb.result(ra) is None or cb.result(rb) is None:
            assert cb.step() or cb.result(ra) is not None
        # int8 rounding may drift argmax on random-weight logits; the
        # float-cache run must at least agree on the prefill-derived
        # first token (prefill is float in both)
        assert cb.result(ra)[0] == _alone(params, pa, 1)[0]
        assert len(cb.result(ra)) == 8 and len(cb.result(rb)) == 8

    def test_pallas_composes_with_int8(self, params):
        """The decode kernel reads the int8 cache directly (scale
        operands, VMEM dequant) — tokens match the inline-XLA int8 path
        exactly (both attend the same dequantized values)."""
        prompt = _prompt(9, 14)
        outs = {}
        for impl in ("xla", "pallas"):
            cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=48,
                                   prompt_len=16, cache_dtype="int8",
                                   attn_impl=impl)
            rid = cb.submit(prompt, 8)
            while cb.result(rid) is None:
                cb.step()
            outs[impl] = cb.result(rid)
        assert outs["xla"] == outs["pallas"]


def test_submit_releases_slot_when_prefill_fails(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                           prompt_len=8)

    def boom(_):
        raise RuntimeError("prefill exploded")

    cb._prefill = boom
    with pytest.raises(RuntimeError, match="prefill exploded"):
        cb.submit(_prompt(4, 20), 2)
    assert cb.n_free == 1  # slot released, server still serviceable


def test_mesh_sharded_slots_match_unsharded(params):
    """Slots sharded over an 8-device mesh (SPMD decode) produce the same
    greedy tokens as the single-device batcher."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axes=("dp",))
    prompts = [_prompt(4 + i, 30 + i) for i in range(3)]
    outs = {}
    for label, kw in (("plain", {}), ("mesh", dict(mesh=mesh))):
        cb = ContinuousBatcher(params, N_HEADS, n_slots=8, max_len=32,
                               prompt_len=16, **kw)
        rids = [cb.submit(p, 5) for p in prompts]
        while any(cb.result(r) is None for r in rids):
            cb.step()
        outs[label] = [cb.result(r) for r in rids]
    assert outs["plain"] == outs["mesh"]


def test_mesh_requires_divisible_slots(params):
    from nnstreamer_tpu.parallel.mesh import make_mesh

    with pytest.raises(ValueError, match="divide"):
        ContinuousBatcher(params, N_HEADS, n_slots=3,
                          mesh=make_mesh(8, axes=("dp",)))


def test_mesh_plus_pallas_matches_unsharded(params):
    """attn_impl='pallas' + mesh=: the step program is shard_mapped over
    the slot axis, each device running the kernel on its local slots —
    tokens match the unsharded pallas batcher."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axes=("dp",))
    prompts = [_prompt(5 + i, 35 + i) for i in range(2)]
    outs = {}
    for label, kw in (
        ("plain", {}),
        ("mesh", dict(mesh=mesh)),
    ):
        cb = ContinuousBatcher(params, N_HEADS, n_slots=8, max_len=32,
                               prompt_len=16, attn_impl="pallas", **kw)
        rids = [cb.submit(p, 5) for p in prompts]
        while any(cb.result(r) is None for r in rids):
            cb.step()
        outs[label] = [cb.result(r) for r in rids]
    assert outs["plain"] == outs["mesh"]


class TestSampling:
    def test_sampled_deterministic_per_seed(self, params):
        outs = []
        for _ in range(2):
            cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=48,
                                   prompt_len=16)
            rid = cb.submit(_prompt(8, 40), 10, temperature=0.9, seed=123)
            while cb.result(rid) is None:
                cb.step()
            outs.append(cb.result(rid))
        assert outs[0] == outs[1]

    def test_different_seeds_diverge(self, params):
        outs = []
        for seed in (1, 2):
            cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=48,
                                   prompt_len=16)
            rid = cb.submit(_prompt(8, 41), 12, temperature=1.5, seed=seed)
            while cb.result(rid) is None:
                cb.step()
            outs.append(cb.result(rid))
        assert outs[0] != outs[1]  # astronomically unlikely to collide

    def test_mixed_batch_greedy_stream_unaffected(self, params):
        """A sampling request sharing the batch must not perturb a greedy
        request's tokens (host-side picks are per-slot)."""
        pg = _prompt(9, 42)
        cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=48,
                               prompt_len=16)
        rg = cb.submit(pg, 8)  # greedy
        rs = cb.submit(_prompt(5, 43), 8, temperature=1.0, seed=7)
        while cb.result(rg) is None or cb.result(rs) is None:
            cb.step()
        assert cb.result(rg) == _alone(params, pg, 8)

    def test_top_k_one_is_greedy(self, params):
        p = _prompt(7, 44)
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=48,
                               prompt_len=16)
        rid = cb.submit(p, 6, temperature=0.8, top_k=1, seed=5)
        while cb.result(rid) is None:
            cb.step()
        assert cb.result(rid) == _alone(params, p, 6)


def test_stop_token_ends_request_early(params):
    """The request finishes as soon as its stop token is emitted; the
    stop token stays in the output (EOS-id semantics)."""
    prompt = _prompt(8, 60)
    full = _alone(params, prompt, 12)
    stop = full[4]  # force an early stop at a token we know appears
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=64,
                           prompt_len=16)
    rid = cb.submit(prompt, 12, stop_token=stop)
    while cb.result(rid) is None:
        cb.step()
    got = cb.result(rid)
    assert got == full[:5]
    assert got[-1] == stop
    assert cb.n_free == 1


class TestSlidingWindow:
    def test_window_large_enough_matches_plain(self, params):
        """When no wrap happens, windowed == plain (same programs,
        identical ring/prefix masks)."""
        prompt = _prompt(10, 70)
        outs = {}
        for label, kw in (("plain", {}), ("ring", dict(windowed=True))):
            cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=64,
                                   prompt_len=16, **kw)
            rid = cb.submit(prompt, 8)
            while cb.result(rid) is None:
                cb.step()
            outs[label] = cb.result(rid)
        assert outs["plain"] == outs["ring"]

    def test_generation_beyond_cache_length(self, params):
        """A generation much longer than the cache runs in fixed memory
        and every token is finite/valid (the whole point of the ring)."""
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=24,
                               prompt_len=16, windowed=True)
        prompt = _prompt(8, 71)
        rid = cb.submit(prompt, 60)  # 8 + 60 >> 24
        while cb.result(rid) is None:
            assert cb.step()
        toks = cb.result(rid)
        assert len(toks) == 60
        assert all(0 <= t < 257 for t in toks)

    def test_ring_matches_sliding_mask_on_unbounded_cache(self, params):
        """The real post-wrap check: the ring stream must equal a
        reference stream computed on an UNBOUNDED cache whose attention
        is masked to exactly the last W positions (_sliding_reference) —
        byte-identical through many wrapped steps."""
        W = 16
        n_new = 40  # wraps the W-ring several times
        prompt = _prompt(10, 72)
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=W,
                               prompt_len=16, windowed=True)
        rid = cb.submit(prompt, n_new)
        while cb.result(rid) is None:
            cb.step()
        assert cb.result(rid) == _sliding_reference(params, prompt, n_new, W)

    def test_ring_with_pallas_kernel(self, params):
        """windowed composes with the Pallas kernel (its <=pos mask
        saturates identically past the wrap)."""
        prompt = _prompt(8, 73)
        outs = {}
        for impl in ("xla", "pallas"):
            cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=16,
                                   prompt_len=16, windowed=True,
                                   attn_impl=impl)
            rid = cb.submit(prompt, 20)
            while cb.result(rid) is None:
                cb.step()
            outs[impl] = cb.result(rid)
        assert outs["xla"] == outs["pallas"]


class TestChunkedPrefill:
    @pytest.mark.parametrize("plen", [17, 32, 41])  # partial/exact/2.5 buckets
    def test_long_prompt_matches_generate(self, params, plen):
        """Prompts longer than the bucket prefill in chunks and still
        yield exactly the solo-generation tokens."""
        prompt = _prompt(plen, 80 + plen)
        cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                               prompt_len=16)
        rid = cb.submit(prompt, 6)
        while cb.result(rid) is None:
            cb.step()
        assert cb.result(rid) == _alone(params, prompt, 6)

    def test_prompt_beyond_cache_rejected(self, params):
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                               prompt_len=16)
        with pytest.raises(ValueError, match="> max_len"):
            cb.submit(_prompt(40, 90), 2)

    @pytest.mark.parametrize("plen", [20, 32, 50])  # ≤W, =W, wraps W
    def test_windowed_long_prompt_matches_sliding_reference(
        self, params, plen
    ):
        """Windowed chunked prefill (decode.windowed_chunk ring prefill)
        matches a reference computed on an unbounded cache with an exact
        sliding-window attention mask — including prompts LONGER than
        the window (the ring keeps the last W prompt tokens)."""
        W = 32
        n_new = 6
        prompt = _prompt(plen, 91 + plen)
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=W,
                               prompt_len=16, windowed=True)
        rid = cb.submit(prompt, n_new)
        while cb.result(rid) is None:
            cb.step()
        assert cb.result(rid) == _sliding_reference(
            params, prompt, n_new, W
        )

    def test_windowed_chunk_alignment_required(self, params):
        """Unaligned windowed configs serve bucket-sized prompts fine;
        a LONG prompt is rejected before any slot is claimed."""
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=24,
                               prompt_len=16, windowed=True)
        with pytest.raises(ValueError, match="multiple of prompt_len"):
            cb.submit(_prompt(20, 95), 2)
        assert cb.n_free == 1  # nothing claimed by the rejected submit


class TestPrefixCaching:
    def test_prefix_matches_concat_prompt(self, params):
        """submit(prefix=id) yields exactly the tokens of solo generation
        on prefix+prompt — for short, bucket-crossing, and multi-bucket
        prefix lengths."""
        cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=96,
                               prompt_len=16)
        for plen, tlen in ((5, 7), (16, 10), (23, 20), (37, 4)):
            pfx_toks = _prompt(plen, 100 + plen)
            prompt = _prompt(tlen, 200 + tlen)
            pid = cb.register_prefix(pfx_toks)
            rid = cb.submit(prompt, 6, prefix=pid)
            while cb.result(rid) is None:
                cb.step()
            full = np.concatenate([pfx_toks, prompt])
            assert cb.result(rid) == _alone(params, full, 6), (
                f"prefix {plen} + prompt {tlen} diverged"
            )

    def test_prefix_shared_across_requests(self, params):
        """Two concurrent requests share one registered prefix."""
        cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=64,
                               prompt_len=16)
        pfx_toks = _prompt(12, 300)
        pid = cb.register_prefix(pfx_toks)
        pa, pb = _prompt(6, 301), _prompt(9, 302)
        ra = cb.submit(pa, 5, prefix=pid)
        rb = cb.submit(pb, 5, prefix=pid)
        while cb.result(ra) is None or cb.result(rb) is None:
            cb.step()
        assert cb.result(ra) == _alone(params, np.concatenate([pfx_toks, pa]), 5)
        assert cb.result(rb) == _alone(params, np.concatenate([pfx_toks, pb]), 5)

    def test_prefix_validation(self, params):
        cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                               prompt_len=16)
        with pytest.raises(ValueError, match="unknown prefix"):
            cb.submit(_prompt(4, 310), 2, prefix=99)
        pid = cb.register_prefix(_prompt(20, 311))
        with pytest.raises(ValueError, match="> max_len"):
            cb.submit(_prompt(13, 312), 2, prefix=pid)
        wcb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                                prompt_len=16, windowed=True)
        # windowed prefixes must be bucket-aligned (continuation chunks
        # start at base=plen and must not wrap the ring mid-write)
        with pytest.raises(ValueError, match="multiple of prompt_len"):
            wcb.register_prefix(_prompt(4, 313))
        assert wcb.register_prefix(_prompt(16, 314)) is not None

    def test_windowed_prefix_matches_concat_prompt(self, params):
        """windowed × prefix caching (r4): a prefix always starts at
        absolute position 0, so its ring placement is request-invariant
        — submit(prefix=id) must equal submitting the concatenated
        prompt to a fresh windowed batcher, including through ring
        wraps during generation."""
        W = 32
        pfx_toks = _prompt(16, 330)
        tail = _prompt(6, 331)
        n_new = 30  # 16 + 6 + 30 wraps the W=32 ring
        wcb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=W,
                                prompt_len=16, windowed=True)
        pid = wcb.register_prefix(pfx_toks)
        rid = wcb.submit(tail, n_new, prefix=pid)
        while wcb.result(rid) is None:
            wcb.step()
        ref = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=W,
                                prompt_len=16, windowed=True)
        rr = ref.submit(np.concatenate([pfx_toks, tail]), n_new)
        while ref.result(rr) is None:
            ref.step()
        assert wcb.result(rid) == ref.result(rr)
        # and both equal the exact sliding-window ground truth
        assert wcb.result(rid) == _sliding_reference(
            params, np.concatenate([pfx_toks, tail]), n_new, W
        )

    def test_windowed_prefix_longer_than_window(self, params):
        """A windowed prefix may exceed the window: the stored ring
        holds its last W tokens — exactly what sliding-window semantics
        prescribe for any prefix that long."""
        W = 32
        pfx_toks = _prompt(48, 332)  # 1.5× the window, 3 buckets
        tail = _prompt(5, 333)
        wcb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=W,
                                prompt_len=16, windowed=True)
        pid = wcb.register_prefix(pfx_toks)
        rid = wcb.submit(tail, 8, prefix=pid)
        while wcb.result(rid) is None:
            wcb.step()
        assert wcb.result(rid) == _sliding_reference(
            params, np.concatenate([pfx_toks, tail]), 8, W
        )

    def test_windowed_prefix_with_spec_step(self, params):
        """prefix × windowed × speculation all compose: the spec pump
        serves a prefixed windowed request and matches the plain pump."""
        W = 32
        pfx_toks = np.tile(np.asarray([3, 4, 5, 6], np.int32), 4)  # 16
        tail = np.asarray([3, 4, 5], np.int32)

        def run(spec):
            wcb = ContinuousBatcher(params, N_HEADS, n_slots=1,
                                    max_len=W, prompt_len=16,
                                    windowed=True)
            pid = wcb.register_prefix(pfx_toks)
            rid = wcb.submit(tail, 20, prefix=pid)
            while wcb.result(rid) is None:
                wcb.spec_step(ngram=1) if spec else wcb.step()
            return wcb.result(rid)

        assert run(True) == run(False)


def test_unregister_prefix_releases(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=32,
                           prompt_len=16)
    pid = cb.register_prefix(_prompt(8, 320))
    assert cb.unregister_prefix(pid)
    assert not cb.unregister_prefix(pid)
    with pytest.raises(ValueError, match="unknown prefix"):
        cb.submit(_prompt(4, 321), 2, prefix=pid)


def test_stats_surface(params):
    cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=32,
                           prompt_len=16)
    assert cb.stats()["steps"] == 0
    rid = cb.submit(_prompt(6, 400), 5)
    while cb.result(rid) is None:
        cb.step()
    s = cb.stats()
    assert s["steps"] == 4  # first token came from prefill
    assert s["tokens_emitted"] == 4
    assert s["decode_tok_s"] > 0
    assert s["slots_free"] == 2
    assert s["results_pending_pickup"] == 1


def test_mesh_with_int8_cache(params):
    """Slot sharding composes with the quantized cache (scale leaves
    shard on the same slot axis)."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(8, axes=("dp",))
    prompt = _prompt(6, 500)
    cb = ContinuousBatcher(params, N_HEADS, n_slots=8, max_len=32,
                           prompt_len=16, mesh=mesh, cache_dtype="int8")
    rid = cb.submit(prompt, 5)
    while cb.result(rid) is None:
        cb.step()
    plain = ContinuousBatcher(params, N_HEADS, n_slots=8, max_len=32,
                              prompt_len=16, cache_dtype="int8")
    rid2 = plain.submit(prompt, 5)
    while plain.result(rid2) is None:
        plain.step()
    assert cb.result(rid) == plain.result(rid2)


def test_top_p_tiny_is_greedy_and_deterministic(params):
    """top_p small enough keeps only the argmax token → equals greedy;
    and a mid-range top_p is deterministic per seed."""
    p = _prompt(7, 600)
    cb = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=48,
                           prompt_len=16)
    rid = cb.submit(p, 6, temperature=0.7, top_p=1e-9, seed=3)
    while cb.result(rid) is None:
        cb.step()
    assert cb.result(rid) == _alone(params, p, 6)
    outs = []
    for _ in range(2):
        cb2 = ContinuousBatcher(params, N_HEADS, n_slots=1, max_len=48,
                                prompt_len=16)
        r = cb2.submit(p, 8, temperature=1.2, top_p=0.8, seed=9)
        while cb2.result(r) is None:
            cb2.step()
        outs.append(cb2.result(r))
    assert outs[0] == outs[1]


def test_device_sampling_at_real_vocab(params):
    """Device-side sampling at a realistic (32k) vocab: the step program
    samples on device and transfers ONE token id per slot — the [B, V]
    logits (128 KB/slot/step at 32k) never cross to host. Validity +
    determinism checked; mixed greedy/sampled batch served together."""
    big = tfm.init_params(
        jax.random.PRNGKey(11), vocab=32768, d_model=64, n_heads=N_HEADS,
        n_layers=1,
    )
    outs = []
    for _ in range(2):
        cb = ContinuousBatcher(big, N_HEADS, n_slots=2, max_len=32,
                               prompt_len=8)
        rs = cb.submit(
            np.asarray([5, 17, 900], np.int32), 6,
            temperature=1.0, top_k=50, top_p=0.9, seed=42,
        )
        rg = cb.submit(np.asarray([3, 4], np.int32), 6)  # greedy neighbor
        while cb.result(rs) is None or cb.result(rg) is None:
            cb.step()
        assert all(0 <= t < 32768 for t in cb.result(rs))
        outs.append((cb.result(rs), cb.result(rg)))
    assert outs[0] == outs[1]  # deterministic per (seed, position)


def test_concurrent_submit_spec_and_streaming_soak(params):
    """Concurrency soak: one thread pumps spec rounds, one pumps plain
    steps, two submitter threads race admissions, and a reader polls
    partials — no deadlock, every request completes, and every greedy
    result matches its solo generation (slot isolation under real
    thread interleaving, the Python-side analogue of the TSAN suites)."""
    import threading

    cb = ContinuousBatcher(params, N_HEADS, n_slots=4, max_len=96,
                           prompt_len=16)
    prompts = [_prompt(4 + i % 9, 400 + i) for i in range(12)]
    rids: dict = {}
    rid_lock = threading.Lock()
    stop = threading.Event()

    def pump(spec):
        while not stop.is_set():
            (cb.spec_step(k=3, ngram=1) if spec else cb.step())

    def submitter(idx0):
        for i in range(idx0, len(prompts), 2):
            while True:
                rid = cb.submit(prompts[i], 6)
                if rid is not None:
                    with rid_lock:
                        rids[i] = rid
                    break
                cb.step()  # batch full: pumping IS the backpressure

    def reader():
        while not stop.is_set():
            with rid_lock:
                known = list(rids.values())
            cb.partials(known)

    threads = [
        threading.Thread(target=pump, args=(True,), daemon=True),
        threading.Thread(target=pump, args=(False,), daemon=True),
        threading.Thread(target=reader, daemon=True),
    ]
    subs = [
        threading.Thread(target=submitter, args=(k,), daemon=True)
        for k in (0, 1)
    ]
    for t in threads + subs:
        t.start()
    for t in subs:
        t.join(timeout=300)
        assert not t.is_alive(), "submitter deadlocked"
    deadline = __import__("time").monotonic() + 300
    while True:
        with rid_lock:
            done = (
                len(rids) == len(prompts)
                and all(cb.result(r) is not None for r in rids.values())
            )
        if done:
            break
        assert __import__("time").monotonic() < deadline, "requests stuck"
    stop.set()
    for t in threads:
        t.join(timeout=10)
    for i, rid in rids.items():
        assert cb.result(rid) == _alone(params, prompts[i], 6), (
            f"request {i} diverged under concurrency"
        )


def test_mesh_with_draft_speculation_matches_unsharded(params):
    """mesh-sharded slots × draft speculation: the spec-round program
    GSPMD-partitions over the slot axis and the (replicated) draft's
    batched proposals feed it — same tokens as the unsharded batcher."""
    from nnstreamer_tpu.parallel.mesh import make_mesh

    draft = tfm.init_params(
        jax.random.PRNGKey(77), vocab=257, d_model=32, n_heads=2,
        n_layers=1,
    )
    mesh = make_mesh(8, axes=("dp",))
    prompts = [_prompt(4 + i, 60 + i) for i in range(3)]
    outs = {}
    for label, kw in (("plain", {}), ("mesh", dict(mesh=mesh))):
        cb = ContinuousBatcher(params, N_HEADS, n_slots=8, max_len=48,
                               prompt_len=16, draft_params=draft,
                               draft_n_heads=2, **kw)
        rids = [cb.submit(p, 6) for p in prompts]
        while any(cb.result(r) is None for r in rids):
            cb.spec_step(k=3)
        outs[label] = [cb.result(r) for r in rids]
        assert cb.stats()["spec_rounds"] > 0
    assert outs["plain"] == outs["mesh"]


def test_latency_telemetry_surface(params):
    """stats() reports p50 TTFT and p50 request wall time from bounded
    per-request windows — the serving analogue of the pipeline's
    wall-stamped p50-e2e cell (BASELINE 'p50 e2e tracked')."""
    cb = ContinuousBatcher(params, N_HEADS, n_slots=2, max_len=48,
                           prompt_len=16)
    rids = [cb.submit(_prompt(5 + i, 900 + i), 4) for i in range(2)]
    while any(cb.result(r) is None for r in rids):
        cb.step_pump(4)
    st = cb.stats()
    assert st["p50_ttft_ms"] > 0.0
    assert st["p50_request_s"] > 0.0
    assert st["p50_request_s"] * 1000.0 >= st["p50_ttft_ms"]
