"""Model parity against the reference's REAL pretrained fixtures.

VERDICT r4 #2: prior rounds proved cross-engine agreement on our own
seeded models; these tests prove the actual reference networks run in
this framework — the canonical .tflite files the reference tests
against (tests/test_models/models/, loaded by
tensor_filter_tensorflow_lite.cc:154-218) are read read-only, their
weights imported, and outputs compared against the real TFLite
interpreter:

- mobilenet_v2_1.0_224_quant.tflite → models/mobilenet_v2.py via
  load_tflite_params (from-scratch topology + imported weights):
  top-1 label agreement on 10 fixture images
- the same file compiled whole-graph to XLA (tools/tflite_exec) and
  run through the FULL pipeline (converter ! filter ! decoder
  image_labeling) with the reference labels file
- deeplabv3_257_mv_gpu.tflite compiled to XLA: per-pixel argmax mask
  IoU vs the interpreter, plus the full image_segment pipeline
"""

import os

import numpy as np
import pytest

REF = "/root/reference/tests/test_models"
MOBILENET = f"{REF}/models/mobilenet_v2_1.0_224_quant.tflite"
DEEPLAB = f"{REF}/models/deeplabv3_257_mv_gpu.tflite"
LABELS = f"{REF}/labels/labels.txt"

pytestmark = pytest.mark.skipif(
    not os.path.isfile(MOBILENET), reason="reference fixtures unavailable"
)


def _interpreter(path):
    try:
        from nnstreamer_tpu.backends.tflite_backend import _load_interpreter

        Interpreter = _load_interpreter()
    except Exception:
        pytest.skip("no TFLite interpreter available")
    it = Interpreter(model_path=path)
    it.allocate_tensors()
    return it


def _invoke(it, x):
    idet, odet = it.get_input_details()[0], it.get_output_details()[0]
    it.set_tensor(idet["index"], x)
    it.invoke()
    return it.get_tensor(odet["index"])


def _fixture_images(n=10, size=224):
    """orange.png (real photo) + multi-scale structured patterns —
    upsampled coarse noise has edges/blobs at several frequencies, which
    separates classes far better than white noise."""
    cv2 = pytest.importorskip("cv2")
    orange = cv2.cvtColor(cv2.imread(f"{REF}/data/orange.png"),
                          cv2.COLOR_BGR2RGB)
    imgs = [cv2.resize(orange, (size, size))]
    rng = np.random.default_rng(7)
    scales = (4, 8, 16, 2, 32)
    k = 0
    while len(imgs) < n:
        s = scales[k % len(scales)]
        k += 1
        base = rng.integers(0, 256, (s, s, 3), np.uint8)
        up = cv2.resize(base, (size, size), interpolation=cv2.INTER_CUBIC)
        imgs.append(np.clip(up, 0, 255).astype(np.uint8))
    return [im.reshape(1, size, size, 3) for im in imgs]


class TestFlatbufferParser:
    def test_graph_inventory(self):
        from nnstreamer_tpu.tools.tflite_parse import parse

        m = parse(MOBILENET)
        assert len(m.operators) == 65
        assert m.tensors[m.inputs[0]].shape == (1, 224, 224, 3)
        assert m.tensors[m.inputs[0]].dtype == np.uint8
        assert m.tensors[m.outputs[0]].shape == (1, 1001)
        convs = [op for op in m.operators if op.name == "CONV_2D"]
        dws = [op for op in m.operators if op.name == "DEPTHWISE_CONV_2D"]
        assert len(convs) == 36 and len(dws) == 17
        # quantization params decode: stem weights are on a real grid
        w = m.tensors[convs[0].inputs[1]]
        assert w.quant is not None and w.quant.quantized
        assert w.dequantized().dtype == np.float32

        d = parse(DEEPLAB)
        assert d.tensors[d.inputs[0]].dtype == np.float32
        assert d.tensors[d.outputs[0]].shape == (1, 257, 257, 21)
        assert any(op.name == "RESIZE_BILINEAR" for op in d.operators)

    def test_add_tflite_matches_interpreter(self):
        """The third reference fixture (add.tflite, the single/filter
        smoke model) runs through the XLA compiler and agrees with the
        interpreter."""
        from nnstreamer_tpu.tools.tflite_exec import compile_tflite

        path = f"{REF}/models/add.tflite"
        prog = compile_tflite(path)
        x = np.asarray([3.25], np.float32).reshape(prog.input_shape)
        ours = np.asarray(prog(x)[0])
        it = _interpreter(path)
        np.testing.assert_allclose(ours, _invoke(it, x), rtol=1e-6)

    def test_exec_rejects_unknown_op(self, tmp_path):
        from nnstreamer_tpu.tools import tflite_exec, tflite_parse

        m = tflite_parse.parse(MOBILENET)
        m.operators[0].name = "NOT_AN_OP"
        prog = tflite_exec.TFLiteProgram(m)
        with pytest.raises(NotImplementedError):
            prog(np.zeros((1, 224, 224, 3), np.uint8))


class TestMobilenetImportedWeights:
    def test_top1_agreement_10_images(self):
        """The from-scratch jnp topology + imported dequantized weights
        reproduces the reference network: top-1 agrees with the real
        quantized interpreter on all 10 fixtures."""
        import jax

        from nnstreamer_tpu.models import mobilenet_v2 as mb

        it = _interpreter(MOBILENET)
        params = mb.load_tflite_params(MOBILENET)
        fn = jax.jit(lambda x: mb.apply(params, x))
        agree = total = 0
        for x in _fixture_images(10):
            ours = np.asarray(fn(x)).ravel()
            ref = _invoke(it, x).ravel().astype(np.float32)
            ot, rt = ours.argsort()[-3:][::-1], ref.argsort()[-3:][::-1]
            agree += ot[0] == rt[0]
            total += 1
            # float-dequantized vs int arithmetic can swap near-tied
            # ranks, never the class neighborhood: mutual top-3
            # containment must hold on EVERY image
            assert ot[0] in rt and rt[0] in ot, (ot, rt)
        assert total == 10
        assert agree >= 8, f"top-1 agreement {agree}/{total}"

    def test_wrong_graph_refused(self):
        """A non-mobilenet graph must fail LOUDLY, not import garbage
        (deeplab's conv walk diverges from the 1.0-width topology)."""
        from nnstreamer_tpu.models import mobilenet_v2 as mb

        with pytest.raises(ValueError, match="mobilenet_v2"):
            mb.load_tflite_params(DEEPLAB)

    def test_orange_is_orange(self):
        """orange.png through the imported model lands on the citrus
        label the reference's labeling example expects (labels.txt:951
        'orange' / 950 'lemon' neighborhood)."""
        import jax

        from nnstreamer_tpu.models import mobilenet_v2 as mb

        params = mb.load_tflite_params(MOBILENET)
        x = _fixture_images(1)[0]
        idx = int(np.asarray(jax.jit(lambda v: mb.apply(params, v))(x)).argmax())
        labels = [ln.strip() for ln in open(LABELS)]
        assert labels[idx] in ("orange", "lemon")


class TestTFLitePipeline:
    def test_labeling_pipeline_matches_interpreter(self, tmp_path):
        """The reference user's exact artifact — the .tflite file — runs
        through the full pipeline (converter ! filter framework=jax !
        decoder image_labeling) compiled to XLA, and the emitted label
        index matches the interpreter's argmax."""
        cv2 = pytest.importorskip("cv2")
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        it = _interpreter(MOBILENET)
        for i, x in enumerate(_fixture_images(3)):
            png = tmp_path / f"f{i}.png"
            cv2.imwrite(str(png), cv2.cvtColor(x[0], cv2.COLOR_RGB2BGR))
            p = parse_pipeline(
                f"videofilesrc location={png} num-frames=1 ! "
                "tensor_converter ! "
                f"tensor_filter framework=jax model={MOBILENET} ! "
                f"tensor_decoder mode=image_labeling option1={LABELS} ! "
                "tensor_sink name=out"
            )
            p.run(timeout=120)
            sink = p["out"]
            assert sink.rendered == 1
            ours = int(np.asarray(sink.frames[0].tensors[0]).ravel()[0])
            ref = int(_invoke(it, x).argmax())
            assert ours == ref

    def test_deeplab_mask_iou(self):
        """deeplabv3_257_mv_gpu.tflite compiled to one XLA program: the
        per-pixel argmax mask matches the interpreter (float graph —
        near-exact; assert IoU >= 0.95, pixel agreement >= 0.99)."""
        from nnstreamer_tpu.tools.tflite_exec import compile_tflite

        it = _interpreter(DEEPLAB)
        prog = compile_tflite(DEEPLAB)
        for x in _fixture_images(2, size=257):
            xf = (x.astype(np.float32) - 127.5) / 127.5
            ours = np.asarray(prog(xf)[0]).argmax(-1)
            ref = _invoke(it, xf).argmax(-1)
            assert (ours == ref).mean() >= 0.99
            ious = []
            for c in np.union1d(np.unique(ours), np.unique(ref)):
                a, b = ours == c, ref == c
                ious.append((a & b).sum() / max((a | b).sum(), 1))
            assert np.mean(ious) >= 0.95

    def test_deeplab_segment_pipeline(self, tmp_path):
        """Full segmentation chain on the reference model: transform
        normalizes on-device, the graph runs as one XLA program, and
        image_segment renders the RGBA overlay (tensordec-imagesegment.c
        role)."""
        cv2 = pytest.importorskip("cv2")
        from nnstreamer_tpu.pipeline.parse import parse_pipeline

        x = _fixture_images(1, size=257)[0]
        png = tmp_path / "seg.png"
        cv2.imwrite(str(png), cv2.cvtColor(x[0], cv2.COLOR_RGB2BGR))
        p = parse_pipeline(
            f"videofilesrc location={png} num-frames=1 ! tensor_converter ! "
            'tensor_transform mode=arithmetic '
            'option="typecast:float32,add:-127.5,div:127.5" ! '
            f"tensor_filter framework=jax model={DEEPLAB} ! "
            "tensor_decoder mode=image_segment option1=tflite-deeplab ! "
            "tensor_sink name=out"
        )
        p.run(timeout=180)
        sink = p["out"]
        assert sink.rendered == 1
        rgba = np.asarray(sink.frames[0].tensors[0])
        assert rgba.shape[-1] == 4 and rgba.shape[-3:-1] == (257, 257)
