"""Resident streaming executor (pipeline/transfer.py, docs/streaming.md):
the in-flight frame ring, activation donation, staged H2D / coalesced
D2H, and the device-resident handoff between adjacent fused segments.

Every pipeline here runs under the runtime sanitizer, so in-order
delivery and the offered == delivered + dropped + routed latch are
checked at EVERY ring depth, not just asserted by the tests.

Wall-time discipline: tier-1 stays well under 5 s (tiny frames, tiny
counts); the mixed-depth chaos soak is marked ``slow``.
"""

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import transfer
from nnstreamer_tpu.pipeline.executor import Executor
from nnstreamer_tpu.pipeline.parse import parse_pipeline
from nnstreamer_tpu.tensors.frame import Frame


@pytest.fixture(autouse=True)
def _sanitize(monkeypatch):
    monkeypatch.setenv("NNS_TPU_SANITIZE", "1")


def _counter_values(frames):
    return [int(np.asarray(f.tensors[0]).ravel()[0]) for f in frames]


# ------------------------------------------------------------ frame ring
class TestRingDelivery:
    @pytest.mark.parametrize("depth", [1, 2, 3])
    def test_in_order_delivery_at_every_depth(self, depth):
        """The ring holds up to ``depth`` frames in flight; delivery is
        strictly FIFO, so the counter stream arrives 0..N-1 exactly —
        and the sanitizer latch proves offered == delivered."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=50 pattern=counter ! "
            f"tensor_filter name=f framework=scaler ring-depth={depth} ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        frames = p["out"].frames
        assert len(frames) == 50
        # scaler doubles; counter pattern survives in order
        vals = [
            float(np.asarray(f.tensors[0]).ravel()[0]) for f in frames
        ]
        assert vals == [2.0 * i for i in range(50)]
        assert ex.totals()["balance"] == 0

    def test_ring_deeper_than_stream_flushes_at_eos(self):
        """A ring that never fills must still deliver everything when
        the stream ends (EOS flush)."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=3 pattern=counter ! "
            "tensor_filter name=f framework=scaler ring-depth=8 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        assert len(p["out"].frames) == 3
        assert ex.totals()["balance"] == 0

    def test_host_node_ring_depth_property(self):
        """Host-path filters stay synchronous unless ring-depth is set
        explicitly; with it set, delivery still preserves order."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=40 pattern=counter ! "
            "tensor_filter name=f framework=framecounter ring-depth=3 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        frames = p["out"].frames
        assert len(frames) == 40
        vals = _counter_values(frames)
        assert vals == sorted(vals)
        assert ex.totals()["balance"] == 0

    def test_ring_depth_resolution_layering(self, monkeypatch):
        """Element property > [executor] ring_depth config (env wins
        over ini); bad values fall back; the depth clamps to [1, 32]."""

        class _E:
            def __init__(self, v):
                self.v = v

            def get_property(self, key):
                return self.v if key == "ring-depth" else None

        assert transfer.resolve_ring_depth([_E(None)]) == 2  # default
        assert transfer.resolve_ring_depth([_E(5)]) == 5
        assert transfer.resolve_ring_depth([_E(0)]) == 1     # clamp lo
        assert transfer.resolve_ring_depth([_E(99)]) == 32   # clamp hi
        assert transfer.resolve_ring_depth([_E("junk")]) == 2
        monkeypatch.setenv("NNS_TPU_EXECUTOR_RING_DEPTH", "4")
        assert transfer.resolve_ring_depth([_E(None)]) == 4


# ------------------------------------------------------------- donation
class TestDonation:
    def _segment(self):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=1 ! "
            "tensor_filter name=f framework=scaler ! tensor_sink"
        )
        plan = p.compile_plan()
        (seg,) = plan.segments
        return seg

    def test_donated_input_never_read_after_submit(self):
        """The donation contract: stage_frame(force=True) gives the
        program a PRIVATE device copy, so mutating the host array after
        submit cannot reach the output — and the donated buffer is
        consumed (deleted), proving XLA actually reused it rather than
        keeping the input alive."""
        seg = self._segment()
        src = np.full((4,), 3.0, np.float32)
        staged = transfer.stage_frame(Frame(tensors=(src,)), force=True)
        assert staged.tensors[0] is not src  # a real copy, not an alias
        out = seg.process(staged, donate=True)
        src[:] = 777.0  # post-submit mutation — must not be visible
        np.testing.assert_array_equal(
            np.asarray(out.tensors[0]), np.full((4,), 6.0, np.float32)
        )
        # donated & consumed: the input buffer is dead after the call
        assert staged.tensors[0].is_deleted()

    def test_undonated_process_keeps_input_alive(self):
        seg = self._segment()
        staged = transfer.stage_frame(
            Frame(tensors=(np.ones((4,), np.float32),)), force=True
        )
        seg.process(staged, donate=False)
        assert not staged.tensors[0].is_deleted()

    def test_donation_only_aliases_matching_outputs(self):
        """An input whose (shape, dtype) matches no output cannot be
        aliased — it must NOT be donated (XLA would just delete it and
        warn). The scaler's output matches its input, so argnum 0 is
        aliasable; a dtype-changing program yields no argnums."""
        seg = self._segment()
        sig = ((tuple([4]), np.dtype(np.float32)),)
        composed = seg._compose()
        assert seg._aliasable_argnums(composed, sig, 0) == (0,)

        def cast(*ts):
            return tuple(t.astype(np.int32) for t in ts)

        assert seg._aliasable_argnums(cast, sig, 0) == ()

    def test_batched_pipeline_with_donation_is_correct(self):
        """End-to-end: the batched fused path donates its stacked
        windows (seg.donate default on); values and order must be
        bitwise right anyway."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=64 pattern=counter ! "
            "tensor_filter name=f framework=scaler batching=true "
            "max-batch=8 batch-timeout-ms=2 ! tensor_sink name=out"
        )
        ex = p.run(timeout=30)
        assert not ex.errors
        vals = [
            float(np.asarray(f.tensors[0]).ravel()[0])
            for f in p["out"].frames
        ]
        assert vals == [2.0 * i for i in range(64)]
        assert ex.totals()["balance"] == 0


# ------------------------------------------- fault-mid-ring (governor)
class TestFaultMidRing:
    def test_oom_mid_ring_drains_in_order_before_degrading(self):
        """BucketGovernor × ring interplay: an OOM inside a batched
        window with ring depth 3 shrinks the bucket and retries, while
        the frames already in flight deliver FIRST and in order — the
        sanitizer latch plus the sorted counter prove no reorder, no
        loss."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=100 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,oom_above_rows:2 "
            "batching=true max-batch=8 batch-timeout-ms=2 ring-depth=3 ! "
            "tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        s = ex.stats()["f"]
        assert len(p["out"].frames) == 100
        assert s["oom_events"] >= 1
        assert s["batch_ceiling"] == 2
        vals = _counter_values(p["out"].frames)
        assert vals == sorted(vals)
        assert ex.totals()["balance"] == 0

    def test_host_oom_every_n_with_ring_retries_in_order(self):
        """FaultyBackend oom_every_n on the host path with a ring: the
        per-frame retry gate re-invokes (the next attempt succeeds) and
        the ring's FIFO keeps the stream ordered."""
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=60 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=oom_every_n:5 on-error=retry retry-max=3 "
            "ring-depth=2 ! tensor_sink name=out"
        )
        ex = p.run(timeout=60)
        assert not ex.errors
        frames = p["out"].frames
        assert len(frames) == 60
        vals = _counter_values(frames)
        assert vals == sorted(vals)
        assert ex.totals()["balance"] == 0


# ------------------------------------------------- resident handoff
class TestResidentHandoff:
    def _run(self, desc):
        p = parse_pipeline(desc)
        ex = p.run(timeout=30)
        assert not ex.errors
        assert ex.totals()["balance"] == 0
        return p, ex

    def test_adjacent_segments_zero_host_materialization(self):
        """Two fused segments joined by a queue hand frames off as
        device arrays: the run's D2H byte count equals the single-
        segment control's (only the sink fetches), i.e. ZERO host
        materialization between the segments — while a host-bound
        element in the gap forces a mid-stream fetch and the counter
        shows it."""
        n = 40
        src = f"tensorsrc dimensions=4 num-frames={n} pattern=counter ! "
        _, ex1 = self._run(
            src + "tensor_filter framework=scaler ! tensor_sink name=out"
        )
        d2h_control = ex1.transfer_totals()["d2h"]

        p2, ex2 = self._run(
            src + "tensor_filter framework=scaler ! queue ! "
            "tensor_filter framework=scaler ! tensor_sink name=out"
        )
        assert ex2.transfer_totals()["d2h"] == d2h_control
        # and the chain still computed: scaler twice = ×4
        vals = [
            float(np.asarray(f.tensors[0]).ravel()[0])
            for f in p2["out"].frames
        ]
        assert vals == [4.0 * i for i in range(n)]

        _, ex3 = self._run(
            src + "tensor_filter framework=scaler ! queue ! "
            "tensor_filter framework=framecounter ! queue ! "
            "tensor_filter framework=scaler ! tensor_sink name=out"
        )
        assert ex3.transfer_totals()["d2h"] > d2h_control

    def test_transfer_totals_in_executor_totals(self):
        _, ex = self._run(
            "tensorsrc dimensions=4 num-frames=10 ! "
            "tensor_filter framework=scaler ! tensor_sink name=out"
        )
        t = ex.totals()["transfer"]
        assert set(t) == {"h2d", "d2h"}
        assert t["d2h"] > 0  # the sink materialized its frames


# ------------------------------------------------------ coalesced D2H
class TestCoalescedD2H:
    def test_packed_fetch_roundtrip_mixed_dtypes(self, monkeypatch):
        """T tensors ride ONE packed transfer; the host side splits the
        buffer back by dtype/shape bit-exactly (bool included, which
        bitcast rejects and the packer routes through uint8)."""
        import jax.numpy as jnp

        monkeypatch.setattr(transfer, "is_local_cpu", lambda t: False)
        ts = [
            jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
            jnp.arange(6, dtype=jnp.int32),
            jnp.array([True, False, True]),
            jnp.arange(5, dtype=jnp.uint8),
        ]
        ff = transfer.FrameFetch(list(ts)).start()
        assert ff._packed is not None  # the packed path engaged
        out = ff.finish()
        assert all(isinstance(a, np.ndarray) for a in out)
        for got, want in zip(out, ts):
            np.testing.assert_array_equal(got, np.asarray(want))
            assert got.dtype == np.asarray(want).dtype

    def test_lone_tensor_skips_the_packer(self, monkeypatch):
        import jax.numpy as jnp

        monkeypatch.setattr(transfer, "is_local_cpu", lambda t: False)
        ff = transfer.FrameFetch([jnp.arange(4.0)]).start()
        assert ff._packed is None  # already one transfer
        np.testing.assert_array_equal(ff.finish()[0], np.arange(4.0))

    def test_fetch_window_all_host_is_a_passthrough(self):
        frames = [
            Frame(tensors=(np.arange(4, dtype=np.float32),))
            for _ in range(3)
        ]
        base = transfer.tally.snapshot()["d2h_bytes"]
        assert transfer.fetch_window(frames) is frames
        assert transfer.tally.snapshot()["d2h_bytes"] == base

    def test_mixed_host_device_finishes_to_host(self):
        import jax.numpy as jnp

        f = transfer.FrameFetch(
            [np.ones(3, np.float32), jnp.zeros(3)]
        ).start()
        out = f.finish()
        assert all(isinstance(a, np.ndarray) for a in out)


# ----------------------------------------------------------- H2D staging
class TestStagedH2D:
    def test_stage_frame_cpu_default_is_bypass(self):
        f = Frame(tensors=(np.ones(4, np.float32),))
        assert transfer.stage_frame(f) is f  # local CPU: put is a copy
        # for nothing — the jitted ingest is the cheaper path

    def test_stage_frame_force_counts_h2d(self):
        base = transfer.tally.snapshot()["h2d_bytes"]
        f = Frame(tensors=(np.ones(4, np.float32),))
        staged = transfer.stage_frame(f, force=True)
        assert transfer.is_device_array(staged.tensors[0])
        assert transfer.tally.snapshot()["h2d_bytes"] - base == 16

    def test_stage_iter_preserves_order(self):
        arrays = [np.full((2,), i, np.float32) for i in range(20)]
        # force the feeder-thread path even on CPU by faking a target
        out = list(transfer.stage_iter(iter(arrays), device=None))
        assert [int(a.ravel()[0]) for a in out] == list(range(20))


# ------------------------------------------------------------------ soak
@pytest.mark.slow
def test_ring_depth_chaos_soak():
    """Long mixed run: every ring depth × intermittent OOM faults, 1000
    frames each, sanitizer on — order, accounting, and completion."""
    for depth in (1, 2, 3):
        p = parse_pipeline(
            "tensorsrc dimensions=4 num-frames=1000 pattern=counter ! "
            "tensor_filter name=f framework=faulty "
            "custom=traceable:true,oom_above_rows:4 "
            f"batching=true max-batch=8 batch-timeout-ms=2 "
            f"ring-depth={depth} ! tensor_sink name=out"
        )
        ex = p.run(timeout=120)
        assert not ex.errors
        assert len(p["out"].frames) == 1000
        vals = _counter_values(p["out"].frames)
        assert vals == sorted(vals)
        assert ex.totals()["balance"] == 0
