"""Flexible-header codec, sparse codec, scalar data op tests.

Mirrors reference coverage of GstTensorMetaInfo
(tensor_typedef.h:279-294) and gsttensor_sparseutil.c.
"""

import numpy as np
import pytest

from nnstreamer_tpu.tensors.meta import (
    FlexTensorMeta,
    HEADER_SIZE,
    decode_frame_tensors,
    encode_frame_tensors,
)
from nnstreamer_tpu.tensors.sparse import sparse_decode, sparse_density, sparse_encode
from nnstreamer_tpu.tensors import data
from nnstreamer_tpu.tensors.spec import DType, TensorFormat


class TestFlexMeta:
    def test_roundtrip_header(self):
        m = FlexTensorMeta(DType.FLOAT32, (1, 224, 224, 3), payload_size=100)
        buf = m.pack()
        assert len(buf) == HEADER_SIZE
        m2 = FlexTensorMeta.unpack(buf)
        assert m2 == m

    def test_roundtrip_array(self):
        a = np.arange(24, dtype=np.int16).reshape(2, 3, 4)
        buf = FlexTensorMeta.encode_array(a)
        b, used = FlexTensorMeta.decode_array(buf)
        assert used == len(buf)
        np.testing.assert_array_equal(a, b)

    def test_bfloat16_roundtrip(self):
        a = np.arange(8).astype(DType.BFLOAT16.np_dtype)
        b, _ = FlexTensorMeta.decode_array(FlexTensorMeta.encode_array(a))
        np.testing.assert_array_equal(np.asarray(a, np.float32), np.asarray(b, np.float32))

    def test_bad_magic(self):
        buf = bytearray(FlexTensorMeta(DType.UINT8, (2,)).pack())
        buf[0] = 0xFF
        with pytest.raises(ValueError, match="magic"):
            FlexTensorMeta.unpack(bytes(buf))

    def test_truncated(self):
        a = np.zeros(10, np.float32)
        buf = FlexTensorMeta.encode_array(a)[:-4]
        with pytest.raises(ValueError, match="truncated"):
            FlexTensorMeta.decode_array(buf)

    def test_multi_tensor_frame(self):
        arrays = [np.ones((2, 2), np.uint8), np.zeros((5,), np.float64)]
        out = decode_frame_tensors(encode_frame_tensors(arrays))
        assert len(out) == 2
        for a, b in zip(arrays, out):
            np.testing.assert_array_equal(a, b)


class TestSparse:
    def test_roundtrip(self):
        a = np.zeros((4, 8), np.float32)
        a[1, 2] = 3.5
        a[3, 7] = -1.0
        buf = sparse_encode(a)
        dense, used = sparse_decode(buf)
        assert used == len(buf)
        np.testing.assert_array_equal(a, dense)

    def test_compression_wins_when_sparse(self):
        a = np.zeros((100, 100), np.float32)
        a[0, 0] = 1
        assert len(sparse_encode(a)) < a.nbytes

    def test_density(self):
        a = np.zeros(10)
        a[:3] = 1
        assert sparse_density(a) == pytest.approx(0.3)

    def test_format_tag(self):
        buf = sparse_encode(np.ones(4, np.int32))
        meta = FlexTensorMeta.unpack(buf)
        assert meta.format is TensorFormat.SPARSE

    def test_decode_rejects_non_sparse(self):
        buf = FlexTensorMeta.encode_array(np.ones(4, np.int32))
        with pytest.raises(ValueError, match="not a sparse"):
            sparse_decode(buf)


class TestScalarData:
    def test_typecast(self):
        v = data.typecast(3.9, "int32")
        assert v == 3 and v.dtype == np.int32

    def test_average(self):
        assert data.tensor_average(np.array([1, 2, 3, 4])) == 2.5

    def test_per_channel_average(self):
        a = np.arange(12).reshape(2, 2, 3)
        pc = data.tensor_average_per_channel(a, axis=-1)
        assert pc.shape == (3,)
        np.testing.assert_allclose(pc, np.mean(a.reshape(-1, 3), axis=0))

    def test_compare_ops(self):
        assert data.compare(1, "LT", 2)
        assert data.compare(2, "GE", 2)
        assert not data.compare(1, "EQ", 2)
        with pytest.raises(ValueError):
            data.compare(1, "XX", 2)
