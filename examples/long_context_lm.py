"""Ring-attention LM training step on a virtual mesh (run with
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
on CPU; on a TPU slice the same code spans real chips)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env

honor_jax_platforms_env()
import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.parallel import lm
from nnstreamer_tpu.parallel.mesh import make_mesh

n = len(jax.devices())
shape = (2, 2, 2) if n == 8 else (n, 1, 1)
mesh = make_mesh(axes=("dp", "sp", "ep"), shape=shape)
print("mesh:", dict(mesh.shape))
params = lm.init_lm_params(jax.random.PRNGKey(0), vocab=256, d_model=128,
                           n_heads=8, n_layers=4, n_experts=4)
step, params = lm.make_lm_train_step(
    mesh, params, n_heads=8,
    ep_axis="ep" if "ep" in mesh.shape else None)
b = 2 * mesh.shape["dp"]  # batch shards over dp
toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (b, 129)),
                   jnp.int32)
for i in range(5):
    params, loss = step(params, toks)
    print(f"step {i}: loss {float(loss):.4f}")
