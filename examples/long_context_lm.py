"""Ring-attention LM training step on a virtual mesh (run with
XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu
on CPU; on a TPU slice the same code spans real chips)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))
import jax
import jax.numpy as jnp
import numpy as np

from nnstreamer_tpu.parallel import lm
from nnstreamer_tpu.parallel.mesh import make_mesh

mesh = make_mesh(axes=("dp", "sp", "ep"), shape=None)
print("mesh:", dict(mesh.shape))
params = lm.init_lm_params(jax.random.PRNGKey(0), vocab=256, d_model=128,
                           n_heads=8, n_layers=4, n_experts=4)
step, params = lm.make_lm_train_step(
    mesh, params, n_heads=8,
    ep_axis="ep" if "ep" in mesh.shape else None)
toks = jnp.asarray(np.random.default_rng(0).integers(0, 256, (4, 129)),
                   jnp.int32)
for i in range(5):
    params, loss = step(params, toks)
    print(f"step {i}: loss {float(loss):.4f}")
