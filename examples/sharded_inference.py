"""Multi-chip inference from the filter surface: mesh-sharded filters and
the fused face cascade.

Run on any host (the virtual CPU mesh stands in for a TPU slice):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/sharded_inference.py

- ``custom="mesh:dp2tp4"`` pjits one tensor_filter over a 2x4 device mesh:
  batch shards over dp, weights column-parallel over tp, XLA GSPMD inserts
  the collectives (reference analogue: the accelerator-selection machinery
  of tensor_filter_common.c:451-, where the "accelerator" here is a slice).
- ``zoo:face_composite`` runs detect→crop+resize→landmark as ONE XLA
  program (the reference's tensor_crop cascade without the host hop).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

from nnstreamer_tpu.platform_pin import honor_jax_platforms_env  # noqa: E402

honor_jax_platforms_env()

import jax  # noqa: E402

import numpy as np  # noqa: E402

from nnstreamer_tpu.single import SingleShot  # noqa: E402


def main() -> None:
    rng = np.random.default_rng(0)

    # -- TP/DP-sharded ViT classifier, one property away
    batch = 8
    with SingleShot(
        framework="jax",
        model="zoo:vit",
        custom=f"batch:{batch},size:64,patch:16,d_model:128,n_heads:4,"
               "n_layers:2,num_classes:10,mesh:dp2tp4",
    ) as s:
        imgs = rng.integers(0, 255, (batch, 64, 64, 3), np.uint8)
        (logits,) = s.invoke(imgs)
        print(f"sharded vit logits: {np.asarray(logits).shape} "
              f"(mesh dp2tp4 over {len(jax.devices())} devices)")

    # -- fused face cascade: one program, no host hop at the crop
    with SingleShot(
        framework="jax", model="zoo:face_composite", custom="threshold:0.25"
    ) as s:
        frame = rng.integers(0, 255, (1, 128, 128, 3), np.uint8)
        landmarks, detections = s.invoke(frame)
        det = np.asarray(detections)
        n = int((det[:, 2] >= 0.25).sum())
        print(f"fused cascade: {n} faces above threshold, "
              f"landmarks {np.asarray(landmarks).shape}")


if __name__ == "__main__":
    main()
